"""Chaos soak on the networked server path.

A multi-worker optimistic eval storm rides REAL RPC (ConnPool -> the
server's mux plane) while nodes heartbeat-expire mid-storm through the
actual TTL-expiry path (HeartbeatManager._invalidate -> node down ->
node-update evals).  After the dust settles, the invariants the
reference guarantees must hold (analogue: nomad/plan_apply_test.go +
worker_test.go):

  1. no node is oversubscribed (exact allocs_fit per node);
  2. the incremental usage mirror equals a from-scratch rebuild;
  3. every evaluation is terminal (none stuck in the broker).

Deterministic job/topology seeds; worker/raft/heartbeat interleaving is
whatever the scheduler actually does under concurrency — the point is
that the invariants hold for EVERY interleaving.
"""
from __future__ import annotations

import time

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.models.fleet import build_usage, fleet_cache, mirror_for
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool
from nomad_tpu.structs import (
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
    NetworkResource,
    Resources,
    Task,
    TaskGroup,
    allocs_fit,
)

TERMINAL = ("complete", "failed", "canceled")


def _storm_job(rng, n_groups: int):
    job = mock.job()
    job.task_groups = [
        TaskGroup(name=f"tg-{g}", count=int(rng.integers(1, 3)),
                  tasks=[Task(
                      name="web", driver="exec",
                      resources=Resources(
                          cpu=int(rng.integers(100, 700)),
                          memory_mb=int(rng.integers(32, 256)),
                          networks=[NetworkResource(
                              mbits=int(rng.integers(1, 10)),
                              dynamic_ports=["http"])]),
                  )])
        for g in range(n_groups)]
    return job


@pytest.mark.parametrize("seed", [7, 23])
def test_chaos_storm_with_heartbeat_expiry(seed):
    rng = np.random.default_rng(seed)
    srv = Server(ServerConfig(num_schedulers=4, enable_rpc=True))
    srv.establish_leadership()
    pool = ConnPool()
    try:
        addr = srv.rpc_address()

        # Fleet registered over real RPC (heartbeat TTLs armed).
        n_nodes = 40
        node_ids = []
        for i in range(n_nodes):
            node = mock.node(i)
            out = pool.call(addr, "Node.Register",
                            {"node": node.to_dict()})
            assert out["heartbeat_ttl"] > 0
            node_ids.append(node.id)

        # Optimistic storm: 18 jobs x 12 TGs submitted over RPC; the
        # 4-worker pool processes them concurrently against snapshots.
        eval_ids = []
        job_ids = []
        for _ in range(18):
            job = _storm_job(rng, 12)
            resp = pool.call(addr, "Job.Register",
                            {"job": job.to_dict()})
            eval_ids.append(resp["eval_id"])
            job_ids.append(job.id)

        # Mid-storm chaos: a deterministic subset of nodes misses its
        # heartbeats — the REAL expiry path marks them down and spawns
        # node-update evals that race the in-flight storm.
        time.sleep(0.15)  # sleep-ok: mid-storm pacing before injected expiry
        expire = [node_ids[int(i)] for i in
                  rng.choice(n_nodes, size=10, replace=False)]
        for node_id in expire:
            srv.heartbeats._invalidate(node_id)

        # Drain to quiescence: every eval (the storm's AND the
        # node-update ones the expiries spawn) terminal.  Surviving
        # nodes keep heartbeating while we wait so the real ~20s TTL
        # (min_ttl + grace) can't expire them under a slow run and
        # muddy the deterministic down-set.
        survivors = [nid for nid in node_ids if nid not in set(expire)]
        deadline = time.monotonic() + 55
        last_beat = 0.0
        while time.monotonic() < deadline:
            if time.monotonic() - last_beat > 4.0:
                for nid in survivors:
                    pool.call(addr, "Node.Heartbeat", {"node_id": nid})
                last_beat = time.monotonic()
            evals = srv.fsm.state.evals()
            if evals and all(e.status in TERMINAL for e in evals) and \
                    len(evals) >= len(eval_ids):
                break
            time.sleep(0.2)  # sleep-ok: poll cadence between liveness heartbeats

        state = srv.fsm.state

        # (3) every eval terminal — nothing stuck in the broker.
        stuck = [(e.id, e.status) for e in state.evals()
                 if e.status not in TERMINAL]
        assert not stuck, f"non-terminal evals after soak: {stuck[:5]}"

        # Expired nodes are down; the rest stayed ready.
        downed = {nid for nid in expire}
        for nid in node_ids:
            node = state.node_by_id(nid)
            want = NODE_STATUS_DOWN if nid in downed else NODE_STATUS_READY
            assert node.status == want, (nid, node.status)

        # (1) no oversubscription anywhere, exact accounting.
        total_live = 0
        for nid in node_ids:
            live = [a for a in state.allocs_by_node(nid)
                    if not a.terminal_status() and a.node_id]
            total_live += len(live)
            node = state.node_by_id(nid)
            fit, dim, _util = allocs_fit(node, live)
            assert fit, f"node {nid} oversubscribed on {dim}"
            # Port uniqueness per node (the native finish's contract).
            ports = [p for a in live
                     for tr in a.task_resources.values()
                     for net in tr.networks for p in net.reserved_ports]
            assert len(ports) == len(set(ports)), f"port collision {nid}"
        assert total_live > 0, "storm placed nothing"

        # (2) incremental mirror == from-scratch rebuild.
        snap = state.snapshot()
        statics = fleet_cache.statics_for(snap)
        mirror = mirror_for(statics)
        mirror.sync(snap)  # prime/converge (side effect is the point)
        live = [a for a in snap.allocs() if not a.terminal_status()]
        scratch = build_usage(statics, live, job_id=job_ids[0])
        np.testing.assert_allclose(mirror.usage, scratch.usage,
                                   rtol=0, atol=0)
    finally:
        pool.shutdown()
        srv.shutdown()


def test_chaos_storm_with_drain():
    """Drain-mid-storm soak: nodes drain over real RPC while the worker
    pool is placing; at quiescence drained nodes hold no live allocs,
    nothing is oversubscribed, and the round-5 NET tracking
    (sync_net's incremental port/bandwidth state, which the vectorized
    plan verifier consumed throughout the storm) equals a from-scratch
    rebuild."""
    rng = np.random.default_rng(11)
    srv = Server(ServerConfig(num_schedulers=4, enable_rpc=True))
    srv.establish_leadership()
    pool = ConnPool()
    try:
        addr = srv.rpc_address()
        n_nodes = 30
        node_ids = []
        for i in range(n_nodes):
            node = mock.node(i)
            pool.call(addr, "Node.Register", {"node": node.to_dict()})
            node_ids.append(node.id)

        eval_ids = []
        for _ in range(14):
            job = _storm_job(rng, 10)
            resp = pool.call(addr, "Job.Register",
                             {"job": job.to_dict()})
            eval_ids.append(resp["eval_id"])

        time.sleep(0.1)  # sleep-ok: mid-storm pacing before injected drain
        drained = [node_ids[int(i)] for i in
                   rng.choice(n_nodes, size=8, replace=False)]
        for nid in drained:
            pool.call(addr, "Node.UpdateDrain",
                      {"node_id": nid, "drain": True})

        survivors = [nid for nid in node_ids if nid not in set(drained)]
        deadline = time.monotonic() + 55
        last_beat = 0.0
        while time.monotonic() < deadline:
            if time.monotonic() - last_beat > 4.0:
                for nid in node_ids:
                    pool.call(addr, "Node.Heartbeat", {"node_id": nid})
                last_beat = time.monotonic()
            evals = srv.fsm.state.evals()
            if evals and all(e.status in TERMINAL for e in evals) and \
                    len(evals) >= len(eval_ids):
                break
            time.sleep(0.2)  # sleep-ok: poll cadence between liveness heartbeats

        state = srv.fsm.state
        stuck = [(e.id, e.status) for e in state.evals()
                 if e.status not in TERMINAL]
        assert not stuck, f"non-terminal evals after soak: {stuck[:5]}"

        # A placement can slip onto a draining node inside the
        # applier's optimistic verify window (plan verified against the
        # snapshot taken just before the drain committed — the same
        # window the reference's overlapped verify/apply has,
        # plan_apply.go:68-85).  Drain is ENFORCED by node evals, so a
        # follow-up node evaluation must clear any straggler.
        n_evals = len(srv.fsm.state.evals())
        for nid in drained:
            pool.call(addr, "Node.Evaluate", {"node_id": nid})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if time.monotonic() - last_beat > 4.0:
                for nid in node_ids:
                    pool.call(addr, "Node.Heartbeat", {"node_id": nid})
                last_beat = time.monotonic()
            evals = srv.fsm.state.evals()
            if len(evals) > n_evals and \
                    all(e.status in TERMINAL for e in evals):
                break
            time.sleep(0.2)  # sleep-ok: poll cadence between liveness heartbeats
        state = srv.fsm.state

        # Drained nodes end empty; survivors are never oversubscribed.
        total_live = 0
        for nid in node_ids:
            live = [a for a in state.allocs_by_node(nid)
                    if not a.terminal_status() and a.node_id]
            if nid in set(drained):
                assert not live, f"drained node {nid} still has allocs"
                continue
            total_live += len(live)
            node = state.node_by_id(nid)
            fit, dim, _util = allocs_fit(node, live)
            assert fit, f"node {nid} oversubscribed on {dim}"
        assert total_live > 0, "storm placed nothing on survivors"

        # Round-5 net tracking: incremental == rebuild after the storm.
        snap = state.snapshot()
        statics = fleet_cache.statics_for(snap)
        mirror = mirror_for(statics)
        assert mirror.sync_net(snap)
        from nomad_tpu.models.fleet import UsageMirror
        fresh = UsageMirror(statics)
        fresh.sync_net(snap)
        assert mirror.net_rows == fresh.net_rows
        assert mirror.node_ports == fresh.node_ports
        assert mirror.node_bw == fresh.node_bw
        assert mirror.node_dup == fresh.node_dup
        np.testing.assert_allclose(mirror.usage, fresh.usage,
                                   rtol=0, atol=0)
    finally:
        pool.shutdown()
        srv.shutdown()


def test_leader_failover_mid_storm():
    """Raft-failover chaos: the leader dies while a storm is in flight;
    the new leader restores the eval broker from replicated state,
    finishes every evaluation, and the committed allocations still
    satisfy exact fit (plans commit atomically through raft, so a
    half-processed storm can never leave torn placements)."""
    from tests.test_raft_net import (
        make_cluster,
        wait_for_stable_leader,
        wait_until,
    )

    servers = make_cluster(3)
    try:
        leader = wait_for_stable_leader(servers)
        nodes = [mock.node(i) for i in range(10)]
        for node in nodes:
            leader.node_register(node)

        rng = np.random.default_rng(11)
        eval_ids = []
        for _ in range(8):
            job = _storm_job(rng, 6)
            _, eid = leader.job_register(job)
            eval_ids.append(eid)

        # Kill the leader immediately: the storm is mid-flight.
        # (Server.shutdown tears down raft + RPC too.)
        survivors = [s for s in servers if s is not leader]
        leader.shutdown()
        for s in survivors:
            s.raft.remove_peer(leader.rpc_address())

        # Load-tolerant: the two survivors may flap leadership for a
        # while when the host is starving their tickers — wait for a
        # leader that HOLDS, with a generous bar (this soak proves
        # convergence invariants, not election latency; bench 5e owns
        # the timing numbers).
        wait_for_stable_leader(survivors, timeout=60)

        # Every raft-committed eval must reach a terminal status on a
        # survivor's replica (the broker restores from replicated
        # state on WHICHEVER survivor currently leads — a mid-wait
        # re-flap must not fail the check, so read both replicas).
        def all_terminal():
            for s in survivors:
                state = s.fsm.state
                evs = [state.eval_by_id(eid) for eid in eval_ids]
                if all(e is not None and e.status in TERMINAL
                       for e in evs):
                    return True
            return False
        wait_until(all_terminal, timeout=90,
                   msg="storm evals terminal on a survivor")

        # Committed placements satisfy exact fit on every node, on every
        # survivor's replica.
        for s in survivors:
            state = s.fsm.state
            for node in nodes:
                live = [a for a in state.allocs_by_node(node.id)
                        if not a.terminal_status() and a.node_id]
                fit, dim, _ = allocs_fit(state.node_by_id(node.id), live)
                assert fit, f"node {node.id} oversubscribed on {dim}"
        # Replicas agree on the alloc set (load-tolerant bar: replication
        # to the trailing survivor rides the same starved tickers).
        def alloc_ids(s):
            return frozenset(a.id for a in s.fsm.state.allocs())
        wait_until(lambda: alloc_ids(survivors[0]) == alloc_ids(
            survivors[1]), timeout=60, msg="replicas agree on allocs")
    finally:
        for s in servers:
            try:
                s.shutdown()
            except Exception:
                pass
