"""Port of the reference's plan_apply_test.go scenario table (485 LoC,
/root/reference/nomad/plan_apply_test.go) against server/plan_apply.py.

Three blocks, mirroring the upstream table:

  1. evaluate_plan (TestPlanApply_EvalPlan_*): full accept, partial
     accept with RefreshIndex, all-at-once whole rejection.
  2. _evaluate_node_plan (TestPlanApply_EvalNodePlan_*): per-node
     verdicts — missing/not-ready/draining/full nodes, frees via
     eviction, terminal existing allocs, evict-only on a down node.
  3. applyPlan end to end (TestPlanApply_applyPlan) + the
     snapshot-vs-commit drain window and the optimistic verify/apply
     overlay (plan N+1 verified against plan N's uncommitted result).

Fleet arithmetic: mock nodes expose 4000 cpu / 8192 MB with 100 cpu /
256 MB reserved, so a 3900-cpu alloc fills a node exactly and a
4000-cpu ask can never fit.
"""
from __future__ import annotations

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server.plan_apply import (
    OptimisticSnapshot,
    _evaluate_node_plan,
    evaluate_plan,
)
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    NODE_STATUS_DOWN,
    NODE_STATUS_INIT,
    Allocation,
    Plan,
    Resources,
    generate_uuid,
)

FREE_CPU = 3900  # mock node capacity 4000 minus 100 reserved


def make_alloc(node, *, cpu=1000, mem=1024, job_id="j1",
               desired=ALLOC_DESIRED_STATUS_RUN) -> Allocation:
    return Allocation(
        id=generate_uuid(),
        node_id=node.id,
        job_id=job_id,
        task_group="web",
        resources=Resources(cpu=cpu, memory_mb=mem),
        desired_status=desired,
        client_status=ALLOC_CLIENT_STATUS_PENDING,
    )


def place_plan(*allocs) -> Plan:
    plan = Plan(eval_id=generate_uuid())
    for a in allocs:
        plan.append_alloc(a)
    return plan


@pytest.fixture
def store():
    return StateStore()


# ---------------------------------------------------------------------------
# 1. evaluate_plan (TestPlanApply_EvalPlan_Simple / _Partial /
#    _Partial_AllAtOnce)
# ---------------------------------------------------------------------------

class TestEvalPlan:
    def test_simple_full_accept(self, store):
        node = mock.node()
        store.upsert_node(1000, node)
        plan = place_plan(make_alloc(node))
        result = evaluate_plan(store.snapshot(), plan)
        assert result.node_allocation == plan.node_allocation
        assert result.refresh_index == 0
        assert result.full_commit(plan)[0]

    def test_partial_accept_sets_refresh(self, store):
        """One fitting node, one over-committed: the fitting node's
        placements commit, the other's are dropped, and RefreshIndex
        forces the scheduler onto fresh state."""
        good, full = mock.node(), mock.node(1)
        store.upsert_node(1000, good)
        store.upsert_node(1001, full)
        store.upsert_allocs(1002, [make_alloc(full, cpu=FREE_CPU)])
        plan = place_plan(make_alloc(good), make_alloc(full, cpu=1000))
        result = evaluate_plan(store.snapshot(), plan)
        assert list(result.node_allocation) == [good.id]
        assert result.refresh_index >= 1002
        ok, expected, actual = result.full_commit(plan)
        assert not ok and expected == 2 and actual == 1

    def test_partial_all_at_once_rejects_whole_plan(self, store):
        good, full = mock.node(), mock.node(1)
        store.upsert_node(1000, good)
        store.upsert_node(1001, full)
        store.upsert_allocs(1002, [make_alloc(full, cpu=FREE_CPU)])
        plan = place_plan(make_alloc(good), make_alloc(full, cpu=1000))
        plan.all_at_once = True
        result = evaluate_plan(store.snapshot(), plan)
        assert result.node_allocation == {}
        assert result.node_update == {}
        assert result.refresh_index > 0

    def test_failed_allocs_always_ride_along(self, store):
        """failedAllocs carry scheduler verdicts, not node state — they
        commit even when every placement is rejected."""
        full = mock.node()
        store.upsert_node(1000, full)
        store.upsert_allocs(1001, [make_alloc(full, cpu=FREE_CPU)])
        plan = place_plan(make_alloc(full, cpu=1000))
        failed = make_alloc(full, cpu=1)
        failed.node_id = ""
        plan.append_failed(failed)
        result = evaluate_plan(store.snapshot(), plan)
        assert result.node_allocation == {}
        assert result.failed_allocs == [failed]


# ---------------------------------------------------------------------------
# 2. _evaluate_node_plan (TestPlanApply_EvalNodePlan_* table)
# ---------------------------------------------------------------------------

class TestEvalNodePlan:
    def _verdict(self, store, plan, node_id) -> bool:
        return _evaluate_node_plan(store.snapshot(), plan, node_id)

    def test_simple_fit(self, store):
        node = mock.node()
        store.upsert_node(1000, node)
        plan = place_plan(make_alloc(node))
        assert self._verdict(store, plan, node.id)

    def test_missing_node(self, store):
        node = mock.node()  # never upserted
        plan = place_plan(make_alloc(node))
        assert not self._verdict(store, plan, node.id)

    def test_node_not_ready(self, store):
        node = mock.node()
        node.status = NODE_STATUS_INIT
        store.upsert_node(1000, node)
        plan = place_plan(make_alloc(node))
        assert not self._verdict(store, plan, node.id)

    def test_node_drain(self, store):
        node = mock.node()
        node.drain = True
        store.upsert_node(1000, node)
        plan = place_plan(make_alloc(node))
        assert not self._verdict(store, plan, node.id)

    def test_node_full(self, store):
        node = mock.node()
        store.upsert_node(1000, node)
        store.upsert_allocs(1001, [make_alloc(node, cpu=FREE_CPU)])
        plan = place_plan(make_alloc(node, cpu=1000))
        assert not self._verdict(store, plan, node.id)

    def test_update_existing_in_place(self, store):
        """A plan REPLACING the alloc that fills the node fits: the
        proposed set removes the old copy first (in-place update
        semantics, upstream _UpdateExisting)."""
        node = mock.node()
        store.upsert_node(1000, node)
        existing = make_alloc(node, cpu=FREE_CPU)
        store.upsert_allocs(1001, [existing])
        replacement = existing.copy()
        plan = place_plan(replacement)
        assert self._verdict(store, plan, node.id)

    def test_node_full_with_evict(self, store):
        """Eviction in the same plan frees the capacity the placement
        needs (upstream _NodeFull_Evict)."""
        node = mock.node()
        store.upsert_node(1000, node)
        existing = make_alloc(node, cpu=FREE_CPU)
        store.upsert_allocs(1001, [existing])
        plan = place_plan(make_alloc(node, cpu=1000))
        plan.append_update(existing, ALLOC_DESIRED_STATUS_STOP, "evict")
        assert self._verdict(store, plan, node.id)

    def test_node_full_terminal_alloc_ignored(self, store):
        """A terminal existing alloc no longer holds resources
        (upstream _NodeFull_AllocEvict)."""
        node = mock.node()
        store.upsert_node(1000, node)
        store.upsert_allocs(1001, [
            make_alloc(node, cpu=FREE_CPU,
                       desired=ALLOC_DESIRED_STATUS_STOP)])
        plan = place_plan(make_alloc(node, cpu=1000))
        assert self._verdict(store, plan, node.id)

    def test_evict_only_on_down_node(self, store):
        """Evictions need no node health — a down node's allocs must
        still be stoppable (upstream _NodeDown_EvictOnly)."""
        node = mock.node()
        store.upsert_node(1000, node)
        existing = make_alloc(node)
        store.upsert_allocs(1001, [existing])
        store.update_node_status(1002, node.id, NODE_STATUS_DOWN)
        plan = Plan(eval_id=generate_uuid())
        plan.append_update(existing, ALLOC_DESIRED_STATUS_STOP, "evict")
        assert self._verdict(store, plan, node.id)


# ---------------------------------------------------------------------------
# 3. applyPlan end to end, the snapshot-vs-commit drain window, and the
#    optimistic verify/apply overlay
# ---------------------------------------------------------------------------

class TestApplyPlan:
    def test_apply_plan_end_to_end(self):
        """TestPlanApply_applyPlan: a token-fenced plan flows queue ->
        applier -> raft -> FSM; the result carries the commit index and
        the allocs land in state."""
        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0))
        srv.establish_leadership()
        try:
            node = mock.node()
            srv.node_register(node)
            from nomad_tpu.structs import Evaluation
            ev = Evaluation(id=generate_uuid(), priority=50,
                            type="service", job_id="j1",
                            status="pending",
                            triggered_by="job-register")
            srv.apply_eval_update([ev])
            got, token = srv.eval_broker.dequeue(["service"], timeout=2)
            assert got.id == ev.id

            plan = place_plan(make_alloc(node))
            plan.eval_id = ev.id
            plan.eval_token = token
            result = srv.plan_queue.enqueue(plan).wait(5.0)
            assert result.alloc_index > 0
            placed = srv.fsm.state.allocs_by_node(node.id)
            assert [a.id for a in placed] == \
                [a.id for v in plan.node_allocation.values() for a in v]
        finally:
            srv.shutdown()

    def test_snapshot_vs_commit_drain_window(self, store):
        """The applier verifies against a SNAPSHOT: a drain landing
        between snapshot and commit is invisible to that verification
        (same window as the reference, plan_apply.go:238-284 — README
        Known limits) — but any verification on a post-drain snapshot
        rejects."""
        node = mock.node()
        store.upsert_node(1000, node)
        snap = store.snapshot()            # applier's view
        plan = place_plan(make_alloc(node))
        # Drain lands INSIDE the window (after snapshot, before apply).
        store.update_node_drain(1001, node.id, True)
        inside = evaluate_plan(snap, plan)
        assert inside.node_allocation == plan.node_allocation, \
            "the drain window is open by design: snapshot-time verdicts"
        after = evaluate_plan(store.snapshot(), plan)
        assert after.node_allocation == {}
        assert after.refresh_index > 0

    def test_optimistic_overlay_catches_uncommitted_conflicts(self, store):
        """Verify/apply overlap: plan N+1 must be checked against plan
        N's not-yet-committed allocs (OptimisticSnapshot), or two
        optimistic schedulers double-book the node."""
        node = mock.node()
        store.upsert_node(1000, node)
        snap = OptimisticSnapshot(store.snapshot())

        plan_n = place_plan(make_alloc(node, cpu=FREE_CPU))
        result_n = evaluate_plan(snap, plan_n)
        assert result_n.node_allocation == plan_n.node_allocation
        # Fold plan N's result into the overlay (raft apply in flight).
        snap.upsert_allocs(
            [a for v in result_n.node_allocation.values() for a in v])

        plan_n1 = place_plan(make_alloc(node, cpu=1000))
        overlay_verdict = evaluate_plan(snap, plan_n1)
        assert overlay_verdict.node_allocation == {}, \
            "overlay must reject the double-booked node"
        assert overlay_verdict.refresh_index > 0
        # Against the bare base snapshot the conflict is invisible —
        # which is exactly why the overlay exists.
        base_verdict = evaluate_plan(store.snapshot(), plan_n1)
        assert base_verdict.node_allocation == plan_n1.node_allocation

    def test_overlay_eviction_then_replacement_window(self, store):
        """Drain-window companion on the alloc axis: an eviction folded
        into the overlay frees capacity for the NEXT plan in the same
        apply window."""
        node = mock.node()
        store.upsert_node(1000, node)
        existing = make_alloc(node, cpu=FREE_CPU)
        store.upsert_allocs(1001, [existing])
        snap = OptimisticSnapshot(store.snapshot())

        evict = Plan(eval_id=generate_uuid())
        evict.append_update(existing, ALLOC_DESIRED_STATUS_STOP, "gone")
        result = evaluate_plan(snap, evict)
        assert result.node_update == evict.node_update
        snap.upsert_allocs(
            [a for v in result.node_update.values() for a in v])

        refill = place_plan(make_alloc(node, cpu=FREE_CPU))
        verdict = evaluate_plan(snap, refill)
        assert verdict.node_allocation == refill.node_allocation, \
            "overlay must see the eviction's freed capacity"
