"""Second state-store scenario suite, mirroring the reference's table
coverage (nomad/state/state_store_test.go): per-table CRUD + raft-index
bumps, secondary-index maintenance on delete/replace, JobsByScheduler,
eval deletion cascading to its allocations' index, client-vs-scheduler
authoritative merge on replace, and restore of every table."""
from __future__ import annotations

from nomad_tpu import mock
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import (
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    Allocation,
    Evaluation,
    Resources,
    generate_uuid,
)


def _alloc(node_id="n1", job_id="j1", eval_id="e1", **kw):
    defaults = dict(
        id=generate_uuid(), node_id=node_id, job_id=job_id,
        eval_id=eval_id, task_group="web",
        resources=Resources(cpu=100, memory_mb=64),
        desired_status=ALLOC_DESIRED_STATUS_RUN,
    )
    defaults.update(kw)
    return Allocation(**defaults)


# ---------------------------------------------------------------------------
# nodes (state_store_test.go:24-214)
# ---------------------------------------------------------------------------

def test_delete_node_removes_and_bumps_index():
    s = StateStore()
    n = mock.node(0)
    s.upsert_node(1000, n)
    assert s.get_index("nodes") == 1000
    s.delete_node(1001, n.id)
    assert s.node_by_id(n.id) is None
    assert s.get_index("nodes") == 1001
    assert list(s.nodes()) == []


def test_nodes_iterates_all():
    s = StateStore()
    nodes = [mock.node(i) for i in range(5)]
    for i, n in enumerate(nodes):
        s.upsert_node(1000 + i, n)
    assert {n.id for n in s.nodes()} == {n.id for n in nodes}


def test_upsert_node_replaces_existing():
    s = StateStore()
    n = mock.node(0)
    s.upsert_node(1000, n)
    n2 = mock.node(0)
    n2.id = n.id
    n2.datacenter = "dc9"
    s.upsert_node(1001, n2)
    assert s.node_by_id(n.id).datacenter == "dc9"
    assert len(list(s.nodes())) == 1


# ---------------------------------------------------------------------------
# jobs (state_store_test.go:215-443)
# ---------------------------------------------------------------------------

def test_update_job_keeps_create_index():
    s = StateStore()
    job = mock.job()
    s.upsert_job(1000, job)
    assert s.job_by_id(job.id).create_index == 1000
    assert s.job_by_id(job.id).modify_index == 1000
    j2 = mock.job()
    j2.id = job.id
    s.upsert_job(1010, j2)
    got = s.job_by_id(job.id)
    assert got.create_index == 1000      # preserved across update
    assert got.modify_index == 1010      # bumped
    assert s.get_index("jobs") == 1010


def test_delete_job():
    s = StateStore()
    job = mock.job()
    s.upsert_job(1000, job)
    s.delete_job(1001, job.id)
    assert s.job_by_id(job.id) is None
    assert s.get_index("jobs") == 1001
    assert list(s.jobs()) == []


def test_jobs_by_scheduler():
    s = StateStore()
    svc, system = mock.job(), mock.system_job()
    s.upsert_job(1000, svc)
    s.upsert_job(1001, system)
    assert [j.id for j in s.jobs_by_scheduler("service")] == [svc.id]
    assert [j.id for j in s.jobs_by_scheduler("system")] == [system.id]
    assert s.jobs_by_scheduler("batch") == []


# ---------------------------------------------------------------------------
# evals (state_store_test.go:502-746)
# ---------------------------------------------------------------------------

def test_upsert_evals_update_and_index():
    s = StateStore()
    ev = mock.eval()
    s.upsert_evals(1000, [ev])
    got = s.eval_by_id(ev.id)
    assert got.create_index == 1000 and got.modify_index == 1000
    ev2 = ev.copy()
    ev2.status = "complete"
    s.upsert_evals(1003, [ev2])
    got = s.eval_by_id(ev.id)
    assert got.status == "complete"
    assert got.create_index == 1000 and got.modify_index == 1003
    assert s.get_index("evals") == 1003


def test_delete_eval_cascades_to_allocs():
    s = StateStore()
    ev = mock.eval()
    s.upsert_evals(1000, [ev])
    a1 = _alloc(eval_id=ev.id)
    a2 = _alloc(eval_id=ev.id)
    keeper = _alloc(eval_id="other-eval")
    s.upsert_allocs(1001, [a1, a2, keeper])
    s.delete_eval(1002, [ev.id], [a1.id, a2.id])
    assert s.eval_by_id(ev.id) is None
    assert s.alloc_by_id(a1.id) is None
    assert s.alloc_by_id(a2.id) is None
    assert s.alloc_by_id(keeper.id) is not None
    assert s.get_index("evals") == 1002
    assert s.get_index("allocs") == 1002
    # Secondary indexes must not resurrect the dead.
    assert s.allocs_by_eval(ev.id) == []


def test_evals_by_job_multiple():
    s = StateStore()
    evs = [mock.eval() for _ in range(3)]
    for ev in evs:
        ev.job_id = "j-common"
    s.upsert_evals(1000, evs)
    assert {e.id for e in s.evals_by_job("j-common")} == \
        {e.id for e in evs}
    assert {e.id for e in s.evals()} == {e.id for e in evs}


# ---------------------------------------------------------------------------
# allocs (state_store_test.go:747-1008)
# ---------------------------------------------------------------------------

def test_alloc_replace_moves_secondary_indexes():
    s = StateStore()
    a = _alloc(node_id="n1")
    s.upsert_allocs(1000, [a])
    assert [x.id for x in s.allocs_by_node("n1")] == [a.id]
    moved = _alloc(node_id="n2")
    moved.id = a.id
    s.upsert_allocs(1001, [moved])
    assert s.allocs_by_node("n1") == []
    assert [x.id for x in s.allocs_by_node("n2")] == [a.id]
    assert len(list(s.allocs())) == 1


def test_evict_alloc_keeps_record_desired_stop():
    s = StateStore()
    a = _alloc()
    s.upsert_allocs(1000, [a])
    evicted = a.copy()
    evicted.desired_status = ALLOC_DESIRED_STATUS_STOP
    s.upsert_allocs(1001, [evicted])
    got = s.alloc_by_id(a.id)
    assert got.desired_status == ALLOC_DESIRED_STATUS_STOP
    assert got.terminal_status()
    # Still listed (the reference keeps evicted allocs until GC).
    assert [x.id for x in s.allocs_by_job(a.job_id)] == [a.id]


def test_allocs_by_node_and_job():
    s = StateStore()
    batch = [_alloc(node_id="nA", job_id="j1"),
             _alloc(node_id="nA", job_id="j2"),
             _alloc(node_id="nB", job_id="j1")]
    s.upsert_allocs(1000, batch)
    assert len(s.allocs_by_node("nA")) == 2
    assert len(s.allocs_by_job("j1")) == 2
    assert s.has_allocs_on_node("nA") and not s.has_allocs_on_node("nC")


def test_watch_allocs_fires_on_upsert():
    s = StateStore()
    ev = s.watch.watch(("allocs",))
    s.upsert_allocs(1000, [_alloc()])
    assert ev.wait(1.0)


# ---------------------------------------------------------------------------
# restore of every table (state_store_test.go:189, 418, 476, 721, 1009)
# ---------------------------------------------------------------------------

def test_restore_every_table_and_indexes():
    s = StateStore()
    s.upsert_node(1, mock.node(0))  # pre-restore world, to be replaced

    node, job, ev = mock.node(1), mock.job(), mock.eval()
    alloc = _alloc(node_id=node.id, job_id=job.id, eval_id=ev.id)
    r = s.restore()
    r.node_restore(node)
    r.job_restore(job)
    r.eval_restore(ev)
    r.alloc_restore(alloc)
    r.index_restore("nodes", 5000)
    r.index_restore("jobs", 5001)
    r.index_restore("evals", 5002)
    r.index_restore("allocs", 5003)
    r.commit()

    assert {n.id for n in s.nodes()} == {node.id}
    assert s.job_by_id(job.id) is not None
    assert s.eval_by_id(ev.id) is not None
    assert s.alloc_by_id(alloc.id) is not None
    assert [x.id for x in s.allocs_by_node(node.id)] == [alloc.id]
    assert [x.id for x in s.allocs_by_eval(ev.id)] == [alloc.id]
    assert s.get_index("nodes") == 5000
    assert s.get_index("allocs") == 5003
    assert s.latest_index() >= 5003
