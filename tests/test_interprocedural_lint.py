"""Unit + regression tests for the interprocedural analyzers (PR 4).

Layout mirrors tests/test_static_analysis.py's philosophy:

1. **Call-graph units** — resolution facts the passes depend on
   (self/MRO, attr types, typed module constants, jit aliases,
   self-coverage accounting).
2. **Pass units on synthetic packages** — every new rule
   (blocking-under-lock, interprocedural lock-cycle /
   nested-self-acquire, thread/future/event lifecycle,
   immutable-write) proves it fires AND proves its exemptions hold;
   a lint that cannot fail gates nothing.
3. **Regression tests for the defects the passes surfaced** in the real
   package — each was a genuine pre-existing bug fixed in this PR.
"""
from __future__ import annotations

import textwrap
import threading
import time

import pytest

from nomad_tpu.analysis import blocking, lockcheck
from nomad_tpu.analysis.callgraph import CallGraph

from tests.conftest import wait_until


def write_pkg(tmp_path, name, source) -> str:
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    (d / "mod.py").write_text(textwrap.dedent(source))
    return str(d)


def run_blocking(pkg: str) -> list:
    scan = lockcheck.scan_package(pkg)
    lockcheck.analyze_package(pkg, scan=scan)  # populates cycle dedup
    return blocking.analyze_package(pkg, scan=scan)


# ---------------------------------------------------------------------------
# 1. call-graph units
# ---------------------------------------------------------------------------

class TestCallGraph:
    def test_self_and_mro_resolution(self, tmp_path):
        pkg = write_pkg(tmp_path, "cg1", """
            class Base:
                def helper(self):
                    return 1
            class Derived(Base):
                def run(self):
                    return self.helper()
        """)
        g = CallGraph.build(pkg)
        calls = list(g.callees("cg1.mod:Derived.run"))
        assert calls[0].kind == "intra"
        assert calls[0].callee == "cg1.mod:Base.helper"

    def test_attr_type_and_module_constant(self, tmp_path):
        pkg = write_pkg(tmp_path, "cg2", """
            class Engine:
                def fire(self):
                    pass
            GLOBAL = Engine()
            class Car:
                def __init__(self, engine=None):
                    self.engine = engine if engine is not None else GLOBAL
                def drive(self):
                    self.engine.fire()
                    GLOBAL.fire()
        """)
        g = CallGraph.build(pkg)
        calls = {c.text: c.callee
                 for c in g.callees("cg2.mod:Car.drive")}
        assert calls["self.engine.fire"] == "cg2.mod:Engine.fire"
        assert calls["GLOBAL.fire"] == "cg2.mod:Engine.fire"

    def test_jit_alias_reaches_wrapped_impl(self, tmp_path):
        pkg = write_pkg(tmp_path, "cg3", """
            import jax

            def _impl(x):
                return x

            kernel = jax.jit(_impl)

            def caller(x):
                return kernel(x)
        """)
        g = CallGraph.build(pkg)
        calls = list(g.callees("cg3.mod:caller"))
        assert calls[0].callee == "cg3.mod:_impl"

    def test_nested_def_calls_not_attributed_to_parent(self, tmp_path):
        pkg = write_pkg(tmp_path, "cg4", """
            import time
            def outer():
                def inner():
                    time.sleep(1)
                return inner
        """)
        g = CallGraph.build(pkg)
        outer = [c.text for c in g.callees("cg4.mod:outer")]
        assert "time.sleep" not in outer
        inner = [c.callee for c in g.callees("cg4.mod:outer.inner")]
        assert "time.sleep" in inner

    def test_coverage_counts_dynamic_sites(self, tmp_path):
        pkg = write_pkg(tmp_path, "cg5", """
            def f(cb):
                cb()        # dynamic
                len([])     # builtin
                g()         # intra
            def g():
                pass
        """)
        g = CallGraph.build(pkg)
        cov = g.coverage()
        assert cov["dynamic"] == 1 and cov["builtin"] == 1 \
            and cov["resolved"] == 1
        assert 0 < cov["resolved_fraction"] < 1

    def test_real_package_coverage_is_reported(self):
        g = CallGraph.build("nomad_tpu")
        cov = g.coverage()
        assert cov["functions"] > 500
        assert cov["call_sites"] > 2000
        # The analyzer's blind spots are visible, not silent.
        assert cov["dynamic"] > 0
        assert 0.3 < cov["resolved_fraction"] <= 1.0


# ---------------------------------------------------------------------------
# 2a. blocking-under-lock units
# ---------------------------------------------------------------------------

class TestBlockingUnderLock:
    def test_direct_sleep_under_lock(self, tmp_path):
        pkg = write_pkg(tmp_path, "b1", """
            import threading
            import time
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def bad(self):
                    with self._lock:
                        time.sleep(1)
        """)
        fs = [f for f in run_blocking(pkg)
              if f.rule == "blocking-under-lock"]
        assert len(fs) == 1
        assert fs[0].where == "C.bad[C._lock]"
        assert "time.sleep" in fs[0].message

    def test_chain_through_helpers_flagged_with_chain(self, tmp_path):
        pkg = write_pkg(tmp_path, "b2", """
            import socket
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.sock = socket.socket()
                def a(self):
                    with self._lock:
                        self.b()
                def b(self):
                    self.c()
                def c(self):
                    self.sock.sendall(b"x")
        """)
        fs = [f for f in run_blocking(pkg)
              if f.rule == "blocking-under-lock"]
        assert len(fs) == 1
        assert fs[0].where == "C.a[C._lock]"
        # The full call chain is in the finding.
        assert "self.b" in fs[0].message and "socket send" in fs[0].message

    def test_condition_wait_on_guarding_lock_exempt(self, tmp_path):
        pkg = write_pkg(tmp_path, "b3", """
            import threading
            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)
                    self.items = []
                def get(self):
                    with self._lock:
                        while not self.items:
                            self._cond.wait(1.0)
                        return self.items.pop()
        """)
        assert [f for f in run_blocking(pkg)
                if f.rule == "blocking-under-lock"] == []

    def test_foreign_lock_held_across_wait_still_flagged(self, tmp_path):
        pkg = write_pkg(tmp_path, "b4", """
            import threading
            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition(self._lock)
                    self.items = []
                def get(self):
                    with self._lock:
                        self._cond.wait(1.0)
            class User:
                def __init__(self):
                    self._mu = threading.Lock()
                    self.q = Q()
                def drain(self):
                    with self._mu:
                        self.q.get()
        """)
        fs = [f for f in run_blocking(pkg)
              if f.rule == "blocking-under-lock"]
        assert any(f.where == "User.drain[User._mu]" for f in fs)

    def test_unbounded_queue_put_not_a_root(self, tmp_path):
        pkg = write_pkg(tmp_path, "b5", """
            import queue
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()          # unbounded
                    self._bq = queue.Queue(maxsize=4)  # bounded
                def ok(self):
                    with self._lock:
                        self._q.put(1)
                def ok_negative(self):
                    # stdlib: maxsize <= 0 is unbounded too
                    import queue as q2
                    nq = q2.Queue(-1)
                    with self._lock:
                        nq.put(1)
                def bad(self):
                    with self._lock:
                        self._bq.put(1)
                def also_bad(self):
                    with self._lock:
                        self._q.get()
        """)
        fs = [f for f in run_blocking(pkg)
              if f.rule == "blocking-under-lock"]
        wheres = {f.where for f in fs}
        assert "C.bad[C._lock]" in wheres
        assert "C.also_bad[C._lock]" in wheres
        assert "C.ok[C._lock]" not in wheres
        assert "C.ok_negative[C._lock]" not in wheres

    def test_retry_sleep_path_via_typed_constant(self, tmp_path):
        """The utils/retry.py shape: a module-level policy object whose
        .call sleeps, invoked under a lock three frames up."""
        pkg = write_pkg(tmp_path, "b6", """
            import threading
            import time
            class Policy:
                def call(self, fn):
                    time.sleep(0.1)
                    return fn()
            POLICY = Policy()
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def sync(self):
                    with self._lock:
                        self._locked_sync()
                def _locked_sync(self):
                    POLICY.call(lambda: None)
        """)
        fs = [f for f in run_blocking(pkg)
              if f.rule == "blocking-under-lock"]
        assert any(f.where == "C.sync[C._lock]" for f in fs)

    def test_acquire_release_region_tracked(self, tmp_path):
        """The try/finally acquire pattern extends the held region."""
        pkg = write_pkg(tmp_path, "b7", """
            import threading
            import time
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def bad(self):
                    self._lock.acquire()
                    try:
                        time.sleep(1)
                    finally:
                        self._lock.release()
        """)
        fs = [f for f in run_blocking(pkg)
              if f.rule == "blocking-under-lock"]
        assert len(fs) == 1 and fs[0].where == "C.bad[C._lock]"

    def test_device_dispatch_is_a_root(self, tmp_path):
        pkg = write_pkg(tmp_path, "b8", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def bad(self, sched, args):
                    with self._lock:
                        sched.collect_device(args, None)
        """)
        fs = [f for f in run_blocking(pkg)
              if f.rule == "blocking-under-lock"]
        assert len(fs) == 1 and "device collect" in fs[0].message


# ---------------------------------------------------------------------------
# 2b. cross-function lock-order units
# ---------------------------------------------------------------------------

class TestCrossFunctionLockOrder:
    def test_cycle_visible_only_interprocedurally(self, tmp_path):
        """A->B syntactically, B->A only through a helper whose callee
        resolves via a parameter annotation — and whose method name is
        deliberately ambiguous (two lock-holding owners), so lockcheck's
        uniqueness devirtualizer cannot see the back edge.  Only the
        call-graph pass closes the cycle."""
        pkg = write_pkg(tmp_path, "xl1", """
            import threading
            class Decoy:
                def __init__(self):
                    self._lock = threading.Lock()
                def touch(self):
                    with self._lock:
                        pass
            class A:
                def __init__(self):
                    self._lock = threading.Lock()
                def touch(self):
                    with self._lock:
                        pass
                def forward(self, b: "B"):
                    with self._lock:
                        b.poke()
            class B:
                def __init__(self):
                    self._lock = threading.Lock()
                def poke(self):
                    with self._lock:
                        pass
                def back(self, a: A):
                    with self._lock:
                        self._helper(a)
                def _helper(self, a: A):
                    a.touch()
        """)
        scan = lockcheck.scan_package(pkg)
        lc = lockcheck.analyze_package(pkg, scan=scan)
        assert [f for f in lc if f.rule == "lock-cycle"] == []
        fs = blocking.analyze_package(pkg, scan=scan)
        cycles = [f for f in fs if f.rule == "lock-cycle"]
        assert cycles, [f.render() for f in fs]
        assert "A._lock" in cycles[0].where and "B._lock" in \
            cycles[0].where

    def test_interprocedural_self_acquire(self, tmp_path):
        pkg = write_pkg(tmp_path, "xl2", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def outer(self):
                    with self._lock:
                        self._middle()
                def _middle(self):
                    self._leaf()
                def _leaf(self):
                    with self._lock:
                        pass
        """)
        fs = run_blocking(pkg)
        hits = [f for f in fs if f.rule == "nested-self-acquire"]
        assert hits and hits[0].where.startswith("C.outer->")

    def test_rlock_self_acquire_not_flagged(self, tmp_path):
        pkg = write_pkg(tmp_path, "xl3", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.RLock()
                def outer(self):
                    with self._lock:
                        self._middle()
                def _middle(self):
                    self._leaf()
                def _leaf(self):
                    with self._lock:
                        pass
        """)
        fs = run_blocking(pkg)
        assert [f for f in fs if f.rule == "nested-self-acquire"] == []

    def test_syntactic_cycles_not_double_reported(self, tmp_path):
        """A cycle lockcheck's own pass sees must NOT come back from the
        interprocedural pass under a second key."""
        pkg = write_pkg(tmp_path, "xl4", """
            import threading
            class Inner:
                def __init__(self):
                    self._lock = threading.Lock()
                def poke(self, outer):
                    with self._lock:
                        outer.touch()
            class Outer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.inner = Inner()
                def go(self):
                    with self._lock:
                        self.inner.poke(self)
                def touch(self):
                    with self._lock:
                        pass
        """)
        scan = lockcheck.scan_package(pkg)
        lc = lockcheck.analyze_package(pkg, scan=scan)
        assert any(f.rule == "lock-cycle" for f in lc)
        bl = blocking.analyze_package(pkg, scan=scan)
        assert [f for f in bl if f.rule == "lock-cycle"] == []


# ---------------------------------------------------------------------------
# 2c. lifecycle units
# ---------------------------------------------------------------------------

class TestLifecycle:
    def test_anonymous_thread_flagged(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf1", """
            import threading
            def spawn():
                threading.Thread(target=print, daemon=True).start()
        """)
        fs = run_blocking(pkg)
        hits = [f for f in fs if f.rule == "thread-leak"]
        assert len(hits) == 1 and "<anonymous>" in hits[0].where

    def test_attr_thread_without_join_flagged(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf2", """
            import threading
            class C:
                def start(self):
                    self._t = threading.Thread(target=print)
                    self._t.start()
        """)
        fs = run_blocking(pkg)
        assert [f.rule for f in fs] == ["thread-leak"]

    def test_attr_thread_with_join_clean(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf3", """
            import threading
            class C:
                def start(self):
                    self._t = threading.Thread(target=print)
                    self._t.start()
                def stop(self):
                    self._t.join(1.0)
        """)
        assert [f for f in run_blocking(pkg)
                if f.rule == "thread-leak"] == []

    def test_local_thread_handed_off_clean(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf4", """
            import threading
            class C:
                def __init__(self):
                    self._threads = []
                def start(self):
                    t = threading.Thread(target=print)
                    t.start()
                    self._threads.append(t)
                def stop(self):
                    for t in self._threads:
                        t.join(1.0)
        """)
        assert [f for f in run_blocking(pkg)
                if f.rule == "thread-leak"] == []

    def test_future_without_resolution_flagged(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf5", """
            import threading
            class ApplyFuture:
                def __init__(self):
                    self._event = threading.Event()
                def respond(self):
                    self._event.set()
                def wait(self):
                    self._event.wait(5)
            class Broken:
                def submit(self):
                    f = ApplyFuture()
                    f.wait()
        """)
        fs = run_blocking(pkg)
        hits = [f for f in fs if f.rule == "future-leak"]
        assert len(hits) == 1 and hits[0].where == "Broken.submit.f"

    def test_future_responded_or_returned_clean(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf6", """
            import threading
            class ApplyFuture:
                def __init__(self):
                    self._event = threading.Event()
                def respond(self):
                    self._event.set()
            class Good:
                def submit(self):
                    f = ApplyFuture()
                    return f
                def apply(self):
                    f = ApplyFuture()
                    f.respond()
        """)
        assert [f for f in run_blocking(pkg)
                if f.rule == "future-leak"] == []

    def test_untimed_event_wait_without_set_flagged(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf7", """
            import threading
            class C:
                def __init__(self):
                    self._ready = threading.Event()
                def block(self):
                    self._ready.wait()
        """)
        fs = run_blocking(pkg)
        hits = [f for f in fs if f.rule == "event-leak"]
        assert len(hits) == 1 and "_ready" in hits[0].where

    def test_event_with_set_or_timeout_clean(self, tmp_path):
        pkg = write_pkg(tmp_path, "lf8", """
            import threading
            class C:
                def __init__(self):
                    self._ready = threading.Event()
                    self._gone = threading.Event()
                def block(self):
                    self._ready.wait()
                def arm(self):
                    self._ready.set()
                def poll(self):
                    self._gone.wait(0.5)
        """)
        assert [f for f in run_blocking(pkg)
                if f.rule == "event-leak"] == []


# ---------------------------------------------------------------------------
# 2d. Immutable / CopySwap annotation units
# ---------------------------------------------------------------------------

class TestSyncAnnotations:
    def test_immutable_suppresses_bare_read(self, tmp_path):
        pkg = write_pkg(tmp_path, "sa1", """
            import threading
            from nomad_tpu.utils.sync import Immutable
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.addr: Immutable = ("h", 1)
                def locked_use(self):
                    with self._lock:
                        return self.addr
                def bare_use(self):
                    return self.addr
        """)
        assert lockcheck.analyze_package(pkg, strict=True) == []

    def test_immutable_write_after_init_flagged(self, tmp_path):
        pkg = write_pkg(tmp_path, "sa2", """
            import threading
            from nomad_tpu.utils.sync import Immutable
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.addr: Immutable = ("h", 1)
                def locked_use(self):
                    with self._lock:
                        return self.addr
                def rebind(self):
                    with self._lock:
                        self.addr = ("h", 2)   # locked, still illegal
        """)
        fs = lockcheck.analyze_package(pkg, strict=True)
        assert [f.rule for f in fs] == ["immutable-write"]
        assert fs[0].where == "C.addr" and "rebind" in fs[0].message

    def test_immutable_receiver_mutation_is_not_a_rebind(self, tmp_path):
        """Calling a mutator on the OBJECT (log.append) is the object's
        own business; Immutable only promises the binding is stable."""
        pkg = write_pkg(tmp_path, "sa3", """
            import threading
            from nomad_tpu.utils.sync import Immutable
            class Store:
                def append(self, x):
                    pass
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.store: Immutable = Store()
                def locked_use(self):
                    with self._lock:
                        self.store.append(1)
                def bare_use(self):
                    self.store.append(2)
        """)
        assert lockcheck.analyze_package(pkg, strict=True) == []

    def test_copy_swap_reads_exempt_writes_still_locked(self, tmp_path):
        pkg = write_pkg(tmp_path, "sa4", """
            import threading
            from nomad_tpu.utils.sync import CopySwap
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state: CopySwap = {}
                def publish(self, new):
                    with self._lock:
                        self.state = new
                def read(self):
                    return self.state          # exempt
                def bad(self, new):
                    self.state = new           # still a bare-write
        """)
        fs = lockcheck.analyze_package(pkg, strict=True)
        assert [f.rule for f in fs] == ["bare-write"]
        assert fs[0].where == "C.state"

    def test_markers_are_inert_at_runtime(self):
        from nomad_tpu.utils.sync import CopySwap, Immutable

        assert Immutable[str] is Immutable
        assert CopySwap[dict] is CopySwap
        with pytest.raises(TypeError):
            Immutable()


# ---------------------------------------------------------------------------
# 3. regression tests for analyzer-found defects fixed in this PR
# ---------------------------------------------------------------------------

class TestAnalyzerFoundDefects:
    def test_pool_dial_does_not_block_other_addresses(self, monkeypatch):
        """blocking-under-lock ConnPool._session: the MuxConn dial (up
        to the 330s connect timeout) ran INSIDE the pool-wide lock, so
        one dead peer stalled every thread's RPC to every address."""
        from nomad_tpu.server import rpc as rpc_mod

        hang = threading.Event()
        release = threading.Event()

        class StubMux:
            def __init__(self, address, tls_context=None,
                         server_hostname=""):
                self.address = address
                if address[1] == 1:   # the "dead" peer
                    hang.set()
                    release.wait(10)
                self.broken = False

            def call(self, method, args, timeout=None):
                return {"ok": self.address[1]}

            def close(self):
                pass

        monkeypatch.setattr(rpc_mod, "MuxConn", StubMux)
        pool = rpc_mod.ConnPool()
        t = threading.Thread(
            target=lambda: pool._session(("127.0.0.1", 1)), daemon=True)
        t.start()
        assert hang.wait(5), "dial thread never started"
        # While address 1's dial hangs, address 2 must connect at once.
        start = time.monotonic()
        out = pool.call(("127.0.0.1", 2), "X.y", {})
        elapsed = time.monotonic() - start
        release.set()
        t.join(5)
        assert out == {"ok": 2}
        assert elapsed < 2.0, \
            f"call to a healthy peer waited {elapsed:.1f}s on a dead " \
            "peer's dial"

    def test_session_redial_race_keeps_one_session(self, monkeypatch):
        """Two threads re-dialing the same broken address converge on
        ONE installed session; the loser is closed, not leaked."""
        from nomad_tpu.server import rpc as rpc_mod

        closed = []

        class StubMux:
            def __init__(self, address, tls_context=None,
                         server_hostname=""):
                self.address = address
                self.broken = False

            def close(self):
                closed.append(self)

        monkeypatch.setattr(rpc_mod, "MuxConn", StubMux)
        pool = rpc_mod.ConnPool()
        addr = ("127.0.0.1", 9)
        sessions = []
        threads = [threading.Thread(
            target=lambda: sessions.append(pool._session(addr)))
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert len(sessions) == 4
        installed = pool._sessions[addr]
        assert all(s is installed for s in sessions) or \
            len({id(s) for s in sessions}) <= 2
        # Everything not installed was closed.
        for s in set(sessions):
            if s is not installed:
                assert s in closed

    def test_gossip_shutdown_reaps_loops(self):
        """thread-leak Gossip._rx/_probe: shutdown set the stop event
        but never joined, leaking two threads per torn-down server."""
        from nomad_tpu.server.gossip import Gossip

        g = Gossip(tags={"role": "t"})
        assert g._rx.is_alive() and g._probe.is_alive()
        g.shutdown()
        assert not g._rx.is_alive(), "rx loop still running"
        assert not g._probe.is_alive(), "probe loop still running"

    def test_netraft_shutdown_reaps_all_threads(self):
        """thread-leak NetRaft._ticker/_notifier/_PeerReplicator:
        shutdown signaled the threads but never joined them."""
        from nomad_tpu.server.raft_net import NetRaft
        from nomad_tpu.server.rpc import ConnPool, RPCServer

        class NullFSM:
            def apply(self, index, entry):
                return None

            def snapshot(self):
                return b"{}"

            def restore(self, blob):
                pass

        rpc = RPCServer()
        rpc.start()
        pool = ConnPool(multiplex=False)
        raft = NetRaft(NullFSM(), rpc, pool,
                       election_timeout=(5.0, 6.0))
        raft.add_peer(("127.0.0.1", 65500))  # unreachable peer
        repl = list(raft._replicators.values())[0]
        assert raft._ticker.is_alive() and raft._notifier.is_alive()
        raft.shutdown()
        assert not raft._ticker.is_alive()
        assert not raft._notifier.is_alive()
        assert not repl.thread.is_alive()
        rpc.shutdown()
        pool.shutdown()
        assert rpc._thread is not None and not rpc._thread.is_alive()

    def test_netraft_remove_peer_reaps_replicator(self):
        from nomad_tpu.server.raft_net import NetRaft
        from nomad_tpu.server.rpc import ConnPool, RPCServer

        class NullFSM:
            def apply(self, index, entry):
                return None

            def snapshot(self):
                return b"{}"

            def restore(self, blob):
                pass

        rpc = RPCServer()
        rpc.start()
        pool = ConnPool(multiplex=False)
        raft = NetRaft(NullFSM(), rpc, pool,
                       election_timeout=(5.0, 6.0))
        peer = ("127.0.0.1", 65501)
        raft.add_peer(peer)
        repl = raft._replicators[peer]
        raft.remove_peer(peer)
        assert not repl.thread.is_alive()
        raft.shutdown()
        rpc.shutdown()
        pool.shutdown()

    def test_muxconn_close_reaps_reader(self):
        """thread-leak MuxConn._reader: close() left the reader thread
        parked in recv on the dead socket."""
        from nomad_tpu.server.rpc import MuxConn, RPCServer

        server = RPCServer()
        server.register("Echo.ping", lambda args: {"pong": True})
        server.start()
        conn = MuxConn(server.address)
        assert conn.call("Echo.ping", {}) == {"pong": True}
        reader = conn._reader
        assert reader.is_alive()
        conn.close()
        assert not reader.is_alive(), "reader thread survived close()"
        server.shutdown()

    def test_server_shutdown_joins_workers(self):
        """thread-leak Worker._thread: server shutdown stopped workers
        without joining them."""
        from nomad_tpu.server.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=1,
                                  use_device_scheduler=False,
                                  tune_gc=False))
        srv.establish_leadership()
        threads = [w._thread for w in srv.workers]
        assert all(t is not None and t.is_alive() for t in threads)
        srv.shutdown()
        for t in threads:
            assert not t.is_alive(), "worker thread survived shutdown"

    def test_broken_mux_session_error_is_lock_consistent(self):
        """bare-read MuxConn._broken: the 'reader died' error path read
        _broken without the lock; now both the property and the raise
        read it under _lock (no torn read of the exception slot)."""
        from nomad_tpu.server.rpc import MuxConn, RPCServer

        server = RPCServer()
        server.register("Echo.ping", lambda args: {"pong": True})
        server.start()
        conn = MuxConn(server.address)
        server.shutdown()  # severs the live connection server-side
        wait_until(lambda: conn.broken, timeout=5,
                   msg="reader observes the severed session")
        from nomad_tpu.server.rpc import _SendError
        with pytest.raises((_SendError, ConnectionError, OSError)):
            conn.call("Echo.ping", {})
        conn.close()
