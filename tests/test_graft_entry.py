"""Smoke tests for the driver entry points (__graft_entry__.py).

The round-4 multi-chip artifact failed because nothing in the suite ever
executed ``dryrun_multichip`` — a mixed-backend ``device_put`` shipped
silently.  These tests run the REAL driver entry points in a subprocess
under the driver's own conditions (``--xla_force_host_platform_device_count=8``)
so a device-plane backend leak can never ship silently again.
"""
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, timeout: int = 600) -> subprocess.CompletedProcess:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    # Force EXACTLY 8 virtual devices (the driver's condition), replacing
    # any pre-existing count so the test is hermetic in any shell.
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    return subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
def test_dryrun_multichip_8():
    """The driver's multi-chip acceptance path, end to end, 8 devices."""
    r = _run("import __graft_entry__ as g; g.dryrun_multichip(8)")
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-4000:])
    assert "dryrun_multichip(8)" in r.stdout
    assert "parity" in r.stdout
    # The forced-device pipeline (NOMAD_TPU_EXECUTOR=device twin of the
    # bench's 4_device_pipelined row) must really dispatch on the mesh
    # platform — AND, with sharding first-class, every one of those
    # dispatches must have ridden the node-axis mesh.
    m = re.search(r"executor=device device_fraction=([0-9.]+) "
                  r"sharded_dispatches=(\d+) placed=(\d+)", r.stdout)
    assert m, r.stdout[-2000:]
    assert float(m.group(1)) > 0, r.stdout[-2000:]
    assert int(m.group(2)) > 0, r.stdout[-2000:]
    assert int(m.group(3)) > 0, r.stdout[-2000:]
    # The columnar node-table bridge phase ran.
    assert "columnar slab bridge" in r.stdout


def test_entry_compiles():
    """entry() must return a jittable fn + example args (driver contract)."""
    r = _run(
        "import __graft_entry__ as g\n"
        "import jax, numpy as np\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "chosen = np.asarray(out[0])\n"
        "assert (chosen >= 0).all(), chosen\n"
        "print('entry-ok')\n")
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-4000:])
    assert "entry-ok" in r.stdout
