"""Randomized invariant tests for the device-backed scheduler.

Deterministically-seeded random fleets and jobs run through the full
jax-binpack path (host/native executors, rounds or scan mode, network
assignment) and every committed plan is checked against the hard
invariants the reference guarantees: exact resource fit, per-node port
uniqueness, bandwidth bounds, distinct_hosts, and conservation of
requested placements.  This is the property-test net under the
fast paths (template construction, C bulk finish, rounds mode).
"""
from __future__ import annotations

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    EVAL_TRIGGER_JOB_REGISTER,
    Constraint,
    Evaluation,
    NetworkResource,
    Resources,
    Task,
    TaskGroup,
    allocs_fit,
    generate_uuid,
)


def make_eval(job):
    return Evaluation(id=generate_uuid(), priority=job.priority,
                      type=job.type or "service",
                      triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                      job_id=job.id)


def random_fleet(rng, n):
    nodes = []
    for i in range(n):
        node = mock.node(i)
        node.resources.cpu = int(rng.integers(500, 6000))
        node.resources.memory_mb = int(rng.integers(512, 16384))
        if rng.random() < 0.1:
            node.attributes["kernel.name"] = "windows"
        if rng.random() < 0.05:
            node.drain = True
        nodes.append(node)
    return nodes


def random_job(rng, tag):
    job = mock.job()
    job.id = f"fuzz-{tag}"
    job.name = job.id
    job.type = "service" if rng.random() < 0.7 else "batch"
    groups = []
    for g in range(int(rng.integers(1, 5))):
        n_ports = int(rng.integers(0, 3))
        networks = []
        if n_ports or rng.random() < 0.5:
            networks = [NetworkResource(
                mbits=int(rng.integers(1, 120)),
                dynamic_ports=[f"p{j}" for j in range(n_ports)])]
        res = Resources(
            cpu=int(rng.integers(20, 900)) *
            (100 if rng.random() < 0.05 else 1),  # occasional giant ask
            memory_mb=int(rng.integers(16, 1200)),
            networks=networks)
        constraints = []
        if rng.random() < 0.25:
            constraints.append(Constraint(
                hard=True, operand=CONSTRAINT_DISTINCT_HOSTS))
        groups.append(TaskGroup(
            name=f"tg-{g}", count=int(rng.integers(1, 14)),
            constraints=constraints,
            tasks=[Task(name="t0", driver="exec", resources=res)]))
    job.task_groups = groups
    return job


def check_invariants(h: Harness, nodes, jobs, conservation=True):
    by_id = {n.id: n for n in nodes}
    state_allocs = [a for a in h.state.allocs()
                    if not a.terminal_status()]
    per_node: dict = {}
    for a in state_allocs:
        per_node.setdefault(a.node_id, []).append(a)

    for node_id, allocs in per_node.items():
        node = by_id[node_id]
        # 1. Exact fit, every dimension, via the golden scalar math.
        fit, dim, _ = allocs_fit(node, allocs)
        assert fit, f"node {node_id} oversubscribed on {dim}"
        # 2. Port uniqueness + bandwidth bound per node.
        ports: list = []
        bw = 0
        for a in allocs:
            for tr in a.task_resources.values():
                for net in tr.networks:
                    ports.extend(net.reserved_ports)
                    bw += net.mbits
        assert len(ports) == len(set(ports)), f"port clash on {node_id}"
        cap = sum(n.mbits for n in node.resources.networks if n.device)
        reserved_bw = sum(
            n.mbits for n in (node.reserved.networks
                              if node.reserved else []))
        assert bw + reserved_bw <= cap, f"bandwidth blown on {node_id}"
        # 3. Never placed on drained/incompatible nodes.
        assert not node.drain, f"placed on drained node {node_id}"
        assert node.attributes.get("kernel.name") == "linux"

    # 4. distinct_hosts: the constraint gates the CONSTRAINED group's
    # placements at placement time (same as the sequential chain), so the
    # state-level guarantee is that a constrained group's own copies
    # never share a node (an unconstrained sibling group may still join
    # the node afterwards).
    for job in jobs:
        for tg in job.task_groups:
            if not any(c.operand == CONSTRAINT_DISTINCT_HOSTS
                       for c in tg.constraints + job.constraints):
                continue
            seen: set = set()
            for a in state_allocs:
                if a.job_id == job.id and a.task_group == tg.name:
                    assert a.node_id not in seen, \
                        f"distinct_hosts violated for {job.id}/{tg.name}"
                    seen.add(a.node_id)

    # 5. Conservation: every requested instance is placed, failed, or
    # coalesced onto a failed alloc.  (Skipped for optimistic-conflict
    # rigs where retries submit several plans per job — state-level
    # conservation is asserted by the caller instead.)
    if not conservation:
        return
    for job, plan in zip(jobs, h.plans):
        requested = sum(tg.count for tg in job.task_groups)
        placed = sum(len(v) for v in plan.node_allocation.values())
        failed = len(plan.failed_allocs)
        coalesced = sum(a.metrics.coalesced_failures
                        for a in plan.failed_allocs)
        assert placed + failed + coalesced == requested, (
            job.id, requested, placed, failed, coalesced)


@pytest.mark.parametrize("seed", [3, 17, 42, 99, 2026])
def test_fuzz_invariants(seed):
    rng = np.random.default_rng(seed)
    h = Harness()
    nodes = random_fleet(rng, int(rng.integers(12, 120)))
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    jobs = [random_job(rng, t) for t in range(4)]
    for job in jobs:
        h.state.upsert_job(h.next_index(), job)
        h.process("jax-binpack", make_eval(job))
    assert len(h.plans) == len(jobs)
    check_invariants(h, nodes, jobs)


@pytest.mark.parametrize("seed", [7, 1234])
def test_fuzz_invariants_native_off(seed, monkeypatch):
    """Same invariants with the native path disabled: the pure-Python
    fallback must hold them too."""
    import nomad_tpu.scheduler.jax_binpack as jb

    monkeypatch.setattr(jb, "_native_bulk", lambda: None)
    rng = np.random.default_rng(seed)
    h = Harness()
    nodes = random_fleet(rng, 40)
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    jobs = [random_job(rng, t) for t in range(3)]
    for job in jobs:
        h.state.upsert_job(h.next_index(), job)
        h.process("jax-binpack", make_eval(job))
    check_invariants(h, nodes, jobs)


@pytest.mark.parametrize("seed", [5, 58])
def test_fuzz_invariants_fused_mesh_storm(seed, monkeypatch):
    """The fused BatchEvalRunner with the device executor forced, so
    the dispatch rides the runtime-selected mesh on the 8-device test
    host (parallel/mesh.py dispatch_mesh).  Lanes plan optimistically
    against one snapshot; a plan-applier-semantics planner serializes
    commits (partial accept + refresh), and the hard invariants must
    hold on the committed state — the multi-chip storm path gets the
    same property net as the single-eval paths."""
    from nomad_tpu.scheduler.batch import BatchEvalRunner
    from nomad_tpu.scheduler.harness import VerifyingPlanner
    from nomad_tpu.scheduler.jax_binpack import JaxBinPackScheduler

    monkeypatch.setattr(JaxBinPackScheduler, "HOST_SINGLE_SHOT_COST", 0)
    rng = np.random.default_rng(seed)
    h = Harness()
    h.planner = VerifyingPlanner(h)
    nodes = random_fleet(rng, int(rng.integers(16, 80)))
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    jobs = [random_job(rng, t) for t in range(4)]
    for job in jobs:
        h.state.upsert_job(h.next_index(), job)
    runner = BatchEvalRunner(h.state.snapshot(), h.planner)
    runner.process([make_eval(j) for j in jobs])
    check_invariants(h, nodes, jobs, conservation=False)
    # State-level conservation: per job, committed non-terminal
    # placements never exceed the request, and everything requested is
    # accounted placed or failed/coalesced.
    for job in jobs:
        requested = sum(tg.count for tg in job.task_groups)
        allocs = h.state.allocs_by_job(job.id)
        placed = len([a for a in allocs
                      if a.node_id and not a.terminal_status()])
        failed = [a for a in allocs if a.desired_status == "failed"]
        coalesced = sum(a.metrics.coalesced_failures for a in failed)
        assert placed <= requested, (job.id, placed, requested)
        assert placed + len(failed) + coalesced >= requested, (
            job.id, placed, len(failed), coalesced, requested)
