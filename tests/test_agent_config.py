"""Agent config files: HCL/JSON parse, multi-file merge, SIGHUP reload
(reference command/agent/config.go LoadConfig/Merge, command.go:463)."""
from __future__ import annotations

import json
import logging
import os

import pytest

from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.agent.config import (ConfigError, apply_to_agent_config,
                                    load_config, load_config_sources,
                                    merge_config, parse_config_string)

BASE_HCL = """
# base agent config
region = "global"
datacenter = "dc1"
name = "node-a"
data_dir = "/tmp/nomad-a"
log_level = "INFO"
bind_addr = "0.0.0.0"
enable_debug = false
leave_on_terminate = true

ports {
    http = 5646
    rpc = 5647
    serf = 5648
}

client {
    enabled = true
    servers = ["10.0.0.1:4647", "10.0.0.2:4647"]
    node_class = "edge"
    meta {
        rack = "r1"
    }
    options {
        "driver.raw_exec.enable" = "true"
    }
}

telemetry {
    statsd_address = "127.0.0.1:8125"
}
"""

OVERRIDE_HCL = """
# second file: later wins, sections merge key-wise
log_level = "DEBUG"
enable_debug = true

ports {
    http = 6646
}

client {
    node_class = "core"
    meta {
        rack = "r2"
        zone = "z1"
    }
}

server {
    enabled = true
    num_schedulers = 4
    enabled_schedulers = ["service", "batch"]
    bootstrap_expect = 3
}
"""


def test_parse_hcl_config():
    tree = parse_config_string(BASE_HCL)
    assert tree["region"] == "global"
    assert tree["ports"] == {"http": 5646, "rpc": 5647, "serf": 5648}
    assert tree["client"]["enabled"] is True
    assert tree["client"]["meta"] == {"rack": "r1"}
    assert tree["client"]["servers"] == ["10.0.0.1:4647", "10.0.0.2:4647"]
    assert tree["telemetry"]["statsd_address"] == "127.0.0.1:8125"
    assert tree["leave_on_terminate"] is True


def test_parse_json_config():
    tree = parse_config_string(json.dumps(
        {"region": "eu", "ports": {"http": 7000},
         "server": {"enabled": True}}), hint="agent.json")
    assert tree["region"] == "eu"
    assert tree["ports"]["http"] == 7000
    assert tree["server"]["enabled"] is True


def test_merge_two_files(tmp_path):
    a = tmp_path / "a.hcl"
    b = tmp_path / "b.hcl"
    a.write_text(BASE_HCL)
    b.write_text(OVERRIDE_HCL)
    tree = load_config_sources([str(a), str(b)])
    # Later file wins per key ...
    assert tree["log_level"] == "DEBUG"
    assert tree["enable_debug"] is True
    assert tree["ports"]["http"] == 6646
    # ... but untouched keys in the same section survive.
    assert tree["ports"]["rpc"] == 5647
    assert tree["client"]["enabled"] is True
    assert tree["client"]["node_class"] == "core"
    assert tree["client"]["meta"] == {"rack": "r2", "zone": "z1"}
    assert tree["server"]["num_schedulers"] == 4


def test_load_config_dir(tmp_path):
    d = tmp_path / "conf.d"
    d.mkdir()
    (d / "10-base.hcl").write_text('region = "a"\nlog_level = "INFO"\n')
    (d / "20-over.json").write_text('{"region": "b"}')
    (d / "ignored.txt").write_text("not config")
    tree = load_config(str(d))
    assert tree["region"] == "b"          # sorted order: 20 over 10
    assert tree["log_level"] == "INFO"


def test_apply_to_agent_config(tmp_path):
    a = tmp_path / "a.hcl"
    b = tmp_path / "b.hcl"
    a.write_text(BASE_HCL)
    b.write_text(OVERRIDE_HCL)
    cfg = AgentConfig()
    apply_to_agent_config(cfg, load_config_sources([str(a), str(b)]))
    assert cfg.region == "global"
    assert cfg.name == "node-a"
    assert cfg.http_port == 6646 and cfg.rpc_port == 5647
    assert cfg.client_enabled and cfg.server_enabled
    assert cfg.servers == [("10.0.0.1", 4647), ("10.0.0.2", 4647)]
    assert cfg.node_class == "core"
    assert cfg.meta == {"rack": "r2", "zone": "z1"}
    assert cfg.client_options["driver.raw_exec.enable"] == "true"
    assert cfg.num_schedulers == 4
    assert cfg.enabled_schedulers == ["service", "batch"]
    assert cfg.bootstrap_expect == 3
    assert cfg.log_level == "DEBUG"
    assert cfg.enable_debug is True
    assert cfg.leave_on_term is True
    assert cfg.telemetry["statsd_address"] == "127.0.0.1:8125"


def test_unknown_key_rejected():
    with pytest.raises(ConfigError):
        apply_to_agent_config(AgentConfig(), {"bogus_key": 1})


def test_server_executor_parsed_and_validated():
    """server { executor = ... } plumbs the placement-kernel executor
    override (scheduler/executor.py) into AgentConfig; a typo fails the
    config load, not the first dispatch."""
    cfg = AgentConfig()
    apply_to_agent_config(cfg, parse_config_string(
        'server {\n  enabled = true\n  executor = "device"\n}\n'))
    assert cfg.executor == "device"
    with pytest.raises(ConfigError):
        apply_to_agent_config(AgentConfig(), parse_config_string(
            'server {\n  executor = "tpu"\n}\n'))


def test_merge_config_scalars_and_sections():
    merged = merge_config(
        {"x": 1, "s": {"a": 1, "b": 2}, "l": [1, 2]},
        {"x": 9, "s": {"b": 3}, "l": [7]})
    assert merged == {"x": 9, "s": {"a": 1, "b": 3}, "l": [7]}


def test_agent_reload_applies_reloadable_fields():
    agent = Agent(AgentConfig.dev())
    try:
        applied = agent.reload({
            "log_level": "WARNING",
            "enable_debug": True,
            "region": "other",          # not reloadable: ignored
        })
        assert sorted(applied) == ["enable_debug", "log_level"]
        assert agent.config.log_level == "WARNING"
        assert agent.config.enable_debug is True
        assert agent.config.region == "global"
        assert logging.getLogger("nomad_tpu").level == logging.WARNING
    finally:
        agent.shutdown()
        logging.getLogger("nomad_tpu").setLevel(logging.NOTSET)
