"""Device-plane resolver semantics (nomad_tpu/parallel/devices.py).

The round-4 multi-chip failure was a mixed-backend ``device_put``; the
resolver is the one authority that prevents it.  These tests pin/re-pin
``jax_default_device`` and assert the cache-invalidation policy:
same-platform re-pins keep buffers, platform changes invalidate.
"""
import jax
import numpy as np
import pytest

from nomad_tpu.parallel.devices import (
    current_platform,
    default_device,
    default_platform,
    default_platform_devices,
    ensure_on_default,
    on_default_platform,
)


@pytest.fixture
def restore_pin():
    prior = jax.config.jax_default_device
    yield
    jax.config.update("jax_default_device", prior)


def test_default_platform_devices_follow_pin(restore_pin):
    cpus = jax.devices("cpu")
    jax.config.update("jax_default_device", cpus[0])
    assert default_platform() == "cpu"
    assert default_platform_devices() == cpus
    assert default_device() is cpus[0]


def test_string_pin_resolves(restore_pin):
    jax.config.update("jax_default_device", "cpu")
    assert default_platform() == "cpu"
    assert default_device() is jax.devices("cpu")[0]


def test_same_platform_repin_keeps_cached_buffer(restore_pin):
    cpus = jax.devices("cpu")
    jax.config.update("jax_default_device", cpus[0])
    buf = ensure_on_default(None, np.ones(4, dtype=np.float32))
    assert on_default_platform(buf)
    # Re-pin to another device of the SAME platform: bench-scale fleet
    # tensors must not be re-uploaded.
    jax.config.update("jax_default_device", cpus[-1])
    assert on_default_platform(buf)
    assert ensure_on_default(buf, np.ones(4, dtype=np.float32)) is buf


def test_unpinned_checks_default_backend_platform(restore_pin):
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    buf = ensure_on_default(None, np.ones(4, dtype=np.float32))
    jax.config.update("jax_default_device", None)
    # Unpinned: the policy compares against the default backend's
    # platform (what a bare device_put would use), not "anything goes".
    assert current_platform() == jax.devices()[0].platform
    assert on_default_platform(buf) == \
        (jax.devices()[0].platform == "cpu")


def test_usage_mirror_survives_repin(restore_pin):
    import nomad_tpu.mock as mock
    from nomad_tpu.models.fleet import build_fleet

    cpus = jax.devices("cpu")
    jax.config.update("jax_default_device", cpus[0])
    fleet = build_fleet([mock.node(i) for i in range(4)])
    cap_d, res_d = fleet.device_capacity_reserved()
    assert on_default_platform(cap_d)
    # Same-platform re-pin: cache identity must be preserved.
    jax.config.update("jax_default_device", cpus[-1])
    cap2, res2 = fleet.device_capacity_reserved()
    assert cap2 is cap_d and res2 is res_d
