"""Port of the reference core_sched_test.go GC table.

Eval GC (thresholds, alloc gating, partial batches), node GC (down +
empty vs. pinned vs. alive, thresholds), force GC (threshold bypass),
and the System.GarbageCollect endpoint path that emits the force-gc
core eval over RPC (reference nomad/core_sched_test.go +
system_endpoint.go).
"""
from __future__ import annotations

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.core_sched import CoreScheduler
from nomad_tpu.structs import (
    CORE_JOB_EVAL_GC,
    CORE_JOB_FORCE_GC,
    CORE_JOB_NODE_GC,
    NODE_STATUS_DOWN,
    Evaluation,
    codec,
    generate_uuid,
)
from tests.conftest import wait_until


def make_server(**kw) -> Server:
    kw.setdefault("num_schedulers", 0)
    srv = Server(ServerConfig(**kw))
    srv.establish_leadership()
    return srv


def _core_eval(job_id: str) -> Evaluation:
    return Evaluation(id=generate_uuid(), type="_core", job_id=job_id)


def _insert_eval(srv, status: str = "complete") -> str:
    ev = mock.eval()
    ev.status = status
    srv.raft_apply(codec.EVAL_UPDATE_REQUEST, {"evals": [ev.to_dict()]})
    return ev.id


def _insert_alloc(srv, eval_id: str, desired: str = "stop",
                  node_id: str = "foo") -> str:
    a = mock.alloc()
    a.eval_id = eval_id
    a.node_id = node_id
    a.desired_status = desired
    srv.raft_apply(codec.ALLOC_UPDATE_REQUEST, {"alloc": [a.to_dict()]})
    return a.id


def _age_everything(srv) -> None:
    """Make the timetable call every current index old (bypasses the
    5-minute witness granularity, reference test's fake time advance)."""
    srv.fsm.timetable.granularity = 0.0
    srv.fsm.timetable.witness(srv.raft.applied_index() + 1, time.time())


def _run_gc(srv, job_id: str) -> None:
    CoreScheduler(srv, srv.fsm.state.snapshot()).process(_core_eval(job_id))


class TestEvalGC:
    def test_reaps_old_terminal_eval_and_allocs(self):
        """core_sched_test.go TestCoreScheduler_EvalGC: a terminal eval
        past the threshold goes, and its terminal allocs go with it."""
        srv = make_server(eval_gc_threshold=0.0)
        try:
            eid = _insert_eval(srv)
            aid = _insert_alloc(srv, eid)
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_EVAL_GC)
            assert srv.fsm.state.eval_by_id(eid) is None
            assert srv.fsm.state.alloc_by_id(aid) is None
        finally:
            srv.shutdown()

    def test_threshold_keeps_young_evals(self):
        """An eval younger than eval_gc_threshold survives even though
        it is terminal."""
        srv = make_server(eval_gc_threshold=3600.0)
        try:
            eid = _insert_eval(srv)
            _age_everything(srv)  # witnesses are recent: cutoff finds none
            _run_gc(srv, CORE_JOB_EVAL_GC)
            assert srv.fsm.state.eval_by_id(eid) is not None
        finally:
            srv.shutdown()

    def test_non_terminal_eval_survives(self):
        srv = make_server(eval_gc_threshold=0.0)
        try:
            eid = _insert_eval(srv, status="pending")
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_EVAL_GC)
            assert srv.fsm.state.eval_by_id(eid) is not None
        finally:
            srv.shutdown()

    def test_live_alloc_pins_its_eval(self):
        """A terminal eval with a non-terminal alloc stays — collecting
        it would orphan a running allocation's bookkeeping."""
        srv = make_server(eval_gc_threshold=0.0)
        try:
            eid = _insert_eval(srv)
            aid = _insert_alloc(srv, eid, desired="run")
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_EVAL_GC)
            assert srv.fsm.state.eval_by_id(eid) is not None
            assert srv.fsm.state.alloc_by_id(aid) is not None
        finally:
            srv.shutdown()

    def test_partial_batch(self):
        """core_sched_test.go TestCoreScheduler_EvalGC_Partial: in one
        GC round, the collectable eval (terminal, terminal allocs) goes
        while the pinned eval (live alloc) and ALL its allocs stay."""
        srv = make_server(eval_gc_threshold=0.0)
        try:
            gone = _insert_eval(srv)
            gone_alloc = _insert_alloc(srv, gone)
            kept = _insert_eval(srv)
            kept_live = _insert_alloc(srv, kept, desired="run")
            kept_dead = _insert_alloc(srv, kept)  # rides its eval's fate
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_EVAL_GC)
            state = srv.fsm.state
            assert state.eval_by_id(gone) is None
            assert state.alloc_by_id(gone_alloc) is None
            assert state.eval_by_id(kept) is not None
            assert state.alloc_by_id(kept_live) is not None
            assert state.alloc_by_id(kept_dead) is not None
        finally:
            srv.shutdown()


class TestNodeGC:
    def _down(self, srv, node) -> None:
        srv.raft_apply(codec.NODE_UPDATE_STATUS_REQUEST,
                       {"node_id": node.id, "status": NODE_STATUS_DOWN})

    def test_reaps_old_down_empty_node(self):
        srv = make_server(node_gc_threshold=0.0)
        try:
            node = mock.node(1)
            srv.node_register(node)
            self._down(srv, node)
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_NODE_GC)
            assert srv.fsm.state.node_by_id(node.id) is None
        finally:
            srv.shutdown()

    def test_terminal_allocs_do_not_pin_node(self):
        """core_sched_test.go TestCoreScheduler_NodeGC_TerminalAllocs:
        only non-terminal allocs keep a down node registered."""
        srv = make_server(node_gc_threshold=0.0)
        try:
            node = mock.node(1)
            srv.node_register(node)
            eid = _insert_eval(srv)
            _insert_alloc(srv, eid, desired="stop", node_id=node.id)
            self._down(srv, node)
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_NODE_GC)
            assert srv.fsm.state.node_by_id(node.id) is None
        finally:
            srv.shutdown()

    def test_running_allocs_pin_node(self):
        """core_sched_test.go TestCoreScheduler_NodeGC_RunningAllocs."""
        srv = make_server(node_gc_threshold=0.0)
        try:
            node = mock.node(1)
            srv.node_register(node)
            eid = _insert_eval(srv)
            aid = _insert_alloc(srv, eid, desired="run", node_id=node.id)
            self._down(srv, node)
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_NODE_GC)
            assert srv.fsm.state.node_by_id(node.id) is not None
            assert srv.fsm.state.alloc_by_id(aid) is not None
        finally:
            srv.shutdown()

    def test_ready_node_survives(self):
        srv = make_server(node_gc_threshold=0.0)
        try:
            node = mock.node(1)
            srv.node_register(node)
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_NODE_GC)
            assert srv.fsm.state.node_by_id(node.id) is not None
        finally:
            srv.shutdown()

    def test_threshold_keeps_young_down_node(self):
        srv = make_server(node_gc_threshold=24 * 3600.0)
        try:
            node = mock.node(1)
            srv.node_register(node)
            self._down(srv, node)
            _age_everything(srv)
            _run_gc(srv, CORE_JOB_NODE_GC)
            assert srv.fsm.state.node_by_id(node.id) is not None
        finally:
            srv.shutdown()


class TestForceGC:
    def test_force_bypasses_both_thresholds(self):
        """One force-gc core eval collects the terminal eval AND the
        down node despite day-long thresholds and no timetable aging."""
        srv = make_server(eval_gc_threshold=3600.0,
                          node_gc_threshold=24 * 3600.0)
        try:
            eid = _insert_eval(srv)
            aid = _insert_alloc(srv, eid)
            node = mock.node(1)
            srv.node_register(node)
            srv.raft_apply(codec.NODE_UPDATE_STATUS_REQUEST,
                           {"node_id": node.id,
                            "status": NODE_STATUS_DOWN})
            _run_gc(srv, CORE_JOB_FORCE_GC)
            state = srv.fsm.state
            assert state.eval_by_id(eid) is None
            assert state.alloc_by_id(aid) is None
            assert state.node_by_id(node.id) is None
        finally:
            srv.shutdown()

    def test_unknown_core_job_rejected(self):
        srv = make_server()
        try:
            with pytest.raises(ValueError):
                _run_gc(srv, "not-a-core-job")
        finally:
            srv.shutdown()


class TestSystemGarbageCollectEndpoint:
    def test_rpc_path_runs_force_gc(self):
        """System.GarbageCollect over real RPC: the leader enqueues the
        force-gc core eval and a worker collects the garbage with the
        thresholds bypassed (reference system_endpoint.go)."""
        from nomad_tpu.server.rpc import ConnPool

        srv = make_server(num_schedulers=2, enable_rpc=True,
                          eval_gc_threshold=3600.0,
                          node_gc_threshold=24 * 3600.0)
        pool = ConnPool()
        try:
            eid = _insert_eval(srv)
            node = mock.node(1)
            srv.node_register(node)
            srv.raft_apply(codec.NODE_UPDATE_STATUS_REQUEST,
                           {"node_id": node.id,
                            "status": NODE_STATUS_DOWN})
            out = pool.call(srv.rpc_address(), "System.GarbageCollect",
                            {}, timeout=5.0)
            assert out["index"] >= 0
            wait_until(lambda: srv.fsm.state.eval_by_id(eid) is None and
                       srv.fsm.state.node_by_id(node.id) is None,
                       timeout=10.0,
                       msg="force-gc core eval never collected")
        finally:
            pool.shutdown()
            srv.shutdown()
