"""RPC plane tests: transport, endpoints, blocking queries, forwarding."""
from __future__ import annotations

import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool, RPCError, RPCServer

from tests.conftest import wait_until


@pytest.fixture
def srv():
    s = Server(ServerConfig(num_schedulers=2, enable_rpc=True))
    s.establish_leadership()
    yield s
    s.shutdown()


@pytest.fixture
def pool():
    p = ConnPool()
    yield p
    p.shutdown()


class TestTransport:
    def test_call_roundtrip(self, pool):
        rs = RPCServer()
        rs.register("Echo.Hello", lambda args: {"hi": args.get("name")})
        rs.start()
        try:
            out = pool.call(rs.address, "Echo.Hello", {"name": "x"})
            assert out == {"hi": "x"}
            with pytest.raises(RPCError):
                pool.call(rs.address, "No.Such", {})
        finally:
            rs.shutdown()

    def test_conn_reuse_and_concurrency(self, pool):
        rs = RPCServer()
        rs.register(
            "S.Slow",
            lambda args: (time.sleep(0.02), {"n": 1})[1],  # sleep-ok: slow handler
        )
        rs.start()
        try:
            results = []

            def worker():
                results.append(pool.call(rs.address, "S.Slow", {}))

            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == 8
        finally:
            rs.shutdown()


class TestEndpoints:
    def test_job_lifecycle_over_rpc(self, srv, pool):
        addr = srv.rpc_address()
        for i in range(4):
            pool.call(addr, "Node.Register",
                      {"node": mock.node(i).to_dict()})
        job = mock.job()
        job.task_groups[0].count = 4
        out = pool.call(addr, "Job.Register", {"job": job.to_dict()})
        assert out["eval_id"]

        # Poll eval until complete via blocking queries.
        deadline = time.monotonic() + 15
        index = 0
        while time.monotonic() < deadline:
            got = pool.call(addr, "Eval.GetEval",
                            {"eval_id": out["eval_id"],
                             "min_query_index": index,
                             "max_query_time": 1.0})
            index = got["index"]
            if got["eval"] and got["eval"]["status"] == "complete":
                break
        else:
            raise AssertionError("eval did not complete")

        allocs = pool.call(addr, "Job.Allocations",
                           {"job_id": job.id})["allocations"]
        assert len(allocs) == 4
        assert all(a["node_id"] for a in allocs)

        nodes = pool.call(addr, "Node.List", {})["nodes"]
        assert len(nodes) == 4
        one = pool.call(addr, "Node.GetAllocs",
                        {"node_id": allocs[0]["node_id"]})
        assert one["allocs"]

    def test_status_endpoints(self, srv, pool):
        addr = srv.rpc_address()
        assert pool.call(addr, "Status.Ping", {}) == {}
        assert pool.call(addr, "Status.Version", {})["version"]
        leader = pool.call(addr, "Status.Leader", {})["leader"]
        assert leader.endswith(str(addr[1]))

    def test_blocking_query_wakes_on_write(self, srv, pool):
        addr = srv.rpc_address()
        srv.node_register(mock.node(0))  # nonzero base index
        base = pool.call(addr, "Node.List", {})
        assert base["index"] > 0

        got = {}

        def blocked():
            got.update(pool.call(addr, "Node.List",
                                 {"min_query_index": base["index"],
                                  "max_query_time": 10.0}))

        t = threading.Thread(target=blocked)
        start = time.monotonic()
        t.start()
        time.sleep(0.1)  # sleep-ok: park the blocking query server-side first
        srv.node_register(mock.node(1))
        t.join(timeout=5)
        assert not t.is_alive()
        assert time.monotonic() - start < 5
        assert got["index"] > base["index"]
        assert len(got["nodes"]) == 2

    def test_client_alloc_update_over_rpc(self, srv, pool):
        addr = srv.rpc_address()
        pool.call(addr, "Node.Register", {"node": mock.node().to_dict()})
        job = mock.job()
        job.task_groups[0].count = 1
        out = pool.call(addr, "Job.Register", {"job": job.to_dict()})
        srv.wait_for_evals([out["eval_id"]], timeout=15)
        alloc = srv.fsm.state.allocs_by_job(job.id)[0]
        up = alloc.copy()
        up.client_status = "running"
        pool.call(addr, "Node.UpdateAlloc", {"alloc": [up.to_dict()]})
        assert srv.fsm.state.alloc_by_id(alloc.id).client_status == \
            "running"

    def test_heartbeat_over_rpc(self, srv, pool):
        addr = srv.rpc_address()
        node = mock.node()
        out = pool.call(addr, "Node.Register", {"node": node.to_dict()})
        assert out["heartbeat_ttl"] > 0
        hb = pool.call(addr, "Node.Heartbeat", {"node_id": node.id})
        assert hb["heartbeat_ttl"] >= 10.0


class TestRegionForwarding:
    """Multi-region federation: requests addressed to another region
    route to a server there; unknown regions error (reference
    nomad/rpc.go:162-227 forward/forwardRegion)."""

    def _two_regions(self):
        a = Server(ServerConfig(num_schedulers=1, enable_rpc=True,
                                region="region-a"))
        b = Server(ServerConfig(num_schedulers=1, enable_rpc=True,
                                region="region-b"))
        a.establish_leadership()
        b.establish_leadership()
        # Static federation (the serf-WAN-tags analogue).
        a.add_region_server("region-b", b.rpc_address())
        b.add_region_server("region-a", a.rpc_address())
        return a, b

    def test_cross_region_register_and_read(self, pool):
        a, b = self._two_regions()
        try:
            node = mock.node()
            # Send to region A's server, addressed to region B.
            pool.call(a.rpc_address(), "Node.Register",
                      {"node": node.to_dict(), "region": "region-b"})
            # The write landed in B, not A.
            assert b.fsm.state.node_by_id(node.id) is not None
            assert a.fsm.state.node_by_id(node.id) is None
            # Cross-region read sees it too.
            out = pool.call(a.rpc_address(), "Node.GetNode",
                            {"node_id": node.id, "region": "region-b"})
            assert out["node"]["id"] == node.id
        finally:
            a.shutdown()
            b.shutdown()

    def test_unknown_region_errors(self, pool):
        a, b = self._two_regions()
        try:
            with pytest.raises(RPCError, match="no path to region"):
                pool.call(a.rpc_address(), "Node.Register",
                          {"node": mock.node().to_dict(),
                           "region": "atlantis"})
        finally:
            a.shutdown()
            b.shutdown()

    def test_own_region_is_local(self, pool):
        a, b = self._two_regions()
        try:
            node = mock.node()
            pool.call(a.rpc_address(), "Node.Register",
                      {"node": node.to_dict(), "region": "region-a"})
            assert a.fsm.state.node_by_id(node.id) is not None
            assert b.fsm.state.node_by_id(node.id) is None
            assert a.regions() == ["region-a", "region-b"]
        finally:
            a.shutdown()
            b.shutdown()


def _make_cert(tmp_path, cn="nomad-tpu-test"):
    """Self-signed cert/key pair via the cryptography package (tests
    calling this skip cleanly when the package is absent)."""
    import datetime

    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost"),
                                         x509.DNSName(cn)]),
            critical=False)
        .sign(key, hashes.SHA256())
    )
    cert_path = tmp_path / "cert.pem"
    key_path = tmp_path / "key.pem"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    return str(cert_path), str(key_path)


class TestTLS:
    """TLS plane: 0x04 demux wraps the stream, inner planes unchanged
    (reference nomad/rpc.go:73-117)."""

    def test_rpc_over_tls(self, tmp_path):
        from nomad_tpu.server.rpc import (
            RPCServer,
            client_tls_context,
            server_tls_context,
        )

        cert, key = _make_cert(tmp_path)
        srv = RPCServer(tls_context=server_tls_context(cert, key))
        srv.register("Echo.Hello", lambda args: {"hi": args.get("x")})
        srv.start()
        pool = ConnPool(
            tls_context=client_tls_context(ca_file=cert),
            server_hostname="localhost")
        try:
            out = pool.call(srv.address, "Echo.Hello", {"x": 42})
            assert out == {"hi": 42}
            # Pooled connection reuse over TLS.
            for i in range(5):
                assert pool.call(srv.address, "Echo.Hello",
                                 {"x": i}) == {"hi": i}
            # Plaintext clients still work on the same listener.
            plain = ConnPool()
            assert plain.call(srv.address, "Echo.Hello",
                              {"x": 1}) == {"hi": 1}
            plain.shutdown()
        finally:
            pool.shutdown()
            srv.shutdown()

    def test_server_endpoints_over_tls(self, tmp_path):
        from nomad_tpu.server.rpc import client_tls_context

        cert, key = _make_cert(tmp_path)
        s = Server(ServerConfig(
            num_schedulers=1, enable_rpc=True,
            tls_cert_file=cert, tls_key_file=key, tls_ca_file=cert))
        s.establish_leadership()
        pool = ConnPool(tls_context=client_tls_context(ca_file=cert),
                        server_hostname="localhost")
        try:
            node = mock.node()
            pool.call(s.rpc_address(), "Node.Register",
                      {"node": node.to_dict()})
            assert s.fsm.state.node_by_id(node.id) is not None
            out = pool.call(s.rpc_address(), "Node.GetNode",
                            {"node_id": node.id})
            assert out["node"]["id"] == node.id
        finally:
            pool.shutdown()
            s.shutdown()

    def test_tls_refused_without_config(self):
        from nomad_tpu.server.rpc import RPCServer, client_tls_context

        srv = RPCServer()  # no TLS context
        srv.register("Echo.Hello", lambda args: {})
        srv.start()
        pool = ConnPool(tls_context=client_tls_context())
        try:
            with pytest.raises((ConnectionError, OSError, Exception)):
                pool.call(srv.address, "Echo.Hello", {}, timeout=2)
        finally:
            pool.shutdown()
            srv.shutdown()

    def test_require_tls_rejects_plaintext(self, tmp_path):
        from nomad_tpu.server.rpc import (
            RPCServer,
            client_tls_context,
            server_tls_context,
        )

        cert, key = _make_cert(tmp_path)
        srv = RPCServer(tls_context=server_tls_context(cert, key),
                        require_tls=True)
        srv.register("Echo.Hello", lambda args: {"hi": 1})
        srv.start()
        tls_pool = ConnPool(tls_context=client_tls_context(ca_file=cert),
                            server_hostname="localhost")
        plain = ConnPool()
        try:
            # TLS clients work; plaintext is rejected outright.
            assert tls_pool.call(srv.address, "Echo.Hello", {}) == {"hi": 1}
            with pytest.raises((ConnectionError, OSError)):
                plain.call(srv.address, "Echo.Hello", {}, timeout=2)
        finally:
            tls_pool.shutdown()
            plain.shutdown()
            srv.shutdown()

    def test_tls_servers_forward_without_hostname_config(self, tmp_path):
        """Inter-server forwarding with CA-only verification (no
        tls_server_name): follower forwards to leader over TLS even
        though servers are addressed by raw IP (code-review regression)."""
        cert, key = _make_cert(tmp_path)
        a = Server(ServerConfig(
            num_schedulers=1, enable_rpc=True, region="ra",
            tls_cert_file=cert, tls_key_file=key, tls_ca_file=cert))
        b = Server(ServerConfig(
            num_schedulers=1, enable_rpc=True, region="rb",
            tls_cert_file=cert, tls_key_file=key, tls_ca_file=cert))
        a.establish_leadership()
        b.establish_leadership()
        a.add_region_server("rb", b.rpc_address())
        try:
            node = mock.node()
            # Cross-region forward rides A's TLS'd ConnPool to B.
            from nomad_tpu.server.rpc import client_tls_context
            pool = ConnPool(tls_context=client_tls_context(ca_file=cert),
                            server_hostname="localhost")
            pool.call(a.rpc_address(), "Node.Register",
                      {"node": node.to_dict(), "region": "rb"})
            assert b.fsm.state.node_by_id(node.id) is not None
            pool.shutdown()
        finally:
            a.shutdown()
            b.shutdown()


class TestMuxPlane:
    """The 0x03 multiplexed plane (yamux equivalent)."""

    def test_out_of_order_responses_share_one_connection(self):
        """A slow handler must not stall other streams on the same
        session: fast responses arrive while the slow one is pending."""
        rpc = RPCServer()
        release = threading.Event()

        def slow(args):
            release.wait(10)
            return {"who": "slow"}

        rpc.register("T.slow", slow)
        rpc.register("T.fast", lambda args: {"who": "fast"})
        rpc.start()
        try:
            pool = ConnPool()  # multiplex by default
            results = {}

            def call_slow():
                results["slow"] = pool.call(rpc.address, "T.slow", {})

            t = threading.Thread(target=call_slow)
            t.start()
            time.sleep(0.1)  # sleep-ok: slow request is in flight on the session
            for i in range(5):
                assert pool.call(rpc.address, "T.fast", {})["who"] == \
                    "fast"
            # All of that rode ONE session (and one TCP connection).
            assert len(pool._sessions) == 1 and not pool._pools
            release.set()
            t.join(10)
            assert results["slow"]["who"] == "slow"
            pool.shutdown()
        finally:
            rpc.shutdown()

    def test_concurrent_mux_calls(self):
        rpc = RPCServer()
        rpc.register("T.echo", lambda args: {"n": args["n"]})
        rpc.start()
        try:
            pool = ConnPool()
            out = [None] * 32
            def call(i):
                out[i] = pool.call(rpc.address, "T.echo", {"n": i})["n"]
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(32)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(10)
            assert out == list(range(32))
            pool.shutdown()
        finally:
            rpc.shutdown()

    def test_mux_session_reconnects_after_server_restart(self):
        rpc = RPCServer()
        rpc.register("T.ping", lambda args: "pong")
        rpc.start()
        pool = ConnPool()
        assert pool.call(rpc.address, "T.ping", {}) == "pong"
        address = rpc.address
        rpc.shutdown()
        time.sleep(0.1)  # sleep-ok: let the OS release the listening port
        rpc2 = RPCServer(host=address[0], port=address[1])
        rpc2.register("T.ping", lambda args: "pong2")
        rpc2.start()
        try:
            def reconnected():
                try:
                    return pool.call(address, "T.ping", {}) == "pong2"
                except (ConnectionError, OSError):
                    return False

            wait_until(reconnected, timeout=5,
                       msg="mux session reconnect")
            pool.shutdown()
        finally:
            rpc2.shutdown()

    def test_mux_errors_propagate(self):
        rpc = RPCServer()

        def boom(args):
            raise ValueError("kaboom")

        rpc.register("T.boom", boom)
        rpc.start()
        try:
            pool = ConnPool()
            with pytest.raises(RPCError, match="kaboom"):
                pool.call(rpc.address, "T.boom", {})
            # Session stays healthy after an application error.
            rpc.register("T.ok", lambda args: 1)
            assert pool.call(rpc.address, "T.ok", {}) == 1
            pool.shutdown()
        finally:
            rpc.shutdown()


def test_many_blocking_queries_share_one_mux_session(srv, pool):
    """Concurrent blocking queries park server-side on ONE mux session;
    a single write wakes them all (the reference needs a yamux stream
    per query — here they're seq-multiplexed frames)."""
    import nomad_tpu.mock as mock

    srv.node_register(mock.node(0))  # nonzero base index
    base = pool.call(srv.rpc_address(), "Node.List", {})["index"]
    results = []
    errors = []

    def blocker(i):
        try:
            resp = pool.call(srv.rpc_address(), "Node.List",
                             {"min_query_index": base,
                              "max_query_time": 10.0})
            results.append((i, resp["index"]))
        except Exception as e:  # pragma: no cover - fail loudly below
            errors.append(e)

    threads = [threading.Thread(target=blocker, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # sleep-ok: all blocking queries parked server-side
    assert not results
    srv.node_register(mock.node())
    for t in threads:
        t.join(15)
    assert not errors and len(results) == 16
    assert all(idx > base for _i, idx in results)
    # All sixteen rode one multiplexed session.
    assert len(pool._sessions) == 1


class TestMuxRobustness:
    def test_malformed_frame_drops_mux_connection(self):
        """A non-dict msgpack frame must drop the connection promptly
        (not strand a worker and leave callers blocked on timeout)."""
        import socket
        import struct

        import msgpack

        from nomad_tpu.server.rpc import RPC_MUX

        rpc = RPCServer()
        rpc.register("T.ping", lambda args: {"ok": True})
        rpc.start()
        try:
            s = socket.create_connection(rpc.address, timeout=5)
            s.sendall(bytes([RPC_MUX]))
            body = msgpack.packb([1, 2, 3])  # a list, not a request dict
            s.sendall(struct.pack(">I", len(body)) + body)
            s.settimeout(5)
            assert s.recv(1) == b""  # server closed, no 330s hang
            s.close()
            # The listener is still healthy for well-formed sessions.
            pool = ConnPool()
            assert pool.call(rpc.address, "T.ping", {})["ok"] is True
            pool.shutdown()
        finally:
            rpc.shutdown()

    def test_malformed_frame_drops_plain_rpc_connection(self):
        import socket
        import struct

        import msgpack

        from nomad_tpu.server.rpc import RPC_NOMAD

        rpc = RPCServer()
        rpc.start()
        try:
            s = socket.create_connection(rpc.address, timeout=5)
            s.sendall(bytes([RPC_NOMAD]))
            body = msgpack.packb("nope")
            s.sendall(struct.pack(">I", len(body)) + body)
            s.settimeout(5)
            assert s.recv(1) == b""
            s.close()
        finally:
            rpc.shutdown()

    def test_mux_send_does_not_hold_session_state_lock(self):
        """While one caller's large frame is mid-send, the reader thread
        must still deliver completed responses (head-of-line liveness:
        the waiter-table lock and the write lock are separate)."""
        from nomad_tpu.server.rpc import MuxConn

        rpc = RPCServer()
        release = threading.Event()

        def slow(args):
            release.wait(10)
            return {"who": "slow"}

        rpc.register("T.slow", slow)
        rpc.register("T.echo", lambda args: {"n": len(args["blob"])})
        rpc.start()
        try:
            sess = MuxConn(tuple(rpc.address))
            results = {}

            def call_slow():
                results["slow"] = sess.call("T.slow", {}, timeout=10)

            t = threading.Thread(target=call_slow)
            t.start()
            time.sleep(0.05)  # sleep-ok: large send in flight on the write lock
            # Large frames keep the write lock busy; replies must still
            # flow for other streams, and the state lock must never be
            # held across a send (deadlock would fail this in 10s).
            threads = []
            for _ in range(4):
                th = threading.Thread(
                    target=lambda: results.setdefault(
                        "echo", sess.call("T.echo",
                                          {"blob": b"x" * (4 << 20)},
                                          timeout=10)))
                th.start()
                threads.append(th)
            for th in threads:
                th.join(10)
                assert not th.is_alive()
            assert results["echo"]["n"] == 4 << 20
            release.set()
            t.join(10)
            assert results["slow"]["who"] == "slow"
            sess.close()
        finally:
            rpc.shutdown()


def test_raft_uses_dedicated_non_mux_pool(tmp_path):
    """Raft traffic must not share the mux session with bulk RPC: one
    large frame under the session write lock would stall every
    heartbeat/vote queued behind it (election churn)."""
    cfg = ServerConfig(data_dir=str(tmp_path / "s1"), raft_mode="net",
                       enable_rpc=True)
    srv = Server(cfg)
    try:
        assert srv.raft_pool is not srv.conn_pool
        assert srv.raft_pool.multiplex is False
        assert srv.conn_pool.multiplex is True
        assert srv.raft.pool is srv.raft_pool
    finally:
        srv.shutdown()
