"""RPC plane tests: transport, endpoints, blocking queries, forwarding."""
from __future__ import annotations

import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool, RPCError, RPCServer


@pytest.fixture
def srv():
    s = Server(ServerConfig(num_schedulers=2, enable_rpc=True))
    s.establish_leadership()
    yield s
    s.shutdown()


@pytest.fixture
def pool():
    p = ConnPool()
    yield p
    p.shutdown()


class TestTransport:
    def test_call_roundtrip(self, pool):
        rs = RPCServer()
        rs.register("Echo.Hello", lambda args: {"hi": args.get("name")})
        rs.start()
        try:
            out = pool.call(rs.address, "Echo.Hello", {"name": "x"})
            assert out == {"hi": "x"}
            with pytest.raises(RPCError):
                pool.call(rs.address, "No.Such", {})
        finally:
            rs.shutdown()

    def test_conn_reuse_and_concurrency(self, pool):
        rs = RPCServer()
        rs.register("S.Slow", lambda args: (time.sleep(0.02), {"n": 1})[1])
        rs.start()
        try:
            results = []

            def worker():
                results.append(pool.call(rs.address, "S.Slow", {}))

            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == 8
        finally:
            rs.shutdown()


class TestEndpoints:
    def test_job_lifecycle_over_rpc(self, srv, pool):
        addr = srv.rpc_address()
        for i in range(4):
            pool.call(addr, "Node.Register",
                      {"node": mock.node(i).to_dict()})
        job = mock.job()
        job.task_groups[0].count = 4
        out = pool.call(addr, "Job.Register", {"job": job.to_dict()})
        assert out["eval_id"]

        # Poll eval until complete via blocking queries.
        deadline = time.monotonic() + 15
        index = 0
        while time.monotonic() < deadline:
            got = pool.call(addr, "Eval.GetEval",
                            {"eval_id": out["eval_id"],
                             "min_query_index": index,
                             "max_query_time": 1.0})
            index = got["index"]
            if got["eval"] and got["eval"]["status"] == "complete":
                break
        else:
            raise AssertionError("eval did not complete")

        allocs = pool.call(addr, "Job.Allocations",
                           {"job_id": job.id})["allocations"]
        assert len(allocs) == 4
        assert all(a["node_id"] for a in allocs)

        nodes = pool.call(addr, "Node.List", {})["nodes"]
        assert len(nodes) == 4
        one = pool.call(addr, "Node.GetAllocs",
                        {"node_id": allocs[0]["node_id"]})
        assert one["allocs"]

    def test_status_endpoints(self, srv, pool):
        addr = srv.rpc_address()
        assert pool.call(addr, "Status.Ping", {}) == {}
        assert pool.call(addr, "Status.Version", {})["version"]
        leader = pool.call(addr, "Status.Leader", {})["leader"]
        assert leader.endswith(str(addr[1]))

    def test_blocking_query_wakes_on_write(self, srv, pool):
        addr = srv.rpc_address()
        srv.node_register(mock.node(0))  # nonzero base index
        base = pool.call(addr, "Node.List", {})
        assert base["index"] > 0

        got = {}

        def blocked():
            got.update(pool.call(addr, "Node.List",
                                 {"min_query_index": base["index"],
                                  "max_query_time": 10.0}))

        t = threading.Thread(target=blocked)
        start = time.monotonic()
        t.start()
        time.sleep(0.1)
        srv.node_register(mock.node(1))
        t.join(timeout=5)
        assert not t.is_alive()
        assert time.monotonic() - start < 5
        assert got["index"] > base["index"]
        assert len(got["nodes"]) == 2

    def test_client_alloc_update_over_rpc(self, srv, pool):
        addr = srv.rpc_address()
        pool.call(addr, "Node.Register", {"node": mock.node().to_dict()})
        job = mock.job()
        job.task_groups[0].count = 1
        out = pool.call(addr, "Job.Register", {"job": job.to_dict()})
        srv.wait_for_evals([out["eval_id"]], timeout=15)
        alloc = srv.fsm.state.allocs_by_job(job.id)[0]
        up = alloc.copy()
        up.client_status = "running"
        pool.call(addr, "Node.UpdateAlloc", {"alloc": [up.to_dict()]})
        assert srv.fsm.state.alloc_by_id(alloc.id).client_status == \
            "running"

    def test_heartbeat_over_rpc(self, srv, pool):
        addr = srv.rpc_address()
        node = mock.node()
        out = pool.call(addr, "Node.Register", {"node": node.to_dict()})
        assert out["heartbeat_ttl"] > 0
        hb = pool.call(addr, "Node.Heartbeat", {"node_id": node.id})
        assert hb["heartbeat_ttl"] >= 10.0
