"""Group-commit plan applier: vectorized cross-plan conflict windows,
the multi-plan raft apply, and the sequential-parity contract.

The load-bearing property (ISSUE acceptance): for a contended plan
stream, group-commit results — alloc set, per-plan partial rejections,
state indexes — are byte-identical to sequential per-plan application in
eval order.  Two parity rigs lock it down: a hand-built adversarial
stream covering every verdict family (full accept, partial rejection,
all_at_once, evict+refill, port collision, in-place update), and a
recorded stream captured from a real contended storm run.
"""
from __future__ import annotations

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.ops.plan_conflict import evaluate_window
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.fsm import NomadFSM
from nomad_tpu.server.plan_apply import (
    OptimisticSnapshot,
    PlanApplier,
    evaluate_plan,
)
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.server.raft import InmemRaft
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    Allocation,
    Evaluation,
    NetworkResource,
    Plan,
    PlanResult,
    Resources,
    codec,
    generate_uuid,
)

FREE_CPU = 3900  # mock node capacity 4000 minus 100 reserved


def make_alloc(node, *, cpu=1000, mem=1024, job_id="j1",
               desired=ALLOC_DESIRED_STATUS_RUN) -> Allocation:
    return Allocation(
        id=generate_uuid(),
        node_id=node.id,
        job_id=job_id,
        task_group="web",
        resources=Resources(cpu=cpu, memory_mb=mem),
        desired_status=desired,
        client_status=ALLOC_CLIENT_STATUS_PENDING,
    )


def net_alloc(node, *, cpu=200, ports=(), mbits=10) -> Allocation:
    """An alloc whose offer claims ports/bandwidth on the node's one
    network — the shape the incremental port/bandwidth verifier tracks."""
    a = make_alloc(node, cpu=cpu)
    ip = node.reserved.networks[0].ip
    a.task_resources = {"web": Resources(
        cpu=cpu, memory_mb=64,
        networks=[NetworkResource(device="eth0", ip=ip, mbits=mbits,
                                  reserved_ports=list(ports))])}
    return a


def place_plan(*allocs, priority=50) -> Plan:
    plan = Plan(eval_id=generate_uuid(), priority=priority)
    for a in allocs:
        plan.append_alloc(a)
    return plan


def sequential_apply(store: StateStore, plans: list,
                     base_index: int) -> list:
    """The reference semantics: evaluate each plan against live state in
    eval order, commit its accepted portion, one index per plan."""
    results = []
    for i, plan in enumerate(plans):
        result = evaluate_plan(store, plan)
        allocs = []
        for v in result.node_update.values():
            allocs.extend(v)
        for v in result.node_allocation.values():
            allocs.extend(v)
        allocs.extend(result.failed_allocs)
        if allocs:
            store.upsert_allocs(base_index + i, allocs)
        results.append(result)
    return results


def grouped_apply(store: StateStore, plans: list,
                  base_index: int, executor=None,
                  partition: bool = True) -> list:
    """The group-commit path: one window verify (partitioned by
    default; optionally concurrent via a ComponentExecutor, or the
    flat ``partition=False`` walk), one batched upsert, same per-plan
    index sequence."""
    outcomes = evaluate_window(store, plans, executor=executor,
                               partition=partition)
    items = []
    for i, outcome in enumerate(outcomes):
        result = outcome.result
        allocs = []
        for v in result.node_update.values():
            allocs.extend(v)
        for v in result.node_allocation.values():
            allocs.extend(v)
        allocs.extend(result.failed_allocs)
        if allocs:
            items.append((base_index + i, allocs))
    if items:
        store.upsert_allocs_batched(items)
    return [o.result for o in outcomes]


def result_key(result: PlanResult) -> tuple:
    return (
        {n: [a.id for a in v] for n, v in result.node_update.items()},
        {n: [a.id for a in v]
         for n, v in result.node_allocation.items()},
        [a.id for a in result.failed_allocs],
        result.refresh_index > 0,
    )


def store_image(store: StateStore) -> tuple:
    return (
        {a.id: a.to_dict() for a in store.allocs()},
        {t: store.get_index(t)
         for t in ("nodes", "jobs", "evals", "allocs")},
    )


def assert_parity(nodes_setup, plans_fn) -> tuple:
    """Build two identical worlds, apply the same plan stream
    sequentially and grouped, assert byte-identical results + state."""
    s_seq, s_grp = StateStore(), StateStore()
    for store in (s_seq, s_grp):
        nodes_setup(store)
    plans = plans_fn(s_seq)  # same objects verified against both worlds
    res_seq = sequential_apply(s_seq, plans, 2000)
    res_grp = grouped_apply(s_grp, plans, 2000)
    assert [result_key(r) for r in res_seq] == \
        [result_key(r) for r in res_grp]
    assert store_image(s_seq) == store_image(s_grp)
    return res_seq, s_seq


# ---------------------------------------------------------------------------
# 1. window semantics: order sensitivity, fallbacks, evict windows
# ---------------------------------------------------------------------------

class TestWindowSemantics:
    def test_disjoint_window_full_accepts(self):
        store = StateStore()
        nodes = [mock.node(i) for i in range(4)]
        for i, n in enumerate(nodes):
            store.upsert_node(1000 + i, n)
        plans = [place_plan(make_alloc(n)) for n in nodes]
        outcomes = evaluate_window(store, plans)
        assert all(o.result.full_commit(p)[0]
                   for o, p in zip(outcomes, plans))
        assert all(not o.fallback for o in outcomes)

    def test_prefix_conflict_is_order_sensitive(self):
        """Two plans over-committing one node: the FIRST wins, the
        second is rejected with a refresh — and is reported as the
        conflict fallback."""
        store = StateStore()
        node = mock.node()
        store.upsert_node(1000, node)
        first = place_plan(make_alloc(node, cpu=FREE_CPU))
        second = place_plan(make_alloc(node, cpu=1000))
        outcomes = evaluate_window(store, [first, second])
        assert outcomes[0].result.node_allocation == \
            first.node_allocation
        assert outcomes[1].result.node_allocation == {}
        assert outcomes[1].result.refresh_index > 0
        assert not outcomes[0].fallback and outcomes[1].fallback

    def test_window_port_collision_rejects_later_plan(self):
        """A static-port claim staged by an earlier plan in the window
        must reject a later plan's identical claim (the incremental
        port mirror extended with window-local state)."""
        store = StateStore()
        node = mock.node()
        store.upsert_node(1000, node)
        first = place_plan(net_alloc(node, ports=[8080]))
        second = place_plan(net_alloc(node, ports=[8080]))
        outcomes = evaluate_window(store, [first, second])
        assert outcomes[0].result.node_allocation == \
            first.node_allocation
        assert outcomes[1].result.node_allocation == {}

    def test_window_evict_frees_capacity_for_later_plan(self):
        store = StateStore()
        node = mock.node()
        store.upsert_node(1000, node)
        existing = make_alloc(node, cpu=FREE_CPU)
        store.upsert_allocs(1001, [existing])
        evict = Plan(eval_id=generate_uuid())
        evict.append_update(existing, ALLOC_DESIRED_STATUS_STOP, "gone")
        refill = place_plan(make_alloc(node, cpu=FREE_CPU))
        outcomes = evaluate_window(store, [evict, refill])
        assert outcomes[0].result.node_update == evict.node_update
        assert outcomes[1].result.node_allocation == \
            refill.node_allocation, \
            "the window overlay must see the eviction's freed capacity"

    def test_window_respects_inflight_overlay(self):
        """The verify/apply overlap extends to windows: claims against
        a node the in-flight apply already filled must reject."""
        store = StateStore()
        a, b = mock.node(), mock.node(1)
        store.upsert_node(1000, a)
        store.upsert_node(1001, b)
        snap = OptimisticSnapshot(store.snapshot())
        snap.upsert_allocs([make_alloc(a, cpu=FREE_CPU)])  # in flight
        plans = [place_plan(make_alloc(a, cpu=1000)),
                 place_plan(make_alloc(b, cpu=1000))]
        outcomes = evaluate_window(snap, plans)
        assert outcomes[0].result.node_allocation == {}
        assert outcomes[1].result.node_allocation == \
            plans[1].node_allocation

    def test_all_at_once_window_member(self):
        store = StateStore()
        good, full = mock.node(), mock.node(1)
        store.upsert_node(1000, good)
        store.upsert_node(1001, full)
        store.upsert_allocs(1002, [make_alloc(full, cpu=FREE_CPU)])
        plan = place_plan(make_alloc(good), make_alloc(full, cpu=1000))
        plan.all_at_once = True
        outcomes = evaluate_window(
            store, [plan, place_plan(make_alloc(good, cpu=100))])
        assert outcomes[0].result.node_allocation == {}
        assert outcomes[0].result.refresh_index > 0


# ---------------------------------------------------------------------------
# 2. sequential parity (the acceptance bar)
# ---------------------------------------------------------------------------

def _parity_modes():
    """The grouped paths the rigs pin against sequential truth: the
    default partitioned walk, the partitioned walk on a REAL concurrent
    ComponentExecutor, and the flat pre-partition walk (the bench's
    sequential-applier baseline)."""
    from nomad_tpu.server.plan_apply import ComponentExecutor

    executor = ComponentExecutor(workers=2)
    return [
        ("partitioned", None, True),
        ("concurrent", executor, True),
        ("flat", None, False),
    ], executor


def _stamp_adversarial_deadlines(plans) -> None:
    """Deadlines DESCENDING by window position, so the deadline-aware
    component scheduler verifies components in roughly REVERSE window
    order — results must still be byte-identical to eval order."""
    import time as _time
    now = _time.monotonic()
    n = len(plans)
    for i, plan in enumerate(plans):
        plan.deadline = now + 100.0 + (n - i) * 10.0


class TestSequentialParity:
    def test_adversarial_stream_parity(self):
        """Hand-built contended stream covering every verdict family:
        clean full accepts (with port claims), an order-sensitive accept
        on a shared node, a window port collision, cross-plan
        over-commit, all_at_once whole-rejection, evict+refill, an
        in-place update, and failed allocs riding a rejected plan —
        replayed through the partitioned, concurrent-executor and flat
        grouped paths against one sequential truth, with adversarial
        deadlines so component scheduling order != eval order."""
        nodes = [mock.node(i) for i in range(6)]

        def setup(store):
            for i, n in enumerate(nodes):
                store.upsert_node(1000 + i, n)

        # Pre-existing allocs must exist in EVERY world with the same
        # ids: build once, upsert into each store.
        existing = make_alloc(nodes[3], cpu=FREE_CPU)
        existing2 = make_alloc(nodes[4], cpu=2000)

        def world():
            store = StateStore()
            setup(store)
            store.upsert_allocs(1500, [existing, existing2])
            return store

        plans = []
        plans.append(place_plan(net_alloc(nodes[0], ports=[9000])))
        plans.append(place_plan(net_alloc(nodes[0], ports=[9001])))
        plans.append(place_plan(net_alloc(nodes[0], ports=[9000])))
        plans.append(place_plan(make_alloc(nodes[1], cpu=FREE_CPU)))
        plans.append(place_plan(make_alloc(nodes[1], cpu=500)))
        p = place_plan(make_alloc(nodes[2], cpu=100),
                       make_alloc(nodes[1], cpu=500))
        p.all_at_once = True
        plans.append(p)
        evict = Plan(eval_id=generate_uuid())
        evict.append_update(existing, ALLOC_DESIRED_STATUS_STOP, "drain")
        plans.append(evict)
        plans.append(place_plan(make_alloc(nodes[3], cpu=FREE_CPU)))
        replacement = existing2.copy()
        replacement.resources = Resources(cpu=3000, memory_mb=1024)
        plans.append(place_plan(replacement))
        full_plan = place_plan(make_alloc(nodes[1], cpu=FREE_CPU))
        failed = make_alloc(nodes[1], cpu=1)
        failed.node_id = ""
        full_plan.append_failed(failed)
        plans.append(full_plan)
        _stamp_adversarial_deadlines(plans)

        s_seq = world()
        res_seq = sequential_apply(s_seq, plans, 2000)
        modes, executor = _parity_modes()
        try:
            for name, ex, part in modes:
                s_grp = world()
                res_grp = grouped_apply(s_grp, plans, 2000,
                                        executor=ex, partition=part)
                assert [result_key(r) for r in res_seq] == \
                    [result_key(r) for r in res_grp], name
                assert store_image(s_seq) == store_image(s_grp), name
        finally:
            executor.stop()
        # Sanity on the interesting verdicts.
        assert result_key(res_seq[2])[1] == {}      # port collision
        assert result_key(res_seq[4])[1] == {}      # over-commit
        assert result_key(res_seq[5])[1] == {}      # all_at_once
        assert res_seq[7].node_allocation            # refill accepted

    def test_recorded_contended_storm_stream_parity(self):
        """Record a REAL contended plan stream (fused storm through the
        verifying planner), then replay it onto fresh worlds through
        every grouped path — partitioned, concurrent-executor, flat —
        against one sequential truth."""
        from nomad_tpu.scheduler import Harness
        from nomad_tpu.scheduler.batch import BatchEvalRunner
        from nomad_tpu.scheduler.harness import VerifyingPlanner
        from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER,
                                       Task, TaskGroup)

        nodes = [mock.node(i) for i in range(8)]
        h = Harness()
        for n in nodes:
            h.state.upsert_node(h.next_index(), n.copy())
        jobs = []
        for j in range(6):
            job = mock.job()
            job.task_groups = [
                TaskGroup(name=f"tg-{g}", count=2,
                          tasks=[Task(name="web", driver="exec",
                                      resources=Resources(
                                          cpu=600, memory_mb=256,
                                          networks=[NetworkResource(
                                              mbits=5,
                                              dynamic_ports=["http"])]))])
                for g in range(4)]
            h.state.upsert_job(h.next_index(), job)
            jobs.append(job)
        h.planner = VerifyingPlanner(h)
        evals = [Evaluation(id=generate_uuid(), priority=50,
                            type=j.type,
                            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                            job_id=j.id) for j in jobs]
        BatchEvalRunner(h.state.snapshot(), h,
                        state_refresh=h.snapshot).process(evals)
        plans = h.plans
        assert plans, "storm recorded no plans"
        _stamp_adversarial_deadlines(plans)

        def world():
            store = StateStore()
            for i, n in enumerate(nodes):
                store.upsert_node(1000 + i, n.copy())
            return store

        s_seq = world()
        res_seq = sequential_apply(s_seq, plans, 5000)
        modes, executor = _parity_modes()
        try:
            for name, ex, part in modes:
                s_grp = world()
                res_grp = grouped_apply(s_grp, plans, 5000,
                                        executor=ex, partition=part)
                assert [result_key(r) for r in res_seq] == \
                    [result_key(r) for r in res_grp], name
                assert store_image(s_seq) == store_image(s_grp), name
        finally:
            executor.stop()


# ---------------------------------------------------------------------------
# 2b. host vs device verify-engine parity (NOMAD_TPU_VERIFY)
# ---------------------------------------------------------------------------

def device_grouped_apply(store: StateStore, plans: list,
                         base_index: int) -> list:
    """grouped_apply through the DEVICE verify engine, with the
    cold-start warm-up (the first window after a mirror rebuild always
    falls back — the window-lease rule) and a hard assertion that the
    replayed window actually dispatched: a silent fallback would test
    host against host and prove nothing."""
    from nomad_tpu.ops.verify_policy import verify_override

    with verify_override("device"):
        evaluate_window(store, plans)          # warm the lease
        probe = evaluate_window(store, plans)  # store untouched
        dev = probe.info["device"] if probe.info else None
        assert dev is not None and dev["dispatched"], \
            f"device verify did not dispatch: {dev}"
        return grouped_apply(store, plans, base_index)


class TestDeviceVerifyParity:
    """The device engine's acceptance bar: verdict stream, alloc set
    and store fingerprint byte-identical to the host engine (and to
    sequential truth) on every rig, with the dispatch PROVEN."""

    def test_recorded_storm_host_vs_device(self):
        """The recorded contended storm (same recipe as the grouped
        parity rig) replayed through the host engine and through a
        dispatched device window, byte-compared."""
        from nomad_tpu.ops.verify_policy import verify_override
        from nomad_tpu.scheduler import Harness
        from nomad_tpu.scheduler.batch import BatchEvalRunner
        from nomad_tpu.scheduler.harness import VerifyingPlanner
        from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER,
                                       Task, TaskGroup)

        nodes = [mock.node(i) for i in range(8)]
        h = Harness()
        for n in nodes:
            h.state.upsert_node(h.next_index(), n.copy())
        jobs = []
        for j in range(6):
            job = mock.job()
            job.task_groups = [
                TaskGroup(name=f"tg-{g}", count=2,
                          tasks=[Task(name="web", driver="exec",
                                      resources=Resources(
                                          cpu=600, memory_mb=256,
                                          networks=[NetworkResource(
                                              mbits=5,
                                              dynamic_ports=["http"])]))])
                for g in range(4)]
            h.state.upsert_job(h.next_index(), job)
            jobs.append(job)
        h.planner = VerifyingPlanner(h)
        evals = [Evaluation(id=generate_uuid(), priority=50,
                            type=j.type,
                            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                            job_id=j.id) for j in jobs]
        BatchEvalRunner(h.state.snapshot(), h,
                        state_refresh=h.snapshot).process(evals)
        plans = h.plans
        assert plans, "storm recorded no plans"
        _stamp_adversarial_deadlines(plans)

        def world():
            store = StateStore()
            for i, n in enumerate(nodes):
                store.upsert_node(1000 + i, n.copy())
            return store

        s_seq = world()
        res_seq = sequential_apply(s_seq, plans, 5000)
        s_host = world()
        with verify_override("host"):
            res_host = grouped_apply(s_host, plans, 5000)
        s_dev = world()
        res_dev = device_grouped_apply(s_dev, plans, 5000)
        assert [result_key(r) for r in res_seq] == \
            [result_key(r) for r in res_host] == \
            [result_key(r) for r in res_dev]
        assert store_image(s_seq) == store_image(s_host) \
            == store_image(s_dev)

    @pytest.mark.parametrize("n_nodes", [8, 24, 64])
    def test_seeded_random_windows_across_fleet_sizes(self, n_nodes):
        """Seeded random contended windows at three fleet sizes —
        including evict-frees-capacity and port-collision shapes — each
        replayed sequentially, through the host engine, and through a
        dispatched device window; all three byte-compared."""
        import random

        from nomad_tpu.ops.verify_policy import verify_override

        rng = random.Random(171_000 + n_nodes)
        nodes = [mock.node(i) for i in range(n_nodes)]
        # Standing allocs: every third node starts near-full so random
        # refills contend, and their evictions free real capacity.
        existing = [make_alloc(nodes[i], cpu=FREE_CPU - 500)
                    for i in range(0, n_nodes, 3)]

        def world():
            store = StateStore()
            for i, n in enumerate(nodes):
                store.upsert_node(1000 + i, n)
            store.upsert_allocs(1500, existing)
            return store

        plans = []
        hot = nodes[:max(2, n_nodes // 4)]  # contention focus
        for _ in range(24):
            kind = rng.random()
            if kind < 0.25:
                # Evict-frees-capacity: stop a standing alloc, refill
                # the node to the brim in a LATER plan.
                victim = rng.choice(existing)
                evict = Plan(eval_id=generate_uuid())
                evict.append_update(victim,
                                    ALLOC_DESIRED_STATUS_STOP, "churn")
                plans.append(evict)
                node = next(n for n in nodes if n.id == victim.node_id)
                plans.append(place_plan(make_alloc(node, cpu=FREE_CPU)))
            elif kind < 0.45:
                # Port collision: two claims on one hot node, one
                # shared static port — the later one must reject.
                node = rng.choice(hot)
                port = 8000 + rng.randrange(4)
                plans.append(place_plan(net_alloc(node, ports=[port])))
                plans.append(place_plan(net_alloc(node, ports=[port])))
            elif kind < 0.7:
                # Over-commit pressure on a hot node.
                node = rng.choice(hot)
                plans.append(place_plan(make_alloc(
                    node, cpu=rng.choice((500, 1500, FREE_CPU)))))
            else:
                # Clean placement on a random node.
                node = rng.choice(nodes)
                plans.append(place_plan(make_alloc(
                    node, cpu=rng.choice((100, 400, 900)))))
        _stamp_adversarial_deadlines(plans)

        s_seq = world()
        res_seq = sequential_apply(s_seq, plans, 5000)
        s_host = world()
        with verify_override("host"):
            res_host = grouped_apply(s_host, plans, 5000)
        s_dev = world()
        res_dev = device_grouped_apply(s_dev, plans, 5000)
        assert [result_key(r) for r in res_seq] == \
            [result_key(r) for r in res_host] == \
            [result_key(r) for r in res_dev]
        assert store_image(s_seq) == store_image(s_host) \
            == store_image(s_dev)

    def test_device_info_and_fallback_taxonomy(self):
        """The window info record: host policy reports no device entry,
        a cold device window reports the lease-miss fallback, a warmed
        one reports the dispatch with its counted transfers."""
        from nomad_tpu.ops.verify_policy import verify_override

        nodes = [mock.node(i) for i in range(8)]
        store = StateStore()
        for i, n in enumerate(nodes):
            store.upsert_node(1000 + i, n)
        plans = [place_plan(make_alloc(n, cpu=100)) for n in nodes]

        with verify_override("host"):
            out = evaluate_window(store, plans)
            assert out.info["device"] is None

        with verify_override("device"):
            cold = evaluate_window(store, plans)
            dev = cold.info["device"]
            if not dev["dispatched"]:  # twins may be resident already
                assert dev["fallback"] in ("lease-miss", "capres-miss")
            warm = evaluate_window(store, plans)
            dev = warm.info["device"]
            assert dev["dispatched"] and dev["fallback"] is None
            assert dev["pairs"] == len(plans)
            assert dev["d2h"] == 3  # used/caps/fits through fetch_host
            assert dev["bucket"] >= dev["pairs"]


# ---------------------------------------------------------------------------
# 3. the applier's window drain + one-raft-apply commit
# ---------------------------------------------------------------------------

def _rig(on_apply=None):
    broker = EvalBroker()
    broker.set_enabled(True)
    fsm = NomadFSM(eval_broker=broker, on_apply=on_apply)
    raft = InmemRaft(fsm)
    queue = PlanQueue()
    queue.set_enabled(True)
    applier = PlanApplier(queue, broker, raft, lambda: fsm.state)
    return broker, fsm, raft, queue, applier


def _outstanding_plan(broker, fsm, raft, node, *, cpu=1000):
    """A token-fenced plan for a fresh eval the broker handed out."""
    ev = Evaluation(id=generate_uuid(), priority=50, type="service",
                    job_id=generate_uuid(), status="pending",
                    triggered_by="job-register")
    entry = codec.encode(codec.EVAL_UPDATE_REQUEST,
                         {"evals": [ev.to_dict()]})
    raft.apply(entry).wait(5.0)
    got, token = broker.dequeue(["service"], timeout=2.0)
    assert got.id == ev.id
    plan = place_plan(make_alloc(node, cpu=cpu))
    plan.eval_id = ev.id
    plan.eval_token = token
    return plan


class TestApplierWindow:
    def test_window_commits_as_one_batched_apply(self):
        applied = []
        broker, fsm, raft, queue, applier = _rig(
            on_apply=lambda i, t, p: applied.append((i, t)))
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        applied.clear()

        futures = [queue.enqueue(_outstanding_plan(broker, fsm, raft,
                                                   node, cpu=500))
                   for _ in range(4)]
        window = [queue.dequeue(0)] + queue.drain_pending(63)
        assert len(window) == 4
        applier._apply_window(window, None, None)

        results = [f.wait(5.0) for f in futures]
        # ONE raft apply carried the whole window...
        plan_applies = [t for _i, t in applied
                        if t in (codec.ALLOC_UPDATE_REQUEST,
                                 codec.PLAN_BATCH_APPLY_REQUEST)]
        assert plan_applies == [codec.PLAN_BATCH_APPLY_REQUEST]
        # ...every member future got the commit index, and state has
        # every plan's allocs exactly once.
        assert len({r.alloc_index for r in results}) == 1
        assert len(fsm.state.allocs_by_node(node.id)) == 4
        stats = applier.stats()
        assert stats["commits"] == 1
        assert stats["plans_committed"] == 4
        assert stats["batch_occupancy"] == 4.0
        assert stats["windows"] == [4]

    def test_window_results_match_sequential_order(self):
        """Two window plans over-commit one node: the first commits,
        the second is rejected with a refresh — eval-order semantics
        through the real applier."""
        broker, fsm, raft, queue, applier = _rig()
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        f1 = queue.enqueue(_outstanding_plan(broker, fsm, raft, node,
                                             cpu=FREE_CPU))
        f2 = queue.enqueue(_outstanding_plan(broker, fsm, raft, node,
                                             cpu=1000))
        window = [queue.dequeue(0)] + queue.drain_pending(63)
        applier._apply_window(window, None, None)
        r1 = f1.wait(5.0)
        r2 = f2.wait(5.0)
        assert r1.node_allocation and r1.alloc_index > 0
        assert r2.node_allocation == {} and r2.refresh_index > 0
        assert len(fsm.state.allocs_by_node(node.id)) == 1
        assert applier.stats()["conflict_fallbacks"] == 1

    def test_single_committer_keeps_legacy_wire_format(self):
        applied = []
        broker, fsm, raft, queue, applier = _rig(
            on_apply=lambda i, t, p: applied.append(t))
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        applied.clear()
        f = queue.enqueue(_outstanding_plan(broker, fsm, raft, node))
        window = [queue.dequeue(0)] + queue.drain_pending(63)
        applier._apply_window(window, None, None)
        assert f.wait(5.0).alloc_index > 0
        plan_applies = [t for t in applied
                        if t in (codec.ALLOC_UPDATE_REQUEST,
                                 codec.PLAN_BATCH_APPLY_REQUEST)]
        assert plan_applies == [codec.ALLOC_UPDATE_REQUEST]

    def test_bad_tokens_fenced_out_of_window(self):
        broker, fsm, raft, queue, applier = _rig()
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        good = _outstanding_plan(broker, fsm, raft, node)
        bad = place_plan(make_alloc(node))
        bad.eval_id = generate_uuid()  # never outstanding
        f_bad = queue.enqueue(bad)
        f_good = queue.enqueue(good)
        window = [queue.dequeue(0)] + queue.drain_pending(63)
        applier._apply_window(window, None, None)
        with pytest.raises(RuntimeError, match="not outstanding"):
            f_bad.wait(5.0)
        assert f_good.wait(5.0).alloc_index > 0

    def test_errored_batch_apply_responds_every_member_future(self):
        """The raft.apply fault site (ISSUE satellite): an errored batch
        apply must respond EVERY member future with the error, move no
        state, and a retry must not double-place."""
        from nomad_tpu import faultinject
        from nomad_tpu.faultinject import FaultPlan

        broker, fsm, raft, queue, applier = _rig()
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        plans = [_outstanding_plan(broker, fsm, raft, node)
                 for _ in range(3)]

        fplan = FaultPlan.parse("raft.apply=error(count=1)")
        with faultinject.injected(fplan):
            futures = [queue.enqueue(p) for p in plans]
            window = [queue.dequeue(0)] + queue.drain_pending(63)
            applier._apply_window(window, None, None)
            errs = 0
            for f in futures:
                with pytest.raises(Exception):
                    f.wait(5.0)
                errs += 1
            assert errs == 3
            assert fsm.state.allocs_by_node(node.id) == [], \
                "an errored batch apply must move no state"

            # Retry (same eval tokens are still outstanding): the full
            # window commits exactly once — no double placement.
            futures = [queue.enqueue(p) for p in plans]
            window = [queue.dequeue(0)] + queue.drain_pending(63)
            applier._apply_window(window, None, None)
            for f in futures:
                assert f.wait(5.0).alloc_index > 0
        assert len(fsm.state.allocs_by_node(node.id)) == 3
        assert fplan.fire_count("raft.apply") == 1

    def test_applier_thread_drains_queue_window(self):
        """End to end with the real applier thread: plans enqueued
        before the thread starts drain as one window."""
        applied = []
        broker, fsm, raft, queue, applier = _rig(
            on_apply=lambda i, t, p: applied.append(t))
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        applied.clear()
        futures = [queue.enqueue(_outstanding_plan(broker, fsm, raft,
                                                   node))
                   for _ in range(3)]
        applier.start()
        try:
            for f in futures:
                assert f.wait(5.0).alloc_index > 0
            assert codec.PLAN_BATCH_APPLY_REQUEST in applied
        finally:
            queue.set_enabled(False)
            applier.join(5.0)


# ---------------------------------------------------------------------------
# 4. plan queue window drain
# ---------------------------------------------------------------------------

class TestDrainPending:
    def test_drains_in_priority_order(self):
        q = PlanQueue()
        q.set_enabled(True)
        lo = Plan(eval_id=generate_uuid(), priority=10)
        hi = Plan(eval_id=generate_uuid(), priority=90)
        mid = Plan(eval_id=generate_uuid(), priority=50)
        q.enqueue(lo)
        q.enqueue(hi)
        q.enqueue(mid)
        first = q.dequeue(0)
        rest = q.drain_pending(8)
        assert first.plan is hi
        assert [f.plan for f in rest] == [mid, lo]
        assert q.drain_pending(8) == []
        assert q.stats()["depth"] == 0

    def test_respects_max(self):
        q = PlanQueue()
        q.set_enabled(True)
        for _ in range(5):
            q.enqueue(Plan(eval_id=generate_uuid(), priority=50))
        assert len(q.drain_pending(3)) == 3
        assert len(q.drain_pending(0)) == 0
        assert len(q.drain_pending(9)) == 2

    def test_deadline_promotion_pulls_near_deadline_plan_forward(self):
        """A LOW-priority plan whose deadline falls inside the drain
        horizon jumps the high-priority stream — without promotion it
        would sit past the window cut until _fence expires it."""
        import time as _time

        q = PlanQueue()
        q.set_enabled(True)
        urgent = Plan(eval_id=generate_uuid(), priority=1)
        urgent.deadline = _time.monotonic() + 0.05
        hi = [Plan(eval_id=generate_uuid(), priority=90)
              for _ in range(4)]
        for p in hi:
            q.enqueue(p)
        q.enqueue(urgent)
        # Window of 3 out of 5 pending: plain priority order would
        # never include the low-priority near-deadline plan.
        first = q.dequeue(0)
        window = [first.plan] + [f.plan
                                 for f in q.drain_pending(2,
                                                          horizon=1.0)]
        assert urgent in window, "near-deadline plan must be promoted"
        assert window[1] is urgent, "promoted plans lead the window"
        assert q.stats()["deadline_promotions"] == 1
        # The remaining high-priority plans are still there, in order.
        rest = q.drain_pending(8, horizon=1.0)
        assert len(rest) == 2
        assert q.stats()["depth"] == 0

    def test_far_deadlines_keep_priority_order(self):
        import time as _time

        q = PlanQueue()
        q.set_enabled(True)
        lo = Plan(eval_id=generate_uuid(), priority=10)
        lo.deadline = _time.monotonic() + 500.0  # far outside horizon
        hi = Plan(eval_id=generate_uuid(), priority=90)
        q.enqueue(lo)
        q.enqueue(hi)
        first = q.dequeue(0)
        assert first.plan is hi
        assert [f.plan for f in q.drain_pending(4, horizon=0.25)] == [lo]
        assert q.stats()["deadline_promotions"] == 0

    def test_await_depth_returns_on_fill_and_timeout(self):
        import threading
        import time as _time

        q = PlanQueue()
        q.set_enabled(True)
        t0 = _time.monotonic()
        assert q.await_depth(2, timeout=0.05) == 0  # times out empty
        assert _time.monotonic() - t0 >= 0.04

        def fill():
            q.enqueue(Plan(eval_id=generate_uuid(), priority=50))
            q.enqueue(Plan(eval_id=generate_uuid(), priority=50))

        t = threading.Thread(target=fill)
        t.start()
        assert q.await_depth(2, timeout=5.0) >= 2  # wakes on fill
        t.join(2.0)


# ---------------------------------------------------------------------------
# 5. the claim-graph partitioner (ISSUE 13 satellite: exactness)
# ---------------------------------------------------------------------------

def _brute_force_components(plans) -> set:
    """Reference partition: adjacency over shared claimed nodes,
    flood-filled."""
    from nomad_tpu.ops.plan_conflict import _touched

    n = len(plans)
    touched = [_touched(p) for p in plans]
    adj = {i: set() for i in range(n)}
    for i in range(n):
        for j in range(i + 1, n):
            if touched[i] & touched[j]:
                adj[i].add(j)
                adj[j].add(i)
    seen: set = set()
    comps = []
    for i in range(n):
        if i in seen:
            continue
        comp = set()
        stack = [i]
        while stack:
            k = stack.pop()
            if k in comp:
                continue
            comp.add(k)
            stack.extend(adj[k] - comp)
        seen |= comp
        comps.append(frozenset(comp))
    return set(comps)


class TestPartitioner:
    def test_random_claim_sets_match_brute_force(self):
        """Property test: union-find components over random windows ==
        the brute-force adjacency flood fill, and no two plans in
        different components share a node claim — across many seeds,
        with evict-frees-capacity and port-collision window shapes
        mixed in."""
        import random

        from nomad_tpu.ops.plan_conflict import (_touched,
                                                 partition_window)

        nodes = [mock.node(i) for i in range(12)]
        for seed in range(40):
            rng = random.Random(seed)
            plans = []
            for _ in range(rng.randrange(1, 24)):
                kind = rng.random()
                picked = rng.sample(nodes, rng.randrange(1, 4))
                if kind < 0.25:
                    # evict-frees-capacity shape: stop + refill
                    plan = Plan(eval_id=generate_uuid())
                    victim = make_alloc(picked[0], cpu=FREE_CPU)
                    plan.append_update(victim,
                                       ALLOC_DESIRED_STATUS_STOP,
                                       "preempted")
                    if len(picked) > 1:
                        plan.append_alloc(make_alloc(picked[1]))
                elif kind < 0.5:
                    # port-collision shape: static port claims
                    plan = place_plan(*[net_alloc(n, ports=[9000])
                                        for n in picked])
                else:
                    plan = place_plan(*[make_alloc(n) for n in picked])
                plans.append(plan)

            comps = partition_window(plans)
            # Exact partition of indices.
            flat = [i for c in comps for i in c]
            assert sorted(flat) == list(range(len(plans)))
            assert all(c == sorted(c) for c in comps)
            # Matches brute force.
            assert {frozenset(c) for c in comps} == \
                _brute_force_components(plans), seed
            # Cross-component node-claim disjointness.
            for a in range(len(comps)):
                for b in range(a + 1, len(comps)):
                    nodes_a = set().union(*[_touched(plans[i])
                                            for i in comps[a]])
                    nodes_b = set().union(*[_touched(plans[i])
                                            for i in comps[b]])
                    assert not (nodes_a & nodes_b), seed

    def test_components_ordered_by_first_member(self):
        from nomad_tpu.ops.plan_conflict import partition_window

        a, b = mock.node(), mock.node(1)
        plans = [place_plan(make_alloc(a)),     # comp 0
                 place_plan(make_alloc(b)),     # comp 1
                 place_plan(make_alloc(a))]     # joins comp 0
        comps = partition_window(plans)
        assert comps == [[0, 2], [1]]

    def test_window_info_reports_partition(self):
        store = StateStore()
        nodes = [mock.node(i) for i in range(4)]
        for i, n in enumerate(nodes):
            store.upsert_node(1000 + i, n)
        plans = [place_plan(make_alloc(n)) for n in nodes]
        outcomes = evaluate_window(store, plans)
        assert outcomes.info is not None
        assert outcomes.info["components"] == 4
        assert outcomes.info["sizes"] == [1, 1, 1, 1]
        assert {o.component for o in outcomes} == {0, 1, 2, 3}

    def test_big_component_rides_the_executor(self):
        """A window with a real conflict cluster (>= the concurrency
        threshold) dispatches to the ComponentExecutor, and verdicts
        stay byte-identical to sequential application."""
        from nomad_tpu.ops.plan_conflict import MIN_CONCURRENT_COMPONENT
        from nomad_tpu.server.plan_apply import ComponentExecutor

        shared = mock.node()
        others = [mock.node(i + 1) for i in range(4)]

        def world():
            store = StateStore()
            store.upsert_node(1000, shared)
            for i, n in enumerate(others):
                store.upsert_node(1001 + i, n)
            return store

        plans = [place_plan(make_alloc(shared, cpu=300))
                 for _ in range(MIN_CONCURRENT_COMPONENT)]
        plans += [place_plan(make_alloc(n)) for n in others]

        s_seq = world()
        res_seq = sequential_apply(s_seq, plans, 3000)
        executor = ComponentExecutor(workers=2)
        try:
            s_grp = world()
            res_grp = grouped_apply(s_grp, plans, 3000,
                                    executor=executor)
            assert [result_key(r) for r in res_seq] == \
                [result_key(r) for r in res_grp]
            assert store_image(s_seq) == store_image(s_grp)
            stats = executor.stats()
            assert stats["batches"] >= 1, \
                "a >= threshold component must ride the executor"
            assert stats["components_run"] >= 5
        finally:
            executor.stop()


# ---------------------------------------------------------------------------
# 6. deadline fencing + the applier's service threads
# ---------------------------------------------------------------------------

class TestDeadlineFence:
    def test_expired_plan_dropped_before_verification(self):
        """_fence_window answers an already-expired plan with
        ErrDeadlineExceeded, commits the live plans, and counts the
        drop."""
        import time as _time

        from nomad_tpu.server.overload import ErrDeadlineExceeded

        broker, fsm, raft, queue, applier = _rig()
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        live = _outstanding_plan(broker, fsm, raft, node, cpu=100)
        live.deadline = _time.monotonic() + 30.0
        dead = _outstanding_plan(broker, fsm, raft, node, cpu=100)
        dead.deadline = _time.monotonic() - 0.1
        f_live = queue.enqueue(live)
        f_dead = queue.enqueue(dead)
        window = [queue.dequeue(0)] + queue.drain_pending(63)
        try:
            applier._apply_window(window, None, None)
            with pytest.raises(ErrDeadlineExceeded):
                f_dead.wait(5.0)
            assert f_live.wait(5.0).alloc_index > 0
            assert applier.stats()["expired_drops"] == 1
            assert len(fsm.state.allocs_by_node(node.id)) == 1
        finally:
            applier.shutdown(5.0)
            broker.shutdown()


class TestDispatchFailureOverlay:
    def test_dispatch_failure_drops_phantom_overlay_folds(self):
        """A window whose raft DISPATCH fails has already folded its
        allocs into the applier's optimistic overlay (the partitioned
        path folds before the committer hand-off): the next window
        must verify against a fresh snapshot, not the phantoms — a
        later plan that fits only if the failed window never happened
        must be ACCEPTED."""
        from nomad_tpu import faultinject
        from nomad_tpu.faultinject import FaultPlan

        broker, fsm, raft, queue, applier = _rig()
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        try:
            full_a = _outstanding_plan(broker, fsm, raft, node,
                                       cpu=FREE_CPU)
            full_b = _outstanding_plan(broker, fsm, raft, node,
                                       cpu=FREE_CPU)
            fplan = FaultPlan.parse("raft.apply=error(count=1)")
            with faultinject.injected(fplan):
                f_a = queue.enqueue(full_a)
                window = [queue.dequeue(0)] + queue.drain_pending(63)
                wait_future, snap = applier._apply_window(
                    window, None, None)
                with pytest.raises(Exception):
                    f_a.wait(5.0)  # dispatch failed; flag raised

                # Same node, full capacity again: fits ONLY if the
                # failed window's folds are dropped.  Thread the
                # RETURNED overlay state through, like run() does.
                f_b = queue.enqueue(full_b)
                window = [queue.dequeue(0)] + queue.drain_pending(63)
                applier._apply_window(window, wait_future, snap)
                assert f_b.wait(5.0).alloc_index > 0, \
                    "phantom folds from a failed dispatch must not " \
                    "reject later plans"
            assert len(fsm.state.allocs_by_node(node.id)) == 1
        finally:
            applier.shutdown(5.0)
            broker.shutdown()

    def test_window_queued_behind_failed_dispatch_is_refused(self):
        """The in-flight variant: window B verifies (and is ACCEPTED)
        against window A's overlay folds while A's dispatch has not
        yet failed, and queues behind A in the committer.  FIFO means
        B's commit job observes A's failure — it must be REFUSED with
        a retryable error (B fits only thanks to A's phantom
        eviction; committing it would durably over-commit the node) —
        and B's retry against refreshed state must see the truth."""
        import threading

        from nomad_tpu import faultinject
        from nomad_tpu.faultinject import FaultPlan

        broker, fsm, raft, queue, applier = _rig()
        applier.max_inflight_commits = 4  # let B queue behind A
        node = mock.node()
        raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                                {"node": node.to_dict()})).wait(5.0)
        existing = make_alloc(node, cpu=FREE_CPU)
        raft.apply(codec.encode(
            codec.ALLOC_UPDATE_REQUEST,
            {"alloc": [existing.to_dict()]})).wait(5.0)
        try:
            # A: token-fenced EVICTION of the full-node alloc.
            ev_a = _outstanding_plan(broker, fsm, raft, node, cpu=1)
            plan_a = Plan(eval_id=ev_a.eval_id,
                          eval_token=ev_a.eval_token, priority=50)
            plan_a.append_update(existing, ALLOC_DESIRED_STATUS_STOP,
                                 "preempted")
            # B: fills the capacity A's eviction would free.
            plan_b = _outstanding_plan(broker, fsm, raft, node,
                                       cpu=FREE_CPU)

            # Hold the committer so BOTH windows queue before either
            # dispatches, then fail A's dispatch.
            gate = threading.Event()
            applier._committer.submit(lambda: gate.wait(10.0))
            fplan = FaultPlan.parse("raft.apply=error(count=1)")
            with faultinject.injected(fplan):
                f_a = queue.enqueue(plan_a)
                window = [queue.dequeue(0)] + queue.drain_pending(63)
                wait_future, snap = applier._apply_window(
                    window, None, None)
                f_b = queue.enqueue(plan_b)
                window = [queue.dequeue(0)] + queue.drain_pending(63)
                applier._apply_window(window, wait_future, snap)
                gate.set()
                with pytest.raises(Exception):
                    f_a.wait(5.0)   # A: dispatch error
                with pytest.raises(RuntimeError, match="retry"):
                    f_b.wait(5.0)   # B: refused, never committed

            # Nothing moved: the existing alloc still owns the node.
            live = [a for a in fsm.state.allocs_by_node(node.id)
                    if not a.terminal_status()]
            assert [a.id for a in live] == [existing.id], \
                "a phantom-verified window must never commit"

            # B's retry sees refreshed truth: the node is still full,
            # so the plan is rejected with a refresh (not placed).
            f_b2 = queue.enqueue(plan_b)
            window = [queue.dequeue(0)] + queue.drain_pending(63)
            applier._apply_window(window, None, None)
            result = f_b2.wait(5.0)
            assert result.node_allocation == {}
            assert result.refresh_index > 0
        finally:
            applier.shutdown(5.0)
            broker.shutdown()


class TestApplierServiceThreads:
    def test_component_executor_active_attribution(self):
        """The executor's active() snapshot names what is verifying
        RIGHT NOW — the flight recorder's per-component stall
        attribution rides it."""
        import threading

        from nomad_tpu.server.plan_apply import ComponentExecutor

        executor = ComponentExecutor(workers=1)
        started = threading.Event()
        release = threading.Event()

        def slow():
            started.set()
            release.wait(5.0)
            return "done"

        tasks = [slow] + [lambda: "fast"] * 3
        descs = [{"component": 0, "eval_ids": ["ev-slow"]},
                 None, None, None]
        out = []
        runner = threading.Thread(
            target=lambda: out.append(
                executor.run_components(tasks, descs)))
        runner.start()
        try:
            assert started.wait(5.0)
            active = executor.active()
            assert active["verifying"], "a walk is live"
            blob = str(active)
            assert "ev-slow" in blob, \
                "the stall attribution must name the slow component"
        finally:
            release.set()
            runner.join(5.0)
            executor.stop()
        assert out and [r for chunk in out for r in [chunk]] is not None

    def test_executor_stop_reaps_workers(self):
        import threading

        from nomad_tpu.server.plan_apply import ComponentExecutor

        executor = ComponentExecutor(workers=2, name="test-comps")
        executor.run_components([lambda: 1, lambda: 2, lambda: 3])
        assert any(t.name.startswith("test-comps")
                   for t in threading.enumerate())
        executor.stop()
        assert not any(t.name.startswith("test-comps") and t.is_alive()
                       for t in threading.enumerate())

    def test_committer_survives_and_keeps_order(self):
        """FIFO commit order: jobs resolve in submission order even
        when earlier jobs are slower."""
        import threading
        import time as _time

        from nomad_tpu.server.plan_apply import _Committer

        committer = _Committer(name="test-committer")
        order = []
        done = threading.Event()

        def job(k, delay):
            def run():
                _time.sleep(delay)  # sleep-ok: ordering probe
                order.append(k)
                if k == 2:
                    done.set()
            return run

        committer.submit(job(0, 0.05))
        committer.submit(job(1, 0.0))
        committer.submit(job(2, 0.0))
        assert done.wait(5.0)
        assert order == [0, 1, 2]
        committer.stop()
        assert not any(t.name == "test-committer" and t.is_alive()
                       for t in threading.enumerate())
