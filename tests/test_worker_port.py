"""Port of the reference's worker_test.go scenario table (476 LoC,
/root/reference/nomad/worker_test.go) against server/worker.py.

Covers the upstream table's worker-side seams:

  - dequeueEvaluation + sendAck: the run loop dequeues, invokes the
    scheduler, and acks (eval reaches a terminal status, nothing left
    unacked);
  - invalidateEval: a scheduler crash nacks; past the delivery limit
    the broker routes the eval to the `_failed` queue;
  - waitForIndex: returns when raft catches up (including an apply
    landing WHILE waiting), times out when it never does;
  - SubmitPlan: token stamped, full-commit plans return no refreshed
    state, rejected plans come back with a fresh snapshot
    (RefreshIndex), a stale/wrong token is fenced by the applier;
  - UpdateEval/CreateEval: token-fenced eval writes through raft.
"""
from __future__ import annotations

import threading
import time

import pytest

import nomad_tpu.mock as mock
from tests.conftest import wait_until
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.worker import Worker
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    Allocation,
    Evaluation,
    Plan,
    Resources,
    generate_uuid,
)


def make_eval(job_id=None, type_="service") -> Evaluation:
    return Evaluation(
        id=generate_uuid(), priority=50, type=type_,
        job_id=job_id or generate_uuid(), status="pending",
        triggered_by="job-register",
    )


def make_server(**kw) -> Server:
    srv = Server(ServerConfig(num_schedulers=0, **kw))
    srv.establish_leadership()
    return srv


def place_plan(node, ev, token, cpu=1000) -> Plan:
    plan = Plan(eval_id=ev.id, eval_token=token)
    plan.append_alloc(Allocation(
        id=generate_uuid(), node_id=node.id, job_id=ev.job_id,
        task_group="web", resources=Resources(cpu=cpu, memory_mb=256),
        desired_status=ALLOC_DESIRED_STATUS_RUN,
        client_status=ALLOC_CLIENT_STATUS_PENDING,
    ))
    return plan


class TestDequeueAck:
    def test_dequeue_invoke_ack(self):
        """TestWorker_dequeueEvaluation + sendAck: the loop drains a
        ready eval to a terminal status and leaves nothing unacked."""
        srv = make_server()
        try:
            ev = make_eval()
            srv.apply_eval_update([ev])
            w = Worker(srv)
            w.start()
            try:
                wait_until(
                    lambda: (srv.fsm.state.eval_by_id(ev.id) or ev
                             ).status == "complete",
                    msg="worker completes eval")
                wait_until(
                    lambda: srv.eval_broker.stats()[
                        "total_unacked"] == 0,
                    msg="eval acked")
            finally:
                w.stop()
        finally:
            srv.shutdown()

    def test_shutdown_stops_loop(self):
        """TestWorker_dequeueEvaluation_shutdown: stop() ends the run
        loop even with an empty queue."""
        srv = make_server()
        try:
            w = Worker(srv)
            w.start()
            w.stop()
            w._thread.join(timeout=5)
            assert not w._thread.is_alive()
        finally:
            srv.shutdown()

    def test_scheduler_crash_nacks_to_failed_status(self, monkeypatch):
        """TestWorker_invalidateEval: a crashing scheduler nacks; past
        the delivery limit the broker routes the eval to `_failed`,
        where the leader's reaper marks it terminally failed with the
        delivery-limit description."""
        srv = make_server(eval_nack_timeout=5.0, eval_delivery_limit=2)
        try:
            import nomad_tpu.server.worker as worker_mod

            def boom(name, state, planner):
                raise RuntimeError("scheduler exploded")

            monkeypatch.setattr(worker_mod, "new_scheduler", boom)
            ev = make_eval()
            srv.apply_eval_update([ev])
            w = Worker(srv)
            w.start()
            try:
                wait_until(
                    lambda: (srv.fsm.state.eval_by_id(ev.id) or ev
                             ).status == "failed",
                    msg="eval failed after delivery limit")
                got = srv.fsm.state.eval_by_id(ev.id)
                assert "delivery limit" in got.status_description
                assert srv.eval_broker.stats()["total_unacked"] == 0
            finally:
                w.stop()
        finally:
            srv.shutdown()


class TestWaitPlan:
    def test_responded_timeout_error_propagates_not_spins(self):
        """A plan RESPONDED with a TimeoutError result (e.g. a raft
        apply timeout surfaced through the applier) must re-raise to
        the worker, not be mistaken for the poll expiring — that
        mistake zero-sleep spun _wait_plan forever (code-review
        regression)."""
        from nomad_tpu.server.plan_queue import PlanFuture

        srv = make_server()
        try:
            srv.plan_queue.set_enabled(True)
            w = Worker(srv)
            future = PlanFuture(mock.plan())
            future.respond(None, TimeoutError("raft apply timed out"))
            start = time.monotonic()
            with pytest.raises(TimeoutError, match="raft apply"):
                w._wait_plan(future)
            # Propagated immediately — not after a poll interval, and
            # certainly not never.
            assert time.monotonic() - start < 1.0
        finally:
            srv.shutdown()

    def test_respond_racing_poll_expiry_returns_result(self):
        """respond(result) landing between the poll's TimeoutError and
        the done() check must surface the RESULT, not the spurious poll
        error — a committed plan reported as failed would be retried
        and double-place (code-review regression)."""
        srv = make_server()
        try:
            w = Worker(srv)

            class ScriptedFuture:
                """First wait raises like an expired poll; by then the
                applier has responded."""

                def __init__(self, result):
                    self._result = result
                    self._calls = 0

                def wait(self, timeout=None):
                    self._calls += 1
                    if self._calls == 1:
                        raise TimeoutError("poll expired")
                    return self._result

                def done(self):
                    return True

            sentinel = object()
            assert w._wait_plan(ScriptedFuture(sentinel)) is sentinel
        finally:
            srv.shutdown()


class TestWaitForIndex:
    def test_returns_when_index_lands_mid_wait(self):
        """TestWorker_waitForIndex: an apply landing WHILE the worker
        waits releases it (raft catch-up, worker.go:209-230)."""
        srv = make_server()
        try:
            w = Worker(srv)
            target = srv.raft.applied_index() + 1

            def apply_later():
                time.sleep(0.1)  # sleep-ok: delayed apply exercises mid-wait wakeup
                srv.apply_eval_update([make_eval()])

            t = threading.Thread(target=apply_later)
            t.start()
            w._wait_for_index(target, timeout=5.0)  # must not raise
            t.join()
            assert srv.raft.applied_index() >= target
        finally:
            srv.shutdown()

    def test_timeout(self):
        srv = make_server()
        try:
            w = Worker(srv)
            with pytest.raises(TimeoutError):
                w._wait_for_index(srv.raft.applied_index() + 100,
                                  timeout=0.15)
        finally:
            srv.shutdown()


class TestSubmitPlan:
    def _outstanding_eval(self, srv):
        ev = make_eval()
        srv.apply_eval_update([ev])
        got, token = srv.eval_broker.dequeue(["service"], timeout=2)
        assert got.id == ev.id
        return got, token

    def test_submit_plan_commits(self):
        """TestWorker_SubmitPlan: full commit — result carries the
        commit index, no refreshed state handed back."""
        srv = make_server()
        try:
            node = mock.node()
            srv.node_register(node)
            ev, token = self._outstanding_eval(srv)
            w = Worker(srv)
            w.eval_token = token
            plan = place_plan(node, ev, "")  # worker stamps the token
            result, state = w.submit_plan(plan)
            assert plan.eval_token == token, "worker must stamp token"
            assert state is None
            assert result.alloc_index > 0
            assert srv.fsm.state.allocs_by_node(node.id)
        finally:
            srv.shutdown()

    def test_submit_plan_rejection_returns_fresh_state(self):
        """TestWorker_SubmitPlan_MissingNodeRefresh: a plan touching a
        node the applier can't verify comes back empty with a caught-up
        snapshot so the scheduler retries against fresh data."""
        srv = make_server()
        try:
            srv.node_register(mock.node())  # nodes table index > 0
            ev, token = self._outstanding_eval(srv)
            w = Worker(srv)
            w.eval_token = token
            ghost = mock.node()  # never registered
            result, state = w.submit_plan(place_plan(ghost, ev, ""))
            assert result.node_allocation == {}
            assert result.refresh_index > 0
            assert state is not None
            assert state.node_by_id(ghost.id) is None
        finally:
            srv.shutdown()

    def test_submit_plan_invalid_token_fenced(self):
        """A stale/wrong token is refused by the applier before
        touching state (split-brain fence, plan_apply.go:53-65)."""
        srv = make_server()
        try:
            node = mock.node()
            srv.node_register(node)
            ev, _token = self._outstanding_eval(srv)
            w = Worker(srv)
            w.eval_token = "not-the-token"
            with pytest.raises(RuntimeError, match="token does not"):
                w.submit_plan(place_plan(node, ev, ""))
            assert not srv.fsm.state.allocs_by_node(node.id)
        finally:
            srv.shutdown()


class TestEvalWrites:
    def test_update_eval_persists_through_raft(self):
        """TestWorker_UpdateEval: the worker's status write lands in
        the FSM under its delivery token."""
        srv = make_server()
        try:
            ev = make_eval()
            srv.apply_eval_update([ev])
            got, token = srv.eval_broker.dequeue(["service"], timeout=2)
            w = Worker(srv)
            w.eval_token = token
            done = got.copy()
            done.status = "complete"
            w.update_eval(done)
            assert srv.fsm.state.eval_by_id(ev.id).status == "complete"
        finally:
            srv.shutdown()

    def test_update_eval_wrong_token_rejected(self):
        """An outstanding eval may only be updated by its holder."""
        srv = make_server()
        try:
            ev = make_eval()
            srv.apply_eval_update([ev])
            got, _token = srv.eval_broker.dequeue(["service"], timeout=2)
            w = Worker(srv)
            w.eval_token = "imposter"
            done = got.copy()
            done.status = "complete"
            with pytest.raises(PermissionError):
                w.update_eval(done)
        finally:
            srv.shutdown()

    def test_create_eval_enqueues_follow_up(self):
        """TestWorker_CreateEval: a follow-up eval (rolling-update
        stagger) written by the worker reaches the broker as pending
        work for its job."""
        srv = make_server()
        try:
            ev = make_eval()
            srv.apply_eval_update([ev])
            got, token = srv.eval_broker.dequeue(["service"], timeout=2)
            w = Worker(srv)
            w.eval_token = token
            follow = make_eval(job_id=got.job_id)
            follow.previous_eval = got.id
            w.create_eval(follow)
            assert srv.fsm.state.eval_by_id(follow.id) is not None
            # Same job, earlier eval outstanding: serialized behind it.
            srv.eval_broker.ack(got.id, token)
            nxt, _ = srv.eval_broker.dequeue(["service"], timeout=2)
            assert nxt.id == follow.id
        finally:
            srv.shutdown()
