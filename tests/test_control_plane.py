"""Feedback control plane (ISSUE 14): actuators, laws, controller,
wiring, and the seeded chaos rig.

The contract under test: a deterministic, seeded tick loop reads the
gauges the metrics registry already publishes and adjusts the live
knobs through railed actuators — with every decision observable
(control.tick/control.adjust spans, the ``controller`` registry
provider) and every misbehavior self-indicting (flight dumps on
reversal and rail saturation).  The convergence proof lives in
bench.py (5c/5f rerun with 4x-mis-set constants); these tests pin the
mechanisms.
"""
from __future__ import annotations

import threading
import time

import pytest

from nomad_tpu import faultinject, mock
from nomad_tpu.control import (
    AIMD,
    Actuator,
    Controller,
    GradientStep,
    applier_controller,
    runner_controller,
)
from nomad_tpu.control.controller import TickView
from nomad_tpu.faultinject import FaultPlan
from nomad_tpu.obs import flight, trace

from tests.conftest import wait_until


def _box(value):
    state = {"v": value}
    return state, (lambda: state["v"]), \
        (lambda v: state.__setitem__("v", v))


def _actuator(value=8, lo=1, hi=16, integer=True, name="k"):
    state, get, set_ = _box(value)
    return state, Actuator(name, get=get, set=set_, lo=lo, hi=hi,
                           integer=integer, gauge="g")


# ---------------------------------------------------------------------------
# 1. actuators: rails, reversals, pin
# ---------------------------------------------------------------------------

class TestActuator:
    def test_clamps_into_rails_and_counts_saturation_once(self):
        state, act = _actuator(8, lo=1, hi=10)
        old, new, ev = act.apply(50)
        assert (old, new, state["v"]) == (8, 10, 10)
        assert ev["rail"] is True and act.rail_hits == 1
        # Parked at the rail: further saturated decisions book NO new
        # rail hit (transition-counted, not per-tick).
        _old, _new, ev2 = act.apply(50)
        assert ev2["rail"] is False and act.rail_hits == 1
        # Moving back inside re-arms the transition.
        act.apply(5)
        act.apply(50)
        assert act.rail_hits == 2

    def test_reversals_count_direction_flips(self):
        _state, act = _actuator(8)
        act.apply(9)    # up
        act.apply(10)   # up: no reversal
        assert act.reversals == 0
        act.apply(5)    # down: reversal
        act.apply(7)    # up again: reversal
        assert act.reversals == 2
        assert act.stats()["trajectory"] == [8, 9, 10, 5, 7]

    def test_integer_knob_rounds(self):
        state, act = _actuator(3, integer=True)
        act.apply(4.6)
        assert state["v"] == 5

    def test_pin_takes_knob_out_of_the_loop(self):
        state, act = _actuator(8)
        ctl = Controller(lambda: {"g": 1.0}, interval=0.05)
        ctl.add_knob(act, law=AIMD(), driver=lambda v: +1)
        ctl.tick()                      # baseline
        assert ctl.tick()               # adjusts
        act.pin(4)
        assert state["v"] == 4
        assert ctl.tick() == []         # pinned: untouched
        assert state["v"] == 4
        act.pin(None)
        assert ctl.tick()               # back in the loop
        assert act.stats()["pinned"] is False

    def test_pin_clamps_to_rails(self):
        state, act = _actuator(8, lo=2, hi=10)
        act.pin(100)
        assert state["v"] == 10

    def test_rejects_inverted_rails(self):
        with pytest.raises(ValueError):
            Actuator("bad", get=lambda: 1, set=lambda v: None,
                     lo=5, hi=5)


class TestLaws:
    def test_aimd_shape(self):
        law = AIMD(add=2.0, mult=0.5)
        assert law.step(8, +1) == 10
        assert law.step(8, -1) == 4
        assert law.step(8, 0) == 8
        with pytest.raises(ValueError):
            AIMD(add=0)
        with pytest.raises(ValueError):
            AIMD(mult=1.5)

    def test_gradient_shape(self):
        law = GradientStep(up=1.5, down=0.5)
        assert law.step(8, +1) == 12
        assert law.step(8, -1) == 4
        assert law.step(8, 0) == 8
        assert law.step(0.0, +1) > 0  # never wedges at zero
        with pytest.raises(ValueError):
            GradientStep(up=0.9)


# ---------------------------------------------------------------------------
# 2. the controller: determinism, isolation, spans, flight, lifecycle
# ---------------------------------------------------------------------------

def _scripted_controller(script, seed=7):
    """A controller over a scripted gauge stream (one dict per tick)."""
    feed = {"i": -1}

    def gauges():
        feed["i"] = min(feed["i"] + 1, len(script) - 1)
        return dict(script[feed["i"]])

    ctl = Controller(gauges, interval=0.05, seed=seed)
    _state, act = _actuator(8, lo=1, hi=64)
    ctl.add_knob(act, law=AIMD(add=1, mult=0.5),
                 driver=lambda v: +1 if v.get("g") > 0
                 else (-1 if v.get("g") < 0 else 0))
    return ctl


class TestController:
    SCRIPT = [{"g": 0}, {"g": 1}, {"g": 1}, {"g": -1}, {"g": 0},
              {"g": 1}]

    def test_deterministic_over_a_gauge_stream(self):
        runs = []
        for _ in range(2):
            ctl = _scripted_controller(self.SCRIPT)
            decisions = [ctl.tick() for _ in self.SCRIPT]
            stats = ctl.stats()
            stats.pop("interval_s")
            runs.append((decisions, stats))
        assert runs[0] == runs[1]
        # And the decisions are what the script dictates: two grows, a
        # halving (reversal), a hold, a grow (reversal).
        flat = [d for tick in runs[0][0] for d in tick]
        assert [d["new"] for d in flat] == [9, 10, 5, 6]
        assert [d["reversal"] for d in flat] == [False, False, True,
                                                 True]

    def test_first_tick_only_seeds_the_baseline(self):
        ctl = _scripted_controller([{"g": 1}, {"g": 1}])
        assert ctl.tick() == []
        assert ctl.tick() != []

    def test_every_n_slow_lane(self):
        gauges = {"g": 1.0}
        ctl = Controller(lambda: dict(gauges), interval=0.05)
        _state, act = _actuator(8, name="slow")
        ctl.add_knob(act, law=AIMD(), driver=lambda v: +1, every=3)
        moved = [bool(ctl.tick()) for _ in range(10)]
        # Evaluated on ticks 3/6/9; tick 3 seeds the knob's own delta
        # baseline (slow-lane deltas span the knob's whole cadence).
        assert moved == [False, False, False, False, False, True,
                         False, False, True, False]

    def test_broken_driver_is_isolated(self):
        gauges = {"g": 1.0}
        ctl = Controller(lambda: dict(gauges), interval=0.05)
        _s1, bad = _actuator(8, name="bad")

        def boom(view):
            raise RuntimeError("driver bug")
        ctl.add_knob(bad, law=AIMD(), driver=boom)
        s2, good = _actuator(8, name="good")
        ctl.add_knob(good, law=AIMD(), driver=lambda v: +1)
        ctl.tick()
        ctl.tick()
        assert s2["v"] == 9              # the healthy knob still moved
        assert ctl.stats()["driver_errors"] == 1

    def test_broken_gauges_fn_is_isolated(self):
        def boom():
            raise RuntimeError("gauge bug")
        ctl = Controller(boom, interval=0.05)
        assert ctl.tick() == []
        assert ctl.stats()["tick_errors"] == 1

    def test_decision_spans(self):
        with trace.tracing(seed=3) as tracer:
            ctl = _scripted_controller(self.SCRIPT)
            for _ in range(3):
                ctl.tick()
            spans = tracer.snapshot()
        ticks = [s for s in spans if s["name"] == "control.tick"]
        adjusts = [s for s in spans if s["name"] == "control.adjust"]
        assert len(ticks) == 3 and len(adjusts) == 2
        by_id = {s["span_id"]: s for s in spans}
        for adj in adjusts:
            parent = by_id[adj["parent_id"]]
            assert parent["name"] == "control.tick"
            tags = adj["tags"]
            assert tags["knob"] == "k" and tags["gauge"] == "g"
            assert tags["new"] == tags["old"] + 1
            assert tags["direction"] == 1

    def test_reversal_and_rail_trip_the_flight_recorder(self, tmp_path):
        with flight.installed(str(tmp_path), min_interval=0.0) as rec:
            gauges = {"g": 1.0}
            ctl = Controller(lambda: dict(gauges), interval=0.05,
                             name="ctl-test")
            _state, act = _actuator(8, lo=1, hi=9)
            ctl.add_knob(act, law=AIMD(), driver=lambda v: +1
                         if v.get("g") > 0 else -1)
            ctl.tick()          # baseline
            ctl.tick()          # 8 -> 9 (at rail, desired 9 in-range)
            ctl.tick()          # desired 10: rail saturation
            gauges["g"] = -1.0
            ctl.tick()          # halve: reversal
            names = [n.split("-", 2)[2] for n in rec.incidents()]
            assert any("control.rail" in n for n in names)
            assert any("control.reversal" in n for n in names)

    def test_tick_thread_starts_and_joins(self):
        gauges = {"g": 0.0}
        ctl = Controller(lambda: dict(gauges), interval=0.01,
                         seed=5, name="control-tick-t")
        ctl.start()
        wait_until(lambda: ctl.stats()["ticks"] >= 2,
                   msg="controller ticking")
        ctl.stop()
        assert not ctl.running()
        assert not any(t.name == "control-tick-t"
                       for t in threading.enumerate())

    def test_duplicate_knob_rejected(self):
        ctl = Controller(lambda: {}, interval=0.05)
        _s, act = _actuator(8)
        ctl.add_knob(act, law=AIMD(), driver=lambda v: 0)
        _s2, act2 = _actuator(9)
        with pytest.raises(ValueError):
            ctl.add_knob(act2, law=AIMD(), driver=lambda v: 0)


# ---------------------------------------------------------------------------
# 3. wiring: drivers, server assembly, invariants out of reach
# ---------------------------------------------------------------------------

def _view(cur, prev=None, dt=1.0):
    return TickView(cur, prev if prev is not None else
                    {k: 0 for k in cur}, dt, None)


class TestDrivers:
    def test_max_window_driver(self):
        from nomad_tpu.control.wiring import _max_window_driver as drv

        base = {"nomad.applier.commits": 0,
                "nomad.applier.plans_committed": 0}
        # Occupancy tracking the cap -> the cap binds -> grow.
        assert drv(_view({"nomad.applier.commits": 10,
                          "nomad.applier.plans_committed": 150,
                          "nomad.applier.max_window": 16}, base)) == 1
        # Thin windows far under a fat cap -> drift back.
        assert drv(_view({"nomad.applier.commits": 10,
                          "nomad.applier.plans_committed": 100,
                          "nomad.applier.max_window": 256}, base)) == -1
        # Verify latency blowing up -> shrink regardless.
        assert drv(_view({"nomad.applier.commits": 10,
                          "nomad.applier.plans_committed": 150,
                          "nomad.applier.max_window": 16,
                          "nomad.plan.evaluate_window.p99": 0.5},
                         base)) == -1
        # No commits this tick -> no signal.
        assert drv(_view({"nomad.applier.commits": 0,
                          "nomad.applier.plans_committed": 0,
                          "nomad.applier.max_window": 16}, base)) == 0

    def test_gather_driver_cost_vs_benefit(self):
        from nomad_tpu.control.wiring import _gather_driver as drv

        base = {"nomad.applier.commits": 0,
                "nomad.applier.plans_committed": 0,
                "nomad.applier.gather_wall_s": 0.0}
        # Burning gather wall while windows stay thin -> shrink.
        assert drv(_view({"nomad.applier.commits": 2,
                          "nomad.applier.plans_committed": 40,
                          "nomad.applier.max_window": 256,
                          "nomad.applier.gather_wall_s": 0.8},
                         base)) == -1
        # Many small commits per second -> amortize: grow.
        assert drv(_view({"nomad.applier.commits": 40,
                          "nomad.applier.plans_committed": 120,
                          "nomad.applier.max_window": 64,
                          "nomad.applier.gather_wall_s": 0.01},
                         base)) == 1
        # Full windows: hold (max_window's business, not gather's).
        assert drv(_view({"nomad.applier.commits": 40,
                          "nomad.applier.plans_committed": 2500,
                          "nomad.applier.max_window": 64,
                          "nomad.applier.gather_wall_s": 0.8},
                         base)) == 0

    def test_inflight_driver(self):
        from nomad_tpu.control.wiring import _inflight_driver as drv

        base = {"nomad.applier.commit_backpressure_s": 0,
                "nomad.applier.dispatch_failures": 0}
        assert drv(_view({"nomad.applier.commit_backpressure_s": 0.5,
                          "nomad.applier.dispatch_failures": 0},
                         base)) == 1
        assert drv(_view({"nomad.applier.commit_backpressure_s": 0.5,
                          "nomad.applier.dispatch_failures": 1},
                         base)) == -1
        assert drv(_view({"nomad.applier.commit_backpressure_s": 0.0,
                          "nomad.applier.dispatch_failures": 0},
                         base)) == 0

    def test_depth_limit_driver_residence_band(self):
        from nomad_tpu.control.wiring import _depth_limit_driver as drv

        base = {"nomad.broker.acks": 0,
                "nomad.overload.shed.service": 0,
                "nomad.overload.shed.batch": 0,
                "nomad.broker.depth_sheds": 0}
        # Shedding while the queue clears fast -> grow.
        assert drv(_view({"nomad.broker.acks": 100,
                          "nomad.broker.depth": 10,
                          "nomad.overload.shed.service": 5,
                          "nomad.overload.shed.batch": 0,
                          "nomad.broker.depth_sheds": 0}, base)) == 1
        # Queue residence past the band -> shrink.
        assert drv(_view({"nomad.broker.acks": 10,
                          "nomad.broker.depth": 100,
                          "nomad.overload.shed.service": 5,
                          "nomad.overload.shed.batch": 0,
                          "nomad.broker.depth_sheds": 0}, base)) == -1
        # No acks -> no residence estimate -> hold.
        assert drv(_view({"nomad.broker.acks": 0,
                          "nomad.broker.depth": 100}, base)) == 0

    def test_brownout_driver_reads_wheel_pressure(self):
        from nomad_tpu.control.wiring import _brownout_ratio_driver as drv

        base = {"nomad.broker.acks": 0,
                "nomad.overload.shed.batch": 0}
        # A backlog of paced expiries keeps brownout engaged.
        assert drv(_view({"nomad.heartbeat.pending_expiries": 12,
                          "nomad.broker.acks": 100,
                          "nomad.broker.depth": 1}, base)) == -1

    def test_runner_depth_driver_learned_floor(self):
        from nomad_tpu.control.wiring import _make_depth_driver

        drv = _make_depth_driver()
        base = {}
        assert drv(_view({"nomad.runner.rtt_ms_ewma": 2.0},
                         base)) == 1      # floor = 2: healthy
        assert drv(_view({"nomad.runner.rtt_ms_ewma": 5.0},
                         base)) == 0      # 2.5x floor: hold band
        assert drv(_view({"nomad.runner.rtt_ms_ewma": 20.0},
                         base)) == -1     # 10x floor: retreat
        assert drv(_view({"nomad.runner.rtt_ms_ewma": 0.0},
                         base)) == 0      # no samples yet


class TestServerWiring:
    def test_server_controller_knobs_and_registry(self):
        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0,
                                  control_enabled=True,
                                  control_interval=0.02,
                                  control_seed=11))
        try:
            assert srv.controller is not None
            knobs = srv.controller.stats()["knobs"]
            assert set(knobs) == {
                "broker.depth_limit", "overload.overload_ratio",
                "overload.brownout_ratio", "applier.max_window",
                "applier.max_inflight_commits", "applier.gather_s"}
            # Decisions mirror into the unified registry document.
            snap = srv.obs_registry.snapshot()
            assert "nomad.controller.ticks" in snap
            assert "nomad.controller.knobs.broker.depth_limit.value" \
                in snap
            wait_until(lambda:
                       srv.obs_registry.snapshot()
                       ["nomad.controller.ticks"] >= 2,
                       msg="server controller ticking")
        finally:
            srv.shutdown()
        assert not srv.controller.running()

    def test_depth_limit_actuator_moves_broker_and_pressure_source(self):
        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0,
                                  control_enabled=True,
                                  broker_depth_limit=64))
        try:
            act = srv.controller.knob("broker.depth_limit")
            act.apply(128)
            # BOTH the broker's hard bound and the overload pressure
            # source's denominator moved (they must stay one number).
            assert srv.eval_broker.max_depth == 128
            assert srv.config.broker_depth_limit == 128
        finally:
            srv.shutdown()

    def test_set_ratios_preserves_the_invariant(self):
        from nomad_tpu.server.overload import OverloadController

        ctl = OverloadController(brownout_ratio=0.5, overload_ratio=1.0)
        ctl.set_ratios(overload=0.4)
        brown, over = ctl.ratios()
        assert over == 0.4 and brown <= over
        ctl.set_ratios(brownout=0.9)
        brown, over = ctl.ratios()
        assert brown <= over  # clamped, never inverted
        # The hysteresis scaling (enter/exit asymmetry) is untouched.
        assert ctl.hysteresis == 0.9

    def test_liveness_lane_is_out_of_the_controllers_reach(self):
        """Admission correctness invariants: however low the
        controller drives the thresholds, Node.Heartbeat bypasses
        admission entirely and force=True enqueues bypass the depth
        bound — a tuning decision can never shed liveness or diverge
        broker from state."""
        from nomad_tpu.server.eval_broker import EvalBroker
        from nomad_tpu.server.overload import (OVERLOAD, ErrOverloaded,
                                               OverloadController)
        from nomad_tpu.structs import Evaluation, generate_uuid

        ctl = OverloadController(brownout_ratio=0.5, overload_ratio=1.0)
        ctl.set_ratios(brownout=1e-6, overload=1e-6)  # floor of rails
        ctl.add_source("stuck", lambda: (1, 1))       # pressure = 1.0
        assert ctl.state() == OVERLOAD
        ctl.admit_rpc("Node.Heartbeat", {})           # never shed
        with pytest.raises(ErrOverloaded):
            ctl.admit_rpc("Job.Register", {"job": {"type": "service"}})

        broker = EvalBroker(admission=ctl, max_depth=1)
        broker.set_enabled(True)
        try:
            for _ in range(3):  # force: past admission AND the bound
                broker.enqueue(Evaluation(
                    id=generate_uuid(), priority=1, type="service",
                    triggered_by="test", job_id=generate_uuid()),
                    force=True)
            assert broker.stats()["depth"] == 3
        finally:
            broker.shutdown()


# ---------------------------------------------------------------------------
# 4. live commit pipeline: applier knobs move under a real stream
# ---------------------------------------------------------------------------

class TestApplierControl:
    def test_applier_controller_relieves_commit_backpressure(self):
        """A mis-set max_inflight_commits=1 under a live plan stream:
        the applier books backpressure wall, and the AIMD knob grows
        the commit pipeline until the wall subsides."""
        from nomad_tpu.server.eval_broker import EvalBroker
        from nomad_tpu.server.fsm import NomadFSM
        from nomad_tpu.server.plan_apply import PlanApplier
        from nomad_tpu.server.plan_queue import PlanQueue
        from nomad_tpu.server.raft import InmemRaft
        from nomad_tpu.structs import (ALLOC_CLIENT_STATUS_PENDING,
                                       ALLOC_DESIRED_STATUS_RUN,
                                       EVAL_TRIGGER_JOB_REGISTER,
                                       Allocation, Evaluation, Plan,
                                       Resources, codec, generate_uuid)

        broker = EvalBroker(nack_timeout=60.0)
        fsm = NomadFSM(eval_broker=broker)
        raft = InmemRaft(fsm)
        queue = PlanQueue()
        applier = PlanApplier(queue, broker, raft,
                              state_fn=lambda: fsm.state,
                              max_window=8, gather_s=0.002)
        applier.max_inflight_commits = 1
        broker.set_enabled(True)
        queue.set_enabled(True)
        applier.start()
        ctl = applier_controller(applier, queue, broker=broker, seed=3)
        try:
            raft.apply(codec.encode(
                codec.NODE_REGISTER_REQUEST,
                {"node": mock.node(0).to_dict()})).wait()
            node_id = fsm.state.nodes()[0].id
            ctl.tick()  # baseline
            for burst in range(6):
                futures = []
                for _ in range(8):
                    ev = Evaluation(
                        id=generate_uuid(), priority=50,
                        type="service",
                        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                        job_id=generate_uuid())
                    broker.enqueue(ev, force=True)
                    got, token = broker.dequeue(["service"],
                                                timeout=10)
                    plan = Plan(eval_id=got.id, eval_token=token,
                                priority=50)
                    plan.node_allocation[node_id] = [Allocation(
                        id=generate_uuid(), node_id=node_id,
                        job_id=ev.job_id, task_group="web",
                        resources=Resources(cpu=1, memory_mb=1),
                        desired_status=ALLOC_DESIRED_STATUS_RUN,
                        client_status=ALLOC_CLIENT_STATUS_PENDING)]
                    futures.append((got, token, queue.enqueue(plan)))
                for got, token, fut in futures:
                    fut.wait(30)
                    broker.ack(got.id, token)
                ctl.tick()
            knob = ctl.stats()["knobs"]["applier.max_inflight_commits"]
            stats = applier.stats()
            # The stream committed, backpressure was observed, and the
            # knob either grew past the mis-set floor or the pipeline
            # never saturated (a fast host may drain depth-1 without
            # measurable wall) — in which case holding IS converged.
            assert stats["plans_committed"] == 48
            if stats["commit_backpressure_s"] > 0.01:
                assert knob["value"] > 1
        finally:
            ctl.stop()
            queue.set_enabled(False)
            broker.set_enabled(False)
            applier.shutdown(5.0)
            broker.shutdown()


# ---------------------------------------------------------------------------
# 5. the seeded chaos rig: depth retreat and recovery, no oscillation
# ---------------------------------------------------------------------------

def _pipeline_world(n_nodes, n_jobs):
    from nomad_tpu.scheduler.harness import Harness

    h = Harness()
    for i in range(n_nodes):
        h.state.upsert_node(h.next_index(), mock.node(i))
    jobs = []
    for _ in range(n_jobs):
        j = mock.job()
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)
    return h, jobs


def _mk_eval(job):
    from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER, Evaluation,
                                   generate_uuid)

    return Evaluation(id=generate_uuid(), priority=job.priority,
                      type=job.type,
                      triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                      job_id=job.id)


class TestChaosDepthRetreat:
    def test_injected_dispatch_delay_forces_retreat_then_recovery(self):
        """The rig the tentpole names: seeded ``device.dispatch``
        delays inflate the runner's RTT EWMA; the AIMD depth knob
        retreats multiplicatively, then — when the injection stops and
        the EWMA decays back under the probe band — recovers
        additively, WITHOUT oscillating (reversal count bounded by the
        two phase changes; the hold band between 2x and 4x of the
        learned floor is what prevents flapping)."""
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner

        h, jobs = _pipeline_world(8, 40)
        with executor_override("device"):
            runner = PipelinedEvalRunner(
                h.state.snapshot(), h, depth=8,
                state_refresh=lambda: h.state.snapshot())
            # Warm the compile/prep caches so the floor the driver
            # learns is the steady-state RTT, not the first compile.
            runner.process([_mk_eval(j) for j in jobs[:4]])
            with runner._count_lock:
                runner._rtt_ewma = 0.0  # drop warmup samples
            ctl = runner_controller(runner, seed=7, lo=1, hi=8)
            depth_seen = []

            def round_trip(batch, ticks=1):
                runner.process([_mk_eval(j) for j in batch])
                for _ in range(ticks):
                    ctl.tick()
                depth_seen.append(runner.depth)

            # Phase A (healthy): learn the floor.
            round_trip(jobs[4:8])
            round_trip(jobs[8:12])
            assert runner.depth >= 8 or runner.depth >= depth_seen[0]

            # Phase B (chaos): seeded dispatch delays, every dispatch.
            plan = FaultPlan(seed=5).add("device.dispatch", "delay",
                                         secs=0.25, count=6)
            with faultinject.injected(plan):
                round_trip(jobs[12:15])
                round_trip(jobs[15:18])
            assert runner.depth < 8, depth_seen
            retreated_to = runner.depth

            # Phase C (recovery): clean dispatches decay the EWMA back
            # under the probe band; depth climbs additively.
            for lo in range(18, 38, 4):
                round_trip(jobs[lo:lo + 4])
            assert runner.depth > retreated_to, depth_seen

            # No oscillation: one retreat run + one recovery run.
            knob = ctl.stats()["knobs"]["pipeline.depth"]
            assert knob["reversals"] <= 2, (knob, depth_seen)
            assert knob["rail_hits"] <= 2, knob
        # Every eval still placed (the knob never touched correctness).
        assert all(e.status == "complete" for e in h.evals)


# ---------------------------------------------------------------------------
# 6. the operator drill: pin via the controller
# ---------------------------------------------------------------------------

class TestOperatorPin:
    def test_controller_pin_by_name(self):
        gauges = {"g": 1.0}
        ctl = Controller(lambda: dict(gauges), interval=0.05)
        state, act = _actuator(8)
        ctl.add_knob(act, law=AIMD(), driver=lambda v: +1)
        ctl.pin("k", 3)
        assert state["v"] == 3
        ctl.tick()
        ctl.tick()
        assert state["v"] == 3
        ctl.pin("k", None)
        ctl.tick()
        assert state["v"] == 4
