"""Consensus-plane lint (analysis/consensuslint.py) + the defects it
found + the runtime shadow-replica sanitizer.

Three layers, mirroring tests/test_devlint.py:

1. **Rule units** on synthetic packages: every consensus rule
   (apply-wall-clock, apply-rng, apply-env, apply-iter-order,
   apply-float-accum, leader-fence, read-consistency,
   stale-read-bypass) proves it fires, and every sanctioned pattern
   (sorted() set walks, seeded instance RNGs, leadership fences —
   syntactic, hook, call-graph-propagated, and Thread(target=...)
   arming — plus justified ``# consensus-ok`` markers) proves it is
   exempt.
2. **Analyzer-found defect regressions**: the real bugs the passes
   surfaced — hash-order watch-notify fan-out in
   ``StateStore.delete_eval`` / ``upsert_allocs_batched`` and the
   unfenced heartbeat arming in ``Server.node_heartbeat`` — each
   pinned by a test that fails on the pre-fix shape.
3. **ReplicaDivergenceSanitizer**: an injected nondeterministic apply
   diverges the shadow twin and raises in the offending apply; clean
   replays stay byte-identical; out-of-band store writes drop the pair
   (counted) instead of reporting a false divergence.
"""
from __future__ import annotations

import textwrap
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.analysis import consensuslint
from nomad_tpu.structs import codec


def write_files(tmp_path, files: dict) -> str:
    d = tmp_path / "pkg"
    d.mkdir(exist_ok=True)
    for name, source in files.items():
        (d / name).write_text(textwrap.dedent(source))
    return str(d)


def rules_of(findings) -> dict:
    out: dict = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# ---------------------------------------------------------------------------
# 1. rule units
# ---------------------------------------------------------------------------

class TestApplyDeterminism:
    def test_wall_clock_in_apply_fires_and_marker_waives(self, tmp_path):
        pkg = write_files(tmp_path, {
            "fsm.py": """
                import time

                class TinyFSM:
                    def apply(self, index, entry):
                        self.when = time.time()
                        return entry

                class WaivedFSM:
                    def apply(self, index, entry):
                        # consensus-ok(apply-wall-clock): audited — local
                        # observability only, outside the fingerprint.
                        self.when = time.time()
                        return entry
                """,
        })
        cov: dict = {}
        by = rules_of(consensuslint.analyze_package(pkg, coverage_out=cov))
        hits = by.get("apply-wall-clock", [])
        assert len(hits) == 1
        assert "TinyFSM.apply" in hits[0].where
        assert cov["waived"] == 1
        assert cov["apply_roots"] >= 2

    def test_rng_and_env_reads_fire_seeded_rng_exempt(self, tmp_path):
        pkg = write_files(tmp_path, {
            "store.py": """
                import os
                import random
                import uuid

                class TinyStore:
                    def __init__(self):
                        self._rng = random.Random(7)

                    def upsert_thing(self, index, thing):
                        thing["id"] = str(uuid.uuid4())
                        thing["salt"] = os.urandom(4)
                        thing["jitter"] = random.random()
                        thing["ok_jitter"] = self._rng.random()

                    def update_host(self, index):
                        import socket
                        return (os.environ.get("HOST"),
                                socket.gethostname())
                """,
        })
        by = rules_of(consensuslint.analyze_package(pkg))
        rng = by.get("apply-rng", [])
        assert len(rng) == 3, [f.message for f in rng]
        assert not any("_rng" in f.message for f in rng)
        env = by.get("apply-env", [])
        assert len(env) == 2, [f.message for f in env]

    def test_set_order_escape_fires_sorted_walk_exempt(self, tmp_path):
        pkg = write_files(tmp_path, {
            "store.py": """
                class TinyStore:
                    def upsert_many(self, index, ids):
                        touched = set(ids)
                        keys = [("k", n) for n in touched]
                        good = [("k", n) for n in sorted(touched)]
                        total = sum(touched)
                        acc = 0.0
                        for n in {x * 1.5 for x in ids}:
                            acc += n
                        return keys, good, total, acc
                """,
        })
        by = rules_of(consensuslint.analyze_package(pkg))
        assert len(by.get("apply-iter-order", [])) == 1
        assert len(by.get("apply-float-accum", [])) == 2

    def test_taint_follows_calls_and_skips_obs_sinks(self, tmp_path):
        pkg = write_files(tmp_path, {
            "__init__.py": "",
            "fsm.py": """
                from pkg.helper import stamp
                from pkg.obs.trace import record

                class TinyFSM:
                    def apply(self, index, entry):
                        record(index)
                        return stamp(entry)
                """,
            "helper.py": """
                import time

                def stamp(entry):
                    return (entry, time.time())
                """,
        })
        (tmp_path / "pkg" / "obs").mkdir()
        (tmp_path / "pkg" / "obs" / "__init__.py").write_text("")
        (tmp_path / "pkg" / "obs" / "trace.py").write_text(textwrap.dedent(
            """
            import time

            def record(index):
                return time.perf_counter()
            """))
        cov: dict = {}
        by = rules_of(consensuslint.analyze_package(pkg, coverage_out=cov))
        hits = by.get("apply-wall-clock", [])
        # helper.stamp is tainted through the call chain; the obs sink
        # is excluded (its perf_counter is fine) and counted.
        assert len(hits) == 1
        assert "stamp" in hits[0].where
        assert "TinyFSM.apply -> stamp" in hits[0].message
        assert cov["sinks_excluded"] == 1


class TestLeadershipFencing:
    def test_unfenced_force_enqueue_fires_fenced_paths_exempt(
            self, tmp_path):
        pkg = write_files(tmp_path, {
            "srv.py": """
                class Srv:
                    def is_leader(self):
                        return self._leader

                    def establish_leadership(self):
                        self._leader = True
                        self._restore()

                    def _restore(self):
                        self.broker.enqueue(1, force=True)

                    def fenced_inline(self):
                        if self.is_leader():
                            self.broker.enqueue(2, force=True)

                    def unfenced(self):
                        self.broker.enqueue(3, force=True)
                        self.heartbeats.reset_heartbeat_timer("n1")
                """,
        })
        cov: dict = {}
        by = rules_of(consensuslint.analyze_package(pkg, coverage_out=cov))
        hits = by.get("leader-fence", [])
        # Only the two sites in `unfenced`: _restore is fenced through
        # its sole caller (a leadership hook), fenced_inline checks.
        assert len(hits) == 2, [f.where for f in hits]
        assert all("Srv.unfenced" in f.where for f in hits)
        assert cov["fence_targets"] == 4

    def test_thread_target_arming_propagates_the_fence(self, tmp_path):
        pkg = write_files(tmp_path, {
            "applier.py": """
                import threading

                class PlanApplier:
                    def establish_leadership(self):
                        self.start()

                    def start(self):
                        t = threading.Thread(target=self._run)
                        t.start()

                    def _run(self):
                        self.queue.set_enabled(True)
                """,
        })
        by = rules_of(consensuslint.analyze_package(pkg))
        # _run's only entry is the Thread armed inside start, whose only
        # caller is the leadership hook: fenced end-to-end.
        assert by.get("leader-fence", []) == []

    def test_orphan_thread_body_with_leader_machinery_fires(
            self, tmp_path):
        pkg = write_files(tmp_path, {
            "applier.py": """
                class LoosePlanApplier:
                    def _run(self):
                        self.queue.set_enabled(True)
                """,
        })
        by = rules_of(consensuslint.analyze_package(pkg))
        hits = by.get("leader-fence", [])
        assert len(hits) == 1 and "LoosePlanApplier._run" in hits[0].where


ENDPOINT_PKG = {
    "endpoints.py": """
        CONSISTENT_READS = frozenset({"Node.GetNode"})

        class Endpoints:
            def __init__(self, server):
                self.server = server

            def install(self, rpc_server):
                for service, methods in {
                    "Node": ["GetNode", "List", "Register"],
                    "Status": ["Ping"],
                }.items():
                    for m in methods:
                        rpc_server.register(service, m)

            def _forward(self, method, args):
                if self.server.is_leader():
                    return None
                return {}

            def _blocking(self, args, table, run):
                return run()

            def _state(self):
                return self.server.state

            def node_get_node(self, args):
                def run():
                    return {"node": self._state().get(args["id"])}
                return self._blocking(args, "nodes", run)

            def node_list(self, args):
                def run():
                    return {"nodes": list(self._state())}
                return self._blocking(args, "nodes", run)

            def node_register(self, args):
                return {"seen": self._state().get(args["id"])}

            def status_ping(self, args):
                return {}
        """,
}


class TestReadConsistencyContract:
    def test_classification_and_both_rules(self, tmp_path):
        pkg = write_files(tmp_path, dict(ENDPOINT_PKG))
        cov: dict = {}
        by = rules_of(consensuslint.analyze_package(pkg, coverage_out=cov))
        assert cov["endpoint_contract"] == {
            "Node.GetNode": "stale-safe",
            "Node.List": "local-read",
            "Node.Register": "unfenced-read",
            "Status.Ping": "server-local",
        }
        bypass = by.get("stale-read-bypass", [])
        assert len(bypass) == 1 and bypass[0].where == "Node.List"
        unfenced = by.get("read-consistency", [])
        assert len(unfenced) == 1 and unfenced[0].where == "Node.Register"

    def test_forward_fence_makes_the_read_leader_only(self, tmp_path):
        src = dict(ENDPOINT_PKG)
        src["endpoints.py"] = src["endpoints.py"].replace(
            'return {"seen": self._state().get(args["id"])}',
            'fwd = self._forward("Node.Register", args)\n'
            '                if fwd is not None:\n'
            '                    return fwd\n'
            '                return {"seen": self._state().get(args["id"])}')
        pkg = write_files(tmp_path, src)
        cov: dict = {}
        by = rules_of(consensuslint.analyze_package(pkg, coverage_out=cov))
        assert cov["endpoint_contract"]["Node.Register"] == "leader-only"
        assert by.get("read-consistency", []) == []


# ---------------------------------------------------------------------------
# 2. analyzer-found defect regressions
# ---------------------------------------------------------------------------

class TestAnalyzerFoundDefects:
    def _recording_store(self):
        from nomad_tpu.state.store import StateStore

        store = StateStore()
        recorded: list = []
        real = store.watch.notify

        def record(*keys, index=0):
            recorded.append(list(keys))
            return real(*keys, index=index)

        store.watch.notify = record
        return store, recorded

    def test_batched_upsert_notify_fanout_is_hash_order_free(self):
        """consensuslint apply-iter-order @ store.py upsert_allocs_batched:
        the alloc-node notify keys walked a raw set — hash-seeded order
        escaping to watch subscribers.  Now sorted."""
        store, recorded = self._recording_store()
        allocs = []
        for i in range(8):
            a = mock.alloc()
            a.node_id = f"node-{i:02d}"
            allocs.append(a)
        store.upsert_allocs_batched([(5, allocs)])
        node_keys = [k for k in recorded[-1] if k[0] == "alloc-node"]
        assert len(node_keys) == 8
        assert node_keys == sorted(node_keys)

    def test_delete_eval_notify_fanout_is_hash_order_free(self):
        """Same defect class in StateStore.delete_eval's reap fan-out."""
        store, recorded = self._recording_store()
        allocs = []
        for i in range(8):
            a = mock.alloc()
            a.node_id = f"node-{i:02d}"
            allocs.append(a)
        store.upsert_allocs(5, allocs)
        store.delete_eval(6, [], [a.id for a in allocs])
        node_keys = [k for k in recorded[-1] if k[0] == "alloc-node"]
        assert len(node_keys) == 8
        assert node_keys == sorted(node_keys)

    def test_node_heartbeat_does_not_arm_off_leader(self):
        """consensuslint leader-fence @ server.py node_heartbeat: TTL
        timers are leader state, but a second-hop forwarded heartbeat
        (or an UpdateStatus served on a demoted server) armed one
        anyway — a timer the real leader never fires or clears.  Now
        the no-TTL answer off-leader, like node_register."""
        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0))
        try:
            srv.establish_leadership()
            node = mock.node(1)
            srv.node_register(node)
            assert srv.node_heartbeat(node.id) > 0
            assert srv.heartbeats.active() == 1
            srv.revoke_leadership()
            assert srv.heartbeats.active() == 0
            assert srv.node_heartbeat(node.id) == 0.0
            assert srv.heartbeats.active() == 0, \
                "demoted server must not arm heartbeat timers"
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# 3. the shadow-replica divergence sanitizer
# ---------------------------------------------------------------------------

@pytest.fixture
def divergence():
    """The session-installed sanitizer when active (conftest), else a
    locally installed one; either way, divergences this test injects
    are scrubbed afterwards so the session-teardown check stays clean."""
    import conftest as cft
    from nomad_tpu.analysis.sanitizers import ReplicaDivergenceSanitizer

    san = cft.DIVERGENCE
    if san is not None:
        before = len(san.mismatches)
        yield san
        del san.mismatches[before:]
    else:
        san = ReplicaDivergenceSanitizer().install()
        try:
            yield san
        finally:
            san.uninstall()


def _node_entry(i: int) -> bytes:
    node = mock.node(i)
    return codec.encode(codec.NODE_REGISTER_REQUEST,
                        {"node": node.to_dict()})


class TestReplicaDivergenceSanitizer:
    def test_catches_injected_nondeterministic_apply(self, divergence):
        from nomad_tpu.server.fsm import NomadFSM

        fsm = NomadFSM()
        assert fsm._divergence_twin is not None
        clean = fsm._handlers[codec.NODE_REGISTER_REQUEST]

        def tainted(index, payload):
            # The injected bug: a wall-clock value smuggled into
            # replicated state (exactly what consensuslint's
            # apply-wall-clock rule bans statically).
            payload["node"]["name"] = f"joined-{time.time_ns()}"
            return clean(index, payload)

        fsm._handlers[codec.NODE_REGISTER_REQUEST] = tainted
        with pytest.raises(AssertionError, match="replica divergence"):
            fsm.apply(1, _node_entry(1))
        assert fsm._divergence_twin is None   # pair dropped, once
        assert divergence.mismatches

    def test_clean_replay_stays_byte_identical(self, divergence):
        from nomad_tpu.server.fsm import NomadFSM

        fsm = NomadFSM()
        for i in range(1, 7):
            fsm.apply(i, _node_entry(i))
        assert fsm._divergence_twin is not None
        assert fsm.state.fingerprint() == \
            fsm._divergence_twin.state.fingerprint()

    def test_out_of_band_writes_drop_the_pair_not_a_report(
            self, divergence):
        from nomad_tpu.server.fsm import NomadFSM

        desynced_before = divergence.desynced
        mismatches_before = len(divergence.mismatches)
        fsm = NomadFSM()
        # Test-style direct seeding: a store write that never rode the
        # raft log.  The twin can't see it — that's not divergence.
        fsm.state.upsert_job(1, mock.job())
        fsm.apply(2, _node_entry(2))
        assert fsm._divergence_twin is None
        assert divergence.desynced == desynced_before + 1
        assert len(divergence.mismatches) == mismatches_before

    def test_twin_skips_broker_and_span_recording(self, divergence):
        from nomad_tpu.server.fsm import NomadFSM

        fsm = NomadFSM()
        twin = fsm._divergence_twin
        assert twin.eval_broker is None
        assert twin._record_apply_spans("t", ["env"], [], 0, 0, 0, 0) \
            is None
