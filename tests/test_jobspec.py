"""Jobspec parser scenario suite (reference jobspec/parse_test.go +
test-fixtures/*.hcl).  Fixtures are authored inline with our own
workload shapes; the SCENARIOS mirror the reference case table: a
full-featured job, defaults, id-vs-name labels, constraint sugar
(version/regexp/distinct_hosts), bare job-level tasks wrapping into
groups, and the error cases (multi-network, multi-resource, multi-
update, bad dynamic-port labels, case-insensitive label collisions)."""
from __future__ import annotations

import pytest

from nomad_tpu.jobspec.parse import ParseError, parse

FULL = """
job "render-farm" {
    region = "emea"
    type = "service"
    priority = 70
    all_at_once = true
    datacenters = ["fr1", "de2"]

    meta {
        team = "render"
    }

    constraint {
        attribute = "kernel.os"
        value = "linux"
    }

    update {
        stagger = "45s"
        max_parallel = 3
    }

    task "janitor" {
        driver = "exec"
        config {
            command = "/usr/bin/cleanup"
        }
        meta {
            cadence = "hourly"
        }
    }

    group "tiles" {
        count = 4
        constraint {
            attribute = "kernel.arch"
            value = "amd64"
        }
        meta {
            tier = "gold"
            retries = 2
        }
        task "tiler" {
            driver = "docker"
            config {
                image = "example/tiler"
            }
            env {
                MODE = "fast"
                DEPTH = 8
            }
            resources {
                cpu = 750
                memory = 256
                network {
                    mbits = 25
                    reserved_ports = [8080, 8081]
                    dynamic_ports = ["metrics", "api"]
                }
            }
        }
        task "uploader" {
            driver = "exec"
            config {
                command = "/usr/bin/upload"
            }
            resources {
                cpu = 200
                memory = 64
            }
            constraint {
                attribute = "driver.exec"
                value = "1"
            }
        }
    }
}
"""


def test_full_featured_job():
    job = parse(FULL)
    assert job.id == job.name == "render-farm"
    assert job.region == "emea"
    assert job.type == "service"
    assert job.priority == 70
    assert job.all_at_once is True
    assert job.datacenters == ["fr1", "de2"]
    assert job.meta == {"team": "render"}
    (c,) = job.constraints
    assert (c.l_target, c.r_target, c.operand, c.hard) == \
        ("kernel.os", "linux", "=", True)
    assert job.update.stagger == 45.0
    assert job.update.max_parallel == 3

    # Group order: declared groups first, then bare-task wrappers?
    # The reference appends bare tasks as single-task groups after
    # groups are collected in declaration order (parse.go:128-141);
    # we preserve file semantics: look them up by name.
    by_name = {tg.name: tg for tg in job.task_groups}
    assert set(by_name) == {"janitor", "tiles"}

    jan = by_name["janitor"]
    assert jan.count == 1 and len(jan.tasks) == 1
    assert jan.tasks[0].driver == "exec"
    assert jan.tasks[0].meta == {"cadence": "hourly"}
    assert jan.tasks[0].config["command"] == "/usr/bin/cleanup"

    tiles = by_name["tiles"]
    assert tiles.count == 4
    assert tiles.meta == {"tier": "gold", "retries": "2"}  # stringified
    (gc,) = tiles.constraints
    assert (gc.l_target, gc.r_target) == ("kernel.arch", "amd64")
    tiler, uploader = tiles.tasks
    assert tiler.name == "tiler" and tiler.driver == "docker"
    assert tiler.env == {"MODE": "fast", "DEPTH": "8"}
    res = tiler.resources
    assert (res.cpu, res.memory_mb) == (750, 256)
    (net,) = res.networks
    assert net.mbits == 25
    assert net.reserved_ports == [8080, 8081]
    assert net.dynamic_ports == ["metrics", "api"]
    assert uploader.constraints[0].l_target == "driver.exec"


def test_default_job_fields():
    job = parse('job "tiny" { datacenters = ["dc1"] '
                'task "t" { driver = "exec" } }')
    assert job.id == job.name == "tiny"
    assert job.region == "global"          # parse.go defaults
    assert job.type == "service"
    assert job.priority == 50
    assert job.all_at_once is False
    assert job.update.stagger == 0 and job.update.max_parallel == 0
    # Bare task wraps into a single-task group named after it.
    (tg,) = job.task_groups
    assert tg.name == "t" and tg.count == 1


def test_job_label_is_id_name_may_differ():
    job = parse('job "job7" { name = "Pretty Name" '
                'datacenters = ["dc1"] '
                'task "t" { driver = "exec" } }')
    assert job.id == "job7"
    # The reference keeps ID from the label; name from the field when
    # present (specify-job.hcl).
    assert job.name == "Pretty Name"


def test_version_constraint_sugar():
    job = parse('job "v" { datacenters = ["dc1"] '
                'constraint { attribute = '
                '"$attr.kernel.version" version = "~> 3.2" } '
                'task "t" { driver = "exec" } }')
    (c,) = job.constraints
    assert c.operand == "version"
    assert c.r_target == "~> 3.2"


def test_regexp_constraint_sugar():
    job = parse('job "r" { datacenters = ["dc1"] '
                'constraint { attribute = '
                '"$attr.kernel.version" regexp = "[0-9.]+" } '
                'task "t" { driver = "exec" } }')
    (c,) = job.constraints
    assert c.operand == "regexp"
    assert c.r_target == "[0-9.]+"


def test_distinct_hosts_sugar():
    job = parse('job "d" { datacenters = ["dc1"] '
                'group "g" { constraint { distinct_hosts '
                '= true } task "t" { driver = "exec" } } }')
    (c,) = job.task_groups[0].constraints
    assert c.operand == "distinct_hosts"


def test_multi_network_rejected():
    bad = ('job "m" { task "t" { driver = "exec" resources { '
           'network { mbits = 10 } network { mbits = 20 } } } }')
    with pytest.raises(ParseError, match="one 'network'"):
        parse(bad)


def test_multi_resource_rejected():
    bad = ('job "m" { task "t" { driver = "exec" '
           'resources { cpu = 100 } resources { cpu = 200 } } }')
    with pytest.raises(ParseError, match="one 'resource'"):
        parse(bad)


def test_multi_update_rejected():
    bad = ('job "m" { update { stagger = "5s" } update { stagger = '
           '"6s" } task "t" { driver = "exec" } }')
    with pytest.raises(ParseError, match="one 'update'"):
        parse(bad)


def test_bad_dynamic_port_label_rejected():
    bad = ('job "m" { task "t" { driver = "exec" resources { '
           'network { dynamic_ports = ["ok_port", "bad#label!"] } '
           '} } }')
    with pytest.raises(ParseError, match="dynamic port label"):
        parse(bad)


def test_port_label_collision_case_insensitive():
    bad = ('job "m" { task "t" { driver = "exec" resources { '
           'network { dynamic_ports = ["Http", "http"] } } } }')
    with pytest.raises(ParseError,
                       match="port label collision"):
        parse(bad)


def test_no_job_block_rejected():
    with pytest.raises(ParseError, match="job"):
        parse('group "g" { }')


def test_two_job_blocks_rejected():
    with pytest.raises(ParseError, match="one 'job'"):
        parse('job "a" { task "t" { driver = "exec" } } '
              'job "b" { task "t" { driver = "exec" } }')


def test_bad_field_type_is_parse_error():
    with pytest.raises(ParseError):
        parse('job "x" { priority = "high" '
              'task "t" { driver = "exec" } }')


def test_stagger_duration_forms():
    for text, want in (('"90s"', 90.0), ('"2m"', 120.0),
                       ('"500ms"', 0.5)):
        job = parse(f'job "s" {{ datacenters = ["dc1"] '
                    f'update {{ stagger = {text} }} '
                    'task "t" { driver = "exec" } }')
        assert job.update.stagger == want, text
