"""Gossip membership tests: discovery, failure detection, raft reconcile."""
from __future__ import annotations

import time

import pytest

from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.gossip import ALIVE, DEAD, Gossip

from tests.conftest import wait_until


FAST_GOSSIP = dict(probe_interval=0.05, probe_timeout=0.05,
                   suspect_timeout=0.3)


def test_join_merges_membership():
    g1 = Gossip({"name": "a"}, **FAST_GOSSIP)
    g2 = Gossip({"name": "b"}, **FAST_GOSSIP)
    g3 = Gossip({"name": "c"}, **FAST_GOSSIP)
    try:
        g2.join(g1.addr)
        g3.join(g1.addr)  # learns about g2 transitively
        wait_until(lambda: len(g1.alive_addrs()) == 3, msg="g1 sees 3")
        wait_until(lambda: len(g3.alive_addrs()) == 3, msg="g3 sees 3")
    finally:
        for g in (g1, g2, g3):
            g.shutdown()


def test_failure_detection():
    g1 = Gossip({"name": "a"}, **FAST_GOSSIP)
    g2 = Gossip({"name": "b"}, **FAST_GOSSIP)
    failed = []
    g1.on_fail = lambda m: failed.append(m.addr)
    try:
        g2.join(g1.addr)
        wait_until(lambda: len(g1.alive_addrs()) == 2, msg="join")
        g2._stop.set()
        g2.sock.close()
        wait_until(lambda: g2.addr in failed, msg="failure detection")
        members = {tuple(m["addr"]): m["status"]
                   for m in g1.members(status=None)}
        assert members[g2.addr] == DEAD
    finally:
        g1.shutdown()


def test_join_events_fire():
    joined = []
    g1 = Gossip({"name": "a"}, on_join=lambda m: joined.append(
        m.tags.get("name")), **FAST_GOSSIP)
    g2 = Gossip({"name": "b"}, **FAST_GOSSIP)
    try:
        g2.join(g1.addr)
        wait_until(lambda: "b" in joined, msg="join event")
    finally:
        g1.shutdown()
        g2.shutdown()


def test_gossip_reconciles_raft_peers():
    """Servers discover each other via gossip and converge on one raft
    cluster with a single leader."""
    cfg = dict(raft_mode="net", raft_election_timeout=(0.05, 0.10),
               raft_heartbeat_interval=0.02, num_schedulers=1,
               enable_gossip=True)
    servers = [Server(ServerConfig(**cfg)) for _ in range(3)]
    try:
        for s in servers[1:]:
            s.gossip.join(servers[0].gossip.addr)
        # Every server learns every peer via gossip -> raft peers.
        wait_until(lambda: all(len(s.raft.peer_addresses()) == 3
                               for s in servers),
                   msg="raft peers from gossip")
        wait_until(lambda: sum(1 for s in servers
                               if s.raft.is_leader()) == 1,
                   msg="single leader")
        import nomad_tpu.mock as mock

        leader = next(s for s in servers if s.raft.is_leader())
        node = mock.node()
        leader.node_register(node)
        wait_until(lambda: all(
            s.fsm.state.node_by_id(node.id) is not None
            for s in servers), msg="replication")
    finally:
        for s in servers:
            s.shutdown()


def test_bootstrap_expect_defers_elections_until_quorum():
    """bootstrap_expect > 1: no server may elect itself before gossip
    shows the expected count (the reference's maybeBootstrap) — a lone
    booting server must never commit entries to a one-node cluster that
    a later join would discard."""
    cfg = dict(raft_mode="net", raft_election_timeout=(0.05, 0.10),
               raft_heartbeat_interval=0.02, num_schedulers=1,
               enable_gossip=True, bootstrap_expect=3)
    servers = [Server(ServerConfig(**cfg)) for _ in range(2)]
    try:
        # Two of three: still passive, nobody becomes leader.
        servers[1].gossip.join(servers[0].gossip.addr)
        time.sleep(0.8)  # sleep-ok: prove NOBODY elects below quorum
        assert not any(s.raft.is_leader() for s in servers)
        assert not any(s.raft.elections_enabled() for s in servers)

        # Third server arrives: quorum visible, elections arm, one wins.
        servers.append(Server(ServerConfig(**cfg)))
        servers[2].gossip.join(servers[0].gossip.addr)
        wait_until(lambda: sum(1 for s in servers
                               if s.raft.is_leader()) == 1,
                   msg="single leader after bootstrap quorum")

        # The cluster is fully functional: writes replicate everywhere.
        import nomad_tpu.mock as mock

        leader = next(s for s in servers if s.raft.is_leader())
        node = mock.node()
        leader.node_register(node)
        wait_until(lambda: all(
            s.fsm.state.node_by_id(node.id) is not None
            for s in servers), msg="replication after bootstrap")
    finally:
        for s in servers:
            s.shutdown()


def test_agent_bootstrap_expect_cluster(tmp_path):
    """Three server agents with bootstrap_expect=3 + retry_join form one
    raft cluster through the agent layer (reference `nomad agent -server
    -bootstrap-expect 3 -retry-join ...`)."""
    from nomad_tpu.agent import Agent, AgentConfig

    agents = []
    try:
        first = Agent(AgentConfig(
            server_enabled=True, dev_mode=False, bootstrap_expect=3,
            http_port=0, rpc_port=0, serf_port=0,
            num_schedulers=1))
        agents.append(first)
        seed = first.server.gossip.addr
        for _ in range(2):
            agents.append(Agent(AgentConfig(
                server_enabled=True, dev_mode=False, bootstrap_expect=3,
                http_port=0, rpc_port=0, serf_port=0,
                num_schedulers=1, retry_join=[seed])))
        wait_until(lambda: all(
            len(a.server.raft.peer_addresses()) == 3 for a in agents),
            timeout=20, msg="full gossip->raft membership")
        wait_until(lambda: sum(
            1 for a in agents if a.server.raft.is_leader()) == 1,
            timeout=20, msg="agent cluster leader")
    finally:
        for a in agents:
            a.shutdown()


def test_bootstrap_deferral_skipped_after_restart(tmp_path):
    """A restarted server with persisted raft state must NOT defer
    elections: survivors of a bootstrapped cluster may hold raft quorum
    without gossip ever showing bootstrap_expect members again
    (code-review regression; reference maybeBootstrap skips when
    LastIndex != 0)."""
    import json as _json
    import os as _os

    def mk(data_dir):
        return ServerConfig(
            raft_mode="net", raft_election_timeout=(0.05, 0.10),
            raft_heartbeat_interval=0.02, num_schedulers=1,
            enable_gossip=True, bootstrap_expect=3,
            data_dir=str(data_dir))

    # Fresh boot: passive until quorum is visible.
    fresh = Server(mk(tmp_path / "fresh"))
    try:
        assert not fresh.raft.elections_enabled()
    finally:
        fresh.shutdown()

    # Prior raft state on disk (a persisted term): elections stay armed.
    veteran_dir = tmp_path / "veteran"
    _os.makedirs(veteran_dir / "raft")
    with open(veteran_dir / "raft" / "meta.json", "w") as fh:
        _json.dump({"term": 3, "voted_for": None}, fh)
    veteran = Server(mk(veteran_dir))
    try:
        assert veteran.raft.elections_enabled()
    finally:
        veteran.shutdown()
