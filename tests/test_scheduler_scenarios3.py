"""Third scheduler scenario suite: the generic_sched_test.go /
system_sched_test.go cases not yet mirrored — destructive JobModify
(all allocs replaced), service NodeDrain, system AddNode / JobModify
(destructive + in-place) / NodeDrain / RetryLimit."""
from __future__ import annotations

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness, RejectPlan
from nomad_tpu.structs import (
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    Evaluation,
    generate_uuid,
)


from tests.test_scheduler import make_eval  # one eval factory


def _flat(plan):
    """(stopped, placed) across the plan's per-node buckets."""
    stopped = [a for ups in plan.node_update.values() for a in ups]
    placed = [a for al in plan.node_allocation.values() for a in al]
    return stopped, placed


def _rig(n_nodes, job):
    h = Harness()
    nodes = [mock.node(i) for i in range(n_nodes)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    h.state.upsert_job(h.next_index(), job)
    return h, nodes


def _seed_allocs(h, job, nodes, count, old_config=None,
                 stale_version=False, per_node=False):
    """Existing allocs: against the CURRENT job version by default (so
    drain/add-node tests isolate their trigger), or an older version
    (``stale_version``/``old_config``) for the update scenarios."""
    if old_config is not None or stale_version:
        alloc_job = job.copy()
        alloc_job.modify_index = 1
        if old_config is not None:
            alloc_job.task_groups[0].tasks[0].config = old_config
    else:
        alloc_job = h.state.job_by_id(job.id)
    allocs = []
    for i in range(count):
        a = mock.alloc()
        a.job = alloc_job
        a.job_id = job.id
        a.node_id = nodes[i % len(nodes)].id
        # System jobs run ONE copy per node: every alloc is name [0]
        # (diff_system_allocs matches required names per node).
        a.name = "my-job.web[0]" if per_node else f"my-job.web[{i}]"
        allocs.append(a)
    h.state.upsert_allocs(h.next_index(), allocs)
    return allocs


# ---------------------------------------------------------------------------
# service (generic_sched_test.go:116-538)
# ---------------------------------------------------------------------------

def test_service_job_modify_destructive_replaces_all():
    """Changed task config with no rolling limit: every alloc is
    stopped and replaced in one pass (generic_sched_test.go:116-213)."""
    job = mock.job()
    job.task_groups[0].count = 6
    job.task_groups[0].tasks[0].config = {"command": "/bin/sleep"}
    h, nodes = _rig(6, job)
    old = _seed_allocs(h, job, nodes, 6,
                       old_config={"command": "/bin/date"})

    h.process("service", make_eval(job))
    plan = h.plans[0]
    stopped, placed = _flat(plan)
    assert len(stopped) == 6 and len(placed) == 6
    assert {a.id for a in stopped} == {a.id for a in old}
    assert all(a.desired_status == ALLOC_DESIRED_STATUS_STOP
               for a in stopped)
    assert all(a.id not in {o.id for o in old} for a in placed)
    assert h.evals[-1].status == EVAL_STATUS_COMPLETE


def test_service_node_drain_migrates():
    """Draining a node migrates its allocs elsewhere
    (generic_sched_test.go:462-538)."""
    job = mock.job()
    job.task_groups[0].count = 4
    h, nodes = _rig(5, job)
    allocs = _seed_allocs(h, job, nodes[:4], 4)
    h.state.update_node_drain(h.next_index(), nodes[0].id, True)

    h.process("service", make_eval(job, EVAL_TRIGGER_NODE_UPDATE))
    plan = h.plans[0]
    stopped, placed = _flat(plan)
    assert [a.id for a in stopped] == [allocs[0].id]
    assert len(placed) == 1
    assert placed[0].node_id != nodes[0].id  # never back onto drained


def test_service_retry_limit_fails_eval():
    """Plans rejected past the retry limit fail the eval
    (generic_sched_test.go:539-583)."""
    job = mock.job()
    h, nodes = _rig(3, job)
    h.planner = RejectPlan(h)

    h.process("service", make_eval(job))
    assert h.evals[-1].status == EVAL_STATUS_FAILED
    assert "attempts" in h.evals[-1].status_description


# ---------------------------------------------------------------------------
# system (system_sched_test.go:65-664)
# ---------------------------------------------------------------------------

def test_system_add_node_places_only_there():
    """A node-update eval after a node joins places the system job on
    the NEW node only (system_sched_test.go:65-151)."""
    job = mock.system_job()
    h, nodes = _rig(3, job)
    _seed_allocs(h, job, nodes, 3, per_node=True)

    newcomer = mock.node(99)
    h.state.upsert_node(h.next_index(), newcomer)
    h.process("system", make_eval(job, EVAL_TRIGGER_NODE_UPDATE))
    plan = h.plans[0]
    _stopped, placed = _flat(plan)
    assert not plan.node_update
    assert [a.node_id for a in placed] == [newcomer.id]


def test_system_job_modify_destructive():
    """Changed config: every node's alloc replaced in place — same
    node, new alloc (system_sched_test.go:182-279)."""
    job = mock.system_job()
    job.task_groups[0].tasks[0].config = {"command": "/bin/sleep"}
    h, nodes = _rig(4, job)
    old = _seed_allocs(h, job, nodes, 4, per_node=True,
                       old_config={"command": "/bin/date"})

    h.process("system", make_eval(job))
    plan = h.plans[0]
    stopped, placed = _flat(plan)
    assert len(stopped) == 4 and len(placed) == 4
    assert {a.node_id for a in placed} == {n.id for n in nodes}
    assert {a.id for a in stopped} == {a.id for a in old}
    assert all(a.id not in {o.id for o in old} for a in placed)


def test_system_job_modify_in_place():
    """Version bump without task changes: in-place update on every
    node, no evictions (system_sched_test.go:381-474)."""
    job = mock.system_job()
    h, nodes = _rig(4, job)
    old = _seed_allocs(h, job, nodes, 4, stale_version=True,
                       per_node=True)

    h.process("system", make_eval(job))
    plan = h.plans[0]
    _stopped, placed = _flat(plan)
    assert not plan.node_update
    assert len(placed) == 4
    assert {a.id for a in placed} == {a.id for a in old}  # same allocs
    current = h.state.job_by_id(job.id)
    assert all(a.job.modify_index == current.modify_index
               for a in placed)


def test_system_node_drain_stops_there():
    """Draining a node stops its system alloc; system jobs never
    migrate it elsewhere (system_sched_test.go:540-606)."""
    job = mock.system_job()
    h, nodes = _rig(3, job)
    allocs = _seed_allocs(h, job, nodes, 3, per_node=True)
    h.state.update_node_drain(h.next_index(), nodes[1].id, True)

    h.process("system", make_eval(job, EVAL_TRIGGER_NODE_UPDATE))
    plan = h.plans[0]
    stopped, placed = _flat(plan)
    assert [a.id for a in stopped] == [allocs[1].id]
    assert not placed  # nothing re-placed on other nodes


def test_system_retry_limit_fails_eval():
    """System scheduler retry cap (system_sched_test.go:607-664)."""
    job = mock.system_job()
    h, nodes = _rig(3, job)
    h.planner = RejectPlan(h)

    h.process("system", make_eval(job))
    assert h.evals[-1].status == EVAL_STATUS_FAILED
