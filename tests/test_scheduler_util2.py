"""Second scheduler-util scenario suite: the reference util_test.go /
context_test.go / worker_test.go cases not covered by test_scheduler.py
— shuffle, set_status eval chaining, the three inplace_update verdicts,
the evict_and_place limit boundary cases, task_group_constraints
aggregation, EvalContext.proposed_allocs, and the worker's
missing-node plan refresh (worker_test.go:317-383)."""
from __future__ import annotations

import random

from nomad_tpu import mock
from nomad_tpu.scheduler import EvalContext, GenericStack, Harness
from nomad_tpu.scheduler.util import (
    DiffResult,
    evict_and_place,
    inplace_update,
    retry_max,
    set_status,
    shuffle_nodes,
    task_group_constraints,
    AllocTuple,
)
from nomad_tpu.structs import (
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_STATUS_COMPLETE,
    Allocation,
    Constraint,
    Evaluation,
    NetworkResource,
    Resources,
    Task,
    generate_uuid,
)


def _harness(n_nodes=4):
    h = Harness()
    nodes = [mock.node(i) for i in range(n_nodes)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    return h, nodes


def _ctx(h):
    from nomad_tpu.structs import Plan
    return EvalContext(h.state.snapshot(), Plan())


# ---------------------------------------------------------------------------
# shuffle / retry / set_status (util_test.go:220-247, 290-312, 400-433)
# ---------------------------------------------------------------------------

def test_shuffle_nodes_permutes_in_place():
    nodes = list(range(50))
    orig = list(nodes)
    shuffle_nodes(nodes, rng=random.Random(1))
    assert sorted(nodes) == orig
    assert nodes != orig  # 50 elements: astronomically unlikely to match


def test_retry_max_counts_attempts():
    calls = []

    def cb():
        calls.append(1)
        return len(calls) >= 3

    retry_max(5, cb)
    assert len(calls) == 3

    import pytest

    from nomad_tpu.scheduler.interfaces import SetStatusError
    with pytest.raises(SetStatusError):
        retry_max(2, lambda: False)


def test_set_status_links_next_eval():
    h, _ = _harness(1)
    job = mock.job()
    ev = Evaluation(id=generate_uuid(), job_id=job.id, status="pending")
    nxt = Evaluation(id=generate_uuid(), job_id=job.id)
    set_status(h, ev, nxt, EVAL_STATUS_COMPLETE, "done")
    updated = [e for e in h.evals if e.id == ev.id]
    assert updated, "planner must receive the status update"
    got = updated[-1]
    assert got.status == EVAL_STATUS_COMPLETE
    assert got.status_description == "done"
    assert got.next_eval == nxt.id
    # The original eval object is untouched (update is a copy).
    assert ev.status == "pending"


# ---------------------------------------------------------------------------
# inplace_update verdicts (util_test.go:435-570)
# ---------------------------------------------------------------------------

def _existing_alloc(job, node, ev_id="e0"):
    tg = job.task_groups[0]
    a = Allocation(
        id=generate_uuid(), eval_id=ev_id, node_id=node.id,
        job=job, job_id=job.id, task_group=tg.name,
        name=f"{job.name}.{tg.name}[0]",
        resources=Resources(cpu=500, memory_mb=256),
        task_resources={"web": Resources(
            cpu=500, memory_mb=256,
            networks=[NetworkResource(device="eth0", ip="1.2.3.4",
                                      reserved_ports=[5000],
                                      mbits=50)])},
        desired_status=ALLOC_DESIRED_STATUS_RUN,
    )
    return a


def _update_rig(h, job, nodes):
    ev = Evaluation(id=generate_uuid(), job_id=job.id, priority=50)
    ctx = _ctx(h)
    stack = GenericStack(False, ctx)
    stack.set_job(job)
    return ev, ctx, stack


def test_inplace_update_success_keeps_node_and_networks():
    h, nodes = _harness(2)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    alloc = _existing_alloc(job, nodes[0])
    h.state.upsert_allocs(h.next_index(), [alloc])

    # Same task group shape, bumped job version: in-place eligible.
    new_job = mock.job()
    new_job.id = job.id
    new_job.name = job.name
    new_job.task_groups = [tg.copy() for tg in job.task_groups]
    ev, ctx, stack = _update_rig(h, new_job, nodes)
    updates = [AllocTuple(alloc.name, new_job.task_groups[0], alloc)]
    remaining = inplace_update(ctx, ev, new_job, stack, updates)
    assert remaining == []
    placed = [a for allocs in ctx.plan().node_allocation.values()
              for a in allocs]
    assert len(placed) == 1
    got = placed[0]
    assert got.id == alloc.id              # same alloc, updated in place
    assert got.node_id == nodes[0].id      # never moves
    # Network assignment is immutable across in-place updates.
    assert got.task_resources["web"].networks[0].reserved_ports == [5000]
    assert got.eval_id == ev.id


def test_inplace_update_changed_task_group_is_destructive():
    h, nodes = _harness(2)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    alloc = _existing_alloc(job, nodes[0])
    h.state.upsert_allocs(h.next_index(), [alloc])

    new_job = mock.job()
    new_job.id = job.id
    new_job.task_groups = [tg.copy() for tg in job.task_groups]
    # Adding a task forbids in-place (util.tasks_updated).
    new_job.task_groups[0].tasks = list(new_job.task_groups[0].tasks) + [
        Task(name="sidecar", driver="exec",
             resources=Resources(cpu=50, memory_mb=32))]
    ev, ctx, stack = _update_rig(h, new_job, nodes)
    updates = [AllocTuple(alloc.name, new_job.task_groups[0], alloc)]
    remaining = inplace_update(ctx, ev, new_job, stack, updates)
    assert remaining == updates            # falls to evict + place
    assert not ctx.plan().node_allocation


def test_inplace_update_no_longer_fits_is_destructive():
    h, nodes = _harness(1)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    alloc = _existing_alloc(job, nodes[0])
    # Another job fills the node so re-selection on it must fail.
    filler = Allocation(
        id=generate_uuid(), node_id=nodes[0].id, job_id="other",
        task_group="f",
        resources=Resources(cpu=3300, memory_mb=7600),
        desired_status=ALLOC_DESIRED_STATUS_RUN)
    h.state.upsert_allocs(h.next_index(), [alloc, filler])

    new_job = mock.job()
    new_job.id = job.id
    new_job.task_groups = [tg.copy() for tg in job.task_groups]
    # Same shape but a bigger ask than the speculative eviction frees.
    new_job.task_groups[0].tasks[0].resources = Resources(
        cpu=900, memory_mb=600,
        networks=new_job.task_groups[0].tasks[0].resources.networks)
    ev, ctx, stack = _update_rig(h, new_job, nodes)
    updates = [AllocTuple(alloc.name, new_job.task_groups[0], alloc)]
    remaining = inplace_update(ctx, ev, new_job, stack, updates)
    assert remaining == updates


# ---------------------------------------------------------------------------
# evict_and_place limit boundaries (util_test.go:352-399, 571-594)
# ---------------------------------------------------------------------------

def _tuples(job, nodes, n):
    tg = job.task_groups[0]
    out = []
    for i in range(n):
        a = _existing_alloc(job, nodes[i % len(nodes)])
        out.append(AllocTuple(f"{job.name}.{tg.name}[{i}]", tg, a))
    return out


def test_evict_and_place_limit_boundaries():
    h, nodes = _harness(4)
    job = mock.job()
    for n_allocs, limit, want_limited, want_left in (
            (4, 2, True, 0),    # less than allocs: budget exhausted
            (4, 4, False, 0),   # equal: all moved, budget zero
            (4, 6, False, 2)):  # greater: all moved, budget remains
        ctx = _ctx(h)
        diff = DiffResult()
        budget = [limit]
        limited = evict_and_place(ctx, diff, _tuples(job, nodes, n_allocs),
                                  "test", budget)
        assert limited is want_limited, (n_allocs, limit)
        moved = min(n_allocs, limit)
        assert len(diff.place) == moved
        stops = sum(len(v) for v in ctx.plan().node_update.values())
        assert stops == moved
        assert budget[0] == want_left


# ---------------------------------------------------------------------------
# task_group_constraints aggregation (util_test.go:595+)
# ---------------------------------------------------------------------------

def test_task_group_constraints_aggregates():
    tg = mock.job().task_groups[0]
    tg.constraints = [Constraint(l_target="a", r_target="1")]
    tg.tasks[0].constraints = [Constraint(l_target="b", r_target="2")]
    tg.tasks.append(Task(name="extra", driver="qemu",
                         resources=Resources(cpu=100, memory_mb=64),
                         constraints=[Constraint(l_target="c",
                                                 r_target="3")]))
    c = task_group_constraints(tg)
    assert {cc.l_target for cc in c.constraints} == {"a", "b", "c"}
    assert c.drivers == {"exec", "qemu"}
    want_cpu = sum(t.resources.cpu for t in tg.tasks)
    assert c.size.cpu == want_cpu


# ---------------------------------------------------------------------------
# EvalContext.proposed_allocs (context_test.go:28-77)
# ---------------------------------------------------------------------------

def test_proposed_allocs_folds_plan_deltas():
    h, nodes = _harness(1)
    job = mock.job()
    existing = _existing_alloc(job, nodes[0])
    stopped = _existing_alloc(job, nodes[0])
    stopped.desired_status = ALLOC_DESIRED_STATUS_STOP  # terminal: invisible
    h.state.upsert_allocs(h.next_index(), [existing, stopped])

    ctx = _ctx(h)
    ids = {a.id for a in ctx.proposed_allocs(nodes[0].id)}
    assert ids == {existing.id}

    # Plan eviction removes it; plan placement adds the new one.
    ctx.plan().append_update(existing, ALLOC_DESIRED_STATUS_STOP, "bye")
    newcomer = _existing_alloc(job, nodes[0])
    ctx.plan().append_alloc(newcomer)
    ids = {a.id for a in ctx.proposed_allocs(nodes[0].id)}
    assert ids == {newcomer.id}


# ---------------------------------------------------------------------------
# lexical-order constraint operands (feasible_test.go:275-314)
# ---------------------------------------------------------------------------

def test_check_lexical_order_operands():
    from nomad_tpu.scheduler.feasible import check_constraint_values

    cases = [
        ("<", "abc", "abd", True),
        ("<", "abd", "abc", False),
        ("<=", "abc", "abc", True),
        (">", "abd", "abc", True),
        (">", "abc", "abd", False),
        (">=", "abc", "abc", True),
    ]
    for op, l, r, want in cases:
        assert check_constraint_values(None, op, l, r) is want, \
            (op, l, r)
    # Non-string operands never satisfy an order constraint.
    assert check_constraint_values(None, "<", 1, "a") is False


# ---------------------------------------------------------------------------
# worker submit-plan missing-node refresh (worker_test.go:317-383)
# ---------------------------------------------------------------------------

def test_plan_on_unknown_node_is_dropped_with_refresh():
    from nomad_tpu.server.plan_apply import evaluate_plan
    from nomad_tpu.state.store import StateStore
    from nomad_tpu.structs import Plan

    state = StateStore()
    known = mock.node(0)
    state.upsert_node(10, known)
    ghost = mock.node(99)  # never registered
    job = mock.job()
    plan = Plan(node_allocation={
        known.id: [_existing_alloc(job, known)],
        ghost.id: [_existing_alloc(job, ghost)],
    })
    result = evaluate_plan(state, plan)
    assert known.id in result.node_allocation
    assert ghost.id not in result.node_allocation
    assert result.refresh_index > 0  # scheduler must refresh its state
