"""Black-box rig: the real agent process driven over HTTP.

The fourth test rig from the reference's strategy (SURVEY §4:
testutil/server.go forks the built binary, waits for /v1/agent/self,
then API tests drive the HTTP surface).  Everything else in tests/ runs
in-process; this spawns ``python -m nomad_tpu.cli agent -dev`` as a real
subprocess and exercises submit -> schedule -> run -> reload -> graceful
shutdown end to end across the process boundary.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

JOB = {"job": {
    "id": "bb", "name": "bb", "type": "service",
    "datacenters": ["dc1"],
    "task_groups": [{
        "name": "tg", "count": 2,
        "tasks": [{"name": "sleep", "driver": "raw_exec",
                   "config": {"command": "/bin/sleep", "args": "300"},
                   "resources": {"cpu": 50, "memory_mb": 16}}]}]}}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http(method: str, url: str, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"null")


@pytest.fixture
def agent_proc(tmp_path):
    cfg = tmp_path / "agent.hcl"
    cfg.write_text('log_level = "WARN"\n')
    proc, base, _rpc = _spawn_agent(tmp_path, "dev", "-dev",
                                    "-config", str(cfg))
    _wait_http(proc, base)
    yield proc, base
    if proc.poll() is None:
        proc.kill()
        proc.wait(10)


def _spawn_agent(tmp_path, tag, *argv):
    http_port = _free_port()
    rpc_port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "nomad_tpu.cli", "agent",
         "-http-port", str(http_port), "-rpc-port", str(rpc_port),
         "-serf-port", "0",  # ephemeral: parallel agents never collide
         "-data-dir", str(tmp_path / f"data-{tag}")] + list(argv),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    return proc, f"http://127.0.0.1:{http_port}", rpc_port


def _wait_http(proc, base, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(f"agent died:\n{proc.stdout.read()}")
        try:
            return _http("GET", base + "/v1/agent/self", timeout=2)
        except Exception:
            time.sleep(0.2)  # sleep-ok: poll interval of the bounded wait
    raise AssertionError("agent never served HTTP")


def _write_client_cfg(tmp_path):
    cfg = tmp_path / "client.hcl"
    cfg.write_text(
        'client {\n'
        '  options {\n'
        '    "driver.raw_exec.enable" = "1"\n'
        '    "fingerprint.skip_accel" = "1"\n'
        '  }\n'
        '}\n')
    return cfg


def wait_for(fn, msg, timeout=45):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.3)  # sleep-ok: poll interval of the bounded wait
    raise AssertionError(f"timeout: {msg}")


def test_blackbox_two_process_cluster(tmp_path):
    """A server-only agent and a client-only agent as separate OS
    processes: registration, heartbeats, long-poll alloc delivery, and
    task execution all cross a real process + network boundary."""
    server = client = None
    try:
        server, server_base, server_rpc = _spawn_agent(
            tmp_path, "srv", "-server")
        _wait_http(server, server_base)
        cli_cfg = _write_client_cfg(tmp_path)
        client, client_base, _ = _spawn_agent(
            tmp_path, "cli", "-client",
            "-servers", f"127.0.0.1:{server_rpc}",
            "-config", str(cli_cfg))
        _wait_http(client, client_base)

        # Client node registers with the server over real RPC.
        wait_for(lambda: any(
            n["status"] == "ready"
            for n in _http("GET", server_base + "/v1/nodes")),
            "client node ready")

        # A raw_exec task needs the option enabled: client agents enable
        # it via config; dev-mode defaults don't apply here, so use a
        # job the exec fallback can run.
        job = dict(JOB)
        resp = _http("PUT", server_base + "/v1/jobs", job)
        wait_for(lambda: _http(
            "GET",
            f"{server_base}/v1/evaluation/{resp['eval_id']}"
        )["status"] == "complete", "eval complete")
        wait_for(lambda: any(
            a["client_status"] == "running"
            for a in _http("GET", server_base + "/v1/job/bb/allocations")),
            "alloc running on remote client")

        # The client's own HTTP agent-self sees its allocs.
        self_doc = _http("GET", client_base + "/v1/agent/self")
        assert self_doc["stats"]["client"]["allocs"] >= 1
    finally:
        for proc in (client, server):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(10)


def test_blackbox_job_lifecycle(agent_proc):
    proc, base = agent_proc
    resp = _http("PUT", base + "/v1/jobs", JOB)
    eval_id = resp["eval_id"]

    wait_for(lambda: _http(
        "GET", f"{base}/v1/evaluation/{eval_id}")["status"] == "complete",
        "eval complete")
    wait_for(lambda: len([
        a for a in _http("GET", base + "/v1/job/bb/allocations")
        if a["client_status"] == "running"]) == 2, "2 allocs running")

    # SIGHUP config reload across the process boundary.
    proc.send_signal(signal.SIGHUP)
    # SIGUSR1 metrics dump (reference go-metrics InmemSignal).
    proc.send_signal(signal.SIGUSR1)
    time.sleep(1.0)  # sleep-ok: prove the agent SURVIVES the signals
    assert proc.poll() is None, "agent must survive SIGHUP/SIGUSR1"
    self_doc = _http("GET", base + "/v1/agent/self")
    assert self_doc["stats"]["nomad"]["leader"] == "true"

    # Stop the job; allocs wind down.
    _http("DELETE", base + "/v1/job/bb")
    wait_for(lambda: all(
        a["desired_status"] == "stop"
        for a in _http("GET", base + "/v1/job/bb/allocations")),
        "job stopped")

    # Graceful shutdown on SIGTERM.
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(20) == 0
    out = proc.stdout.read()
    assert "shutting down" in out
    assert "metrics snapshot" in out


def test_blackbox_agent_kill9_reattach(tmp_path):
    """Checkpoint/resume across a real process boundary: SIGKILL the
    client agent mid-run, restart it on the same state dir, and the
    task PROCESS must survive and be re-attached — not restarted
    (reference client restore, task_runner.go:92-105; SURVEY §5)."""
    server = client = client2 = None
    pid = None
    pid_job = {"job": {
        "id": "pidjob", "name": "pidjob", "type": "service",
        "datacenters": ["dc1"],
        "task_groups": [{
            "name": "tg", "count": 1,
            "tasks": [{"name": "pidtask", "driver": "raw_exec",
                       "config": {
                           "command": "/bin/sh",
                           "args": "-c 'echo $$ > \"$NOMAD_TASK_DIR/pid\";"
                                   " exec sleep 300'"},
                       "resources": {"cpu": 20, "memory_mb": 16}}]}]}}

    def alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False

    try:
        server, server_base, server_rpc = _spawn_agent(
            tmp_path, "srv", "-server")
        _wait_http(server, server_base)
        cli_cfg = _write_client_cfg(tmp_path)
        spawn_client = lambda: _spawn_agent(
            tmp_path, "cli", "-client",
            "-servers", f"127.0.0.1:{server_rpc}",
            "-config", str(cli_cfg))
        client, client_base, _ = spawn_client()
        _wait_http(client, client_base)
        wait_for(lambda: any(
            n["status"] == "ready"
            for n in _http("GET", server_base + "/v1/nodes")),
            "client node ready")

        _http("PUT", server_base + "/v1/jobs", pid_job)
        wait_for(lambda: any(
            a["client_status"] == "running"
            for a in _http("GET",
                           server_base + "/v1/job/pidjob/allocations")),
            "alloc running")

        # The task wrote its own pid into its task dir.
        import glob

        def read_pid():
            nonlocal pid
            for path in glob.glob(str(tmp_path / "data-cli" / "**" /
                                      "pid"), recursive=True):
                content = open(path).read().strip()
                if content:
                    pid = int(content)
                    return True
            return False
        wait_for(read_pid, "task pid file")
        assert alive(pid)

        # Hard-kill the agent: the task (own session) must survive.
        client.kill()
        client.wait(10)
        assert alive(pid), "task died with the agent"

        # Restart on the same state dir: re-attach, don't restart.
        client2, client2_base, _ = spawn_client()
        _wait_http(client2, client2_base)
        wait_for(lambda: _http(
            "GET", client2_base + "/v1/agent/self"
        )["stats"]["client"]["allocs"] >= 1, "restored alloc", timeout=60)
        assert alive(pid), "task was restarted, not re-attached"
        wait_for(lambda: any(
            a["client_status"] == "running"
            for a in _http("GET",
                           server_base + "/v1/job/pidjob/allocations")),
            "alloc still running after restart")

        # Stopping the job through the restarted agent kills the
        # re-attached process — proving the new handle controls it.
        _http("DELETE", server_base + "/v1/job/pidjob")
        wait_for(lambda: not alive(pid), "re-attached task killed")
    finally:
        # The task detaches into its own session (start_new_session), so
        # killing the agents cannot reap it: kill it directly if the
        # test bailed before the job delete.
        if pid is not None and alive(pid):
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        for proc in (client2, client, server):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(10)


def test_blackbox_leader_kill_failover(tmp_path):
    """Full-stack failover: three server agent PROCESSES bootstrap one
    raft cluster through gossip, a client agent runs a job, the leader
    is SIGKILLed, the survivors elect, and a new job still schedules —
    while the first job's task keeps running untouched (reference
    topology: `nomad agent -server -bootstrap-expect 3 -retry-join`)."""
    servers = []
    client = None
    try:
        serf_seed = _free_port()
        srv_cfg = tmp_path / "server.hcl"
        srv_cfg.write_text(
            'log_level = "WARN"\n'
            'server {\n'
            '  bootstrap_expect = 3\n'
            f'  retry_join = ["127.0.0.1:{serf_seed}"]\n'
            '}\n')
        proc0, base0, rpc0 = _spawn_agent(
            tmp_path, "s0", "-server", "-serf-port", str(serf_seed),
            "-config", str(srv_cfg))
        servers.append([proc0, base0, rpc0])
        for i in (1, 2):
            p, b, r = _spawn_agent(tmp_path, f"s{i}", "-server",
                                   "-config", str(srv_cfg))
            servers.append([p, b, r])
        for proc, base, _ in servers:
            _wait_http(proc, base)
        wait_for(lambda: all(
            len(_http("GET", b + "/v1/agent/members")["members"]) == 3
            for _p, b, _r in servers), "3-member gossip", timeout=60)
        wait_for(lambda: _http(
            "GET", servers[0][1] + "/v1/status/leader") != "",
            "first leader", timeout=60)

        cli_cfg = _write_client_cfg(tmp_path)
        all_rpc = ",".join(f"127.0.0.1:{r}" for _p, _b, r in servers)
        client, client_base, _ = _spawn_agent(
            tmp_path, "cli", "-client", "-servers", all_rpc,
            "-config", str(cli_cfg))
        _wait_http(client, client_base)
        wait_for(lambda: any(
            n["status"] == "ready"
            for n in _http("GET", servers[0][1] + "/v1/nodes")),
            "client ready", timeout=60)

        job1 = {"job": dict(JOB["job"], id="pre", name="pre")}
        _http("PUT", servers[0][1] + "/v1/jobs", job1)
        wait_for(lambda: any(
            a["client_status"] == "running"
            for a in _http("GET",
                           servers[0][1] + "/v1/job/pre/allocations")),
            "job pre running", timeout=60)

        # Identify and SIGKILL the leader agent.
        leader_addr = _http("GET",
                            servers[0][1] + "/v1/status/leader")
        leader_i = next(i for i, (_p, _b, r) in enumerate(servers)
                        if leader_addr.endswith(f":{r}"))
        servers[leader_i][0].kill()
        servers[leader_i][0].wait(10)
        survivors = [s for i, s in enumerate(servers) if i != leader_i]

        # Survivors elect a NEW leader; remember who reported it
        # (the other survivor may briefly hold a stale pointer).
        converged = []

        def new_leader():
            for _p, b, _r in survivors:
                try:
                    lead = _http("GET", b + "/v1/status/leader",
                                 timeout=2)
                except Exception:
                    continue
                if lead and not lead.endswith(
                        f":{servers[leader_i][2]}"):
                    converged.append(b)
                    return True
            return False
        wait_for(new_leader, "re-election", timeout=60)
        base = converged[0]

        def http_retry(method, url, body=None, timeout=30):
            deadline = time.monotonic() + timeout
            while True:
                try:
                    return _http(method, url, body)
                except Exception:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.5)  # sleep-ok: poll interval of the bounded retry

        # The cluster still schedules: a new job through the converged
        # survivor (retried across any residual forwarding churn).
        job2 = {"job": dict(JOB["job"], id="post", name="post")}
        http_retry("PUT", base + "/v1/jobs", job2)
        wait_for(lambda: any(
            a["client_status"] == "running"
            for a in http_retry("GET",
                                base + "/v1/job/post/allocations")),
            "job post running after failover", timeout=90)
        # And the pre-failover job never stopped.
        assert any(
            a["client_status"] == "running"
            for a in http_retry("GET", base + "/v1/job/pre/allocations"))
        # Wind the jobs down so the detached sleep tasks don't outlive
        # the test (raw_exec tasks survive agent kills by design).
        for jid in ("pre", "post"):
            http_retry("DELETE", base + f"/v1/job/{jid}")
        wait_for(lambda: all(
            a["desired_status"] == "stop"
            for jid in ("pre", "post")
            for a in http_retry("GET",
                                base + f"/v1/job/{jid}/allocations")),
            "jobs wound down", timeout=60)
        wait_for(lambda: all(
            a["client_status"] != "running"
            for jid in ("pre", "post")
            for a in http_retry("GET",
                                base + f"/v1/job/{jid}/allocations")),
            "tasks stopped", timeout=60)
    finally:
        for group in ([client] if client else []) + \
                [p for p, _b, _r in servers]:
            if group is not None and group.poll() is None:
                group.kill()
                group.wait(10)
