"""Batched optimistic scheduling: many evals fused into one dispatch."""
from __future__ import annotations

import nomad_tpu.mock as mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.batch import BatchEvalRunner
from nomad_tpu.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    JOB_TYPE_SERVICE,
    Evaluation,
    allocs_fit,
    generate_uuid,
)


def make_eval(job):
    return Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )


def test_batch_runner_schedules_many_jobs():
    h = Harness()
    nodes = [mock.node(i) for i in range(16)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)

    jobs = []
    for _ in range(6):
        j = mock.job()
        j.task_groups[0].count = 4
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)

    runner = BatchEvalRunner(h.state.snapshot(), h)
    runner.process([make_eval(j) for j in jobs])

    assert len(h.plans) == 6
    by_node = {n.id: n for n in nodes}
    for plan, job in zip(h.plans, jobs):
        placed = [a for v in plan.node_allocation.values() for a in v]
        assert len(placed) == 4
        assert all(a.job_id == job.id for a in placed)
        # Anti-affinity spreads each job's allocs.
        assert len(plan.node_allocation) == 4
    # Each eval marked complete.
    assert len(h.evals) == 6
    assert all(e.status == "complete" for e in h.evals)


def test_batch_runner_mixed_service_and_batch():
    h = Harness()
    for i in range(8):
        h.state.upsert_node(h.next_index(), mock.node(i))
    j1 = mock.job()
    j1.task_groups[0].count = 3
    j2 = mock.job()
    j2.type = "batch"
    j2.task_groups[0].count = 3
    for j in (j1, j2):
        h.state.upsert_job(h.next_index(), j)

    runner = BatchEvalRunner(h.state.snapshot(), h)
    runner.process([make_eval(j1), make_eval(j2)])
    assert len(h.plans) == 2
    for plan in h.plans:
        assert sum(len(v) for v in plan.node_allocation.values()) == 3


def test_batch_runner_noop_and_invalid_trigger():
    h = Harness()
    for i in range(4):
        h.state.upsert_node(h.next_index(), mock.node(i))
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    good = make_eval(job)
    bad = make_eval(job)
    bad.triggered_by = "bogus-trigger"
    missing_job = make_eval(job)
    missing_job.job_id = "no-such-job"

    runner = BatchEvalRunner(h.state.snapshot(), h)
    runner.process([good, bad, missing_job])

    statuses = {e.id: e.status for e in h.evals}
    assert statuses[good.id] == "complete"
    assert statuses[bad.id] == "failed"
    assert statuses[missing_job.id] == "complete"  # noop plan


def test_batch_runner_plans_all_fit():
    """Fused lanes plan optimistically against the same snapshot; each
    individual plan must still fit on an empty fleet."""
    h = Harness()
    nodes = [mock.node(i) for i in range(4)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    jobs = []
    for _ in range(3):
        j = mock.job()
        j.task_groups[0].count = 2
        j.task_groups[0].tasks[0].resources.cpu = 1000
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)

    runner = BatchEvalRunner(h.state.snapshot(), h)
    runner.process([make_eval(j) for j in jobs])

    by_node = {n.id: n for n in nodes}
    for plan in h.plans:
        for node_id, allocs in plan.node_allocation.items():
            fit, dim, _ = allocs_fit(by_node[node_id], allocs)
            assert fit, dim


def test_batch_runner_serializes_same_job_evals():
    """Two evals for the same job in one call must not double-place
    (code-review regression): the second runs against refreshed state."""
    h = Harness()
    for i in range(8):
        h.state.upsert_node(h.next_index(), mock.node(i))
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)

    runner = BatchEvalRunner(h.state.snapshot(), h,
                             state_refresh=lambda: h.state.snapshot())
    runner.process([make_eval(job), make_eval(job)])

    live = [a for a in h.state.allocs_by_job(job.id)
            if not a.terminal_status()]
    assert len(live) == 4, f"expected 4 allocs, got {len(live)}"


def test_batch_runner_same_job_without_refresh_fails_safe():
    h = Harness()
    for i in range(8):
        h.state.upsert_node(h.next_index(), mock.node(i))
    job = mock.job()
    job.task_groups[0].count = 2
    h.state.upsert_job(h.next_index(), job)

    runner = BatchEvalRunner(h.state.snapshot(), h)
    e1, e2 = make_eval(job), make_eval(job)
    runner.process([e1, e2])
    statuses = {e.id: e.status for e in h.evals}
    assert statuses[e1.id] == "complete"
    assert statuses[e2.id] == "failed"
    live = [a for a in h.state.allocs_by_job(job.id)
            if not a.terminal_status()]
    assert len(live) == 2


def test_fused_dispatch_rides_the_mesh_on_multi_device(monkeypatch):
    """On a multi-device host the fused dispatch routes through the
    mesh-sharded kernels (storm layout when the lane count splits), and
    the plans match a single-device run lane for lane."""
    import nomad_tpu.parallel.mesh as mesh_mod

    def build(runner_patch=None):
        h = Harness()
        for i in range(16):
            h.state.upsert_node(h.next_index(), mock.node(i))
        jobs = []
        for _ in range(4):
            j = mock.job()
            j.task_groups[0].count = 4
            h.state.upsert_job(h.next_index(), j)
            jobs.append(j)
        return h, jobs

    # Force the device executor (the tiny fleet would otherwise take
    # the host twins) and record which mesh the dispatch used.
    from nomad_tpu.scheduler.jax_binpack import JaxBinPackScheduler

    monkeypatch.setattr(JaxBinPackScheduler, "HOST_SINGLE_SHOT_COST", 0)
    used = []
    orig = mesh_mod.dispatch_mesh

    def spy(n_lanes, n_pad):
        mesh = orig(n_lanes, n_pad)
        used.append(mesh)
        return mesh
    monkeypatch.setattr(mesh_mod, "dispatch_mesh", spy)

    h, jobs = build()
    BatchEvalRunner(h.state.snapshot(), h).process(
        [make_eval(j) for j in jobs])
    assert used and used[-1] is not None, "mesh not used on 8 devices"
    assert "lanes" in used[-1].axis_names  # storm layout chosen
    mesh_counts = [sum(len(v) for v in p.node_allocation.values())
                   for p in h.plans]

    # Same workload forced down the single-device path (the
    # NOMAD_TPU_MESH="off" lever, here via its process override).
    monkeypatch.setattr(mesh_mod, "dispatch_mesh", orig)
    h2, jobs2 = build()
    with mesh_mod.mesh_override("off"):
        BatchEvalRunner(h2.state.snapshot(), h2).process(
            [make_eval(j) for j in jobs2])
    single_counts = [sum(len(v) for v in p.node_allocation.values())
                     for p in h2.plans]
    assert mesh_counts == single_counts == [4, 4, 4, 4]
    assert all(e.status == "complete" for e in h.evals)
