"""UsageMirror: the incremental state->HBM usage bridge.

Verifies the mirror stays exactly equal to a from-scratch build_usage
through every kind of store delta (upserts, client updates, reaps,
changelog compaction, snapshot restore), that it does O(changed) work
(no full rebuilds once primed), that plan-delta views match the
_proposed_allocs_all path, and that the device-resident copy tracks the
host arrays through scatter maintenance.

Reference analogue: the alloc feed of nomad/state/state_store.go:115-156;
SURVEY.md section 7 "Incremental device state".
"""
from __future__ import annotations

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.models.fleet import (
    UsageMirror,
    build_fleet,
    build_usage,
    fleet_cache,
    mirror_for,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    Allocation,
    Plan,
    Resources,
    generate_uuid,
)


def _mk_store(n_nodes: int = 8):
    store = StateStore()
    nodes = []
    for i in range(n_nodes):
        n = mock.node(i)
        nodes.append(n)
        store.upsert_node(i + 1, n)
    return store, nodes


def _alloc(node_id: str, job_id: str = "j1", cpu: int = 500,
           mem: int = 256) -> Allocation:
    return Allocation(
        id=generate_uuid(), node_id=node_id, job_id=job_id,
        resources=Resources(cpu=cpu, memory_mb=mem),
    )


def _assert_mirror_matches(mirror: UsageMirror, store, job_id: str = "j1"):
    """Mirror state must equal a from-scratch aggregation of the store."""
    live = [a for a in store.allocs() if not a.terminal_status()]
    scratch = build_usage(mirror.statics, live, job_id=job_id)
    np.testing.assert_allclose(mirror.usage, scratch.usage)
    dense = np.zeros(mirror.statics.n_pad, dtype=np.int32)
    for ni, c in mirror.job_counts.get(job_id, {}).items():
        dense[ni] = c
    np.testing.assert_array_equal(dense, scratch.job_counts)
    # alloc_rows tracks exactly the live allocs on known nodes.
    expect_rows = {a.id for a in live
                   if a.node_id in mirror.statics.index_of}
    assert set(mirror.alloc_rows) == expect_rows


def test_sync_through_upsert_update_delete():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    assert mirror.sync(store)
    _assert_mirror_matches(mirror, store)
    assert mirror.rebuilds == 1

    # Upserts land incrementally.
    a1 = _alloc(nodes[0].id)
    a2 = _alloc(nodes[1].id, job_id="j2")
    store.upsert_allocs(10, [a1, a2])
    assert mirror.sync(store)
    _assert_mirror_matches(mirror, store)

    # Client update to terminal removes the contribution.
    done = a1.copy()
    done.client_status = ALLOC_CLIENT_STATUS_FAILED
    store.update_alloc_from_client(11, done)
    assert mirror.sync(store)
    _assert_mirror_matches(mirror, store)

    # Replacing an alloc's node moves its usage row.
    moved = a2.copy()
    moved.node_id = nodes[2].id
    store.upsert_allocs(12, [moved])
    assert mirror.sync(store)
    _assert_mirror_matches(mirror, store)
    _assert_mirror_matches(mirror, store, job_id="j2")

    # Reap (delete_eval with alloc ids) drops rows.
    store.delete_eval(13, [], [a2.id])
    assert mirror.sync(store)
    _assert_mirror_matches(mirror, store, job_id="j2")
    # Everything above was incremental: exactly the one initial rebuild.
    assert mirror.rebuilds == 1


def test_sync_survives_changelog_compaction():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    mirror.sync(store)

    # Force the changelog past its compaction bound while the mirror
    # isn't watching; the gap exceeds the retained log so sync must
    # detect it and rebuild, not silently under-apply.
    n_writes = StateStore._ALLOC_LOG_MAX + 10
    for i in range(n_writes):
        store.upsert_allocs(100 + i, [_alloc(nodes[i % len(nodes)].id)])
    assert mirror.sync(store)
    _assert_mirror_matches(mirror, store)
    assert mirror.rebuilds == 2  # initial + post-compaction


def test_sync_incremental_when_log_covers_gap():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    mirror.sync(store)
    for i in range(200):
        store.upsert_allocs(10 + i, [_alloc(nodes[i % len(nodes)].id)])
        assert mirror.sync(store)
    _assert_mirror_matches(mirror, store)
    assert mirror.rebuilds == 1


def test_mirror_is_monotonic_old_snapshot_refused():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    old_snap = store.snapshot()
    store.upsert_allocs(10, [_alloc(nodes[0].id)])
    assert mirror.sync(store)
    # A snapshot from before the mirror's fence cannot be served.
    assert not mirror.sync(old_snap)
    _assert_mirror_matches(mirror, store)


def test_view_applies_plan_deltas():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    a1 = _alloc(nodes[0].id)
    a2 = _alloc(nodes[1].id)
    store.upsert_allocs(10, [a1, a2])
    mirror.sync(store)

    plan = Plan()
    plan.append_update(a1, "stop", "")
    placed = _alloc(nodes[3].id)
    plan.append_alloc(placed)

    view = mirror.view(plan, "j1")
    # Equivalent from-scratch: existing minus evictions plus placements.
    proposed = [a2, placed]
    scratch = build_usage(statics, proposed, job_id="j1")
    np.testing.assert_allclose(view.usage, scratch.usage)
    np.testing.assert_array_equal(view.job_counts, scratch.job_counts)
    # Plan-delta views are private copies with no resident device copy.
    assert view.usage_device is None
    # The mirror's own arrays were not touched (copy-on-write).
    _assert_mirror_matches(mirror, store)


def test_view_without_deltas_shares_device_copy():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    store.upsert_allocs(10, [_alloc(nodes[0].id)])
    mirror.sync(store)
    view = mirror.view(Plan(), "j1")
    assert view.usage_device is not None
    np.testing.assert_allclose(np.asarray(view.usage_device), view.usage)
    assert view.dispatch_usage() is view.usage_device


def test_device_copy_tracks_scatter_maintenance():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    mirror.sync(store)
    d0 = mirror.device_usage()
    for i in range(20):
        store.upsert_allocs(10 + i, [_alloc(nodes[i % len(nodes)].id)])
        mirror.sync(store)
        np.testing.assert_allclose(np.asarray(mirror.device_usage()),
                                   mirror.usage)
    # No donation: the first handed-out buffer is still readable.
    np.testing.assert_allclose(np.asarray(d0),
                               np.zeros_like(mirror.usage))


def test_views_frozen_under_later_syncs():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    store.upsert_allocs(10, [_alloc(nodes[0].id)])
    mirror.sync(store)
    view = mirror.view(None, "j1")
    before = view.usage.copy()
    for i in range(5):
        store.upsert_allocs(11 + i, [_alloc(nodes[1].id)])
        mirror.sync(store)
    np.testing.assert_allclose(view.usage, before)


def test_restore_forces_rebuild():
    store, nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    mirror = UsageMirror(statics)
    store.upsert_allocs(10, [_alloc(nodes[0].id)])
    mirror.sync(store)
    assert mirror.rebuilds == 1

    # Snapshot-restore rebuilds the store wholesale; the changelog base
    # moves past the mirror so it must rebuild.
    restore = store.restore()
    for n in store.nodes():
        restore.node_restore(n)
    restore.alloc_restore(_alloc(nodes[2].id))
    restore.index_restore("allocs", 50)
    restore.commit()
    assert mirror.sync(store)
    assert mirror.rebuilds == 2
    _assert_mirror_matches(mirror, store)
    # ... and exactly once: repeated syncs of the restored (quiet) state
    # must be no-ops, not rebuild thrash (code-review regression).
    for _ in range(5):
        assert mirror.sync(store)
    assert mirror.rebuilds == 2

    # A restore that lands on the SAME allocs index still forces one
    # rebuild (the world changed wholesale even though the index didn't).
    restore2 = store.restore()
    for n in store.nodes():
        restore2.node_restore(n)
    restore2.alloc_restore(_alloc(nodes[3].id))
    restore2.index_restore("allocs", 50)
    restore2.commit()
    assert mirror.sync(store)
    assert mirror.rebuilds == 3
    _assert_mirror_matches(mirror, store)


def test_scheduler_path_uses_mirror_o_changed(monkeypatch):
    """1k sequential evals against a growing store do O(changed) host
    work: the mirror rebuilds once and the O(allocs) fallback
    (_proposed_allocs_all) is never taken."""
    from nomad_tpu.scheduler import Harness
    from nomad_tpu.scheduler.jax_binpack import JaxBinPackScheduler

    calls = {"fallback": 0}
    orig = JaxBinPackScheduler._proposed_allocs_all

    def counting(self):
        calls["fallback"] += 1
        return orig(self)

    monkeypatch.setattr(JaxBinPackScheduler, "_proposed_allocs_all",
                        counting)

    h = Harness()
    for i in range(16):
        h.state.upsert_node(h.next_index(), mock.node(i))
    n_evals = 50
    jobs = []
    for _ in range(n_evals):
        j = mock.job()
        j.task_groups[0].count = 1
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)

    from nomad_tpu.structs import (
        EVAL_TRIGGER_JOB_REGISTER,
        Evaluation,
    )
    for j in jobs:
        ev = Evaluation(
            id=generate_uuid(), priority=50, type="service",
            triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=j.id)
        h.process("jax-binpack", ev)

    assert calls["fallback"] == 0
    statics = fleet_cache.statics_for(h.state)
    assert statics.mirror is not None
    assert statics.mirror.rebuilds <= 1
    # And the plans actually placed (the path was live, not short-circuited).
    assert len(h.plans) == n_evals


def test_mirror_for_is_singleton():
    store, _nodes = _mk_store()
    statics = build_fleet(list(store.nodes()))
    assert mirror_for(statics) is mirror_for(statics)


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v"]))


def test_scatter_rows_pads_to_pow2_and_stays_exact():
    """_scatter_rows pads every batch to a power-of-two row count (the
    jit would otherwise recompile per distinct delta size) with no-op
    rewrites — results must equal a plain numpy row assignment for odd,
    even, single and empty batches."""
    import jax
    import numpy as np
    from nomad_tpu.models.fleet import _scatter_rows

    base = np.arange(40, dtype=np.float32).reshape(10, 4)
    usage_d = jax.device_put(base)
    rng = np.random.default_rng(7)
    for n in (0, 1, 2, 3, 5, 7, 10):
        idx = rng.choice(10, size=n, replace=False).astype(np.int64) \
            if n else np.zeros(0, dtype=np.int64)
        rows = rng.normal(size=(n, 4)).astype(np.float32)
        want = np.asarray(usage_d).copy()
        want[idx] = rows
        usage_d = _scatter_rows(usage_d, idx, rows)
        np.testing.assert_array_equal(np.asarray(usage_d), want)
