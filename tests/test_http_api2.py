"""Second HTTP-surface suite: the wire-level contracts the reference
asserts in command/agent/http_test.go and the per-endpoint method
tables of {job,node,eval,alloc}_endpoint_test.go — response headers
(X-Nomad-Index), JSON content type, ?pretty, bad ?wait/?index -> 400,
405s, job update/delete/force-evaluate, node drain/evaluate via HTTP,
and unknown-region errors."""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu.jobspec import parse
from tests.conftest import boot_dev_agent, wait_until

JOBSPEC = """
job "pings" {
    datacenters = ["dc1"]
    group "g" {
        count = 1
        task "t" {
            driver = "raw_exec"
            config {
                command = "/bin/sleep"
                args = "120"
            }
            resources {
                cpu = 50
                memory = 32
            }
        }
    }
}
"""


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    a, _client = boot_dev_agent(
        str(tmp_path_factory.mktemp("agent-http2")))
    yield a
    a.shutdown()


def _url(agent, path):
    return f"http://127.0.0.1:{agent.http.address[1]}{path}"


def _req(agent, path, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(_url(agent, path), data=data,
                                 method=method)
    try:
        resp = urllib.request.urlopen(req, timeout=10)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _register(agent):
    job = parse(JOBSPEC)
    code, _h, raw = _req(agent, "/v1/jobs", "PUT",
                         {"job": job.to_dict()})
    assert code == 200, raw
    return job, json.loads(raw)


# ---------------------------------------------------------------------------
# wire-level contracts (http_test.go:48-160)
# ---------------------------------------------------------------------------

def test_content_type_and_index_header(agent):
    code, headers, raw = _req(agent, "/v1/nodes")
    assert code == 200
    assert headers.get("Content-Type", "").startswith("application/json")
    assert int(headers.get("X-Nomad-Index", "0")) > 0
    assert isinstance(json.loads(raw), list)


def test_pretty_print(agent):
    _code, _h, plain = _req(agent, "/v1/nodes")
    _code, _h, pretty = _req(agent, "/v1/nodes?pretty=1")
    assert b"\n" in pretty and len(pretty) > len(plain)
    assert json.loads(pretty) == json.loads(plain)


def test_invalid_wait_and_index_are_400(agent):
    code, _h, _raw = _req(agent, "/v1/nodes?wait=nope")
    assert code == 400
    code, _h, _raw = _req(agent, "/v1/nodes?index=abc")
    assert code == 400


def test_unknown_path_404(agent):
    code, _h, _raw = _req(agent, "/v1/nonsense")
    assert code == 404
    code, _h, _raw = _req(agent, "/notv1")
    assert code == 404


def test_method_not_allowed_405(agent):
    code, _h, _raw = _req(agent, "/v1/jobs", "DELETE")
    assert code == 405


def test_unknown_region_errors(agent):
    code, _h, raw = _req(agent, "/v1/nodes?region=mars")
    assert code == 500
    assert b"region" in raw.lower()


# ---------------------------------------------------------------------------
# job endpoint methods (job_endpoint_test.go:59-340)
# ---------------------------------------------------------------------------

def test_job_register_query_update_delete(agent):
    job, reg = _register(agent)
    assert reg["eval_id"]

    code, _h, raw = _req(agent, f"/v1/job/{job.id}")
    assert code == 200
    got = json.loads(raw)
    assert got["id"] == job.id

    # Update: re-register with a different count through PUT /v1/job/<id>.
    job.task_groups[0].count = 2
    code, _h, raw = _req(agent, f"/v1/job/{job.id}", "PUT",
                         {"job": job.to_dict()})
    assert code == 200
    code, _h, raw = _req(agent, f"/v1/job/{job.id}")
    assert json.loads(raw)["task_groups"][0]["count"] == 2

    # Evaluations + allocations sub-endpoints list this job's records.
    def evals_listed():
        _c, _h, r = _req(agent, f"/v1/job/{job.id}/evaluations")
        return len(json.loads(r)) >= 1
    wait_until(evals_listed, msg="job evaluations")

    def allocs_listed():
        _c, _h, r = _req(agent, f"/v1/job/{job.id}/allocations")
        return len(json.loads(r)) >= 1
    wait_until(allocs_listed, msg="job allocations")

    # Force evaluate mints a fresh eval.
    code, _h, raw = _req(agent, f"/v1/job/{job.id}/evaluate", "PUT", {})
    assert code == 200
    assert json.loads(raw)["eval_id"]

    # Delete deregisters; the job disappears.
    code, _h, _raw = _req(agent, f"/v1/job/{job.id}", "DELETE")
    assert code == 200
    wait_until(lambda: _req(agent, f"/v1/job/{job.id}")[0] == 404,
               msg="job deregistered")


def test_job_query_missing_404(agent):
    code, _h, _raw = _req(agent, "/v1/job/no-such-job")
    assert code == 404


# ---------------------------------------------------------------------------
# node endpoint methods (node_endpoint_test.go:59-256)
# ---------------------------------------------------------------------------

def test_node_query_allocations_drain_evaluate(agent):
    _code, _h, raw = _req(agent, "/v1/nodes")
    nodes = json.loads(raw)
    assert nodes, "dev agent registers one node"
    node_id = nodes[0]["id"]

    code, _h, raw = _req(agent, f"/v1/node/{node_id}")
    assert code == 200 and json.loads(raw)["id"] == node_id

    code, _h, raw = _req(agent, f"/v1/node/{node_id}/allocations")
    assert code == 200 and isinstance(json.loads(raw), list)

    code, _h, raw = _req(agent, f"/v1/node/{node_id}/evaluate", "PUT")
    assert code == 200

    # Drain on, visible in the node record, then off again.
    code, _h, _raw = _req(agent,
                          f"/v1/node/{node_id}/drain?enable=true", "PUT")
    assert code == 200
    _c, _h, raw = _req(agent, f"/v1/node/{node_id}")
    assert json.loads(raw)["drain"] is True
    _req(agent, f"/v1/node/{node_id}/drain?enable=false", "PUT")
    _c, _h, raw = _req(agent, f"/v1/node/{node_id}")
    assert json.loads(raw)["drain"] is False


def test_eval_endpoints(agent):
    job, reg = _register(agent)
    eval_id = reg["eval_id"]
    code, _h, raw = _req(agent, f"/v1/evaluation/{eval_id}")
    assert code == 200 and json.loads(raw)["id"] == eval_id

    code, _h, raw = _req(agent, f"/v1/evaluation/{eval_id}/allocations")
    assert code == 200 and isinstance(json.loads(raw), list)

    code, _h, raw = _req(agent, "/v1/evaluations")
    assert code == 200
    assert any(e["id"] == eval_id for e in json.loads(raw))
    _req(agent, f"/v1/job/{job.id}", "DELETE")


def test_blocking_query_returns_on_change(agent):
    # Self-containment: on a fresh agent the jobs table index is 0 and
    # `?index=0` takes the immediate-return path without ever parking a
    # watcher — seed one write so the long-poll actually blocks.
    seed, _ = _register(agent)
    _req(agent, f"/v1/job/{seed.id}", "DELETE")
    _c, headers, _raw = _req(agent, "/v1/jobs")
    index = int(headers["X-Nomad-Index"])

    import threading
    results = []

    def blocked():
        results.append(_req(
            agent, f"/v1/jobs?index={index}&wait=10s"))

    t = threading.Thread(target=blocked)
    t.start()
    # Event-driven: the query is parked once the store has a watcher on
    # the jobs table (was a fixed 0.2s sleep).
    wait_until(lambda: agent.server.fsm.state.watch.live_waiters() > 0,
               msg="blocking query parked server-side")
    job, _ = _register(agent)
    t.join(timeout=10)
    assert not t.is_alive(), "blocking query must return on the write"
    code, headers2, raw = results[0]
    assert code == 200
    assert int(headers2["X-Nomad-Index"]) > index
    assert any(j["id"] == job.id for j in json.loads(raw))
    _req(agent, f"/v1/job/{job.id}", "DELETE")
