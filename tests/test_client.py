"""Client agent tests: fingerprints, drivers, runners, full integration."""
from __future__ import annotations

import os
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.alloc_runner import AllocRunner
from nomad_tpu.client.driver.base import ExecContext
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.client.task_env import task_environment
from nomad_tpu.client.task_runner import TaskRunner
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import (
    NetworkResource,
    Node,
    Resources,
    Task,
    generate_uuid,
)

from tests.conftest import wait_until


def raw_task(name="echo", command="/bin/sh",
             args="-c 'echo hello-from-task'") -> Task:
    return Task(name=name, driver="raw_exec",
                config={"command": command, "args": args},
                resources=Resources(cpu=100, memory_mb=64))


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def test_fingerprint_populates_node():
    cfg = ClientConfig(options={"fingerprint.skip_accel": "1"})
    node = Node()
    applied = fingerprint_node(cfg, node)
    assert "arch" in applied and "cpu" in applied and "memory" in applied
    assert node.attributes["kernel.name"]
    assert node.resources.cpu > 0
    assert node.resources.memory_mb > 0
    assert node.resources.disk_mb > 0
    assert node.attributes["cpu.numcores"]
    assert node.resources.networks


def test_driver_fingerprints():
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    cfg = ClientConfig(options={"driver.raw_exec.enable": "1"})
    node = Node(attributes={"kernel.name": "linux"})
    assert BUILTIN_DRIVERS["raw_exec"].fingerprint(cfg, node)
    assert node.attributes["driver.raw_exec"] == "1"
    assert BUILTIN_DRIVERS["exec"].fingerprint(cfg, node)
    assert node.attributes["driver.exec"] == "1"
    # raw_exec off by default
    node2 = Node()
    assert not BUILTIN_DRIVERS["raw_exec"].fingerprint(ClientConfig(),
                                                       node2)


# ---------------------------------------------------------------------------
# alloc dir + env
# ---------------------------------------------------------------------------

def test_env_cloud_fingerprints():
    """AWS/GCE metadata probes: off by default, detect against a local
    fake metadata server when enabled (reference env_aws_test.go /
    gce_test.go with httptest)."""
    import http.server
    import threading

    from nomad_tpu.client.fingerprint import (
        env_aws_fingerprint,
        env_gce_fingerprint,
    )

    class _Meta(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"ok")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), _Meta)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        # Off by default: no probe, no attributes.
        node = mock.node()
        assert not env_aws_fingerprint(ClientConfig(), node)
        assert not env_gce_fingerprint(ClientConfig(), node)
        assert "platform.aws.detected" not in node.attributes

        cfg = ClientConfig(options={
            "fingerprint.env_aws": "1",
            "fingerprint.env_aws.url": url,
            "fingerprint.env_gce": "1",
            "fingerprint.env_gce.url": url,
        })
        node = mock.node()
        assert env_aws_fingerprint(cfg, node)
        assert node.attributes["platform.aws.detected"] == "true"
        assert env_gce_fingerprint(cfg, node)
        assert node.attributes["platform.gce.detected"] == "true"

        # Unreachable endpoint: enabled but cleanly not-detected.
        # A freshly bound-then-closed port is deterministically dead.
        import socket
        s2 = socket.socket()
        s2.bind(("127.0.0.1", 0))
        dead_port = s2.getsockname()[1]
        s2.close()
        cfg = ClientConfig(options={
            "fingerprint.env_aws": "1",
            "fingerprint.env_aws.url": f"http://127.0.0.1:{dead_port}",
        })
        node = mock.node()
        assert not env_aws_fingerprint(cfg, node)
    finally:
        srv.shutdown()
        srv.server_close()


def test_alloc_dir_tree(tmp_path):
    ad = AllocDir(str(tmp_path / "a1"))
    ad.build([raw_task("t1"), raw_task("t2")])
    assert os.path.isdir(ad.shared_dir + "/logs")
    assert os.path.isdir(os.path.join(ad.task_dirs["t1"], "local"))
    # Shared dir visible from inside each task dir.
    assert os.path.islink(os.path.join(ad.task_dirs["t2"], "alloc"))
    ad.destroy()
    assert not os.path.exists(ad.alloc_dir)


def test_task_environment():
    task = raw_task()
    task.env = {"CUSTOM": "yes"}
    task.meta = {"owner": "ops"}
    res = Resources(cpu=250, memory_mb=128, networks=[NetworkResource(
        ip="10.0.0.5", reserved_ports=[22, 8080],
        dynamic_ports=["http"], mbits=10)])
    env = task_environment(task, alloc_dir="/a", task_dir="/t",
                          resources=res)
    assert env["NOMAD_ALLOC_DIR"] == "/a"
    assert env["NOMAD_MEMORY_LIMIT"] == "128"
    assert env["NOMAD_CPU_LIMIT"] == "250"
    assert env["NOMAD_IP"] == "10.0.0.5"
    assert env["NOMAD_PORT_http"] == "8080"
    assert env["NOMAD_META_OWNER"] == "ops"
    assert env["CUSTOM"] == "yes"


# ---------------------------------------------------------------------------
# task runner
# ---------------------------------------------------------------------------

def test_task_runner_completes(tmp_path):
    ad = AllocDir(str(tmp_path / "alloc"))
    task = raw_task()
    ad.build([task])
    ctx = ExecContext(ad, "alloc-1")
    states = []
    tr = TaskRunner(ctx, task, state_dir=str(tmp_path / "state"),
                    on_state=lambda n, s, d: states.append(s))
    tr.start()
    wait_until(lambda: tr.state == "dead", msg="task completion")
    assert not tr.failed
    with open(ad.log_path("echo", "stdout")) as fh:
        assert "hello-from-task" in fh.read()


def test_task_runner_failure(tmp_path):
    ad = AllocDir(str(tmp_path / "alloc"))
    task = raw_task(command="/bin/false", args="")
    ad.build([task])
    tr = TaskRunner(ExecContext(ad, "a"), task)
    tr.start()
    wait_until(lambda: tr.state == "dead", msg="task exit")
    assert tr.failed


def test_task_runner_destroy_kills(tmp_path):
    ad = AllocDir(str(tmp_path / "alloc"))
    task = raw_task(command="/bin/sleep", args="300")
    ad.build([task])
    tr = TaskRunner(ExecContext(ad, "a"), task)
    tr.start()
    wait_until(lambda: tr.state == "running", msg="task start")
    tr.destroy()
    wait_until(lambda: tr.state == "dead", msg="task killed")


def test_task_runner_reattach(tmp_path):
    """Agent restart: a new TaskRunner re-attaches to the live process via
    the persisted handle id instead of restarting the task."""
    ad = AllocDir(str(tmp_path / "alloc"))
    task = raw_task(command="/bin/sleep", args="30")
    ad.build([task])
    state_dir = str(tmp_path / "state")
    tr = TaskRunner(ExecContext(ad, "a"), task, state_dir=state_dir)
    tr.start()
    wait_until(lambda: tr.state == "running", msg="task start")
    pid = tr.handle.pid

    # "Restart": fresh runner from persisted state.
    tr2 = TaskRunner(ExecContext(ad, "a"), task, state_dir=state_dir)
    assert tr2.restore_state()
    assert tr2.handle.pid == pid
    tr2.start()
    wait_until(lambda: tr2.state == "running", msg="re-attached running")
    tr2.destroy()
    wait_until(lambda: tr2.state == "dead", msg="killed after re-attach")
    tr.destroy()


@pytest.mark.skipif(os.geteuid() != 0, reason="rkt driver is root-only")
def test_rkt_driver_fingerprint_and_start(tmp_path, fake_bin):
    install, fake_log = fake_bin
    install("rkt",
            'if [ "$1" = "version" ]; then '
            'echo "rkt Version: 1.30.0"; '
            'echo "appc Version: 0.8.11"; fi')
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    node = Node(attributes={"kernel.name": "linux"})
    assert BUILTIN_DRIVERS["rkt"].fingerprint(ClientConfig(), node)
    assert node.attributes["driver.rkt"] == "1"
    assert node.attributes["driver.rkt.version"] == "1.30.0"
    assert node.attributes["driver.rkt.appc.version"] == "0.8.11"

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="pod", driver="rkt",
                config={"image": "coreos.com/etcd:v2.0.4",
                        "command": "/etcd", "args": "--version"},
                resources=Resources(cpu=100, memory_mb=64))
    ad.build([task])
    drv = BUILTIN_DRIVERS["rkt"](ExecContext(ad, "alloc-rkt"))
    handle = drv.start(task)
    assert handle.wait(10) == 0
    line = [l for l in fake_log.read_text().splitlines()
            if " run " in l][-1]
    assert "--insecure-skip-verify" in line
    assert "run --mds-register=false coreos.com/etcd:v2.0.4" in line
    assert "--exec=/etcd" in line and line.endswith("-- --version")


def test_rkt_driver_fingerprint_absent_without_binary(monkeypatch,
                                                      tmp_path):
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    empty = tmp_path / "emptybin"
    empty.mkdir()
    monkeypatch.setenv("PATH", str(empty))
    node = Node(attributes={"kernel.name": "linux"})
    assert not BUILTIN_DRIVERS["rkt"].fingerprint(ClientConfig(), node)
    assert "driver.rkt" not in node.attributes


@pytest.fixture
def fake_bin(tmp_path, monkeypatch):
    """Install fake binaries on PATH; returns (bindir, invocation log)."""
    bindir = tmp_path / "fakebin"
    bindir.mkdir()
    log = tmp_path / "invocations.log"
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    def install(name: str, body: str = ""):
        exe = bindir / name
        exe.write_text(f'#!/bin/sh\necho "{name} $@" >> {log}\n{body}\n')
        exe.chmod(0o755)
        return exe

    return install, log


def test_java_driver_fingerprint_and_start(tmp_path, fake_bin):
    install, log = fake_bin
    install("java",
            'if [ "$1" = "-version" ]; then '
            'echo \'openjdk version "21.0.2" 2024\' >&2; fi')
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    node = Node(attributes={"kernel.name": "linux"})
    assert BUILTIN_DRIVERS["java"].fingerprint(ClientConfig(), node)
    assert node.attributes["driver.java"] == "1"
    assert node.attributes["driver.java.version"] == "21.0.2"

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="jvm", driver="java",
                config={"jar_path": "/srv/app.jar",
                        "jvm_options": "-Xmx128m", "args": "serve"},
                resources=Resources(cpu=100, memory_mb=256))
    ad.build([task])
    drv = BUILTIN_DRIVERS["java"](ExecContext(ad, "alloc-j"))
    handle = drv.start(task)
    assert handle.wait(10) == 0
    line = [l for l in log.read_text().splitlines() if "-jar" in l][-1]
    assert line == "java -Xmx128m -jar /srv/app.jar serve"


def test_qemu_driver_fingerprint_and_start(tmp_path, fake_bin):
    install, log = fake_bin
    install("qemu-system-x86_64",
            'if [ "$1" = "--version" ]; then '
            'echo "QEMU emulator version 8.2.1"; fi')
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    node = Node(attributes={"kernel.name": "linux"})
    assert BUILTIN_DRIVERS["qemu"].fingerprint(ClientConfig(), node)
    assert node.attributes["driver.qemu.version"] == "8.2.1"

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="vm", driver="qemu",
                config={"image_path": "/srv/disk.img",
                        "accelerator": "tcg",
                        "port_map": {"ssh": 22}},
                resources=Resources(
                    cpu=500, memory_mb=512,
                    networks=[NetworkResource(
                        ip="10.0.0.1", dynamic_ports=["ssh"],
                        reserved_ports=[31022])]))
    # map_dynamic_ports pairs labels with assigned reserved ports.
    ad.build([task])
    drv = BUILTIN_DRIVERS["qemu"](ExecContext(ad, "alloc-q"))
    handle = drv.start(task)
    assert handle.wait(10) == 0
    line = [l for l in log.read_text().splitlines()
            if "qemu-system" in l][-1]
    assert "-m 512M" in line and "file=/srv/disk.img" in line
    assert "hostfwd=tcp::31022-:22" in line


@pytest.mark.skipif(os.geteuid() != 0, reason="requires root")
def test_exec_driver_drops_privileges(tmp_path):
    """Root exec tasks run as nobody after chroot (reference
    client/executor/exec_linux.go privilege drop)."""
    import pwd

    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="iduid", driver="exec",
                config={"command": "/usr/bin/id", "args": "-u"},
                resources=Resources(cpu=100, memory_mb=64))
    ad.build([task])
    drv = BUILTIN_DRIVERS["exec"](ExecContext(ad, "alloc-priv"))
    handle = drv.start(task)
    assert handle.wait(30) == 0
    out = open(ad.log_path("iduid", "stdout")).read().strip()
    assert out == str(pwd.getpwnam("nobody").pw_uid)


@pytest.mark.skipif(os.geteuid() != 0, reason="requires root")
def test_exec_driver_user_override_keeps_root(tmp_path):
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="idroot", driver="exec",
                config={"command": "/usr/bin/id", "args": "-u",
                        "user": "root"},
                resources=Resources(cpu=100, memory_mb=64))
    ad.build([task])
    drv = BUILTIN_DRIVERS["exec"](ExecContext(ad, "alloc-priv2"))
    handle = drv.start(task)
    assert handle.wait(30) == 0
    out = open(ad.log_path("idroot", "stdout")).read().strip()
    assert out == "0"


# ---------------------------------------------------------------------------
# alloc runner
# ---------------------------------------------------------------------------

def make_alloc(command="/bin/sh", args="-c 'echo done'"):
    job = mock.job()
    tg = job.task_groups[0]
    tg.tasks = [raw_task(command=command, args=args)]
    alloc = mock.alloc()
    alloc.job = job
    alloc.job_id = job.id
    alloc.task_group = tg.name
    alloc.task_resources = {}
    return alloc


def test_alloc_runner_lifecycle(tmp_path):
    alloc = make_alloc()
    statuses = []
    runner = AllocRunner(alloc, str(tmp_path / "alloc"),
                         state_dir=str(tmp_path / "state"),
                         on_status=lambda a: statuses.append(
                             a.client_status))
    runner.run()
    wait_until(lambda: runner.alloc.client_status == "dead",
               msg="alloc completion")
    assert "dead" in statuses


def test_alloc_runner_failed_task(tmp_path):
    alloc = make_alloc(command="/bin/false", args="")
    runner = AllocRunner(alloc, str(tmp_path / "alloc"))
    runner.run()
    wait_until(lambda: runner.alloc.client_status == "failed",
               msg="alloc failure")


# ---------------------------------------------------------------------------
# full integration: server + client over real RPC
# ---------------------------------------------------------------------------

@pytest.fixture
def cluster(tmp_path):
    srv = Server(ServerConfig(num_schedulers=2, enable_rpc=True))
    srv.establish_leadership()
    cfg = ClientConfig(
        state_dir=str(tmp_path / "client-state"),
        alloc_dir=str(tmp_path / "allocs"),
        servers=[srv.rpc_address()],
        options={"driver.raw_exec.enable": "1",
                 "fingerprint.skip_accel": "1"},
    )
    client = Client(cfg)
    client.start()
    yield srv, client
    client.shutdown()
    client.destroy_all()
    srv.shutdown()


def test_client_registers_and_runs_job(cluster):
    srv, client = cluster
    wait_until(lambda: srv.fsm.state.node_by_id(client.node.id)
               is not None, msg="node registration")
    node = srv.fsm.state.node_by_id(client.node.id)
    assert node.status == "ready"
    assert node.attributes.get("driver.raw_exec") == "1"

    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks = [Task(
        name="hello", driver="raw_exec",
        config={"command": "/bin/sh", "args": "-c 'echo job-output'"},
        resources=Resources(cpu=100, memory_mb=64))]
    job.constraints = []
    _, eval_id = srv.job_register(job)
    srv.wait_for_evals([eval_id], timeout=15)

    # The client picks up the alloc, runs it, and syncs terminal status.
    def alloc_done():
        allocs = srv.fsm.state.allocs_by_job(job.id)
        return allocs and allocs[0].client_status == "dead"
    wait_until(alloc_done, timeout=20, msg="alloc ran to completion")

    alloc = srv.fsm.state.allocs_by_job(job.id)[0]
    log = os.path.join(client._alloc_root(alloc.id), "alloc", "logs",
                       "hello.stdout")
    with open(log) as fh:
        assert "job-output" in fh.read()


def test_client_stops_alloc_on_deregister(cluster):
    srv, client = cluster
    wait_until(lambda: srv.fsm.state.node_by_id(client.node.id)
               is not None, msg="node registration")
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks = [Task(
        name="sleeper", driver="raw_exec",
        config={"command": "/bin/sleep", "args": "300"},
        resources=Resources(cpu=100, memory_mb=64))]
    job.constraints = []
    _, eval_id = srv.job_register(job)
    srv.wait_for_evals([eval_id], timeout=15)

    def running():
        allocs = srv.fsm.state.allocs_by_job(job.id)
        return allocs and allocs[0].client_status == "running"
    wait_until(running, timeout=20, msg="task running")

    _, e2 = srv.job_deregister(job.id)
    srv.wait_for_evals([e2], timeout=15)

    def stopped():
        runner = client.alloc_runners.get(
            srv.fsm.state.allocs_by_job(job.id)[0].id)
        return runner is not None and \
            runner.alloc.client_status in ("dead", "failed")
    wait_until(stopped, timeout=20, msg="task stopped after deregister")


def test_agent_restart_does_not_resurrect_completed_allocs(tmp_path):
    """A finished alloc must not re-run its tasks when the agent restarts
    (code-review regression)."""
    alloc = make_alloc(command="/bin/sh",
                       args=f"-c 'echo ran >> {tmp_path}/count'")
    state_dir = str(tmp_path / "state")
    runner = AllocRunner(alloc, str(tmp_path / "alloc"),
                         state_dir=state_dir)
    runner.run()
    wait_until(lambda: runner.alloc.client_status == "dead",
               msg="first run completes")

    # Simulate agent restart via a fresh client restore pass.
    cfg = ClientConfig(
        state_dir=str(tmp_path),
        alloc_dir=str(tmp_path / "alloc-root"),
        rpc_handler=type("NoRPC", (), {
            "call": lambda self, m, a, timeout=None: {}})(),
        options={"fingerprint.skip_accel": "1"},
    )
    os.makedirs(os.path.join(str(tmp_path), "allocs"), exist_ok=True)
    os.rename(state_dir, os.path.join(str(tmp_path), "allocs", alloc.id))
    client = Client(cfg)
    assert alloc.id not in client.alloc_runners
    time.sleep(0.3)  # sleep-ok: window proves the ABSENCE of a second run
    with open(tmp_path / "count") as fh:
        assert fh.read().count("ran") == 1


@pytest.mark.skipif(os.geteuid() != 0, reason="requires root")
def test_exec_driver_unknown_user_fails_closed(tmp_path):
    """A typo'd `user` must fail the task start, not silently run as
    root (chroot contents are hardlinked host inodes)."""
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="typo", driver="exec",
                config={"command": "/usr/bin/id", "args": "-u",
                        "user": "no-such-user-xyz"},
                resources=Resources(cpu=100, memory_mb=64))
    ad.build([task])
    drv = BUILTIN_DRIVERS["exec"](ExecContext(ad, "alloc-typo"))
    with pytest.raises(RuntimeError, match="does not exist"):
        drv.start(task)


def test_alloc_dir_reembed_refreshes_stale_entries(tmp_path):
    """Re-embedding picks up changed files and retargeted symlinks
    (previously any existing dest was skipped forever)."""
    src = tmp_path / "srcdir"
    src.mkdir()
    (src / "config").write_text("v1")
    (src / "current").symlink_to("config")

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="t", driver="exec", config={},
                resources=Resources(cpu=100, memory_mb=64))
    ad.build([task])
    dest = os.path.join(ad.task_dirs["t"], "embedded")
    ad.embed("t", {str(src): "embedded"})
    assert open(os.path.join(dest, "config")).read() == "v1"
    assert os.readlink(os.path.join(dest, "current")) == "config"

    # Change content (newer mtime) and retarget the symlink.
    time.sleep(0.01)  # sleep-ok: force a distinct mtime
    (src / "other").write_text("v2-content")
    cfg = src / "config"
    cfg.unlink()
    cfg.write_text("v2")
    now = time.time() + 5
    os.utime(cfg, (now, now))
    cur = src / "current"
    cur.unlink()
    cur.symlink_to("other")

    ad.embed("t", {str(src): "embedded"})
    assert open(os.path.join(dest, "config")).read() == "v2"
    assert os.readlink(os.path.join(dest, "current")) == "other"


@pytest.fixture
def artifact_server(tmp_path):
    """Local HTTP server serving tmp_path/artifacts (no egress here)."""
    import http.server
    import threading as _threading

    adir = tmp_path / "artifacts"
    adir.mkdir()

    class Handler(http.server.SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=str(adir), **kw)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield adir, f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def test_java_driver_downloads_artifact(tmp_path, fake_bin,
                                        artifact_server):
    """jar_source over HTTP lands in the task's local dir before launch
    (reference client/driver/java.go:96-130)."""
    import hashlib

    install, log = fake_bin
    install("java")
    adir, base = artifact_server
    (adir / "app.jar").write_bytes(b"PK\x03\x04 fake jar")
    digest = hashlib.sha256(b"PK\x03\x04 fake jar").hexdigest()

    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="jvm", driver="java",
                config={"artifact_source": f"{base}/app.jar",
                        "checksum": f"sha256:{digest}", "args": "serve"},
                resources=Resources(cpu=100, memory_mb=256))
    ad.build([task])
    drv = BUILTIN_DRIVERS["java"](ExecContext(ad, "alloc-dl"))
    handle = drv.start(task)
    assert handle.wait(10) == 0
    local_jar = os.path.join(ad.task_dirs["jvm"], "local", "app.jar")
    assert open(local_jar, "rb").read() == b"PK\x03\x04 fake jar"
    line = [l for l in log.read_text().splitlines() if "-jar" in l][-1]
    assert line == f"java -jar {local_jar} serve"


def test_qemu_driver_artifact_url_checksum(tmp_path, fake_bin,
                                           artifact_server):
    """?checksum= on the artifact URL is honored (go-getter convention,
    reference client/driver/qemu.go:95-150)."""
    import hashlib

    install, log = fake_bin
    install("qemu-system-x86_64")
    adir, base = artifact_server
    (adir / "disk.img").write_bytes(b"qcow2-bytes")
    digest = hashlib.sha256(b"qcow2-bytes").hexdigest()

    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="vm", driver="qemu",
                config={"artifact_source":
                        f"{base}/disk.img?checksum=sha256:{digest}"},
                resources=Resources(cpu=500, memory_mb=256))
    ad.build([task])
    drv = BUILTIN_DRIVERS["qemu"](ExecContext(ad, "alloc-qdl"))
    handle = drv.start(task)
    assert handle.wait(10) == 0
    img = os.path.join(ad.task_dirs["vm"], "local", "disk.img")
    assert os.path.exists(img)
    line = [l for l in log.read_text().splitlines()
            if "qemu-system" in l][-1]
    assert f"file={img}" in line


def test_artifact_checksum_mismatch_fails_task(tmp_path, fake_bin,
                                               artifact_server):
    """A bad digest rejects the artifact: no file left behind, start
    raises (surfaced as a task error by the TaskRunner)."""
    from nomad_tpu.client.artifact import ArtifactError
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    install, _log = fake_bin
    install("qemu-system-x86_64")
    adir, base = artifact_server
    (adir / "disk.img").write_bytes(b"tampered-bytes")

    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="vm", driver="qemu",
                config={"artifact_source": f"{base}/disk.img",
                        "checksum": "sha256:" + "0" * 64},
                resources=Resources(cpu=500, memory_mb=256))
    ad.build([task])
    drv = BUILTIN_DRIVERS["qemu"](ExecContext(ad, "alloc-bad"))
    with pytest.raises(ArtifactError, match="checksum mismatch"):
        drv.start(task)
    assert not os.path.exists(
        os.path.join(ad.task_dirs["vm"], "local", "disk.img"))


def test_artifact_fetch_error_is_task_error(tmp_path, fake_bin,
                                            artifact_server):
    from nomad_tpu.client.artifact import ArtifactError
    from nomad_tpu.client.driver import BUILTIN_DRIVERS

    install, _log = fake_bin
    install("java")
    _adir, base = artifact_server
    ad = AllocDir(str(tmp_path / "alloc"))
    task = Task(name="jvm", driver="java",
                config={"artifact_source": f"{base}/missing.jar"},
                resources=Resources(cpu=100, memory_mb=128))
    ad.build([task])
    drv = BUILTIN_DRIVERS["java"](ExecContext(ad, "alloc-404"))
    with pytest.raises(ArtifactError, match="failed to fetch"):
        drv.start(task)


def test_artifact_keeps_presigned_query(tmp_path, artifact_server):
    """Only the checksum query parameter is stripped from the download
    URL — presigned/tokenized query strings survive."""
    import hashlib
    import http.server
    import threading as _threading

    from nomad_tpu.client.artifact import fetch_artifact

    seen = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            seen["path"] = self.path
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"payload")

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        digest = hashlib.sha256(b"payload").hexdigest()
        url = (f"http://127.0.0.1:{httpd.server_address[1]}/f.bin"
               f"?X-Amz-Signature=tok123&checksum=sha256:{digest}")
        dest = fetch_artifact(url, str(tmp_path / "dl"))
        assert open(dest, "rb").read() == b"payload"
        assert "X-Amz-Signature=tok123" in seen["path"]
        assert "checksum" not in seen["path"]
    finally:
        httpd.shutdown()
