"""State store tests (parity targets: nomad/state/state_store_test.go)."""
import pytest

from nomad_tpu import mock
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_RUNNING,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
)


def test_upsert_node_and_indexes():
    s = StateStore()
    n = mock.node()
    s.upsert_node(1000, n)
    out = s.node_by_id(n.id)
    assert out is not None
    assert out.create_index == 1000 and out.modify_index == 1000
    assert s.get_index("nodes") == 1000
    # stored object is a copy, original mutation does not leak
    n.status = "bogus"
    assert s.node_by_id(n.id).status == NODE_STATUS_READY


def test_update_node_status_and_drain():
    s = StateStore()
    n = mock.node()
    s.upsert_node(1000, n)
    s.update_node_status(1001, n.id, NODE_STATUS_DOWN)
    assert s.node_by_id(n.id).status == NODE_STATUS_DOWN
    assert s.node_by_id(n.id).modify_index == 1001
    s.update_node_drain(1002, n.id, True)
    assert s.node_by_id(n.id).drain is True
    with pytest.raises(ValueError):
        s.update_node_status(1003, n.id, "bogus")
    with pytest.raises(KeyError):
        s.update_node_status(1003, "missing", NODE_STATUS_READY)


def test_snapshot_isolation():
    s = StateStore()
    n1 = mock.node()
    s.upsert_node(1000, n1)
    snap = s.snapshot()

    n2 = mock.node()
    s.upsert_node(1001, n2)
    s.delete_node(1002, n1.id)

    # snapshot still sees the old world
    assert snap.node_by_id(n1.id) is not None
    assert snap.node_by_id(n2.id) is None
    assert snap.get_index("nodes") == 1000
    # live store sees the new world
    assert s.node_by_id(n1.id) is None
    assert s.node_by_id(n2.id) is not None
    assert s.get_index("nodes") == 1002


def test_snapshot_isolation_secondary_indexes():
    s = StateStore()
    a = mock.alloc()
    s.upsert_allocs(1000, [a])
    snap = s.snapshot()

    a2 = mock.alloc()
    a2.node_id = a.node_id
    s.upsert_allocs(1001, [a2])

    assert len(snap.allocs_by_node(a.node_id)) == 1
    assert len(s.allocs_by_node(a.node_id)) == 2


def test_upsert_allocs_preserves_client_fields():
    s = StateStore()
    a = mock.alloc()
    s.upsert_allocs(1000, [a])

    client_view = s.alloc_by_id(a.id).copy()
    client_view.client_status = ALLOC_CLIENT_STATUS_RUNNING
    client_view.client_description = "up"
    s.update_alloc_from_client(1001, client_view)

    # A scheduler rewrite must not clobber the client-authoritative fields
    sched_view = a.copy()
    sched_view.client_status = "pending"
    s.upsert_allocs(1002, [sched_view])
    out = s.alloc_by_id(a.id)
    assert out.client_status == ALLOC_CLIENT_STATUS_RUNNING
    assert out.client_description == "up"
    assert out.create_index == 1000 and out.modify_index == 1002


def test_update_alloc_from_client_missing():
    s = StateStore()
    with pytest.raises(KeyError):
        s.update_alloc_from_client(1000, mock.alloc())


def test_evals_by_job_and_reap():
    s = StateStore()
    ev = mock.eval()
    s.upsert_evals(1000, [ev])
    assert [e.id for e in s.evals_by_job(ev.job_id)] == [ev.id]

    a = mock.alloc()
    a.eval_id = ev.id
    s.upsert_allocs(1001, [a])
    assert [x.id for x in s.allocs_by_eval(ev.id)] == [a.id]

    s.delete_eval(1002, [ev.id], [a.id])
    assert s.eval_by_id(ev.id) is None
    assert s.alloc_by_id(a.id) is None
    assert s.evals_by_job(ev.job_id) == []
    assert s.allocs_by_node(a.node_id) == []


def test_watch_notification():
    s = StateStore()
    ev = s.watch.watch(("nodes",))
    assert not ev.is_set()
    s.upsert_node(1000, mock.node())
    assert ev.is_set()

    a = mock.alloc()
    node_ev = s.watch.watch(("alloc-node", a.node_id))
    other_ev = s.watch.watch(("alloc-node", "other"))
    s.upsert_allocs(1001, [a])
    assert node_ev.is_set()
    assert not other_ev.is_set()


def test_restore_swaps_world():
    s = StateStore()
    s.upsert_node(5, mock.node())
    snap = s.snapshot()

    r = s.restore()
    n = mock.node()
    j = mock.job()
    ev = mock.eval()
    a = mock.alloc()
    n.modify_index = 100
    r.node_restore(n)
    r.job_restore(j)
    r.eval_restore(ev)
    r.alloc_restore(a)
    r.index_restore("nodes", 100)
    r.commit()

    assert s.node_by_id(n.id) is not None
    assert s.job_by_id(j.id) is not None
    assert [e.id for e in s.evals_by_job(ev.job_id)] == [ev.id]
    assert [x.id for x in s.allocs_by_job(a.job_id)] == [a.id]
    assert s.get_index("nodes") == 100
    # pre-restore snapshot still intact
    assert len(list(snap.nodes())) == 1


def test_latest_index():
    s = StateStore()
    s.upsert_node(7, mock.node())
    s.upsert_job(9, mock.job())
    assert s.latest_index() == 9


def test_scheduling_never_mutates_store_objects():
    """The race-safety cornerstone (reference state_store.go:17-19):
    every object the store returns is treated as immutable by the
    schedulers.  Deep-serialize the cluster, run generic + system evals
    (device and sequential paths, placements and failures), and assert
    the stored objects' serialized forms are bit-identical.  (Scheduler
    memo caches annotate job.__dict__ with private keys; the dataclass
    fields — the shared contract — must never move.)"""
    import nomad_tpu.mock as mock
    from nomad_tpu.scheduler import Harness
    from nomad_tpu.structs import (Constraint, Evaluation,
                                   generate_uuid)

    h = Harness()
    for i in range(8):
        h.state.upsert_node(h.next_index(), mock.node(i))
    jobs = []
    for k in range(3):
        j = mock.job()
        j.task_groups[0].count = 4
        if k == 2:  # one job that fails everywhere
            j.task_groups[0].constraints = [
                Constraint(hard=True, l_target="$attr.kernel.name",
                           r_target="plan9", operand="=")]
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)
    sysjob = mock.system_job()
    h.state.upsert_job(h.next_index(), sysjob)

    def frozen():
        return {
            "nodes": {n.id: n.to_dict() for n in h.state.nodes()},
            "jobs": {j.id: j.to_dict() for j in h.state.jobs()},
        }

    def make_eval(job):
        return Evaluation(id=generate_uuid(), priority=job.priority,
                          type=job.type, triggered_by="job-register",
                          job_id=job.id)

    before = frozen()
    for j in jobs:
        h.process("jax-binpack", make_eval(j))
        h.process("service", make_eval(j))
    h.process("system", make_eval(sysjob))
    h.process("system-seq", make_eval(sysjob))
    assert frozen() == before, "a scheduler mutated a store object"
