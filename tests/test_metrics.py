"""Telemetry suite (utils/metrics.py): InmemSink aggregation + snapshot
shape, the statsd UDP wire format against a real bound socket, timer
plumbing, and the multi-sink fanout — the go-metrics capability set the
reference wires in command/agent/command.go:487-533."""
from __future__ import annotations

import socket
import time

from nomad_tpu.utils.metrics import InmemSink, Metrics, StatsdSink


def test_inmem_sink_aggregates():
    sink = InmemSink()
    sink.incr_counter("nomad.rpc.query", 1)
    sink.incr_counter("nomad.rpc.query", 2)
    sink.set_gauge("nomad.broker.ready", 7)
    sink.set_gauge("nomad.broker.ready", 3)  # last write wins
    for v in (0.1, 0.2, 0.3):
        sink.add_sample("nomad.plan.evaluate", v)
    snap = sink.snapshot()
    assert snap["counters"]["nomad.rpc.query"] == 3
    assert snap["gauges"]["nomad.broker.ready"] == 3
    s = snap["samples"]["nomad.plan.evaluate"]
    assert s["count"] == 3
    assert abs(s["mean"] - 0.2) < 1e-9
    assert s["max"] == 0.3


def test_inmem_sample_ring_bounded():
    sink = InmemSink()
    for i in range(5000):
        sink.add_sample("k", float(i))
    assert sink.snapshot()["samples"]["k"]["count"] == 4096


def test_inmem_samples_are_interval_windowed():
    """ISSUE 10 satellite: percentiles age OUT — a latency spike from
    many intervals ago must not pin the reported p99 forever (the old
    sink was forever-cumulative)."""
    now = [0.0]
    sink = InmemSink(interval=10.0, retain=3, clock=lambda: now[0])
    sink.add_sample("lat", 9.0)       # the ancient spike
    now[0] = 15.0
    for _ in range(10):
        sink.add_sample("lat", 0.001)
    s = sink.snapshot()["samples"]["lat"]
    assert s["count"] == 11 and s["p99"] == 9.0  # spike still in window
    now[0] = 45.0   # both earlier windows aged past the 3-interval horizon
    sink.add_sample("lat", 0.002)
    s = sink.snapshot()["samples"]["lat"]
    assert s["max"] < 1.0, "stale p99 never aged out"
    assert s["count"] == 1            # only the live window reports


def test_inmem_windows_age_out_on_read_too():
    """A key nobody samples anymore still drops off the summary once
    its windows pass out of the retained horizon."""
    now = [0.0]
    sink = InmemSink(interval=10.0, retain=2, clock=lambda: now[0])
    sink.add_sample("old", 1.0)
    now[0] = 100.0
    assert "old" not in sink.snapshot()["samples"]


def test_statsd_wire_format():
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(2.0)
    try:
        sink = StatsdSink(rx.getsockname())
        sink.incr_counter("nomad.worker.dequeue", 1.0)
        sink.set_gauge("nomad.broker.ready", 4.0)
        sink.add_sample("nomad.plan.apply", 0.25)
        got = {rx.recv(1024).decode() for _ in range(3)}
        assert "nomad.worker.dequeue:1.0|c" in got
        assert "nomad.broker.ready:4.0|g" in got
        assert "nomad.plan.apply:250.000|ms" in got
    finally:
        rx.close()


def test_metrics_fanout_and_timer():
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(2.0)
    try:
        m = Metrics()
        host, port = rx.getsockname()
        m.add_statsd(host, port)
        with m.timer("nomad.test.op"):
            time.sleep(0.01)  # sleep-ok: the timed workload itself
        # Both sinks saw the sample.
        snap = m.inmem.snapshot()
        assert snap["samples"]["nomad.test.op"]["count"] == 1
        assert snap["samples"]["nomad.test.op"]["max"] >= 0.01
        wire = rx.recv(1024).decode()
        assert wire.startswith("nomad.test.op:") and wire.endswith("|ms")

        m.incr_counter("nomad.test.count")
        assert m.inmem.snapshot()["counters"]["nomad.test.count"] == 1
        assert rx.recv(1024).decode() == "nomad.test.count:1.0|c"
    finally:
        rx.close()


def test_statsd_send_failure_is_silent():
    # A closed socket must never raise into the measured code path.
    sink = StatsdSink(("127.0.0.1", 9))
    sink.sock.close()
    sink.incr_counter("k", 1)  # no exception
