"""Golden parity: vectorized system scheduler vs the sequential
iterator-chain SystemScheduler ("system-seq") on identical states."""
from __future__ import annotations

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    Constraint,
    Evaluation,
    NetworkResource,
    Resources,
    allocs_fit,
)


def sys_eval(job, trigger=EVAL_TRIGGER_JOB_REGISTER):
    return Evaluation(id=f"ev-{id(job)}-{trigger}", priority=job.priority,
                      type="system", triggered_by=trigger, job_id=job.id)


def plan_summary(plan):
    """Comparable plan shape: node -> (tg names), failed count, scores."""
    placed = {}
    for node_id, allocs in plan.node_allocation.items():
        placed[node_id] = sorted((a.task_group, a.name) for a in allocs)
    return placed, len(plan.failed_allocs)


def build_cluster(h: Harness, n: int, constrained: bool = False):
    nodes = []
    for i in range(n):
        node = mock.node(i)
        if constrained and i % 3 == 0:
            node.attributes["kernel.name"] = "windows"
        h.state.upsert_node(h.next_index(), node)
        nodes.append(node)
    return nodes


def run_both(n_nodes: int, job_fn, constrained=False):
    # One set of nodes + one job, fed to both harnesses, so ids line up.
    proto = Harness()
    nodes = build_cluster(proto, n_nodes, constrained)
    job = job_fn()
    plans = []
    for sched in ("system", "system-seq"):
        h = Harness()
        for n in nodes:
            h.state.upsert_node(h.next_index(), n)
        h.state.upsert_job(h.next_index(), job)
        h.process(sched, sys_eval(job))
        assert h.plans, sched
        plans.append((h, h.plans[-1]))
    return plans


def test_system_parity_simple():
    (h1, p1), (h2, p2) = run_both(17, mock.system_job)
    assert plan_summary(p1) == plan_summary(p2)
    placed, failed = plan_summary(p1)
    assert len(placed) == 17 and failed == 0


def test_system_parity_constrained_nodes():
    (h1, p1), (h2, p2) = run_both(20, mock.system_job, constrained=True)
    assert plan_summary(p1) == plan_summary(p2)
    placed, _ = plan_summary(p1)
    # Only linux nodes take the job (mock system job requires linux).
    assert len(placed) == 20 - 7


def test_system_parity_multi_tg_and_network():
    def job_fn():
        j = mock.system_job()
        tg2 = j.task_groups[0].copy()
        tg2.name = "sidecar"
        tg2.tasks[0].resources = Resources(
            cpu=64, memory_mb=32,
            networks=[NetworkResource(mbits=4, dynamic_ports=["metrics"])])
        j.task_groups.append(tg2)
        return j

    (h1, p1), (h2, p2) = run_both(9, job_fn)
    assert plan_summary(p1) == plan_summary(p2)
    placed, failed = plan_summary(p1)
    assert failed == 0
    assert all(len(v) == 2 for v in placed.values())
    # Dynamic ports actually assigned, unique per node.
    for node_id, allocs in p1.node_allocation.items():
        ports = []
        for a in allocs:
            for tr in a.task_resources.values():
                for net in tr.networks:
                    ports.extend(net.reserved_ports)
        assert len(ports) == len(set(ports))


def test_system_parity_exhaustion():
    """Nodes too small for the ask fail identically on both paths."""
    def job_fn():
        j = mock.system_job()
        j.task_groups[0].tasks[0].resources = Resources(
            cpu=100_000, memory_mb=64)
        return j

    (h1, p1), (h2, p2) = run_both(5, job_fn)
    assert plan_summary(p1) == plan_summary(p2)
    placed, failed = plan_summary(p1)
    assert not placed
    # One failed alloc, the rest coalesced (both paths coalesce).
    assert failed == 1
    assert p1.failed_allocs[0].metrics.coalesced_failures == \
        p2.failed_allocs[0].metrics.coalesced_failures == 4


def test_system_vec_plans_fit_and_scores_match_seq():
    h = Harness()
    nodes = build_cluster(h, 8)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    h.process("system", sys_eval(job))
    plan = h.plans[-1]
    by_id = {n.id: n for n in nodes}
    for node_id, allocs in plan.node_allocation.items():
        fit, dim, _ = allocs_fit(by_id[node_id], allocs)
        assert fit, dim
        for a in allocs:
            assert a.metrics.scores  # binpack score recorded

    # Same state through the sequential path: identical score values.
    h2 = Harness()
    for n in nodes:
        h2.state.upsert_node(h2.next_index(), n)
    h2.state.upsert_job(h2.next_index(), job)
    h2.process("system-seq", sys_eval(job))
    p2 = h2.plans[-1]
    s1 = {nid: sorted(a.metrics.scores.values())
          for nid, al in plan.node_allocation.items() for a in al
          for nid in [nid]}
    s2 = {nid: sorted(a.metrics.scores.values())
          for nid, al in p2.node_allocation.items() for a in al
          for nid in [nid]}
    assert set(s1) == set(s2)
    for nid in s1:
        assert s1[nid] == pytest.approx(s2[nid], abs=1e-4)


def test_system_vec_node_update_migrates():
    """Node-update trigger: down node's allocs stop; new node gets one."""
    h = Harness()
    nodes = build_cluster(h, 4)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)
    h.process("system", sys_eval(job))
    # Apply plan to state (harness does), then drain one node.
    nodes[0].drain = True
    h.state.upsert_node(h.next_index(), nodes[0])
    h.process("system", sys_eval(job, EVAL_TRIGGER_NODE_UPDATE))
    plan = h.plans[-1]
    stopped = [a for allocs in plan.node_update.values() for a in allocs]
    assert any(a.node_id == nodes[0].id for a in stopped)


def test_system_parity_count_gt_one_tight_node():
    """System TG with count > 1 on nodes that fit only one copy: the
    batched fit must not check both copies against pre-accumulation
    usage (regression: numpy fancy-index add collapsed the duplicate
    node rows, oversubscribing every node)."""
    def job_fn():
        j = mock.system_job()
        tg = j.task_groups[0]
        tg.count = 2
        # One copy fits a mock node (4000 cpu / 8192 mb); two do not.
        tg.tasks[0].resources = Resources(cpu=2500, memory_mb=5000)
        return j

    (h1, p1), (h2, p2) = run_both(5, job_fn)
    s1, f1 = plan_summary(p1)
    s2, f2 = plan_summary(p2)
    assert s1 == s2
    assert f1 == f2
    # Every node fits exactly one copy (mock nodes have limited cpu/mem).
    for node_id, placed in s1.items():
        node = h1.state.node_by_id(node_id)
        allocs = [a for al in p1.node_allocation.values() for a in al
                  if a.node_id == node_id]
        fit, _dim, _util = allocs_fit(node, allocs)
        assert fit, f"oversubscribed node {node_id}: {placed}"


def test_system_vec_failures_carry_explanations():
    """A mask-rejected system placement's failed alloc carries the
    node's actual constraint verdict, same as the sequential chain
    (the vectorized path patches the first failure per task group)."""
    def job_fn():
        j = mock.system_job()
        j.task_groups[0].constraints = [
            Constraint(hard=True, l_target="$attr.kernel.name",
                       r_target="plan9", operand="=")]
        return j

    (h_vec, plan_vec), (h_seq, plan_seq) = run_both(4, job_fn)
    for plan in (plan_vec, plan_seq):
        assert plan.failed_allocs
        m = plan.failed_allocs[0].metrics
        assert sum(m.constraint_filtered.values()) >= 1, (
            m.constraint_filtered)
