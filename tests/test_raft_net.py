"""Multi-server raft tests: election, replication, forwarding, failover.

Parity with the reference's in-process multi-server integration rig
(nomad/server_test.go testServer + testJoin): full servers on loopback
ports with aggressively tightened raft timings.
"""
from __future__ import annotations

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool

FAST = dict(
    raft_mode="net",
    raft_election_timeout=(0.05, 0.10),
    raft_heartbeat_interval=0.02,
    num_schedulers=1,
)


def make_cluster(n: int):
    servers = [Server(ServerConfig(**FAST)) for _ in range(n)]
    addrs = [s.rpc_address() for s in servers]
    for s in servers:
        for a in addrs:
            s.raft.add_peer(a)
    return servers


def wait_for_leader(servers, timeout=5.0) -> Server:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [s for s in servers if s.raft.is_leader()]
        if len(leaders) == 1 and leaders[0].is_leader():
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no single leader elected")


def wait_until(fn, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def pool():
    p = ConnPool()
    yield p
    p.shutdown()


def test_single_node_self_elects():
    s = Server(ServerConfig(**FAST))
    try:
        wait_until(lambda: s.raft.is_leader() and s.is_leader(),
                   msg="self-election")
    finally:
        s.shutdown()
        s.raft.shutdown()


def test_three_node_election_and_replication(pool):
    servers = make_cluster(3)
    try:
        leader = wait_for_leader(servers)
        node = mock.node()
        leader.node_register(node)
        wait_until(
            lambda: all(s.fsm.state.node_by_id(node.id) is not None
                        for s in servers),
            msg="replication to all followers")
    finally:
        for s in servers:
            s.shutdown()
            s.raft.shutdown()


def test_follower_forwards_writes(pool):
    servers = make_cluster(3)
    try:
        leader = wait_for_leader(servers)
        follower = next(s for s in servers if not s.raft.is_leader())
        for i in range(3):
            pool.call(follower.rpc_address(), "Node.Register",
                      {"node": mock.node(i).to_dict()})
        job = mock.job()
        job.task_groups[0].count = 3
        out = pool.call(follower.rpc_address(), "Job.Register",
                        {"job": job.to_dict()})
        assert out["eval_id"]
        leader.wait_for_evals([out["eval_id"]], timeout=15)
        # Allocations replicate everywhere.
        wait_until(
            lambda: all(len(s.fsm.state.allocs_by_job(job.id)) == 3
                        for s in servers),
            msg="alloc replication")
    finally:
        for s in servers:
            s.shutdown()
            s.raft.shutdown()


def test_leader_failover():
    servers = make_cluster(3)
    try:
        leader = wait_for_leader(servers)
        node = mock.node()
        leader.node_register(node)

        # Kill the leader: remaining two must elect a new one.
        survivors = [s for s in servers if s is not leader]
        leader.shutdown()
        leader.raft.shutdown()
        leader.rpc_server.shutdown()
        for s in survivors:
            s.raft.remove_peer(leader.rpc_address())

        new_leader = wait_for_leader(survivors, timeout=10)
        assert new_leader is not leader
        # Replicated state survives the failover; prior-term entries apply
        # once the new leader commits its own-term no-op.
        wait_until(
            lambda: new_leader.fsm.state.node_by_id(node.id) is not None,
            msg="committed entry visible on new leader")
        # And the new leader can make progress.
        node2 = mock.node(2)
        new_leader.node_register(node2)
        wait_until(
            lambda: all(s.fsm.state.node_by_id(node2.id) is not None
                        for s in survivors),
            msg="post-failover replication")
    finally:
        for s in servers:
            try:
                s.shutdown()
                s.raft.shutdown()
            except Exception:
                pass


def test_net_raft_durability(tmp_path):
    """Term/vote metadata and log entries survive a restart (raft safety)."""
    cfg = dict(FAST)
    cfg["data_dir"] = str(tmp_path)
    s = Server(ServerConfig(**cfg))
    try:
        wait_until(lambda: s.raft.is_leader(), msg="election")
        node = mock.node()
        s.node_register(node)
        term_before = s.raft._term
    finally:
        s.shutdown()

    s2 = Server(ServerConfig(**cfg))
    try:
        # Persisted term is restored (never moves backwards).
        assert s2.raft._term >= term_before
        wait_until(lambda: s2.raft.is_leader(), msg="re-election")
        # Replayed log is reapplied once the new term commits.
        wait_until(lambda: s2.fsm.state.node_by_id(node.id) is not None,
                   msg="log replay apply")
    finally:
        s2.shutdown()
