"""Multi-server raft tests: election, replication, forwarding, failover.

Parity with the reference's in-process multi-server integration rig
(nomad/server_test.go testServer + testJoin): full servers on loopback
ports with aggressively tightened raft timings.
"""
from __future__ import annotations

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool

from tests.conftest import wait_until

FAST = dict(
    raft_mode="net",
    raft_election_timeout=(0.05, 0.10),
    raft_heartbeat_interval=0.02,
    num_schedulers=1,
)


def make_cluster(n: int):
    servers = [Server(ServerConfig(**FAST)) for _ in range(n)]
    addrs = [s.rpc_address() for s in servers]
    for s in servers:
        for a in addrs:
            s.raft.add_peer(a)
    return servers


def wait_for_leader(servers, timeout=5.0) -> Server:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [s for s in servers if s.raft.is_leader()]
        if len(leaders) == 1 and leaders[0].is_leader():
            return leaders[0]
        time.sleep(0.02)  # sleep-ok: poll interval of the bounded wait
    raise AssertionError("no single leader elected")


def wait_for_stable_leader(servers, timeout=30.0,
                           stable_polls=5) -> Server:
    """A leader that HOLDS leadership across ``stable_polls``
    consecutive observations.  Under host load, election RPCs and
    ticker threads get starved and leadership can flap between
    wait_for_leader's single-instant polls — the documented chaos-soak
    leader-flap flake.  The soak tests need a leader that survived a
    whole observation window, with a load-tolerant deadline, not a
    lucky single sample."""
    deadline = time.monotonic() + timeout
    candidate, streak = None, 0
    while time.monotonic() < deadline:
        leaders = [s for s in servers if s.raft.is_leader()]
        if len(leaders) == 1 and leaders[0].is_leader():
            if leaders[0] is candidate:
                streak += 1
                if streak >= stable_polls:
                    return candidate
            else:
                candidate, streak = leaders[0], 1
        else:
            candidate, streak = None, 0
        time.sleep(0.05)  # sleep-ok: poll interval of the bounded wait
    raise AssertionError("no stable single leader within "
                         f"{timeout}s (last candidate {candidate})")


@pytest.fixture
def pool():
    p = ConnPool()
    yield p
    p.shutdown()


def test_single_node_self_elects():
    s = Server(ServerConfig(**FAST))
    try:
        wait_until(lambda: s.raft.is_leader() and s.is_leader(),
                   msg="self-election")
    finally:
        s.shutdown()
        s.raft.shutdown()


def test_three_node_election_and_replication(pool):
    servers = make_cluster(3)
    try:
        leader = wait_for_leader(servers)
        node = mock.node()
        leader.node_register(node)
        wait_until(
            lambda: all(s.fsm.state.node_by_id(node.id) is not None
                        for s in servers),
            msg="replication to all followers")
    finally:
        for s in servers:
            s.shutdown()
            s.raft.shutdown()


def _call_retry(pool, addr, method, args, timeout=10.0):
    """RPC with retry across leadership churn: the tight test timings
    (50-100ms elections) can drop leadership mid-call under host load;
    real clients retry exactly like this."""
    from nomad_tpu.server.rpc import RPCError

    deadline = time.monotonic() + timeout
    while True:
        try:
            return pool.call(addr, method, args)
        except RPCError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.1)  # sleep-ok: poll interval of the bounded retry


def test_follower_forwards_writes(pool):
    servers = make_cluster(3)
    try:
        wait_for_leader(servers)
        follower = next(s for s in servers if not s.raft.is_leader())
        for i in range(3):
            _call_retry(pool, follower.rpc_address(), "Node.Register",
                        {"node": mock.node(i).to_dict()})
        job = mock.job()
        job.task_groups[0].count = 3
        out = _call_retry(pool, follower.rpc_address(), "Job.Register",
                          {"job": job.to_dict()})
        assert out["eval_id"]
        # Eval completion may migrate across a mid-test re-election;
        # watch replicated state rather than one server's broker.
        wait_until(
            lambda: all(len(s.fsm.state.allocs_by_job(job.id)) == 3
                        for s in servers),
            timeout=20, msg="alloc replication")
    finally:
        for s in servers:
            s.shutdown()
            s.raft.shutdown()


def test_stale_reads_serve_locally_on_follower(pool):
    """A read with ``stale`` set is answered from the follower's own
    snapshot — never forwarded (reference nomad/rpc.go forward +
    structs.QueryOptions.AllowStale).  Non-stale follower reads forward
    to the leader."""
    servers = make_cluster(3)
    try:
        leader = wait_for_leader(servers)
        node = mock.node()
        leader.node_register(node)
        follower = next(s for s in servers if not s.raft.is_leader())
        wait_until(lambda: follower.fsm.state.node_by_id(node.id)
                   is not None, msg="replication to follower")

        # Any forward attempt from the follower must blow up loudly.
        def boom(*a, **kw):
            raise AssertionError("stale read was forwarded")
        orig_call = follower.conn_pool.call
        follower.conn_pool.call = boom
        try:
            out = pool.call(follower.rpc_address(), "Node.GetNode",
                            {"node_id": node.id, "stale": True})
            assert out["node"]["id"] == node.id
            assert out["known_leader"] is True
            # Without stale, the same read needs the leader: the
            # sabotaged pool surfaces as an RPC error.
            from nomad_tpu.server.rpc import RPCError
            with pytest.raises(RPCError):
                pool.call(follower.rpc_address(), "Node.GetNode",
                          {"node_id": node.id})
        finally:
            follower.conn_pool.call = orig_call
    finally:
        for s in servers:
            s.shutdown()
            s.raft.shutdown()


def test_leader_failover():
    from nomad_tpu.structs import Evaluation, generate_uuid

    servers = make_cluster(3)
    try:
        leader = wait_for_leader(servers)
        node = mock.node()
        leader.node_register(node)
        # A committed pending eval of a scheduler type no worker
        # consumes: it must survive the failover INSIDE the new
        # leader's broker (leadership-restore re-enqueue), not just in
        # state.
        parked_eval = Evaluation(
            id=generate_uuid(), priority=50, type="exotic",
            triggered_by="test", job_id="parked-job", status="pending")
        leader.apply_eval_update([parked_eval])

        # Kill the leader: remaining two must elect a new one.
        survivors = [s for s in servers if s is not leader]
        leader.shutdown()
        leader.raft.shutdown()
        leader.rpc_server.shutdown()
        for s in survivors:
            s.raft.remove_peer(leader.rpc_address())

        new_leader = wait_for_leader(survivors, timeout=10)
        assert new_leader is not leader
        # Replicated state survives the failover; prior-term entries apply
        # once the new leader commits its own-term no-op.
        wait_until(
            lambda: new_leader.fsm.state.node_by_id(node.id) is not None,
            msg="committed entry visible on new leader")
        # ISSUE 8 satellite: post-failover leader bring-up actually
        # repopulates the leader-only machinery on the NEW leader —
        # HeartbeatManager.initialize re-arms every live node at the
        # failover TTL, and the broker restore re-enqueues the
        # committed pending eval.
        wait_until(lambda: new_leader.heartbeats.active() >= 1,
                   msg="heartbeat timers re-armed on new leader")
        wait_until(
            lambda: any(e.id == parked_eval.id
                        for q in new_leader.eval_broker._ready.values()
                        for *_prio, e in q._heap),
            msg="pending eval restored into new leader's broker")
        # And the new leader can make progress.
        node2 = mock.node(2)
        new_leader.node_register(node2)
        wait_until(
            lambda: all(s.fsm.state.node_by_id(node2.id) is not None
                        for s in survivors),
            msg="post-failover replication")
    finally:
        for s in servers:
            try:
                s.shutdown()
                s.raft.shutdown()
            except Exception:
                pass


def test_net_raft_durability(tmp_path):
    """Term/vote metadata and log entries survive a restart (raft safety)."""
    cfg = dict(FAST)
    cfg["data_dir"] = str(tmp_path)
    s = Server(ServerConfig(**cfg))
    try:
        wait_until(lambda: s.raft.is_leader(), msg="election")
        node = mock.node()
        s.node_register(node)
        term_before = s.raft._term
    finally:
        s.shutdown()

    s2 = Server(ServerConfig(**cfg))
    try:
        # Persisted term is restored (never moves backwards).
        assert s2.raft._term >= term_before
        wait_until(lambda: s2.raft.is_leader(), msg="re-election")
        # Replayed log is reapplied once the new term commits.
        wait_until(lambda: s2.fsm.state.node_by_id(node.id) is not None,
                   msg="log replay apply")
    finally:
        s2.shutdown()


def test_net_raft_compaction_survives_restart(tmp_path):
    """Log compaction persists the snapshot to disk: a full restart after
    the durable log was truncated must restore the FSM from the snapshot
    file, not silently come up empty (reference FileSnapshotStore role)."""
    cfg = dict(FAST)
    cfg["data_dir"] = str(tmp_path)
    cfg["raft_snapshot_threshold"] = 8
    s = Server(ServerConfig(**cfg))
    nodes = [mock.node(i) for i in range(12)]
    try:
        wait_until(lambda: s.raft.is_leader(), msg="election")
        for n in nodes:
            s.node_register(n)
        # Enough applies to cross the threshold and truncate the log.
        wait_until(lambda: s.raft._log_base_index > 0, msg="compaction")
    finally:
        s.shutdown()

    s2 = Server(ServerConfig(**cfg))
    try:
        # State is restored from the persisted snapshot immediately (the
        # truncated log alone can no longer rebuild it).
        assert s2.raft._last_applied >= 8
        wait_until(lambda: s2.raft.is_leader(), msg="re-election")
        wait_until(
            lambda: all(s2.fsm.state.node_by_id(n.id) is not None
                        for n in nodes),
            msg="full state after snapshot restore + log tail replay")
    finally:
        s2.shutdown()


class _StubRPC:
    address = ("127.0.0.1", 0)

    def register(self, name, fn):
        pass


class _RecordingFSM:
    def __init__(self):
        self.applied = []

    def apply(self, index, data):
        self.applied.append((index, bytes(data)))

    def snapshot(self):
        return b"snap"

    def restore(self, blob):
        pass


def test_net_raft_replay_is_last_writer_wins(tmp_path):
    """A record re-appended at an existing index marks a follower conflict
    truncation; boot replay must take the LAST record per index or stale
    (possibly uncommitted) entries resurrect under committed ones."""
    from nomad_tpu.server.raft import FileLogStore
    from nomad_tpu.server.raft_net import NetRaft

    store = FileLogStore(str(tmp_path / "raft" / "log.bin"))
    store.append(1, {"t": 1, "d": b"a"})
    store.append(2, {"t": 1, "d": b"stale"})
    store.append(3, {"t": 1, "d": b"stale2"})
    # Conflict truncation at index 2: leader of term 2 rewrites the suffix.
    store.append(2, {"t": 2, "d": b"B"})
    store.append(3, {"t": 2, "d": b"C"})
    store.append(4, {"t": 2, "d": b"D"})
    store.close()

    raft = NetRaft(_RecordingFSM(), _StubRPC(), None,
                   election_timeout=(30.0, 60.0),
                   data_dir=str(tmp_path))
    try:
        log = [(e["index"], e["term"], bytes(e["data"])) for e in raft._log]
        assert log == [(1, 1, b"a"), (2, 2, b"B"), (3, 2, b"C"),
                       (4, 2, b"D")]
    finally:
        raft.shutdown()


def test_inmem_raft_append_before_apply(tmp_path):
    """Entries are persisted BEFORE the FSM applies them (raft
    discipline, reference raft-boltdb ordering): a failing apply consumes
    its index and leaves a poisoned entry that boot replay skips; the
    in-memory FSM can never run ahead of the durable log."""
    from nomad_tpu.server.raft import FileLogStore, InmemRaft

    class FSM(_RecordingFSM):
        def apply(self, index, data):
            if data == b"boom":
                raise RuntimeError("bad entry")
            super().apply(index, data)

    path = str(tmp_path / "log.bin")
    raft = InmemRaft(FSM(), FileLogStore(path))
    raft.apply(b"one").wait(1)
    bad = raft.apply(b"boom")
    assert bad.error is not None
    raft.apply(b"two").wait(1)
    assert raft.applied_index() == 3
    raft.log_store.close()

    fsm2 = FSM()
    raft2 = InmemRaft(fsm2, FileLogStore(path))
    assert [d for _, d in fsm2.applied] == [b"one", b"two"]
    assert raft2.applied_index() == 3
    raft2.log_store.close()


def test_inmem_raft_disk_failure_rejects_before_apply(tmp_path):
    """A failing durable append rejects the entry with NO state moved:
    the FSM is untouched and the index is not consumed."""
    from nomad_tpu.server.raft import FileLogStore, InmemRaft

    class FlakyLog(FileLogStore):
        fail = False

        def append(self, index, entry):
            if self.fail:
                raise OSError("disk full")
            super().append(index, entry)

    fsm = _RecordingFSM()
    log = FlakyLog(str(tmp_path / "log.bin"))
    raft = InmemRaft(fsm, log)
    raft.apply(b"one").wait(1)
    log.fail = True
    fut = raft.apply(b"lost")
    assert isinstance(fut.error, OSError)
    assert raft.applied_index() == 1
    assert [d for _, d in fsm.applied] == [b"one"]
    log.fail = False
    raft.apply(b"two").wait(1)
    assert [d for _, d in fsm.applied] == [b"one", b"two"]
    log.close()


def test_log_rewrite_is_atomic_replacement(tmp_path):
    """FileLogStore.rewrite replaces the log via tmp+rename and appends
    keep working afterwards."""
    import os

    from nomad_tpu.server.raft import FileLogStore

    path = str(tmp_path / "log.bin")
    log = FileLogStore(path)
    for i in range(1, 6):
        log.append(i, f"e{i}".encode())
    log.rewrite((i, f"e{i}".encode()) for i in (4, 5))
    log.append(6, b"e6")
    log.close()
    assert not os.path.exists(path + ".tmp")
    replayed = list(FileLogStore(path).replay())
    assert [(i, bytes(d)) for i, d in replayed] == \
        [(4, b"e4"), (5, b"e5"), (6, b"e6")]


def test_snapshot_legacy_format_and_location(tmp_path):
    """Pre-layout data_dirs restore: bare (unwrapped) snapshot blobs in
    the legacy <data_dir>/snapshots location are found and decoded."""
    from nomad_tpu.server.raft import (
        InmemRaft,
        SnapshotStore,
        resolve_snapshot_dir,
        unwrap_snapshot,
    )

    data_dir = str(tmp_path)
    legacy = SnapshotStore(f"{data_dir}/snapshots")
    legacy.save(7, b"raw-fsm-blob")  # old format: bare blob, no wrapper

    resolved = resolve_snapshot_dir(data_dir)
    assert resolved == f"{data_dir}/snapshots"

    term, blob = unwrap_snapshot(b"raw-fsm-blob")
    assert (term, blob) == (0, b"raw-fsm-blob")

    class FSM(_RecordingFSM):
        restored = None

        def restore(self, blob):
            self.restored = blob

    fsm = FSM()
    raft = InmemRaft(fsm, None, SnapshotStore(resolved))
    assert fsm.restored == b"raw-fsm-blob"
    assert raft.applied_index() == 7

    # Once the current layout has snapshots, it wins.
    import msgpack
    cur = SnapshotStore(f"{data_dir}/raft/snapshots")
    cur.save(9, msgpack.packb((3, b"new-blob"), use_bin_type=True))
    assert resolve_snapshot_dir(data_dir) == f"{data_dir}/raft/snapshots"
    assert unwrap_snapshot(
        msgpack.packb((3, b"new-blob"), use_bin_type=True)) == \
        (3, b"new-blob")


def test_inmem_replay_last_writer_wins_and_torn_tail(tmp_path):
    """Duplicate indexes in the durable log (re-append after a reported
    disk failure whose record nonetheless landed) replay last-writer-wins;
    a torn tail record ends replay cleanly (code-review regression)."""
    from nomad_tpu.server.raft import FileLogStore, InmemRaft

    path = str(tmp_path / "log.bin")
    log = FileLogStore(path)
    log.append(1, b"one")
    log.append(2, b"lost-but-landed")
    log.append(2, b"two-retry")
    log.close()
    # Torn tail: a length prefix promising more bytes than exist.
    with open(path, "ab") as fh:
        fh.write((999).to_bytes(4, "big"))
        fh.write(b"partial")

    fsm = _RecordingFSM()
    raft = InmemRaft(fsm, FileLogStore(path))
    assert [(i, bytes(d)) for i, d in fsm.applied] == \
        [(1, b"one"), (2, b"two-retry")]
    assert raft.applied_index() == 2
    raft.log_store.close()
