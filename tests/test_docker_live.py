"""Opportunistic LIVE docker integration: runs real containers through
AllocRunner/TaskRunner when a docker daemon is reachable, and skips
cleanly otherwise — the same gating discipline as the reference's
`dockerIsConnected` (client/driver/docker_test.go:20-60).

Asserts the full driver contract against a real daemon: bind mounts
(/alloc shared dir visible in-container), dynamic-port publishing,
status aggregation through AllocRunner, and container cleanup.
Image: ``busybox`` by default (override with NOMAD_TEST_DOCKER_IMAGE).
"""
from __future__ import annotations

import os
import socket
import subprocess
import time

import pytest

from nomad_tpu.client.alloc_runner import AllocRunner
from nomad_tpu.structs import (
    Allocation,
    Job,
    NetworkResource,
    Resources,
    Task,
    TaskGroup,
    generate_uuid,
)

IMAGE = os.environ.get("NOMAD_TEST_DOCKER_IMAGE", "busybox")

_READY: list = []  # memoized verdict, evaluated lazily at first test


def _docker_ready() -> bool:
    """Daemon reachable AND the test image present or pullable.  Every
    subprocess call is timeout-bounded and exception-guarded so a hung
    daemon or slow registry yields a SKIP, never a collection error."""
    if _READY:
        return _READY[0]
    ok = False
    try:
        out = subprocess.run(["docker", "version", "--format",
                              "{{.Server.Version}}"],
                             capture_output=True, text=True, timeout=5)
        if out.returncode == 0:
            have = subprocess.run(["docker", "image", "inspect", "-f",
                                   "{{.Id}}", IMAGE],
                                  capture_output=True, timeout=10)
            if have.returncode == 0:
                ok = True
            else:
                pull = subprocess.run(["docker", "pull", IMAGE],
                                      capture_output=True, timeout=120)
                ok = pull.returncode == 0
    except Exception:
        ok = False
    _READY.append(ok)
    return ok


# Lazy condition (string-less callable form would run at collection;
# a deferred fixture keeps the probe out of `pytest tests/` entirely
# unless these tests are selected).
@pytest.fixture
def docker_or_skip():
    if not _docker_ready():
        pytest.skip("docker daemon not reachable (reference skips the "
                    "same way, docker_test.go:20-60)")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _docker_alloc(command: list, port: int | None = None) -> Allocation:
    task = Task(
        name="web", driver="docker",
        config={"image": IMAGE, "command": command[0],
                "args": command[1:]},
        resources=Resources(cpu=100, memory_mb=64),
    )
    tg = TaskGroup(name="web", count=1, tasks=[task])
    job = Job(id=generate_uuid(), name="live-docker", type="service",
              task_groups=[tg])
    nets = []
    if port is not None:
        # The scheduler's offer shape: assigned dynamic ports land in
        # reserved_ports, labels in dynamic_ports (structs/model.py
        # map_dynamic_ports).
        nets = [NetworkResource(device="eth0", ip="127.0.0.1",
                                reserved_ports=[port],
                                dynamic_ports=["http"])]
    return Allocation(
        id=generate_uuid(), node_id="n1", job=job, job_id=job.id,
        task_group="web",
        resources=Resources(cpu=100, memory_mb=64, networks=nets),
        task_resources={"web": Resources(cpu=100, memory_mb=64,
                                         networks=nets)},
        desired_status="run", client_status="pending",
    )


def _wait(cond, timeout=60.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.25)  # sleep-ok: poll interval of the bounded wait
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.mark.slow
def test_live_container_bind_mount_and_exit(tmp_path, docker_or_skip):
    """A real container writes through the /alloc bind mount and exits;
    AllocRunner aggregates to dead and the container is removed."""
    alloc = _docker_alloc(["/bin/sh", "-c", "echo live > /alloc/out.txt"])
    runner = AllocRunner(alloc, str(tmp_path / "alloc"))
    runner.run()
    _wait(lambda: runner.alloc.client_status == "dead",
          msg="container exit")
    out = os.path.join(runner.alloc_dir.shared_dir, "out.txt")
    with open(out) as fh:
        assert fh.read().strip() == "live"  # bind mount worked
    name = f"nomad-{alloc.id[:8]}-web"
    ps = subprocess.run(["docker", "ps", "-a", "--filter",
                         f"name={name}", "--format", "{{.Names}}"],
                        capture_output=True, text=True)
    assert name not in ps.stdout  # cleanup removed the container


@pytest.mark.slow
def test_live_container_port_publish_and_kill(tmp_path, docker_or_skip):
    """A long-running container publishes its assigned dynamic port;
    destroy() stops and removes it."""
    port = _free_port()
    alloc = _docker_alloc(["/bin/sleep", "120"], port=port)
    runner = AllocRunner(alloc, str(tmp_path / "alloc"))
    runner.run()
    name = f"nomad-{alloc.id[:8]}-web"

    def running():
        out = subprocess.run(["docker", "inspect", "-f",
                              "{{.State.Running}}", name],
                             capture_output=True, text=True)
        return out.stdout.strip() == "true"

    _wait(running, msg="container running")
    ports = subprocess.run(["docker", "port", name],
                           capture_output=True, text=True)
    assert str(port) in ports.stdout  # dynamic port published

    runner.destroy()
    _wait(lambda: not running(), msg="container stopped")
    ps = subprocess.run(["docker", "ps", "-a", "--filter",
                         f"name={name}", "--format", "{{.Names}}"],
                        capture_output=True, text=True)
    assert name not in ps.stdout
