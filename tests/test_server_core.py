"""Server-core tests: broker, plan queue, timetable, FSM, full pipeline."""
from __future__ import annotations

import threading
import time

import pytest

import nomad_tpu.mock as mock
from tests.conftest import wait_until
from nomad_tpu.server import (
    EvalBroker,
    NomadFSM,
    InmemRaft,
    PlanQueue,
    Server,
    ServerConfig,
    TimeTable,
)
from nomad_tpu.structs import Evaluation, Plan, codec, generate_uuid


def make_eval(priority=50, type_="service", job_id=None) -> Evaluation:
    return Evaluation(
        id=generate_uuid(), priority=priority, type=type_,
        job_id=job_id or generate_uuid(), status="pending",
        triggered_by="job-register",
    )


# ---------------------------------------------------------------------------
# EvalBroker
# ---------------------------------------------------------------------------

class TestEvalBroker:
    def test_enqueue_dequeue_priority(self):
        b = EvalBroker(nack_timeout=5, delivery_limit=3)
        b.set_enabled(True)
        low = make_eval(priority=20)
        high = make_eval(priority=90)
        b.enqueue(low)
        b.enqueue(high)
        ev, token = b.dequeue(["service"], timeout=1)
        assert ev.id == high.id
        assert token
        ev2, _ = b.dequeue(["service"], timeout=1)
        assert ev2.id == low.id

    def test_disabled_raises(self):
        b = EvalBroker(5, 3)
        with pytest.raises(RuntimeError):
            b.dequeue(["service"], timeout=0.05)

    def test_per_job_serialization(self):
        b = EvalBroker(5, 3)
        b.set_enabled(True)
        e1 = make_eval(job_id="job-1")
        e2 = make_eval(job_id="job-1")
        b.enqueue(e1)
        b.enqueue(e2)
        ev, token = b.dequeue(["service"], timeout=1)
        assert ev.id == e1.id
        # Second eval for the job is blocked.
        none, _ = b.dequeue(["service"], timeout=0.05)
        assert none is None
        assert b.stats()["total_blocked"] == 1
        # Ack unblocks it.
        b.ack(e1.id, token)
        ev2, _ = b.dequeue(["service"], timeout=1)
        assert ev2.id == e2.id

    def test_nack_requeues_then_fails(self):
        b = EvalBroker(5, delivery_limit=2)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        for _ in range(2):
            got, token = b.dequeue(["service"], timeout=1)
            assert got.id == ev.id
            b.nack(ev.id, token)
        # Past the delivery limit: routed to _failed.
        got, token = b.dequeue(["_failed"], timeout=1)
        assert got.id == ev.id

    def test_nack_timer_fires(self):
        b = EvalBroker(nack_timeout=0.05, delivery_limit=3)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        got, token = b.dequeue(["service"], timeout=1)
        # Event-driven: the timer's auto-nack shows up as a ready eval.
        wait_until(lambda: b.stats()["total_ready"] == 1,
                   msg="nack timer requeue")
        got2, _ = b.dequeue(["service"], timeout=1)
        assert got2.id == ev.id

    def test_wait_delay(self):
        b = EvalBroker(5, 3)
        b.set_enabled(True)
        ev = make_eval()
        ev.wait = 0.08
        b.enqueue(ev)
        none, _ = b.dequeue(["service"], timeout=0.02)
        assert none is None
        got, _ = b.dequeue(["service"], timeout=1)
        assert got.id == ev.id

    def test_dequeue_batch(self):
        b = EvalBroker(5, 3)
        b.set_enabled(True)
        evs = [make_eval() for _ in range(5)]
        for e in evs:
            b.enqueue(e)
        batch = b.dequeue_batch(["service"], max_batch=3, timeout=1)
        assert len(batch) == 3
        assert len({e.id for e, _ in batch}) == 3

    def test_dedup_enqueue(self):
        b = EvalBroker(5, 3)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        b.enqueue(ev)
        b.dequeue(["service"], timeout=1)
        none, _ = b.dequeue(["service"], timeout=0.05)
        assert none is None

    def test_token_mismatch(self):
        b = EvalBroker(5, 3)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        got, token = b.dequeue(["service"], timeout=1)
        with pytest.raises(ValueError):
            b.ack(ev.id, "wrong-token")
        b.ack(ev.id, token)


class TestEvalBrokerEdgeTable:
    """The reference's eval_broker_test.go scenario table
    (/root/reference/nomad/eval_broker_test.go): nack-timer redelivery
    accounting, delivery-limit -> `_failed` lifecycle, token rotation,
    and ordering guarantees."""

    def test_nack_timer_redeliveries_count_toward_limit(self):
        """TestEvalBroker_Nack_Timeout + delivery limit: redeliveries
        caused by the nack TIMER (a worker died silently) are deliveries
        too — enough of them routes the eval to `_failed`, it does not
        ping-pong forever."""
        b = EvalBroker(nack_timeout=0.05, delivery_limit=2)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        for _ in range(2):  # two deliveries, neither acked
            got, _token = b.dequeue(["service"], timeout=1)
            assert got.id == ev.id
            wait_until(lambda: b.stats()["total_ready"] == 1,
                       msg="nack timer requeue")
        # Past the limit: the timer's own nack routed it to _failed.
        got, token = b.dequeue(["_failed"], timeout=1)
        assert got.id == ev.id
        b.ack(ev.id, token)

    def test_token_rotates_on_timer_redelivery(self):
        """After a nack-timer redelivery the OLD delivery token is dead:
        a zombie worker acking with it must be rejected, and
        `outstanding` reports the new token."""
        b = EvalBroker(nack_timeout=0.05, delivery_limit=5)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        _got, token1 = b.dequeue(["service"], timeout=1)
        wait_until(lambda: b.stats()["total_ready"] == 1,
                   msg="nack timer requeue")
        _got2, token2 = b.dequeue(["service"], timeout=1)
        assert token1 != token2
        out_token, ok = b.outstanding(ev.id)
        assert ok and out_token == token2
        with pytest.raises(ValueError):
            b.ack(ev.id, token1)
        b.ack(ev.id, token2)

    def test_failed_queue_ack_releases_job_serialization(self):
        """TestEvalBroker_DeliveryLimit: an eval nacked past the limit is
        dequeued from `_failed` like any queue; acking it releases the
        per-job serialization so the job's NEXT eval flows."""
        b = EvalBroker(nack_timeout=5, delivery_limit=1)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        got, token = b.dequeue(["service"], timeout=1)
        b.nack(ev.id, token)
        # Delivery limit 1: straight to the failed queue.
        stats = b.stats()
        assert stats["by_scheduler"].get("_failed") == 1
        got, token = b.dequeue(["_failed"], timeout=1)
        assert got.id == ev.id
        # While outstanding from _failed, a sibling eval stays blocked.
        ev2 = make_eval(job_id=ev.job_id)
        b.enqueue(ev2)
        assert b.stats()["total_blocked"] == 1
        b.ack(ev.id, token)
        got2, token2 = b.dequeue(["service"], timeout=1)
        assert got2.id == ev2.id
        b.ack(ev2.id, token2)
        assert b.stats()["total_ready"] == 0

    def test_fifo_within_priority(self):
        """TestEvalBroker_Dequeue_FIFO: same priority drains in create
        order (create_index ascending)."""
        b = EvalBroker(5, 3)
        b.set_enabled(True)
        evs = []
        for i in range(5):
            ev = make_eval(priority=50)
            ev.create_index = 100 + i
            evs.append(ev)
        for ev in reversed(evs):  # enqueue newest first on purpose
            b.enqueue(ev)
        got = [b.dequeue(["service"], timeout=1)[0].id for _ in evs]
        assert got == [ev.id for ev in evs]

    def test_blocked_promotion_is_priority_ordered(self):
        """Blocked same-job evals promote highest-priority first when the
        in-flight eval acks (PendingEvaluations heap ordering)."""
        b = EvalBroker(5, 3)
        b.set_enabled(True)
        first = make_eval(priority=50)
        b.enqueue(first)
        low = make_eval(priority=10, job_id=first.job_id)
        high = make_eval(priority=90, job_id=first.job_id)
        b.enqueue(low)
        b.enqueue(high)
        got, token = b.dequeue(["service"], timeout=1)
        assert got.id == first.id
        assert b.stats()["total_blocked"] == 2
        b.ack(first.id, token)
        got2, token2 = b.dequeue(["service"], timeout=1)
        assert got2.id == high.id
        b.ack(high.id, token2)
        got3, token3 = b.dequeue(["service"], timeout=1)
        assert got3.id == low.id
        b.ack(low.id, token3)

    def test_nack_resets_delivery_token_immediately(self):
        """An explicit Nack invalidates the old token synchronously (no
        timer involved) — the redelivered eval carries a fresh one."""
        b = EvalBroker(nack_timeout=5, delivery_limit=3)
        b.set_enabled(True)
        ev = make_eval()
        b.enqueue(ev)
        _got, token1 = b.dequeue(["service"], timeout=1)
        b.nack(ev.id, token1)
        _token, ok = b.outstanding(ev.id)
        assert not ok  # nothing outstanding until redelivered
        _got2, token2 = b.dequeue(["service"], timeout=1)
        assert token2 != token1
        with pytest.raises(ValueError):
            b.ack(ev.id, token1)
        b.ack(ev.id, token2)


# ---------------------------------------------------------------------------
# PlanQueue
# ---------------------------------------------------------------------------

def test_worker_unblocks_when_plan_queue_dies(monkeypatch):
    """A worker awaiting a plan future whose applier died (leadership
    loss mid-pop) must error out once the queue is closed, not block
    forever — a parked worker pins its dispatch's gc_pause for the
    process lifetime (runtime-sanitizer regression)."""
    from nomad_tpu.server import worker as worker_mod

    monkeypatch.setattr(worker_mod, "PLAN_WAIT_POLL", 0.05)
    pq = PlanQueue()
    pq.set_enabled(True)

    class FakeServer:
        plan_queue = pq

    w = worker_mod.Worker(FakeServer())
    future = pq.enqueue(Plan())
    pending = pq.dequeue(timeout=1)   # the applier popped it...
    assert pending is not None
    pq.set_enabled(False)             # ...then leadership died: no respond
    with pytest.raises(RuntimeError, match="plan queue closed"):
        w._wait_plan(future)


class TestPlanQueue:
    def test_priority_order_and_future(self):
        q = PlanQueue()
        q.set_enabled(True)
        f1 = q.enqueue(Plan(priority=10))
        f2 = q.enqueue(Plan(priority=90))
        first = q.dequeue(timeout=1)
        assert first.plan.priority == 90
        second = q.dequeue(timeout=1)
        assert second.plan.priority == 10
        # future round trip
        from nomad_tpu.structs import PlanResult
        result = PlanResult(alloc_index=7)
        first.respond(result)
        assert f2.wait(1).alloc_index == 7

    def test_flush_fails_waiters(self):
        q = PlanQueue()
        q.set_enabled(True)
        f = q.enqueue(Plan())
        q.set_enabled(False)
        with pytest.raises(RuntimeError):
            f.wait(1)


# ---------------------------------------------------------------------------
# TimeTable
# ---------------------------------------------------------------------------

def test_timetable_witness_and_lookup():
    tt = TimeTable(granularity=10, limit=3)
    tt.witness(10, 100.0)
    tt.witness(20, 200.0)
    tt.witness(30, 300.0)
    tt.witness(25, 305.0)  # lower index ignored
    assert tt.nearest_index(250.0) == 20
    assert tt.nearest_index(50.0) == 0
    assert tt.nearest_index(1000.0) == 30
    rows = tt.serialize()
    tt2 = TimeTable()
    tt2.deserialize(rows)
    assert tt2.nearest_index(250.0) == 20


# ---------------------------------------------------------------------------
# FSM
# ---------------------------------------------------------------------------

class TestFSM:
    def test_apply_and_snapshot_roundtrip(self):
        fsm = NomadFSM()
        node = mock.node()
        job = mock.job()
        fsm.apply(1, codec.encode(codec.NODE_REGISTER_REQUEST,
                                  {"node": node.to_dict()}))
        fsm.apply(2, codec.encode(codec.JOB_REGISTER_REQUEST,
                                  {"job": job.to_dict()}))
        ev = make_eval(job_id=job.id)
        fsm.apply(3, codec.encode(codec.EVAL_UPDATE_REQUEST,
                                  {"evals": [ev.to_dict()]}))
        alloc = mock.alloc()
        alloc.node_id = node.id
        fsm.apply(4, codec.encode(codec.ALLOC_UPDATE_REQUEST,
                                  {"alloc": [alloc.to_dict()]}))

        blob = fsm.snapshot()
        fsm2 = NomadFSM()
        fsm2.restore(blob)
        assert fsm2.state.node_by_id(node.id).name == node.name
        assert fsm2.state.job_by_id(job.id).name == job.name
        assert fsm2.state.eval_by_id(ev.id) is not None
        restored = fsm2.state.alloc_by_id(alloc.id)
        assert restored.resources.cpu == alloc.resources.cpu
        assert restored.job.task_groups[0].tasks[0].name == "web"

    def test_eval_apply_enqueues_into_broker(self):
        broker = EvalBroker(5, 3)
        broker.set_enabled(True)
        fsm = NomadFSM(eval_broker=broker)
        ev = make_eval()
        fsm.apply(1, codec.encode(codec.EVAL_UPDATE_REQUEST,
                                  {"evals": [ev.to_dict()]}))
        got, _ = broker.dequeue(["service"], timeout=1)
        assert got.id == ev.id

    def test_unknown_type(self):
        fsm = NomadFSM()
        with pytest.raises(ValueError):
            fsm.apply(1, codec.encode(99, {}))
        # ignorable flag: no error
        fsm.apply(2, codec.encode(99 | codec.IGNORE_UNKNOWN_TYPE_FLAG, {}))

    def test_apply_every_remaining_type(self):
        """The apply table rows not covered above: node deregister /
        status / drain, job deregister, eval delete (reference
        fsm_test.go:100-366)."""
        fsm = NomadFSM()
        node = mock.node()
        fsm.apply(1, codec.encode(codec.NODE_REGISTER_REQUEST,
                                  {"node": node.to_dict()}))
        fsm.apply(2, codec.encode(codec.NODE_UPDATE_STATUS_REQUEST,
                                  {"node_id": node.id, "status": "down"}))
        assert fsm.state.node_by_id(node.id).status == "down"
        fsm.apply(3, codec.encode(codec.NODE_UPDATE_DRAIN_REQUEST,
                                  {"node_id": node.id, "drain": True}))
        assert fsm.state.node_by_id(node.id).drain is True
        fsm.apply(4, codec.encode(codec.NODE_DEREGISTER_REQUEST,
                                  {"node_id": node.id}))
        assert fsm.state.node_by_id(node.id) is None

        job = mock.job()
        fsm.apply(5, codec.encode(codec.JOB_REGISTER_REQUEST,
                                  {"job": job.to_dict()}))
        fsm.apply(6, codec.encode(codec.JOB_DEREGISTER_REQUEST,
                                  {"job_id": job.id}))
        assert fsm.state.job_by_id(job.id) is None

        ev = make_eval()
        alloc = mock.alloc()
        alloc.eval_id = ev.id
        fsm.apply(7, codec.encode(codec.EVAL_UPDATE_REQUEST,
                                  {"evals": [ev.to_dict()]}))
        fsm.apply(8, codec.encode(codec.ALLOC_UPDATE_REQUEST,
                                  {"alloc": [alloc.to_dict()]}))
        fsm.apply(9, codec.encode(codec.EVAL_DELETE_REQUEST,
                                  {"evals": [ev.id],
                                   "allocs": [alloc.id]}))
        assert fsm.state.eval_by_id(ev.id) is None
        assert fsm.state.alloc_by_id(alloc.id) is None
        assert fsm.state.get_index("evals") == 9

    def test_snapshot_restores_timetable(self):
        """TimeTable witnesses ride the snapshot so GC cutoffs survive a
        restore (reference fsm_test.go:590-626)."""
        fsm = NomadFSM()
        fsm.timetable.granularity = 0.0
        fsm.timetable.witness(1000, 12345.0)
        fsm.timetable.witness(2000, 23456.0)
        blob = fsm.snapshot()
        fsm2 = NomadFSM()
        fsm2.restore(blob)
        assert fsm2.timetable.nearest_index(20000.0) == 1000
        assert fsm2.timetable.nearest_index(30000.0) == 2000

    def test_client_update_merges_status_only(self):
        fsm = NomadFSM()
        alloc = mock.alloc()
        fsm.apply(1, codec.encode(codec.ALLOC_UPDATE_REQUEST,
                                  {"alloc": [alloc.to_dict()]}))
        update = alloc.copy()
        update.client_status = "running"
        update.desired_status = "SHOULD-NOT-MOVE"
        fsm.apply(2, codec.encode(codec.ALLOC_CLIENT_UPDATE_REQUEST,
                                  {"alloc": [update.to_dict()]}))
        stored = fsm.state.alloc_by_id(alloc.id)
        assert stored.client_status == "running"
        assert stored.desired_status == alloc.desired_status


# ---------------------------------------------------------------------------
# Durable raft backend
# ---------------------------------------------------------------------------

def test_raft_log_replay_and_snapshot(tmp_path):
    from nomad_tpu.server.raft import FileLogStore, SnapshotStore

    log = FileLogStore(str(tmp_path / "log.bin"))
    fsm = NomadFSM()
    raft = InmemRaft(fsm, log)
    node = mock.node()
    raft.apply(codec.encode(codec.NODE_REGISTER_REQUEST,
                            {"node": node.to_dict()})).wait(1)
    log.close()

    # Reboot: replay from disk.
    fsm2 = NomadFSM()
    raft2 = InmemRaft(fsm2, FileLogStore(str(tmp_path / "log.bin")))
    assert raft2.applied_index() == 1
    assert fsm2.state.node_by_id(node.id) is not None


# ---------------------------------------------------------------------------
# Full pipeline: Server end-to-end
# ---------------------------------------------------------------------------

def make_server(**kw) -> Server:
    kw.setdefault("num_schedulers", 2)
    cfg = ServerConfig(**kw)
    srv = Server(cfg)
    srv.establish_leadership()
    return srv


class TestWorker:
    def test_pause_holds_work_until_resume(self):
        """A paused worker leaves ready evals on the broker; resuming
        drains them (reference worker.go:77-93 — the leader pauses one
        worker to reserve CPU for its own duties)."""
        srv = Server(ServerConfig(num_schedulers=1))
        srv.establish_leadership()
        try:
            srv.node_register(mock.node())
            worker = srv.workers[0]
            worker.set_pause(True)
            # Outwait an in-flight dequeue (0.25s timeout) started
            # before the pause flag was set: the loop only re-checks
            # the gate between iterations.
            time.sleep(0.4)  # sleep-ok: outwait the in-flight dequeue
            job = mock.job()
            _, eval_id = srv.job_register(job)
            time.sleep(0.4)  # sleep-ok: prove the ABSENCE of processing
            ev = srv.fsm.state.eval_by_id(eval_id)
            assert ev.status == "pending", "paused worker processed eval"
            worker.set_pause(False)
            srv.wait_for_evals([eval_id], timeout=10)
            assert srv.fsm.state.eval_by_id(eval_id).status == "complete"
        finally:
            srv.shutdown()

    def test_wait_for_index_times_out_on_lagging_fsm(self):
        """An eval whose modify_index outruns the local FSM must not be
        scheduled from a stale snapshot; past the sync limit the worker
        gives up (reference worker.go:209-230)."""
        from nomad_tpu.server.worker import Worker

        srv = Server(ServerConfig(num_schedulers=0))
        srv.establish_leadership()
        try:
            w = Worker(srv)
            far_future = srv.raft.applied_index() + 1000
            with pytest.raises(TimeoutError):
                w._wait_for_index(far_future, timeout=0.2)
            # An already-applied index returns immediately.
            w._wait_for_index(srv.raft.applied_index(), timeout=0.2)
        finally:
            srv.shutdown()


class TestPlanTokenFencing:
    def test_stale_or_wrong_token_plans_rejected(self):
        """The plan applier is the split-brain fence: a plan whose eval
        token doesn't match the outstanding delivery — or whose eval is
        no longer outstanding at all — must be refused before touching
        state (reference plan_apply.go:53-65)."""
        srv = Server(ServerConfig(num_schedulers=0))
        srv.establish_leadership()
        try:
            srv.node_register(mock.node())
            ev = make_eval()
            srv.apply_eval_update([ev])
            got, token = srv.eval_broker.dequeue(["service"], timeout=2)
            assert got.id == ev.id

            # Wrong token (another scheduler's claim): rejected.
            plan = got.make_plan(None)
            plan.eval_token = "not-the-token"
            future = srv.plan_queue.enqueue(plan)
            with pytest.raises(RuntimeError, match="token does not"):
                future.wait(5.0)

            # Right token while outstanding: accepted (empty plan).
            plan2 = got.make_plan(None)
            plan2.eval_token = token
            result = srv.plan_queue.enqueue(plan2).wait(5.0)
            assert result is not None

            # After ack the eval is no longer outstanding: even the
            # once-valid token is fenced out.
            srv.eval_broker.ack(got.id, token)
            plan3 = got.make_plan(None)
            plan3.eval_token = token
            with pytest.raises(RuntimeError, match="not outstanding"):
                srv.plan_queue.enqueue(plan3).wait(5.0)
        finally:
            srv.shutdown()


class TestServerEndToEnd:
    def test_job_register_schedules_allocs(self):
        srv = make_server()
        try:
            for i in range(5):
                srv.node_register(mock.node(i))
            job = mock.job()
            job.task_groups[0].count = 5
            _, eval_id = srv.job_register(job)
            statuses = srv.wait_for_evals([eval_id], timeout=15)
            assert statuses[eval_id] == "complete"
            allocs = srv.fsm.state.allocs_by_job(job.id)
            placed = [a for a in allocs if a.node_id]
            assert len(placed) == 5
            # Spread across nodes by anti-affinity.
            assert len({a.node_id for a in placed}) == 5
        finally:
            srv.shutdown()

    def test_job_register_device_scheduler_off(self):
        srv = make_server(use_device_scheduler=False)
        try:
            for i in range(4):
                srv.node_register(mock.node(i))
            job = mock.job()
            job.task_groups[0].count = 4
            _, eval_id = srv.job_register(job)
            statuses = srv.wait_for_evals([eval_id], timeout=15)
            assert statuses[eval_id] == "complete"
            assert len(srv.fsm.state.allocs_by_job(job.id)) == 4
        finally:
            srv.shutdown()

    def test_device_unavailable_falls_back_to_sequential(self,
                                                         monkeypatch):
        """A broken device backend degrades to the sequential schedulers
        instead of failing every eval into the delivery-limit reaper."""
        import nomad_tpu.scheduler as sched_registry
        from nomad_tpu.server.worker import BatchWorker

        monkeypatch.setattr(sched_registry, "device_available",
                            lambda: False)
        srv = make_server(use_device_scheduler=True)
        try:
            assert not srv.config.use_device_scheduler
            assert not any(isinstance(w, BatchWorker)
                           for w in srv.workers)
            srv.node_register(mock.node(0))
            job = mock.job()
            _, eval_id = srv.job_register(job)
            statuses = srv.wait_for_evals([eval_id], timeout=15)
            assert statuses[eval_id] == "complete"
            assert srv.fsm.state.allocs_by_job(job.id)
        finally:
            srv.shutdown()

    def test_concurrent_jobs_no_oversubscription(self):
        from nomad_tpu.structs import allocs_fit

        srv = make_server()
        try:
            nodes = [mock.node(i) for i in range(4)]
            for n in nodes:
                srv.node_register(n)
            eval_ids, jobs = [], []
            for _ in range(6):
                job = mock.job()
                job.task_groups[0].count = 2
                job.task_groups[0].tasks[0].resources.cpu = 800
                _, eid = srv.job_register(job)
                jobs.append(job)
                eval_ids.append(eid)
            srv.wait_for_evals(eval_ids, timeout=20)
            # The plan applier must never commit an oversubscribed node.
            state = srv.fsm.state
            for node in nodes:
                allocs = [a for a in state.allocs_by_node(node.id)
                          if not a.terminal_status() and a.node_id]
                fit, dim, _ = allocs_fit(node, allocs)
                assert fit, f"node oversubscribed: {dim}"
        finally:
            srv.shutdown()

    def test_job_deregister_stops_allocs(self):
        srv = make_server()
        try:
            for i in range(3):
                srv.node_register(mock.node(i))
            job = mock.job()
            job.task_groups[0].count = 3
            _, e1 = srv.job_register(job)
            srv.wait_for_evals([e1], timeout=15)
            _, e2 = srv.job_deregister(job.id)
            srv.wait_for_evals([e2], timeout=15)
            allocs = srv.fsm.state.allocs_by_job(job.id)
            stopped = [a for a in allocs if a.desired_status == "stop"]
            assert len(stopped) == 3
        finally:
            srv.shutdown()


class TestNodeLifecycle:
    def test_node_down_triggers_migration(self):
        srv = make_server()
        try:
            nodes = [mock.node(i) for i in range(4)]
            for n in nodes:
                srv.node_register(n)
            job = mock.job()
            job.task_groups[0].count = 2
            _, e1 = srv.job_register(job)
            srv.wait_for_evals([e1], timeout=15)
            placed = {a.node_id for a in srv.fsm.state.allocs_by_job(job.id)}

            victim = next(iter(placed))
            srv.node_update_status(victim, "down")
            # A node-update eval per affected job reschedules the allocs.
            def migrated():
                allocs = srv.fsm.state.allocs_by_job(job.id)
                live = [a for a in allocs if not a.terminal_status()]
                return len(live) == 2 and all(
                    a.node_id != victim for a in live)

            wait_until(migrated, msg="allocs migrated off the down node")
        finally:
            srv.shutdown()

    def test_drain_migrates_allocs(self):
        srv = make_server()
        try:
            for i in range(3):
                srv.node_register(mock.node(i))
            job = mock.job()
            job.task_groups[0].count = 1
            _, e1 = srv.job_register(job)
            srv.wait_for_evals([e1], timeout=15)
            alloc = srv.fsm.state.allocs_by_job(job.id)[0]

            srv.node_update_drain(alloc.node_id, True)
            def migrated():
                live = [a for a in srv.fsm.state.allocs_by_job(job.id)
                        if not a.terminal_status()]
                return bool(live) and all(
                    a.node_id != alloc.node_id for a in live)

            wait_until(migrated, msg="alloc migrated off drained node")
        finally:
            srv.shutdown()

    def test_heartbeat_ttl_and_expiry(self):
        srv = make_server()
        srv.heartbeats.min_ttl = 0.1
        srv.heartbeats.grace = 0.05
        try:
            node = mock.node()
            srv.node_register(node)
            ttl = srv.node_heartbeat(node.id)
            assert ttl >= 0.1
            # Stop heartbeating: the node must be marked down.
            wait_until(
                lambda: srv.fsm.state.node_by_id(node.id).status == "down",
                timeout=5, msg="node marked down after TTL")
        finally:
            srv.shutdown()

    def test_heartbeat_ttl_rate_scaled(self):
        """TTL stretches with fleet size so aggregate heartbeat rate
        stays under max_rate (reference heartbeat.go:37-72,
        MaxHeartbeatsPerSecond=50)."""
        from nomad_tpu.server.heartbeat import HeartbeatManager

        hb = HeartbeatManager(server=None)
        try:
            # Small fleet: the 10s floor dominates (jitter adds <= 1/16).
            ttl = hb.reset_heartbeat_timer("n-small")
            assert 10.0 <= ttl <= 10.0 * (1 + 1 / 16)
            # ~1000-node fleet: ttl >= n/50 (~20s), so at most 50
            # heartbeats/s arrive in aggregate.  Seed the timer table
            # with inert entries — the math only reads len().
            class _Inert:
                def cancel(self):
                    pass
            for i in range(1000):
                hb._timers[f"n-{i}"] = _Inert()
            base = hb.active() / hb.max_rate
            ttl = hb.reset_heartbeat_timer("n-0")
            assert base <= ttl <= base * (1 + 1 / 16)
        finally:
            hb.clear()

    def test_failover_rearms_all_nodes_at_long_ttl(self):
        """A new leader can't know when the last heartbeats happened, so
        initialize() re-arms every live node at the failover TTL
        (heartbeat.go:21-35)."""
        srv = make_server()
        try:
            for i in range(3):
                srv.node_register(mock.node(i))
            down = mock.node(9)
            srv.node_register(down)
            srv.node_update_status(down.id, "down")
            srv.heartbeats.clear()
            assert srv.heartbeats.active() == 0
            srv.heartbeats.initialize()
            # Live nodes re-armed; the down node is not.
            assert srv.heartbeats.active() == 3
        finally:
            srv.shutdown()

    def test_system_job_runs_everywhere(self):
        srv = make_server()
        try:
            for i in range(3):
                srv.node_register(mock.node(i))
            job = mock.system_job()
            _, e1 = srv.job_register(job)
            srv.wait_for_evals([e1], timeout=15)
            allocs = srv.fsm.state.allocs_by_job(job.id)
            assert len({a.node_id for a in allocs}) == 3
            # A new node joining gets the system job via node evals.
            late = mock.node(99)
            srv.node_register(late)
            eval_ids = srv.node_evaluate(late.id)
            srv.wait_for_evals(eval_ids, timeout=15)
            allocs = [a for a in srv.fsm.state.allocs_by_job(job.id)
                      if not a.terminal_status()]
            assert len({a.node_id for a in allocs}) == 4
        finally:
            srv.shutdown()


class TestLeaderLifecycle:
    def test_reap_failed_eval(self):
        """An eval nacked past the delivery limit lands in the failed
        queue and the leader's reaper marks it failed in replicated
        state (reference leader_test.go:309-360)."""
        srv = make_server(num_schedulers=0, eval_delivery_limit=1)
        try:
            ev = mock.eval()
            srv.eval_broker.enqueue(ev)
            out, token = srv.eval_broker.dequeue(["service"], timeout=2)
            assert out.id == ev.id
            srv.eval_broker.nack(out.id, token)

            srv.wait_for_evals([ev.id], timeout=10)
            got = srv.fsm.state.eval_by_id(ev.id)
            assert got.status == "failed"
            assert "delivery limit" in got.status_description
        finally:
            srv.shutdown()

    def test_periodic_dispatch_enqueues_core_evals(self):
        """Tiny GC intervals: the leader's periodic loop mints _core
        evals for eval-gc and node-gc (reference
        leader_test.go:289-307 + leader.go:171-199)."""
        from nomad_tpu.structs import CORE_JOB_EVAL_GC, CORE_JOB_NODE_GC

        srv = make_server(num_schedulers=0, eval_gc_interval=0.05,
                          node_gc_interval=0.05)
        try:
            seen = set()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and len(seen) < 2:
                ev, token = srv.eval_broker.dequeue(["_core"],
                                                    timeout=0.5)
                if ev is not None:
                    seen.add(ev.job_id)
                    srv.eval_broker.ack(ev.id, token)
            assert seen == {CORE_JOB_EVAL_GC, CORE_JOB_NODE_GC}
        finally:
            srv.shutdown()


class TestCoreGC:
    def test_eval_gc_reaps_old_terminal_evals(self):
        from nomad_tpu.server.core_sched import CoreScheduler
        from nomad_tpu.structs import CORE_JOB_EVAL_GC

        srv = make_server()
        srv.config.eval_gc_threshold = 0.0  # everything is old
        try:
            srv.node_register(mock.node())
            job = mock.job()
            job.task_groups[0].count = 1
            _, e1 = srv.job_register(job)
            srv.wait_for_evals([e1], timeout=15)
            _, e2 = srv.job_deregister(job.id)
            srv.wait_for_evals([e2], timeout=15)
            # Mark allocs terminal via client update so GC can take them.
            for a in srv.fsm.state.allocs_by_job(job.id):
                up = a.copy()
                up.client_status = "dead"
                srv.raft_apply(codec.ALLOC_CLIENT_UPDATE_REQUEST,
                               {"alloc": [up.to_dict()]})
            # Force the timetable to see current indexes as old (bypass the
            # 5-minute witness granularity).
            srv.fsm.timetable.granularity = 0.0
            srv.fsm.timetable.witness(srv.raft.applied_index() + 1,
                                      time.time())

            gc_eval = Evaluation(id=generate_uuid(), type="_core",
                                 job_id=CORE_JOB_EVAL_GC)
            CoreScheduler(srv, srv.fsm.state.snapshot()).process(gc_eval)
            assert srv.fsm.state.eval_by_id(e1) is None
            assert srv.fsm.state.eval_by_id(e2) is None
        finally:
            srv.shutdown()

    def test_node_gc_deregisters_down_empty_nodes(self):
        """Down nodes with no remaining allocs are deregistered; down
        nodes still carrying allocs, and ready nodes, survive
        (reference nomad/core_sched_test.go:72-130)."""
        from nomad_tpu.server.core_sched import CoreScheduler
        from nomad_tpu.structs import CORE_JOB_NODE_GC, NODE_STATUS_DOWN

        srv = make_server()
        srv.config.node_gc_threshold = 0.0
        try:
            empty_down = mock.node(1)
            busy_down = mock.node(2)
            alive = mock.node(3)
            for n in (empty_down, busy_down, alive):
                srv.node_register(n)
            # An alloc pins busy_down.
            a = mock.alloc()
            a.node_id = busy_down.id
            srv.raft_apply(codec.ALLOC_UPDATE_REQUEST,
                           {"alloc": [a.to_dict()]})
            for nid in (empty_down.id, busy_down.id):
                srv.raft_apply(codec.NODE_UPDATE_STATUS_REQUEST,
                               {"node_id": nid,
                                "status": NODE_STATUS_DOWN})
            srv.fsm.timetable.granularity = 0.0
            srv.fsm.timetable.witness(srv.raft.applied_index() + 1,
                                      time.time())
            gc_eval = Evaluation(id=generate_uuid(), type="_core",
                                 job_id=CORE_JOB_NODE_GC)
            CoreScheduler(srv, srv.fsm.state.snapshot()).process(gc_eval)
            state = srv.fsm.state
            assert state.node_by_id(empty_down.id) is None
            assert state.node_by_id(busy_down.id) is not None
            assert state.node_by_id(alive.id) is not None
        finally:
            srv.shutdown()
