"""Second structs suite: validation verdicts and port-slicing edge
cases from the reference's structs_test.go / network_test.go /
funcs_test.go not covered by test_structs.py."""
from __future__ import annotations

import re

from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Job,
    NetworkIndex,
    NetworkResource,
    Resources,
    Task,
    TaskGroup,
    allocs_fit,
    generate_uuid,
    generate_uuids,
)
from nomad_tpu import mock


# ---------------------------------------------------------------------------
# validation (structs_test.go:11-164)
# ---------------------------------------------------------------------------

def test_job_validate_collects_all_errors():
    job = Job()  # everything missing (type/region carry defaults)
    errs = job.validate()
    text = " ".join(errs).lower()
    for needle in ("id", "name", "datacenter", "task group"):
        assert needle in text, (needle, errs)

    # ID with a space (reference structs.go Job.Validate).
    job = mock.job()
    job.id = "has space"
    assert any("space" in e for e in job.validate())

    # System jobs require count == 1 per group.
    sysjob = mock.system_job()
    sysjob.task_groups[0].count = 3
    assert any("count of 1" in e for e in sysjob.validate())

    # Duplicate group names are rejected.
    job = mock.job()
    job.task_groups = [job.task_groups[0], job.task_groups[0]]
    errs = job.validate()
    assert any("2 times" in e or "duplicate" in e.lower() for e in errs)


def test_task_group_validate():
    tg = TaskGroup()  # no name, no tasks, count 1? -> errors
    errs = tg.validate()
    text = " ".join(errs).lower()
    assert "name" in text and "task" in text

    tg = TaskGroup(name="web", count=-1,
                   tasks=[Task(name="t", driver="exec"),
                          Task(name="t", driver="exec")])
    errs = tg.validate()
    text = " ".join(errs).lower()
    assert "count" in text
    assert any("2 times" in e or "duplicate" in e.lower() for e in errs)


def test_task_validate():
    errs = Task().validate()
    text = " ".join(errs).lower()
    assert "name" in text and "driver" in text


def test_constraint_validate():
    errs = Constraint(operand="").validate()
    assert errs
    # Bad regexp is surfaced (reference Constraint.Validate).
    errs = Constraint(operand="regexp", l_target="$attr.x",
                      r_target="(unclosed").validate()
    assert any("regular expression" in e.lower() for e in errs)
    # Bad version constraint too.
    errs = Constraint(operand="version", l_target="$attr.v",
                      r_target=">> nope ><").validate()
    assert any("version constraint" in e.lower() for e in errs)
    # Valid forms pass.
    assert Constraint(operand="=", l_target="a", r_target="b") \
        .validate() == []
    assert Constraint(operand="regexp", r_target="[0-9]+") \
        .validate() == []
    assert Constraint(operand="version", r_target=">= 1.0, < 2.0") \
        .validate() == []


# ---------------------------------------------------------------------------
# port slicing edges (structs_test.go:306-423)
# ---------------------------------------------------------------------------

def test_port_slicing_edges():
    # Empty network: nothing to slice.
    n = NetworkResource()
    assert n.map_dynamic_ports() == {}
    assert n.list_static_ports() == []
    # Static only.
    n = NetworkResource(reserved_ports=[22, 80])
    assert n.map_dynamic_ports() == {}
    assert n.list_static_ports() == [22, 80]
    # Dynamic only: assigned ports fill reserved_ports.
    n = NetworkResource(reserved_ports=[20001, 20002],
                        dynamic_ports=["http", "https"])
    assert n.map_dynamic_ports() == {"http": 20001, "https": 20002}
    assert n.list_static_ports() == []
    # Mixed: statics first, assigned dynamics last.
    n = NetworkResource(reserved_ports=[22, 20005],
                        dynamic_ports=["admin"])
    assert n.map_dynamic_ports() == {"admin": 20005}
    assert n.list_static_ports() == [22]


# ---------------------------------------------------------------------------
# fit: ports overcommitted (funcs_test.go:42-88)
# ---------------------------------------------------------------------------

def test_allocs_fit_ports_overcommitted():
    node = mock.node(0)
    ip = node.reserved.networks[0].ip

    def holder(port):
        return Allocation(
            id=generate_uuid(), node_id=node.id, job_id="j",
            task_group="g",
            resources=Resources(cpu=100, memory_mb=64),
            task_resources={"t": Resources(
                cpu=100, memory_mb=64,
                networks=[NetworkResource(device="eth0", ip=ip,
                                          reserved_ports=[port],
                                          mbits=10)])},
            desired_status="run")

    a1, a2 = holder(30100), holder(30100)
    fit, dim, _util = allocs_fit(node, [a1, a2])
    assert not fit and "port" in dim.lower()
    fit, _dim, _util = allocs_fit(node, [a1, holder(30101)])
    assert fit


# ---------------------------------------------------------------------------
# NetworkIndex ip yielding (network_test.go:175-212)
# ---------------------------------------------------------------------------

def test_network_index_yields_cidr_ips():
    idx = NetworkIndex()
    node = mock.node(0)
    node.resources.networks[0].cidr = "192.168.7.0/30"
    node.resources.networks[0].ip = ""
    node.reserved = None
    idx.set_node(node)
    ips = [ip for _n, ip in idx._yield_ips()]
    assert "192.168.7.0" in ips and "192.168.7.1" in ips
    assert len(ips) == 4  # a /30 yields 4 addresses


# ---------------------------------------------------------------------------
# uuids (funcs_test.go:215-230)
# ---------------------------------------------------------------------------

UUID_RE = re.compile(
    r"^[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{4}-[0-9a-f]{12}$")


def test_generate_uuid_format_and_uniqueness():
    seen = set()
    for _ in range(100):
        u = generate_uuid()
        assert UUID_RE.match(u), u
        seen.add(u)
    assert len(seen) == 100
    batch = generate_uuids(50)
    assert len(batch) == 50
    for u in batch:
        assert UUID_RE.match(u), u
    assert len(set(batch) | seen) == 150
