"""Failure-plane lint (analysis/faultlint.py) unit tests + defect
regressions.

Layer 2 of the static-analysis discipline (see
tests/test_static_analysis.py): each faultlint rule proves it FIRES on
a synthetic package — a lint that cannot fail gates nothing — and each
defect the analyzer found in the real tree keeps a behavioral
regression test:

- endpoints._forward/_with_region dropped the re-based budget on the
  transport hop (deadline-drop): the forwarded call now clips its
  timeout to the caller's remaining envelope.
- plan_apply waited on raft-commit futures with no supervision
  (unbounded-wait): _wait_commit polls in bounded slices and gives up
  only when the plan queue is disabled with the future unresolved.
"""
from __future__ import annotations

import os
import sys
import textwrap
import threading
import time

import pytest

from nomad_tpu.analysis import faultlint


def write_pkg(tmp_path, name, source) -> str:
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    (d / "mod.py").write_text(textwrap.dedent(source))
    return str(d)


def lint(tmp_path, source, name="pkg"):
    cov: dict = {}
    findings = faultlint.analyze_package(
        write_pkg(tmp_path, name, source), coverage_out=cov)
    return findings, cov


# ---------------------------------------------------------------------------
# pass 1: deadline propagation
# ---------------------------------------------------------------------------

class TestDeadlinePass:
    def test_unbounded_wait_on_loop_entry_flagged(self, tmp_path):
        findings, cov = lint(tmp_path, """
            import threading

            class QueueWorker:
                def __init__(self):
                    self.ev = threading.Event()

                def _run(self):
                    self.ev.wait()
        """)
        assert [f.rule for f in findings] == ["unbounded-wait"]
        assert "QueueWorker._run" in findings[0].where
        assert cov["entries"] == 1 and cov["unbounded_waits"] == 1

    def test_bounded_wait_clean(self, tmp_path):
        findings, cov = lint(tmp_path, """
            import threading

            class QueueWorker:
                def __init__(self):
                    self.ev = threading.Event()

                def _run(self):
                    self.ev.wait(5.0)
        """)
        assert findings == []
        assert cov["wait_sites"] == 1 and cov["unbounded_waits"] == 0

    def test_explicit_timeout_none_is_unbounded(self, tmp_path):
        findings, _ = lint(tmp_path, """
            import threading

            class QueueWorker:
                def __init__(self):
                    self.ev = threading.Event()

                def _run(self):
                    self.ev.wait(timeout=None)
        """)
        assert [f.rule for f in findings] == ["unbounded-wait"]

    def test_budget_aware_unbounded_wait_is_deadline_drop(self, tmp_path):
        """A function that touched the envelope (remaining/...) and then
        blocks without a timeout DROPPED the budget, a stronger claim
        than mere unboundedness."""
        findings, _ = lint(tmp_path, """
            import threading

            def remaining(deadline, default):
                return default

            class PlanApplier:
                def __init__(self):
                    self.ev = threading.Event()

                def _run(self):
                    remaining(None, 5.0)
                    self.ev.wait()
        """)
        assert [f.rule for f in findings] == ["deadline-drop"]

    def test_wait_reachable_through_callee_flagged_with_chain(
            self, tmp_path):
        findings, cov = lint(tmp_path, """
            import threading

            class EvalWorker:
                def __init__(self):
                    self.ev = threading.Event()

                def run(self):
                    self._park()

                def _park(self):
                    self.ev.wait()
        """)
        assert [f.rule for f in findings] == ["unbounded-wait"]
        # The finding renders the entry -> wait call chain.
        assert "EvalWorker.run" in findings[0].message
        assert cov["entry_closure"] > cov["entries"]

    def test_transport_form_deadline_drop(self, tmp_path):
        """restamp_forward then a pool .call() with no timeout= — the
        hop would wait the transport default, not the re-based
        envelope (the endpoints.py defect shape)."""
        findings, cov = lint(tmp_path, """
            def restamp_forward(args, clock=None):
                return args

            class Router:
                def __init__(self, conn_pool):
                    self.conn_pool = conn_pool

                def forward(self, addr, method, args):
                    fwd = restamp_forward(dict(args))
                    return self.conn_pool.call(addr, method, fwd)
        """)
        assert [f.rule for f in findings] == ["deadline-drop"]
        assert cov["transport_drops"] == 1

    def test_transport_form_clean_with_timeout(self, tmp_path):
        findings, cov = lint(tmp_path, """
            def restamp_forward(args, clock=None):
                return args

            class Router:
                def __init__(self, conn_pool):
                    self.conn_pool = conn_pool

                def forward(self, addr, method, args):
                    fwd = restamp_forward(dict(args))
                    return self.conn_pool.call(addr, method, fwd,
                                               timeout=fwd.get("_deadline"))
        """)
        assert findings == []
        assert cov["transport_drops"] == 0

    def test_marker_waives_wait(self, tmp_path):
        findings, cov = lint(tmp_path, """
            import threading

            class QueueWorker:
                def __init__(self):
                    self.ev = threading.Event()

                def _run(self):
                    # faultlint-ok(unbounded-wait): teardown parking;
                    # stop() always sets the event.
                    self.ev.wait()
        """)
        assert findings == []
        assert cov["waived"] == 1

    def test_unjustified_marker_does_not_waive(self, tmp_path):
        findings, _ = lint(tmp_path, """
            import threading

            class QueueWorker:
                def __init__(self):
                    self.ev = threading.Event()

                def _run(self):
                    self.ev.wait()  # faultlint-ok(unbounded-wait):
        """)
        assert [f.rule for f in findings] == ["unbounded-wait"]


# ---------------------------------------------------------------------------
# pass 2: fault-injectability coverage
# ---------------------------------------------------------------------------

class TestInjectabilityPass:
    def test_uncovered_boundary_flagged(self, tmp_path):
        findings, cov = lint(tmp_path, """
            def send_bytes(sock):
                sock.sendall(b"x")
        """)
        assert [f.rule for f in findings] == ["uninjectable-io"]
        assert cov["boundary_count"] == 1
        assert cov["covered_fraction"] == 0.0

    def test_own_consult_covers(self, tmp_path):
        findings, cov = lint(tmp_path, """
            def fire(site):
                pass

            def send_bytes(sock):
                fire("rpc.send")
                sock.sendall(b"x")
        """)
        assert findings == []
        assert cov["boundaries"][0]["covered_by"] == "rpc.send"
        assert cov["covered_fraction"] == 1.0

    def test_caller_consult_covers(self, tmp_path):
        """Coverage is a path property: the consulted site may live in
        the caller that drives the boundary."""
        findings, cov = lint(tmp_path, """
            def fire(site):
                pass

            def raw_send(sock):
                sock.sendall(b"x")

            def send(sock):
                fire("rpc.send")
                raw_send(sock)
        """)
        assert findings == []
        assert cov["covered_fraction"] == 1.0

    def test_dead_site_flagged(self, tmp_path):
        findings, cov = lint(tmp_path, """
            SITES = ("rpc.send", "disk.sync")

            def fire(site):
                pass

            def go(sock):
                fire("rpc.send")
                sock.sendall(b"x")
        """)
        assert [f.rule for f in findings] == ["dead-site"]
        assert cov["dead_sites"] == ["disk.sync"]
        assert cov["sites"] == {"rpc.send": 1, "disk.sync": 0}

    def test_waived_boundary_counts_as_covered(self, tmp_path):
        findings, cov = lint(tmp_path, """
            def fingerprint(sock):
                # faultlint-ok(uninjectable-io): boot-time probe, not
                # a live data path.
                sock.connect(("10.0.0.1", 1))
        """)
        assert findings == []
        assert cov["boundaries"][0]["waived"] is True
        assert cov["covered_fraction"] == 1.0

    def test_disk_and_subprocess_kinds_detected(self, tmp_path):
        findings, cov = lint(tmp_path, """
            import os
            import subprocess

            def persist(path, fd):
                os.fsync(fd)
                os.replace(path + ".tmp", path)

            def probe():
                subprocess.run(["true"], check=False)
        """)
        assert {f.rule for f in findings} == {"uninjectable-io"}
        kinds = {b["kind"] for b in cov["boundaries"]}
        assert kinds == {"disk", "subprocess"}


# ---------------------------------------------------------------------------
# pass 3: retry safety
# ---------------------------------------------------------------------------

_RETRY_PKG = """
    class RetryPolicy:
        def call(self, fn):
            return fn()

    POLICY = RetryPolicy()

    class Sender:
        def __init__(self):
            self.sent = []

        def push(self, item):
            def attempt():
                self.sent.append(item)
                return True
            return POLICY.call(attempt)
"""


class TestRetryPass:
    def test_accumulating_closure_flagged(self, tmp_path):
        findings, cov = lint(tmp_path, _RETRY_PKG)
        assert [f.rule for f in findings] == ["retry-unsafe"]
        assert "Sender.push.attempt" in findings[0].where
        assert cov["retry_closures"] == 1 and cov["retry_tainted"] == 1

    def test_fencing_token_exempts(self, tmp_path):
        findings, cov = lint(tmp_path, _RETRY_PKG.replace(
            "self.sent.append(item)",
            "token = item.modify_index\n"
            "                self.sent.append((token, item))"))
        assert findings == []
        assert cov["retry_tainted"] == 0

    def test_newest_wins_replacement_exempts(self, tmp_path):
        findings, _ = lint(tmp_path, """
            class RetryPolicy:
                def call(self, fn):
                    return fn()

            POLICY = RetryPolicy()

            class Mirror:
                def __init__(self):
                    self.view = {}

                def refresh(self, snapshot):
                    def attempt():
                        self.view.clear()
                        self.view.update(snapshot)
                        return True
                    return POLICY.call(attempt)
        """)
        assert findings == []

    def test_apply_closure_unforced_broker_enqueue_flagged(self, tmp_path):
        findings, cov = lint(tmp_path, """
            class TinyFSM:
                def __init__(self, broker):
                    self.eval_broker = broker

                def apply(self, index, entry):
                    self.eval_broker.enqueue(entry)
        """)
        assert [f.rule for f in findings] == ["retry-unsafe"]
        assert "shed-reachable" in findings[0].where
        assert cov["apply_shed_calls"] == 1

    def test_apply_closure_forced_enqueue_clean(self, tmp_path):
        findings, cov = lint(tmp_path, """
            class TinyFSM:
                def __init__(self, broker):
                    self.eval_broker = broker

                def apply(self, index, entry):
                    self.eval_broker.enqueue(entry, force=True)
        """)
        assert findings == []
        assert cov["apply_shed_calls"] == 0


# ---------------------------------------------------------------------------
# defect regression #1: forwarded-RPC budget re-basing (endpoints.py)
# ---------------------------------------------------------------------------

class _RecordingPool:
    def __init__(self):
        self.calls = []

    def call(self, address, method, args, timeout=None):
        self.calls.append((address, method, args, timeout))
        return {"ok": True}


class _FakeConfig:
    region = "global"


class _FakeServer:
    def __init__(self):
        self.conn_pool = _RecordingPool()
        self.config = _FakeConfig()
        self.overload = None

    def is_leader(self):
        return False

    def leader_rpc_address(self):
        return ("10.0.0.1", 4647)

    def rpc_address(self):
        return ("10.0.0.2", 4647)

    def region_server(self, region):
        return ("10.1.0.1", 4647)


class TestForwardBudgetClip:
    """The deadline-drop faultlint found: _forward/_with_region re-based
    the envelope (restamp_forward) but let the transport hop wait
    DEFAULT_CALL_TIMEOUT instead of the caller's remaining budget."""

    def _endpoints(self):
        from nomad_tpu.server.endpoints import Endpoints

        ep = Endpoints.__new__(Endpoints)
        ep.server = _FakeServer()
        return ep

    def test_leader_forward_clips_timeout_to_envelope(self):
        ep = self._endpoints()
        args = {"_abs_deadline": time.monotonic() + 2.5}
        out = ep._forward("Job.GetJob", args)
        assert out == {"ok": True}
        (_addr, _method, fwd, timeout), = ep.server.conn_pool.calls
        assert timeout is not None, \
            "forwarded hop must clip to the re-based budget"
        assert 0 < timeout <= 2.5
        assert fwd["_deadline"] == pytest.approx(timeout)
        assert fwd["_forwarded"] is True

    def test_leader_forward_without_envelope_keeps_default(self):
        """No envelope -> timeout None -> the transport default applies
        unchanged (the fix must not invent budgets)."""
        ep = self._endpoints()
        ep._forward("Job.GetJob", {})
        (_a, _m, _fwd, timeout), = ep.server.conn_pool.calls
        assert timeout is None

    def test_region_forward_clips_timeout_to_envelope(self):
        ep = self._endpoints()
        handler_ran = []
        routed = ep._with_region("Job.GetJob",
                                 lambda a: handler_ran.append(a))
        args = {"region": "eu", "_abs_deadline": time.monotonic() + 1.5}
        out = routed(args)
        assert out == {"ok": True} and not handler_ran
        (_a, _m, fwd, timeout), = ep.server.conn_pool.calls
        assert timeout is not None and 0 < timeout <= 1.5
        assert fwd["_region_forwarded"] is True


# ---------------------------------------------------------------------------
# defect regression #2: supervised raft-commit wait (plan_apply.py)
# ---------------------------------------------------------------------------

class _FakeQueue:
    def __init__(self):
        self._enabled = True

    def enabled(self):
        return self._enabled


def _applier():
    from nomad_tpu.server.plan_apply import PlanApplier

    a = PlanApplier.__new__(PlanApplier)
    a.plan_queue = _FakeQueue()
    a.COMMIT_WAIT_POLL = 0.05
    return a


class TestWaitCommit:
    """The unbounded-wait faultlint found: four raft-commit
    future.wait() sites parked forever; _wait_commit re-arms in
    bounded slices and gives up only when the plan queue has been
    disabled with the future still unresolved."""

    def test_late_commit_still_returned(self):
        from nomad_tpu.server.raft import ApplyFuture

        a = _applier()
        fut = ApplyFuture()
        threading.Timer(0.12, fut.respond, args=(7, "resp")).start()
        # Longer than one poll slice: proves the wait re-arms instead
        # of giving up on a commit that legitimately outlasts a slice.
        assert a._wait_commit(fut) == (7, "resp")

    def test_disabled_queue_with_unresolved_future_raises(self):
        from nomad_tpu.server.raft import ApplyFuture

        a = _applier()
        a.plan_queue._enabled = False
        start = time.monotonic()
        with pytest.raises(TimeoutError, match="plan queue disabled"):
            a._wait_commit(ApplyFuture())
        # One slice, not forever.
        assert time.monotonic() - start < 2.0

    def test_responded_timeout_error_propagates(self):
        """A future RESPONDED with a timeout error is the commit's
        outcome, not a poll expiry: it must propagate immediately
        (regression for the spin this path had pre-review)."""
        from nomad_tpu.server.raft import ApplyFuture

        a = _applier()
        fut = ApplyFuture()
        fut.respond(0, None, error=TimeoutError("apply timed out"))
        start = time.monotonic()
        with pytest.raises(TimeoutError, match="apply timed out"):
            a._wait_commit(fut)
        assert time.monotonic() - start < 1.0

    def test_enabled_queue_keeps_waiting(self):
        from nomad_tpu.server.raft import ApplyFuture

        a = _applier()
        fut = ApplyFuture()
        done = []
        t = threading.Thread(target=lambda: done.append(
            a._wait_commit(fut)), daemon=True)
        t.start()
        time.sleep(0.2)       # sleep-ok: let several poll slices lapse
        assert not done, "an enabled queue must keep the wait armed"
        fut.respond(3, None)
        t.join(2.0)
        assert done == [(3, None)]


# ---------------------------------------------------------------------------
# the runtime twin: BudgetWitnessSanitizer
# ---------------------------------------------------------------------------

def _session_budget():
    """The conftest-installed session witness (None when sanitizers are
    off): it must be paused while this test installs its own, or the
    nested wrappers' package-frame callers would record spurious hits
    against the enclosing test."""
    for m in list(sys.modules.values()):
        f = getattr(m, "__file__", None) or ""
        if f.endswith(os.path.join("tests", "conftest.py")):
            return getattr(m, "BUDGET", None)
    return None


class TestBudgetWitness:
    def test_records_unbounded_wait_on_serving_thread_only(self):
        from nomad_tpu.analysis.sanitizers import BudgetWitnessSanitizer
        from nomad_tpu.server.endpoints import Endpoints

        session = _session_budget()
        if session is not None:
            session.uninstall()
        # This test file plays the "package": waits issued from here
        # count, stdlib-internal ones don't.
        san = BudgetWitnessSanitizer(
            package_prefix=os.path.dirname(os.path.abspath(__file__)))
        san.install()
        try:
            ep = Endpoints.__new__(Endpoints)
            ep.server = _FakeServer()
            ev = threading.Event()
            ev.set()              # the wait returns immediately

            def unbounded(args):
                ev.wait()
                return {}

            def bounded(args):
                ev.wait(0.01)
                return {}

            # Off a serving thread: not recorded.
            ev.wait()
            assert san.hits == []
            # On a serving thread, no timeout: recorded with the stack.
            Endpoints._admitted_body(ep, "Job.GetJob", unbounded, {})
            assert len(san.hits) == 1
            method, primitive, _test, stack = san.hits[0]
            assert method == "Job.GetJob"
            assert primitive == "Event.wait"
            assert "test_faultlint" in stack
            san.hits.clear()
            # Bounded wait: clean.
            Endpoints._admitted_body(ep, "Job.GetJob", bounded, {})
            assert san.hits == []
            # Heartbeat/liveness lane: exempt, same as the static pass.
            Endpoints._admitted_body(ep, "Node.Heartbeat", unbounded, {})
            assert san.hits == []
        finally:
            san.uninstall()
            if session is not None:
                session.install()

    def test_check_test_reports_and_resets(self):
        from nomad_tpu.analysis.sanitizers import BudgetWitnessSanitizer

        san = BudgetWitnessSanitizer()
        san.hits.append(("Job.GetJob", "Queue.get", "t::x", "  stack\n"))
        with pytest.raises(AssertionError, match="Queue.get"):
            san.check_test()
        # Reported hits are consumed: the next test starts clean.
        san.check_test()
        san.check()
