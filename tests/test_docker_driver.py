"""Docker driver fidelity against a fake docker binary: exact run argv
for static + dynamic + mapped ports, pull-if-absent (":latest" always
re-pulled), network_mode, and cleanup knobs (reference
client/driver/docker.go:169-360)."""
from __future__ import annotations

import os

import pytest

from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.driver import BUILTIN_DRIVERS
from nomad_tpu.client.driver.base import ExecContext
from nomad_tpu.structs import NetworkResource, Resources, Task


@pytest.fixture
def fake_docker(tmp_path, monkeypatch):
    """A scripted `docker` CLI: logs every invocation; `image inspect`
    succeeds only after a `pull` created the image marker."""
    bindir = tmp_path / "fakebin"
    bindir.mkdir()
    state = tmp_path / "docker-state"
    state.mkdir()
    log = tmp_path / "invocations.log"
    exe = bindir / "docker"
    exe.write_text(f"""#!/bin/sh
echo "docker $@" >> {log}
state={state}
case "$1" in
  version) echo "24.0.7" ;;
  image)
    # image inspect -f {{.Id}} IMG -> image name is $5
    img=$(echo "$5" | tr '/:' '__')
    if [ -f "$state/$img" ]; then echo "sha256:id-$img"; else exit 1; fi ;;
  pull)
    img=$(echo "$2" | tr '/:' '__')
    touch "$state/$img" ;;
  run) echo "cid-12345" ;;
  stop|rm|rmi) : ;;
  inspect) echo "true" ;;
esac
""")
    exe.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    return log


def _start(tmp_path, config, resources=None, options=None,
           name="web") -> tuple:
    ad = AllocDir(str(tmp_path / f"alloc-{name}"))
    task = Task(name=name, driver="docker", config=config,
                resources=resources or Resources(cpu=250, memory_mb=128))
    ad.build([task])
    drv = BUILTIN_DRIVERS["docker"](
        ExecContext(ad, "a1b2c3d4e5f6", options=options))
    return drv.start(task), ad


def _run_line(log) -> str:
    return [ln for ln in log.read_text().splitlines()
            if ln.startswith("docker run")][-1]


def test_run_argv_static_dynamic_mapped_ports(tmp_path, fake_docker):
    net = NetworkResource(
        ip="10.0.0.1",
        # static 8080; dynamic labels: "http" (no mapping), "6379"
        # (numeric -> container 6379), "db" (explicit port_map -> 5432)
        reserved_ports=[8080, 20100, 20200, 20300],
        dynamic_ports=["http", "6379", "db"])
    handle, ad = _start(
        tmp_path,
        {"image": "redis:7.2", "port_map": {"db": 5432},
         "command": "redis-server", "args": "--appendonly yes"},
        Resources(cpu=250, memory_mb=128, networks=[net]))
    line = _run_line(fake_docker)
    expected = (
        "docker run -d --name nomad-a1b2c3d4-web "
        "--cpu-shares 250 --memory 128m "
        f"-v {ad.shared_dir}:/alloc -v {ad.task_dirs['web']}/local:/local "
        "-p 8080:8080 "        # static 1:1
        "-p 20100:20100 "      # non-numeric label, no mapping: 1:1
        "-p 20200:6379 "       # numeric label names the container port
        "-p 20300:5432 "       # explicit port_map wins
        "redis:7.2 redis-server --appendonly yes")
    assert line == expected
    assert handle.container_id == "cid-12345"
    assert handle.image_id == "sha256:id-redis_7.2"


def test_pull_if_absent_and_cache_hit(tmp_path, fake_docker):
    _start(tmp_path, {"image": "redis:7.2"}, name="a")
    lines = fake_docker.read_text().splitlines()
    assert any(ln.startswith("docker pull redis:7.2") for ln in lines)
    fake_docker.write_text("")
    _start(tmp_path, {"image": "redis:7.2"}, name="b")
    lines = fake_docker.read_text().splitlines()
    # Cached tag: inspect hits, no second pull.
    assert not any(ln.startswith("docker pull") for ln in lines)


def test_latest_always_repulled(tmp_path, fake_docker):
    _start(tmp_path, {"image": "redis"}, name="a")
    fake_docker.write_text("")
    _start(tmp_path, {"image": "redis"}, name="b")
    lines = fake_docker.read_text().splitlines()
    assert any(ln.startswith("docker pull redis") for ln in lines), \
        "implied :latest must re-pull every start"


def test_network_mode_passthrough(tmp_path, fake_docker):
    net = NetworkResource(ip="10.0.0.1", reserved_ports=[20100],
                          dynamic_ports=["http"])
    _start(tmp_path, {"image": "redis:7.2", "network_mode": "host"},
           Resources(cpu=100, memory_mb=64, networks=[net]))
    line = _run_line(fake_docker)
    assert "--net host" in line


def test_cleanup_knobs_from_client_options(tmp_path, fake_docker):
    handle, _ad = _start(
        tmp_path, {"image": "redis:7.2"},
        options={"docker.cleanup.container": "false",
                 "docker.cleanup.image": "false"})
    assert handle.cleanup_container is False
    assert handle.cleanup_image is False
    fake_docker.write_text("")
    handle.kill()
    lines = fake_docker.read_text().splitlines()
    assert any(ln.startswith("docker stop") for ln in lines)
    assert not any(ln.startswith("docker rm ") for ln in lines)
    assert not any(ln.startswith("docker rmi") for ln in lines)


def test_cleanup_defaults_remove_container_and_image(tmp_path,
                                                     fake_docker):
    handle, _ad = _start(tmp_path, {"image": "redis:7.2"})
    fake_docker.write_text("")
    handle.kill()
    lines = fake_docker.read_text().splitlines()
    assert any(ln.startswith("docker rm -f cid-12345") for ln in lines)
    assert any(ln.startswith("docker rmi sha256:id-redis_7.2")
               for ln in lines)


def test_reattach_roundtrip_carries_image_and_flags(tmp_path,
                                                    fake_docker):
    handle, _ad = _start(tmp_path, {"image": "redis:7.2"})
    drv = BUILTIN_DRIVERS["docker"](ExecContext(None, "x"))
    re = drv.open(handle.id())
    assert re.container_id == handle.container_id
    assert re.image_id == handle.image_id
    assert re.cleanup_container is True and re.cleanup_image is True


def test_latest_pull_failure_falls_back_to_cache(tmp_path, fake_docker,
                                                 monkeypatch):
    """An unreachable registry must not fail a task whose image is in
    the local cache (':latest' freshness pull is best-effort)."""
    # Prime the cache, then make pulls fail.
    _start(tmp_path, {"image": "redis"}, name="prime")
    state = tmp_path / "docker-state"
    bindir = tmp_path / "fakebin"
    exe = bindir / "docker"
    script = exe.read_text().replace(
        'pull)\n    img=$(echo "$2" | tr \'/:\' \'__\')\n    touch "$state/$img" ;;',
        'pull) echo "registry unreachable" >&2; exit 1 ;;')
    assert "registry unreachable" in script, "fake rewrite failed"
    exe.write_text(script)
    handle, _ad = _start(tmp_path, {"image": "redis"}, name="offline")
    assert handle.container_id == "cid-12345"
    assert handle.image_id == "sha256:id-redis"
