"""Seeded chaos: 5x offered overload + injected RPC/heartbeat latency
against the overload control plane (slow tier).

The metastable-failure rehearsal: a submission storm far beyond worker
capacity hits a server with a deliberately tiny broker bound while
every node keeps heartbeating through injected ``rpc.send`` /
``heartbeat.deliver`` delays.  Without the control plane this is the
canonical spiral (overload -> missed heartbeats -> mass TTL expiry ->
reschedule storm -> deeper overload).  With it, the bar is:

  - admission actually engaged (sheds > 0) and every shed submission
    converged through the retry policy's overload classification —
    exactly-once placement, nothing lost, nothing doubled;
  - ZERO false TTL expiries: every heartbeating node is still ready
    (brownout deferral + paced reconciliation + heartbeat rescue);
  - deadline-expired work was dropped, not scheduled (expired_drops);
  - goodput above a floor: the storm drains within the soak budget —
    no congestion collapse.
"""
from __future__ import annotations

import random
import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu import faultinject
from nomad_tpu.faultinject import FaultPlan
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool
from nomad_tpu.structs import (
    NODE_STATUS_READY,
    Evaluation,
    Resources,
    Task,
    TaskGroup,
    allocs_fit,
    generate_uuid,
)
from nomad_tpu.utils.retry import RetryPolicy, transport_or_overload

pytestmark = pytest.mark.slow

TERMINAL = ("complete", "failed", "canceled")

# Rides both transport faults AND ErrOverloaded NACKs — the designed
# client behavior under a shedding server: full-jitter backoff, never a
# lockstep stampede.
SUBMIT_POLICY = RetryPolicy(
    base=0.05, max_delay=0.8, max_attempts=60,
    retryable=transport_or_overload,
    name="chaos.overload_submit")


def _job(n_groups: int, count: int):
    job = mock.job()
    job.task_groups = [
        TaskGroup(name=f"tg-{g}", count=count,
                  tasks=[Task(name="web", driver="exec",
                              resources=Resources(cpu=100,
                                                  memory_mb=64))])
        for g in range(n_groups)]
    return job


def test_chaos_overload_brownout_converges():
    plan = FaultPlan.parse(
        "seed=77;"
        # Transport latency on every plane the storm rides.
        "rpc.send=delay(secs=0.01,p=0.3,count=300);"
        # Heartbeat deliveries are DELAYED (never dropped): any expiry
        # the server records would be a FALSE one, by construction.
        "heartbeat.deliver=delay(secs=0.02,p=0.5,count=1000)")
    with faultinject.injected(plan):
        _soak(plan)


def _soak(plan: FaultPlan) -> None:
    srv = Server(ServerConfig(
        num_schedulers=2,
        use_device_scheduler=False,
        enable_rpc=True,
        # Tiny bound so the 5x storm genuinely crosses brownout AND
        # overload; hysteresis + jittered retries converge it.
        broker_depth_limit=12,
        overload_brownout_ratio=0.5,
        overload_ratio=1.0,
        heartbeat_seed=7,
        # Slow reconciliation: were a TTL ever to expire, the pacing
        # queue gives the next heartbeat a wide rescue window.
        heartbeat_reconcile_rate=2.0,
        heartbeat_reconcile_burst=1.0,
    ))
    srv.heartbeats.min_ttl = 1.0
    srv.heartbeats.grace = 0.5
    srv.heartbeats.brownout_defer = 0.5
    srv.establish_leadership()
    pool = ConnPool()
    try:
        addr = srv.rpc_address()
        n_nodes = 16
        nodes = []
        for i in range(n_nodes):
            node = mock.node(i)
            out = SUBMIT_POLICY.call(
                lambda n=node: pool.call(addr, "Node.Register",
                                         {"node": n.to_dict()},
                                         timeout=5.0))
            assert out["heartbeat_ttl"] > 0
            nodes.append(node.id)

        # Background heartbeater: every node beats well inside its TTL
        # for the WHOLE soak.  Liveness must ride the bypass lane
        # untouched while the storm sheds all around it.
        stop_beat = threading.Event()
        beat_errors: list = []

        def _beater() -> None:
            while not stop_beat.is_set():
                for nid in nodes:
                    try:
                        pool.call(addr, "Node.Heartbeat",
                                  {"node_id": nid}, timeout=3.0)
                    except Exception as e:
                        beat_errors.append((nid, repr(e)))
                stop_beat.wait(0.2)

        beater = threading.Thread(target=_beater, daemon=True,
                                  name="overload-heartbeater")
        beater.start()

        # Synthetic deadline-bounded work: submissions beyond capacity
        # whose usefulness expires — they must be DROPPED (failed via
        # the reaper), never scheduled.
        n_expired = 6
        for _ in range(n_expired):
            ev = Evaluation(id=generate_uuid(), priority=1,
                            type="service", triggered_by="job-register",
                            job_id=generate_uuid(), status="pending")
            srv.eval_broker.enqueue(ev, deadline=time.monotonic() - 0.01,
                                    force=True)

        # The 5x storm: offered load must EXCEED capacity for real, so
        # the workers are paused while 4 concurrent submitters push 20
        # jobs at a 12-deep broker bound — queues fill, the controller
        # crosses brownout into overload, submissions get shed and ride
        # the retry policy; then capacity returns and the storm drains.
        for w in srv.workers:
            w.set_pause(True)
        t0 = time.monotonic()
        jobs = [_job(n_groups=4, count=2) for _ in range(20)]
        submit_errors: list = []

        def _submitter(lane: int) -> None:
            rng = random.Random(2026 + lane)
            for job in jobs[lane::4]:
                try:
                    SUBMIT_POLICY.call(
                        lambda j=job: pool.call(addr, "Job.Register",
                                                {"job": j.to_dict()},
                                                timeout=3.0),
                        rng=rng)
                except Exception as e:
                    submit_errors.append(repr(e))

        submitters = [threading.Thread(target=_submitter, args=(i,),
                                       daemon=True,
                                       name=f"submitter-{i}")
                      for i in range(4)]
        for t in submitters:
            t.start()
        # Hold the brownout until admission demonstrably engaged.
        from tests.conftest import wait_until
        wait_until(lambda: srv.overload.shed_count() > 0, timeout=30.0,
                   msg="admission shed under the paused-worker storm")
        for w in srv.workers:
            w.set_pause(False)
        for t in submitters:
            t.join(60.0)
        assert not submit_errors, \
            f"submissions failed to converge: {submit_errors[:3]}"

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            evals = srv.fsm.state.evals()
            if evals and len(evals) >= len(jobs) + n_expired and \
                    all(e.status in TERMINAL for e in evals):
                break
            time.sleep(0.1)  # sleep-ok: poll cadence while the storm converges
        storm_wall = time.monotonic() - t0

        stop_beat.set()
        beater.join(5.0)
        state = srv.fsm.state

        # 1) Converged: nothing stuck.
        stuck = [(e.id, e.status) for e in state.evals()
                 if e.status not in TERMINAL]
        assert not stuck, f"non-terminal evals after soak: {stuck[:5]}"

        # 2) ZERO false expiries.  Every node heartbeated throughout;
        # every one must still be ready and the manager must have
        # invalidated nobody.
        hb_stats = srv.heartbeats.stats()
        assert hb_stats["expiries"] == 0, hb_stats
        for nid in nodes:
            assert state.node_by_id(nid).status == NODE_STATUS_READY, \
                f"false TTL expiry on {nid}"
        assert not beat_errors, \
            f"heartbeats failed under overload: {beat_errors[:3]}"

        # 3) Admission engaged and the storm still converged: the
        # overload plane genuinely shed (this test is meaningless if
        # the storm never crossed the thresholds).
        assert srv.overload.shed_count() > 0, srv.overload.stats()

        # 4) Deadline-expired work dropped, not scheduled: each
        # synthetic eval was failed by the reaper, placed nowhere.
        assert srv.eval_broker.stats()["expired_drops"] >= n_expired
        expired_failed = [e for e in state.evals()
                         if e.priority == 1 and e.status == "failed"]
        assert len(expired_failed) == n_expired

        # 5) Exactly-once placement on live capacity.
        for job in jobs:
            live = [a for a in state.allocs_by_job(job.id)
                    if not a.terminal_status()]
            want = sum(tg.count for tg in job.task_groups)
            assert len(live) == want, \
                f"job {job.id}: {len(live)} live allocs, want {want}"
            by_group: dict = {}
            for a in live:
                by_group[a.task_group] = by_group.get(a.task_group, 0) + 1
            assert all(by_group[tg.name] == tg.count
                       for tg in job.task_groups), "duplicate placement"

        # 6) No oversubscription.
        for nid in nodes:
            node = state.node_by_id(nid)
            live = [a for a in state.allocs_by_node(nid)
                    if not a.terminal_status()]
            fit, dim, _ = allocs_fit(node, live)
            assert fit, f"node {nid} oversubscribed on {dim}"

        # 7) Goodput floor — no congestion collapse: the storm drained
        # at real throughput, not a crawl of synchronized retries.
        goodput = len(jobs) / storm_wall
        assert goodput >= 0.5, \
            f"congestion collapse: {goodput:.2f} jobs/s over " \
            f"{storm_wall:.1f}s"

        # 8) The latency chaos really ran.
        assert plan.fire_count("heartbeat.deliver") > 0
        assert plan.fire_count("rpc.send") > 0
    finally:
        pool.shutdown()
        srv.shutdown()
