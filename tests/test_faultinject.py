"""Fault-injection subsystem: spec grammar, registry lifecycle, and one
fast unit test per instrumented site (rpc.send, rpc.recv, raft.apply,
heartbeat.deliver, device.dispatch, device.collect, driver.start), plus
the device-executor circuit breaker's state machine and the client
retry regressions the subsystem was built to catch.
"""
from __future__ import annotations

import logging
import os
import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu import faultinject
from nomad_tpu.faultinject import (
    FaultDropped,
    FaultInjected,
    FaultPlan,
    FaultSpecError,
)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test here starts and ends with no active plan."""
    faultinject.clear_plan()
    yield
    faultinject.clear_plan()


# ---------------------------------------------------------------------------
# spec grammar + registry lifecycle
# ---------------------------------------------------------------------------

class TestSpecAndRegistry:
    def test_trivial_plan_injects_and_clears(self):
        """Tier-1 smoke: install -> fire -> clear is airtight."""
        assert not faultinject.ACTIVE
        faultinject.fire("raft.apply")  # no plan: no-op
        plan = FaultPlan().add("raft.apply", "error", count=1)
        faultinject.install_plan(plan)
        assert faultinject.ACTIVE
        with pytest.raises(FaultInjected):
            faultinject.fire("raft.apply")
        faultinject.fire("raft.apply")  # budget spent: no-op
        assert plan.exhausted()
        faultinject.clear_plan()
        assert not faultinject.ACTIVE
        assert faultinject.active_plan() is None
        faultinject.fire("raft.apply")  # cleared: no-op again
        assert plan.fire_count() == 1

    def test_injected_context_restores_previous(self):
        outer = FaultPlan()
        faultinject.install_plan(outer)
        with faultinject.injected(FaultPlan()) as inner:
            assert faultinject.active_plan() is inner
        assert faultinject.active_plan() is outer

    def test_injected_context_clears_on_exception(self):
        with pytest.raises(RuntimeError):
            with faultinject.injected(FaultPlan()):
                raise RuntimeError("test failure mid-soak")
        assert not faultinject.ACTIVE

    def test_parse_full_grammar(self):
        plan = FaultPlan.parse(
            "seed=7;"
            "rpc.send=drop(p=0.5,count=3,method=Node.*);"
            "heartbeat.deliver=drop(node=n-1);"
            "device.collect=hang(secs=0.01);"
            "raft.apply=delay(secs=0.02,after=2)")
        assert plan.seed == 7
        rules = {r.site: r for r in plan.rules()}
        assert rules["rpc.send"].action == "drop"
        assert rules["rpc.send"].p == 0.5
        assert rules["rpc.send"].count == 3
        assert rules["rpc.send"].method == "Node.*"
        assert rules["heartbeat.deliver"].node == "n-1"
        assert rules["device.collect"].secs == 0.01
        assert rules["raft.apply"].after == 2

    def test_serving_plane_sites_registered(self):
        """ISSUE 7 satellite: the edge chokepoints are first-class
        sites with the right predicate contexts."""
        from nomad_tpu.faultinject.plan import SITE_CONTEXT, SITES

        assert len(SITES) == 16
        for site in ("mux.accept", "conn.read", "watch.deliver"):
            assert site in SITES
        assert SITE_CONTEXT["mux.accept"] == ()
        assert SITE_CONTEXT["conn.read"] == ()
        assert SITE_CONTEXT["watch.deliver"] == ("method",)
        # The grammar accepts table-name predicates on watch.deliver.
        plan = FaultPlan.parse(
            "mux.accept=error(count=1);conn.read=drop(p=0.1);"
            "watch.deliver=drop(method=allocs)")
        rules = {r.site: r for r in plan.rules()}
        assert rules["watch.deliver"].method == "allocs"

    def test_storage_sites_registered(self):
        """ISSUE 8 satellite: the durable-storage chokepoints are
        first-class sites (16-site table) with path predicates, and
        the ``crash`` action is storage-only."""
        from nomad_tpu.faultinject.plan import (
            SITE_CONTEXT,
            SITES,
            STORAGE_SITES,
        )

        assert STORAGE_SITES == ("log.append", "log.fsync",
                                 "snapshot.persist", "meta.persist")
        for site in STORAGE_SITES:
            assert site in SITES
            # Stores pass their on-disk path as ``method`` so one
            # server's data_dir is targetable in a cluster soak.
            assert SITE_CONTEXT[site] == ("method",)
        plan = FaultPlan.parse(
            "seed=3;log.append=crash(count=1,after=2);"
            "snapshot.persist=crash(method=/tmp/cluster/s1*)")
        rules = {r.site: r for r in plan.rules()}
        assert rules["log.append"].action == "crash"
        assert rules["snapshot.persist"].method == "/tmp/cluster/s1*"
        # Non-crash actions remain legal at storage sites (a plain
        # slow disk is delay/error, not power loss).
        FaultPlan.parse("log.fsync=delay(secs=0.01);meta.persist=error")

    def test_crash_is_seeded_and_latches(self, tmp_path):
        """The crash action draws its torn-byte layout from the plan's
        seeded RNG (same seed = same bytes) and latches the plan so
        every storage site refuses writes until reset."""
        from nomad_tpu.faultinject import FaultCrash
        from nomad_tpu.server.raft import FileLogStore, StorageDead

        def torn_size(seed: int) -> int:
            path = str(tmp_path / f"log-{seed}.bin")
            store = FileLogStore(path)
            plan = FaultPlan(seed=seed).add("log.append", "crash",
                                            count=1)
            with faultinject.injected(plan):
                with pytest.raises(FaultCrash):
                    store.append(1, b"payload-payload-payload")
                assert plan.is_crashed()
                assert faultinject.crashed()
                with pytest.raises(StorageDead):
                    store.append(2, b"more")
            assert not faultinject.crashed()  # plan uninstalled
            store.close()
            return os.path.getsize(path)

        assert torn_size(42) == torn_size(42)  # deterministic replay
        sizes = {torn_size(s) for s in (1, 2, 3, 4, 5)}
        assert len(sizes) > 1  # the offset really is seed-drawn

    @pytest.mark.parametrize("bad", [
        "nope.site=error",               # unknown site
        "rpc.send=explode",              # unknown action
        "rpc.send=error(p=oops)",        # bad float
        "rpc.send=error(count=1.5)",     # bad int
        "rpc.send=error(zap=1)",         # unknown param
        "rpc.send",                      # missing '='
        "seed=abc",                      # bad seed
        "rpc.send=error(p=0.5",          # unterminated params
        "rpc.send=error(p=2)",           # probability out of range
        "raft.apply=error(method=X)",    # site supplies no method ctx
        "device.collect=error(node=n)",  # site supplies no node ctx
        "heartbeat.deliver=drop(method=Node.Heartbeat)",  # node-only site
        "mux.accept=error(method=X)",    # edge accept has no request ctx
        "conn.read=drop(node=n-1)",      # bytes have no node identity
        "watch.deliver=drop(node=n-1)",  # fan-out passes table as method
        "rpc.send=crash",                # crash only at storage sites
        "raft.apply=crash(count=1)",     # ditto: no bytes in flight
        "log.append=crash(node=n-1)",    # stores pass path as method
    ])
    def test_parse_rejects_malformed(self, bad):
        with pytest.raises(FaultSpecError):
            FaultPlan.parse(bad)

    def test_node_predicate_matches_alloc_update_payload(self):
        """fire_rpc digs the node id out of Node.UpdateAlloc's nested
        update dicts, so node-targeted rules cover that traffic too
        (a predicate that can never fire is rejected at parse; one
        that CAN fire must actually see the id)."""
        plan = FaultPlan().add("rpc.send", "error", node="n-7")
        with faultinject.injected(plan):
            faultinject.fire_rpc("rpc.send", "Node.UpdateAlloc",
                                 {"alloc": [{"id": "a", "node_id": "x"}]})
            with pytest.raises(FaultInjected):
                faultinject.fire_rpc(
                    "rpc.send", "Node.UpdateAlloc",
                    {"alloc": [{"id": "a", "node_id": "n-7"}]})

    def test_seeded_probability_is_deterministic(self):
        def run():
            out = []
            with faultinject.injected(
                    FaultPlan.parse("seed=11;rpc.send=drop(p=0.5)")):
                for _ in range(32):
                    try:
                        faultinject.fire("rpc.send")
                        out.append(0)
                    except FaultDropped:
                        out.append(1)
            return out

        first = run()
        assert first == run()
        assert 0 < sum(first) < 32  # actually probabilistic

    def test_match_predicates_and_after(self):
        plan = FaultPlan()
        plan.add("rpc.send", "error", method="Node.Register",
                 node="n-*", after=1)
        with faultinject.injected(plan):
            # Wrong method / wrong node / first match skipped.
            faultinject.fire("rpc.send", method="Job.Register", node="n-1")
            faultinject.fire("rpc.send", method="Node.Register", node="x")
            faultinject.fire("rpc.send", method="Node.Register", node="n-1")
            with pytest.raises(FaultInjected):
                faultinject.fire("rpc.send", method="Node.Register",
                                 node="n-2")


# ---------------------------------------------------------------------------
# per-site units
# ---------------------------------------------------------------------------

class TestSites:
    def test_rpc_send_site(self):
        """ConnPool.call consults rpc.send before anything touches the
        wire — no server needed to prove the drop."""
        from nomad_tpu.server.rpc import ConnPool

        pool = ConnPool()
        plan = FaultPlan().add("rpc.send", "drop", count=1,
                               method="Status.Ping")
        with faultinject.injected(plan):
            with pytest.raises(FaultDropped):
                pool.call(("127.0.0.1", 1), "Status.Ping", {})
        assert plan.fire_count("rpc.send") == 1
        pool.shutdown()

    def test_rpc_recv_drop_and_error(self):
        """Server-side receive faults: ``drop`` swallows the request
        (caller sees only its own timeout), ``error`` surfaces as an
        RPC error reply."""
        from nomad_tpu.server.rpc import ConnPool, RPCError, RPCServer

        srv = RPCServer()
        srv.register("Echo.Hello", lambda args: {"hi": 1})
        srv.start()
        pool = ConnPool()
        try:
            plan = FaultPlan()
            plan.add("rpc.recv", "drop", count=1)
            plan.add("rpc.recv", "error", count=1)
            with faultinject.injected(plan):
                with pytest.raises(TimeoutError):
                    pool.call(srv.address, "Echo.Hello", {}, timeout=0.4)
                with pytest.raises(RPCError, match="injected"):
                    pool.call(srv.address, "Echo.Hello", {})
                # Budget spent: the plane is healthy again.
                assert pool.call(srv.address, "Echo.Hello", {}) == \
                    {"hi": 1}
        finally:
            pool.shutdown()
            srv.shutdown()

    def test_rpc_recv_drop_on_plain_plane(self):
        """The non-mux (0x01) plane swallows dropped frames too."""
        from nomad_tpu.server.rpc import ConnPool, RPCServer

        srv = RPCServer()
        srv.register("Echo.Hello", lambda args: {"hi": 1})
        srv.start()
        pool = ConnPool(multiplex=False)
        try:
            with faultinject.injected(
                    FaultPlan().add("rpc.recv", "drop", count=1)):
                with pytest.raises((TimeoutError, OSError)):
                    pool.call(srv.address, "Echo.Hello", {}, timeout=0.4)
            assert pool.call(srv.address, "Echo.Hello", {}) == {"hi": 1}
        finally:
            pool.shutdown()
            srv.shutdown()

    def test_raft_apply_site(self):
        from nomad_tpu.server.raft import InmemRaft

        class _FSM:
            def apply(self, index, entry):
                return None

        raft = InmemRaft(_FSM())
        with faultinject.injected(
                FaultPlan().add("raft.apply", "error", count=1)):
            with pytest.raises(FaultInjected):
                raft.apply(b"entry")
            # Budget spent: the log moves again.
            raft.apply(b"entry").wait(1.0)
        assert raft.applied_index() == 1

    def test_heartbeat_deliver_site(self):
        """A dropped delivery leaves the TTL timer un-reset: the node
        is on the path to expiry while the client sees an error."""
        from nomad_tpu.server.heartbeat import HeartbeatManager

        hb = HeartbeatManager(server=None, timer_factory=_FakeTimer)
        try:
            plan = FaultPlan().add("heartbeat.deliver", "drop",
                                   node="n-victim")
            with faultinject.injected(plan):
                assert hb.reset_heartbeat_timer("n-ok") > 0
                with pytest.raises(FaultDropped):
                    hb.reset_heartbeat_timer("n-victim")
            with hb._lock:
                assert "n-ok" in hb._timers
                assert "n-victim" not in hb._timers
        finally:
            hb.clear()

    def test_driver_start_site(self, tmp_path):
        from nomad_tpu.client.allocdir import AllocDir
        from nomad_tpu.client.driver.base import ExecContext
        from nomad_tpu.client.task_runner import TaskRunner
        from nomad_tpu.structs import Resources, Task

        task = Task(name="echo", driver="raw_exec",
                    config={"command": "/bin/sh",
                            "args": "-c 'echo hi'"},
                    resources=Resources(cpu=100, memory_mb=64))
        ad = AllocDir(str(tmp_path / "alloc"))
        ad.build([task])
        states = []
        tr = TaskRunner(ExecContext(ad, "a"), task,
                        on_state=lambda n, s, d: states.append((s, d)))
        with faultinject.injected(
                FaultPlan().add("driver.start", "error",
                                method="raw_exec")):
            tr.run()  # inline: deterministic, no thread needed
        assert tr.failed
        assert tr.state == "dead"
        assert any("injected" in d for _s, d in states)


def _FakeTimer(ttl, fn, args):
    """Inert timer for fake-clock heartbeat tests."""
    class _T:
        def __init__(self):
            self.ttl = ttl
            self.fn = fn
            self.args = args
            self.cancelled = False

        def start(self):
            pass

        def cancel(self):
            self.cancelled = True

        def fire(self):
            self.fn(*self.args)
    return _T()


# ---------------------------------------------------------------------------
# device sites + circuit breaker through the pipeline
# ---------------------------------------------------------------------------

def _pipeline_cluster(n_nodes: int, n_jobs: int):
    from nomad_tpu.scheduler import Harness

    h = Harness()
    for i in range(n_nodes):
        h.state.upsert_node(h.next_index(), mock.node(i))
    jobs = []
    for _ in range(n_jobs):
        j = mock.job()
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)
    return h, jobs


def _make_eval(job):
    from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER, Evaluation,
                                   generate_uuid)

    return Evaluation(id=generate_uuid(), priority=job.priority,
                      type=job.type,
                      triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                      job_id=job.id)


class TestDeviceBreaker:
    def test_dispatch_fault_trips_breaker_then_probe_closes(self):
        """device.dispatch fault: the eval re-runs on the host twin
        (still completes), the breaker opens, holds subsequent evals on
        host, then a half-open probe parity-checks and closes."""
        from nomad_tpu.scheduler.breaker import (CLOSED, OPEN,
                                                 DeviceCircuitBreaker)
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner

        h, jobs = _pipeline_cluster(8, 3)
        breaker = DeviceCircuitBreaker(failure_threshold=1, cooldown=30.0)
        plan = FaultPlan().add("device.dispatch", "error", count=1)
        with faultinject.injected(plan), executor_override("device"):
            # Round 1: first dispatch faults -> open; the window's
            # remaining evals are held on host.
            r1 = PipelinedEvalRunner(h.state.snapshot(), h, depth=2,
                                     breaker=breaker)
            r1.process([_make_eval(j) for j in jobs[:2]])
            assert breaker.state == OPEN
            assert r1.breaker_reruns == 1
            assert breaker.stats()["opens"] == 1
            assert breaker.stats()["host_holds"] >= 1

            # Round 2: cooldown elapsed (fake it) -> probe -> parity
            # asserted -> closed.
            with breaker._lock:
                breaker._opened_at = -1e9
            r2 = PipelinedEvalRunner(h.state.snapshot(), h, depth=2,
                                     breaker=breaker,
                                     state_refresh=lambda:
                                     h.state.snapshot())
            r2.process([_make_eval(jobs[2])])
            assert breaker.state == CLOSED
            assert breaker.stats()["probes"] == 1
            assert breaker.stats()["closes"] == 1
            assert r2.parity_checks == 1
        assert all(e.status == "complete" for e in h.evals)
        assert len(h.plans) == 3

    def test_collect_fault_reruns_on_host(self):
        """device.collect fault mid-window: drain re-runs that eval on
        the host twin; plans still land, breaker records the failure."""
        import time as _time

        from nomad_tpu.scheduler.breaker import DeviceCircuitBreaker
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner, _Item

        h, jobs = _pipeline_cluster(8, 3)
        breaker = DeviceCircuitBreaker(failure_threshold=2, cooldown=30.0)
        runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=8,
                                     breaker=breaker)
        plan = FaultPlan().add("device.collect", "error", count=1)
        with faultinject.injected(plan), executor_override("device"):
            window = []
            for j in jobs:
                start = _time.perf_counter()
                sched = runner._begin_eval(_make_eval(j),
                                           finish_noop=False)
                place, args = sched.deferred
                handles, probe = runner._dispatch(sched, args)
                window.append(_Item(sched, place, args, handles, start,
                                    probe=probe))
            runner._drain_window(window)
        assert runner.breaker_reruns == 1
        assert breaker.stats()["failures"] == 1
        assert breaker.state == "closed"  # threshold=2, one failure
        assert all(e.status == "complete" for e in h.evals)
        assert len(h.plans) == 3

    def test_collect_deadline_breaks_hang(self):
        """A hung device collect (injected hang) is cut off by the
        watchdog deadline and re-run on host."""
        import time as _time

        from nomad_tpu.scheduler.breaker import OPEN, DeviceCircuitBreaker
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner, _Item

        h, jobs = _pipeline_cluster(8, 1)
        breaker = DeviceCircuitBreaker(failure_threshold=1, cooldown=30.0)
        runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=2,
                                     breaker=breaker,
                                     device_deadline=0.2)
        plan = FaultPlan().add("device.collect", "hang", secs=1.5,
                               count=1)
        t0 = _time.monotonic()
        with faultinject.injected(plan), executor_override("device"):
            sched = runner._begin_eval(_make_eval(jobs[0]),
                                       finish_noop=False)
            place, args = sched.deferred
            handles, probe = runner._dispatch(sched, args)
            runner._drain_window([_Item(sched, place, args, handles,
                                        _time.perf_counter(),
                                        probe=probe)])
        # The watchdog cut the hang off well before its 1.5s.
        assert _time.monotonic() - t0 < 1.2
        assert runner.breaker_reruns == 1
        assert breaker.state == OPEN
        assert all(e.status == "complete" for e in h.evals)

    def test_breaker_state_machine_with_fake_clock(self):
        from nomad_tpu.scheduler.breaker import (ADMIT_DEVICE, ADMIT_HOST,
                                                 ADMIT_PROBE, CLOSED,
                                                 HALF_OPEN, OPEN,
                                                 DeviceCircuitBreaker)

        now = [0.0]
        b = DeviceCircuitBreaker(failure_threshold=2, cooldown=10.0,
                                 clock=lambda: now[0])
        assert b.admit() == ADMIT_DEVICE
        b.record_failure()
        assert b.state == CLOSED          # below threshold
        b.record_success()                # success resets the streak
        b.record_failure()
        b.record_failure()
        assert b.state == OPEN            # threshold consecutive
        assert b.admit() == ADMIT_HOST    # held during cooldown
        now[0] += 10.0
        assert b.admit() == ADMIT_PROBE   # cooldown elapsed
        assert b.state == HALF_OPEN
        assert b.admit() == ADMIT_HOST    # one probe in flight at a time
        b.record_failure(probe=True)      # probe failed: re-open
        assert b.state == OPEN
        now[0] += 10.0
        assert b.admit() == ADMIT_PROBE
        b.record_success(probe=True)
        assert b.state == CLOSED
        stats = b.stats()
        assert stats["opens"] == 2 and stats["closes"] == 1
        assert stats["probes"] == 2 and stats["host_holds"] == 2

    def test_lost_probe_outcome_reprobes_after_timeout(self):
        """Review regression: a probe whose outcome is never recorded
        (its window was discarded by an unrelated drain error) must not
        pin the breaker half-open-on-host forever — past probe_timeout
        a fresh probe is issued."""
        from nomad_tpu.scheduler.breaker import (ADMIT_HOST, ADMIT_PROBE,
                                                 CLOSED,
                                                 DeviceCircuitBreaker)

        now = [0.0]
        b = DeviceCircuitBreaker(failure_threshold=1, cooldown=1.0,
                                 probe_timeout=5.0,
                                 clock=lambda: now[0])
        b.record_failure()           # open
        now[0] += 1.0
        assert b.admit() == ADMIT_PROBE
        # ... the probe item is lost: no outcome ever recorded ...
        now[0] += 4.0
        assert b.admit() == ADMIT_HOST    # not yet presumed lost
        now[0] += 1.5
        assert b.admit() == ADMIT_PROBE   # presumed lost: re-probe
        b.record_success(probe=True)
        assert b.state == CLOSED

    def test_probe_parity_mismatch_fails_loudly_and_reopens(self):
        """Review regression: a probe whose device result disagrees
        with the host twin must raise (not silently close the breaker)
        and re-open it."""
        import numpy as np

        from nomad_tpu.scheduler.breaker import OPEN, DeviceCircuitBreaker
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner

        h, jobs = _pipeline_cluster(8, 1)
        breaker = DeviceCircuitBreaker(failure_threshold=1, cooldown=0.0)
        breaker.record_failure()  # open; next admission is a probe

        class _CorruptHostTwin(PipelinedEvalRunner):
            def _host_rerun(self, it):
                chosen, scores = super()._host_rerun(it)
                return np.asarray(chosen) + 1, scores  # disagree

        runner = _CorruptHostTwin(h.state.snapshot(), h, depth=2,
                                  breaker=breaker)
        with executor_override("device"):
            with pytest.raises(RuntimeError, match="parity violation"):
                runner.process([_make_eval(jobs[0])])
        assert breaker.state == OPEN  # probe failure re-opened it
        assert runner.parity_checks == 0

    def test_pipeline_unaffected_without_faults(self):
        """No plan, forced device: the breaker stays closed and counts
        stay clean (the parity suite guards semantics; this guards the
        new plumbing's no-fault path)."""
        from nomad_tpu.scheduler.breaker import DeviceCircuitBreaker
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner

        h, jobs = _pipeline_cluster(8, 3)
        breaker = DeviceCircuitBreaker()
        runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=2,
                                     breaker=breaker)
        with executor_override("device"):
            runner.process([_make_eval(j) for j in jobs])
        assert breaker.state == "closed"
        assert breaker.stats() == {"opens": 0, "closes": 0, "probes": 0,
                                   "host_holds": 0, "failures": 0,
                                   "state": "closed"}
        assert runner.breaker_reruns == 0
        assert runner.device_dispatches == len(jobs)
        assert runner.host_dispatches == 0
        assert all(e.status == "complete" for e in h.evals)


# ---------------------------------------------------------------------------
# client retry regressions (the satellites)
# ---------------------------------------------------------------------------

class _ScriptedRPC:
    """In-proc rpc_handler whose UpdateAlloc failures are scripted."""

    def __init__(self, fail_updates: int = 0) -> None:
        self.fail_updates = fail_updates
        self.update_payloads: list = []
        self.lock = threading.Lock()

    def call(self, method: str, args: dict, timeout=None):
        if method == "Node.UpdateAlloc":
            with self.lock:
                if self.fail_updates > 0:
                    self.fail_updates -= 1
                    raise ConnectionError("scripted outage")
                self.update_payloads.append(args["alloc"])
            return {}
        return {"heartbeat_ttl": 10.0}


def _make_client(rpc_handler):
    from nomad_tpu.client import Client, ClientConfig

    return Client(ClientConfig(
        rpc_handler=rpc_handler,
        options={"fingerprint.skip_accel": "1"}))


def _alloc_update(alloc_id: str, status: str):
    from nomad_tpu.structs import Allocation

    return Allocation(id=alloc_id, client_status=status,
                      node_id="n-1", task_states={})


class TestClientRetries:
    def test_update_alloc_failure_queues_for_heartbeat(self, monkeypatch):
        """Satellite: a Node.UpdateAlloc that exhausts its retry burst
        is queued, not dropped, and the next heartbeat delivers it."""
        import nomad_tpu.client.client as client_mod
        from nomad_tpu.utils.retry import RetryPolicy

        monkeypatch.setattr(
            client_mod, "UPDATE_ALLOC_POLICY",
            RetryPolicy(base=0.01, max_delay=0.02, max_attempts=2,
                        retryable=lambda e: isinstance(e, Exception),
                        name="test.update_alloc"))
        rpc = _ScriptedRPC(fail_updates=5)  # outlasts one burst
        client = _make_client(rpc)
        try:
            client._sync_alloc_status(_alloc_update("a-1", "failed"))
            with client._update_lock:
                assert "a-1" in client._pending_updates  # queued, not lost
            # Newer status for the same alloc supersedes the queued one.
            client._sync_alloc_status(_alloc_update("a-1", "complete"))

            rpc.fail_updates = 0  # server back: heartbeat flushes
            client._flush_alloc_updates()
            with client._update_lock:
                assert not client._pending_updates
            assert len(rpc.update_payloads) == 1
            (delivered,) = rpc.update_payloads[0]
            assert delivered["id"] == "a-1"
            assert delivered["client_status"] == "complete"
        finally:
            client.shutdown()

    def test_flush_retry_resnapshots_queue(self, monkeypatch):
        """Review regression: a retry attempt must re-snapshot the
        queue, never re-send a payload a newer update superseded
        mid-burst (the stale re-send would regress a terminal status
        on the server)."""
        import nomad_tpu.client.client as client_mod
        from nomad_tpu.utils.retry import RetryPolicy

        monkeypatch.setattr(
            client_mod, "UPDATE_ALLOC_POLICY",
            RetryPolicy(base=0.01, max_delay=0.02, max_attempts=3,
                        retryable=lambda e: isinstance(e, Exception),
                        name="test.update_alloc"))

        client = _make_client(None)  # handler installed below

        class _FailOnceThenRecord:
            def __init__(self):
                self.payloads = []
                self.failed = False

            def call(self, method, args, timeout=None):
                if method != "Node.UpdateAlloc":
                    return {"heartbeat_ttl": 10.0}
                if not self.failed:
                    self.failed = True
                    # Simulate a runner queueing a NEWER status while
                    # this attempt is failing.
                    with client._update_lock:
                        client._pending_updates["a-1"] = {
                            "id": "a-1", "client_status": "complete",
                            "client_description": "",
                            "task_states": {}, "node_id": "n-1"}
                    raise ConnectionError("first attempt lost")
                self.payloads.append(args["alloc"])
                return {}

        rpc = _FailOnceThenRecord()
        client.rpc = rpc
        try:
            client._sync_alloc_status(_alloc_update("a-1", "running"))
            assert len(rpc.payloads) == 1
            (delivered,) = rpc.payloads[0]
            assert delivered["client_status"] == "complete"  # not stale
            with client._update_lock:
                assert not client._pending_updates
        finally:
            client.shutdown()

    def test_update_alloc_success_path_unqueued(self):
        rpc = _ScriptedRPC()
        client = _make_client(rpc)
        try:
            client._sync_alloc_status(_alloc_update("a-2", "running"))
            with client._update_lock:
                assert not client._pending_updates
            assert len(rpc.update_payloads) == 1
        finally:
            client.shutdown()

    def test_register_backoff_with_injected_fault(self, monkeypatch,
                                                  caplog):
        """Satellite: registration under an injected rpc.send fault
        retries with capped backoff and logs one traceback then
        one-line WARNs — and eventually registers."""
        import nomad_tpu.client.client as client_mod
        from nomad_tpu.server import Server, ServerConfig

        monkeypatch.setattr(client_mod, "REGISTER_RETRY_INTERVAL", 0.02)
        monkeypatch.setattr(client_mod, "REGISTER_RETRY_MAX", 0.05)
        srv = Server(ServerConfig(enable_rpc=True, num_schedulers=0))
        srv.establish_leadership()
        client = None
        try:
            from nomad_tpu.client import Client, ClientConfig

            client = Client(ClientConfig(
                servers=[srv.rpc_address()],
                options={"fingerprint.skip_accel": "1"}))
            plan = FaultPlan().add("rpc.send", "error", count=3,
                                   method="Node.Register")
            with caplog.at_level(logging.WARNING, logger="nomad_tpu"):
                with faultinject.injected(plan):
                    client._register()
            assert srv.fsm.state.node_by_id(client.node.id) is not None
            assert plan.fire_count("rpc.send") == 3
            warns = [r for r in caplog.records
                     if "registration" in r.getMessage()]
            assert len(warns) == 3
            assert all(r.levelno == logging.WARNING for r in warns)
            # Traceback on the first only; the rest are one-liners.
            assert warns[0].exc_info
            assert not any(r.exc_info for r in warns[1:])
        finally:
            if client is not None:
                client.shutdown()
            srv.shutdown()

    def test_register_gives_up_on_shutdown(self, monkeypatch):
        """The capped backoff honors shutdown: _register returns when
        the client stops, instead of spinning forever."""
        import nomad_tpu.client.client as client_mod

        monkeypatch.setattr(client_mod, "REGISTER_RETRY_INTERVAL", 0.02)
        monkeypatch.setattr(client_mod, "REGISTER_RETRY_MAX", 0.05)

        class _DeadRPC:
            def call(self, method, args, timeout=None):
                raise ConnectionError("nobody home")

        client = _make_client(_DeadRPC())
        t = threading.Thread(target=client._register, daemon=True)
        t.start()
        time.sleep(0.1)  # sleep-ok: park _register inside its backoff sleep
        client._shutdown.set()
        t.join(2.0)
        assert not t.is_alive()
        client.shutdown()


# ---------------------------------------------------------------------------
# site liveness: every registered site fires under one seeded plan
# ---------------------------------------------------------------------------

class TestSiteLiveness:
    """One seeded plan with a benign delay rule per registered site,
    driven through a live server (plus the device pipeline, a raw_exec
    driver, and the durable meta store — the planes a single server
    process does not own).  Every site must fire at least once, and
    placement must still converge exactly once: a site that never
    fires is registered-but-dead instrumentation the static pass's
    ``dead-site`` rule cannot see from the callgraph alone."""

    TERMINAL = ("complete", "failed", "canceled")

    def test_every_registered_site_fires(self, tmp_path):
        from nomad_tpu.faultinject.plan import SITES

        plan = FaultPlan(seed=19)
        for site in SITES:
            # delay(1ms): proves the chokepoint is consulted without
            # perturbing any outcome the convergence bar asserts.
            plan.add(site, "delay", secs=0.001)

        with faultinject.injected(plan):
            self._server_phase(plan, tmp_path)
            self._device_phase()
            self._driver_phase(tmp_path)
            self._meta_phase(tmp_path)

        silent = [s for s in SITES if plan.fire_count(s) == 0]
        assert not silent, f"registered-but-dead fault sites: {silent}"

    def _server_phase(self, plan, tmp_path):
        """Real RPC server with a durable raft plane: covers the rpc,
        mux, raft-storage, broker, heartbeat, and watch sites."""
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.server.rpc import ConnPool
        from nomad_tpu.structs import Resources, Task, TaskGroup

        srv = Server(ServerConfig(
            num_schedulers=2, enable_rpc=True,
            data_dir=str(tmp_path / "data"),
            raft_snapshot_threshold=4))  # trip snapshot.persist early
        srv.establish_leadership()
        pool = ConnPool()
        try:
            addr = srv.rpc_address()

            nodes = [mock.node(i) for i in range(4)]
            for node in nodes:
                out = pool.call(addr, "Node.Register",
                                {"node": node.to_dict()}, timeout=5.0)
                assert out["heartbeat_ttl"] > 0
            for node in nodes:
                pool.call(addr, "Node.Heartbeat",
                          {"node_id": node.id}, timeout=5.0)

            # Park a blocking query at the current index, then advance
            # it: the matured waiter rides the watch.deliver site.
            cur = srv.fsm.state.get_index("nodes")
            blocked: list = []
            waiter = threading.Thread(
                target=lambda: blocked.append(
                    pool.call(addr, "Node.List",
                              {"min_query_index": cur,
                               "max_query_time": 5.0}, timeout=10.0)),
                daemon=True)
            waiter.start()
            time.sleep(0.2)  # sleep-ok: let the query park on the watch
            late = mock.node(99)
            pool.call(addr, "Node.Register",
                      {"node": late.to_dict()}, timeout=5.0)
            waiter.join(10.0)
            assert not waiter.is_alive(), "blocking query never woke"
            assert blocked and blocked[0]["index"] > cur

            jobs = []
            for _ in range(2):
                job = mock.job()
                job.task_groups = [
                    TaskGroup(name=f"tg-{g}", count=1,
                              tasks=[Task(name="web", driver="exec",
                                          resources=Resources(
                                              cpu=200, memory_mb=64))])
                    for g in range(2)]
                pool.call(addr, "Job.Register",
                          {"job": job.to_dict()}, timeout=5.0)
                jobs.append(job)

            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                state = srv.fsm.state
                evals = state.evals()
                if evals and len(evals) >= len(jobs) and \
                        all(e.status in self.TERMINAL for e in evals):
                    break
                time.sleep(0.05)  # sleep-ok: poll cadence for convergence

            state = srv.fsm.state
            stuck = [(e.id, e.status) for e in state.evals()
                     if e.status not in self.TERMINAL]
            assert not stuck, f"non-terminal evals: {stuck}"
            # Exactly-once placement: per job AND per group.
            for job in jobs:
                live = [a for a in state.allocs_by_job(job.id)
                        if not a.terminal_status()]
                want = sum(tg.count for tg in job.task_groups)
                assert len(live) == want, \
                    f"job {job.id}: {len(live)} live allocs, want {want}"
                by_group: dict = {}
                for a in live:
                    by_group[a.task_group] = \
                        by_group.get(a.task_group, 0) + 1
                assert all(by_group.get(tg.name) == tg.count
                           for tg in job.task_groups), "duplicate placement"
        finally:
            pool.shutdown()
            srv.shutdown()

    def _device_phase(self):
        """Pipelined runner on the device executor: covers the
        device.dispatch / device.collect sites."""
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner

        h, jobs = _pipeline_cluster(4, 2)
        with executor_override("device"):
            runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=2)
            runner.process([_make_eval(j) for j in jobs])
        assert all(e.status == "complete" for e in h.evals)

    def _driver_phase(self, tmp_path):
        """raw_exec task through the real TaskRunner: covers the
        driver.start site; the delay must not fail the task."""
        from nomad_tpu.client.allocdir import AllocDir
        from nomad_tpu.client.driver.base import ExecContext
        from nomad_tpu.client.task_runner import TaskRunner
        from nomad_tpu.structs import Resources, Task

        task = Task(name="echo", driver="raw_exec",
                    config={"command": "/bin/sh",
                            "args": "-c 'echo site-liveness'"},
                    resources=Resources(cpu=100, memory_mb=64))
        ad = AllocDir(str(tmp_path / "alloc"))
        ad.build([task])
        tr = TaskRunner(ExecContext(ad, "alloc-live"), task)
        tr.run()  # inline: deterministic, no thread needed
        assert tr.state == "dead"
        assert not tr.failed

    def _meta_phase(self, tmp_path):
        """The raft term/vote MetaStore is NetRaft's plane (a single
        inmem server never persists meta); its site liveness is proved
        against the real store directly."""
        from nomad_tpu.server.raft import MetaStore

        meta = MetaStore(str(tmp_path / "meta" / "meta.json"))
        meta.save({"term": 1, "voted_for": "s1"})
        assert meta.load() == {"term": 1, "voted_for": "s1"}
