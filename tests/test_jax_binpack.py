"""Golden-parity + property tests for the TPU jax-binpack scheduler.

Parity model: the sequential schedulers (GenericStack with the LimitIterator
truncation) are the reference-faithful truth; the device path scores every
feasible node, so its *scores* must match the scalar score_fit math exactly
and its plans must obey the same invariants (fit, constraints, counts).
"""
from __future__ import annotations

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.models.constraints import compile_group_mask
from nomad_tpu.models.fleet import build_fleet, build_usage
from nomad_tpu.ops.binpack import place_sequence, score_all_nodes
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import check_single_constraint
from nomad_tpu.scheduler.util import task_group_constraints
from nomad_tpu.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    JOB_TYPE_SERVICE,
    Allocation,
    Constraint,
    Evaluation,
    Plan,
    Resources,
    allocs_fit,
    score_fit,
)


def make_eval(job):
    return Evaluation(
        id="eval-1", priority=job.priority, type=JOB_TYPE_SERVICE,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )


# ---------------------------------------------------------------------------
# score parity: device score == scalar score_fit for every node
# ---------------------------------------------------------------------------

def test_score_parity_all_nodes():
    nodes = [mock.node(i) for i in range(13)]
    # Vary free capacity: preload usage on some nodes.
    allocs = []
    for i in (0, 3, 7):
        a = Allocation(id=f"a{i}", node_id=nodes[i].id, job_id="other",
                       resources=Resources(cpu=2000, memory_mb=4096),
                       desired_status="run")
        allocs.append(a)

    fleet = build_fleet(nodes)
    view = build_usage(fleet, allocs, job_id="j1")

    ask = Resources(cpu=500, memory_mb=256)
    ask_vec = np.asarray(ask.as_vector(), dtype=np.float32)

    feasible = np.zeros(fleet.n_pad, dtype=bool)
    feasible[:fleet.n_real] = True

    scores = np.asarray(score_all_nodes(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        ask_vec, feasible, False, 10.0))

    for i, node in enumerate(nodes):
        proposed = [a for a in allocs if a.node_id == node.id]
        proposed = proposed + [Allocation(resources=ask)]
        fit, _dim, util = allocs_fit(node, proposed)
        assert fit, f"mock node {i} should fit the ask"
        expected = score_fit(node, util)
        assert scores[i] == pytest.approx(expected, abs=1e-4), f"node {i}"


def test_score_marks_unfit_nodes():
    nodes = [mock.node(i) for i in range(4)]
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [])
    # Ask for more cpu than any node has.
    ask = np.asarray(Resources(cpu=99999, memory_mb=10).as_vector(),
                     dtype=np.float32)
    feasible = np.ones(fleet.n_pad, dtype=bool)
    scores = np.asarray(score_all_nodes(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        ask, feasible, False, 10.0))
    assert (scores < -1e29).all()


def test_anti_affinity_penalty_applied():
    nodes = [mock.node(i) for i in range(4)]
    a = Allocation(id="a1", node_id=nodes[0].id, job_id="j1",
                   resources=Resources(cpu=100, memory_mb=100),
                   desired_status="run")
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [a], job_id="j1")
    assert view.job_counts[0] == 1

    ask = np.asarray(Resources(cpu=100, memory_mb=64).as_vector(),
                     dtype=np.float32)
    feasible = np.ones(fleet.n_pad, dtype=bool)
    feasible[fleet.n_real:] = False
    scores = np.asarray(score_all_nodes(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        ask, feasible, False, 10.0))
    # Node 0 carries the same-job alloc: penalized by 10 (plus usage delta).
    assert scores[0] < scores[1] - 5.0


# ---------------------------------------------------------------------------
# constraint mask parity vs the sequential predicate walk
# ---------------------------------------------------------------------------

def test_constraint_mask_parity():
    nodes = []
    for i in range(20):
        n = mock.node(i)
        if i % 3 == 0:
            n.attributes["kernel.name"] = "windows"
        if i % 4 == 0:
            n.attributes["driver.exec"] = "0"
        nodes.append(n)

    job = mock.job()
    tg = job.task_groups[0]
    tg_constr = task_group_constraints(tg)
    fleet = build_fleet(nodes)
    mask, distinct = compile_group_mask(
        fleet, job.datacenters, job.constraints, tg_constr.constraints,
        tg_constr.drivers)
    assert not distinct

    ctx = EvalContext(None, Plan())
    for i, node in enumerate(nodes):
        expected = all(
            check_single_constraint(ctx, c, node)
            for c in job.constraints + tg_constr.constraints if c.hard)
        for d in tg_constr.drivers:
            v = node.attributes.get(f"driver.{d}")
            expected = expected and v is not None and \
                str(v).strip().lower() in ("1", "t", "true")
        assert mask[i] == expected, f"node {i}"
    assert not mask[fleet.n_real:].any()


def test_version_and_regexp_masks():
    nodes = [mock.node(i) for i in range(6)]
    for i, n in enumerate(nodes):
        n.attributes["version"] = f"0.{i}.0"
    fleet = build_fleet(nodes)
    cons = [Constraint(hard=True, l_target="$attr.version",
                       r_target=">= 0.3.0", operand="version")]
    mask, _ = compile_group_mask(fleet, ["dc1"], cons, [], set())
    assert list(mask[:6]) == [False, False, False, True, True, True]

    cons = [Constraint(hard=True, l_target="$node.name",
                       r_target=r"node-[0-2]$", operand="regexp")]
    mask, _ = compile_group_mask(fleet, ["dc1"], cons, [], set())
    assert list(mask[:6]) == [True, True, True, False, False, False]


# ---------------------------------------------------------------------------
# placement scan semantics
# ---------------------------------------------------------------------------

def test_place_sequence_spreads_via_anti_affinity():
    nodes = [mock.node(i) for i in range(8)]
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [])

    ask = np.zeros((1, 6), dtype=np.float32)
    ask[0] = Resources(cpu=500, memory_mb=256).as_vector()
    feasible = np.zeros((1, fleet.n_pad), dtype=bool)
    feasible[0, :fleet.n_real] = True
    group_idx = np.zeros(8, dtype=np.int32)
    valid = np.ones(8, dtype=bool)

    chosen, scores, usage = place_sequence(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, ask, np.zeros(1, dtype=bool), group_idx, valid, 10.0)
    chosen = np.asarray(chosen)
    # 8 placements on 8 identical nodes with a 10-point penalty: all spread.
    assert sorted(chosen.tolist()) == list(range(8))
    # Usage accounted on device.
    assert np.asarray(usage)[:8, 0].sum() == pytest.approx(500 * 8)


def test_place_sequence_distinct_hosts_exhausts():
    nodes = [mock.node(i) for i in range(4)]
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [])

    ask = np.zeros((1, 6), dtype=np.float32)
    ask[0] = Resources(cpu=10, memory_mb=10).as_vector()
    feasible = np.zeros((1, fleet.n_pad), dtype=bool)
    feasible[0, :fleet.n_real] = True
    group_idx = np.zeros(8, dtype=np.int32)
    valid = np.ones(8, dtype=bool)

    chosen, _, _ = place_sequence(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, ask, np.ones(1, dtype=bool), group_idx, valid, 0.0)
    chosen = np.asarray(chosen).tolist()
    # 4 distinct hosts then exhaustion (-1): placements beyond N fail.
    assert sorted(c for c in chosen if c >= 0) == list(range(4))
    assert chosen.count(-1) == 4


def test_padding_rows_never_chosen():
    nodes = [mock.node(i) for i in range(3)]  # padded to 8
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [])
    ask = np.zeros((1, 6), dtype=np.float32)
    ask[0] = Resources(cpu=10, memory_mb=10).as_vector()
    feasible = np.zeros((1, fleet.n_pad), dtype=bool)
    feasible[0, :fleet.n_real] = True
    group_idx = np.zeros(8, dtype=np.int32)
    valid = np.ones(8, dtype=bool)
    chosen, _, _ = place_sequence(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, ask, np.zeros(1, dtype=bool), group_idx, valid, 10.0)
    assert max(np.asarray(chosen).tolist()) <= 2


# ---------------------------------------------------------------------------
# end-to-end through the Harness: jax-binpack vs sequential service scheduler
# ---------------------------------------------------------------------------

def _register_cluster(h: Harness, n_nodes: int):
    nodes = [mock.node(i) for i in range(n_nodes)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    return nodes


def test_jax_scheduler_places_all():
    h = Harness()
    _register_cluster(h, 10)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    h.process("jax-binpack", make_eval(job))

    assert len(h.plans) == 1
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 10
    assert not plan.failed_allocs
    # Anti-affinity spreads 10 allocs over 10 nodes.
    assert len(plan.node_allocation) == 10
    for a in placed:
        assert a.node_id
        assert a.task_resources["web"].networks[0].mbits == 50
        assert len(a.task_resources["web"].networks[0].reserved_ports) == 1
        assert a.metrics.nodes_evaluated == 10


def test_jax_scheduler_matches_sequential_counts():
    """Same cluster, same job -> both schedulers place the full count and
    produce fitting, constraint-respecting plans."""
    for name in ("service", "jax-binpack"):
        h = Harness()
        nodes = _register_cluster(h, 16)
        # Poison half the nodes: wrong kernel.
        for n in nodes[8:]:
            n2 = n.copy()
            n2.attributes = dict(n2.attributes)
            n2.attributes["kernel.name"] = "windows"
            h.state.upsert_node(h.next_index(), n2)
        job = mock.job()
        job.task_groups[0].count = 8
        h.state.upsert_job(h.next_index(), job)

        h.process(name, make_eval(job))
        plan = h.plans[0]
        placed = [a for allocs in plan.node_allocation.values()
                  for a in allocs]
        assert len(placed) == 8, name
        good = {n.id for n in nodes[:8]}
        for a in placed:
            assert a.node_id in good, name


def test_jax_scheduler_exhaustion_fails_allocs():
    h = Harness()
    _register_cluster(h, 2)
    job = mock.job()
    job.task_groups[0].count = 4
    job.task_groups[0].tasks[0].resources.cpu = 3000  # 2 per fleet max
    h.state.upsert_job(h.next_index(), job)

    h.process("jax-binpack", make_eval(job))
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    # 4000 MHz nodes, 100 reserved: one 3000 MHz task fits per node.
    assert len(placed) == 2
    assert len(plan.failed_allocs) >= 1  # coalesced failures

    # Evals recorded as complete.
    assert h.evals and h.evals[0].status == "complete"


def test_jax_scheduler_distinct_hosts_end_to_end():
    h = Harness()
    _register_cluster(h, 4)
    job = mock.job()
    job.task_groups[0].count = 6
    job.constraints.append(Constraint(hard=True, operand="distinct_hosts"))
    h.state.upsert_job(h.next_index(), job)

    h.process("jax-binpack", make_eval(job))
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 4
    assert len({a.node_id for a in placed}) == 4
    assert plan.failed_allocs


def test_jax_scheduler_plans_fit():
    """Every node's final proposed alloc set passes the exact allocs_fit."""
    h = Harness()
    nodes = _register_cluster(h, 6)
    job = mock.job()
    job.task_groups[0].count = 30
    job.task_groups[0].tasks[0].resources.cpu = 700
    h.state.upsert_job(h.next_index(), job)

    h.process("jax-binpack", make_eval(job))
    plan = h.plans[0]
    by_node = {n.id: n for n in nodes}
    for node_id, allocs in plan.node_allocation.items():
        fit, dim, _ = allocs_fit(by_node[node_id], allocs)
        assert fit, f"node {node_id} overcommitted: {dim}"


def test_jax_scheduler_updates_in_place():
    """Job modify-index bump with unchanged tasks -> in-place update path
    still works (runs through the sequential single-node stack)."""
    h = Harness()
    _register_cluster(h, 4)
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    h.process("jax-binpack", make_eval(job))
    allocs = [a for allocs in h.plans[0].node_allocation.values()
              for a in allocs]
    h.state.upsert_allocs(h.next_index(), allocs)

    job2 = job.copy()
    job2.modify_index = job.modify_index + 1
    h.state.upsert_job(h.next_index(), job2)
    h.process("jax-binpack", make_eval(job2))

    plan = h.plans[-1]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 10  # all updated in place
    assert not plan.failed_allocs


def test_fallback_divergence_never_oversubscribes(monkeypatch):
    """When the exact host network check rejects a device winner (forcing a
    sequential fallback), later device choices must be re-verified so the
    plan never oversubscribes a node (code-review regression)."""
    from nomad_tpu.scheduler.jax_binpack import JaxBinPackScheduler

    h = Harness()
    nodes = _register_cluster(h, 4)
    job = mock.job()
    job.task_groups[0].count = 8
    job.task_groups[0].tasks[0].resources.cpu = 900
    h.state.upsert_job(h.next_index(), job)

    # Reject the first two device winners to force fallback + divergence.
    real = JaxBinPackScheduler._assign_networks
    calls = {"n": 0}

    def flaky(self, node, tg):
        calls["n"] += 1
        if calls["n"] <= 2:
            return None
        return real(self, node, tg)

    monkeypatch.setattr(JaxBinPackScheduler, "_assign_networks", flaky)
    h.process("jax-binpack", make_eval(job))

    plan = h.plans[0]
    by_node = {n.id: n for n in nodes}
    for node_id, allocs in plan.node_allocation.items():
        fit, dim, _ = allocs_fit(by_node[node_id], allocs)
        assert fit, f"node {node_id} oversubscribed: {dim}"
    placed = sum(len(v) for v in plan.node_allocation.values())
    assert placed + len(plan.failed_allocs) >= 8 - 7  # coalescing allowed
    assert placed >= 4


def test_fast_network_rollback_keeps_cached_index_coherent():
    """A bandwidth failure in the fast network assigner must undo the
    offers it already mirrored into the cached exact-path NetworkIndex —
    otherwise later exact-path assignments on the node see phantom
    port/bandwidth reservations (advisor regression)."""
    from nomad_tpu.models.fleet import build_fleet
    from nomad_tpu.scheduler.jax_binpack import JaxBinPackScheduler
    from nomad_tpu.structs import NetworkIndex, NetworkResource, Resources

    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import Plan

    node = mock.node(0)  # eth0, 1000 mbits, 1 reserved
    sched = JaxBinPackScheduler.__new__(JaxBinPackScheduler)
    sched._statics = build_fleet([node])
    sched._node_net = {}
    sched._port_lcg = 12345
    sched.state = StateStore()
    sched.plan = Plan()

    class _Ctx:
        def proposed_allocs(self, node_id):
            return []

    sched.ctx = _Ctx()

    idx = NetworkIndex()
    idx.set_node(node)
    sched._net_cache = {node.id: idx}
    bw_before = dict(idx.used_bandwidth)
    ports_before = {ip: set(p) for ip, p in idx.used_ports.items()}

    ask_ok = NetworkResource(mbits=500, dynamic_ports=["a"])
    ask_too_big = NetworkResource(mbits=10_000, dynamic_ports=["b"])
    plan_tasks = [
        ("t1", Resources(cpu=100, memory_mb=64, networks=[ask_ok]), ask_ok),
        ("t2", Resources(cpu=100, memory_mb=64, networks=[ask_too_big]),
         ask_too_big),
    ]
    assert sched._assign_networks_fast(0, node, plan_tasks) is None

    # The cached exact-path index must be exactly as it was.
    assert idx.used_bandwidth == bw_before
    assert {ip: set(p) for ip, p in idx.used_ports.items()
            if p} == {ip: set(p) for ip, p in ports_before.items() if p}


# ---------------------------------------------------------------------------
# host (numpy) executor: kernel parity + dispatch policy
# ---------------------------------------------------------------------------

def _random_case(rng, n_nodes=23, n_groups=3, n_place=17):
    nodes = [mock.node(i) for i in range(n_nodes)]
    for i, n in enumerate(nodes):
        n.resources.cpu = int(rng.integers(800, 4000))
        n.resources.memory_mb = int(rng.integers(900, 8000))
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [])
    g_pad = max(4, n_groups)
    asks = np.zeros((g_pad, 6), dtype=np.float32)
    for g in range(n_groups):
        asks[g] = Resources(
            cpu=int(rng.integers(50, 700)),
            memory_mb=int(rng.integers(40, 900))).as_vector()
    feasible = np.zeros((g_pad, fleet.n_pad), dtype=bool)
    feasible[:n_groups, :fleet.n_real] = \
        rng.random((n_groups, fleet.n_real)) > 0.2
    distinct = rng.random(g_pad) > 0.7
    group_idx = rng.integers(0, n_groups, n_place).astype(np.int32)
    valid = np.ones(n_place, dtype=bool)
    valid[-2:] = False
    return fleet, view, asks, feasible, distinct, group_idx, valid


def test_host_place_sequence_parity():
    from nomad_tpu.ops.binpack_host import place_sequence_host

    rng = np.random.default_rng(7)
    for trial in range(4):
        fleet, view, asks, feasible, distinct, group_idx, valid = \
            _random_case(rng)
        dev = place_sequence(
            fleet.capacity, fleet.reserved, view.usage, view.job_counts,
            feasible, asks, distinct, group_idx, valid, 10.0)
        host = place_sequence_host(
            fleet.capacity, fleet.reserved, view.usage, view.job_counts,
            feasible, asks, distinct, group_idx, valid, 10.0)
        dev_chosen = np.asarray(dev[0])
        assert np.array_equal(dev_chosen, host[0]), trial
        placed = dev_chosen >= 0  # scores are meaningless where -1
        np.testing.assert_allclose(np.asarray(dev[1])[placed],
                                   host[1][placed], rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(dev[2]), host[2],
                                   rtol=1e-5, atol=1e-3)


def test_host_place_rounds_parity():
    from nomad_tpu.ops.binpack import place_rounds
    from nomad_tpu.ops.binpack_host import place_rounds_host

    rng = np.random.default_rng(11)
    for trial in range(4):
        fleet, view, asks, feasible, distinct, _gi, _v = \
            _random_case(rng)
        counts = np.zeros(asks.shape[0], dtype=np.int32)
        counts[:3] = rng.integers(1, 9, 3)
        dev = place_rounds(
            fleet.capacity, fleet.reserved, view.usage, view.job_counts,
            feasible, asks, distinct, counts, 10.0, k_cap=4, rounds=3)
        host = place_rounds_host(
            fleet.capacity, fleet.reserved, view.usage, view.job_counts,
            feasible, asks, distinct, counts, 10.0, k_cap=4, rounds=3)
        assert np.array_equal(np.asarray(dev[0]), host[0]), trial
        np.testing.assert_allclose(np.asarray(dev[2]), host[2],
                                   rtol=1e-5, atol=1e-3)


def test_small_eval_uses_host_executor(monkeypatch):
    """Tiny fleets must never pay a device dispatch: the executor policy
    routes them to the numpy kernels."""
    import nomad_tpu.scheduler.jax_binpack as jb

    def boom(*a, **k):
        raise AssertionError("device dispatched for a tiny workload")

    monkeypatch.setattr(jb, "place_sequence", boom)
    monkeypatch.setattr(
        "nomad_tpu.ops.binpack.place_rounds", boom)
    h = Harness()
    _register_cluster(h, 10)
    job = mock.job()
    job.task_groups[0].count = 5
    h.state.upsert_job(h.next_index(), job)
    h.process("jax-binpack", make_eval(job))
    placed = sum(len(v) for v in h.plans[0].node_allocation.values())
    assert placed == 5


def test_large_eval_uses_device_when_pipelined():
    """The policy must keep big pipelined workloads on the device."""
    from nomad_tpu.scheduler.jax_binpack import DeviceArgs, \
        JaxBinPackScheduler

    class _S:
        n_real = 20_000

    args = DeviceArgs(statics=_S(), rounds_eligible=False,
                      n_groups=64, n_place=1_000, rounds=1)
    sched = JaxBinPackScheduler.__new__(JaxBinPackScheduler)
    assert not sched.choose_host_executor(args, pipelined=True)
    # Single-shot: same workload prefers the host (one RTT >> numpy).
    assert sched.choose_host_executor(args, pipelined=False)


def test_fast_proto_matches_dataclass():
    """The template constructor (finish loop hot path) must stay
    field-for-field identical to the dataclass constructor."""
    import dataclasses

    from nomad_tpu.scheduler.jax_binpack import (_ALLOC_FACTORIES,
                                                 _ALLOC_STATIC,
                                                 _METRIC_FACTORIES,
                                                 _METRIC_STATIC)
    from nomad_tpu.structs import AllocMetric

    for cls, static, factories in (
            (Allocation, _ALLOC_STATIC, _ALLOC_FACTORIES),
            (AllocMetric, _METRIC_STATIC, _METRIC_FACTORIES)):
        names = {f.name for f in dataclasses.fields(cls)}
        assert set(static) | {n for n, _ in factories} == names
        d = dict(static)
        for n, fac in factories:
            d[n] = fac()
        assert d == cls().__dict__

    # The network fast path fills factory fields explicitly instead of
    # looping; it must fail loudly if the dataclasses grow new ones.
    from nomad_tpu.scheduler.jax_binpack import (_NET_FACTORIES,
                                                 _RES_FACTORIES)

    assert {n for n, _ in _RES_FACTORIES} == {"networks"}
    assert {n for n, _ in _NET_FACTORIES} == {"reserved_ports",
                                              "dynamic_ports"}


def test_host_place_rounds_tie_parity():
    """Homogeneous fleets tie on every score — the common case for a
    fresh cluster of identical nodes.  Host and device top-k must break
    ties the same way (lowest node index first) or the executor policy
    would change placements (code-review regression)."""
    from nomad_tpu.ops.binpack import place_rounds
    from nomad_tpu.ops.binpack_host import place_rounds_host

    nodes = [mock.node(i) for i in range(33)]  # identical resources
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [])
    asks = np.zeros((4, 6), dtype=np.float32)
    asks[0] = Resources(cpu=100, memory_mb=64).as_vector()
    feasible = np.zeros((4, fleet.n_pad), dtype=bool)
    feasible[0, :fleet.n_real] = True
    distinct = np.zeros(4, dtype=bool)
    counts = np.zeros(4, dtype=np.int32)
    counts[0] = 8
    dev = place_rounds(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, asks, distinct, counts, 10.0, k_cap=4, rounds=3)
    host = place_rounds_host(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, asks, distinct, counts, 10.0, k_cap=4, rounds=3,
        n_real=fleet.n_real)
    assert np.array_equal(np.asarray(dev[0]), host[0])
    assert np.asarray(dev[2]).shape == host[2].shape


class TestTopkExact:
    """Direct coverage for the host kernel's packed-key top-k
    (ops/binpack_host._topk_exact): must match lax.top_k's contract —
    k largest, ties broken by LOWER index — exactly, byte-for-byte with
    the stable-argsort reference on the docstring's hazard cases."""

    def _ref(self, vals, k):
        return np.argsort(-vals, kind="stable")[:k]

    def test_ties_straddling_the_boundary(self):
        from nomad_tpu.ops.binpack_host import _topk_exact

        vals = np.array([5.0, 7.0, 5.0, 5.0, 7.0, 5.0, 3.0],
                        dtype=np.float32)
        for k in (1, 2, 3, 4, 5):
            assert np.array_equal(_topk_exact(vals, k),
                                  self._ref(vals, k)), k

    def test_negative_zero_and_neg_inf_rows(self):
        from nomad_tpu.ops.binpack_host import NEG_INF, _topk_exact

        vals = np.array([0.0, -0.0, NEG_INF, -0.0, 0.0, -3.5],
                        dtype=np.float32)
        for k in range(1, 7):
            assert np.array_equal(_topk_exact(vals, k),
                                  self._ref(vals, k)), k

    def test_k_bounds(self):
        from nomad_tpu.ops.binpack_host import _topk_exact

        vals = np.array([1.0, 2.0], dtype=np.float32)
        assert len(_topk_exact(vals, 0)) == 0
        assert np.array_equal(_topk_exact(vals, 5), self._ref(vals, 5))

    def test_randomized_tie_heavy_parity(self):
        from nomad_tpu.ops.binpack_host import NEG_INF, _topk_exact

        rng = np.random.default_rng(1234)
        pool = np.array([NEG_INF, -10.0, -0.0, 0.0, 1.25, 1.25, 9.5,
                         18.0], dtype=np.float32)
        for _ in range(500):
            n = int(rng.integers(2, 80))
            k = int(rng.integers(1, n + 3))
            vals = rng.choice(pool, size=n)
            assert np.array_equal(_topk_exact(vals, k),
                                  self._ref(vals, k))
        # Continuous values at fleet scale.
        vals = rng.random(16384).astype(np.float32)
        assert np.array_equal(_topk_exact(vals, 1024),
                              self._ref(vals, 1024))


def test_jax_scheduler_failures_carry_explanations():
    """Device-path failures must carry the reference's AllocMetric
    explanation — constraint filter counts when no node matches,
    dimension exhaustion counts when resources run out (monitor.go
    dumpAllocStatus is downstream of this data)."""
    # 1) Constraint nobody satisfies: constraint_filtered populated.
    h = Harness()
    _register_cluster(h, 3)
    job = mock.job()
    job.task_groups[0].constraints = [
        Constraint(hard=True, l_target="$attr.kernel.name",
                   r_target="plan9", operand="=")]
    h.state.upsert_job(h.next_index(), job)
    h.process("jax-binpack", make_eval(job))
    plan = h.plans[0]
    assert plan.failed_allocs
    m = plan.failed_allocs[0].metrics
    assert m.nodes_evaluated >= 3
    assert sum(m.constraint_filtered.values()) >= 3, m.constraint_filtered

    # 2) Resource exhaustion: dimension_exhausted populated.
    h2 = Harness()
    _register_cluster(h2, 2)
    job2 = mock.job()
    job2.task_groups[0].count = 4
    job2.task_groups[0].tasks[0].resources.cpu = 3000
    h2.state.upsert_job(h2.next_index(), job2)
    h2.process("jax-binpack", make_eval(job2))
    plan2 = h2.plans[0]
    assert plan2.failed_allocs
    m2 = plan2.failed_allocs[0].metrics
    assert m2.nodes_exhausted >= 1 or m2.dimension_exhausted, \
        (m2.nodes_exhausted, m2.dimension_exhausted)


def test_rounds_mode_places_past_fleet_fullness():
    """Regression: with N constraint-feasible nodes but only a few
    having room, the rounds estimate must grow (fit-aware _fit_rounds)
    or the finish fallback must rescue — a 100-copy task group on a
    fleet where just 5 nodes have capacity places ALL copies, not one
    per fitting node."""
    h = Harness()
    # 5 roomy nodes + 25 full-ish nodes (room for exactly one task).
    for i in range(30):
        n = mock.node(i)
        if i >= 5:
            n.resources = Resources(
                cpu=260, memory_mb=160, disk_mb=10_000, iops=150,
                networks=n.resources.networks)
        h.state.upsert_node(h.next_index(), n)
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 100
    from nomad_tpu.structs import NetworkResource

    tg.tasks[0].resources = Resources(
        cpu=100, memory_mb=64,
        networks=[NetworkResource(mbits=5, dynamic_ports=["http"])])
    h.state.upsert_job(h.next_index(), job)
    h.process("jax-binpack", make_eval(job))
    plan = h.plans[0]
    placed = sum(len(v) for v in plan.node_allocation.values())
    # 5 roomy nodes hold 38 each (cpu 4000-100-100*38...), plenty for
    # 100; the 25 tight nodes hold one each.
    assert placed == 100, (placed, len(plan.failed_allocs))
