"""utils/retry.py: the unified backoff/retry policy every ad-hoc
``time.sleep`` retry loop was replaced with."""
from __future__ import annotations

import random
import threading
import time

import pytest

from nomad_tpu.utils.retry import (
    Backoff,
    RetryAborted,
    RetryPolicy,
)


class TestBackoff:
    def test_exponential_growth_and_cap(self):
        b = Backoff(base=0.1, max_delay=1.0, multiplier=2.0, jitter=0.0)
        assert [round(b.next(), 3) for _ in range(6)] == \
            [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]

    def test_reset_snaps_back(self):
        b = Backoff(base=0.1, max_delay=5.0, jitter=0.0)
        b.next()
        b.next()
        assert b.failures == 2
        b.reset()
        assert b.failures == 0
        assert b.next() == pytest.approx(0.1)

    def test_full_jitter_bounds(self):
        rng = random.Random(3)
        b = Backoff(base=1.0, max_delay=1.0, jitter=1.0, rng=rng)
        draws = [b.next() for _ in range(100)]
        assert all(0.0 < d <= 1.0 for d in draws)
        assert len({round(d, 6) for d in draws}) > 50  # actually jittered

    def test_partial_jitter_stays_near_nominal(self):
        rng = random.Random(3)
        b = Backoff(base=1.0, max_delay=1.0, jitter=0.25, rng=rng)
        assert all(0.75 <= b.next() <= 1.0 for _ in range(50))

    def test_huge_failure_count_no_overflow(self):
        b = Backoff(base=0.1, max_delay=2.0, jitter=0.0)
        for _ in range(200):
            delay = b.next()
        assert delay == 2.0

    def test_sleep_returns_true_on_stop(self):
        b = Backoff(base=5.0, max_delay=5.0, jitter=0.0)
        stop = threading.Event()
        stop.set()
        t0 = time.monotonic()
        assert b.sleep(stop) is True
        assert time.monotonic() - t0 < 1.0

    def test_bad_base_rejected(self):
        with pytest.raises(ValueError):
            Backoff(base=0.0)


class TestRetryPolicy:
    def _flaky(self, failures: int, exc=ConnectionError):
        calls = {"n": 0}

        def fn(timeout=None):  # bounded policies pass the budget in
            calls["n"] += 1
            if calls["n"] <= failures:
                raise exc(f"boom {calls['n']}")
            return calls["n"]
        return fn, calls

    def test_retries_until_success(self):
        policy = RetryPolicy(base=0.001, max_delay=0.002, name="t")
        fn, calls = self._flaky(3)
        assert policy.call(fn) == 4
        assert calls["n"] == 4

    def test_non_retryable_raises_immediately(self):
        policy = RetryPolicy(base=0.001, name="t")
        fn, calls = self._flaky(3, exc=ValueError)
        with pytest.raises(ValueError, match="boom 1"):
            policy.call(fn)
        assert calls["n"] == 1

    def test_max_attempts_reraises_last(self):
        policy = RetryPolicy(base=0.001, max_attempts=3, name="t")
        fn, calls = self._flaky(10)
        with pytest.raises(ConnectionError, match="boom 3"):
            policy.call(fn)
        assert calls["n"] == 3

    def test_deadline_not_burned_asleep(self):
        """The deadline check runs BEFORE the sleep: a policy whose
        next delay would overrun gives up immediately."""
        policy = RetryPolicy(base=10.0, jitter=0.0, deadline=0.5,
                             name="t")
        fn, calls = self._flaky(10)
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            policy.call(fn)
        assert time.monotonic() - t0 < 0.4
        assert calls["n"] == 1

    def test_stop_event_aborts(self):
        policy = RetryPolicy(base=30.0, jitter=0.0, name="t")
        stop = threading.Event()
        fn, _ = self._flaky(10)

        def trip():
            time.sleep(0.05)  # sleep-ok: fire stop mid-backoff-sleep
            stop.set()
        threading.Thread(target=trip, daemon=True).start()
        t0 = time.monotonic()
        with pytest.raises(RetryAborted):
            policy.call(fn, stop=stop)
        assert time.monotonic() - t0 < 5.0

    def test_on_retry_hook_sees_attempts(self):
        policy = RetryPolicy(base=0.001, max_delay=0.002, name="t")
        seen = []
        fn, _ = self._flaky(2)
        policy.call(fn, on_retry=lambda n, e, d: seen.append((n, d)))
        assert [n for n, _ in seen] == [1, 2]
        assert all(d > 0 for _, d in seen)

    def test_callable_retryable_predicate(self):
        policy = RetryPolicy(
            base=0.001, name="t",
            retryable=lambda e: "retry-me" in str(e))
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("retry-me please")
            raise RuntimeError("fatal")
        with pytest.raises(RuntimeError, match="fatal"):
            policy.call(fn)
        assert calls["n"] == 2

    def test_per_attempt_timeout_clips_to_deadline(self):
        policy = RetryPolicy(attempt_timeout=5.0, deadline=1.0)
        start = time.monotonic()
        t = policy.per_attempt_timeout(start)
        assert 0 < t <= 1.0
        assert RetryPolicy(attempt_timeout=2.0).per_attempt_timeout() \
            == 2.0
        assert RetryPolicy().per_attempt_timeout() is None

    def test_bounded_policy_feeds_timeout_to_fn(self):
        """A policy with attempt_timeout/deadline hands each attempt
        its transport budget (clipped to the deadline remainder)."""
        policy = RetryPolicy(base=0.001, max_attempts=3,
                             attempt_timeout=5.0, deadline=60.0,
                             name="t")
        seen = []

        def fn(timeout):
            seen.append(timeout)
            if len(seen) < 2:
                raise ConnectionError("boom")
            return "ok"
        assert policy.call(fn) == "ok"
        assert len(seen) == 2
        assert all(0 < t <= 5.0 for t in seen)

        # attempt_timeout alone also feeds through, un-clipped.
        policy2 = RetryPolicy(base=0.001, attempt_timeout=2.5, name="t")
        got = []
        policy2.call(lambda timeout: got.append(timeout))
        assert got == [2.5]

    def test_metrics_counters(self):
        from nomad_tpu.utils.metrics import metrics

        policy = RetryPolicy(base=0.001, max_attempts=2,
                             name="unit.metrics")
        fn, _ = self._flaky(10)
        with pytest.raises(ConnectionError):
            policy.call(fn)
        counters = metrics.inmem.snapshot()["counters"]
        assert counters.get("nomad.retry.unit.metrics.retries", 0) >= 1
        assert counters.get("nomad.retry.unit.metrics.gaveup", 0) >= 1

    def test_policy_is_reusable_across_threads(self):
        """One module-level policy instance serves many threads: each
        call owns its backoff state."""
        policy = RetryPolicy(base=0.001, max_delay=0.002, name="t")
        results = []

        def work():
            fn, _ = self._flaky(2)
            results.append(policy.call(fn))
        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5.0)
        assert results == [3] * 8
