"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so sharding/pjit paths are
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; bench runs on the real chip).

Note: the environment's sitecustomize may register a TPU backend at
interpreter start, so JAX_PLATFORMS cannot always be overridden here —
instead the default *device* is pinned to cpu:0 and mesh tests build meshes
from ``jax.devices("cpu")`` explicitly.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_default_device", jax.devices("cpu")[0])


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running smoke tests (driver entry points)")
    config.addinivalue_line(
        "markers", "multichip: sharded-parity suite on the forced "
        "8-device host mesh; re-driven hermetically by the tier-1 "
        "subprocess rig (tests/test_multichip_rig.py)")


import pytest  # noqa: E402

# The session's ReplicaDivergenceSanitizer (None when sanitizers are
# disabled): the per-test quiescence fixture and the sanitizer's own
# regression tests reach it through here.
DIVERGENCE = None

# Same for the BudgetWitnessSanitizer (per-test unbounded-wait report).
BUDGET = None


@pytest.fixture(scope="session", autouse=True)
def runtime_sanitizers():
    """Suite-wide runtime sanitizers (nomad_tpu/analysis/sanitizers.py):

    - lock-order witness: every package lock created during the suite is
      instrumented; an observed lock-order cycle (the deadlock
      precondition) fails the session at teardown.
    - recompile sentinel: a jit kernel retracing past its budget fails
      the session — the silent perf-erosion mode behavioral tests miss.
    - transfer guard: the scheduler's device-dispatch seams run under
      jax.transfer_guard_host_to_device("disallow") — an IMPLICIT
      host->device transfer on a dispatch path (a host array/scalar
      silently committed by jit instead of explicitly placed through
      the counted seams) raises in the test that caused it.
    - replica divergence: every NomadFSM carries a shadow twin fed the
      same raft entries; store fingerprints are byte-compared at commit
      quiescence points, so a nondeterministic apply fails the test
      that caused it (the runtime twin of analysis/consensuslint.py).
    - budget witness: while a thread serves an admitted RPC, any
      Event/Condition wait or blocking Queue.get entered with NO
      timeout is recorded with its stack and fails the test that
      caused it — the runtime twin of analysis/faultlint.py's
      deadline pass (catches a timeout variable that evaluates to
      None, which the AST can't see).

    Disable with NOMAD_TPU_SANITIZERS=0 (e.g. when bisecting an
    unrelated failure).  All only observe; no test behavior changes.
    """
    global DIVERGENCE, BUDGET
    if os.environ.get("NOMAD_TPU_SANITIZERS", "1") == "0":
        yield
        return
    from nomad_tpu.analysis.sanitizers import (BudgetWitnessSanitizer,
                                               LockOrderWitness,
                                               RecompileSentinel,
                                               ReplicaDivergenceSanitizer,
                                               TransferGuardSanitizer)

    witness = LockOrderWitness().install()
    sentinel = RecompileSentinel().install()
    guard = TransferGuardSanitizer().install()
    DIVERGENCE = divergence = ReplicaDivergenceSanitizer().install()
    BUDGET = budget = BudgetWitnessSanitizer().install()
    try:
        yield
    finally:
        budget.uninstall()
        BUDGET = None
        divergence.uninstall()
        DIVERGENCE = None
        guard.uninstall()
        witness.uninstall()
    # Collect-then-raise so one sanitizer tripping doesn't mask the
    # other's report for the same session.
    errors = []
    for check in (witness.check, sentinel.check, divergence.check,
                  budget.check):
        try:
            check()
        except AssertionError as e:
            errors.append(str(e))
    if errors:
        raise AssertionError("\n".join(errors))


@pytest.fixture(autouse=True)
def replica_quiescence():
    """Per-test commit quiescence point: fingerprint-compare every live
    primary/twin FSM pair at teardown, so a divergence is pinned to the
    test that caused it instead of surfacing sessions later."""
    yield
    if DIVERGENCE is not None:
        DIVERGENCE.compare_all()


@pytest.fixture(autouse=True)
def budget_quiescence():
    """Per-test budget-witness report: any unbounded wait recorded on a
    serving thread during this test fails THIS test (with the wait's
    stack), not the session summary."""
    yield
    if BUDGET is not None:
        BUDGET.check_test()


def wait_until(fn, timeout=15.0, msg="condition"):
    """The universal convergence helper (reference testutil/wait.go
    WaitForResult); shared by the agent/HTTP suites."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)  # sleep-ok: poll interval of the bounded wait
    raise AssertionError(f"timeout waiting for {msg}")


def boot_dev_agent(data_dir: str):
    """ONE boot sequence for in-process dev-agent rigs: returns
    (agent, api_client) with the client node registered.  Every suite's
    module fixture delegates here so a future boot change (new config
    knob, different readiness condition) lands once."""
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api import APIClient

    cfg = AgentConfig.dev()
    cfg.data_dir = data_dir
    cfg.client_options["fingerprint.skip_accel"] = "1"
    agent = Agent(cfg)
    client = APIClient(f"http://127.0.0.1:{agent.http.address[1]}")
    wait_until(lambda: agent.server.fsm.state.nodes(),
               msg="client node registration")
    return agent, client
