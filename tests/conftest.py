"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so sharding/pjit paths are
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; bench runs on the real chip).

Note: the environment's sitecustomize may register a TPU backend at
interpreter start, so JAX_PLATFORMS cannot always be overridden here —
instead the default *device* is pinned to cpu:0 and mesh tests build meshes
from ``jax.devices("cpu")`` explicitly.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_default_device", jax.devices("cpu")[0])


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running smoke tests (driver entry points)")


def wait_until(fn, timeout=15.0, msg="condition"):
    """The universal convergence helper (reference testutil/wait.go
    WaitForResult); shared by the agent/HTTP suites."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")
