"""Overload control plane units (server/overload.py + ttlwheel.py):
admission state machine + priority shedding, deadline propagation
through envelope → broker → worker → applier, TTL wheel semantics,
brownout expiry deferral, and token-bucket paced reconciliation.

The seeded end-to-end brownout scenario lives in
tests/test_chaos_overload.py (slow tier); these are the fast invariants.
"""
from __future__ import annotations

import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu import faultinject
from nomad_tpu.faultinject import FaultPlan, FaultSpecError
from nomad_tpu.server.eval_broker import FAILED_QUEUE, EvalBroker
from nomad_tpu.server.heartbeat import HeartbeatManager
from nomad_tpu.server.overload import (
    BROWNOUT,
    CLASS_BATCH,
    CLASS_SERVICE,
    CLASS_SYSTEM,
    NORMAL,
    OVERLOAD,
    ErrDeadlineExceeded,
    ErrOverloaded,
    OverloadController,
    TokenBucket,
    absolute_deadline,
    classify_eval,
    classify_rpc,
    expired,
    remaining,
    restamp_forward,
    stamp_arrival,
)
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.server.ttlwheel import TTLWheel
from nomad_tpu.structs import Evaluation, Plan, generate_uuid
from nomad_tpu.utils.retry import (
    DEFAULT_RETRYABLE,
    is_overloaded,
    transport_or_overload,
)

from tests.conftest import wait_until


def make_eval(priority=50, type_="service", job_id=None) -> Evaluation:
    return Evaluation(
        id=generate_uuid(), priority=priority, type=type_,
        job_id=job_id or generate_uuid(), status="pending",
        triggered_by="job-register",
    )


class FakeClock:
    def __init__(self, start: float = 1000.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, secs: float) -> None:
        self.now += secs


# ---------------------------------------------------------------------------
# Error shapes + retry classification
# ---------------------------------------------------------------------------

class TestErrorShapes:
    def test_overloaded_is_transport_shaped(self):
        """In-proc callers ride ErrOverloaded under the DEFAULT
        retryable tuple — it is an OSError by design."""
        e = ErrOverloaded("eval broker")
        assert isinstance(e, OSError)
        assert isinstance(e, DEFAULT_RETRYABLE)
        assert is_overloaded(e)
        assert transport_or_overload(e)

    def test_marker_survives_the_wire_string(self):
        """Over RPC only str(e) survives; the marker classifies it."""
        from nomad_tpu.server.rpc import RPCError

        wire = RPCError(str(ErrOverloaded("plan queue")))
        assert is_overloaded(wire)
        assert transport_or_overload(wire)
        assert not is_overloaded(RPCError("no cluster leader"))

    def test_deadline_exceeded_is_timeout_shaped(self):
        assert isinstance(ErrDeadlineExceeded("x"), TimeoutError)


# ---------------------------------------------------------------------------
# Deadline envelope plumbing
# ---------------------------------------------------------------------------

class TestDeadlineEnvelope:
    def test_stamp_arrival_converts_relative_once(self):
        clock = FakeClock()
        args = {"x": 1, "_deadline": 5.0}
        dl = stamp_arrival(args, clock=clock)
        assert dl == pytest.approx(1005.0)
        assert "_deadline" not in args
        # Idempotent: a second stamp (in-proc chains re-enter the
        # endpoint layer) keeps the original arrival time.
        clock.advance(3.0)
        assert stamp_arrival(args, clock=clock) == pytest.approx(1005.0)
        assert absolute_deadline(args) == pytest.approx(1005.0)

    def test_unbounded_envelope(self):
        args = {"x": 1}
        assert stamp_arrival(args) == 0.0
        assert absolute_deadline(args) == 0.0
        assert remaining(0.0, 60.0) == 60.0
        assert not expired(0.0)

    def test_restamp_forward_rebases_budget(self):
        clock = FakeClock()
        args = {"_deadline": 10.0}
        stamp_arrival(args, clock=clock)
        clock.advance(4.0)
        fwd = restamp_forward(dict(args), clock=clock)
        assert "_abs_deadline" not in fwd
        assert fwd["_deadline"] == pytest.approx(6.0)
        # Expired budgets clamp positive so the remote rejects cheaply.
        clock.advance(60.0)
        fwd = restamp_forward(dict(args), clock=clock)
        assert fwd["_deadline"] == pytest.approx(0.001)

    def test_remaining_caps_and_floors(self):
        clock = FakeClock()
        assert remaining(clock.now + 5.0, 60.0,
                         clock=clock) == pytest.approx(5.0)
        assert remaining(clock.now + 500.0, 60.0, clock=clock) == 60.0
        clock.advance(1000.0)
        assert remaining(clock.now - 1.0, 60.0,
                         clock=clock) == pytest.approx(0.001)

    def test_conn_pool_and_inproc_stamp_the_envelope(self):
        """Both transports attach the caller's budget as _deadline."""
        from nomad_tpu.server.rpc import ConnPool

        seen = {}

        class _Spy(ConnPool):
            def _call_mux(self, address, method, args, timeout):
                seen.update(args)
                return {}

        _Spy().call(("127.0.0.1", 1), "Status.Ping", {"a": 1}, timeout=7.5)
        assert seen["_deadline"] == 7.5


# ---------------------------------------------------------------------------
# TokenBucket
# ---------------------------------------------------------------------------

class TestTokenBucket:
    def test_burst_then_paced(self):
        clock = FakeClock()
        tb = TokenBucket(rate=10.0, burst=3.0, clock=clock)
        assert all(tb.try_take() for _ in range(3))
        assert not tb.try_take()
        assert tb.wait_time() == pytest.approx(0.1)
        clock.advance(0.1)
        assert tb.try_take()
        assert not tb.try_take()

    def test_refill_caps_at_burst(self):
        clock = FakeClock()
        tb = TokenBucket(rate=100.0, burst=2.0, clock=clock)
        clock.advance(60.0)
        assert tb.try_take() and tb.try_take()
        assert not tb.try_take()

    def test_rejects_nonpositive_config(self):
        with pytest.raises(ValueError):
            TokenBucket(rate=0, burst=1)


# ---------------------------------------------------------------------------
# OverloadController
# ---------------------------------------------------------------------------

class TestController:
    def _ctrl(self, depth_ref: dict, limit: int = 100) -> OverloadController:
        ctrl = OverloadController(brownout_ratio=0.5, overload_ratio=1.0)
        ctrl.add_source("q", lambda: (depth_ref["d"], limit))
        return ctrl

    def test_state_machine_with_hysteresis(self):
        depth = {"d": 0}
        ctrl = self._ctrl(depth)
        assert ctrl.state() == NORMAL
        depth["d"] = 50
        assert ctrl.state() == BROWNOUT
        depth["d"] = 100
        assert ctrl.state() == OVERLOAD
        # Pressure dips just below the brownout threshold: hysteresis
        # holds brownout instead of snapping to normal (no flapping).
        depth["d"] = 47
        assert ctrl.state() == BROWNOUT
        depth["d"] = 10
        assert ctrl.state() == NORMAL

    def test_priority_shedding_order(self):
        """system > service > batch: brownout sheds batch only,
        overload sheds batch+service, system always admits."""
        depth = {"d": 60}
        ctrl = self._ctrl(depth)
        assert ctrl.shed_classes() == (CLASS_BATCH,)
        with pytest.raises(ErrOverloaded):
            ctrl.admit(CLASS_BATCH)
        ctrl.admit(CLASS_SERVICE)
        ctrl.admit(CLASS_SYSTEM)
        depth["d"] = 150
        assert set(ctrl.shed_classes()) == {CLASS_BATCH, CLASS_SERVICE}
        with pytest.raises(ErrOverloaded):
            ctrl.admit(CLASS_SERVICE)
        ctrl.admit(CLASS_SYSTEM)
        stats = ctrl.stats()
        assert stats["shed"][CLASS_BATCH] == 1
        assert stats["shed"][CLASS_SERVICE] == 1
        assert stats["shed"][CLASS_SYSTEM] == 0

    def test_heartbeats_bypass_admission(self):
        """The liveness lane: heartbeats get through even in overload —
        shedding them would CAUSE the TTL-expiry storm."""
        depth = {"d": 1000}
        ctrl = self._ctrl(depth)
        assert ctrl.state() == OVERLOAD
        ctrl.admit_rpc("Node.Heartbeat", {"node_id": "n1"})
        assert ctrl.stats()["heartbeat_lane"] == 1

    def test_forced_state_pins_the_machine(self):
        ctrl = OverloadController()
        assert ctrl.state() == NORMAL
        ctrl.force_state(OVERLOAD)
        assert ctrl.state() == OVERLOAD
        assert ctrl.in_brownout()
        ctrl.force_state(None)
        assert ctrl.state() == NORMAL
        with pytest.raises(ValueError):
            ctrl.force_state("meltdown")

    def test_dead_source_does_not_wedge_admission(self):
        ctrl = OverloadController()
        ctrl.add_source("dead", lambda: (_ for _ in ()).throw(
            RuntimeError("torn down")))
        assert ctrl.state() == NORMAL
        ctrl.admit(CLASS_BATCH)


class TestClassification:
    @pytest.mark.parametrize("method,args,want", [
        ("Node.Register", {}, CLASS_SYSTEM),
        ("Eval.Ack", {}, CLASS_SYSTEM),
        ("Plan.Submit", {}, CLASS_SYSTEM),
        ("Status.Ping", {}, CLASS_SYSTEM),
        ("Job.Deregister", {}, CLASS_SYSTEM),
        ("Job.Register", {"job": {"type": "batch"}}, CLASS_BATCH),
        ("Job.Register", {"job": {"type": "service"}}, CLASS_SERVICE),
        ("Job.Register", {"job": {"type": "system"}}, CLASS_SYSTEM),
        ("Job.List", {}, CLASS_SERVICE),
        ("Alloc.List", {}, CLASS_SERVICE),
    ])
    def test_classify_rpc(self, method, args, want):
        assert classify_rpc(method, args) == want

    @pytest.mark.parametrize("type_,want", [
        ("system", CLASS_SYSTEM),
        ("service", CLASS_SERVICE),
        ("batch", CLASS_BATCH),
        ("_core", CLASS_BATCH),  # GC defers under pressure: sheds first
    ])
    def test_classify_eval(self, type_, want):
        assert classify_eval(make_eval(type_=type_)) == want


# ---------------------------------------------------------------------------
# TTL wheel
# ---------------------------------------------------------------------------

class TestTTLWheel:
    def test_expiry_fires_once(self):
        fired = []
        wheel = TTLWheel(fired.append, name="t-wheel")
        try:
            wheel.arm("a", 0.05)
            wait_until(lambda: fired == ["a"], timeout=5.0,
                       msg="wheel expiry")
            assert wheel.active() == 0
        finally:
            wheel.stop()

    def test_rearm_supersedes_and_cancel_disarms(self):
        fired = []
        wheel = TTLWheel(fired.append, name="t-wheel")
        try:
            wheel.arm("a", 0.03)
            wheel.arm("a", 10.0)   # heartbeat: pushes the deadline out
            wheel.arm("b", 0.03)
            wheel.cancel("b")
            wheel.arm("c", 0.03)
            wait_until(lambda: "c" in fired, timeout=5.0, msg="c expiry")
            time.sleep(0.1)  # sleep-ok: settle window proving a/b stayed silent
            assert fired == ["c"]
            assert wheel.armed("a") and not wheel.armed("b")
        finally:
            wheel.stop()

    def test_one_thread_any_fleet_size(self):
        """The point of the wheel: 1000 armed nodes, ONE service
        thread (the per-node threading.Timer army it replaces would be
        1000)."""
        wheel = TTLWheel(lambda k: None, name="t-wheel")
        try:
            before = threading.active_count()
            for i in range(1000):
                wheel.arm(f"n-{i}", 60.0)
            assert wheel.active() == 1000
            assert threading.active_count() <= before + 1
        finally:
            wheel.stop()

    def test_compaction_keeps_live_entries(self):
        """Re-arm churn (every heartbeat) must not leak heap entries or
        lose live deadlines."""
        fired = []
        wheel = TTLWheel(fired.append, name="t-wheel")
        try:
            for rep in range(40):
                for i in range(50):
                    wheel.arm(f"n-{i}", 30.0)
            assert wheel.active() == 50
            assert len(wheel._heap) < 2000  # compacted, not 40*50
            wheel.arm("n-7", 0.02)  # live re-arm after churn still fires
            wait_until(lambda: fired == ["n-7"], timeout=5.0,
                       msg="post-compaction expiry")
        finally:
            wheel.stop()

    def test_callback_failure_does_not_kill_the_wheel(self):
        fired = []

        def cb(key):
            if key == "bad":
                raise RuntimeError("boom")
            fired.append(key)

        wheel = TTLWheel(cb, name="t-wheel")
        try:
            wheel.arm("bad", 0.01)
            wheel.arm("good", 0.05)
            wait_until(lambda: fired == ["good"], timeout=5.0,
                       msg="wheel survives callback failure")
        finally:
            wheel.stop()

    def test_stop_joins_the_thread(self):
        wheel = TTLWheel(lambda k: None, name="t-wheel-stop")
        wheel.arm("a", 60.0)
        thread = wheel._thread
        wheel.stop()
        assert thread is not None and not thread.is_alive()
        with pytest.raises(RuntimeError):
            wheel.arm("b", 1.0)


# ---------------------------------------------------------------------------
# Heartbeat manager: wheel mode, deferral, pacing, seeded jitter
# ---------------------------------------------------------------------------

class _StubServer:
    """Just enough server for the heartbeat manager: records
    invalidations."""

    def __init__(self) -> None:
        self.downed: list = []
        self.down_times: list = []

    def node_update_status(self, node_id, status):
        self.downed.append(node_id)
        self.down_times.append(time.monotonic())
        return 1


class TestHeartbeatDamping:
    def test_real_expiry_invalidates_through_pacing(self):
        srv = _StubServer()
        hb = HeartbeatManager(srv, min_ttl=0.05, grace=0.0)
        try:
            hb._arm("n-1", 0.05)
            wait_until(lambda: srv.downed == ["n-1"], timeout=5.0,
                       msg="paced invalidation")
        finally:
            hb.shutdown()

    def test_heartbeat_rescues_node_pending_invalidation(self):
        """Zero false expiries by construction: a heartbeat arriving
        while the node waits in the pacing queue pulls it back out."""
        srv = _StubServer()
        hb = HeartbeatManager(srv, min_ttl=0.2, grace=0.0,
                              reconcile_rate=0.5, reconcile_burst=1.0)
        try:
            # Exhaust the burst so the victim queues behind pacing.
            hb._bucket.try_take()
            hb._on_ttl_expire("n-victim")
            assert hb.stats()["pending_expiries"] == 1
            hb.reset_heartbeat_timer("n-victim")  # the node IS alive
            assert hb.stats()["pending_expiries"] == 0
            assert hb.stats()["rescued"] == 1
            time.sleep(0.1)  # sleep-ok: settle window proving no invalidation
            assert srv.downed == []
        finally:
            hb.shutdown()

    def test_mass_expiry_drains_at_bounded_rate(self):
        """The damping contract: N simultaneous expiries reach the
        broker as a paced trickle, not one storm."""
        srv = _StubServer()
        hb = HeartbeatManager(srv, reconcile_rate=20.0,
                              reconcile_burst=2.0)
        try:
            for i in range(10):
                hb._on_ttl_expire(f"n-{i}")
            wait_until(lambda: len(srv.downed) == 10, timeout=10.0,
                       msg="paced drain")
            # Burst of 2 immediately; the remaining 8 at 20/s => >=0.35s
            # spread.  A storm (no pacing) lands in ~ms.
            spread = max(srv.down_times) - min(srv.down_times)
            assert spread >= 0.3, f"expiries not paced: {spread:.3f}s"
        finally:
            hb.shutdown()

    def test_brownout_defers_expiry(self):
        """While the server is browning out, a missed TTL re-arms
        instead of invalidating: the server's own slowness can never
        mass-expire the fleet."""
        srv = _StubServer()
        ctrl = OverloadController()
        ctrl.force_state(BROWNOUT)
        hb = HeartbeatManager(srv, overload=ctrl, brownout_defer=0.05)
        try:
            hb._on_ttl_expire("n-1")
            assert hb.stats()["deferred_expiries"] == 1
            assert srv.downed == []
            assert hb.active() == 1  # re-armed at the defer TTL
            # Brownout clears -> the deferred TTL expires for real.
            ctrl.force_state(None)
            wait_until(lambda: srv.downed == ["n-1"], timeout=5.0,
                       msg="post-brownout expiry")
        finally:
            hb.shutdown()

    def test_seeded_jitter_replays_bit_stable(self):
        import random

        ttls = []
        for _ in range(2):
            hb = HeartbeatManager(_StubServer(),
                                  rng=random.Random(42))
            try:
                ttls.append([hb.reset_heartbeat_timer(f"n-{i}")
                             for i in range(5)])
            finally:
                hb.shutdown()
        assert ttls[0] == ttls[1]

    def test_clear_disarms_pending_invalidations(self):
        """Leadership revoked mid-pacing: a follower must never
        invalidate queued nodes."""
        srv = _StubServer()
        hb = HeartbeatManager(srv, reconcile_rate=0.5,
                              reconcile_burst=1.0)
        try:
            hb._bucket.try_take()  # force pacing
            hb._on_ttl_expire("n-1")
            hb.clear()
            assert hb.stats()["pending_expiries"] == 0
            time.sleep(0.05)  # sleep-ok: settle window proving no invalidation
            assert srv.downed == []
        finally:
            hb.shutdown()


# ---------------------------------------------------------------------------
# Broker admission + deadlines
# ---------------------------------------------------------------------------

class TestBrokerAdmission:
    def _broker(self, ctrl=None, **kw) -> EvalBroker:
        b = EvalBroker(nack_timeout=5, delivery_limit=3,
                       admission=ctrl, **kw)
        b.set_enabled(True)
        return b

    def test_brownout_sheds_batch_admits_service(self):
        ctrl = OverloadController()
        ctrl.force_state(BROWNOUT)
        b = self._broker(ctrl)
        with pytest.raises(ErrOverloaded):
            b.enqueue(make_eval(type_="batch"))
        b.enqueue(make_eval(type_="service"))
        b.enqueue(make_eval(type_="system"))
        assert b.stats()["total_ready"] == 2

    def test_force_bypasses_admission_and_bound(self):
        """Committed-state paths (FSM apply, leadership restore) must
        never shed — the broker would diverge from state."""
        ctrl = OverloadController()
        ctrl.force_state(OVERLOAD)
        b = self._broker(ctrl, max_depth=1)
        b.enqueue(make_eval(type_="system"))
        b.enqueue(make_eval(type_="batch"), force=True)
        b.enqueue(make_eval(type_="service"), force=True)
        assert b.stats()["total_ready"] == 3

    def test_depth_bound_sheds(self):
        b = self._broker(max_depth=2)
        b.enqueue(make_eval())
        b.enqueue(make_eval())
        with pytest.raises(ErrOverloaded):
            b.enqueue(make_eval())
        assert b.stats()["depth_sheds"] == 1
        # Re-enqueue of a tracked eval is not a new admission.
        ev = make_eval()
        with pytest.raises(ErrOverloaded):
            b.enqueue(ev)

    def test_deadline_expired_eval_never_reaches_a_worker(self):
        """The dequeue-side drop: expired work routes to the failed
        queue (the reaper makes it terminal) and counts as an
        expired_drop; live work behind it is still delivered."""
        b = self._broker()
        dead = make_eval(priority=90)
        live = make_eval(priority=10)
        b.enqueue(dead, deadline=time.monotonic() - 0.1)
        b.enqueue(live)
        ev, token = b.dequeue(["service"], timeout=0.2)
        assert ev is not None and ev.id == live.id
        assert b.stats()["expired_drops"] == 1
        # The dropped eval is delivered to the reaper's queue instead.
        failed_ev, ftoken = b.dequeue([FAILED_QUEUE], timeout=0.2)
        assert failed_ev is not None and failed_ev.id == dead.id
        b.ack(failed_ev.id, ftoken)
        b.ack(ev.id, token)

    def test_expired_drop_keeps_job_serialization(self):
        """A dropped eval holds its job's in-flight slot until the
        reaper acks (exactly like the delivery-limit path): blocked
        siblings must not double-deliver."""
        b = self._broker()
        job = generate_uuid()
        first = make_eval(job_id=job)
        sibling = make_eval(job_id=job)
        b.enqueue(first, deadline=time.monotonic() - 0.1)
        b.enqueue(sibling)
        assert b.stats()["total_blocked"] == 1
        ev, _ = b.dequeue(["service"], timeout=0.05)
        assert ev is None  # sibling stays blocked behind the drop
        failed_ev, ftoken = b.dequeue([FAILED_QUEUE], timeout=0.2)
        assert failed_ev.id == first.id
        b.ack(failed_ev.id, ftoken)  # reaper acks -> sibling promotes
        ev, token = b.dequeue(["service"], timeout=0.5)
        assert ev is not None and ev.id == sibling.id
        b.ack(ev.id, token)

    def test_live_deadline_is_delivered(self):
        b = self._broker()
        ev_in = make_eval()
        b.enqueue(ev_in, deadline=time.monotonic() + 30.0)
        ev, token = b.dequeue(["service"], timeout=0.2)
        assert ev is not None and ev.id == ev_in.id
        assert b.stats()["expired_drops"] == 0
        b.ack(ev.id, token)

    def test_disabled_broker_arms_no_wait_timers(self):
        """Stray threading.Timers must never fire into a torn-down
        server: a disabled broker queues nothing and arms nothing."""
        b = EvalBroker(nack_timeout=5, delivery_limit=3)
        ev = make_eval()
        ev.wait = 30.0
        b.enqueue(ev, force=True)
        assert b.stats()["total_waiting"] == 0

    def test_broker_enqueue_fault_site(self):
        """The new chokepoint: a broker.enqueue error rule injects at
        admission, predicated on scheduler type via ``method``."""
        b = self._broker()
        plan = FaultPlan(seed=1).add("broker.enqueue", "error",
                                     method="batch")
        with faultinject.injected(plan):
            with pytest.raises(faultinject.FaultInjected):
                b.enqueue(make_eval(type_="batch"))
            b.enqueue(make_eval(type_="service"))
        assert plan.fire_count("broker.enqueue") == 1

    def test_rpc_admit_site_context_validated(self):
        """SITE_CONTEXT rejects predicates the new sites cannot satisfy
        (a silently-never-firing chaos rule is the worst outcome)."""
        FaultPlan().add("rpc.admit", "error", method="Job.*")
        FaultPlan().add("broker.enqueue", "drop", node="n-*")
        with pytest.raises(FaultSpecError):
            FaultPlan().add("raft.apply", "error", method="Job.*")


# ---------------------------------------------------------------------------
# Plan queue bound + applier deadline drops
# ---------------------------------------------------------------------------

class TestPlanPathDeadlines:
    def test_plan_queue_depth_bound(self):
        pq = PlanQueue(max_depth=2)
        pq.set_enabled(True)
        pq.enqueue(Plan(eval_id="e1"))
        pq.enqueue(Plan(eval_id="e2"))
        with pytest.raises(ErrOverloaded):
            pq.enqueue(Plan(eval_id="e3"))
        assert pq.stats()["depth_sheds"] == 1
        pq.set_enabled(False)

    def test_applier_drops_expired_plans(self):
        """An expired plan is answered with ErrDeadlineExceeded without
        verification; live plans in the same window commit normally."""
        from nomad_tpu.server.fsm import NomadFSM
        from nomad_tpu.server.plan_apply import PlanApplier
        from nomad_tpu.server.raft import InmemRaft

        broker = EvalBroker(nack_timeout=30, delivery_limit=3)
        broker.set_enabled(True)
        fsm = NomadFSM(eval_broker=broker)
        raft = InmemRaft(fsm)
        node = mock.node(1)
        fsm.state.upsert_node(1, node)
        pq = PlanQueue()
        pq.set_enabled(True)
        applier = PlanApplier(pq, broker, raft, lambda: fsm.state)

        def outstanding_plan(deadline=0.0) -> Plan:
            ev = make_eval()
            broker.enqueue(ev)
            got, token = broker.dequeue(["service"], timeout=1.0)
            assert got.id == ev.id
            plan = Plan(eval_id=ev.id, eval_token=token,
                        deadline=deadline)
            alloc = mock.alloc()
            alloc.node_id = node.id
            plan.append_alloc(alloc)
            return plan

        expired_f = pq.enqueue(outstanding_plan(
            deadline=time.monotonic() - 0.5))
        live_f = pq.enqueue(outstanding_plan(
            deadline=time.monotonic() + 30.0))
        applier.start()
        try:
            with pytest.raises(ErrDeadlineExceeded):
                expired_f.wait(5.0)
            result = live_f.wait(5.0)
            assert result is not None and result.node_allocation
            assert applier.stats()["expired_drops"] == 1
        finally:
            pq.set_enabled(False)
            applier.join(5.0)

    def test_worker_stamps_delivery_deadline_on_plans(self):
        """The worker propagates its nack-window deadline onto every
        plan it submits — the applier's drop has something to check."""
        from nomad_tpu.server import Server, ServerConfig

        srv = Server(ServerConfig(num_schedulers=0,
                                  eval_nack_timeout=7.0))
        srv.establish_leadership()
        try:
            from nomad_tpu.server.worker import Worker

            w = Worker(srv)
            w._delivery_deadline = time.monotonic() + 7.0
            seen = {}
            real_enqueue = srv.plan_queue.enqueue

            def spy(plan):
                seen["deadline"] = plan.deadline
                return real_enqueue(plan)

            srv.plan_queue.enqueue = spy
            plan = Plan(eval_id=generate_uuid())
            try:
                w.submit_plan(plan)
            except Exception:
                pass  # noop plan fencing may reject; the stamp happened
            assert seen["deadline"] == pytest.approx(
                w._delivery_deadline)
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# End-to-end: RPC admission at a real server
# ---------------------------------------------------------------------------

class TestServerAdmission:
    def test_overloaded_server_sheds_job_but_serves_heartbeat(self):
        from nomad_tpu.server import Server, ServerConfig
        from nomad_tpu.server.rpc import ConnPool, RPCError

        srv = Server(ServerConfig(num_schedulers=0, enable_rpc=True))
        srv.establish_leadership()
        pool = ConnPool()
        try:
            addr = srv.rpc_address()
            node = mock.node(1)
            pool.call(addr, "Node.Register", {"node": node.to_dict()})
            srv.overload.force_state(OVERLOAD)
            job = mock.job()
            with pytest.raises(RPCError) as exc:
                pool.call(addr, "Job.Register", {"job": job.to_dict()})
            assert is_overloaded(exc.value)
            # The liveness lane stays open in full overload.
            out = pool.call(addr, "Node.Heartbeat",
                            {"node_id": node.id})
            assert out["heartbeat_ttl"] > 0
            # Shedding cleared: the SAME register now rides a retry
            # policy to success (the client-side story).
            srv.overload.force_state(None)
            from nomad_tpu.utils.retry import (RetryPolicy,
                                               transport_or_overload)
            policy = RetryPolicy(base=0.01, max_attempts=3,
                                 retryable=transport_or_overload,
                                 name="test.overload")
            out = policy.call(lambda: pool.call(
                addr, "Job.Register", {"job": job.to_dict()}))
            assert out["eval_id"]
            assert srv.overload.stats()["shed"][CLASS_SERVICE] >= 1
        finally:
            pool.shutdown()
            srv.shutdown()
