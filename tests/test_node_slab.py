"""Columnar node table (structs/node_slab.py + store bulk path).

The 100k-1M-node contract: a NodeSlab's lazy rows must be
indistinguishable from full Node objects everywhere one is read — dict
round trip, store semantics, fleet tensors, constraint masks, and
end-to-end scheduler placements — while the bulk-load and
fleet-build paths never walk per-node Python.
"""
from __future__ import annotations

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.models.constraints import compile_group_mask
from nomad_tpu.models.fleet import build_fleet, fleet_cache
from nomad_tpu.scheduler.util import task_group_constraints
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import Node, node_slab_of

pytestmark = pytest.mark.multichip


def _norm(d: dict) -> dict:
    d = dict(d)
    d["id"] = "X"
    d["create_index"] = 0
    d["modify_index"] = 0
    return d


def test_slab_row_materializes_bit_identical_to_mock_node():
    slab = mock.node_slab(8)
    for r in (0, 3, 7):
        assert _norm(slab.node(r).to_dict()) == _norm(mock.node(r).to_dict())


def test_store_bulk_upsert_semantics():
    slab = mock.node_slab(6)
    st = StateStore()
    st.upsert_node_slab(42, slab)
    assert st.get_index("nodes") == 42
    nodes = list(st.nodes())
    assert len(nodes) == 6
    assert node_slab_of(nodes) is slab
    for n in nodes:
        assert st.node_by_id(n.id) is n
        assert n.create_index == n.modify_index == 42
    # A later object-path write rides the normal upsert contract and
    # detaches that row from the slab fast path.
    st.update_node_status(43, nodes[2].id, "down")
    assert node_slab_of(list(st.nodes())) is None
    assert st.node_by_id(nodes[2].id).status == "down"
    # The untouched rows still read through the slab.
    assert st.node_by_id(nodes[3].id).resources.cpu == 4000


def test_slab_copy_honors_node_copy_contract():
    slab = mock.node_slab(3)
    row = slab.node(1)
    c = row.copy()
    # Deep-dict contract: mutating the copy's attributes never leaks
    # into the slab template or sibling rows.
    c.attributes["kernel.name"] = "plan9"
    assert row.attributes["kernel.name"] == "linux"
    assert slab.node(2).attributes["kernel.name"] == "linux"
    # Scalar writes flag the row as mutated (fast-path disqualifier).
    c2 = slab.node(2).copy()
    c2.drain = True
    assert "_hmut" in c2.__dict__
    assert "_hmut" not in slab.node(2).__dict__


def _object_twin(slab) -> list:
    """Plain Node objects with the SAME ids/content as the slab rows —
    the object-path control for byte-parity comparisons."""
    return [Node.from_dict(slab.node(r).to_dict()) for r in range(slab.n)]


def test_build_fleet_slab_fast_path_byte_parity():
    slab = mock.node_slab(24)
    st = StateStore()
    st.upsert_node_slab(7, slab)
    fast = build_fleet(list(st.nodes()))
    assert fast.uniform
    ref = build_fleet(_object_twin(slab))
    assert not ref.uniform
    np.testing.assert_array_equal(fast.capacity, ref.capacity)
    np.testing.assert_array_equal(fast.reserved, ref.reserved)
    np.testing.assert_array_equal(fast.ready, ref.ready)
    assert list(fast.datacenters[:24]) == list(ref.datacenters[:24])
    assert fast.node_ids == ref.node_ids
    assert fast.index_of == ref.index_of
    assert fast.attr_rows[23] == ref.attr_rows[23]
    assert len(fast.attr_rows) == len(ref.attr_rows) == 24


def test_uniform_constraint_masks_match_object_walk():
    """The one-representative-row mask compilation (uniform fleets)
    must produce byte-identical masks to the per-node walk — dc,
    constraint, and driver masks composed."""
    slab = mock.node_slab(16)
    st = StateStore()
    st.upsert_node_slab(7, slab)
    fast = build_fleet(list(st.nodes()))
    ref = build_fleet(_object_twin(slab))
    job = mock.job()
    tgc = task_group_constraints(job.task_groups[0])
    m_fast, d_fast = compile_group_mask(
        fast, job.datacenters, job.constraints, tgc.constraints,
        tgc.drivers)
    m_ref, d_ref = compile_group_mask(
        ref, job.datacenters, job.constraints, tgc.constraints,
        tgc.drivers)
    assert d_fast == d_ref
    np.testing.assert_array_equal(m_fast, m_ref)
    # A constraint no node meets: uniform verdict False everywhere.
    from nomad_tpu.structs import Constraint

    bad = Constraint(hard=True, l_target="$attr.kernel.name",
                     r_target="plan9", operand="=")
    m2, _ = compile_group_mask(fast, job.datacenters, [bad], [], set())
    assert not m2.any()


def test_scheduler_places_identically_on_slab_and_object_fleets():
    """End to end: the same job stream against a slab-backed store and
    its object-backed twin (same node ids) places byte-identically."""
    from nomad_tpu.scheduler import Harness
    from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER, Evaluation,
                                   generate_uuid)

    slab = mock.node_slab(16)

    def run(object_path: bool):
        h = Harness()
        if object_path:
            for n in _object_twin(slab):
                h.state.upsert_node(h.next_index(), n)
        else:
            h.state.upsert_node_slab(h.next_index(), slab)
        placements = []
        for _ in range(3):
            job = mock.job()
            job.task_groups[0].count = 6
            h.state.upsert_job(h.next_index(), job)
            ev = Evaluation(id=generate_uuid(), priority=job.priority,
                            type=job.type,
                            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                            job_id=job.id)
            h.process("jax-binpack", ev)
            rows = sorted(
                (a.node_id, a.task_group)
                for a in h.state.allocs_by_job(job.id)
                if not a.terminal_status())
            placements.append(rows)
        return placements

    slab_rows = run(object_path=False)
    obj_rows = run(object_path=True)
    assert slab_rows == obj_rows
    assert sum(len(r) for r in slab_rows) == 18


def test_mutated_row_falls_back_to_exact_object_build():
    """One drained node: the fleet build leaves the fast path and the
    scheduler must see the drain (no placement on that node)."""
    slab = mock.node_slab(4)
    st = StateStore()
    st.upsert_node_slab(5, slab)
    drained = list(st.nodes())[1]
    st.update_node_drain(6, drained.id, True)
    statics = fleet_cache.statics_for(st)
    assert not statics.uniform
    di = statics.index_of[drained.id]
    assert not statics.ready[di]
    assert statics.ready[statics.index_of[list(st.nodes())[0].id]]


def test_per_row_constraint_targets_skip_the_uniform_fast_path():
    """$node.id / $node.name resolve per ROW (dense slab columns), so
    the one-representative-row mask compilation must not broadcast
    them — review finding: a $node.name = node-5 constraint on a
    uniform fleet compiled to all-False."""
    from nomad_tpu.models.constraints import compile_constraint_mask
    from nomad_tpu.structs import Constraint

    slab = mock.node_slab(8)
    st = StateStore()
    st.upsert_node_slab(7, slab)
    fast = build_fleet(list(st.nodes()))
    assert fast.uniform
    ref = build_fleet(_object_twin(slab))
    for c in (
        Constraint(hard=True, l_target="$node.name", r_target="node-5",
                   operand="="),
        Constraint(hard=True, l_target="$node.name", r_target="node-5",
                   operand="!="),
        Constraint(hard=True, l_target="$node.id",
                   r_target=slab.ids[3], operand="="),
        # Covered-by-uniform targets still take the fast path and must
        # agree too.
        Constraint(hard=True, l_target="$node.datacenter",
                   r_target="dc1", operand="="),
    ):
        np.testing.assert_array_equal(
            compile_constraint_mask(fast, c),
            compile_constraint_mask(ref, c), err_msg=str(c))
    # The node-5 equality mask really selects exactly row 5.
    m = compile_constraint_mask(
        fast, Constraint(hard=True, l_target="$node.name",
                         r_target="node-5", operand="="))
    assert m[:8].tolist() == [False] * 5 + [True] + [False] * 2


def test_bulk_upsert_stamps_pre_materialized_rows():
    """A row materialized BEFORE the bulk upsert (slab.node/rows are
    public) must still read the upsert's index from the store — review
    finding: cached rows kept their eager dict's stale index."""
    slab = mock.node_slab(4)
    early = slab.node(2)  # materialized pre-upsert, index still 0
    assert early.modify_index == 0
    st = StateStore()
    st.upsert_node_slab(42, slab)
    assert st.node_by_id(slab.ids[2]) is early
    assert early.create_index == early.modify_index == 42
    # And the stamp rode the internal poke path: the row is still an
    # unmutated slab row (fast path intact).
    assert node_slab_of(list(st.nodes())) is slab
