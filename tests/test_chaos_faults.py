"""Chaos soak under a seeded FaultPlan (slow tier).

One seeded plan drives three failure families at once and the system
must converge anyway:

  - heartbeat loss: three victim nodes' heartbeats are dropped at the
    delivery site, so the REAL TTL-expiry path marks them down and the
    resulting node-update evals reschedule their work;
  - RPC drops: Job.Register frames are dropped on both the send and
    receive planes mid-storm; submission rides the unified retry
    policy, exactly as a production client would;
  - device faults: the pipelined runner takes dispatch errors and a
    hung collect, re-runs the affected evals on the host twin, and the
    circuit breaker must record full open -> half-open(probe, parity
    asserted) -> closed cycles.

Convergence bar (ISSUE acceptance): every submitted job fully placed
exactly once on live capacity, no eval left non-terminal, breaker
cycled at least once with host/device parity asserted on probe re-runs.
"""
from __future__ import annotations

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu import faultinject
from nomad_tpu.faultinject import FaultDropped, FaultPlan
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool
from nomad_tpu.structs import (
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
    Task,
    TaskGroup,
    Resources,
    allocs_fit,
)
from nomad_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.slow

TERMINAL = ("complete", "failed", "canceled")

SUBMIT_POLICY = RetryPolicy(
    base=0.2, max_delay=1.0, max_attempts=8,
    retryable=lambda e: isinstance(e, Exception),
    name="chaos.submit")


def _job(n_groups: int, count: int):
    job = mock.job()
    job.task_groups = [
        TaskGroup(name=f"tg-{g}", count=count,
                  tasks=[Task(name="web", driver="exec",
                              resources=Resources(cpu=200,
                                                  memory_mb=64))])
        for g in range(n_groups)]
    return job


def test_chaos_soak_with_seeded_fault_plan():
    plan = FaultPlan.parse(
        "seed=2026;"
        # Lost job-submission frames, both planes, mid-storm.
        "rpc.send=drop(p=0.5,count=4,method=Job.Register);"
        "rpc.recv=drop(p=0.5,count=4,method=Job.Register);"
        # Raft latency chaos (never fails, just jitters commit timing).
        "raft.apply=delay(secs=0.005,p=0.2,count=40);"
        # Victim nodes lose every heartbeat delivery after the three
        # registration-time arms (registration precedes the heartbeat
        # loop, so the skip budget lands deterministically).
        "heartbeat.deliver=drop(node=chaos-victim-*,after=3);"
        # Device faults for the pipelined-runner phase.
        "device.dispatch=error(count=1);"
        "device.collect=hang(secs=1.0,count=1)")

    with faultinject.injected(plan):
        _server_phase(plan)
        _device_phase(plan)


def _server_phase(plan: FaultPlan) -> None:
    """Job storm over real RPC with lost frames + heartbeat-loss-driven
    reschedules; must converge to exactly-once placement."""
    srv = Server(ServerConfig(num_schedulers=4, enable_rpc=True))
    srv.heartbeats.min_ttl = 0.5
    srv.heartbeats.grace = 0.3
    srv.establish_leadership()
    pool = ConnPool()
    try:
        addr = srv.rpc_address()

        n_nodes, n_victims = 24, 3
        victims, survivors = [], []
        for i in range(n_nodes):
            node = mock.node(i)
            if i < n_victims:
                # The heartbeat.deliver rule matches this id prefix.
                node.id = f"chaos-victim-{node.id}"
            out = SUBMIT_POLICY.call(
                lambda n=node: pool.call(addr, "Node.Register",
                                         {"node": n.to_dict()}))
            assert out["heartbeat_ttl"] > 0
            (victims if i < n_victims else survivors).append(node.id)

        # Background heartbeater for the WHOLE phase: survivors stay
        # alive through the multi-second submission stalls the RPC
        # drops cause; victims' deliveries are dropped by the plan, so
        # their TTLs expire for real while everything else churns.
        import threading

        stop_beat = threading.Event()

        def _beater() -> None:
            while not stop_beat.is_set():
                for nid in survivors + victims:
                    try:
                        pool.call(addr, "Node.Heartbeat",
                                  {"node_id": nid}, timeout=2.0)
                    except Exception:
                        pass  # victims: delivery dropped — the point
                stop_beat.wait(0.15)

        beater = threading.Thread(target=_beater, daemon=True,
                                  name="chaos-heartbeater")
        beater.start()

        jobs = []
        for _ in range(10):
            job = _job(n_groups=6, count=2)
            # The retry policy carries the submission through injected
            # send/recv drops; a duplicate register (timeout after the
            # server processed it) is converged by the scheduler.
            SUBMIT_POLICY.call(
                lambda j=job: pool.call(addr, "Job.Register",
                                        {"job": j.to_dict()},
                                        timeout=2.0))
            jobs.append(job)
        assert plan.fire_count("rpc.send") + \
            plan.fire_count("rpc.recv") > 0, "no RPC chaos was injected"

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            state = srv.fsm.state
            evals = state.evals()
            victims_down = all(
                state.node_by_id(nid).status == NODE_STATUS_DOWN
                for nid in victims)
            if evals and victims_down and \
                    all(e.status in TERMINAL for e in evals) and \
                    len(evals) >= len(jobs):
                # Quiesced once; re-check after a beat in case expiry
                # evals were still being written.
                time.sleep(0.3)  # sleep-ok: settle window for in-flight expiry evals
                evals = srv.fsm.state.evals()
                if all(e.status in TERMINAL for e in evals):
                    break
            time.sleep(0.1)  # sleep-ok: poll cadence between liveness heartbeats

        stop_beat.set()
        beater.join(5.0)
        state = srv.fsm.state

        # 1) No eval left non-terminal.
        stuck = [(e.id, e.status) for e in state.evals()
                 if e.status not in TERMINAL]
        assert not stuck, f"non-terminal evals after soak: {stuck[:5]}"

        # 2) Victims expired through the real TTL path; survivors ready.
        for nid in victims:
            assert state.node_by_id(nid).status == NODE_STATUS_DOWN, nid
        for nid in survivors:
            assert state.node_by_id(nid).status == NODE_STATUS_READY, nid
        assert plan.fire_count("heartbeat.deliver") >= n_victims

        # 3) Every job fully placed exactly once, on live nodes only.
        victim_set = set(victims)
        for job in jobs:
            live = [a for a in state.allocs_by_job(job.id)
                    if not a.terminal_status()]
            want = sum(tg.count for tg in job.task_groups)
            assert len(live) == want, \
                f"job {job.id}: {len(live)} live allocs, want {want}"
            by_group: dict = {}
            for a in live:
                by_group[a.task_group] = by_group.get(a.task_group, 0) + 1
                assert a.node_id not in victim_set, \
                    "placement left on a down node"
            assert all(by_group[tg.name] == tg.count
                       for tg in job.task_groups), "duplicate placement"

        # 4) No oversubscription anywhere.
        for nid in survivors:
            node = state.node_by_id(nid)
            live = [a for a in state.allocs_by_node(nid)
                    if not a.terminal_status()]
            fit, dim, _ = allocs_fit(node, live)
            assert fit, f"node {nid} oversubscribed on {dim}"
    finally:
        pool.shutdown()
        srv.shutdown()


def test_batched_commit_rides_raft_apply_faults():
    """Group-commit window through the REAL ``raft.apply`` site under
    injected drops/errors (ISSUE 5 satellite): an errored/dropped batch
    apply must respond EVERY member future — no scheduler worker may
    park — and the workers' retries must converge to exactly-once
    placement with no double-placed group."""
    srv = Server(ServerConfig(num_schedulers=2))
    srv.establish_leadership()
    try:
        for i in range(12):
            srv.node_register(mock.node(i))

        # Faults go live only for the eval storm: every batched commit
        # rides the same raft.apply chokepoint, so the first few
        # windows die (drop = entry never entered the log) and the
        # member evals retry through the plan-rejection path.
        plan = FaultPlan.parse(
            "seed=11;raft.apply=drop(p=0.7,count=3)")
        jobs = [_job(n_groups=4, count=2) for _ in range(8)]
        with faultinject.injected(plan):
            for job in jobs:
                SUBMIT_POLICY.call(lambda j=job: srv.job_register(j))

            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                evals = srv.fsm.state.evals()
                if evals and len(evals) >= len(jobs) and \
                        all(e.status in TERMINAL for e in evals):
                    break
                time.sleep(0.1)  # sleep-ok: poll cadence while the storm converges

        state = srv.fsm.state
        stuck = [(e.id, e.status) for e in state.evals()
                 if e.status not in TERMINAL]
        assert not stuck, \
            f"non-terminal evals after raft chaos: {stuck[:5]}"
        assert plan.fire_count("raft.apply") == 3, \
            "the batched commit never crossed the fault site"

        # Exactly-once placement per group despite the dropped windows.
        for job in jobs:
            live = [a for a in state.allocs_by_job(job.id)
                    if not a.terminal_status()]
            want = sum(tg.count for tg in job.task_groups)
            assert len(live) == want, \
                f"job {job.id}: {len(live)} live allocs, want {want}"
            by_group: dict = {}
            for a in live:
                by_group[a.task_group] = by_group.get(a.task_group, 0) + 1
            assert all(by_group[tg.name] == tg.count
                       for tg in job.task_groups), "duplicate placement"
        for node in state.nodes():
            live = [a for a in state.allocs_by_node(node.id)
                    if not a.terminal_status()]
            fit, dim, _ = allocs_fit(node, live)
            assert fit, f"node {node.id} oversubscribed on {dim}"

        # The group-commit applier actually batched: strictly fewer
        # commits than the plans they carried (a drain regression that
        # degrades every window to one plan fails here).
        stats = srv.plan_applier.stats()
        assert stats["plans_committed"] >= len(jobs)
        assert stats["commits"] < stats["plans_committed"], stats
    finally:
        srv.shutdown()


def _device_phase(plan: FaultPlan) -> None:
    """Pipelined-runner stream under device faults: the breaker must
    complete open -> half-open -> closed cycles with parity asserted,
    and every eval must still complete."""
    from nomad_tpu.scheduler import Harness
    from nomad_tpu.scheduler.breaker import (CLOSED, OPEN,
                                             DeviceCircuitBreaker)
    from nomad_tpu.scheduler.executor import executor_override
    from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner
    from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER, Evaluation,
                                   generate_uuid)

    h = Harness()
    for i in range(12):
        h.state.upsert_node(h.next_index(), mock.node(100 + i))
    jobs = []
    for _ in range(6):
        j = mock.job()
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)

    def ev(job):
        return Evaluation(id=generate_uuid(), priority=job.priority,
                          type=job.type,
                          triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                          job_id=job.id)

    breaker = DeviceCircuitBreaker(failure_threshold=1, cooldown=0.05)
    reruns = parity = 0
    with executor_override("device"):
        # One eval per runner call so each breaker transition is
        # observable; the breaker itself persists across runners.
        for i, job in enumerate(jobs):
            runner = PipelinedEvalRunner(
                h.state.snapshot(), h, depth=2, breaker=breaker,
                device_deadline=0.25,
                state_refresh=lambda: h.state.snapshot())
            runner.process([ev(job)])
            reruns += runner.breaker_reruns
            parity += runner.parity_checks
            if breaker.state == OPEN:
                time.sleep(0.06)  # sleep-ok: let the breaker cooldown elapse -> probe next

    stats = breaker.stats()
    # Both fault families tripped it (the hung collect landed on the
    # first probe itself, re-opening it), and at least one full
    # open -> half-open -> closed cycle completed with parity asserted
    # on the probe re-run.
    assert stats["opens"] >= 2, stats
    assert stats["probes"] >= 2, stats
    assert stats["closes"] >= 1, stats
    assert breaker.state == CLOSED, stats
    assert reruns >= 2
    assert parity >= 1
    assert plan.fire_count("device.dispatch") == 1
    assert plan.fire_count("device.collect") == 1

    # Every eval completed and the resulting placements are sane.
    assert all(e.status == "complete" for e in h.evals)
    assert len(h.plans) == len(jobs)
    nodes = {n.id: n for n in h.state.nodes()}
    for p in h.plans:
        for node_id, allocs in p.node_allocation.items():
            fit, dim, _ = allocs_fit(nodes[node_id], allocs)
            assert fit, dim
    total = sum(len(v) for p in h.plans
                for v in p.node_allocation.values())
    assert total == sum(tg.count for j in jobs for tg in j.task_groups)
