"""Boot-log gating + recent-log ring (reference helper/gated-writer +
command/agent/log_writer.go)."""
from __future__ import annotations

import io
import logging

from nomad_tpu.utils.gated_log import BootLogGate, GatedHandler, LogWriter


def test_pre_config_lines_appear_exactly_once_post_setup():
    stream = io.StringIO()
    gate = BootLogGate(logger_name="nomad_tpu.test.boot", stream=stream)
    try:
        log = logging.getLogger("nomad_tpu.test.boot")
        log.info("boot line one")
        log.warning("boot line two")
        assert stream.getvalue() == ""  # nothing until the gate opens

        gate.open("INFO")
        out = stream.getvalue()
        assert out.count("boot line one") == 1
        assert out.count("boot line two") == 1

        log.info("live line")
        out = stream.getvalue()
        # Replay happened once; live lines pass straight through.
        assert out.count("boot line one") == 1
        assert out.count("live line") == 1
    finally:
        gate.remove()


def test_configured_level_filters_buffered_and_live():
    stream = io.StringIO()
    gate = BootLogGate(logger_name="nomad_tpu.test.lvl", stream=stream)
    try:
        log = logging.getLogger("nomad_tpu.test.lvl")
        log.debug("buffered debug")
        log.info("buffered info")
        gate.open("WARN")
        log.info("live info")
        log.warning("live warn")
        out = stream.getvalue()
        assert "buffered debug" not in out
        assert "buffered info" not in out
        assert "live info" not in out
        assert "live warn" in out
    finally:
        gate.remove()


def test_sighup_level_change_refilters(caplog):
    stream = io.StringIO()
    gate = BootLogGate(logger_name="nomad_tpu.test.re", stream=stream)
    try:
        log = logging.getLogger("nomad_tpu.test.re")
        gate.open("INFO")
        log.debug("hidden debug")
        assert "hidden debug" not in stream.getvalue()
        gate.set_level("DEBUG")
        log.debug("visible debug")
        assert "visible debug" in stream.getvalue()
    finally:
        gate.remove()


def test_log_writer_ring_and_monitor():
    writer = LogWriter(maxlen=3)
    log = logging.getLogger("nomad_tpu.test.ring")
    log.setLevel(logging.INFO)
    log.propagate = False
    log.addHandler(writer)
    try:
        for i in range(5):
            log.info("line %d", i)
        ring = writer.lines()
        assert len(ring) == 3
        assert ring[-1].endswith("line 4")
        assert writer.lines(1)[0].endswith("line 4")

        seen: list = []
        unsub = writer.monitor(seen.append)
        assert len(seen) == 3  # backlog replayed into the monitor
        log.info("tail line")
        assert seen[-1].endswith("tail line")
        unsub()
        log.info("after unsub")
        assert not seen[-1].endswith("after unsub")
    finally:
        log.removeHandler(writer)
        log.propagate = True


def test_gated_handler_threadsafe_open():
    gate = GatedHandler()
    sink = LogWriter()
    rec = logging.LogRecord("n", logging.INFO, __file__, 1, "msg-%d", (7,),
                            None)
    gate.emit(rec)
    gate.open_gate([sink])
    assert any("msg-7" in ln for ln in sink.lines())
    rec2 = logging.LogRecord("n", logging.INFO, __file__, 1, "msg-%d",
                             (8,), None)
    gate.emit(rec2)
    assert any("msg-8" in ln for ln in sink.lines())


def test_log_writer_lines_since_offsets_survive_wrap():
    """Follow-mode contract: monotonic offsets work across ring
    eviction — no re-prints, evicted-unread lines simply gone."""
    import logging as _logging

    writer = LogWriter(maxlen=4)
    log = _logging.getLogger("nomad_tpu.test.since")
    log.setLevel(_logging.INFO)
    log.propagate = False
    log.addHandler(writer)
    try:
        for i in range(3):
            log.info("w%d", i)
        lines, off = writer.lines_since(0)
        assert len(lines) == 3 and off == 3
        # Nothing new: empty, offset unchanged.
        lines, off2 = writer.lines_since(off)
        assert lines == [] and off2 == 3
        # Wrap the ring: 6 more lines into a 4-slot ring.
        for i in range(3, 9):
            log.info("w%d", i)
        lines, off3 = writer.lines_since(off)
        assert off3 == 9
        # 6 appended since offset 3, but only 4 survive the ring.
        assert [ln[-2:] for ln in lines] == ["w5", "w6", "w7", "w8"]
        # Duplicate message text cannot confuse offsets.
        log.info("w8")
        lines, off4 = writer.lines_since(off3)
        assert len(lines) == 1 and off4 == 10
    finally:
        log.removeHandler(writer)


def test_lines_since_resets_after_counter_restart():
    """An offset from a previous agent process (since > total) returns
    the full ring — the restart backlog is exactly what a watching
    monitor wants, not silence."""
    import logging as _logging

    writer = LogWriter(maxlen=8)
    log = _logging.getLogger("nomad_tpu.test.restart")
    log.setLevel(_logging.INFO)
    log.propagate = False
    log.addHandler(writer)
    try:
        for i in range(3):
            log.info("boot %d", i)
        lines, off = writer.lines_since(5000)  # stale pre-restart offset
        assert len(lines) == 3 and off == 3
    finally:
        log.removeHandler(writer)
