"""Mesh-resident fleet tensors (the sharded usage mirror + statics).

The fused multi-chip dispatch must not re-upload capacity/reserved/usage
per call: statics cache a (mesh, capacity, reserved) triple, the
UsageMirror keeps a node-axis-sharded twin of its usage maintained by
the same scatter deltas as the single-device copy, and mesh._put skips
placement for already-resident shardings.
"""
import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import nomad_tpu.mock as mock
from nomad_tpu.models.fleet import ShardedResidency, fleet_cache, mirror_for
from nomad_tpu.parallel.mesh import FLEET_AXIS, fleet_mesh, _put
from nomad_tpu.state.store import StateStore
from tests.test_plan_verify_vec import bump, make_alloc

pytestmark = pytest.mark.multichip


def _rig(n_nodes=16):
    state = StateStore()
    nodes = [mock.node(i) for i in range(n_nodes)]
    idx = 10
    for n in nodes:
        state.upsert_node(idx, n)
        idx += 1
    return state, nodes, [idx]


def test_statics_sharded_capres_cached():
    state, nodes, cell = _rig()
    statics = fleet_cache.statics_for(state)
    mesh = fleet_mesh(jax.devices("cpu")[:8])
    cap1, res1 = statics.device_capacity_reserved_sharded(mesh)
    cap2, res2 = statics.device_capacity_reserved_sharded(mesh)
    assert cap1 is cap2 and res1 is res2  # resident, no re-upload
    node_sh = NamedSharding(mesh, P(FLEET_AXIS))
    assert cap1.sharding == node_sh
    np.testing.assert_array_equal(np.asarray(cap1), statics.capacity)
    # A different mesh re-uploads.
    mesh2 = fleet_mesh(jax.devices("cpu")[:4])
    cap3, _ = statics.device_capacity_reserved_sharded(mesh2)
    assert cap3 is not cap1


def test_put_skips_resident_arrays():
    mesh = fleet_mesh(jax.devices("cpu")[:8])
    sh = NamedSharding(mesh, P(FLEET_AXIS))
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    a = _put(x, sh)
    assert _put(a, sh) is a  # no-op on resident sharding


def test_mirror_sharded_usage_scatter_maintained():
    state, nodes, cell = _rig()
    statics = fleet_cache.statics_for(state)
    mirror = mirror_for(statics)
    mirror.sync(state)
    mesh = fleet_mesh(jax.devices("cpu")[:8])

    us1 = mirror.device_usage_sharded(mesh, mirror.usage)
    assert us1 is not None
    assert us1.sharding == NamedSharding(mesh, P(FLEET_AXIS))
    np.testing.assert_allclose(np.asarray(us1), mirror.usage)
    # Same view, same mesh: resident identity.
    assert mirror.device_usage_sharded(mesh, mirror.usage) is us1

    # Commit deltas; incremental scatter must track the host mirror and
    # keep the sharding.
    state.upsert_allocs(bump(cell), [make_alloc(nodes[3], cpu=700),
                                     make_alloc(nodes[5], cpu=900)])
    mirror.sync(state)
    us2 = mirror.device_usage_sharded(mesh, mirror.usage)
    assert us2 is not None and us2 is not us1
    assert us2.sharding == NamedSharding(mesh, P(FLEET_AXIS))
    np.testing.assert_allclose(np.asarray(us2), mirror.usage)

    # A stale view (the mirror has moved past it) gets None, never a
    # silently-wrong resident buffer.
    stale = mirror.usage
    state.upsert_allocs(bump(cell), [make_alloc(nodes[0], cpu=100)])
    mirror.sync(state)
    assert mirror.device_usage_sharded(mesh, stale) is None
    fresh = mirror.device_usage_sharded(mesh, mirror.usage)
    np.testing.assert_allclose(np.asarray(fresh), mirror.usage)


def test_sharded_residency_is_one_policy():
    """ONE bounded residency for every node-axis-sharded cache: keyed
    entries, class-scoped evict-all-at-the-bound, per-entry scatter
    counters — the per-call-site dicts it replaced are gone."""
    mesh = fleet_mesh(jax.devices("cpu")[:8])
    res = ShardedResidency(max_resident=2)
    a = np.arange(32, dtype=np.float32).reshape(16, 2)
    (buf,) = res.install(("usage", mesh), mesh, (a,))
    assert res.lookup(("usage", mesh))[0] is buf
    assert buf.sharding == NamedSharding(mesh, P(FLEET_AXIS))
    assert res.scatters(("usage", mesh)) == 0
    res.replace(("usage", mesh), (buf,))
    assert res.scatters(("usage", mesh)) == 1
    # [G, N] rows shard on the node axis with the group axis replicated.
    g = np.zeros((4, 16), dtype=bool)
    (gbuf,) = res.install(("feas", "k1", mesh), mesh, (g,),
                          spec=P(None, FLEET_AXIS))
    assert gbuf.sharding == NamedSharding(mesh, P(None, FLEET_AXIS))
    # Bound is per CLASS (key[0]): churning feasibility entries evicts
    # only feasibility — a stream of distinct job versions must never
    # evict the fleet-generation-lived capres/usage twins.
    res.install(("feas", "k2", mesh), mesh, (g,),
                spec=P(None, FLEET_AXIS))
    res.install(("feas", "k3", mesh), mesh, (g,),
                spec=P(None, FLEET_AXIS))  # at the bound: clears feas
    assert res.lookup(("feas", "k1", mesh)) is None
    assert res.lookup(("feas", "k2", mesh)) is None
    assert res.lookup(("feas", "k3", mesh)) is not None
    assert res.lookup(("usage", mesh)) is not None  # survived the churn


def test_statics_sharded_feasibility_resident():
    """The per-job feasibility rows get mesh-resident twins keyed by
    the prep cache's feas_key — uploaded once, reused per dispatch."""
    state, nodes, cell = _rig()
    statics = fleet_cache.statics_for(state)
    mesh = fleet_mesh(jax.devices("cpu")[:8])
    host = np.zeros((8, statics.n_pad), dtype=bool)
    host[0, : statics.n_real] = True
    f1 = statics.device_feasible_sharded(mesh, ("feas", "k1", 8), host)
    f2 = statics.device_feasible_sharded(mesh, ("feas", "k1", 8), host)
    assert f1 is f2
    assert f1.sharding == NamedSharding(mesh, P(None, FLEET_AXIS))
    np.testing.assert_array_equal(np.asarray(f1), host)
    # Capacity/reserved ride the SAME residency instance.
    cap, _res = statics.device_capacity_reserved_sharded(mesh)
    assert ("capres", mesh) in statics.sharded.keys()


def test_sharded_dispatch_uses_resident_primaries():
    """A forced-device single-eval dispatch on the 8-device host runs
    node-axis-sharded and reuses the resident twins (no re-upload):
    the statics' capres/feas entries and the mirror's usage twin are
    the SAME buffers across two dispatches of the same job."""
    from nomad_tpu.scheduler import Harness
    from nomad_tpu.scheduler.executor import executor_override
    from nomad_tpu.scheduler.jax_binpack import JaxBinPackScheduler
    from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER, Evaluation,
                                   generate_uuid)

    h = Harness()
    for i in range(16):
        h.state.upsert_node(h.next_index(), mock.node(i))
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    def one_dispatch():
        sched = JaxBinPackScheduler(h.state.snapshot(), h, batch=False)
        sched.eval = Evaluation(
            id=generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id)
        sched.defer_device = True
        sched._begin()
        place, args = sched.deferred
        with executor_override("device"):
            handles = sched.dispatch_device(args)
        chosen, scores = sched.collect_device(args, handles)
        assert sched.dispatched_sharded
        return args.statics, chosen

    statics1, chosen1 = one_dispatch()
    keys1 = set(statics1.sharded.keys())
    assert any(k[0] == "capres" for k in keys1)
    statics2, chosen2 = one_dispatch()
    assert statics2 is statics1  # same fleet generation
    assert set(statics2.sharded.keys()) == keys1  # resident, no churn
    assert np.array_equal(chosen1, chosen2)
