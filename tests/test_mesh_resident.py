"""Mesh-resident fleet tensors (the sharded usage mirror + statics).

The fused multi-chip dispatch must not re-upload capacity/reserved/usage
per call: statics cache a (mesh, capacity, reserved) triple, the
UsageMirror keeps a node-axis-sharded twin of its usage maintained by
the same scatter deltas as the single-device copy, and mesh._put skips
placement for already-resident shardings.
"""
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import nomad_tpu.mock as mock
from nomad_tpu.models.fleet import fleet_cache, mirror_for
from nomad_tpu.parallel.mesh import FLEET_AXIS, fleet_mesh, _put
from nomad_tpu.state.store import StateStore
from tests.test_plan_verify_vec import bump, make_alloc


def _rig(n_nodes=16):
    state = StateStore()
    nodes = [mock.node(i) for i in range(n_nodes)]
    idx = 10
    for n in nodes:
        state.upsert_node(idx, n)
        idx += 1
    return state, nodes, [idx]


def test_statics_sharded_capres_cached():
    state, nodes, cell = _rig()
    statics = fleet_cache.statics_for(state)
    mesh = fleet_mesh(jax.devices("cpu")[:8])
    cap1, res1 = statics.device_capacity_reserved_sharded(mesh)
    cap2, res2 = statics.device_capacity_reserved_sharded(mesh)
    assert cap1 is cap2 and res1 is res2  # resident, no re-upload
    node_sh = NamedSharding(mesh, P(FLEET_AXIS))
    assert cap1.sharding == node_sh
    np.testing.assert_array_equal(np.asarray(cap1), statics.capacity)
    # A different mesh re-uploads.
    mesh2 = fleet_mesh(jax.devices("cpu")[:4])
    cap3, _ = statics.device_capacity_reserved_sharded(mesh2)
    assert cap3 is not cap1


def test_put_skips_resident_arrays():
    mesh = fleet_mesh(jax.devices("cpu")[:8])
    sh = NamedSharding(mesh, P(FLEET_AXIS))
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    a = _put(x, sh)
    assert _put(a, sh) is a  # no-op on resident sharding


def test_mirror_sharded_usage_scatter_maintained():
    state, nodes, cell = _rig()
    statics = fleet_cache.statics_for(state)
    mirror = mirror_for(statics)
    mirror.sync(state)
    mesh = fleet_mesh(jax.devices("cpu")[:8])

    us1 = mirror.device_usage_sharded(mesh, mirror.usage)
    assert us1 is not None
    assert us1.sharding == NamedSharding(mesh, P(FLEET_AXIS))
    np.testing.assert_allclose(np.asarray(us1), mirror.usage)
    # Same view, same mesh: resident identity.
    assert mirror.device_usage_sharded(mesh, mirror.usage) is us1

    # Commit deltas; incremental scatter must track the host mirror and
    # keep the sharding.
    state.upsert_allocs(bump(cell), [make_alloc(nodes[3], cpu=700),
                                     make_alloc(nodes[5], cpu=900)])
    mirror.sync(state)
    us2 = mirror.device_usage_sharded(mesh, mirror.usage)
    assert us2 is not None and us2 is not us1
    assert us2.sharding == NamedSharding(mesh, P(FLEET_AXIS))
    np.testing.assert_allclose(np.asarray(us2), mirror.usage)

    # A stale view (the mirror has moved past it) gets None, never a
    # silently-wrong resident buffer.
    stale = mirror.usage
    state.upsert_allocs(bump(cell), [make_alloc(nodes[0], cpu=100)])
    mirror.sync(state)
    assert mirror.device_usage_sharded(mesh, stale) is None
    fresh = mirror.device_usage_sharded(mesh, mirror.usage)
    np.testing.assert_allclose(np.asarray(fresh), mirror.usage)
