"""Durability & crash-recovery proofs.

Three layers, matching the crash plane's design:

1. **Storage crash points** (fast): every torn-write shape the
   ``crash`` fault can leave — torn log tails, bit-rotted records,
   torn snapshot tmp files, snapshots persisted but never pruned, torn
   meta tmp files — must recover to a clean committed prefix on the
   next open, byte-exactly, never an exception.
2. **Crash-point soak** (slow): a live submission storm against a
   durable server, a seeded crash at each storage site, a
   CrashHarness hard-drop (no graceful teardown), and a
   reboot-from-data_dir whose state store must byte-compare (store
   fingerprint incl. the alloc changelog) against a replay of the
   recorded applied history prefix — with client retries then
   converging to exactly-once placement, zero duplicate allocs.
3. **Leader-kill soak** (slow): a 3-server durable NetRaft cluster
   under a storm; the leader is hard-killed repeatedly, survivors
   elect, the killed node reboots from its own data_dir and catches up
   (log replay or InstallSnapshot), and the cluster converges to
   exactly-once placement with identical stores.
"""
from __future__ import annotations

import os
import random
import shutil
import threading
import time

import msgpack
import pytest

import nomad_tpu.mock as mock
from nomad_tpu import faultinject
from nomad_tpu.faultinject import FaultCrash, FaultPlan
from nomad_tpu.faultinject.crash import CrashHarness, freeze_storage
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.fsm import NomadFSM
from nomad_tpu.server.raft import (
    LOG_MAGIC,
    FileLogStore,
    InmemRaft,
    MetaStore,
    SnapshotStore,
    StorageDead,
    resolve_snapshot_dir,
)
from nomad_tpu.server.rpc import ConnPool
from nomad_tpu.structs import Resources, Task, TaskGroup

from tests.conftest import wait_until

TERMINAL = ("complete", "failed", "canceled")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _small_job(n_groups: int = 2, count: int = 1):
    job = mock.job()
    job.constraints = []
    job.task_groups = [
        TaskGroup(name=f"tg-{g}", count=count,
                  tasks=[Task(name="web", driver="exec",
                              resources=Resources(cpu=100,
                                                  memory_mb=32))])
        for g in range(n_groups)]
    return job


def _assert_exactly_once(state, jobs) -> None:
    """Every job fully placed, no duplicate live alloc names (the
    double-placement signature)."""
    for job in jobs:
        expected = sum(tg.count for tg in job.task_groups)
        live = [a for a in state.allocs_by_job(job.id)
                if not a.terminal_status()]
        names = [a.name for a in live]
        assert len(names) == len(set(names)), \
            f"duplicate allocs for {job.id}: {sorted(names)}"
        assert len(live) == expected, \
            f"job {job.id}: {len(live)} live allocs, want {expected}"


def _evals_terminal(state, jobs) -> bool:
    for job in jobs:
        evals = state.evals_by_job(job.id)
        if not evals:
            return False
        if any(e.status not in TERMINAL for e in evals):
            return False
    return True


def _replay_twin(history: list, upto: int) -> NomadFSM:
    """A fresh FSM fed the recorded applied history up to index
    ``upto`` — the reference state a recovered store must byte-match
    (boot-replay tolerance for poisoned entries mirrored)."""
    twin = NomadFSM()
    for index, entry in history:
        if index > upto:
            break
        try:
            twin.apply(index, entry)
        except Exception:
            pass
    return twin


def _submit_retry(pool, addr_fn, method, args, acked=None, key=None,
                  deadline=30.0):
    """Client-style submission: retry across crashes/reboots until the
    server acks.  Records the acked raft index."""
    end = time.monotonic() + deadline
    while True:
        try:
            resp = pool.call(addr_fn(), method, args, timeout=2.0)
        except Exception:
            if time.monotonic() >= end:
                raise
            time.sleep(0.05)  # sleep-ok: bounded retry poll across a crash
            continue
        if acked is not None and key is not None:
            acked[key] = resp.get("index", 0)
        return resp


# ---------------------------------------------------------------------------
# 1. storage crash points (fast)
# ---------------------------------------------------------------------------

class TestLogStoreCrashPoints:
    def _records(self, store):
        return [(i, bytes(d)) for i, d in store.replay()]

    def test_log_append_crash_leaves_recoverable_prefix(self, tmp_path):
        path = str(tmp_path / "log.bin")
        store = FileLogStore(path)
        store.append(1, b"one")
        store.append(2, b"two")
        plan = FaultPlan(seed=5).add("log.append", "crash", count=1)
        with faultinject.injected(plan):
            with pytest.raises(FaultCrash):
                store.append(3, b"three")
            # The store is dead: not one more byte may land.
            with pytest.raises(StorageDead):
                store.append(4, b"four")
            assert plan.is_crashed()
        store.close()

        # Reboot: tail-scan recovers a committed prefix — the two acked
        # records always, the torn third only if it landed whole.
        reopened = FileLogStore(path)
        records = self._records(reopened)
        full = [(1, b"one"), (2, b"two"), (3, b"three")]
        assert records == full[:len(records)] and len(records) >= 2
        # And the recovered store accepts appends cleanly again.
        reopened.append(len(records) + 1, b"next")
        reopened.close()

    def test_fsync_crash_full_record_lands_and_replays(self, tmp_path):
        """fraction=1.0: the whole record survived the cut (a failed
        fsync that actually hit disk).  Replay keeps it — the caller
        saw an error and will re-append the index; last-writer-wins
        replay resolves the duplicate."""
        path = str(tmp_path / "log.bin")
        store = FileLogStore(path)
        store.append(1, b"one")
        record = msgpack.packb((2, b"lost-but-landed"), use_bin_type=True)
        framed = store._frame(record)
        with store._lock:
            store._power_loss(framed, store._good_offset,
                              FaultCrash("log.fsync", 1.0, "torn"))
        store.close()
        reopened = FileLogStore(path)
        assert self._records(reopened) == [(1, b"one"),
                                           (2, b"lost-but-landed")]
        reopened.close()

    def test_corrupt_crash_detected_by_crc(self, tmp_path):
        """mode=corrupt: every byte landed but one rotted.  The CRC
        catches it; the tail-scan truncates to the prior record."""
        path = str(tmp_path / "log.bin")
        store = FileLogStore(path)
        store.append(1, b"one")
        record = msgpack.packb((2, b"rotted"), use_bin_type=True)
        framed = store._frame(record)
        pos = store._good_offset
        with store._lock:
            store._power_loss(framed, pos,
                              FaultCrash("log.fsync", 1.0, "corrupt"))
        store.close()
        assert os.path.getsize(path) == pos + len(framed)
        reopened = FileLogStore(path)
        assert self._records(reopened) == [(1, b"one")]
        assert os.path.getsize(path) == pos  # rotted tail truncated
        reopened.close()

    def test_append_error_truncates_back_to_known_good(self, tmp_path):
        """ISSUE satellite (the raft.py:79 hazard): a mid-record write
        failure leaves partial bytes; the store re-stats and truncates
        back to the last known-good offset before allowing appends."""
        path = str(tmp_path / "log.bin")
        store = FileLogStore(path)
        store.append(1, b"one")
        good = store._good_offset

        real_fh = store._fh

        class TornWriter:
            """Writes ``budget`` bytes then fails — a dying disk."""

            def __init__(self, fh, budget):
                self.fh = fh
                self.budget = budget

            def write(self, data):
                if len(data) > self.budget:
                    self.fh.write(data[:self.budget])
                    self.fh.flush()
                    self.budget = 0
                    raise OSError("disk error mid-record")
                self.budget -= len(data)
                return self.fh.write(data)

            def __getattr__(self, name):
                return getattr(self.fh, name)

        store._fh = TornWriter(real_fh, budget=7)
        with pytest.raises(OSError):
            store.append(2, b"torn-away")
        store._fh = real_fh
        # Recovery already ran: the partial bytes are gone.
        assert os.path.getsize(path) == good
        store.append(2, b"two-retry")
        store.close()
        reopened = FileLogStore(path)
        assert self._records(reopened) == [(1, b"one"), (2, b"two-retry")]
        reopened.close()

    def test_legacy_log_upgraded_in_place(self, tmp_path):
        """Pre-CRC data_dirs keep restoring: the old [length][record]
        framing is parsed (tail rule included) and rewritten
        checksummed on open."""
        path = str(tmp_path / "log.bin")
        legacy = b""
        for i, data in ((1, b"a"), (2, b"b")):
            record = msgpack.packb((i, data), use_bin_type=True)
            legacy += len(record).to_bytes(4, "big") + record
        legacy += (99).to_bytes(4, "big") + b"torn"  # torn legacy tail
        with open(path, "wb") as fh:
            fh.write(legacy)
        store = FileLogStore(path)
        assert self._records(store) == [(1, b"a"), (2, b"b")]
        with open(path, "rb") as fh:
            assert fh.read(len(LOG_MAGIC)) == LOG_MAGIC
        store.append(3, b"c")
        assert self._records(store) == [(1, b"a"), (2, b"b"), (3, b"c")]
        store.close()

    def test_rotted_magic_header_rescues_intact_records(self, tmp_path):
        """A bit-rotted MAGIC header must not route an otherwise-intact
        CRC-framed log through the legacy parser — that "upgrade" would
        misread the framing and erase every record.  The CRC records
        are individually recoverable; rescue them and rewrite the
        header."""
        path = str(tmp_path / "log.bin")
        store = FileLogStore(path)
        for i, data in ((1, b"a"), (2, b"bb"), (3, b"ccc")):
            store.append(i, data)
        store.close()
        with open(path, "r+b") as fh:
            fh.seek(2)
            byte = fh.read(1)
            fh.seek(2)
            fh.write(bytes([byte[0] ^ 0xFF]))
        rescued = FileLogStore(path)
        assert self._records(rescued) == [(1, b"a"), (2, b"bb"),
                                          (3, b"ccc")]
        with open(path, "rb") as fh:
            assert fh.read(len(LOG_MAGIC)) == LOG_MAGIC
        rescued.append(4, b"dddd")
        assert self._records(rescued)[-1] == (4, b"dddd")
        rescued.close()

    def test_random_crash_offsets_always_yield_committed_prefix(
            self, tmp_path):
        """Property: ANY truncation or single-byte corruption of a
        recorded log replays as a committed prefix — never an
        exception, never a reordering, never a resurrection."""
        path = str(tmp_path / "log.bin")
        store = FileLogStore(path)
        original = []
        for i in range(1, 21):
            data = f"entry-{i}".encode() * (i % 5 + 1)
            store.append(i, data)
            original.append((i, data))
        store.close()
        size = os.path.getsize(path)

        for trial in range(40):
            rng = random.Random(trial)
            victim = str(tmp_path / f"victim-{trial}.bin")
            shutil.copyfile(path, victim)
            offset = rng.randrange(len(LOG_MAGIC), size)
            if rng.random() < 0.5:
                with open(victim, "r+b") as fh:
                    fh.truncate(offset)
            else:
                with open(victim, "r+b") as fh:
                    fh.seek(offset)
                    byte = fh.read(1)
                    fh.seek(offset)
                    fh.write(bytes([byte[0] ^ 0xFF]))
            recovered = FileLogStore(victim)
            records = [(i, bytes(d)) for i, d in recovered.replay()]
            assert records == original[:len(records)], \
                f"trial {trial} @ {offset}: not a committed prefix"
            recovered.close()


class TestSnapshotStoreCrashPoints:
    def _blob(self, tag: bytes) -> bytes:
        return tag * 64

    def test_checksum_fallback_to_older_snapshot(self, tmp_path):
        store = SnapshotStore(str(tmp_path), retain=2)
        store.save(5, self._blob(b"five"))
        path9 = store.save(9, self._blob(b"nine"))
        with open(path9, "r+b") as fh:
            fh.seek(30)
            byte = fh.read(1)
            fh.seek(30)
            fh.write(bytes([byte[0] ^ 0xFF]))
        index, blob = store.latest()
        assert (index, blob) == (5, self._blob(b"five"))

    def test_save_prunes_only_after_durable_rename(self, tmp_path):
        store = SnapshotStore(str(tmp_path), retain=1)
        store.save(1, self._blob(b"one"))
        store.save(2, self._blob(b"two"))
        names = sorted(os.listdir(str(tmp_path)))
        assert names == ["snapshot-%020d.bin" % 2]

    def test_crash_mid_tmp_write_leaves_old_set_untouched(self, tmp_path):
        store = SnapshotStore(str(tmp_path), retain=2)
        store.save(1, self._blob(b"one"))
        framed = b"\0" * 64
        with store._lock:
            store._power_loss(
                os.path.join(str(tmp_path), "snapshot-%020d.bin" % 2),
                os.path.join(str(tmp_path),
                             "snapshot-%020d.bin.tmp" % 2),
                framed, FaultCrash("snapshot.persist", 0.3, "torn"))
        with pytest.raises(StorageDead):
            store.save(3, self._blob(b"three"))
        # The torn tmp was never renamed; the real set still restores.
        fresh = SnapshotStore(str(tmp_path), retain=2)
        index, blob = fresh.latest()
        assert (index, blob) == (1, self._blob(b"one"))

    def test_crash_between_rename_and_prune_keeps_both(self, tmp_path):
        """The fencing case: the new snapshot IS durable; the old one
        (and the caller's log truncate, which only runs after save
        returns) never got deleted.  Both recovery points remain."""
        store = SnapshotStore(str(tmp_path), retain=1)
        store.save(1, self._blob(b"one"))
        blob2 = self._blob(b"two")
        import zlib
        framed = (b"NTPSNP2\n" + zlib.crc32(blob2).to_bytes(4, "big")
                  + blob2)
        with store._lock:
            store._power_loss(
                os.path.join(str(tmp_path), "snapshot-%020d.bin" % 2),
                os.path.join(str(tmp_path),
                             "snapshot-%020d.bin.tmp" % 2),
                framed, FaultCrash("snapshot.persist", 0.9, "torn"))
        names = sorted(n for n in os.listdir(str(tmp_path))
                       if n.endswith(".bin"))
        assert len(names) == 2
        fresh = SnapshotStore(str(tmp_path), retain=1)
        assert fresh.latest() == (2, blob2)

    def test_random_snapshot_truncations_never_raise(self, tmp_path):
        store = SnapshotStore(str(tmp_path / "snaps"), retain=3)
        store.save(3, self._blob(b"three"))
        path7 = store.save(7, self._blob(b"seven"))
        size = os.path.getsize(path7)
        for trial in range(20):
            rng = random.Random(1000 + trial)
            victim_dir = str(tmp_path / f"v{trial}")
            shutil.copytree(str(tmp_path / "snaps"), victim_dir)
            victim = os.path.join(victim_dir, os.path.basename(path7))
            with open(victim, "r+b") as fh:
                fh.truncate(rng.randrange(0, size))
            got = SnapshotStore(victim_dir, retain=3).latest()
            # Either the older snapshot, or — when the truncation kept
            # the whole payload — nothing was actually lost.
            assert got is not None
            assert got[0] in (3, 7)
            if got[0] == 3:
                assert got[1] == self._blob(b"three")


class TestMetaStoreCrashPoints:
    def test_torn_tmp_keeps_previous_meta(self, tmp_path):
        path = str(tmp_path / "meta.json")
        store = MetaStore(path)
        store.save({"term": 3, "voted_for": ["127.0.0.1", 4000]})
        plan = FaultPlan(seed=9).add("meta.persist", "crash", count=1)
        with faultinject.injected(plan):
            with pytest.raises(FaultCrash):
                store.save({"term": 4, "voted_for": None})
            with pytest.raises(StorageDead):
                store.save({"term": 5, "voted_for": None})
        fresh = MetaStore(path)
        assert fresh.load() == {"term": 3,
                                "voted_for": ["127.0.0.1", 4000]}

    def test_crash_latch_freezes_every_storage_site(self, tmp_path):
        """One crash = the whole process is dead: after log.append
        crashes, the snapshot and meta stores refuse writes too."""
        plan = FaultPlan(seed=1).add("log.append", "crash", count=1)
        log = FileLogStore(str(tmp_path / "log.bin"))
        snaps = SnapshotStore(str(tmp_path / "snaps"))
        meta = MetaStore(str(tmp_path / "meta.json"))
        with faultinject.injected(plan):
            with pytest.raises(FaultCrash):
                log.append(1, b"x")
            with pytest.raises(StorageDead):
                snaps.save(1, b"blob")
            with pytest.raises(StorageDead):
                meta.save({"term": 1})
            plan.reset_crashed()
            # The latch cleared (reboot): OTHER stores work again...
            snaps.save(1, b"blob")
            meta.save({"term": 1})
            # ...but the store that took the hit stays dead.
            with pytest.raises(StorageDead):
                log.append(2, b"y")

    def test_scoped_crash_latch_spares_other_data_dirs(self, tmp_path):
        """A crash rule aimed at ONE server's data_dir (``method``
        path-prefix predicate) freezes only that server's stores: its
        in-process peers keep committing — the multi-server power-cut
        model a cluster soak needs."""
        s1, s2 = str(tmp_path / "s1"), str(tmp_path / "s2")
        plan = FaultPlan(seed=2).add("log.append", "crash", count=1,
                                     method=f"{s1}*")
        log1 = FileLogStore(f"{s1}/raft/log.bin")
        snaps1 = SnapshotStore(f"{s1}/raft/snapshots")
        log2 = FileLogStore(f"{s2}/raft/log.bin")
        snaps2 = SnapshotStore(f"{s2}/raft/snapshots")
        meta2 = MetaStore(f"{s2}/raft/meta.json")
        with faultinject.injected(plan):
            log2.append(1, b"unmatched path: no fire")
            with pytest.raises(FaultCrash):
                log1.append(1, b"x")
            # s1 is dead end to end...
            with pytest.raises(StorageDead):
                snaps1.save(1, b"blob")
            # ...while its peers write on, every store kind.
            log2.append(2, b"after the cut")
            snaps2.save(1, b"blob")
            meta2.save({"term": 1})
        log1.close()
        log2.close()


class TestBootRefusesSilentGap:
    """A checksum-failed newest snapshot falls back to an older one;
    if the log was already compacted past the fallback, the durable
    history has a HOLE.  Booting anyway would silently drop the
    committed entries in the gap — both backends must refuse loudly
    instead (CommittedDataLoss), never skip-and-continue."""

    def _lay_down_gap(self, tmp_path, record):
        """data_dir with snapshots at 2 (good) and 5 (rotted CRC) and
        a log compacted to entries 6..7: fallback to 2 leaves entries
        3..5 unrecoverable."""
        snap_dir = resolve_snapshot_dir(str(tmp_path))
        snaps = SnapshotStore(snap_dir)
        snaps.save(2, b"old-state")
        path5 = snaps.save(5, b"new-state")
        with open(path5, "r+b") as fh:
            fh.seek(20)
            byte = fh.read(1)
            fh.seek(20)
            fh.write(bytes([byte[0] ^ 0xFF]))
        log = FileLogStore(str(tmp_path / "raft" / "log.bin"))
        for i in (6, 7):
            log.append(i, record(i))
        log.close()

    def test_inmem_boot_refuses_gap(self, tmp_path):
        from nomad_tpu.server.raft import CommittedDataLoss

        from tests.test_raft_net import _RecordingFSM

        self._lay_down_gap(tmp_path, lambda i: b"entry-%d" % i)
        log = FileLogStore(str(tmp_path / "raft" / "log.bin"))
        snaps = SnapshotStore(resolve_snapshot_dir(str(tmp_path)))
        with pytest.raises(CommittedDataLoss):
            InmemRaft(_RecordingFSM(), log, snaps)
        log.close()

    def test_net_raft_boot_refuses_gap(self, tmp_path):
        from nomad_tpu.server.raft import CommittedDataLoss
        from nomad_tpu.server.raft_net import NetRaft

        from tests.test_raft_net import _RecordingFSM, _StubRPC

        self._lay_down_gap(tmp_path,
                           lambda i: {"t": 1, "d": b"entry-%d" % i})
        with pytest.raises(CommittedDataLoss):
            NetRaft(_RecordingFSM(), _StubRPC(), None,
                    election_timeout=(30.0, 60.0),
                    data_dir=str(tmp_path))

    def test_install_snapshot_persist_failure_refuses_install(
            self, tmp_path):
        """Persist-before-memory on the InstallSnapshot path: a
        follower whose snapshot store cannot make the installed blob
        durable must refuse the install with NO state moved — fsm,
        log, and commit indexes untouched (the leader retries)."""
        from nomad_tpu.server.raft_net import NetRaft

        from tests.test_raft_net import _RecordingFSM, _StubRPC

        class RecordingRestoreFSM(_RecordingFSM):
            def __init__(self):
                super().__init__()
                self.restored = []

            def restore(self, blob):
                self.restored.append(bytes(blob))

        fsm = RecordingRestoreFSM()
        raft = NetRaft(fsm, _StubRPC(), None,
                       election_timeout=(30.0, 60.0),
                       data_dir=str(tmp_path))
        try:
            raft._snap_store.die()
            reply = raft._handle_install_snapshot({
                "term": 1, "leader": ["127.0.0.1", 4000],
                "last_included_index": 5, "last_included_term": 1,
                "data": b"snap-blob"})
            assert reply == {"term": 1}
            assert fsm.restored == []
            assert raft._last_applied == 0
            assert raft._commit_index == 0
            assert raft._log_base_index == 0
            assert raft._snap_blob is None
            # No snapshot file landed either: a reboot replays the
            # old history, matching the refused in-memory state.
            assert raft._snap_store.latest() is None
        finally:
            raft.shutdown()


# ---------------------------------------------------------------------------
# 2. crash-point soak: committed prefix + exactly-once (slow)
# ---------------------------------------------------------------------------

def _soak_config(data_dir: str, snapshot_threshold: int) -> ServerConfig:
    return ServerConfig(
        data_dir=data_dir, enable_rpc=True, num_schedulers=2,
        raft_snapshot_threshold=snapshot_threshold)


@pytest.mark.slow
@pytest.mark.parametrize("site,seed", [
    ("log.append", 11),
    ("log.append", 12),
    ("log.fsync", 21),
    ("snapshot.persist", 31),
    ("snapshot.persist", 32),
])
def test_crash_point_soak_recovers_committed_prefix(tmp_path, site, seed):
    """A live submission storm, a seeded crash at ``site``, a hard
    kill, a reboot from the same data_dir.  The rebooted store must be
    a byte-exact committed prefix of the recorded applied history, no
    acked write may be lost, and retries must converge to exactly-once
    placement."""
    data_dir = str(tmp_path / "server")
    threshold = 8 if site == "snapshot.persist" else 100_000
    server = Server(_soak_config(data_dir, threshold))
    server.establish_leadership()

    history: list = []
    server.fsm.on_entry = lambda i, e: history.append((i, e))

    current = {"server": server}
    harness = CrashHarness()
    pool = ConnPool()
    jobs = [_small_job() for _ in range(12)]
    acked: dict = {}
    stop = threading.Event()

    def addr_fn():
        return current["server"].rpc_address()

    def lane(lane_jobs):
        for job in lane_jobs:
            if stop.is_set():
                return
            _submit_retry(pool, addr_fn, "Job.Register",
                          {"job": job.to_dict()}, acked=acked,
                          key=job.id, deadline=60.0)

    plan = FaultPlan(seed=seed).add(site, "crash", count=1, after=4)
    try:
        # Capacity lands before the faults arm: the crash must hit
        # mid-storm, with submissions in flight.
        for i in range(6):
            _submit_retry(pool, addr_fn, "Node.Register",
                          {"node": mock.node(i).to_dict()})
        with faultinject.injected(plan):
            lanes = [threading.Thread(target=lane, args=(jobs[i::2],),
                                      daemon=True) for i in range(2)]
            for t in lanes:
                t.start()

            wait_until(lambda: plan.fire_count(site) > 0, timeout=30,
                       msg=f"crash fired at {site}")
            harness.kill(server)
            pre_crash_history = list(history)
            acked_max = max(acked.values(), default=0)

            # -- recovery proof on a cold, workerless boot ------------
            snap_store = SnapshotStore(resolve_snapshot_dir(data_dir))
            latest = snap_store.latest()
            since = latest[0] if latest else 0
            probe_fsm = NomadFSM()
            probe_raft = InmemRaft(
                probe_fsm, FileLogStore(f"{data_dir}/raft/log.bin"),
                snap_store)
            k = probe_raft.applied_index()
            probe_raft.log_store.close()
            assert k >= acked_max, \
                f"committed write lost: recovered to {k}, " \
                f"acked up to {acked_max}"
            twin = _replay_twin(pre_crash_history, k)
            assert probe_fsm.state.fingerprint(changelog_since=since) == \
                twin.state.fingerprint(changelog_since=since), \
                "recovered store is not a byte-exact committed prefix"

            # -- reboot for real, converge, exactly-once --------------
            server2 = harness.reboot(_soak_config(data_dir, threshold))
            current["server"] = server2
            for t in lanes:
                t.join(90.0)
            assert all(not t.is_alive() for t in lanes)
            assert set(acked) == {j.id for j in jobs}
            wait_until(lambda: _evals_terminal(server2.fsm.state, jobs),
                       timeout=60, msg="all evals terminal after reboot")
            _assert_exactly_once(server2.fsm.state, jobs)
    finally:
        stop.set()
        pool.shutdown()
        harness.reap(also=[current["server"]])


@pytest.mark.slow
def test_meta_persist_crash_recovers_and_elects(tmp_path):
    """The meta.persist walk: a single-node NetRaft server crashes
    persisting its first election's term bump.  The torn tmp never
    replaced meta.json; the reboot elects cleanly and a storm then
    places exactly once."""
    data_dir = str(tmp_path / "server")
    cfg_kw = dict(
        data_dir=data_dir, raft_mode="net", num_schedulers=2,
        raft_election_timeout=(0.05, 0.10),
        raft_heartbeat_interval=0.02)
    harness = CrashHarness()
    pool = ConnPool()
    plan = FaultPlan(seed=77).add("meta.persist", "crash", count=1)
    server2 = None
    try:
        with faultinject.injected(plan):
            server = Server(ServerConfig(**cfg_kw))
            # The first election attempt hits the crash; the node can
            # never become leader (it cannot persist its term).
            wait_until(lambda: plan.fire_count("meta.persist") > 0,
                       timeout=10, msg="crash fired at meta.persist")
            assert not server.raft.is_leader()
            harness.kill(server)

            server2 = harness.reboot(ServerConfig(**cfg_kw))
            wait_until(lambda: server2.raft.is_leader() and
                       server2.is_leader(), msg="post-reboot election")
            # Meta persistence works again and is valid JSON.
            meta = MetaStore(f"{data_dir}/raft/meta.json").load()
            assert meta is not None and meta["term"] >= 1

            jobs = [_small_job() for _ in range(6)]
            for i in range(4):
                _submit_retry(pool, server2.rpc_address, "Node.Register",
                              {"node": mock.node(i).to_dict()})
            for job in jobs:
                _submit_retry(pool, server2.rpc_address, "Job.Register",
                              {"job": job.to_dict()})
            wait_until(lambda: _evals_terminal(server2.fsm.state, jobs),
                       timeout=30, msg="storm terminal after recovery")
            _assert_exactly_once(server2.fsm.state, jobs)
    finally:
        pool.shutdown()
        harness.reap(also=[server2] if server2 is not None else None)


# ---------------------------------------------------------------------------
# 3. leader-kill soak: rolling failover on a durable cluster (slow)
# ---------------------------------------------------------------------------

def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_leader_kill_soak_converges_exactly_once(tmp_path):
    """≥3 rolling leader kills (hard drops, storage frozen mid-flight)
    on a durable 3-server cluster under a live storm: survivors elect,
    the killed node reboots from its own data_dir and catches up (log
    replay or InstallSnapshot — threshold kept low so compaction
    happens mid-soak), and the cluster converges to exactly-once
    placement with identical stores."""
    ports = [_free_port() for _ in range(3)]
    peers = [("127.0.0.1", p) for p in ports]

    def cfg(i: int) -> ServerConfig:
        return ServerConfig(
            data_dir=str(tmp_path / f"s{i}"), raft_mode="net",
            rpc_port=ports[i], raft_peers=list(peers),
            num_schedulers=1,
            raft_election_timeout=(0.10, 0.20),
            raft_heartbeat_interval=0.03,
            raft_snapshot_threshold=48)

    servers = {i: Server(cfg(i)) for i in range(3)}
    alive = dict(servers)
    harness = CrashHarness()
    pool = ConnPool()
    stop = threading.Event()
    jobs = [_small_job() for _ in range(24)]
    acked: dict = {}
    rr = [0]

    def addr_fn():
        targets = list(alive.values())
        rr[0] += 1
        return targets[rr[0] % len(targets)].rpc_address()

    def leader_of(pool_servers, timeout=15.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [s for s in pool_servers.values()
                       if s.raft.is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.02)  # sleep-ok: poll interval of the bounded wait
        raise AssertionError("no single leader")

    def lane(lane_jobs):
        for job in lane_jobs:
            if stop.is_set():
                return
            _submit_retry(pool, addr_fn, "Job.Register",
                          {"job": job.to_dict()}, acked=acked,
                          key=job.id, deadline=120.0)

    try:
        leader_of(alive)
        for i in range(8):
            _submit_retry(pool, addr_fn, "Node.Register",
                          {"node": mock.node(i).to_dict()})
        lanes = [threading.Thread(target=lane, args=(jobs[i::2],),
                                  daemon=True) for i in range(2)]
        for t in lanes:
            t.start()

        for kill in range(3):
            leader = leader_of(alive)
            victim = next(i for i, s in alive.items() if s is leader)
            harness.kill(leader)
            del alive[victim]

            # Survivors elect among themselves.
            new_leader = leader_of(alive)
            assert new_leader is not leader

            # The killed node reboots from its own disk and catches up
            # via log replay or InstallSnapshot.
            reborn = harness.reboot(cfg(victim))
            alive[victim] = reborn
            canary = mock.node(100 + kill)
            _submit_retry(pool, addr_fn, "Node.Register",
                          {"node": canary.to_dict()})
            wait_until(
                lambda: reborn.fsm.state.node_by_id(canary.id)
                is not None,
                timeout=30, msg=f"reborn s{victim} caught up "
                f"(kill {kill})")

        for t in lanes:
            t.join(150.0)
        assert all(not t.is_alive() for t in lanes)
        assert set(acked) == {j.id for j in jobs}, "lost submissions"

        leader = leader_of(alive)
        wait_until(lambda: _evals_terminal(leader.fsm.state, jobs),
                   timeout=90, msg="storm terminal after 3 kills")
        _assert_exactly_once(leader.fsm.state, jobs)

        # Replicas converge to the same tables (changelogs differ
        # legitimately across InstallSnapshot boundaries).
        def converged():
            prints = {s.fsm.state.fingerprint(changelog_since=10**9)
                      for s in alive.values()}
            return len(prints) == 1
        wait_until(converged, timeout=30, msg="replica convergence")
    finally:
        stop.set()
        pool.shutdown()
        harness.reap(also=list(alive.values()))


# ---------------------------------------------------------------------------
# 4. client crash-reattach (ISSUE satellite)
# ---------------------------------------------------------------------------

def test_client_reboot_with_corrupt_alloc_state_reattaches(tmp_path):
    """A client hard-rebooted mid-task with a TORN alloc state file
    must not silently discard the allocation: the alloc is re-fetched
    from the server and the still-running task re-attached via its
    (separately persisted) handle — same pid, never a double."""
    from nomad_tpu.client import Client
    from nomad_tpu.client.config import ClientConfig

    srv = Server(ServerConfig(num_schedulers=2, enable_rpc=True))
    srv.establish_leadership()
    cfg = ClientConfig(
        state_dir=str(tmp_path / "client-state"),
        alloc_dir=str(tmp_path / "allocs"),
        servers=[srv.rpc_address()],
        options={"driver.raw_exec.enable": "1",
                 "fingerprint.skip_accel": "1"},
    )
    client = Client(cfg)
    client2 = None
    try:
        client.start()
        wait_until(lambda: srv.fsm.state.node_by_id(client.node.id)
                   is not None, msg="node registration")
        job = mock.job()
        job.constraints = []
        job.task_groups[0].count = 1
        job.task_groups[0].tasks = [Task(
            name="sleeper", driver="raw_exec",
            config={"command": "/bin/sleep", "args": "300"},
            resources=Resources(cpu=100, memory_mb=32))]
        _, eval_id = srv.job_register(job)
        srv.wait_for_evals([eval_id], timeout=15)

        def task_running():
            for runner in client.alloc_runners.values():
                tr = runner.task_runners.get("sleeper")
                if tr is not None and tr.state == "running":
                    return True
            return False
        wait_until(task_running, timeout=20, msg="task running")
        alloc_id = next(iter(client.alloc_runners))
        pid = client.alloc_runners[alloc_id] \
            .task_runners["sleeper"].handle.pid

        # Hard reboot: stop the agent's loops (no graceful destroy —
        # the task process survives, as it would a real agent crash)
        # and tear the alloc state file mid-record.
        client.shutdown()
        state_path = os.path.join(str(tmp_path / "client-state"),
                                  "allocs", alloc_id, "state.json")
        size = os.path.getsize(state_path)
        with open(state_path, "r+b") as fh:
            fh.truncate(size // 2)

        client2 = Client(cfg)
        # The torn state did NOT restore a runner — and did NOT get
        # silently discarded either: it is queued for server re-fetch.
        assert alloc_id not in client2.alloc_runners
        assert alloc_id in client2._recover_alloc_ids
        assert os.path.isdir(os.path.dirname(state_path))
        client2.start()

        def reattached():
            runner = client2.alloc_runners.get(alloc_id)
            if runner is None:
                return False
            tr = runner.task_runners.get("sleeper")
            return tr is not None and tr.state == "running" and \
                tr.handle is not None
        wait_until(reattached, timeout=20, msg="re-attach after reboot")
        tr2 = client2.alloc_runners[alloc_id].task_runners["sleeper"]
        # Same pid: the live process was re-attached, not doubled.
        assert tr2.handle.pid == pid
        assert alloc_id not in client2._recover_alloc_ids
    finally:
        if client2 is not None:
            client2.shutdown()
            client2.destroy_all()
        client.destroy_all()
        srv.shutdown()


def test_client_reboot_with_corrupt_state_and_stopped_alloc_reclaims(
        tmp_path):
    """The other half of the reattach satellite: a torn-state alloc
    the SERVER is done with (job stopped while the client was down)
    must not be forgotten — the still-running orphan is re-attached by
    its persisted task handle, killed, and both directories reclaimed,
    with the recover queue drained."""
    from nomad_tpu.client import Client
    from nomad_tpu.client.config import ClientConfig
    from nomad_tpu.client.driver.base import _pid_alive

    srv = Server(ServerConfig(num_schedulers=2, enable_rpc=True))
    srv.establish_leadership()
    cfg = ClientConfig(
        state_dir=str(tmp_path / "client-state"),
        alloc_dir=str(tmp_path / "allocs"),
        servers=[srv.rpc_address()],
        options={"driver.raw_exec.enable": "1",
                 "fingerprint.skip_accel": "1"},
    )
    client = Client(cfg)
    client2 = None
    try:
        client.start()
        wait_until(lambda: srv.fsm.state.node_by_id(client.node.id)
                   is not None, msg="node registration")
        job = mock.job()
        job.constraints = []
        job.task_groups[0].count = 1
        job.task_groups[0].tasks = [Task(
            name="sleeper", driver="raw_exec",
            config={"command": "/bin/sleep", "args": "300"},
            resources=Resources(cpu=100, memory_mb=32))]
        _, eval_id = srv.job_register(job)
        srv.wait_for_evals([eval_id], timeout=15)

        def task_running():
            for runner in client.alloc_runners.values():
                tr = runner.task_runners.get("sleeper")
                if tr is not None and tr.state == "running":
                    return True
            return False
        wait_until(task_running, timeout=20, msg="task running")
        alloc_id = next(iter(client.alloc_runners))
        pid = client.alloc_runners[alloc_id] \
            .task_runners["sleeper"].handle.pid

        # Agent crash with a torn state file...
        client.shutdown()
        state_dir = os.path.join(str(tmp_path / "client-state"),
                                 "allocs", alloc_id)
        state_path = os.path.join(state_dir, "state.json")
        with open(state_path, "r+b") as fh:
            fh.truncate(os.path.getsize(state_path) // 2)
        # ...and the job stopped while the agent was down.
        _, stop_eval = srv.job_deregister(job.id)
        srv.wait_for_evals([stop_eval], timeout=15)

        client2 = Client(cfg)
        assert alloc_id in client2._recover_alloc_ids
        client2.start()

        alloc_root = client2._alloc_root(alloc_id)

        def reclaimed():
            return (alloc_id not in client2.alloc_runners
                    and not os.path.isdir(state_dir)
                    and not os.path.isdir(alloc_root)
                    and not _pid_alive(pid))
        # Load-tolerant bar (documented pre-existing flake, PR 12/13
        # notes): the reclaim rides a background thread + an RPC watch
        # cycle, both starved under full-suite host load — the proof is
        # THAT it converges, not how fast.
        wait_until(reclaimed, timeout=60,
                   msg="orphan killed and directories reclaimed")
        assert alloc_id not in client2._recover_alloc_ids
    finally:
        if client2 is not None:
            client2.shutdown()
            client2.destroy_all()
        client.destroy_all()
        srv.shutdown()
