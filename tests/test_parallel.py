"""Multi-chip sharding tests: node axis over an 8-device CPU mesh.

conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8, so these
exercise the same pjit/collective paths the driver dry-runs and the real TPU
mesh executes.
"""
from __future__ import annotations

import jax
import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.models.fleet import build_fleet, build_usage
from nomad_tpu.ops.binpack import place_sequence, place_sequence_batch
from nomad_tpu.parallel.mesh import (fleet_mesh, mesh_override,
                                     place_sequence_sharded)
from nomad_tpu.structs import Resources

# The whole module is the sharded-parity suite: the tier-1 subprocess
# rig (tests/test_multichip_rig.py) re-drives it `-m multichip` under
# hermetically forced XLA flags so mesh regressions fail before a TPU
# ever sees them.
pytestmark = pytest.mark.multichip


def _problem(n_nodes=64, n_place=16):
    nodes = [mock.node(i) for i in range(n_nodes)]
    fleet = build_fleet(nodes)
    view = build_usage(fleet, [])
    asks = np.zeros((1, 6), dtype=np.float32)
    asks[0] = Resources(cpu=500, memory_mb=256).as_vector()
    feasible = np.zeros((1, fleet.n_pad), dtype=bool)
    feasible[0, :fleet.n_real] = True
    group_idx = np.zeros(n_place, dtype=np.int32)
    valid = np.ones(n_place, dtype=bool)
    distinct = np.zeros(1, dtype=bool)
    return fleet, view, feasible, asks, distinct, group_idx, valid


def test_mesh_has_8_devices():
    assert len(jax.devices("cpu")) == 8


def test_sharded_matches_single_device():
    fleet, view, feasible, asks, distinct, group_idx, valid = _problem()

    ref_chosen, ref_scores, ref_usage = place_sequence(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, asks, distinct, group_idx, valid, 10.0)

    mesh = fleet_mesh(jax.devices("cpu"))
    chosen, scores, usage = place_sequence_sharded(
        mesh, fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, asks, distinct, group_idx, valid, 10.0)

    assert np.asarray(chosen).tolist() == np.asarray(ref_chosen).tolist()
    np.testing.assert_allclose(np.asarray(scores), np.asarray(ref_scores),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(usage), np.asarray(ref_usage))


def test_batched_evals_are_independent():
    """vmap axis = optimistic concurrency: each eval plans on its own copy."""
    fleet, view, feasible, asks, distinct, group_idx, valid = _problem(
        n_nodes=8, n_place=8)

    batch = 4
    # usage is NOT batched (shared snapshot, broadcast on device);
    # job_counts/penalty are per-eval.
    chosen, scores, usage = place_sequence_batch(
        fleet.capacity, fleet.reserved, view.usage,
        np.broadcast_to(view.job_counts,
                        (batch,) + view.job_counts.shape).copy(),
        np.broadcast_to(feasible, (batch,) + feasible.shape).copy(),
        np.broadcast_to(asks, (batch,) + asks.shape).copy(),
        np.broadcast_to(distinct, (batch,) + distinct.shape).copy(),
        np.broadcast_to(group_idx, (batch,) + group_idx.shape).copy(),
        np.broadcast_to(valid, (batch,) + valid.shape).copy(),
        np.full(batch, 10.0, dtype=np.float32))
    chosen = np.asarray(chosen)
    # Every eval sees the same snapshot -> identical independent decisions.
    for b in range(1, batch):
        assert chosen[b].tolist() == chosen[0].tolist()
    # Each eval spread its 8 placements over all 8 nodes.
    assert sorted(chosen[0].tolist()) == list(range(8))


def _rounds_problem(n_nodes=64, count=24):
    fleet, view, feasible, asks, distinct, _gi, _v = _problem(n_nodes)
    counts = np.asarray([count], dtype=np.int32)
    return fleet, view, feasible, asks, distinct, counts


def test_place_rounds_sharded_parity():
    """place_rounds on the 8-device mesh == single-device result."""
    from nomad_tpu.ops.binpack import place_rounds
    from nomad_tpu.parallel.mesh import place_rounds_sharded

    fleet, view, feasible, asks, distinct, counts = _rounds_problem()
    kw = dict(k_cap=32, rounds=1)
    ref_c, ref_s, ref_u = place_rounds(
        fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, asks, distinct, counts, 10.0, **kw)

    mesh = fleet_mesh(jax.devices("cpu"))
    c, s, u = place_rounds_sharded(
        mesh, fleet.capacity, fleet.reserved, view.usage, view.job_counts,
        feasible, asks, distinct, counts, 10.0, **kw)

    # Scores and usage must match exactly; chosen node ids may permute
    # within equal-score ties (top_k tie order is shard-dependent), so
    # compare as multisets plus exact usage.
    np.testing.assert_allclose(np.asarray(u), np.asarray(ref_u))
    assert sorted(np.asarray(c).ravel().tolist()) == \
        sorted(np.asarray(ref_c).ravel().tolist())
    np.testing.assert_allclose(np.sort(np.asarray(s).ravel()),
                               np.sort(np.asarray(ref_s).ravel()),
                               rtol=1e-6)


def test_place_rounds_batch_sharded_parity():
    from nomad_tpu.ops.binpack import place_rounds_batch
    from nomad_tpu.parallel.mesh import place_rounds_batch_sharded

    fleet, view, feasible, asks, distinct, counts = _rounds_problem()
    B = 3
    jc = np.broadcast_to(view.job_counts,
                         (B,) + view.job_counts.shape).copy()
    feas = np.broadcast_to(feasible, (B,) + feasible.shape).copy()
    asks_b = np.broadcast_to(asks, (B,) + asks.shape).copy()
    dist_b = np.broadcast_to(distinct, (B,) + distinct.shape).copy()
    counts_b = np.broadcast_to(counts, (B,) + counts.shape).copy()
    pen = np.full(B, 10.0, dtype=np.float32)
    kw = dict(k_cap=32, rounds=1)

    ref_c, ref_s, _ = place_rounds_batch(
        fleet.capacity, fleet.reserved, view.usage, jc, feas, asks_b,
        dist_b, counts_b, pen, **kw)
    mesh = fleet_mesh(jax.devices("cpu"))
    c, s, _ = place_rounds_batch_sharded(
        mesh, fleet.capacity, fleet.reserved, view.usage, jc, feas,
        asks_b, dist_b, counts_b, pen, **kw)

    for b in range(B):
        assert sorted(np.asarray(c)[b].ravel().tolist()) == \
            sorted(np.asarray(ref_c)[b].ravel().tolist())
    np.testing.assert_allclose(np.sort(np.asarray(s).ravel()),
                               np.sort(np.asarray(ref_s).ravel()),
                               rtol=1e-6)


def test_place_sequence_batch_sharded_parity():
    from nomad_tpu.parallel.mesh import place_sequence_batch_sharded

    fleet, view, feasible, asks, distinct, group_idx, valid = _problem()
    B = 3
    jc = np.broadcast_to(view.job_counts,
                         (B,) + view.job_counts.shape).copy()
    feas = np.broadcast_to(feasible, (B,) + feasible.shape).copy()
    asks_b = np.broadcast_to(asks, (B,) + asks.shape).copy()
    dist_b = np.broadcast_to(distinct, (B,) + distinct.shape).copy()
    gi = np.broadcast_to(group_idx, (B,) + group_idx.shape).copy()
    va = np.broadcast_to(valid, (B,) + valid.shape).copy()
    pen = np.full(B, 10.0, dtype=np.float32)

    ref_c, ref_s, _ = place_sequence_batch(
        fleet.capacity, fleet.reserved, view.usage, jc, feas, asks_b,
        dist_b, gi, va, pen)
    mesh = fleet_mesh(jax.devices("cpu"))
    c, s, _ = place_sequence_batch_sharded(
        mesh, fleet.capacity, fleet.reserved, view.usage, jc, feas,
        asks_b, dist_b, gi, va, pen)

    assert np.asarray(c).tolist() == np.asarray(ref_c).tolist()
    np.testing.assert_allclose(np.asarray(s), np.asarray(ref_s), rtol=1e-6)


def test_storm_mesh_2d_lane_parallel_parity():
    """2-D (lanes, fleet) mesh: lanes shard data-parallel across mesh
    rows, fleet across columns — results identical to unsharded and to
    the 1-D fleet mesh (storms scale across devices, not just memory)."""
    from nomad_tpu.ops.binpack import place_rounds_batch
    from nomad_tpu.parallel.mesh import (place_rounds_batch_sharded,
                                         place_sequence_batch_sharded,
                                         storm_mesh)
    from nomad_tpu.ops.binpack import place_sequence_batch as _psb

    fleet, view, feasible, asks, distinct, counts = _rounds_problem()
    B = 4  # divisible by the 2-way lane axis
    jc = np.broadcast_to(view.job_counts,
                         (B,) + view.job_counts.shape).copy()
    feas = np.broadcast_to(feasible, (B,) + feasible.shape).copy()
    asks_b = np.broadcast_to(asks, (B,) + asks.shape).copy()
    dist_b = np.broadcast_to(distinct, (B,) + distinct.shape).copy()
    counts_b = np.broadcast_to(counts, (B,) + counts.shape).copy()
    pen = np.full(B, 10.0, dtype=np.float32)
    kw = dict(k_cap=32, rounds=1)

    ref_c, ref_s, _ = place_rounds_batch(
        fleet.capacity, fleet.reserved, view.usage, jc, feas, asks_b,
        dist_b, counts_b, pen, **kw)
    mesh2d = storm_mesh(2, jax.devices("cpu"))  # 2 lanes x 4 fleet
    c, s, _ = place_rounds_batch_sharded(
        mesh2d, fleet.capacity, fleet.reserved, view.usage, jc, feas,
        asks_b, dist_b, counts_b, pen, **kw)
    for b in range(B):
        assert sorted(np.asarray(c)[b].ravel().tolist()) == \
            sorted(np.asarray(ref_c)[b].ravel().tolist())
    np.testing.assert_allclose(np.sort(np.asarray(s).ravel()),
                               np.sort(np.asarray(ref_s).ravel()),
                               rtol=1e-6)

    # The scan variant on the same 2-D mesh.
    fleet, view, feasible, asks, distinct, group_idx, valid = _problem()
    jc = np.broadcast_to(view.job_counts,
                         (B,) + view.job_counts.shape).copy()
    feas = np.broadcast_to(feasible, (B,) + feasible.shape).copy()
    asks_b = np.broadcast_to(asks, (B,) + asks.shape).copy()
    dist_b = np.broadcast_to(distinct, (B,) + distinct.shape).copy()
    gi = np.broadcast_to(group_idx, (B,) + group_idx.shape).copy()
    va = np.broadcast_to(valid, (B,) + valid.shape).copy()
    pen = np.full(B, 10.0, dtype=np.float32)
    ref_c, ref_s, _ = _psb(
        fleet.capacity, fleet.reserved, view.usage, jc, feas, asks_b,
        dist_b, gi, va, pen)
    c, s, _ = place_sequence_batch_sharded(
        mesh2d, fleet.capacity, fleet.reserved, view.usage, jc, feas,
        asks_b, dist_b, gi, va, pen)
    assert np.asarray(c).tolist() == np.asarray(ref_c).tolist()
    np.testing.assert_allclose(np.asarray(s), np.asarray(ref_s), rtol=1e-6)


def test_storm_mesh_validates_lane_ways():
    from nomad_tpu.parallel.mesh import storm_mesh

    with pytest.raises(ValueError, match="must divide"):
        storm_mesh(3, jax.devices("cpu"))  # 3 does not divide 8


# -- end-to-end sharded parity (ISSUE 12 acceptance) -----------------------
# Not kernel-level: the full scheduler stream — reconcile, prep, device
# dispatch, finish, plan COMMIT — run sharded (mesh auto-resolved on the
# 8-device host) and unsharded (mesh_override("off")), byte-identical
# placements asserted per eval, including after the UsageMirror's
# incremental device scatters between commits.


def _stream_rig(n_nodes: int, n_jobs: int, count: int):
    from nomad_tpu.scheduler import Harness

    h = Harness()
    for i in range(n_nodes):
        h.state.upsert_node(h.next_index(), mock.node(i))
    jobs = []
    for _ in range(n_jobs):
        job = mock.job()
        job.task_groups[0].count = count
        h.state.upsert_job(h.next_index(), job)
        jobs.append(job)
    return h, jobs


def _run_stream(policy, n_nodes=24, n_jobs=6, count=8):
    """One committed eval stream under a mesh policy.  Returns
    (per-eval placement rows as node INDEXES, runner, mirror) — node
    ids are fresh uuids per rig, so parity compares positional node
    identity, which is exactly what the kernels choose."""
    from nomad_tpu.scheduler.executor import executor_override
    from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner
    from nomad_tpu.models.fleet import fleet_cache, mirror_for

    h, jobs = _stream_rig(n_nodes, n_jobs, count)
    index_of = {n.id: i for i, n in enumerate(h.state.nodes())}
    runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=3,
                                 state_refresh=h.snapshot)
    with mesh_override(policy), executor_override("device"):
        # Process one eval at a time with a refreshed snapshot so every
        # commit lands before the next eval plans — each eval's view
        # then rides the mirror's scatter-updated device copy.
        for job in jobs:
            runner.state = h.snapshot()
            runner.process([make_eval_for(job)])
    placements = []
    for plan in h.plans:
        rows = []
        for node_id, allocs in sorted(plan.node_allocation.items(),
                                      key=lambda kv: index_of[kv[0]]):
            for a in allocs:
                rows.append((index_of[node_id], a.task_group))
        placements.append(sorted(rows))
    statics = fleet_cache.statics_for(h.state)
    return placements, runner, mirror_for(statics), h


def make_eval_for(job):
    from nomad_tpu.structs import (EVAL_TRIGGER_JOB_REGISTER, Evaluation,
                                   generate_uuid)

    return Evaluation(id=generate_uuid(), priority=job.priority,
                      type=job.type,
                      triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                      job_id=job.id)


def test_sharded_stream_byte_identical_placements():
    """Sharded and unsharded committed streams place byte-identically
    — every eval, every instance, every chosen node — including evals
    whose usage view came off the mirror's scatter-maintained device
    copy (commits land between evals)."""
    sharded, runner_s, mirror_s, h_s = _run_stream("auto")
    unsharded, runner_u, _mirror_u, _h_u = _run_stream("off")

    assert runner_s.device_dispatches > 0
    assert runner_s.sharded_dispatches == runner_s.device_dispatches, \
        "auto mesh policy must shard every device dispatch on 8 devices"
    assert runner_u.sharded_dispatches == 0
    assert sharded == unsharded
    # Real work happened: every job placed its full count.
    assert sum(len(p) for p in sharded) == 6 * 8

    # The mirror's sharded twin (the PRIMARY usage of the sharded
    # dispatches) tracked every commit: it must equal the host mirror
    # byte for byte after the stream.
    from nomad_tpu.parallel.mesh import dispatch_mesh
    from nomad_tpu.models.fleet import fleet_cache

    statics = fleet_cache.statics_for(h_s.state)
    mesh = dispatch_mesh(1, statics.n_pad)
    assert mesh is not None
    mirror_s.sync(h_s.state)
    buf = mirror_s.device_usage_sharded(mesh, mirror_s.usage)
    assert buf is not None
    np.testing.assert_array_equal(np.asarray(buf), mirror_s.usage)


def test_sharded_storm_byte_identical_placements():
    """The fused storm (BatchEvalRunner, 2-D storm mesh on 8 devices)
    vs its single-device twin: byte-identical placements lane for
    lane."""
    from nomad_tpu.scheduler.batch import BatchEvalRunner
    from nomad_tpu.scheduler.executor import executor_override

    def run(policy):
        h, jobs = _stream_rig(n_nodes=16, n_jobs=4, count=6)
        index_of = {n.id: i for i, n in enumerate(h.state.nodes())}
        with mesh_override(policy), executor_override("device"):
            BatchEvalRunner(h.state.snapshot(), h,
                            state_refresh=h.snapshot).process(
                [make_eval_for(j) for j in jobs])
        out = []
        for plan in h.plans:
            rows = []
            for node_id, allocs in plan.node_allocation.items():
                rows.extend((index_of[node_id], a.task_group)
                            for a in allocs)
            out.append(sorted(rows))
        return out

    assert run("auto") == run("off")
