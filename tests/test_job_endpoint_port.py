"""Port of the reference job endpoint table (nomad/job_endpoint_test.go,
v0.1.2): register / re-register / deregister / evaluate over the wire
method table — asserting raft-index stamping, eval minting, and the
outstanding-token fence on eval updates.

Rides the same in-proc RPC rig as tests/test_node_endpoint_port.py, so
every call crosses the full endpoint chain (forwarding, admission,
blocking-query plumbing) rather than poking the server directly.
"""
from __future__ import annotations

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent.agent import InprocRPC
from nomad_tpu.server import Server, ServerConfig


@pytest.fixture
def rig():
    srv = Server(ServerConfig(num_schedulers=0))
    srv.establish_leadership()
    rpc = InprocRPC(srv)
    yield srv, rpc
    srv.shutdown()


def _register(rpc, job):
    return rpc.call("Job.Register", {"job": job.to_dict()})


class TestJobRegister:
    def test_register_stamps_index_and_mints_eval(self, rig):
        """TestJobEndpoint_Register: the response carries the raft index
        (doubling as the job's modify index), the job lands in state
        stamped with it, and a job-register eval exists."""
        srv, rpc = rig
        job = mock.job()
        resp = _register(rpc, job)
        assert resp["index"] > 0
        assert resp["job_modify_index"] == resp["index"]
        out = srv.fsm.state.job_by_id(job.id)
        assert out is not None
        assert out.create_index == resp["index"]
        assert out.modify_index == resp["index"]
        ev = srv.fsm.state.eval_by_id(resp["eval_id"])
        assert ev is not None
        assert ev.triggered_by == "job-register"
        assert ev.job_id == job.id
        assert ev.job_modify_index == resp["index"]
        assert ev.priority == job.priority
        # The eval write is itself a raft entry, after the job's.
        assert ev.create_index > resp["index"]

    def test_register_invalid_job_errors(self, rig):
        _srv, rpc = rig
        job = mock.job()
        job.id = ""
        with pytest.raises(ValueError, match="missing job id"):
            _register(rpc, job)

    def test_reregister_bumps_modify_preserves_create(self, rig):
        """TestJobEndpoint_Register_Existing: updating a job advances
        modify_index (the version bump) but keeps create_index, and
        mints a fresh eval for the new version."""
        srv, rpc = rig
        job = mock.job()
        first = _register(rpc, job)
        job.priority = job.priority + 1
        second = _register(rpc, job)
        assert second["index"] > first["index"]
        assert second["eval_id"] != first["eval_id"]
        out = srv.fsm.state.job_by_id(job.id)
        assert out.create_index == first["index"]
        assert out.modify_index == second["index"]
        assert out.priority == job.priority
        ev = srv.fsm.state.eval_by_id(second["eval_id"])
        assert ev.job_modify_index == second["index"]


class TestJobDeregister:
    def test_deregister_removes_job_and_mints_eval(self, rig):
        """TestJobEndpoint_Deregister: the job is gone from state and a
        job-deregister eval (carrying the dead job's priority) exists so
        the scheduler reaps its allocations."""
        srv, rpc = rig
        job = mock.job()
        _register(rpc, job)
        resp = rpc.call("Job.Deregister", {"job_id": job.id})
        assert resp["index"] > 0
        assert srv.fsm.state.job_by_id(job.id) is None
        ev = srv.fsm.state.eval_by_id(resp["eval_id"])
        assert ev is not None
        assert ev.triggered_by == "job-deregister"
        assert ev.job_id == job.id
        assert ev.priority == job.priority


class TestJobEvaluate:
    def test_evaluate_mints_eval_for_existing_job(self, rig):
        """TestJobEndpoint_Evaluate: forces a fresh evaluation of a
        registered job without changing it."""
        srv, rpc = rig
        job = mock.job()
        reg = _register(rpc, job)
        resp = rpc.call("Job.Evaluate", {"job_id": job.id})
        assert resp["eval_id"] != reg["eval_id"]
        ev = srv.fsm.state.eval_by_id(resp["eval_id"])
        assert ev is not None
        assert ev.triggered_by == "job-register"
        assert ev.job_modify_index == reg["index"]

    def test_evaluate_missing_job_errors(self, rig):
        _srv, rpc = rig
        with pytest.raises(KeyError, match="job not found"):
            rpc.call("Job.Evaluate", {"job_id": "no-such-job"})


class TestEvalTokenFence:
    def test_outstanding_eval_rejects_mismatched_token(self, rig):
        """eval_endpoint.go:123-143 via the job path: once a worker holds
        the minted eval, updates without its token are fenced off."""
        srv, rpc = rig
        job = mock.job()
        resp = _register(rpc, job)
        ev, token = srv.eval_broker.dequeue([job.type], timeout=5)
        assert ev is not None and ev.id == resp["eval_id"]
        ev.status = "complete"
        with pytest.raises(PermissionError, match="token"):
            srv.apply_eval_update([ev], token="bogus-token")
        index = srv.apply_eval_update([ev], token=token)
        assert index > resp["index"]
        assert srv.fsm.state.eval_by_id(ev.id).status == "complete"


class TestJobQueries:
    def test_get_list_allocations_evaluations(self, rig):
        srv, rpc = rig
        job = mock.job()
        reg = _register(rpc, job)
        got = rpc.call("Job.GetJob", {"job_id": job.id})
        assert got["job"]["id"] == job.id
        assert rpc.call("Job.GetJob", {"job_id": "nope"})["job"] is None
        listed = rpc.call("Job.List", {})
        assert [j["id"] for j in listed["jobs"]] == [job.id]
        alloc = mock.alloc()
        alloc.job = job
        alloc.job_id = job.id
        idx = srv.raft.applied_index()
        srv.fsm.state.upsert_allocs(idx + 1, [alloc])
        allocs = rpc.call("Job.Allocations", {"job_id": job.id})
        assert [a["id"] for a in allocs["allocations"]] == [alloc.id]
        evals = rpc.call("Job.Evaluations", {"job_id": job.id})
        assert reg["eval_id"] in [e["id"] for e in evals["evaluations"]]
