"""API client library suite (reference api/*_test.go): every typed
wrapper exercised against a live dev agent, plus QueryMeta plumbing.
The HTTP wire contracts live in test_http_api2; this file covers the
CLIENT-side surface the reference's api package tests."""
from __future__ import annotations

import pytest

from nomad_tpu.api import APIError, QueryOptions
from nomad_tpu.jobspec import parse
from tests.conftest import boot_dev_agent, wait_until

JOBSPEC = """
job "api-probe" {
    datacenters = ["dc1"]
    group "g" {
        count = 1
        task "t" {
            driver = "raw_exec"
            config {
                command = "/bin/sleep"
                args = "60"
            }
            resources {
                cpu = 50
                memory = 32
            }
        }
    }
}
"""


@pytest.fixture(scope="module")
def rig(tmp_path_factory):
    agent, client = boot_dev_agent(
        str(tmp_path_factory.mktemp("agent-api-client")))
    yield agent, client
    agent.shutdown()


@pytest.fixture
def job(rig):
    _agent, client = rig
    j = parse(JOBSPEC)
    resp = client.job_register(j)
    assert resp["eval_id"]
    yield j, resp["eval_id"]
    try:
        client.job_deregister(j.id)
    except APIError:
        pass


def test_jobs_surface(rig, job):
    _agent, client = rig
    j, eval_id = job
    jobs, meta = client.jobs_list()
    assert any(x.id == j.id for x in jobs)
    assert meta.last_index > 0

    info, _meta = client.job_info(j.id)
    assert info.id == j.id and info.task_groups[0].name == "g"

    wait_until(lambda: client.job_allocations(j.id)[0],
               msg="job allocations")
    allocs, _ = client.job_allocations(j.id)
    assert allocs[0].job_id == j.id

    evals, _ = client.job_evaluations(j.id)
    assert any(e.id == eval_id for e in evals)

    forced = client.job_evaluate(j.id)
    assert forced["eval_id"] and forced["eval_id"] != eval_id

    client.job_deregister(j.id)
    with pytest.raises(APIError):
        client.job_info(j.id)


def test_nodes_surface(rig):
    _agent, client = rig
    nodes, meta = client.nodes_list()
    assert nodes and meta.last_index > 0
    node_id = nodes[0].id

    info, _ = client.node_info(node_id)
    assert info.id == node_id and info.status == "ready"

    allocs, _ = client.node_allocations(node_id)
    assert isinstance(allocs, list)

    client.node_drain(node_id, True)
    info, _ = client.node_info(node_id)
    assert info.drain is True
    client.node_drain(node_id, False)
    info, _ = client.node_info(node_id)
    assert info.drain is False

    client.node_evaluate(node_id)
    with pytest.raises(APIError):
        client.node_info("definitely-not-a-node")


def test_evals_and_allocs_surface(rig, job):
    _agent, client = rig
    j, eval_id = job
    evs, _ = client.evaluations_list()
    assert any(e.id == eval_id for e in evs)

    ev, meta = client.eval_info(eval_id)
    assert ev.id == eval_id and meta.last_index > 0

    wait_until(lambda: client.eval_allocations(eval_id)[0],
               msg="eval allocations")
    allocs, _ = client.eval_allocations(eval_id)
    a_id = allocs[0].id

    listed, _ = client.allocations_list()
    assert any(a.id == a_id for a in listed)
    alloc, _ = client.alloc_info(a_id)
    assert alloc.id == a_id and alloc.job_id == j.id
    assert alloc.metrics is not None  # explainability travels the wire


def test_agent_and_status_surface(rig):
    agent, client = rig
    self_info = client.agent_self()
    assert "config" in self_info and "stats" in self_info

    members = client.agent_members()
    assert isinstance(members, list)

    leader = client.status_leader()
    assert leader  # dev agent leads itself
    peers = client.status_peers()
    assert isinstance(peers, list) and peers

    servers = client.agent_servers()
    assert isinstance(servers, list)


def test_query_options_stale_and_wait(rig, job):
    import time

    _agent, client = rig
    j, _eval_id = job
    _jobs, meta = client.jobs_list()
    # Already-satisfied index (1 <= current) returns promptly with data.
    t0 = time.monotonic()
    jobs, _m = client.jobs_list(QueryOptions(
        wait_index=1, wait_time=5.0, allow_stale=True))
    assert time.monotonic() - t0 < 2.0
    assert any(x.id == j.id for x in jobs)
    # Unsatisfied index genuinely blocks until wait_time elapses.
    t0 = time.monotonic()
    _jobs, meta2 = client.jobs_list(QueryOptions(
        wait_index=meta.last_index, wait_time=0.3))
    assert time.monotonic() - t0 >= 0.25
    assert meta2.last_index >= meta.last_index
