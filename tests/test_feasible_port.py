"""Port of the reference scheduler's feasibility/rank tables
(scheduler/feasible_test.go + scheduler/rank_test.go), asserted against
BOTH execution paths:

  - the **host truth** — the sequential iterators
    (scheduler/feasible.py StaticIterator/DriverIterator/
    ConstraintIterator, scheduler/rank.py BinPackIterator/
    JobAntiAffinityIterator) and the scalar predicates
    (utils/predicates, structs.score_fit);
  - the **jax-binpack paths** — the compiled constraint mask
    (models/constraints.compile_group_mask) and the vectorized scoring
    kernel (ops/binpack.score_all_nodes), which must agree
    node-for-node / score-for-score with the iterators by construction.

Each table is the reference's case set re-expressed over the repo's
node/alloc mocks; where the Go test asserted an exact iterator output
order or score, so do we.
"""
from __future__ import annotations

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.models.fleet import NDIMS, build_fleet, build_usage
from nomad_tpu.models.constraints import compile_group_mask
from nomad_tpu.ops.binpack import NEG_INF, score_all_nodes
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import (
    ConstraintIterator,
    DriverIterator,
    StaticIterator,
    check_single_constraint,
)
from nomad_tpu.scheduler.rank import (
    BinPackIterator,
    FeasibleRankIterator,
    JobAntiAffinityIterator,
    RankedNode,
    StaticRankIterator,
)
from nomad_tpu.structs import (
    Allocation,
    Constraint,
    Node,
    Resources,
    Task,
    score_fit,
)
from nomad_tpu.utils.predicates import (
    check_constraint_values,
    resolve_constraint_target,
)


class _State:
    """Minimal EvalContext state: allocs_by_node only."""

    def __init__(self) -> None:
        self.by_node: dict = {}

    def allocs_by_node(self, node_id: str) -> list:
        return list(self.by_node.get(node_id, []))


def _ctx(state=None, plan=None) -> EvalContext:
    from nomad_tpu.structs import Plan

    return EvalContext(state or _State(), plan or Plan())


def _drain(it) -> list:
    out = []
    while True:
        n = it.next()
        if n is None:
            return out
        out.append(n)


def _mask_for(nodes, constraints, drivers=(), datacenters=("dc1",)):
    """The device path's verdict vector for the same predicate set."""
    fleet = build_fleet(nodes)
    mask, _dist = compile_group_mask(fleet, list(datacenters),
                                     list(constraints), [],
                                     set(drivers))
    return mask[:fleet.n_real]


# ---------------------------------------------------------------------------
# feasible_test.go
# ---------------------------------------------------------------------------

class TestStaticIteratorPort:
    def test_static_iterator_serves_all_then_exhausts(self):
        # TestStaticIterator_Reset semantics: N nodes out, then None.
        ctx = _ctx()
        nodes = [mock.node(i) for i in range(3)]
        it = StaticIterator(ctx, nodes)
        assert _drain(it) == nodes
        assert it.next() is None

    def test_static_iterator_reset(self):
        ctx = _ctx()
        nodes = [mock.node(i) for i in range(3)]
        it = StaticIterator(ctx, nodes)
        _drain(it)
        it.reset()
        assert len(_drain(it)) == 3

    def test_static_iterator_set_nodes(self):
        ctx = _ctx()
        it = StaticIterator(ctx, [mock.node(0)])
        _drain(it)
        fresh = [mock.node(i) for i in range(2)]
        it.set_nodes(fresh)
        assert _drain(it) == fresh


class TestDriverIteratorPort:
    def test_driver_truthiness_table(self):
        """TestDriverIterator: driver.<name> parse-bools per node —
        "1"/"true"/"T" admit, "0"/"false"/missing reject — and the
        compiled mask agrees node-for-node."""
        values = ["1", "0", "true", "False", None, "T"]
        expect = [True, False, True, False, False, True]
        nodes = []
        for i, v in enumerate(values):
            n = mock.node(i)
            n.attributes = dict(n.attributes)
            n.attributes.pop("driver.exec", None)
            if v is not None:
                n.attributes["driver.exec"] = v
            nodes.append(n)

        ctx = _ctx()
        it = DriverIterator(ctx, StaticIterator(ctx, nodes), ["exec"])
        got = _drain(it)
        assert got == [n for n, ok in zip(nodes, expect) if ok]

        mask = _mask_for(nodes, [], drivers=("exec",))
        assert mask.tolist() == expect

    def test_multiple_drivers_all_required(self):
        n_both = mock.node(0)
        n_both.attributes = dict(n_both.attributes,
                                 **{"driver.docker": "1"})
        n_one = mock.node(1)
        nodes = [n_both, n_one]
        ctx = _ctx()
        it = DriverIterator(ctx, StaticIterator(ctx, nodes),
                            ["exec", "docker"])
        assert _drain(it) == [n_both]
        assert _mask_for(nodes, [], drivers=("exec", "docker")).tolist() \
            == [True, False]


class TestConstraintIteratorPort:
    def _nodes(self):
        # TestConstraintIterator's shape: one matching node, one with a
        # different value, one missing the attribute entirely.
        a = mock.node(0)
        b = mock.node(1)
        b.attributes = dict(b.attributes, **{"kernel.name": "darwin"})
        c = mock.node(2)
        c.attributes = {k: v for k, v in c.attributes.items()
                        if k != "kernel.name"}
        return [a, b, c]

    def test_equality_constraint(self):
        nodes = self._nodes()
        cons = [Constraint(hard=True, l_target="$attr.kernel.name",
                           operand="=", r_target="linux")]
        ctx = _ctx()
        it = ConstraintIterator(ctx, StaticIterator(ctx, nodes), cons)
        assert _drain(it) == [nodes[0]]
        assert _mask_for(nodes, cons).tolist() == [True, False, False]

    def test_soft_constraint_does_not_filter(self):
        nodes = self._nodes()
        cons = [Constraint(hard=False, l_target="$attr.kernel.name",
                           operand="=", r_target="linux")]
        ctx = _ctx()
        it = ConstraintIterator(ctx, StaticIterator(ctx, nodes), cons)
        assert _drain(it) == nodes

    @pytest.mark.parametrize("operand,r_target,expect", [
        ("!=", "linux", [False, True, False]),   # missing attr: infeasible
        ("regexp", "^lin", [True, False, False]),
        ("version", ">= 0.1.0", [True, True, True]),
        ("version", "> 0.2.0", [False, False, False]),
        ("<", "zzz", [True, True, True]),        # lexical order on names
    ])
    def test_operand_table_host_vs_mask(self, operand, r_target, expect):
        nodes = self._nodes()
        l_target = "$attr.kernel.name" if operand in ("!=", "regexp") \
            else ("$attr.version" if operand == "version"
                  else "$node.name")
        cons = [Constraint(hard=True, l_target=l_target,
                           operand=operand, r_target=r_target)]
        ctx = _ctx()
        it = ConstraintIterator(ctx, StaticIterator(ctx, nodes), cons)
        admitted = _drain(it)
        got = [n in admitted for n in nodes]
        verdicts = [check_single_constraint(_ctx(), cons[0], n)
                    for n in nodes]
        assert verdicts == expect, (operand, r_target)
        assert got == expect
        assert _mask_for(nodes, cons).tolist() == expect

    def test_distinct_hosts_against_proposed_allocs(self):
        """ProposedAllocConstraintIterator semantics: feasible iff no
        proposed alloc of the job is on the node (evictions honored)."""
        from nomad_tpu.structs import CONSTRAINT_DISTINCT_HOSTS, Plan

        node = mock.node(0)
        other = mock.node(1)
        a = mock.alloc()
        a.node_id = node.id
        state = _State()
        state.by_node[node.id] = [a]
        cons = Constraint(hard=True, operand=CONSTRAINT_DISTINCT_HOSTS,
                          l_target="", r_target=a.job_id)
        ctx = _ctx(state)
        assert check_single_constraint(ctx, cons, node) is False
        assert check_single_constraint(ctx, cons, other) is True
        # Planned eviction frees the node.
        plan = Plan()
        plan.node_update[node.id] = [a]
        ctx2 = _ctx(state, plan)
        assert check_single_constraint(ctx2, cons, node) is True


class TestCheckConstraintValuesPort:
    """TestCheckConstraint / TestCheckVersionConstraint /
    TestCheckRegexpConstraint operand tables."""

    @pytest.mark.parametrize("operand,l,r,expect", [
        ("=", "foo", "foo", True),
        ("==", "foo", "foo", True),
        ("is", "foo", "foo", True),
        ("=", "foo", "bar", False),
        ("!=", "foo", "bar", True),
        ("not", "foo", "foo", False),
        ("<", "abc", "abd", True),
        (">", "abc", "abd", False),
        ("<=", "abc", "abc", True),
        (">=", "abc", "abc", True),
        ("<", "abc", 3, False),           # non-string lexical: infeasible
        ("bogus-operand", "a", "a", False),
    ])
    def test_basic_operands(self, operand, l, r, expect):
        assert check_constraint_values(_ctx(), operand, l, r) is expect

    @pytest.mark.parametrize("version,constraint,expect", [
        ("0.7.0", "= 0.7.0", True),
        ("0.7.0", "!= 0.7.0", False),
        ("0.6.9", "< 0.7.0", True),
        ("0.7.0", ">= 0.6.0, < 0.8.0", True),
        ("0.8.0", ">= 0.6.0, < 0.8.0", False),
        ("1.7.0-beta", "> 1.6.0", True),
        ("1.7.0-beta", ">= 1.7.0", False),  # prerelease sorts below
        ("not-a-version", "> 0.1.0", False),
    ])
    def test_version_operand(self, version, constraint, expect):
        assert check_constraint_values(
            _ctx(), "version", version, constraint) is expect

    @pytest.mark.parametrize("value,pattern,expect", [
        ("linux", "lin", True),
        ("linux", "^lin", True),
        ("linux", "^win", False),
        ("linux", "(", False),            # invalid pattern: infeasible
        (3, "3", False),                  # non-string value: infeasible
    ])
    def test_regexp_operand(self, value, pattern, expect):
        assert check_constraint_values(
            _ctx(), "regexp", value, pattern) is expect

    def test_resolve_targets(self):
        node = mock.node(0)
        assert resolve_constraint_target("$node.id", node) == \
            (node.id, True)
        assert resolve_constraint_target("$node.datacenter", node) == \
            ("dc1", True)
        assert resolve_constraint_target("$attr.arch", node) == \
            ("x86", True)
        assert resolve_constraint_target("$meta.pci-dss", node) == \
            ("true", True)
        assert resolve_constraint_target("$attr.nope", node)[1] is False
        assert resolve_constraint_target("literal", node) == \
            ("literal", True)


# ---------------------------------------------------------------------------
# rank_test.go
# ---------------------------------------------------------------------------

def _bare_node(idx: int, cpu: int, mem: int) -> Node:
    """A rank-table node with NO reservations (the Go tables' shape)."""
    n = mock.node(idx)
    n.resources = Resources(cpu=cpu, memory_mb=mem,
                            disk_mb=100 * 1024, iops=150)
    n.reserved = None
    return n


def _task(cpu: int, mem: int) -> Task:
    return Task(name="web", driver="exec",
                resources=Resources(cpu=cpu, memory_mb=mem))


def _device_scores(nodes, ask_cpu, ask_mem, proposed=None,
                   job_counts=None, penalty=0.0):
    """score_all_nodes over the same fleet: the kernel's masked scores
    for one ask, NEG_INF where infeasible."""
    fleet = build_fleet(nodes)
    view = build_usage(fleet, proposed or [])
    ask = np.zeros(NDIMS, dtype=np.float32)
    ask[0], ask[1] = ask_cpu, ask_mem
    feasible = np.zeros(fleet.n_pad, dtype=bool)
    feasible[:fleet.n_real] = True
    jc = np.zeros(fleet.n_pad, dtype=np.int32)
    if job_counts:
        for i, c in job_counts.items():
            jc[i] = c
    out = score_all_nodes(fleet.capacity, fleet.reserved, view.usage,
                          jc, ask, feasible, False,
                          np.float32(penalty))
    return np.asarray(out)[:fleet.n_real]


class TestFeasibleRankIteratorPort:
    def test_upgrades_nodes_to_ranked(self):
        ctx = _ctx()
        nodes = [mock.node(i) for i in range(3)]
        it = FeasibleRankIterator(ctx, StaticIterator(ctx, nodes))
        out = _drain(it)
        assert [r.node for r in out] == nodes
        assert all(isinstance(r, RankedNode) and r.score == 0.0
                   for r in out)


class TestBinPackIteratorPort:
    def test_no_existing_allocs_scores_and_fit(self):
        """TestBinPackIterator_NoExistingAlloc: a half-fitting ask on an
        empty node vs a too-small node — the small node is exhausted,
        the exactly-full node is a PERFECT fit (score 18, the BestFit
        ceiling), and the device kernel produces the SAME scores for
        the same utils."""
        empty = _bare_node(0, 2048, 2048)
        exact = _bare_node(1, 1024, 1024)
        small = _bare_node(2, 512, 512)
        ctx = _ctx()
        it = BinPackIterator(ctx, StaticRankIterator(
            ctx, [RankedNode(empty), RankedNode(exact),
                  RankedNode(small)]))
        it.set_tasks([_task(1024, 1024)])
        out = _drain(it)
        assert [r.node for r in out] == [empty, exact]

        want = score_fit(empty, Resources(cpu=1024, memory_mb=1024))
        assert out[0].score == pytest.approx(want)
        # 50% free on both dims: 20 - 2*10^0.5.
        assert want == pytest.approx(20.0 - 2.0 * 10.0 ** 0.5)
        assert out[1].score == pytest.approx(18.0)  # perfect fit caps

        dev = _device_scores([empty, exact, small], 1024, 1024)
        assert dev[0] == pytest.approx(want, rel=1e-6)
        assert dev[1] == pytest.approx(18.0)
        assert dev[2] == NEG_INF  # masked infeasible, like the iterator

    def test_existing_alloc_counts_against_fit(self):
        """TestBinPackIterator_ExistingAlloc: a proposed alloc holding
        half the node leaves no room for a second half+1 ask."""
        node = _bare_node(0, 1024, 1024)
        held = Allocation(id="held", node_id=node.id, job_id="other",
                          resources=Resources(cpu=512, memory_mb=512))
        state = _State()
        state.by_node[node.id] = [held]
        ctx = _ctx(state)
        it = BinPackIterator(ctx, StaticRankIterator(
            ctx, [RankedNode(node)]))
        it.set_tasks([_task(1024, 1024)])
        assert _drain(it) == []

        # Device path: same usage fold, same verdict.
        dev = _device_scores([node], 1024, 1024, proposed=[held])
        assert dev[0] == NEG_INF

    def test_planned_evict_frees_capacity(self):
        """TestBinPackIterator_ExistingAlloc_PlannedEvict: evicting the
        held alloc in the plan makes the node feasible again."""
        from nomad_tpu.structs import Plan

        node = _bare_node(0, 1024, 1024)
        held = Allocation(id="held", node_id=node.id, job_id="other",
                          resources=Resources(cpu=512, memory_mb=512))
        state = _State()
        state.by_node[node.id] = [held]
        plan = Plan()
        plan.node_update[node.id] = [held]
        ctx = _ctx(state, plan)
        it = BinPackIterator(ctx, StaticRankIterator(
            ctx, [RankedNode(node)]))
        it.set_tasks([_task(1024, 1024)])
        out = _drain(it)
        assert [r.node for r in out] == [node]
        want = score_fit(node, Resources(cpu=1024, memory_mb=1024))
        assert out[0].score == pytest.approx(want)

        dev = _device_scores([node], 1024, 1024, proposed=[])
        assert dev[0] == pytest.approx(want, rel=1e-6)

    def test_scores_prefer_packed_node(self):
        """BestFit v3 prefers the node that ends up fuller — the
        iterator's ordering and the kernel's argmax agree."""
        fresh = _bare_node(0, 4096, 4096)
        busy = _bare_node(1, 4096, 4096)
        held = Allocation(id="h", node_id=busy.id, job_id="other",
                          resources=Resources(cpu=2048, memory_mb=2048))
        state = _State()
        state.by_node[busy.id] = [held]
        ctx = _ctx(state)
        it = BinPackIterator(ctx, StaticRankIterator(
            ctx, [RankedNode(fresh), RankedNode(busy)]))
        it.set_tasks([_task(1024, 1024)])
        out = {r.node.id: r.score for r in _drain(it)}
        assert out[busy.id] > out[fresh.id]

        dev = _device_scores([fresh, busy], 1024, 1024, proposed=[held])
        assert int(np.argmax(dev)) == 1
        assert dev[1] == pytest.approx(out[busy.id], rel=1e-6)
        assert dev[0] == pytest.approx(out[fresh.id], rel=1e-6)


class TestJobAntiAffinityPort:
    def test_planned_alloc_penalized(self):
        """TestJobAntiAffinity_PlannedAlloc: two same-job proposed
        allocs on a node score -2*penalty; an uninvolved node scores
        0 — and the kernel's job_counts term applies the SAME
        penalty."""
        from nomad_tpu.structs import Plan

        crowded = _bare_node(0, 4096, 4096)
        empty = _bare_node(1, 4096, 4096)
        job_id = "job-under-test"
        plan = Plan()
        plan.node_allocation[crowded.id] = [
            Allocation(id=f"p{i}", node_id=crowded.id, job_id=job_id,
                       resources=Resources(cpu=1, memory_mb=1))
            for i in range(2)]
        ctx = _ctx(_State(), plan)
        penalty = 50.0
        it = JobAntiAffinityIterator(
            ctx, StaticRankIterator(
                ctx, [RankedNode(crowded), RankedNode(empty)]),
            penalty, job_id)
        out = _drain(it)
        assert out[0].score == pytest.approx(-2 * penalty)
        assert out[1].score == 0.0

        # Device path: the same -penalty * job_counts term, on top of
        # the binpack score for the same (tiny) ask.
        dev = _device_scores([crowded, empty], 1, 1,
                             proposed=list(
                                 plan.node_allocation[crowded.id]),
                             job_counts={0: 2}, penalty=penalty)
        base_crowded = score_fit(
            crowded, Resources(cpu=2 + 1, memory_mb=2 + 1))
        base_empty = score_fit(empty, Resources(cpu=1, memory_mb=1))
        assert dev[0] == pytest.approx(base_crowded - 2 * penalty,
                                       rel=1e-5)
        assert dev[1] == pytest.approx(base_empty, rel=1e-5)
