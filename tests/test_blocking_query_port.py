"""Blocking-query semantics port, run against BOTH serving paths.

The reference's blocking-query contract (nomad/rpc.go:269-338 block,
nomad/http_test.go TestParseWait/blocking tables, node_endpoint_test.go
Node.GetAllocs blocking cases):

- ``min_query_index`` 0 (or absent) answers immediately with the
  current table index;
- ``min_query_index`` below the current index answers immediately;
- ``min_query_index`` at/above the current index blocks until a write
  moves the table past it, then answers with the NEW index;
- a wait that expires answers with the CURRENT data and index — a
  timeout is a normal response, never an error;
- waits are table-keyed: a write to another table must not wake the
  query;
- a query for an object that doesn't exist still honors the table
  semantics (blocks, then answers ``None``).

Every case runs twice — through the in-proc RPC path (the colocated
agent, synchronous fan-out waiter) and through the event-driven mux
wire path (parked fan-out callback) — on identically-driven fresh
servers, and the responses must be byte-identical: the serving-plane
refactor may change WHERE a query waits, never WHAT it answers.
"""
from __future__ import annotations

import json
import threading
import time

import pytest

from nomad_tpu.agent.agent import InprocRPC
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool
from nomad_tpu.structs import Allocation, Node


def _node(i: int) -> Node:
    return Node(id=f"bq-n{i:03d}", name=f"bq-{i}", datacenter="dc1",
                status="ready")


def _alloc(i: int, node_id: str) -> Allocation:
    return Allocation(id=f"bq-a{i:03d}", node_id=node_id,
                      job_id="bq-job", eval_id="bq-eval",
                      name=f"bq[{i}]", desired_status="run",
                      client_status="pending")


class _InprocPath:
    name = "inproc"

    def __enter__(self):
        self.srv = Server(ServerConfig(num_schedulers=0, tune_gc=False,
                                       use_device_scheduler=False))
        self.srv.establish_leadership()
        self.rpc = InprocRPC(self.srv)
        return self

    def call(self, method, args):
        return self.rpc.call(method, args)

    def __exit__(self, *exc):
        self.srv.shutdown()


class _MuxPath:
    name = "mux"

    def __enter__(self):
        self.srv = Server(ServerConfig(num_schedulers=0, tune_gc=False,
                                       use_device_scheduler=False,
                                       enable_rpc=True))
        self.srv.establish_leadership()
        self.pool = ConnPool()
        return self

    def call(self, method, args):
        return self.pool.call(self.srv.rpc_address(), method,
                              dict(args), timeout=30.0)

    def __exit__(self, *exc):
        self.pool.shutdown()
        self.srv.shutdown()


def _canon(resp) -> str:
    return json.dumps(resp, sort_keys=True)


# Each case: (name, run(path) -> response dict).  Writes are
# deterministic (fixed ids, raft-sequenced indexes) so both fresh
# servers produce byte-identical state and responses.

def _case_min_index_zero_immediate(p):
    p.srv.node_register(_node(0))
    return p.call("Node.List", {})


def _case_min_index_below_current_immediate(p):
    first = p.srv.node_register(_node(0))
    p.srv.node_register(_node(1))
    return p.call("Node.List", {"min_query_index": first,
                                "max_query_time": 5.0})


def _case_blocks_until_change(p):
    p.srv.node_register(_node(0))
    cur = p.srv.fsm.state.get_index("nodes")

    def write():
        time.sleep(0.3)  # sleep-ok: park the query before the wake write
        p.srv.node_register(_node(1))

    t = threading.Thread(target=write)
    t.start()
    resp = p.call("Node.List", {"min_query_index": cur,
                                "max_query_time": 10.0})
    t.join(5)
    assert resp["index"] > cur, "must answer with the post-write index"
    return resp


def _case_timeout_returns_current(p):
    p.srv.node_register(_node(0))
    cur = p.srv.fsm.state.get_index("nodes")
    t0 = time.monotonic()
    resp = p.call("Node.List", {"min_query_index": cur,
                                "max_query_time": 0.3})
    assert 0.2 <= time.monotonic() - t0 < 5.0
    assert resp["index"] == cur, "timeout answers with the CURRENT index"
    return resp


def _case_unknown_object_blocks_then_none(p):
    p.srv.node_register(_node(0))  # nonzero world
    cur = p.srv.fsm.state.get_index("evals")
    resp = p.call("Eval.GetEval", {"eval_id": "no-such-eval",
                                   "min_query_index": cur or 0,
                                   "max_query_time": 0.3})
    assert resp["eval"] is None
    return resp


def _case_get_allocs_wakes_on_alloc_write(p):
    p.srv.node_register(_node(0))
    # Seed the table: a pre-first-write index of 0 takes the immediate
    # path by contract (min_query_index 0 never blocks).
    p.srv.fsm.state.upsert_allocs(999, [])
    cur = p.srv.fsm.state.get_index("allocs")

    def write():
        time.sleep(0.3)  # sleep-ok: park the long-poll before the alloc lands
        p.srv.fsm.state.upsert_allocs(1000, [_alloc(0, "bq-n000")])

    t = threading.Thread(target=write)
    t.start()
    resp = p.call("Node.GetAllocs", {"node_id": "bq-n000",
                                     "min_query_index": cur,
                                     "max_query_time": 10.0})
    t.join(5)
    assert len(resp["allocs"]) == 1 and resp["index"] == 1000
    return resp


def _case_waits_are_table_keyed(p):
    p.srv.node_register(_node(0))
    jobs_cur = p.srv.fsm.state.get_index("jobs")

    def write_other_table():
        time.sleep(0.15)  # sleep-ok: the cross-table write lands mid-wait
        p.srv.node_register(_node(1))

    t = threading.Thread(target=write_other_table)
    t.start()
    t0 = time.monotonic()
    resp = p.call("Job.List", {"min_query_index": jobs_cur or 0,
                               "max_query_time": 0.6})
    t.join(5)
    took = time.monotonic() - t0
    if jobs_cur > 0:
        assert took >= 0.5, "a nodes write must not wake a jobs query"
    assert resp["jobs"] == []
    return resp


CASES = [
    _case_min_index_zero_immediate,
    _case_min_index_below_current_immediate,
    _case_blocks_until_change,
    _case_timeout_returns_current,
    _case_unknown_object_blocks_then_none,
    _case_get_allocs_wakes_on_alloc_write,
    _case_waits_are_table_keyed,
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.__name__[6:])
def test_blocking_query_semantics_byte_identical_on_both_paths(case):
    responses = {}
    for path_cls in (_InprocPath, _MuxPath):
        with path_cls() as p:
            resp = case(p)
            assert resp.get("known_leader") is True
            responses[path_cls.name] = _canon(resp)
    assert responses["inproc"] == responses["mux"], \
        "the two serving paths answered differently:\n" \
        f"inproc: {responses['inproc']}\nmux:    {responses['mux']}"


def test_parked_path_actually_parks_while_inproc_blocks_a_thread():
    """Structural sanity for the comparison above: over the wire the
    waiting query is a fan-out waiter with NO dispatch worker pinned;
    in-proc it is the caller's own thread."""
    with _MuxPath() as p:
        p.srv.node_register(_node(0))
        cur = p.srv.fsm.state.get_index("nodes")
        got = []
        t = threading.Thread(target=lambda: got.append(
            p.call("Node.List", {"min_query_index": cur,
                                 "max_query_time": 10.0})))
        t.start()
        from tests.conftest import wait_until
        wait_until(lambda: p.srv.fsm.state.watch.live_waiters() == 1,
                   msg="wire query parked in the fan-out")
        assert p.srv.rpc_server._pool.stats()["busy"] == 0, \
            "a parked blocking query must not pin a dispatch worker"
        p.srv.node_register(_node(1))
        t.join(10)
        assert got and got[0]["index"] > cur
