"""Agent + HTTP API + api client + jobspec + CLI tests."""
from __future__ import annotations

import io
import os
import sys
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import APIClient, APIError, QueryOptions
from nomad_tpu.jobspec import ParseError, parse

from tests.conftest import wait_until


JOBSPEC = """
job "web" {
    datacenters = ["dc1"]
    type = "service"

    constraint {
        attribute = "$attr.kernel.name"
        value = "linux"
    }

    update {
        stagger = "10s"
        max_parallel = 1
    }

    group "frontend" {
        count = 2
        task "server" {
            driver = "raw_exec"
            config {
                command = "/bin/sleep"
                args = "120"
            }
            env {
                PORT = "8080"
            }
            resources {
                cpu = 100
                memory = 64
                network {
                    mbits = 5
                    dynamic_ports = ["http"]
                }
            }
        }
        meta {
            owner = "team-web"
        }
    }
}
"""


# ---------------------------------------------------------------------------
# jobspec parsing
# ---------------------------------------------------------------------------

class TestJobspec:
    def test_parse_full_spec(self):
        job = parse(JOBSPEC)
        assert job.id == "web"
        assert job.datacenters == ["dc1"]
        assert job.constraints[0].l_target == "$attr.kernel.name"
        assert job.update.stagger == 10.0
        assert job.update.max_parallel == 1
        tg = job.task_groups[0]
        assert tg.name == "frontend" and tg.count == 2
        task = tg.tasks[0]
        assert task.driver == "raw_exec"
        assert task.config["command"] == "/bin/sleep"
        assert task.env["PORT"] == "8080"
        assert task.resources.cpu == 100
        assert task.resources.networks[0].dynamic_ports == ["http"]
        assert tg.meta["owner"] == "team-web"

    def test_parse_reference_example(self):
        """The reference's `nomad init` example parses (docker variant)."""
        spec = """
job "example" {
    datacenters = ["dc1"]
    constraint {
        attribute = "$attr.kernel.name"
        value = "linux"
    }
    update {
        stagger = "10s"
        max_parallel = 1
    }
    group "cache" {
        count = 1
        task "redis" {
            driver = "docker"
            config {
                image = "redis:latest"
            }
            resources {
                cpu = 500
                memory = 256
                network {
                    mbits = 10
                    dynamic_ports = ["6379"]
                }
            }
        }
    }
}
"""
        job = parse(spec)
        assert job.task_groups[0].tasks[0].config["image"] == \
            "redis:latest"

    def test_job_level_task_wraps_group(self):
        spec = """
job "solo" {
    datacenters = ["dc1"]
    task "one" {
        driver = "exec"
        config { command = "/bin/true" }
    }
}
"""
        job = parse(spec)
        assert len(job.task_groups) == 1
        assert job.task_groups[0].name == "one"

    def test_constraint_sugar(self):
        spec = """
job "sugar" {
    datacenters = ["dc1"]
    constraint {
        attribute = "$attr.version"
        version = ">= 0.1.0"
    }
    constraint {
        attribute = "$node.name"
        regexp = "web-.*"
    }
    group "g" {
        task "t" { driver = "exec" config { command = "/bin/true" } }
    }
}
"""
        job = parse(spec)
        assert job.constraints[0].operand == "version"
        assert job.constraints[1].operand == "regexp"

    def test_errors(self):
        with pytest.raises(ParseError):
            parse("not a job")
        with pytest.raises(ParseError):
            parse('job "x" {}')  # missing dc + groups
        with pytest.raises(ParseError):
            parse('job "x" { datacenters = ["dc1"] '
                  'group "g" { task "t" { driver = "exec" '
                  'resources { network { dynamic_ports = ["bad!port"] '
                  '} } } } }')


# ---------------------------------------------------------------------------
# agent + HTTP + api client end to end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dev_agent(tmp_path_factory):
    from tests.conftest import boot_dev_agent
    agent, client = boot_dev_agent(str(tmp_path_factory.mktemp("agent")))
    yield agent, client
    agent.shutdown()


class TestHTTPAPI:
    def test_run_job_via_api(self, dev_agent):
        agent, client = dev_agent
        job = parse(JOBSPEC)
        resp = client.job_register(job)
        assert resp["eval_id"]

        # Eval completes; allocations placed and eventually running.
        def eval_done():
            ev, _ = client.eval_info(resp["eval_id"])
            return ev.status == "complete"
        wait_until(eval_done, msg="eval completion")

        allocs, meta = client.job_allocations("web")
        assert len(allocs) == 2
        assert meta.last_index > 0
        wait_until(lambda: all(
            a.client_status == "running"
            for a, _m in [client.alloc_info(al.id) for al in allocs]
            for a in [a]), timeout=20, msg="tasks running")

        got, _ = client.job_info("web")
        assert got.id == "web"
        jobs, _ = client.jobs_list()
        assert any(j.id == "web" for j in jobs)

        evals, _ = client.job_evaluations("web")
        assert evals

        # Node surface.
        nodes, _ = client.nodes_list()
        assert len(nodes) == 1
        node, _ = client.node_info(nodes[0].id)
        assert node.status == "ready"
        node_allocs, _ = client.node_allocations(node.id)
        assert len(node_allocs) == 2

        # Status surface.
        assert client.status_leader()
        assert client.agent_self()["stats"]["nomad"]["leader"] == "true"

        # Stop the job.
        client.job_deregister("web")

        def stopped():
            allocs, _ = client.job_allocations("web")
            return all(a.desired_status == "stop" for a in allocs)
        wait_until(stopped, msg="job stopped")

    def test_blocking_query_via_api(self, dev_agent):
        agent, client = dev_agent
        _, meta = client.nodes_list()
        start = time.monotonic()
        _, meta2 = client.nodes_list(QueryOptions(
            wait_index=meta.last_index, wait_time=0.5))
        elapsed = time.monotonic() - start
        assert elapsed >= 0.4  # blocked until the (jittered) wait expired

    def test_404s(self, dev_agent):
        _, client = dev_agent
        with pytest.raises(APIError) as e:
            client.job_info("no-such-job")
        assert e.value.status == 404
        with pytest.raises(APIError):
            client.raw("GET", "/v1/bogus")

    def test_pprof_endpoint(self, dev_agent):
        """Thread-stack dump — the pprof-goroutine analogue (reference
        http.go:115-120)."""
        _, client = dev_agent
        data, _ = client.raw("GET", "/v1/agent/pprof")
        stacks = data["stacks"]
        assert any("MainThread" in name for name in stacks)
        frames = next(iter(stacks.values()))
        assert frames and {"file", "line", "func"} <= set(frames[0])

    def test_pprof_gated_on_enable_debug(self, dev_agent):
        agent, client = dev_agent
        agent.config.enable_debug = False
        try:
            with pytest.raises(APIError) as e:
                client.raw("GET", "/v1/agent/pprof")
            assert e.value.status == 404
        finally:
            agent.config.enable_debug = True

    def test_device_profile_toggle(self, dev_agent, tmp_path):
        """Start/stop a jax.profiler trace over live dispatches; the
        directory is xprof-loadable (SURVEY §5 device profiler hook)."""
        _, client = dev_agent
        trace_dir = str(tmp_path / "xla-trace")
        data, _ = client.raw(
            "PUT", f"/v1/agent/profile",
            params={"action": "start", "dir": trace_dir})
        assert data["tracing"] == trace_dir
        # double-start is a client error
        with pytest.raises(APIError) as e:
            client.raw("PUT", "/v1/agent/profile",
                       params={"action": "start", "dir": trace_dir})
        assert e.value.status == 400
        import jax.numpy as jnp

        (jnp.ones((16, 16)) @ jnp.ones((16, 16))).block_until_ready()
        data, _ = client.raw("PUT", "/v1/agent/profile",
                             params={"action": "stop"})
        assert data["traced"] == trace_dir
        assert os.path.isdir(trace_dir) and os.listdir(trace_dir)


# ---------------------------------------------------------------------------
# CLI (in-process, pointed at the dev agent)
# ---------------------------------------------------------------------------

class TestCLI:
    def run_cli(self, dev_agent, *argv) -> tuple[int, str]:
        from nomad_tpu.cli import main

        agent, _ = dev_agent
        address = f"http://127.0.0.1:{agent.http.address[1]}"
        stdout = io.StringIO()
        old = sys.stdout
        sys.stdout = stdout
        try:
            code = main(["-address", address] + list(argv))
        finally:
            sys.stdout = old
        return code, stdout.getvalue()

    def test_version(self, dev_agent):
        code, out = self.run_cli(dev_agent, "version")
        assert code == 0 and "nomad-tpu v" in out

    def test_client_config_view_and_update(self, dev_agent):
        agent, _ = dev_agent
        # Dev agent uses in-proc RPC; the config list is what's shown.
        code, out = self.run_cli(
            dev_agent, "client-config",
            "-update-servers", "10.0.0.9:4647,10.0.0.10:4647")
        assert code == 0, out
        assert "2 servers" in out
        assert agent.client.servers() == [("10.0.0.9", 4647),
                                          ("10.0.0.10", 4647)]
        code, out = self.run_cli(dev_agent, "client-config")
        assert code == 0
        assert "10.0.0.9:4647" in out and "10.0.0.10:4647" in out

    def test_server_force_leave_cli(self, dev_agent):
        # No gossip plane on the dev agent: the command still succeeds
        # as a no-op (parity with the reference's idempotent force-leave).
        code, out = self.run_cli(dev_agent, "server-force-leave",
                                 "nonexistent-member")
        assert code == 0
        assert "Forced leave" in out

    def test_node_status(self, dev_agent):
        code, out = self.run_cli(dev_agent, "node-status")
        assert code == 0
        assert "ready" in out

    def test_node_drain_cli(self, dev_agent):
        """`node-drain -enable <id>` marks the node draining; -disable
        clears it (reference command/node_drain.go)."""
        agent, _ = dev_agent
        node_id = agent.client.node.id
        code, out = self.run_cli(dev_agent, "node-drain", "-enable",
                                 node_id)
        assert code == 0, out
        assert agent.server.fsm.state.node_by_id(node_id).drain
        code, out = self.run_cli(dev_agent, "node-drain", "-disable",
                                 node_id)
        assert code == 0, out
        assert not agent.server.fsm.state.node_by_id(node_id).drain

    def test_run_status_stop(self, dev_agent, tmp_path):
        spec = tmp_path / "cli-job.nomad"
        spec.write_text(JOBSPEC.replace('job "web"', 'job "cli-job"')
                        .replace('count = 2', 'count = 1'))
        code, out = self.run_cli(dev_agent, "run", str(spec))
        assert code == 0, out
        assert "complete" in out

        code, out = self.run_cli(dev_agent, "status", "cli-job")
        assert code == 0
        assert "cli-job" in out

        code, out = self.run_cli(dev_agent, "stop", "cli-job")
        assert code == 0

    def test_validate_and_init(self, dev_agent, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        code, out = self.run_cli(dev_agent, "init")
        assert code == 0
        code, out = self.run_cli(dev_agent, "validate", "example.nomad")
        assert code == 0, out
        assert "successful" in out


def test_agent_monitor_ring_and_cli(dev_agent, capsys):
    """/v1/agent/monitor serves the recent-log ring; the monitor CLI
    prints it (reference command/agent/log_writer.go consumer)."""
    import logging

    from nomad_tpu.cli.main import main as cli_main
    from nomad_tpu.utils.gated_log import LogWriter

    agent, client = dev_agent
    writer = LogWriter()
    log = logging.getLogger("nomad_tpu.test.monitorcli")
    log.setLevel(logging.INFO)
    log.propagate = False
    log.addHandler(writer)
    agent.log_writer = writer
    try:
        log.info("monitor line alpha")
        log.info("monitor line beta")
        lines = client.agent_monitor()
        assert any("monitor line alpha" in ln for ln in lines)
        assert any("monitor line beta" in ln for ln in lines)
        assert len(client.agent_monitor(lines=1)) == 1

        addr = f"http://127.0.0.1:{agent.http.address[1]}"
        rc = cli_main(["-address", addr, "monitor", "-lines", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "monitor line alpha" in out and "monitor line beta" in out
    finally:
        agent.log_writer = None
        log.removeHandler(writer)


def test_agent_monitor_absent_without_ring(dev_agent):
    """Library embeddings (no CLI boot gate) 404 the monitor endpoint."""
    agent, client = dev_agent
    assert agent.log_writer is None
    with pytest.raises(Exception) as exc:
        client.agent_monitor()
    assert "404" in str(exc.value) or "not" in str(exc.value).lower()
