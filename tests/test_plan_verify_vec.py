"""Vectorized plan verifier parity (server/plan_apply._evaluate_plan_vec).

The scalar per-node walk (_evaluate_node_plan: allocs_fit + a fresh
NetworkIndex per node, reference nomad/plan_apply.go:238-284) is the
semantic truth; the vector path must produce IDENTICAL PlanResults on
every snapshot it serves, including port collisions, bandwidth limits,
freed-by-eviction fits and in-place updates.  Targeted cases first,
then a randomized fuzz, then incremental net-mirror consistency.
"""
import random

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.models.fleet import fleet_cache, mirror_for
from nomad_tpu.server.plan_apply import (
    _evaluate_node_plan,
    _evaluate_plan_vec,
    evaluate_plan,
)
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    Allocation,
    NetworkResource,
    Plan,
    Resources,
    generate_uuid,
)


def make_alloc(node, *, cpu=500, mem=256, port=None, mbits=10,
               job_id="j1", terminal=False, alloc_id=None) -> Allocation:
    ports = [port] if port is not None else []
    # Offers land on the node's one ip — mock nodes carry it on the
    # reserved network (the /32 cidr resolves to the same address).
    ip = node.reserved.networks[0].ip if node.reserved is not None and \
        node.reserved.networks else "192.168.0.1"
    net = NetworkResource(device="eth0", ip=ip,
                          reserved_ports=list(ports), mbits=mbits)
    a = Allocation(
        id=alloc_id or generate_uuid(),
        node_id=node.id,
        job_id=job_id,
        task_group="web",
        resources=Resources(cpu=cpu, memory_mb=mem,
                            networks=[net.copy()]),
        task_resources={"web": Resources(cpu=cpu, memory_mb=mem,
                                         networks=[net])},
        desired_status=ALLOC_DESIRED_STATUS_STOP if terminal
        else ALLOC_DESIRED_STATUS_RUN,
        client_status=ALLOC_CLIENT_STATUS_PENDING,
    )
    return a


def scalar_truth(snap, plan) -> dict:
    """The scalar walk's verdict for every touched node."""
    node_ids = set(plan.node_update) | set(plan.node_allocation)
    return {nid: _evaluate_node_plan(snap, plan, nid) for nid in node_ids}


def assert_parity(state, plan):
    verdicts = _evaluate_plan_vec(
        state, plan, set(plan.node_update) | set(plan.node_allocation))
    truth = scalar_truth(state, plan)
    assert verdicts is not None
    for nid, want in truth.items():
        got = verdicts[nid]
        if got is None:
            continue  # punted to the scalar walk: trivially consistent
        assert got == want, (nid, got, want)
    return verdicts


@pytest.fixture
def rig():
    state = StateStore()
    nodes = [mock.node(i) for i in range(8)]
    idx = 10
    for n in nodes:
        state.upsert_node(idx, n)
        idx += 1
    return state, nodes, [idx]  # mutable index cell


def bump(cell):
    cell[0] += 1
    return cell[0]


def test_over_capacity_rejected(rig):
    state, nodes, cell = rig
    n = nodes[0]
    plan = Plan(node_allocation={n.id: [
        make_alloc(n, cpu=8000, mem=64)]})  # node has 4000 MHz
    v = assert_parity(state, plan)
    assert v[n.id] is False


def test_fit_accepted_and_eviction_frees(rig):
    state, nodes, cell = rig
    n = nodes[0]
    big = make_alloc(n, cpu=3500, mem=4000)
    state.upsert_allocs(bump(cell), [big])
    # Without eviction the second big alloc cannot fit...
    plan = Plan(node_allocation={n.id: [make_alloc(n, cpu=3500, mem=400)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False
    # ...evicting it in the same plan frees the room.
    stopped = make_alloc(n, cpu=3500, mem=4000, alloc_id=big.id)
    plan = Plan(node_update={n.id: [stopped]},
                node_allocation={n.id: [make_alloc(n, cpu=3500, mem=400)]})
    v = assert_parity(state, plan)
    assert v[n.id] is True


def test_port_collision_with_existing(rig):
    state, nodes, cell = rig
    n = nodes[0]
    state.upsert_allocs(bump(cell), [make_alloc(n, port=30000)])
    plan = Plan(node_allocation={n.id: [make_alloc(n, port=30000)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False
    # A different port fits.
    plan = Plan(node_allocation={n.id: [make_alloc(n, port=30001)]})
    v = assert_parity(state, plan)
    assert v[n.id] is True


def test_port_collision_within_plan(rig):
    state, nodes, cell = rig
    n = nodes[0]
    plan = Plan(node_allocation={n.id: [
        make_alloc(n, port=31000), make_alloc(n, port=31000)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False


def test_port_freed_by_eviction_reusable(rig):
    state, nodes, cell = rig
    n = nodes[0]
    old = make_alloc(n, port=32000)
    state.upsert_allocs(bump(cell), [old])
    stopped = make_alloc(n, port=32000, alloc_id=old.id)
    plan = Plan(node_update={n.id: [stopped]},
                node_allocation={n.id: [make_alloc(n, port=32000)]})
    v = assert_parity(state, plan)
    assert v[n.id] is True


def test_node_reserved_port_always_collides(rig):
    state, nodes, cell = rig
    n = nodes[0]  # mock nodes reserve port 22
    plan = Plan(node_allocation={n.id: [make_alloc(n, port=22)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False


def test_eviction_frees_duplicated_port(rig):
    """State can hold colliding ports (committed without verification);
    a plan that evicts one of the pair must be judged on the
    POST-removal live set, exactly like the scalar walk."""
    state, nodes, cell = rig
    n = nodes[0]
    a1 = make_alloc(n, port=33000)
    a2 = make_alloc(n, port=33000)
    state.upsert_allocs(bump(cell), [a1, a2])
    # Collision still live: rejected.
    plan = Plan(node_allocation={n.id: [make_alloc(n, port=33001)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False
    # Evicting one of the pair clears the duplicate; its port is still
    # held by the survivor, so a placement on it must still reject —
    # but any other port fits.
    stop = make_alloc(n, port=33000, alloc_id=a1.id)
    plan = Plan(node_update={n.id: [stop]},
                node_allocation={n.id: [make_alloc(n, port=33001)]})
    v = assert_parity(state, plan)
    assert v[n.id] is True
    plan = Plan(node_update={n.id: [stop]},
                node_allocation={n.id: [make_alloc(n, port=33000)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False


def test_off_network_reserved_punts_to_scalar(rig):
    """Reserved networks on a different ip/device than the node's
    primary network can't ride the merged fast counting — the verdict
    must come from the scalar walk (None), and the public
    evaluate_plan result must equal the scalar truth."""
    state, nodes, cell = rig
    n = mock.node(50)
    n.reserved.networks.append(NetworkResource(
        device="lo", ip="127.0.0.1", reserved_ports=[8080], mbits=0))
    state.upsert_node(bump(cell), n)
    plan = Plan(node_allocation={n.id: [make_alloc(n, port=8080)]})
    verdicts = _evaluate_plan_vec(state, plan, {n.id})
    assert verdicts[n.id] is None  # punted
    result = evaluate_plan(state, plan)
    want = scalar_truth(state, plan)[n.id]
    assert (n.id in result.node_allocation) == want


def test_bandwidth_exceeded(rig):
    state, nodes, cell = rig
    n = nodes[0]  # 1000 mbits capacity, 1 reserved
    state.upsert_allocs(bump(cell), [make_alloc(n, mbits=800)])
    plan = Plan(node_allocation={n.id: [make_alloc(n, mbits=300)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False
    plan = Plan(node_allocation={n.id: [make_alloc(n, mbits=100)]})
    v = assert_parity(state, plan)
    assert v[n.id] is True


def test_down_node_rejected(rig):
    state, nodes, cell = rig
    n = mock.node(99)
    n.status = "down"
    state.upsert_node(bump(cell), n)
    plan = Plan(node_allocation={n.id: [make_alloc(n)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False


def test_terminal_allocs_ignored(rig):
    state, nodes, cell = rig
    n = nodes[0]
    state.upsert_allocs(bump(cell), [
        make_alloc(n, cpu=3900, mem=7000, terminal=True)])
    plan = Plan(node_allocation={n.id: [make_alloc(n, cpu=3500)]})
    v = assert_parity(state, plan)
    assert v[n.id] is True


def test_evaluate_plan_end_to_end_matches(rig):
    """Whole-result comparison through the public evaluate_plan."""
    state, nodes, cell = rig
    n0, n1 = nodes[0], nodes[1]
    state.upsert_allocs(bump(cell), [make_alloc(n0, port=30000)])
    plan = Plan(node_allocation={
        n0.id: [make_alloc(n0, port=30000)],     # collides -> rejected
        n1.id: [make_alloc(n1, port=30000)],     # fine on another node
    })
    result = evaluate_plan(state, plan)
    assert n1.id in result.node_allocation
    assert n0.id not in result.node_allocation
    assert result.refresh_index > 0


def test_fuzz_parity(rig):
    state, nodes, cell = rig
    rng = random.Random(7)
    live: list = []
    for round_i in range(60):
        # Mutate state: add some allocs, stop some.
        batch = []
        for _ in range(rng.randrange(0, 4)):
            n = rng.choice(nodes)
            batch.append(make_alloc(
                n, cpu=rng.choice([200, 900, 1800]),
                mem=rng.choice([128, 2048]),
                port=rng.choice([None, 30000 + rng.randrange(6)]),
                mbits=rng.choice([0, 10, 400])))
        if batch:
            live.extend(batch)
            state.upsert_allocs(bump(cell), batch)
        if live and rng.random() < 0.4:
            victim = live.pop(rng.randrange(len(live)))
            stopped = make_alloc(victim_node(nodes, victim),
                                 alloc_id=victim.id, terminal=True)
            state.upsert_allocs(bump(cell), [stopped])

        # Random plan over random nodes.
        plan = Plan()
        for n in rng.sample(nodes, rng.randrange(1, 5)):
            if rng.random() < 0.3:
                on_node = [a for a in live if a.node_id == n.id]
                if on_node:
                    victim = rng.choice(on_node)
                    plan.node_update.setdefault(n.id, []).append(
                        make_alloc(n, alloc_id=victim.id))
            k = rng.randrange(0, 3)
            for _ in range(k):
                plan.node_allocation.setdefault(n.id, []).append(
                    make_alloc(n, cpu=rng.choice([200, 1500, 3900]),
                               mem=rng.choice([128, 4096]),
                               port=rng.choice(
                                   [None, 30000 + rng.randrange(6)]),
                               mbits=rng.choice([0, 10, 600])))
        if plan.node_update or plan.node_allocation:
            assert_parity(state, plan)


def victim_node(nodes, alloc):
    for n in nodes:
        if n.id == alloc.node_id:
            return n
    raise AssertionError(alloc.node_id)


def test_net_mirror_rebuilds_after_snapshot_restore(rig):
    """A snapshot restore swaps the world (new lineage): the mirror's
    full rebuild must rebuild the net tracking too, not serve port
    counts from the dead world."""
    state, nodes, cell = rig
    n = nodes[0]
    state.upsert_allocs(bump(cell), [make_alloc(n, port=34000)])
    statics = fleet_cache.statics_for(state)
    mirror = mirror_for(statics)
    assert mirror.sync_net(state)
    assert any(34000 in pc for pc in mirror.node_ports.values())

    # Restore a world where a DIFFERENT port is held.
    r = state.restore()
    for node in nodes:
        r.node_restore(node)
    other = make_alloc(n, port=35000)
    r.alloc_restore(other)
    r.index_restore("allocs", 9000)
    r.commit()

    assert mirror.sync_net(state)
    held = {p for pc in mirror.node_ports.values() for p in pc}
    assert held == {35000}  # dead world's 34000 is gone
    assert other.id in mirror.net_rows
    # And the verifier judges against the restored world.
    plan = Plan(node_allocation={n.id: [make_alloc(n, port=35000)]})
    v = assert_parity(state, plan)
    assert v[n.id] is False
    plan = Plan(node_allocation={n.id: [make_alloc(n, port=34000)]})
    v = assert_parity(state, plan)
    assert v[n.id] is True


def test_optimistic_overlay_nodes_use_scalar_truth(rig):
    """The real PlanApplier verifies against an OptimisticSnapshot
    (base + in-flight allocs).  Overlay-touched nodes must punt to the
    scalar walk (verdict None) and the public evaluate_plan must match
    the scalar truth computed over the SAME overlay view."""
    from nomad_tpu.server.plan_apply import OptimisticSnapshot

    state, nodes, cell = rig
    n = nodes[0]
    state.upsert_allocs(bump(cell), [make_alloc(n, cpu=1000)])
    snap = OptimisticSnapshot(state)
    # An in-flight plan's alloc fills most of the node.
    snap.upsert_allocs([make_alloc(n, cpu=2500, mem=7000)])

    plan = Plan(node_allocation={n.id: [make_alloc(n, cpu=600)]})
    verdicts = _evaluate_plan_vec(snap, plan, {n.id})
    assert verdicts[n.id] is None  # overlay: scalar path decides
    result = evaluate_plan(snap, plan)
    want = scalar_truth(snap, plan)[n.id]
    assert (n.id in result.node_allocation) == want
    # And the overlay genuinely matters: without it the placement fits,
    # with it the node is full.
    assert want is False
    assert scalar_truth(state, plan)[n.id] is True


def test_incremental_net_mirror_matches_rebuild(rig):
    """After arbitrary churn, the incrementally-maintained net state must
    equal a from-scratch rebuild (same invariant style as the usage
    mirror's parity tests)."""
    state, nodes, cell = rig
    statics = fleet_cache.statics_for(state)
    mirror = mirror_for(statics)
    mirror.sync_net(state)  # enable tracking before the churn

    rng = random.Random(3)
    live: list = []
    for _ in range(40):
        batch = []
        for _ in range(rng.randrange(0, 3)):
            n = rng.choice(nodes)
            batch.append(make_alloc(
                n, port=rng.choice([None, 40000 + rng.randrange(4)]),
                mbits=rng.choice([0, 25])))
        if batch:
            live.extend(batch)
            state.upsert_allocs(bump(cell), batch)
        if live and rng.random() < 0.5:
            victim = live.pop(rng.randrange(len(live)))
            state.upsert_allocs(bump(cell), [make_alloc(
                victim_node(nodes, victim), alloc_id=victim.id,
                terminal=True)])
        mirror.sync_net(state)

        from nomad_tpu.models.fleet import UsageMirror
        fresh = UsageMirror(statics)
        fresh.sync_net(state)
        assert mirror.net_rows == fresh.net_rows
        assert mirror.node_ports == fresh.node_ports
        assert mirror.node_dup == fresh.node_dup
        assert mirror.node_bw == fresh.node_bw
        assert mirror.node_net_keys == fresh.node_net_keys
        np.testing.assert_array_equal(mirror.usage, fresh.usage)
