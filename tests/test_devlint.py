"""Device-plane lint (analysis/devlint.py) + the defects it found.

Three layers, mirroring tests/test_interprocedural_lint.py:

1. **Rule units** on synthetic packages: every devlint rule
   (mesh-bypass, resident-bypass, sharding-mix, transfer-under-lock,
   transfer-in-hot-loop, recompile-churn) proves it fires, and every
   sanctioned pattern (placement through the seams, the collect seams,
   bucketed shapes, justified ``# devlint-ok`` markers) proves it is
   exempt — a lint that cannot fail gates nothing.
2. **Analyzer-found defect regressions**: the real bugs the passes
   surfaced — the sharded wrappers' unplaced penalty scalar (an
   implicit per-dispatch transfer), the fused batch's unbucketed lane
   axis (a retrace per distinct batch size), and the usage mirror's
   fleet-sized uploads inside its lock — each pinned by a test that
   fails on the pre-fix shape.
3. **Transfer discipline end-to-end**: the scheduler dispatch seams run
   clean under ``jax.transfer_guard("disallow")`` — zero implicit
   transfers on the hot path — and the explicit-transfer odometer
   (parallel/devices.transfer_counts) moves when placements happen.
"""
from __future__ import annotations

import textwrap
import threading

import numpy as np
import pytest

import jax

import nomad_tpu.mock as mock
from nomad_tpu.analysis import devlint
from nomad_tpu.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    Evaluation,
    Resources,
    generate_uuid,
)


def write_files(tmp_path, files: dict) -> str:
    d = tmp_path / "pkg"
    d.mkdir(exist_ok=True)
    for name, source in files.items():
        (d / name).write_text(textwrap.dedent(source))
    return str(d)


def rules_of(findings) -> dict:
    out: dict = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# ---------------------------------------------------------------------------
# 1. rule units
# ---------------------------------------------------------------------------

class TestShardingPropagation:
    def test_mesh_bypass_fires_and_consult_exempts(self, tmp_path):
        pkg = write_files(tmp_path, {
            "kern.py": """
                import jax

                def _impl(x, p):
                    return x * p

                kern = jax.jit(_impl)
                """,
            "mod.py": """
                from pkg.kern import kern

                def dispatch_mesh(n, pad):
                    return None

                def _put(x):
                    import jax
                    return jax.device_put(x)

                def bad(x):
                    return kern(_put(x), _put(2.0))

                def good(x):
                    mesh = dispatch_mesh(1, 8)
                    return kern(_put(x), _put(2.0))
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("mesh-bypass", ())]
        assert any(w.startswith("bad.") for w in wheres), by
        assert not any(w.startswith("good.") for w in wheres), wheres

    def test_kernel_defining_module_and_kernel_bodies_exempt(
            self, tmp_path):
        """jit-to-jit composition and same-module aliasing are traced
        code / kernel plumbing, not dispatches."""
        pkg = write_files(tmp_path, {
            "kern.py": """
                import jax

                def _inner(x):
                    return x + 1

                def _outer(x):
                    return _inner(x) * 2

                inner = jax.jit(_inner)
                outer = jax.jit(_outer)

                def same_module_call(x):
                    return inner(x)
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        assert "mesh-bypass" not in by, by

    def test_sharding_mix_flags_host_operand(self, tmp_path):
        pkg = write_files(tmp_path, {
            "mod.py": """
                import jax

                def _impl_sharded(x, p):
                    return x * p

                kern_sharded = jax.jit(_impl_sharded)

                def _put(x):
                    return jax.device_put(x)

                def wrapper_bad(mesh, x, penalty):
                    x = _put(x)
                    return kern_sharded(x, penalty)

                def wrapper_good(mesh, x, penalty):
                    x = _put(x)
                    penalty = _put(penalty)
                    return kern_sharded(x, penalty)
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("sharding-mix", ())]
        assert "wrapper_bad.p" in wheres, by
        assert not any(w.startswith("wrapper_good") for w in wheres)

    def test_resident_bypass_fires_and_seams_exempt(self, tmp_path):
        pkg = write_files(tmp_path, {
            "mod.py": """
                import jax

                def sneaky(x):
                    return jax.device_put(x)

                def _put(x):
                    return jax.device_put(x)

                def put_counted(x):
                    return jax.device_put(x)

                class ShardedResidency:
                    def prepare(self, x):
                        return jax.device_put(x)
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        quals = [f.where for f in by.get("resident-bypass", ())]
        assert "sneaky" in quals, by
        assert all(q == "sneaky" for q in quals), quals


class TestTransferDiscipline:
    LOCKED = {
        "mod.py": """
            import threading

            import jax

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def direct(self, x):
                    with self._lock:
                        return jax.device_put(x)

                def chained(self, x):
                    with self._lock:
                        return self._upload(x)

                def _upload(self, x):
                    return jax.device_put(x)
            """,
    }

    def test_transfer_under_lock_direct_and_chain(self, tmp_path):
        pkg = write_files(tmp_path, self.LOCKED)
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("transfer-under-lock", ())]
        assert "C.direct[C._lock]" in wheres, by
        assert "C.chained[C._lock]" in wheres, wheres

    def test_marker_waives_and_is_counted(self, tmp_path):
        src = textwrap.dedent(self.LOCKED["mod.py"]).replace(
            "    def _upload(self, x):\n"
            "        return jax.device_put(x)",
            "    def _upload(self, x):\n"
            "        # devlint-ok(transfer-under-lock): test waiver with"
            " a reason\n"
            "        return jax.device_put(x)")
        src = src.replace(
            "    def direct(self, x):\n"
            "        with self._lock:\n"
            "            return jax.device_put(x)",
            "    def direct(self, x):\n"
            "        with self._lock:\n"
            "            # devlint-ok(transfer-under-lock): test waiver"
            " with a reason\n"
            "            return jax.device_put(x)")
        assert "devlint-ok" in src
        pkg = write_files(tmp_path, {"mod.py": src})
        cov: dict = {}
        findings = devlint.analyze_package(pkg, coverage_out=cov)
        assert not [f for f in findings
                    if f.rule == "transfer-under-lock"], findings
        assert cov["waived"] > 0

    def test_marker_does_not_waive_the_next_statement(self, tmp_path):
        """A marker covers its own block's first code line ONLY: a
        genuine finding introduced directly beneath a waived site must
        still surface (the over-waive would quietly blind the
        strict-clean gate right where it believes itself covered)."""
        pkg = write_files(tmp_path, {
            "mod.py": """
                import threading

                import jax

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def direct(self, x):
                        with self._lock:
                            # devlint-ok(transfer-under-lock): waived
                            # site with a reason
                            a = jax.device_put(x)
                            b = jax.device_put(x)
                            return a, b
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("transfer-under-lock", ())]
        assert "C.direct[C._lock]" in wheres, \
            "the statement after a waived site must still flag"

    def test_inline_marker_waives_its_line_only(self, tmp_path):
        """A trailing (inline) marker waives its own line, never the
        statement below; a comment-block marker separated from the
        site by a blank line attaches to nothing."""
        pkg = write_files(tmp_path, {
            "mod.py": """
                import threading

                import jax

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def inline(self, x):
                        with self._lock:
                            a = jax.device_put(x)  # devlint-ok(transfer-under-lock): reviewed site
                            b = jax.device_put(x)
                            return a, b

                    def detached(self, x):
                        with self._lock:
                            # devlint-ok(transfer-under-lock): floats free

                            return jax.device_put(x)
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("transfer-under-lock", ())]
        # inline: the second put still flags; detached: the blank line
        # breaks the attachment, so the site flags too.
        assert wheres.count("C.inline[C._lock]") == 1, wheres
        assert "C.detached[C._lock]" in wheres, wheres

    def test_unjustified_marker_does_not_waive(self, tmp_path):
        src = textwrap.dedent(self.LOCKED["mod.py"]).replace(
            "            return jax.device_put(x)",
            "            # devlint-ok(transfer-under-lock):\n"
            "            return jax.device_put(x)", 1)
        assert "devlint-ok" in src
        pkg = write_files(tmp_path, {"mod.py": src})
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("transfer-under-lock", ())]
        assert "C.direct[C._lock]" in wheres, by

    def test_hot_loop_flags_implicit_operand_and_concretize(
            self, tmp_path):
        pkg = write_files(tmp_path, {
            "mod.py": """
                import jax
                import numpy as np

                def _impl(x, p):
                    return x * p

                kern = jax.jit(_impl)

                def _put(x):
                    return jax.device_put(x)

                def dispatch_mesh(n, pad):
                    return None

                class R:
                    def _drain_window(self, v):
                        dispatch_mesh(1, 8)
                        host = np.zeros(8, dtype=np.float32)
                        y = kern(host, _put(2.0))
                        return float(y)

                    def cold_path(self, v):
                        dispatch_mesh(1, 8)
                        host = np.zeros(8, dtype=np.float32)
                        return kern(host, _put(2.0))

                def collect_device(handles):
                    y = kern(_put(handles), _put(2.0))
                    return np.asarray(y)
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("transfer-in-hot-loop", ())]
        # host operand + tainted float() inside the hot function...
        assert "R._drain_window.x" in wheres, by
        assert any(w.startswith("R._drain_window.float")
                   for w in wheres), wheres
        # ...but not in cold functions, and not in the collect seams.
        assert not any(w.startswith("R.cold_path") for w in wheres)
        assert not any(w.startswith("collect_device") for w in wheres)


class TestRecompileProvenance:
    def test_unstable_static_arg_and_shape_flag(self, tmp_path):
        pkg = write_files(tmp_path, {
            "mod.py": """
                import jax
                import numpy as np

                def _impl(x, k=1):
                    return x

                kern = jax.jit(_impl, static_argnames=("k",))

                def _put(x):
                    return jax.device_put(x)

                def _pad_to(n):
                    p = 8
                    while p < n:
                        p *= 2
                    return p

                def dispatch_mesh(n, pad):
                    return None

                def churn(items):
                    dispatch_mesh(1, 8)
                    n = len(items)
                    x = np.zeros(n, dtype=np.float32)
                    return kern(x, k=n)

                def bucketed(items):
                    dispatch_mesh(1, 8)
                    n = _pad_to(len(items))
                    x = np.zeros(n, dtype=np.float32)
                    return kern(x, k=n)
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("recompile-churn", ())]
        assert "churn.k" in wheres, by          # static arg churns
        assert "churn.x" in wheres, wheres      # shape churns
        assert not any(w.startswith("bucketed") for w in wheres), wheres

    def test_dtype_less_ctor_feeding_kernel_flags(self, tmp_path):
        pkg = write_files(tmp_path, {
            "mod.py": """
                import jax
                import numpy as np

                def _impl(x):
                    return x

                kern = jax.jit(_impl)

                def dispatch_mesh(n, pad):
                    return None

                def drift():
                    dispatch_mesh(1, 8)
                    x = np.zeros(8)
                    return kern(x)

                def pinned():
                    dispatch_mesh(1, 8)
                    x = np.zeros(8, dtype=np.float32)
                    return kern(x)
                """,
        })
        by = rules_of(devlint.analyze_package(pkg))
        wheres = [f.where for f in by.get("recompile-churn", ())]
        assert "drift.x" in wheres, by
        assert not any(w.startswith("pinned") for w in wheres), wheres

    def test_coverage_counters_reported(self, tmp_path):
        pkg = write_files(tmp_path, {
            "mod.py": """
                import jax

                def _impl(x):
                    return x

                kern = jax.jit(_impl)

                def _put(x):
                    return jax.device_put(x)

                def dispatch_mesh(n, pad):
                    return None

                def go(x):
                    dispatch_mesh(1, 8)
                    return kern(_put(x))
                """,
        })
        cov: dict = {}
        devlint.analyze_package(pkg, coverage_out=cov)
        assert cov["kernels"] == 1
        assert cov["kernel_call_sites"] == 1
        assert cov["placed_args"] == 1 and cov["host_args"] == 0
        assert cov["transfer_sites"] >= 1
        assert "hot_functions" in cov and "waived" in cov


# ---------------------------------------------------------------------------
# 2. analyzer-found defect regressions
# ---------------------------------------------------------------------------

def make_eval(job):
    return Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )


def _cluster(n_nodes: int, n_jobs: int, count: int = 2):
    from nomad_tpu.scheduler import Harness

    h = Harness()
    for i in range(n_nodes):
        h.state.upsert_node(h.next_index(), mock.node(i))
    jobs = []
    for _ in range(n_jobs):
        j = mock.job()
        j.task_groups[0].count = count
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)
    return h, jobs


@pytest.mark.multichip
class TestShardedWrapperDiscipline:
    """Defect 1 (sharding-mix): the single-eval sharded wrappers left
    the penalty scalar to jit — an implicit per-dispatch transfer.  The
    wrappers now place EVERY operand, so a whole sharded dispatch from
    raw host arrays runs under a hard transfer guard."""

    def _problem(self, n_nodes=16, n_place=4):
        from nomad_tpu.models.fleet import build_fleet, build_usage

        nodes = [mock.node(i) for i in range(n_nodes)]
        fleet = build_fleet(nodes)
        view = build_usage(fleet, [])
        asks = np.zeros((1, 6), dtype=np.float32)
        asks[0] = Resources(cpu=500, memory_mb=256).as_vector()
        feasible = np.zeros((1, fleet.n_pad), dtype=bool)
        feasible[0, :fleet.n_real] = True
        group_idx = np.zeros(n_place, dtype=np.int32)
        valid = np.ones(n_place, dtype=bool)
        distinct = np.zeros(1, dtype=bool)
        return fleet, view, feasible, asks, distinct, group_idx, valid

    def test_place_sequence_sharded_is_implicit_free(self):
        from nomad_tpu.parallel.mesh import (fleet_mesh,
                                             place_sequence_sharded)

        fleet, view, feasible, asks, distinct, gi, valid = \
            self._problem()
        mesh = fleet_mesh(jax.devices("cpu"))
        # Warm the trace, then assert the dispatch itself performs NO
        # implicit transfer — host penalty scalar included (the
        # pre-fix shape raised XlaRuntimeError here).
        place_sequence_sharded(mesh, fleet.capacity, fleet.reserved,
                               view.usage, view.job_counts, feasible,
                               asks, distinct, gi, valid, 10.0)
        with jax.transfer_guard("disallow"):
            chosen, _s, _u = place_sequence_sharded(
                mesh, fleet.capacity, fleet.reserved, view.usage,
                view.job_counts, feasible, asks, distinct, gi, valid,
                10.0)
        assert (np.asarray(chosen) >= 0).all()

    def test_place_rounds_sharded_is_implicit_free(self):
        from nomad_tpu.parallel.mesh import (fleet_mesh,
                                             place_rounds_sharded)

        fleet, view, feasible, asks, distinct, _gi, _v = self._problem()
        counts = np.asarray([4], dtype=np.int32)
        mesh = fleet_mesh(jax.devices("cpu"))
        kw = dict(k_cap=8, rounds=1)
        place_rounds_sharded(mesh, fleet.capacity, fleet.reserved,
                             view.usage, view.job_counts, feasible,
                             asks, distinct, counts, 10.0, **kw)
        with jax.transfer_guard("disallow"):
            c, _s, _u = place_rounds_sharded(
                mesh, fleet.capacity, fleet.reserved, view.usage,
                view.job_counts, feasible, asks, distinct, counts,
                10.0, **kw)
        assert (np.asarray(c) >= 0).any()


class TestLaneBucketing:
    """Defect 2 (recompile-churn): the fused batch stacked its lanes at
    the raw batch size — every distinct storm size retraced the vmapped
    kernels (~0.5s each).  The lane axis now buckets to powers of two
    like every other axis."""

    def test_pad_lanes(self):
        from nomad_tpu.scheduler.batch import pad_lanes

        assert [pad_lanes(n) for n in (1, 2, 3, 4, 5, 7, 8, 9)] == \
            [1, 2, 4, 4, 8, 8, 8, 16]

    def test_fused_batch_sizes_share_one_bucket_signature(self):
        """Batch sizes 3 and 4 land in the same lane bucket: after a
        warm dispatch at B=4, a B=3 storm must NOT grow any batched
        kernel's jit cache."""
        from nomad_tpu.analysis.sanitizers import _cache_size
        from nomad_tpu.ops import binpack
        from nomad_tpu.parallel.mesh import mesh_override
        from nomad_tpu.scheduler.batch import BatchEvalRunner
        from nomad_tpu.scheduler.executor import executor_override

        def run(h, jobs):
            runner = BatchEvalRunner(h.state.snapshot(), h)
            with mesh_override("off"), executor_override("device"):
                runner.process([make_eval(j) for j in jobs])

        h4, jobs4 = _cluster(10, 4)
        run(h4, jobs4)
        kernels = [binpack.place_rounds_batch,
                   binpack.place_sequence_batch]
        warm = [_cache_size(k) for k in kernels]
        h3, jobs3 = _cluster(10, 3)
        run(h3, jobs3)
        after = [_cache_size(k) for k in kernels]
        assert after == warm, (
            "a 3-lane storm retraced a batched kernel after a 4-lane "
            f"warm-up: {warm} -> {after} (lane axis must bucket)")
        # Placements still land for the smaller batch.
        assert sum(len(v) for p in h3.plans
                   for v in p.node_allocation.values()) > 0


class TestMirrorUploadDiscipline:
    """Defect 3 (transfer-under-lock): the usage mirror uploaded its
    fleet-sized tensor INSIDE its lock (first device use, platform
    re-pin, and the sharded twin's install) — every concurrent worker's
    sync queued behind a device transfer.  Uploads now happen outside
    the lock with a revalidate-install step."""

    def _mirror(self, n_nodes=8):
        from nomad_tpu.models.fleet import build_fleet, mirror_for
        from nomad_tpu.state.store import StateStore

        store = StateStore()
        idx = 1000
        for i in range(n_nodes):
            store.upsert_node(idx, mock.node(i))
            idx += 1
        fleet = build_fleet(list(store.nodes()))
        mirror = mirror_for(fleet)
        assert mirror.sync(store)
        return store, fleet, mirror

    def test_single_device_upload_runs_outside_the_lock(
            self, monkeypatch):
        from nomad_tpu.parallel import devices as devices_mod

        _store, _fleet, mirror = self._mirror()
        real = devices_mod.put_counted
        seen = []

        def spy(x, device=None):
            seen.append(mirror.lock._is_owned())
            return real(x, device)

        monkeypatch.setattr(devices_mod, "put_counted", spy)
        buf = mirror.device_usage()
        assert seen and not any(seen), \
            "usage upload ran while holding the mirror lock"
        np.testing.assert_allclose(np.asarray(buf), mirror.usage)

    def test_view_attachment_uploads_outside_the_lock(
            self, monkeypatch):
        from nomad_tpu.parallel import devices as devices_mod

        store, _fleet, mirror = self._mirror()
        real = devices_mod.put_counted
        seen = []

        def spy(x, device=None):
            seen.append(mirror.lock._is_owned())
            return real(x, device)

        monkeypatch.setattr(devices_mod, "put_counted", spy)
        view = mirror.view_at(store, None, "job-x")
        assert view is not None and view.usage_device is not None
        assert seen and not any(seen)
        np.testing.assert_allclose(np.asarray(view.usage_device),
                                   view.usage)

    @pytest.mark.multichip
    def test_sharded_upload_outside_lock_and_moved_mirror_refused(
            self, monkeypatch):
        from nomad_tpu.models.fleet import ShardedResidency
        from nomad_tpu.parallel.mesh import fleet_mesh

        _store, _fleet, mirror = self._mirror()
        mesh = fleet_mesh(jax.devices("cpu"))
        real = ShardedResidency.prepare
        seen = []

        def spy(self, mesh_, arrays, spec=None):
            seen.append(mirror.lock._is_owned())
            return real(self, mesh_, arrays, spec=spec)

        monkeypatch.setattr(ShardedResidency, "prepare", spy)
        host = mirror.usage
        buf = mirror.device_usage_sharded(mesh, host)
        assert buf is not None
        assert seen and not any(seen), \
            "sharded usage upload ran while holding the mirror lock"
        np.testing.assert_allclose(np.asarray(buf), host)

        # A mirror that moves on MID-upload must refuse the install and
        # return None (the caller re-syncs) — never serve a stale copy.
        mirror._sharded.clear()
        moved = []

        def mover(self, mesh_, arrays, spec=None):
            out = real(self, mesh_, arrays, spec=spec)
            with mirror.lock:
                mirror.usage = mirror.usage.copy()  # simulate a sync
            moved.append(True)
            return out

        monkeypatch.setattr(ShardedResidency, "prepare", mover)
        assert mirror.device_usage_sharded(mesh, host) is None
        assert moved


# ---------------------------------------------------------------------------
# 3. transfer discipline end-to-end
# ---------------------------------------------------------------------------

class TestDispatchSeamsImplicitFree:
    def test_pipelined_device_stream_under_hard_guard(self):
        """The whole pipelined device stream — prep, mirror attach,
        dispatch, collect, finish — performs zero implicit h2d
        transfers (the suite-wide sanitizer wraps only the dispatch
        seams; this pins the stronger end-to-end property), and the
        explicit odometer records the uploads that DID happen."""
        from nomad_tpu.parallel.devices import transfer_counts
        from nomad_tpu.scheduler.executor import executor_override
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner

        h, jobs = _cluster(12, 4)
        with executor_override("device"):
            runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=3)
            runner.process([make_eval(j) for j in jobs])  # warm traces
            before = transfer_counts()
            h2, jobs2 = _cluster(12, 4)
            runner2 = PipelinedEvalRunner(h2.state.snapshot(), h2,
                                          depth=3)
            with jax.transfer_guard_host_to_device("disallow"):
                runner2.process([make_eval(j) for j in jobs2])
        after = transfer_counts()
        assert runner2.device_dispatches == len(jobs)
        # The per-eval varying operands (usage view, job counts) still
        # crossed — explicitly, visibly.
        assert after["h2d"] > before["h2d"]

    def test_fused_batch_under_hard_guard(self):
        from nomad_tpu.parallel.mesh import mesh_override
        from nomad_tpu.scheduler.batch import BatchEvalRunner
        from nomad_tpu.scheduler.executor import executor_override

        h, jobs = _cluster(10, 4)
        with mesh_override("off"), executor_override("device"):
            BatchEvalRunner(h.state.snapshot(), h).process(
                [make_eval(j) for j in jobs])  # warm
            h2, jobs2 = _cluster(10, 4)
            with jax.transfer_guard_host_to_device("disallow"):
                BatchEvalRunner(h2.state.snapshot(), h2).process(
                    [make_eval(j) for j in jobs2])
        placed = sum(len(v) for p in h2.plans
                     for v in p.node_allocation.values())
        assert placed > 0

    def test_transfer_guard_sanitizer_catches_a_leak(self):
        """The sanitizer has teeth: a seam that commits a host array
        implicitly fails inside the guard scope."""
        from nomad_tpu.analysis.sanitizers import TransferGuardSanitizer

        class FakeSeamHost:
            def dispatch(self, x):
                return jax.jit(lambda a: a + 1)(x)

        sanitizer = TransferGuardSanitizer(
            seams=((__name__, None, "_leaky"),))
        # Wrap a module-level function in THIS module.
        global _leaky

        def _leaky(x):
            return jax.jit(lambda a: a + 1)(x)

        with sanitizer:
            import sys
            wrapped = getattr(sys.modules[__name__], "_leaky")
            with pytest.raises(Exception, match="[Dd]isallowed"):
                wrapped(np.ones(4, dtype=np.float32))
        # Uninstalled: implicit commits pass again.
        _leaky(np.ones(4, dtype=np.float32))
