"""Replica determinism property test: the runtime counterpart of
consensuslint's apply-determinism pass, driven across *interpreter*
boundaries.

30 seeded raft histories (node registers, job registers, eval/alloc
updates, status flips, and eval-delete reaps — the last exercising the
set-walk fan-out paths the lint pass flagged) are generated once,
frozen as encoded log entries, and replayed through fresh FSMs in two
subprocesses running under **different PYTHONHASHSEED values**.  Every
history must produce byte-identical ``store.fingerprint()`` digests —
and an identical watch-notify key sequence — in both interpreters.

A hash-order leak anywhere in the apply path (a set walked into a
replicated table, a dict keyed fan-out escaping to subscribers) shows
up here as a digest that depends on the seed.
"""
from __future__ import annotations

import base64
import json
import os
import random
import subprocess
import sys
import textwrap

import nomad_tpu.mock as mock
from nomad_tpu.structs import codec

HISTORIES = 30

# Runs once per PYTHONHASHSEED: replays every history through a fresh
# FSM, emitting the hash probe (proof the seeds actually differ), then
# one "fingerprint notify-digest" line per history.
_RUNNER = textwrap.dedent("""
    import base64, hashlib, json, sys

    from nomad_tpu.server.fsm import NomadFSM

    with open(sys.argv[1]) as f:
        histories = json.load(f)["histories"]
    print("HASHPROBE", hash("probe-string"))
    for history in histories:
        fsm = NomadFSM()
        notify_digest = hashlib.sha256()
        real_notify = fsm.state.watch.notify

        def record(*keys, index=0):
            notify_digest.update(repr((index, list(keys))).encode())
            return real_notify(*keys, index=index)

        fsm.state.watch.notify = record
        for index, entry_b64 in history:
            fsm.apply(index, base64.b64decode(entry_b64))
        print(fsm.state.fingerprint(), notify_digest.hexdigest())
""")


def _entry(msg_type: int, payload: dict) -> str:
    return base64.b64encode(codec.encode(msg_type, payload)).decode()


def _history(seed: int) -> list:
    """One seeded history: [(index, entry_b64), ...].  The entry bytes
    are frozen here, in the parent — both subprocesses replay the
    exact same log, so the only free variable is the hash seed."""
    rng = random.Random(1000 + seed)
    entries: list = []
    index = 0

    nodes = [mock.node(i) for i in range(rng.randint(4, 8))]
    for n in nodes:
        index += 1
        entries.append((index, _entry(codec.NODE_REGISTER_REQUEST,
                                      {"node": n.to_dict()})))
    evals: list = []
    allocs: list = []
    for _ in range(rng.randint(10, 18)):
        index += 1
        op = rng.randrange(6)
        if op == 0:
            entries.append((index, _entry(codec.JOB_REGISTER_REQUEST,
                                          {"job": mock.job().to_dict()})))
        elif op == 1:
            batch = [mock.eval() for _ in range(rng.randint(1, 4))]
            evals.extend(batch)
            entries.append((index, _entry(
                codec.EVAL_UPDATE_REQUEST,
                {"evals": [e.to_dict() for e in batch]})))
        elif op == 2:
            batch = []
            for _ in range(rng.randint(2, 6)):
                a = mock.alloc()
                a.node_id = rng.choice(nodes).id
                batch.append(a)
            allocs.extend(batch)
            entries.append((index, _entry(
                codec.ALLOC_UPDATE_REQUEST,
                {"alloc": [a.to_dict() for a in batch]})))
        elif op == 3:
            entries.append((index, _entry(
                codec.NODE_UPDATE_STATUS_REQUEST,
                {"node_id": rng.choice(nodes).id,
                 "status": rng.choice(["ready", "down", "ready"])})))
        elif op == 4 and (evals or allocs):
            # The reap: deletes fan out over a set of touched nodes —
            # the exact shape the lint pass caught walking unsorted.
            ev_ids = [e.id for e in evals[:rng.randint(0, len(evals))]]
            del evals[:len(ev_ids)]
            k = rng.randint(0, len(allocs))
            al_ids = [a.id for a in allocs[:k]]
            del allocs[:k]
            entries.append((index, _entry(
                codec.EVAL_DELETE_REQUEST,
                {"evals": ev_ids, "allocs": al_ids})))
        else:
            a = mock.alloc()
            a.node_id = rng.choice(nodes).id
            allocs.append(a)
            entries.append((index, _entry(
                codec.ALLOC_UPDATE_REQUEST, {"alloc": [a.to_dict()]})))
    return entries


def _replay(histories_path: str, runner_path: str, hashseed: str) -> list:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(PYTHONHASHSEED=hashseed, JAX_PLATFORMS="cpu",
               NOMAD_TPU_SANITIZERS="0", PYTHONPATH=repo_root)
    proc = subprocess.run(
        [sys.executable, runner_path, histories_path],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=repo_root)
    assert proc.returncode == 0, proc.stderr[-4000:]
    return proc.stdout.split()


def test_fingerprints_survive_hash_seed_change(tmp_path):
    histories_path = str(tmp_path / "histories.json")
    with open(histories_path, "w") as f:
        json.dump({"histories": [_history(s) for s in range(HISTORIES)]}, f)
    runner_path = str(tmp_path / "runner.py")
    with open(runner_path, "w") as f:
        f.write(_RUNNER)

    out_a = _replay(histories_path, runner_path, "1")
    out_b = _replay(histories_path, runner_path, "2")

    # hash() of a str is seed-dependent: differing probes prove the two
    # interpreters really ran under different hash orders.
    assert out_a[0] == out_b[0] == "HASHPROBE"
    assert out_a[1] != out_b[1], "hash seeds did not take effect"
    digests_a, digests_b = out_a[2:], out_b[2:]
    assert len(digests_a) == 2 * HISTORIES
    assert digests_a == digests_b, \
        "apply path leaked hash order into replicated state or fan-out"
