"""GC-untracking contract for the native finish path.

native/port_alloc.cpp bulk_finish untracks every object it creates
(allocs, metrics, resources, offers, their dicts/lists) so young-gen
collections never scan scheduling bursts.  That is only sound if the
objects are acyclic — reclaimed by refcounting alone, with no reliance
on the cycle collector.  These tests pin both halves of the contract:

  1. produced objects are NOT gc-tracked;
  2. dropping the last reference frees them with gc DISABLED
     (weakrefs die without a collect), proving no cycles pass through
     them (a cycle through an untracked object would leak forever).
"""
from __future__ import annotations

import gc
import weakref

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    Evaluation,
    NetworkResource,
    Resources,
    Task,
    TaskGroup,
    generate_uuid,
)
from nomad_tpu.utils.native import HAS_NATIVE

pytestmark = pytest.mark.skipif(not HAS_NATIVE,
                                reason="native extension unavailable")


def _run_eval(n_nodes=32, n_groups=8, columnar=True, monkeypatch=None):
    if not columnar:
        import nomad_tpu.structs.alloc_slab as alloc_slab
        monkeypatch.setattr(alloc_slab, "COLUMNAR", False)
    h = Harness()
    for i in range(n_nodes):
        h.state.upsert_node(h.next_index(), mock.node(i))
    job = mock.job()
    job.task_groups = [
        TaskGroup(name=f"tg-{g}", count=1, tasks=[Task(
            name="web", driver="exec",
            resources=Resources(
                cpu=100, memory_mb=64,
                networks=[NetworkResource(mbits=5,
                                          dynamic_ports=["http"])]),
        )]) for g in range(n_groups)]
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(id=generate_uuid(), priority=job.priority,
                    type=job.type, triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                    job_id=job.id)
    h.process("jax-binpack", ev)
    plan = h.plans[-1]
    allocs = [a for placed in plan.node_allocation.values() for a in placed]
    assert len(allocs) == n_groups
    return h, plan, allocs


def test_native_allocs_untracked_columnar():
    """Columnar contract: the native loop emits ONE untracked
    SlabAlloc + dict per placement; the heavy fields do not even exist
    until an API-edge consumer reads them (and then materialize as
    ordinary Python objects reclaimed by refcount — see the test
    below)."""
    h, plan, allocs = _run_eval()
    for a in allocs:
        assert not gc.is_tracked(a), "SlabAlloc should be GC-untracked"
        assert not gc.is_tracked(a.__dict__)
        d = a.__dict__
        assert "_slab" in d
        for heavy in ("resources", "task_resources", "metrics",
                      "task_states"):
            assert heavy not in d, \
                f"{heavy} materialized on the scheduling hot path"


def test_native_allocs_untracked_object_path(monkeypatch):
    """Legacy object contract (columnar disabled): the C loop builds
    the full object tree, every piece untracked."""
    h, plan, allocs = _run_eval(columnar=False, monkeypatch=monkeypatch)
    for a in allocs:
        assert not gc.is_tracked(a), "Allocation should be GC-untracked"
        assert not gc.is_tracked(a.__dict__)
        assert not gc.is_tracked(a.metrics)
        assert not gc.is_tracked(a.metrics.__dict__)
        for tr in a.task_resources.values():
            assert not gc.is_tracked(tr)
            for net in tr.networks:
                assert not gc.is_tracked(net)
                assert not gc.is_tracked(net.reserved_ports)
        assert not gc.is_tracked(a.task_resources)


def test_refcount_reclaims_without_collector():
    """The acyclicity proof: with gc disabled, dropping the plan frees
    every alloc (weakrefs die) — no cycle passes through the untracked
    objects, so nothing can leak.  Heavy fields are materialized first
    so the lazily-built objects (and the slab they hang off) are part
    of the proof."""
    h, plan, allocs = _run_eval()
    slabs = {id(a.__dict__["_slab"]): a.__dict__["_slab"]
             for a in allocs if "_slab" in a.__dict__}
    refs = [weakref.ref(a) for a in allocs]
    refs += [weakref.ref(a.metrics) for a in allocs]
    refs += [weakref.ref(tr) for a in allocs
             for tr in a.task_resources.values()]
    refs += [weakref.ref(s) for s in slabs.values()]
    del slabs
    was_enabled = gc.isenabled()
    gc.disable()
    try:
        del allocs
        h.plans.clear()
        plan.node_allocation.clear()
        plan.failed_allocs.clear()
        del plan, h
        dead = sum(1 for r in refs if r() is None)
        assert dead == len(refs), f"{len(refs) - dead} objects survived " \
            "refcount-only teardown: a cycle passes through an untracked " \
            "object"
    finally:
        if was_enabled:
            gc.enable()


def test_mutating_untracked_alloc_retracks_dict():
    """Inserting a container value into an untracked dict re-tracks the
    dict (CPython semantics the untracking design relies on): later
    client-side mutations get cycle-collector coverage again for the
    dict they touch."""
    h, plan, allocs = _run_eval()
    a = allocs[0]
    assert not gc.is_tracked(a.__dict__)
    a.task_states = {"web": ["started"]}  # container value
    assert gc.is_tracked(a.__dict__)
