"""Executor override + host/device parity smoke (tier-1, CPU backend).

The NOMAD_TPU_EXECUTOR override (scheduler/executor.py) only selects
WHICH engine runs the placement kernels — numpy twins or the jit
kernels — never what is planned.  This suite forces a micro eval
stream through PipelinedEvalRunner both ways on the CPU backend and
asserts identical placed counts and scores, gating the bench's
`4_device_pipelined` row (which runs the same code with the device
forced) on every tier-1 run.
"""
from __future__ import annotations

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.executor import (
    EXECUTOR_AUTO,
    EXECUTOR_DEVICE,
    EXECUTOR_HOST,
    ExecutorPolicyError,
    executor_override,
    executor_policy,
    set_executor_policy,
)
from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner
from nomad_tpu.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    Evaluation,
    generate_uuid,
)


def make_eval(job):
    return Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )


def _cluster(n_nodes: int, n_jobs: int, count: int = 3):
    h = Harness()
    for i in range(n_nodes):
        h.state.upsert_node(h.next_index(), mock.node(i))
    jobs = []
    for _ in range(n_jobs):
        j = mock.job()
        j.task_groups[0].count = count
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)
    return h, jobs


def _run_stream(executor: str, depth: int = 3):
    h, jobs = _cluster(12, 5)
    runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=depth)
    with executor_override(executor):
        runner.process([make_eval(j) for j in jobs])
    return h, runner


def _plan_shape(h):
    """Per-plan placement count + per-alloc binpack scores, rounded to
    float32-stable precision (host kernels run f32 like the device)."""
    shape = []
    for p in h.plans:
        allocs = [a for v in p.node_allocation.values() for a in v]
        scores = sorted(
            round(s, 3) for a in allocs
            for s in a.metrics.scores.values())
        shape.append((sum(len(v) for v in p.node_allocation.values()),
                      len(p.failed_allocs), scores))
    return sorted(shape, key=str)


class TestParitySmoke:
    def test_forced_host_vs_forced_device_identical(self):
        """The acceptance gate: same stream, executor forced both ways,
        identical placed counts AND scores."""
        h_host, r_host = _run_stream(EXECUTOR_HOST)
        h_dev, r_dev = _run_stream(EXECUTOR_DEVICE)

        assert r_host.host_dispatches == len(h_host.plans)
        assert r_host.device_dispatches == 0
        assert r_dev.device_dispatches == len(h_dev.plans)
        assert r_dev.host_dispatches == 0

        assert _plan_shape(h_host) == _plan_shape(h_dev)
        assert all(e.status == "complete" for e in h_host.evals)
        assert all(e.status == "complete" for e in h_dev.evals)

    def test_forced_device_matches_auto_plans(self):
        """auto on this micro shape picks host; forcing device must not
        change what is planned."""
        h_auto, _ = _run_stream(EXECUTOR_AUTO)
        h_dev, _ = _run_stream(EXECUTOR_DEVICE)
        assert _plan_shape(h_auto) == _plan_shape(h_dev)

    def test_stage_times_and_windows_recorded(self):
        _, runner = _run_stream(EXECUTOR_DEVICE)
        assert runner.latencies and all(v >= 0 for v in runner.latencies)
        assert runner.windows and sum(runner.windows) == len(
            runner.latencies)
        # Every stage ran: begin/dispatch on the front thread,
        # collect/finish/submit on the drain thread.
        assert all(v >= 0.0 for v in runner.stage_times.values())
        assert runner.stage_times["begin"] > 0.0
        assert runner.stage_times["submit"] > 0.0


class TestPolicyResolution:
    def test_env_wins_over_config(self, monkeypatch):
        set_executor_policy(EXECUTOR_HOST)
        try:
            monkeypatch.setenv("NOMAD_TPU_EXECUTOR", "device")
            assert executor_policy() == EXECUTOR_DEVICE
            monkeypatch.delenv("NOMAD_TPU_EXECUTOR")
            assert executor_policy() == EXECUTOR_HOST
        finally:
            set_executor_policy(EXECUTOR_AUTO)

    def test_invalid_values_fail_loudly(self, monkeypatch):
        with pytest.raises(ExecutorPolicyError):
            set_executor_policy("tpu")
        monkeypatch.setenv("NOMAD_TPU_EXECUTOR", "gpu")
        with pytest.raises(ExecutorPolicyError):
            executor_policy()

    def test_override_restores_prior_env(self, monkeypatch):
        monkeypatch.setenv("NOMAD_TPU_EXECUTOR", "host")
        with executor_override(EXECUTOR_DEVICE):
            assert executor_policy() == EXECUTOR_DEVICE
        assert executor_policy() == EXECUTOR_HOST

    def test_server_boot_validates_env(self, monkeypatch):
        """A typo'd $NOMAD_TPU_EXECUTOR fails the server BOOT, not the
        first dispatch (README Executor policy guarantee)."""
        from nomad_tpu.server import Server, ServerConfig

        monkeypatch.setenv("NOMAD_TPU_EXECUTOR", "gpu")
        with pytest.raises(ExecutorPolicyError):
            Server(ServerConfig(num_schedulers=0))

    def test_batch_runner_honors_force(self):
        """The fused batch path (BatchEvalRunner) obeys the same
        override: forced device must produce the same committed allocs
        as forced host."""
        from nomad_tpu.scheduler.batch import BatchEvalRunner

        placed = {}
        for executor in (EXECUTOR_HOST, EXECUTOR_DEVICE):
            h, jobs = _cluster(10, 4)
            with executor_override(executor):
                BatchEvalRunner(
                    h.state.snapshot(), h,
                    state_refresh=h.snapshot).process(
                    [make_eval(j) for j in jobs])
            placed[executor] = _plan_shape(h)
        assert placed[EXECUTOR_HOST] == placed[EXECUTOR_DEVICE]
