"""Data model unit tests (parity targets: nomad/structs/*_test.go)."""
import pytest

from nomad_tpu import mock
from nomad_tpu.structs import (
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    Allocation,
    Evaluation,
    Job,
    NetworkIndex,
    NetworkResource,
    Node,
    Plan,
    PlanResult,
    Resources,
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
    score_fit,
)
from nomad_tpu.structs.codec import JOB_REGISTER_REQUEST, decode, encode


def test_resources_superset():
    big = Resources(cpu=2000, memory_mb=2048, disk_mb=10000, iops=100)
    small = Resources(cpu=2000, memory_mb=1024, disk_mb=5000, iops=50)
    ok, dim = big.superset(small)
    assert ok and dim == ""
    ok, dim = small.superset(big)
    assert not ok and dim == "memory exhausted"
    ok, dim = Resources(cpu=1).superset(Resources(cpu=2))
    assert not ok and dim == "cpu exhausted"


def test_resources_add_merges_networks():
    r = Resources(networks=[NetworkResource(device="eth0", mbits=100)])
    r.add(Resources(cpu=100, networks=[NetworkResource(device="eth0", mbits=50)]))
    assert r.cpu == 100
    assert len(r.networks) == 1
    assert r.networks[0].mbits == 150
    r.add(Resources(networks=[NetworkResource(device="eth1", mbits=10)]))
    assert len(r.networks) == 2


def test_resources_copy_is_deep_for_networks():
    r = Resources(networks=[NetworkResource(device="eth0", reserved_ports=[1])])
    c = r.copy()
    c.networks[0].reserved_ports.append(2)
    assert r.networks[0].reserved_ports == [1]


def test_map_dynamic_ports():
    n = NetworkResource(reserved_ports=[80, 443, 30001, 30002],
                        dynamic_ports=["http", "https"])
    assert n.map_dynamic_ports() == {"http": 30001, "https": 30002}
    assert n.list_static_ports() == [80, 443]


def test_allocs_fit_and_score():
    n = mock.node()
    a = Allocation(
        id="a1",
        resources=Resources(cpu=2000, memory_mb=2048, disk_mb=10000, iops=50),
    )
    fit, dim, used = allocs_fit(n, [a])
    assert fit, dim
    # reserved (100, 256) + alloc
    assert used.cpu == 2100 and used.memory_mb == 2304
    score = score_fit(n, used)
    assert 0.0 <= score <= 18.0

    # Doubling the alloc exhausts memory (2*2048+256 < 8192 ok; cpu 4100 > 4000)
    fit, dim, _ = allocs_fit(n, [a, a])
    assert not fit and dim == "cpu exhausted"


def test_score_fit_extremes():
    n = mock.node()
    n.reserved = None
    empty = Resources()
    assert score_fit(n, empty) == 0.0  # 20 - 20
    full = Resources(cpu=4000, memory_mb=8192)
    assert score_fit(n, full) == 18.0  # perfect fit


def test_filter_terminal_and_remove():
    a1 = Allocation(id="1", desired_status=ALLOC_DESIRED_STATUS_RUN)
    a2 = Allocation(id="2", desired_status=ALLOC_DESIRED_STATUS_STOP)
    assert filter_terminal_allocs([a1, a2]) == [a1]
    assert remove_allocs([a1, a2], [a1]) == [a2]


def test_network_index_lifecycle():
    n = mock.node()
    idx = NetworkIndex()
    assert not idx.set_node(n)
    assert idx.avail_bandwidth["eth0"] == 1000
    assert 22 in idx.used_ports["192.168.0.100"]
    assert not idx.overcommitted()

    # Reserved port collision
    collide = idx.add_reserved(NetworkResource(
        device="eth0", ip="192.168.0.100", reserved_ports=[22]))
    assert collide


def test_assign_network_dynamic_ports():
    n = mock.node()
    idx = NetworkIndex()
    idx.set_node(n)
    ask = NetworkResource(mbits=100, dynamic_ports=["http", "https"])
    offer, err = idx.assign_network(ask)
    assert offer is not None, err
    assert offer.device == "eth0"
    assert len(offer.reserved_ports) == 2
    ports = offer.map_dynamic_ports()
    assert set(ports) == {"http", "https"}


def test_assign_network_bandwidth_exceeded():
    n = mock.node()
    idx = NetworkIndex()
    idx.set_node(n)
    offer, err = idx.assign_network(NetworkResource(mbits=5000))
    assert offer is None and err == "bandwidth exceeded"


def test_job_validate():
    j = mock.job()
    assert j.validate() == []
    j.priority = 300
    j.task_groups = []
    errs = j.validate()
    assert any("priority" in e for e in errs)
    assert any("task groups" in e for e in errs)


def test_plan_append_pop():
    plan = Plan()
    a = mock.alloc()
    plan.append_update(a, ALLOC_DESIRED_STATUS_STOP, "test")
    assert len(plan.node_update[a.node_id]) == 1
    assert plan.node_update[a.node_id][0].desired_status == \
        ALLOC_DESIRED_STATUS_STOP
    # original untouched (copy-on-append)
    assert a.desired_status == ALLOC_DESIRED_STATUS_RUN
    plan.pop_update(a)
    assert a.node_id not in plan.node_update
    assert plan.is_noop()


def test_plan_result_full_commit():
    plan = Plan()
    a = mock.alloc()
    plan.append_alloc(a)
    res = PlanResult(node_allocation={a.node_id: [a]})
    ok, expected, actual = res.full_commit(plan)
    assert ok and expected == 1 and actual == 1
    res2 = PlanResult()
    ok, expected, actual = res2.full_commit(plan)
    assert not ok and expected == 1 and actual == 0


def test_eval_make_plan_and_rolling():
    e = mock.eval()
    j = mock.job()
    j.all_at_once = True
    p = e.make_plan(j)
    assert p.eval_id == e.id and p.all_at_once
    nxt = e.next_rolling_eval(30.0)
    assert nxt.previous_eval == e.id and nxt.wait == 30.0


def test_struct_dict_roundtrip():
    j = mock.job()
    d = j.to_dict()
    j2 = Job.from_dict(d)
    assert j2.to_dict() == d
    assert j2.task_groups[0].tasks[0].resources.cpu == 500

    a = mock.alloc()
    a2 = Allocation.from_dict(a.to_dict())
    assert a2.to_dict() == a.to_dict()
    assert a2.job.id == a.job.id

    n = mock.node()
    assert Node.from_dict(n.to_dict()).to_dict() == n.to_dict()

    e = mock.eval()
    assert Evaluation.from_dict(e.to_dict()).to_dict() == e.to_dict()


def test_codec_roundtrip():
    j = mock.job()
    buf = encode(JOB_REGISTER_REQUEST, {"job": j.to_dict()})
    t, payload, ignorable = decode(buf)
    assert t == JOB_REGISTER_REQUEST and not ignorable
    assert Job.from_dict(payload["job"]).id == j.id


def test_codec_ignore_unknown_flag_masked():
    from nomad_tpu.structs.codec import IGNORE_UNKNOWN_TYPE_FLAG
    buf = encode(JOB_REGISTER_REQUEST | IGNORE_UNKNOWN_TYPE_FLAG, {})
    t, _, ignorable = decode(buf)
    assert t == JOB_REGISTER_REQUEST and ignorable


def test_alloc_terminal_is_desired_status_only():
    from nomad_tpu.structs import ALLOC_CLIENT_STATUS_FAILED
    a = Allocation(id="1", desired_status=ALLOC_DESIRED_STATUS_RUN,
                   client_status=ALLOC_CLIENT_STATUS_FAILED)
    assert not a.terminal_status()


def test_as_vector_dims():
    r = mock.alloc().resources
    vec = r.as_vector()
    assert vec[0] == 500 and vec[1] == 256
    assert vec[4] == 100  # mbits
    assert vec[5] == 2    # 1 reserved + 1 dynamic port


def test_copy_round_trips_every_field():
    """Hand-rolled copy() constructors must cover every dataclass field —
    this test fails when a new field is added but not copied."""
    import dataclasses
    from nomad_tpu.structs import NetworkResource, Resources

    def distinct_value(f, i):
        if f.type in ("int", int):
            return 1000 + i
        if f.type in ("str", str):
            return f"sentinel-{i}"
        if f.type in ("list", list):
            return [f"item-{i}"]
        if f.type in ("dict", dict):
            return {f"k{i}": i}
        return None

    for cls in (NetworkResource, Resources):
        kwargs = {}
        for i, f in enumerate(dataclasses.fields(cls)):
            v = distinct_value(f, i)
            if v is not None:
                kwargs[f.name] = v
        obj = cls(**{k: v for k, v in kwargs.items()
                     if k != "networks"})
        copied = obj.copy()
        for f in dataclasses.fields(cls):
            if f.name == "networks":
                continue
            assert getattr(copied, f.name) == getattr(obj, f.name), \
                f"{cls.__name__}.copy() drops field {f.name!r}"


def test_native_port_assignment_parity():
    """When the C++ extension is built, its port assignment matches the
    pure-Python path's semantics (collisions, dynamic picks, exhaustion)."""
    from nomad_tpu.utils.native import HAS_NATIVE
    import pytest as _pytest
    if not HAS_NATIVE:
        _pytest.skip("native extension not built")

    from nomad_tpu.structs import NetworkIndex, NetworkResource, Node, Resources

    node = Node(id="n", resources=Resources(networks=[NetworkResource(
        device="eth0", cidr="10.0.0.1/32", mbits=1000)]))
    idx = NetworkIndex()
    idx.set_node(node)
    idx.add_reserved(NetworkResource(device="eth0", ip="10.0.0.1",
                                     reserved_ports=[8080]))

    # Reserved-port collision -> rejected.
    offer, err = idx.assign_network(NetworkResource(
        mbits=10, reserved_ports=[8080]))
    assert offer is None

    # Dynamic ports avoid used + duplicates.
    offer, err = idx.assign_network(NetworkResource(
        mbits=10, reserved_ports=[9090], dynamic_ports=["a", "b"]))
    assert offer is not None
    assert offer.reserved_ports[0] == 9090
    assert len(set(offer.reserved_ports)) == 3
    assert all(20000 <= p < 60000 for p in offer.reserved_ports[1:])
    assert offer.map_dynamic_ports().keys() == {"a", "b"}

    # Bandwidth exceeded.
    offer, err = idx.assign_network(NetworkResource(mbits=10_000))
    assert offer is None and "bandwidth" in err
