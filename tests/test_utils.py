"""Utility-module suite: duration strings (one implementation shared by
the jobspec parser and the HTTP ?wait layer), version encoding/
constraint semantics (the compiled-mask twin of go-version), and
gc_pause nesting."""
from __future__ import annotations

import gc

import pytest

from nomad_tpu.utils.duration import parse_duration
from nomad_tpu.utils.gctune import gc_pause
from nomad_tpu.utils.versions import (
    check_constraint,
    parse_constraint,
    parse_version,
)


def test_parse_duration_units():
    cases = [("500ms", 0.5), ("30s", 30.0), ("1m", 60.0), ("2h", 7200.0),
             ("1.5s", 1.5), ("90", 90.0), (15, 15.0), (0.25, 0.25)]
    for value, want in cases:
        assert parse_duration(value) == want, value


def test_parse_duration_rejects_garbage():
    for bad in ("", "fast", "10x", "s", "1d", "-5s"):
        with pytest.raises(ValueError):
            parse_duration(bad)


def test_version_parse_and_order():
    assert parse_version("banana") is None
    assert parse_version("1.2.3") is not None
    # go-version semantics: pre-releases sort before the release.
    assert check_constraint("1.2.3", ">= 1.2.3")
    assert check_constraint("1.2.3", "> 1.2.2")
    assert not check_constraint("1.2.3", "> 1.2.3")
    assert check_constraint("1.2.3-beta1", "< 1.2.3")
    assert check_constraint("v1.4.0", ">= 1.2, < 2.0")  # v-prefix + multi
    assert not check_constraint("2.1.0", ">= 1.2, < 2.0")


def test_parse_constraint_rejects_unparseable_versions():
    # Pessimistic-operator and encode-ordering semantics live in
    # test_scheduler.py (test_version_constraints /
    # test_version_encoding_order); this covers only the round-5
    # parse-time rejection.
    assert parse_constraint(">= banana") is None
    assert parse_constraint(">= 1.0, < nope") is None
    got = parse_constraint(">= 1.0, < 2.0")
    assert got == [(">=", "1.0"), ("<", "2.0")]


def test_gc_pause_overlapping_threads():
    """Refcounted pause: one thread's exit must NOT re-enable gc while
    another thread's burst is still inside (pre-fix, the per-caller
    save/restore did exactly that — and an interleaved save could then
    leave gc off for the rest of the process)."""
    import threading

    gc.enable()
    entered = threading.Event()
    release = threading.Event()

    def hold():
        with gc_pause():
            entered.set()
            release.wait(5)

    t = threading.Thread(target=hold)
    t.start()
    assert entered.wait(5)
    with gc_pause():
        assert not gc.isenabled()
    # Inner pause exited, outer thread still bursting: stays disabled.
    assert not gc.isenabled()
    release.set()
    t.join(5)
    assert gc.isenabled()


def test_gc_pause_nesting_restores_state():
    # Own the precondition: an abandoned burst thread elsewhere in the
    # suite may have left gc off — this test is about restore semantics,
    # not suite-global hygiene.
    gc.enable()
    assert gc.isenabled()
    with gc_pause():
        assert not gc.isenabled()
        with gc_pause():  # nest-safe
            assert not gc.isenabled()
        assert not gc.isenabled()
    assert gc.isenabled()
