"""Pipelined eval processing: window of in-flight device dispatches.

The pipelined runner must be semantically identical to processing the
same evals one at a time — it only changes WHEN results are collected,
never what is planned.
"""
from __future__ import annotations

import nomad_tpu.mock as mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner
from nomad_tpu.structs import (
    EVAL_TRIGGER_JOB_REGISTER,
    Evaluation,
    allocs_fit,
    generate_uuid,
)


def make_eval(job):
    return Evaluation(
        id=generate_uuid(), priority=job.priority, type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
    )


def _cluster(n_nodes: int, n_jobs: int, count: int = 3):
    h = Harness()
    nodes = [mock.node(i) for i in range(n_nodes)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    jobs = []
    for _ in range(n_jobs):
        j = mock.job()
        j.task_groups[0].count = count
        h.state.upsert_job(h.next_index(), j)
        jobs.append(j)
    return h, nodes, jobs


def test_pipeline_matches_sequential_processing():
    """Same snapshot, same evals: the pipelined runner's plans must equal
    one-at-a-time processing (placement counts and per-job spread)."""
    h, nodes, jobs = _cluster(16, 6)
    snap = h.state.snapshot()

    runner = PipelinedEvalRunner(snap, h, depth=3)
    runner.process([make_eval(j) for j in jobs])
    piped = {p.node_allocation and sorted(
        a.job_id for v in p.node_allocation.values() for a in v)[0]:
        sum(len(v) for v in p.node_allocation.values())
        for p in h.plans}

    h2, _, _ = _cluster(16, 0)
    for j in jobs:
        h2.state.upsert_job(h2.next_index(), j)
    for j in jobs:
        h2.process("jax-binpack", make_eval(j))
    solo = {p.node_allocation and sorted(
        a.job_id for v in p.node_allocation.values() for a in v)[0]:
        sum(len(v) for v in p.node_allocation.values())
        for p in h2.plans}

    assert len(h.plans) == len(jobs)
    assert piped == solo
    assert all(e.status == "complete" for e in h.evals)
    assert len(runner.latencies) == len(jobs)


def test_pipeline_depth_one_equals_depth_many():
    h1, _, jobs = _cluster(12, 5)
    snap1 = h1.state.snapshot()
    PipelinedEvalRunner(snap1, h1, depth=1).process(
        [make_eval(j) for j in jobs])

    h2 = Harness()
    for i in range(12):
        h2.state.upsert_node(h2.next_index(), mock.node(i))
    for j in jobs:
        h2.state.upsert_job(h2.next_index(), j)
    PipelinedEvalRunner(h2.state.snapshot(), h2, depth=8).process(
        [make_eval(j) for j in jobs])

    def shape(plans):
        return sorted(
            (sum(len(v) for v in p.node_allocation.values()),
             len(p.failed_allocs)) for p in plans)

    assert shape(h1.plans) == shape(h2.plans)


def test_pipeline_plans_fit():
    h, nodes, jobs = _cluster(4, 3, count=2)
    for j in jobs:
        j.task_groups[0].tasks[0].resources.cpu = 1000
    runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=4)
    runner.process([make_eval(j) for j in jobs])
    by_node = {n.id: n for n in nodes}
    for plan in h.plans:
        for node_id, allocs in plan.node_allocation.items():
            fit, dim, _ = allocs_fit(by_node[node_id], allocs)
            assert fit, dim


def test_pipeline_serializes_same_job_evals():
    h, _, jobs = _cluster(8, 1, count=4)
    job = jobs[0]
    runner = PipelinedEvalRunner(
        h.state.snapshot(), h, depth=4,
        state_refresh=lambda: h.state.snapshot())
    runner.process([make_eval(job), make_eval(job)])
    live = [a for a in h.state.allocs_by_job(job.id)
            if not a.terminal_status()]
    assert len(live) == 4


def test_pipeline_windowed_drain_matches_sequential():
    """Drive the drain stage's windowed finish DIRECTLY with the whole
    stream as one window (shared uuid slab + one native
    bulk_finish_many call) and assert the plans equal one-at-a-time
    processing — the windowed path must be invisible to semantics.
    Deterministic on purpose: building the window by hand (front-stage
    steps run inline) instead of racing the two threads."""
    import time as _time

    from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner, _Item

    h, _, jobs = _cluster(16, 6)
    runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=8)
    window = []
    for j in jobs:
        start = _time.perf_counter()
        sched = runner._begin_eval(make_eval(j), finish_noop=False)
        assert sched is not None and sched.deferred is not None
        place, args = sched.deferred
        handles = sched.dispatch_device(args, pipelined=True)
        window.append(_Item(sched, place, args, handles, start))
    runner._drain_window(window)
    assert runner.windows == [len(jobs)]
    assert len(runner.latencies) == len(jobs)

    h2, _, _ = _cluster(16, 0)
    for j in jobs:
        h2.state.upsert_job(h2.next_index(), j)
    for j in jobs:
        h2.process("jax-binpack", make_eval(j))

    def shape(plans):
        return sorted(
            (sum(len(v) for v in p.node_allocation.values()),
             len(p.failed_allocs)) for p in plans)

    assert shape(h.plans) == shape(h2.plans)
    assert all(e.status == "complete" for e in h.evals)


def test_pipeline_drain_error_propagates():
    """A failure in the drain stage must surface to the caller, not
    hang the front stage on a full window."""
    import pytest

    from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner as PR

    class Boom(RuntimeError):
        pass

    class FailingDrain(PR):
        def _drain_window(self, window):
            raise Boom("drain stage failure")

    h, _, jobs = _cluster(8, 4)
    runner = FailingDrain(h.state.snapshot(), h, depth=2)
    with pytest.raises(Boom):
        runner.process([make_eval(j) for j in jobs])


def test_pipeline_drain_error_after_sentinel_in_window():
    """Regression: when the window-gather has already consumed the
    _STOP sentinel and THEN the window fails, the error path must not
    block waiting for a sentinel that will never come (that was a
    deadlock: the front is already in drain.join())."""
    import queue as _queue
    import threading as _threading

    from nomad_tpu.scheduler.pipeline import (PipelinedEvalRunner as PR,
                                              _Item, _STOP)

    class Boom(RuntimeError):
        pass

    class FailingDrain(PR):
        def _drain_window(self, window):
            raise Boom("fails after sentinel consumed")

    h, _, _jobs = _cluster(4, 0)
    runner = FailingDrain(h.state.snapshot(), h, depth=4)
    q: _queue.Queue = _queue.Queue()
    q.put(_Item(None, None, None, None, 0.0))
    q.put(_STOP)  # gathered into the same window as the item
    t = _threading.Thread(target=runner._drain_loop, args=(q,),
                          daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "drain loop deadlocked after sentinel"
    with runner._err_lock:
        assert isinstance(runner._drain_err, Boom)


def test_pipeline_handles_migrations_and_noops():
    """Evals whose plans carry deltas (node drain -> migrate) and no-op
    evals pipeline like any other."""
    from nomad_tpu.structs import EVAL_TRIGGER_NODE_UPDATE

    h, nodes, jobs = _cluster(8, 2)
    for j in jobs:
        h.process("jax-binpack", make_eval(j))
    for p in list(h.plans):
        allocs = [a for v in p.node_allocation.values() for a in v]
        h.state.upsert_allocs(h.next_index(), allocs)
    h.plans.clear()

    # Drain one node: its allocs must migrate.
    h.state.update_node_drain(h.next_index(), nodes[0].id, True)
    evs = []
    for j in jobs:
        ev = make_eval(j)
        ev.triggered_by = EVAL_TRIGGER_NODE_UPDATE
        evs.append(ev)
    runner = PipelinedEvalRunner(h.state.snapshot(), h, depth=2)
    runner.process(evs)
    assert all(e.status == "complete" for e in h.evals)
    for plan in h.plans:
        for node_id in plan.node_allocation:
            assert node_id != nodes[0].id, "placed onto draining node"
