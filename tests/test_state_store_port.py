"""Port of the reference's state_store_test.go allocation/index table
(/root/reference/nomad/state/state_store_test.go) against
state/store.py, extended to the group-commit batched upsert:

  1. UpsertAlloc / UpdateAlloc / EvictAlloc semantics — create/modify
     index stamping, client-field preservation, eviction as an upsert
     (TestStateStore_UpsertAlloc_Alloc / _UpdateAlloc_Alloc /
     _EvictAlloc_Alloc).
  2. Secondary-index queries — AllocsByNode / AllocsByJob /
     AllocsByEval / Allocs iteration (TestStateStore_AllocsByNode /
     _Allocs).
  3. Batched vs single upserts: upsert_allocs_batched applied in one
     lock hold must be byte-identical to per-item upsert_allocs calls,
     including index monotonicity and watch notification.
  4. Snapshot round-trip of batch-applied allocs through the FSM
     (TestStateStore_RestoreAlloc shape, driven by the
     PLAN_BATCH_APPLY_REQUEST log entry).
"""
from __future__ import annotations

from nomad_tpu import mock
from nomad_tpu.server.fsm import NomadFSM
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_EVICT,
    codec,
)
from nomad_tpu.structs.codec import PLAN_BATCH_APPLY_REQUEST


def image(store) -> tuple:
    """Byte-comparable store image: every alloc's serialized form plus
    the table indexes."""
    return (
        {a.id: a.to_dict() for a in store.allocs()},
        {t: store.get_index(t)
         for t in ("nodes", "jobs", "evals", "allocs")},
    )


# ---------------------------------------------------------------------------
# 1. the upstream alloc table
# ---------------------------------------------------------------------------

class TestAllocTable:
    def test_upsert_alloc(self):
        """TestStateStore_UpsertAlloc_Alloc: stored copy, both indexes
        stamped, table index bumped."""
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1000, [a])
        out = s.alloc_by_id(a.id)
        assert out is not None and out is not a
        assert out.create_index == 1000 and out.modify_index == 1000
        assert s.get_index("allocs") == 1000

    def test_update_alloc_preserves_create_index(self):
        """TestStateStore_UpdateAlloc_Alloc: a re-upsert moves
        modify_index only."""
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1000, [a])
        update = a.copy()
        update.name = "updated"
        s.upsert_allocs(1001, [update])
        out = s.alloc_by_id(a.id)
        assert out.name == "updated"
        assert out.create_index == 1000 and out.modify_index == 1001
        assert s.get_index("allocs") == 1001

    def test_evict_alloc(self):
        """TestStateStore_EvictAlloc_Alloc: eviction is an upsert with a
        terminal desired status — the record stays queryable."""
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1000, [a])
        evicted = a.copy()
        evicted.desired_status = ALLOC_DESIRED_STATUS_EVICT
        s.upsert_allocs(1001, [evicted])
        out = s.alloc_by_id(a.id)
        assert out.desired_status == ALLOC_DESIRED_STATUS_EVICT
        assert out.terminal_status()
        assert out.create_index == 1000 and out.modify_index == 1001

    def test_allocs_by_node_job_eval(self):
        """TestStateStore_AllocsByNode + the job/eval secondary
        indexes."""
        s = StateStore()
        allocs = []
        for i in range(10):
            a = mock.alloc()
            a.node_id = "the-node"
            allocs.append(a)
        s.upsert_allocs(1000, allocs)
        by_node = s.allocs_by_node("the-node")
        assert sorted(x.id for x in by_node) == \
            sorted(a.id for a in allocs)
        one = allocs[3]
        assert [x.id for x in s.allocs_by_job(one.job_id)
                if x.id == one.id] == [one.id]
        assert [x.id for x in s.allocs_by_eval(one.eval_id)] == [one.id]

    def test_allocs_iteration(self):
        """TestStateStore_Allocs: full-table iteration sees every
        record."""
        s = StateStore()
        allocs = [mock.alloc() for _ in range(10)]
        s.upsert_allocs(1000, allocs)
        assert sorted(a.id for a in s.allocs()) == \
            sorted(a.id for a in allocs)


# ---------------------------------------------------------------------------
# 2. batched upsert: byte parity with singles, index monotonicity
# ---------------------------------------------------------------------------

class TestBatchedUpsert:
    def _stream(self):
        """A mixed stream: fresh placements on two nodes, a client-side
        update in between, an in-place replacement, and an eviction."""
        a1, a2, a3 = mock.alloc(), mock.alloc(), mock.alloc()
        a2.node_id = a1.node_id
        repl = a1.copy()
        repl.name = "replaced"
        evict = a3.copy()
        evict.desired_status = ALLOC_DESIRED_STATUS_EVICT
        return [
            (2000, [a1, a2]),
            (2001, [a3]),
            (2002, [repl, evict]),
        ]

    def test_batched_equals_singles(self):
        items = self._stream()
        s_single, s_batch = StateStore(), StateStore()
        for index, allocs in items:
            s_single.upsert_allocs(index, allocs)
        s_batch.upsert_allocs_batched(items)
        assert image(s_single) == image(s_batch)

    def test_batched_preserves_client_fields(self):
        """The scheduler-authoritative merge holds inside a batch: a
        batched rewrite must not clobber client-owned fields."""
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1000, [a])
        client_view = s.alloc_by_id(a.id).copy()
        client_view.client_status = ALLOC_CLIENT_STATUS_RUNNING
        client_view.client_description = "up"
        s.update_alloc_from_client(1001, client_view)

        sched_view = a.copy()
        sched_view.client_status = "pending"
        s.upsert_allocs_batched([(1002, [sched_view])])
        out = s.alloc_by_id(a.id)
        assert out.client_status == ALLOC_CLIENT_STATUS_RUNNING
        assert out.client_description == "up"
        assert out.create_index == 1000 and out.modify_index == 1002

    def test_index_monotonicity_across_mixed_writes(self):
        """The allocs table index only ever moves forward, through
        singles and batches alike, and lands on the batch's last
        sub-index."""
        s = StateStore()
        seen = [s.get_index("allocs")]
        s.upsert_allocs(1000, [mock.alloc()])
        seen.append(s.get_index("allocs"))
        s.upsert_allocs_batched([(1001, [mock.alloc()]),
                                 (1002, [mock.alloc()]),
                                 (1003, [])])  # empty item: no bump
        seen.append(s.get_index("allocs"))
        s.upsert_allocs(1004, [mock.alloc()])
        seen.append(s.get_index("allocs"))
        assert seen == [0, 1000, 1002, 1004]
        assert seen == sorted(seen)
        assert s.latest_index() == 1004

    def test_batched_last_writer_wins_in_order(self):
        """Two sub-plans touching the same alloc id: the LATER item's
        version lands, exactly as sequential upserts in eval order."""
        s = StateStore()
        a = mock.alloc()
        v1 = a.copy()
        v1.name = "first"
        v2 = a.copy()
        v2.name = "second"
        s.upsert_allocs_batched([(3000, [v1]), (3001, [v2])])
        out = s.alloc_by_id(a.id)
        assert out.name == "second"
        assert out.create_index == 3000 and out.modify_index == 3001

    def test_batched_fires_watches_once_per_touched_node(self):
        s = StateStore()
        a1, a2 = mock.alloc(), mock.alloc()
        ev_all = s.watch.watch(("allocs",))
        ev_n1 = s.watch.watch(("alloc-node", a1.node_id))
        ev_n2 = s.watch.watch(("alloc-node", a2.node_id))
        ev_other = s.watch.watch(("alloc-node", "untouched"))
        s.upsert_allocs_batched([(1000, [a1]), (1001, [a2])])
        assert ev_all.is_set() and ev_n1.is_set() and ev_n2.is_set()
        assert not ev_other.is_set()

    def test_batched_respects_snapshot_isolation(self):
        s = StateStore()
        a = mock.alloc()
        s.upsert_allocs(1000, [a])
        snap = s.snapshot()
        b = mock.alloc()
        b.node_id = a.node_id
        s.upsert_allocs_batched([(1001, [b])])
        assert len(snap.allocs_by_node(a.node_id)) == 1
        assert len(s.allocs_by_node(a.node_id)) == 2
        assert snap.get_index("allocs") == 1000

    def test_batched_feeds_the_mirror_changelog(self):
        """Each batched sub-plan logs its own (index, ids) changelog
        entry so the incremental usage mirror can sync by delta."""
        s = StateStore()
        a1, a2 = mock.alloc(), mock.alloc()
        s.upsert_allocs_batched([(1000, [a1]), (1001, [a2])])
        log = s._t.alloc_log
        assert (1000, (a1.id,)) in log
        assert (1001, (a2.id,)) in log


# ---------------------------------------------------------------------------
# 3. snapshot round-trip of batch-applied allocs
# ---------------------------------------------------------------------------

class TestBatchSnapshotRoundTrip:
    def test_fsm_batch_apply_then_snapshot_restore(self):
        """TestStateStore_RestoreAlloc shape, driven end-to-end: a
        PLAN_BATCH_APPLY_REQUEST log entry lands allocs in state; a
        snapshot/restore round trip preserves them byte-for-byte,
        indexes included."""
        fsm = NomadFSM()
        node = mock.node()
        fsm.apply(10, codec.encode(codec.NODE_REGISTER_REQUEST,
                                   {"node": node.to_dict()}))
        allocs_a = [mock.alloc() for _ in range(3)]
        allocs_b = [mock.alloc() for _ in range(2)]
        for a in allocs_a + allocs_b:
            a.node_id = node.id
        entry = codec.encode(
            PLAN_BATCH_APPLY_REQUEST,
            {"plans": [{"alloc": [a.to_dict() for a in allocs_a]},
                       {"alloc": [a.to_dict() for a in allocs_b]}]})
        fsm.apply(11, entry)
        assert len(fsm.state.allocs_by_node(node.id)) == 5
        before = image(fsm.state)

        blob = fsm.snapshot()
        fresh = NomadFSM()
        fresh.restore(blob)
        assert image(fresh.state) == before
        out = sorted(fresh.state.allocs(), key=lambda a: a.id)
        assert all(a.create_index == 11 and a.modify_index == 11
                   for a in out)

    def test_batch_apply_is_atomic_on_malformed_subplan(self):
        """A malformed sub-plan rejects the whole entry with the store
        untouched (alloc construction precedes any state move)."""
        import pytest

        fsm = NomadFSM()
        good = mock.alloc()
        entry = codec.encode(
            PLAN_BATCH_APPLY_REQUEST,
            {"plans": [{"alloc": [good.to_dict()]},
                       {"allocs_typo": []}]})
        with pytest.raises(Exception):
            fsm.apply(11, entry)
        assert fsm.state.alloc_by_id(good.id) is None
        assert fsm.state.get_index("allocs") == 0
