"""Tier-1 multichip lane: the sharded-parity suite, hermetically.

conftest force-configures 8 virtual devices for the in-process suite,
but that depends on import order and the caller's shell.  This rig
re-drives every ``-m multichip`` test in a SUBPROCESS with the XLA
flags pinned (the same discipline tests/test_graft_entry.py applies to
the driver dry runs), so a mesh regression fails tier-1 even in an
environment whose outer flags differ — before a TPU ever sees it.
"""
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The sharded-parity suite: every test in these modules is marked
# multichip (module-level pytestmark).
SUITE = ("tests/test_parallel.py", "tests/test_mesh_resident.py",
         "tests/test_node_slab.py")


def test_multichip_lane_runs_sharded_parity_suite():
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    # Force EXACTLY 8 virtual devices, replacing any pre-existing count
    # so the lane is hermetic in any shell.
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    # The nested suite runs its own interpreter; the outer session's
    # sanitizers already cover this code in-process.
    env["NOMAD_TPU_SANITIZERS"] = "0"
    r = subprocess.run(
        [sys.executable, "-m", "pytest", *SUITE, "-m", "multichip",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-2000:])
    m = re.search(r"(\d+) passed", r.stdout)
    assert m, r.stdout[-2000:]
    # The lane must actually run the suite, not deselect it away.
    assert int(m.group(1)) >= 15, r.stdout[-2000:]
