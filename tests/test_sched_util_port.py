"""Port of the reference scheduler's util tables
(/root/reference/scheduler/util_test.go): diffAllocs, taintedNodes and
shuffleNodes, re-expressed over the repo's mocks — same case sets, same
bucket counts, same membership assertions as the Go tests.

diff_allocs buckets (scheduler/util.py):
  stop     — existing alloc whose name is no longer required,
  migrate  — required, but its node is tainted (down/draining/missing),
  update   — required on a clean node, but the alloc was created from an
             older job version (modify_index mismatch),
  ignore   — required, clean node, current job version,
  place    — required names with no existing alloc.
"""
from __future__ import annotations

import random

import nomad_tpu.mock as mock
from nomad_tpu.scheduler.util import (
    diff_allocs,
    materialize_task_groups,
    shuffle_nodes,
    tainted_nodes,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import NODE_STATUS_DOWN, generate_uuid


class TestDiffAllocs:
    """util_test.go TestDiffAllocs: 10 required web instances, 4
    existing allocs that hit each non-place bucket exactly once, and
    the remaining 7 required names placed."""

    def test_table(self):
        job = mock.job()          # my-job: web x 10, modify_index 99
        required = materialize_task_groups(job)
        assert len(required) == 10
        assert "my-job.web[0]" in required

        old_job = mock.job()
        old_job.modify_index = job.modify_index - 1

        tainted = {"dead": True}

        ignore_alloc = mock.alloc()
        ignore_alloc.id = generate_uuid()
        ignore_alloc.node_id = "zip"
        ignore_alloc.name = "my-job.web[0]"
        ignore_alloc.job = job

        stop_alloc = mock.alloc()
        stop_alloc.id = generate_uuid()
        stop_alloc.node_id = "zip"
        stop_alloc.name = "my-job.web[10]"   # beyond count: not required
        stop_alloc.job = old_job

        migrate_alloc = mock.alloc()
        migrate_alloc.id = generate_uuid()
        migrate_alloc.node_id = "dead"
        migrate_alloc.name = "my-job.web[2]"
        migrate_alloc.job = old_job

        update_alloc = mock.alloc()
        update_alloc.id = generate_uuid()
        update_alloc.node_id = "zip"
        update_alloc.name = "my-job.web[1]"
        update_alloc.job = old_job

        allocs = [ignore_alloc, stop_alloc, migrate_alloc, update_alloc]
        diff = diff_allocs(job, tainted, dict(required), allocs)

        assert [t.alloc for t in diff.ignore] == [ignore_alloc]
        assert [t.alloc for t in diff.stop] == [stop_alloc]
        assert [t.alloc for t in diff.migrate] == [migrate_alloc]
        assert [t.alloc for t in diff.update] == [update_alloc]

        # Everything required and not existing gets placed: 10 - web[0]
        # (ignored) - web[1] (updated) - web[2] (migrated) = 7.  The
        # stopped web[10] does not count against required names.
        assert len(diff.place) == 7
        placed = {t.name for t in diff.place}
        assert placed == {f"my-job.web[{i}]" for i in range(10)} - {
            "my-job.web[0]", "my-job.web[1]", "my-job.web[2]"}
        for t in diff.place:
            assert t.alloc is None
            assert t.task_group is job.task_groups[0]

    def test_update_bucket_carries_new_task_group(self):
        # The update tuple's task_group is the *new* job's group (the
        # required-map value), so in-place updates re-resource against
        # the new definition — same contract the Go diff relies on.
        job = mock.job()
        old_job = mock.job()
        old_job.modify_index = job.modify_index - 1
        a = mock.alloc()
        a.node_id = "zip"
        a.name = "my-job.web[3]"
        a.job = old_job
        diff = diff_allocs(job, {}, dict(materialize_task_groups(job)),
                           [a])
        (tup,) = diff.update
        assert tup.task_group is job.task_groups[0]


class TestTaintedNodes:
    """util_test.go TestTaintedNodes: ready node clean, draining node
    tainted, down node tainted, missing node tainted; one map entry per
    distinct node referenced by the allocs."""

    def test_table(self):
        store = StateStore()
        node1 = mock.node()                      # ready
        node2 = mock.node()
        node2.drain = True                       # draining
        node3 = mock.node()
        node3.status = NODE_STATUS_DOWN          # down
        for i, n in enumerate((node1, node2, node3)):
            store.upsert_node(1000 + i, n)

        missing_id = "12345678-abcd-efab-cdef-123456789abc"
        allocs = []
        for nid in (node1.id, node2.id, node3.id, missing_id):
            a = mock.alloc()
            a.node_id = nid
            allocs.append(a)

        tainted = tainted_nodes(store.snapshot(), allocs)
        assert len(tainted) == 4
        assert tainted[node1.id] is False
        assert tainted[node2.id] is True
        assert tainted[node3.id] is True
        assert tainted[missing_id] is True

    def test_dedupes_per_node(self):
        # Two allocs on the same node produce one map entry (the Go
        # loop's `if _, ok := out[alloc.NodeID]; ok { continue }`).
        store = StateStore()
        node = mock.node()
        store.upsert_node(1000, node)
        a1, a2 = mock.alloc(), mock.alloc()
        a1.node_id = node.id
        a2.node_id = node.id
        tainted = tainted_nodes(store.snapshot(), [a1, a2])
        assert tainted == {node.id: False}


class TestShuffleNodes:
    """util_test.go TestShuffleNodes: order changes, membership and
    length don't."""

    def test_table(self):
        nodes = [mock.node(i) for i in range(10)]
        orig = list(nodes)
        # Seeded rng: deterministic, and guaranteed != identity for
        # this seed/length (checked below rather than assumed).
        shuffle_nodes(nodes, rng=random.Random(171))
        assert nodes != orig
        assert len(nodes) == len(orig)
        assert {n.id for n in nodes} == {n.id for n in orig}
