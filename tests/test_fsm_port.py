"""Port of the reference's fsm_test.go table (nomad/fsm_test.go).

Continues the plan_apply/worker/heartbeat/eval_broker port series: one
log-apply test per message type (the FSM is the only writer of durable
state, so each dispatch path deserves its own proof), the unknown-type
contract, and the snapshot/restore round-trip
(fsm_test.go TestFSM_SnapshotRestore_*).
"""
from __future__ import annotations

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.fsm import NomadFSM
from nomad_tpu.structs import codec
from nomad_tpu.structs.codec import (
    ALLOC_CLIENT_UPDATE_REQUEST,
    ALLOC_UPDATE_REQUEST,
    EVAL_DELETE_REQUEST,
    EVAL_UPDATE_REQUEST,
    IGNORE_UNKNOWN_TYPE_FLAG,
    JOB_DEREGISTER_REQUEST,
    JOB_REGISTER_REQUEST,
    NODE_DEREGISTER_REQUEST,
    NODE_REGISTER_REQUEST,
    NODE_UPDATE_DRAIN_REQUEST,
    NODE_UPDATE_STATUS_REQUEST,
)


def apply(fsm: NomadFSM, index: int, msg_type: int, payload: dict):
    return fsm.apply(index, codec.encode(msg_type, payload))


# ---------------------------------------------------------------------------
# per-message-type log applies (fsm_test.go:49-353)
# ---------------------------------------------------------------------------

class TestApplyTable:
    def test_upsert_node(self):
        fsm = NomadFSM()
        node = mock.node()
        apply(fsm, 1, NODE_REGISTER_REQUEST, {"node": node.to_dict()})
        got = fsm.state.node_by_id(node.id)
        assert got is not None and got.name == node.name
        assert fsm.state.get_index("nodes") == 1

    def test_deregister_node(self):
        fsm = NomadFSM()
        node = mock.node()
        apply(fsm, 1, NODE_REGISTER_REQUEST, {"node": node.to_dict()})
        apply(fsm, 2, NODE_DEREGISTER_REQUEST, {"node_id": node.id})
        assert fsm.state.node_by_id(node.id) is None
        assert fsm.state.get_index("nodes") == 2

    def test_update_node_status(self):
        fsm = NomadFSM()
        node = mock.node()
        apply(fsm, 1, NODE_REGISTER_REQUEST, {"node": node.to_dict()})
        apply(fsm, 2, NODE_UPDATE_STATUS_REQUEST,
              {"node_id": node.id, "status": "down"})
        got = fsm.state.node_by_id(node.id)
        assert got.status == "down"
        assert got.modify_index == 2

    def test_update_node_drain(self):
        fsm = NomadFSM()
        node = mock.node()
        apply(fsm, 1, NODE_REGISTER_REQUEST, {"node": node.to_dict()})
        apply(fsm, 2, NODE_UPDATE_DRAIN_REQUEST,
              {"node_id": node.id, "drain": True})
        assert fsm.state.node_by_id(node.id).drain is True

    def test_register_job(self):
        fsm = NomadFSM()
        job = mock.job()
        apply(fsm, 1, JOB_REGISTER_REQUEST, {"job": job.to_dict()})
        got = fsm.state.job_by_id(job.id)
        assert got is not None and got.name == job.name
        assert fsm.state.get_index("jobs") == 1

    def test_deregister_job(self):
        fsm = NomadFSM()
        job = mock.job()
        apply(fsm, 1, JOB_REGISTER_REQUEST, {"job": job.to_dict()})
        apply(fsm, 2, JOB_DEREGISTER_REQUEST, {"job_id": job.id})
        assert fsm.state.job_by_id(job.id) is None

    def test_update_eval(self):
        fsm = NomadFSM()
        ev = mock.eval()
        apply(fsm, 1, EVAL_UPDATE_REQUEST, {"evals": [ev.to_dict()]})
        got = fsm.state.eval_by_id(ev.id)
        assert got is not None and got.priority == ev.priority
        assert fsm.state.get_index("evals") == 1

    def test_pending_eval_enters_enabled_broker(self):
        """fsm.go:243-250: pending evals (re-)enter the broker on apply,
        leader only (the broker no-ops unless enabled)."""
        broker = EvalBroker(nack_timeout=5, delivery_limit=2)
        broker.set_enabled(True)
        fsm = NomadFSM(eval_broker=broker)
        ev = mock.eval()
        apply(fsm, 1, EVAL_UPDATE_REQUEST, {"evals": [ev.to_dict()]})
        assert broker.stats()["total_ready"] == 1

    def test_pending_eval_skips_disabled_broker(self):
        broker = EvalBroker(nack_timeout=5, delivery_limit=2)
        fsm = NomadFSM(eval_broker=broker)
        ev = mock.eval()
        apply(fsm, 1, EVAL_UPDATE_REQUEST, {"evals": [ev.to_dict()]})
        assert broker.stats()["total_ready"] == 0

    def test_delete_eval(self):
        fsm = NomadFSM()
        ev = mock.eval()
        apply(fsm, 1, EVAL_UPDATE_REQUEST, {"evals": [ev.to_dict()]})
        apply(fsm, 2, EVAL_DELETE_REQUEST,
              {"evals": [ev.id], "allocs": []})
        assert fsm.state.eval_by_id(ev.id) is None

    def test_upsert_allocs(self):
        fsm = NomadFSM()
        alloc = mock.alloc()
        apply(fsm, 1, ALLOC_UPDATE_REQUEST, {"alloc": [alloc.to_dict()]})
        got = fsm.state.alloc_by_id(alloc.id)
        assert got is not None and got.node_id == alloc.node_id
        assert fsm.state.get_index("allocs") == 1

    def test_client_update_preserves_server_fields(self):
        """fsm_test.go TestFSM_UpdateAllocFromClient: the client owns
        client_status/task_states; the server's desired_status and job
        survive the merge."""
        fsm = NomadFSM()
        alloc = mock.alloc()
        apply(fsm, 1, ALLOC_UPDATE_REQUEST, {"alloc": [alloc.to_dict()]})
        update = alloc.copy()
        update.client_status = "failed"
        update.job = None  # the client strips the job payload
        apply(fsm, 2, ALLOC_CLIENT_UPDATE_REQUEST,
              {"alloc": [update.to_dict()]})
        got = fsm.state.alloc_by_id(alloc.id)
        assert got.client_status == "failed"
        assert got.desired_status == alloc.desired_status
        assert got.job is not None, "server-side job payload was lost"
        assert got.modify_index == 2

    def test_unknown_type_errors_unless_flagged_ignorable(self):
        fsm = NomadFSM()
        with pytest.raises(ValueError, match="unknown type"):
            fsm.apply(1, codec.encode(101, {}))
        # The ignore flag (structs.go:40-43) makes it a no-op instead.
        assert fsm.apply(
            2, codec.encode(IGNORE_UNKNOWN_TYPE_FLAG | 101, {})) is None

    def test_apply_hook_fires_per_entry(self):
        seen = []
        fsm = NomadFSM(on_apply=lambda idx, t, payload:
                       seen.append((idx, t)))
        node = mock.node()
        apply(fsm, 7, NODE_REGISTER_REQUEST, {"node": node.to_dict()})
        assert seen == [(7, NODE_REGISTER_REQUEST)]


# ---------------------------------------------------------------------------
# snapshot / restore round-trip (fsm_test.go:355-520)
# ---------------------------------------------------------------------------

def populated_fsm() -> tuple[NomadFSM, dict]:
    fsm = NomadFSM()
    nodes = [mock.node(i) for i in range(2)]
    jobs = [mock.job() for _ in range(2)]
    evals = [mock.eval() for _ in range(2)]
    allocs = [mock.alloc() for _ in range(2)]
    index = 0
    for n in nodes:
        index += 1
        apply(fsm, index, NODE_REGISTER_REQUEST, {"node": n.to_dict()})
    for j in jobs:
        index += 1
        apply(fsm, index, JOB_REGISTER_REQUEST, {"job": j.to_dict()})
    index += 1
    apply(fsm, index, EVAL_UPDATE_REQUEST,
          {"evals": [e.to_dict() for e in evals]})
    index += 1
    apply(fsm, index, ALLOC_UPDATE_REQUEST,
          {"alloc": [a.to_dict() for a in allocs]})
    return fsm, {"nodes": nodes, "jobs": jobs, "evals": evals,
                 "allocs": allocs, "last_index": index}


class TestSnapshotRestore:
    def test_round_trip_restores_all_tables(self):
        fsm, world = populated_fsm()
        blob = fsm.snapshot()

        fresh = NomadFSM()
        fresh.restore(blob)
        for n in world["nodes"]:
            got = fresh.state.node_by_id(n.id)
            assert got is not None and got.to_dict() == \
                fsm.state.node_by_id(n.id).to_dict()
        for j in world["jobs"]:
            assert fresh.state.job_by_id(j.id) is not None
        for e in world["evals"]:
            assert fresh.state.eval_by_id(e.id) is not None
        for a in world["allocs"]:
            assert fresh.state.alloc_by_id(a.id) is not None

    def test_round_trip_preserves_table_indexes(self):
        """Restore must not reset the MVCC indexes: a blocking query
        armed at the pre-snapshot index would otherwise spin."""
        fsm, world = populated_fsm()
        blob = fsm.snapshot()
        fresh = NomadFSM()
        fresh.restore(blob)
        for table in ("nodes", "jobs", "evals", "allocs"):
            assert fresh.state.get_index(table) == \
                fsm.state.get_index(table), table

    def test_round_trip_preserves_timetable(self):
        """fsm.go:313-410: the TimeTable rides the snapshot stream as
        its own record type."""
        fsm, world = populated_fsm()
        witnessed = fsm.timetable.nearest_index(
            fsm.timetable.nearest_time(world["last_index"]) or 0)
        blob = fsm.snapshot()
        fresh = NomadFSM()
        fresh.restore(blob)
        assert fresh.timetable.serialize() == fsm.timetable.serialize()
        assert witnessed is not None or \
            fresh.timetable.serialize() == fsm.timetable.serialize()

    def test_restore_replaces_not_merges(self):
        """Restoring over a dirty FSM discards the pre-restore state
        (state_store.go:104-112: a fresh store, one big txn)."""
        fsm, world = populated_fsm()
        blob = fsm.snapshot()

        dirty = NomadFSM()
        stray = mock.node()
        apply(dirty, 1, NODE_REGISTER_REQUEST, {"node": stray.to_dict()})
        dirty.restore(blob)
        assert dirty.state.node_by_id(stray.id) is None
        assert len(list(dirty.state.nodes())) == len(world["nodes"])

    def test_snapshot_is_deterministic_for_same_state(self):
        fsm, _ = populated_fsm()
        assert fsm.snapshot() == fsm.snapshot()
