"""CLI argument-handling suite: every command's bad-args behavior, the
reference's per-command *_test.go "fails on misuse" checks
(command/{run,status,stop,validate,node_status,...}_test.go).  All
in-process via cli.main(argv) — no agent needed for arg errors."""
from __future__ import annotations

import pytest

from nomad_tpu.cli.main import main


def run_cli(argv, capsys):
    try:
        rc = main(argv)
    except SystemExit as e:  # argparse errors exit(2)
        rc = e.code
    out = capsys.readouterr()
    return rc, out.out, out.err


@pytest.mark.parametrize("argv", [
    ["validate"],                 # missing file
    ["run"],                      # missing file
    ["stop"],                     # missing job id
    ["status", "--bogus-flag"],
    ["node-drain"],               # missing node + mode
    ["alloc-status"],             # missing alloc id
    ["eval-monitor"],             # missing eval id
    ["server-join"],              # missing address
    ["server-force-leave"],       # missing node
    ["no-such-command"],
])
def test_bad_args_fail_with_usage(argv, capsys):
    rc, out, err = run_cli(argv, capsys)
    assert rc not in (0, None), argv
    assert "usage" in (out + err).lower(), argv


def test_validate_missing_file_errors(tmp_path, capsys):
    rc, out, err = run_cli(
        ["validate", str(tmp_path / "nope.hcl")], capsys)
    assert rc != 0
    # A real file error, not a bogus agent connection message.
    assert "Error reading" in err
    assert "connecting" not in err

    rc, out, err = run_cli(["run", str(tmp_path / "nope.hcl")], capsys)
    assert rc != 0 and "Error reading" in err


def test_validate_bad_spec_errors(tmp_path, capsys):
    bad = tmp_path / "bad.hcl"
    bad.write_text('job "x" { priority = "high" }')
    rc, out, err = run_cli(["validate", str(bad)], capsys)
    assert rc != 0
    assert "validation failed" in (out + err).lower()


def test_init_refuses_to_clobber(tmp_path, capsys, monkeypatch):
    """init + validate roundtrip is covered by test_agent_api; the
    clobber refusal (reference init_test.go) is the new bit."""
    monkeypatch.chdir(tmp_path)
    rc, _out, _err = run_cli(["init"], capsys)
    assert rc == 0
    rc, _out, _err = run_cli(["init"], capsys)
    assert rc != 0


def test_connection_refused_is_clean_error(capsys):
    """Commands against a dead agent fail with a clean message; an
    uncaught exception would propagate out of run_cli and ERROR the
    test, which IS the traceback check (reference meta_test paths)."""
    rc, out, err = run_cli(
        ["-address", "http://127.0.0.1:1", "status"], capsys)
    assert rc != 0
    assert "Error connecting" in err
