"""Scheduler unit tests via the Harness rig.

Parity targets: /root/reference/scheduler/{generic_sched,system_sched,
feasible,rank,select,stack,util}_test.go.
"""
import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import (
    EvalContext,
    Harness,
    RejectPlan,
    new_scheduler,
)
from nomad_tpu.scheduler.feasible import (
    ConstraintIterator,
    DriverIterator,
    StaticIterator,
    check_constraint_values,
    resolve_constraint_target,
)
from nomad_tpu.scheduler.rank import (
    BinPackIterator,
    JobAntiAffinityIterator,
    RankedNode,
    StaticRankIterator,
)
from nomad_tpu.scheduler.select import LimitIterator, MaxScoreIterator
from nomad_tpu.scheduler.util import (
    diff_allocs,
    diff_system_allocs,
    evict_and_place,
    materialize_task_groups,
    tainted_nodes,
    tasks_updated,
)
from nomad_tpu.utils.versions import check_constraint, encode_version
from nomad_tpu.structs import (
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    NODE_STATUS_DOWN,
    Constraint,
    Evaluation,
    Plan,
    Resources,
    generate_uuid,
)


def make_eval(job, triggered_by=EVAL_TRIGGER_JOB_REGISTER):
    return Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        type=job.type,
        triggered_by=triggered_by,
        job_id=job.id,
        status="pending",
    )


# ---------------------------------------------------------------------------
# End-to-end: GenericScheduler
# ---------------------------------------------------------------------------

def test_service_sched_register_places_all():
    """10 ready nodes + count=10 service job -> 10 placements, spread out."""
    h = Harness()
    for i in range(10):
        h.state.upsert_node(h.next_index(), mock.node(i))
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)

    ev = make_eval(job)
    h.process("service", ev)

    assert len(h.plans) == 1
    plan = h.plans[0]
    placed = [a for allocs in plan.node_allocation.values() for a in allocs]
    assert len(placed) == 10
    assert not plan.failed_allocs
    # anti-affinity should spread 10 allocs over 10 nodes
    assert len(plan.node_allocation) > 1
    # eval marked complete
    assert h.evals[-1].status == EVAL_STATUS_COMPLETE
    # state applied
    assert len(h.state.allocs_by_job(job.id)) == 10
    for a in placed:
        assert a.metrics.nodes_evaluated > 0


def test_service_sched_no_nodes_fails_allocs():
    h = Harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    ev = make_eval(job)
    h.process("service", ev)

    plan = h.plans[0]
    assert not plan.node_allocation
    # failures coalesce into a single failed alloc
    assert len(plan.failed_allocs) == 1
    assert plan.failed_allocs[0].metrics.coalesced_failures == 9
    assert h.evals[-1].status == EVAL_STATUS_COMPLETE


def test_service_sched_ignores_unknown_trigger():
    h = Harness()
    job = mock.job()
    ev = make_eval(job, triggered_by="bogus")
    h.process("service", ev)
    assert h.plans == []
    assert h.evals[-1].status == EVAL_STATUS_FAILED


def test_service_sched_job_deregistered_stops_allocs():
    h = Harness()
    job = mock.job()
    for i in range(4):
        h.state.upsert_node(h.next_index(), mock.node(i))
    # Existing allocs for a job that no longer exists in state
    nodes = list(h.state.nodes())
    allocs = []
    for i, n in enumerate(nodes):
        a = mock.alloc()
        a.job = job
        a.job_id = job.id
        a.node_id = n.id
        a.name = f"my-job.web[{i}]"
        allocs.append(a)
    h.state.upsert_allocs(h.next_index(), allocs)

    ev = make_eval(job)
    h.process("service", ev)
    plan = h.plans[0]
    stopped = [a for ups in plan.node_update.values() for a in ups]
    assert len(stopped) == 4
    assert all(a.desired_status == ALLOC_DESIRED_STATUS_STOP for a in stopped)


def test_service_sched_node_down_migrates():
    h = Harness()
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    nodes = [mock.node(i) for i in range(11)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)

    allocs = []
    for i in range(10):
        a = mock.alloc()
        a.job = h.state.job_by_id(job.id)
        a.job_id = job.id
        a.node_id = nodes[0].id if i == 0 else nodes[i].id
        a.name = f"my-job.web[{i}]"
        allocs.append(a)
    h.state.upsert_allocs(h.next_index(), allocs)

    h.state.update_node_status(h.next_index(), nodes[0].id, NODE_STATUS_DOWN)
    ev = make_eval(job, EVAL_TRIGGER_NODE_UPDATE)
    h.process("service", ev)

    plan = h.plans[0]
    stopped = [a for ups in plan.node_update.values() for a in ups]
    placed = [a for allocs_ in plan.node_allocation.values() for a in allocs_]
    assert len(stopped) == 1  # the alloc on the dead node
    assert len(placed) == 1   # replaced elsewhere
    assert nodes[0].id not in plan.node_allocation


def test_service_sched_retry_on_rejected_plans():
    h = Harness()
    for i in range(2):
        h.state.upsert_node(h.next_index(), mock.node(i))
    job = mock.job()
    h.state.upsert_job(h.next_index(), job)
    h.planner = RejectPlan(h)

    ev = make_eval(job)
    h.process("service", ev)
    # 5 attempts then eval failed
    assert len(h.plans) == 5
    assert h.evals[-1].status == EVAL_STATUS_FAILED


def test_batch_sched_retry_limit_is_two():
    h = Harness()
    h.state.upsert_node(h.next_index(), mock.node())
    job = mock.job()
    job.type = "batch"
    h.state.upsert_job(h.next_index(), job)
    h.planner = RejectPlan(h)
    ev = make_eval(job)
    ev.type = "batch"
    h.process("batch", ev)
    assert len(h.plans) == 2
    assert h.evals[-1].status == EVAL_STATUS_FAILED


def test_service_sched_inplace_update():
    """Job modify-index bump w/o task changes -> in-place update, no evict."""
    h = Harness()
    nodes = [mock.node(i) for i in range(4)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    job = mock.job()
    job.task_groups[0].count = 4
    h.state.upsert_job(h.next_index(), job)

    old_job = job.copy()
    old_job.modify_index = 1  # existing allocs made against older version
    allocs = []
    for i, n in enumerate(nodes):
        a = mock.alloc()
        a.job = old_job
        a.job_id = job.id
        a.node_id = n.id
        a.name = f"my-job.web[{i}]"
        allocs.append(a)
    h.state.upsert_allocs(h.next_index(), allocs)

    h.process("service", make_eval(job))
    plan = h.plans[0]
    placed = [a for al in plan.node_allocation.values() for a in al]
    # all in-place: no evictions, every placement stays on its node
    assert not plan.node_update
    assert len(placed) == 4
    assert {a.node_id for a in placed} == {n.id for n in nodes}
    current = h.state.job_by_id(job.id)
    assert all(a.job.modify_index == current.modify_index for a in placed)


def test_service_sched_rolling_update_limit():
    """Destructive updates throttled by update.max_parallel + next eval."""
    h = Harness()
    nodes = [mock.node(i) for i in range(6)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    job = mock.job()
    job.task_groups[0].count = 6
    job.update.stagger = 30.0
    job.update.max_parallel = 2
    # Change the task config so updates are destructive
    job.task_groups[0].tasks[0].config = {"command": "/bin/sleep"}
    h.state.upsert_job(h.next_index(), job)

    old_job = job.copy()
    old_job.modify_index = 1
    old_job.task_groups[0].tasks[0].config = {"command": "/bin/date"}
    allocs = []
    for i, n in enumerate(nodes):
        a = mock.alloc()
        a.job = old_job
        a.job_id = job.id
        a.node_id = n.id
        a.name = f"my-job.web[{i}]"
        allocs.append(a)
    h.state.upsert_allocs(h.next_index(), allocs)

    h.process("service", make_eval(job))
    plan = h.plans[0]
    stopped = [a for ups in plan.node_update.values() for a in ups]
    assert len(stopped) == 2  # max_parallel
    assert len(h.create_evals) == 1  # rolling follow-up eval
    assert h.create_evals[0].wait == 30.0
    assert h.evals[-1].next_eval == h.create_evals[0].id


# ---------------------------------------------------------------------------
# End-to-end: SystemScheduler
# ---------------------------------------------------------------------------

def test_system_sched_places_on_all_nodes():
    h = Harness()
    nodes = [mock.node(i) for i in range(10)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    ev = make_eval(job)
    ev.type = "system"
    h.process("system", ev)

    plan = h.plans[0]
    placed = [a for al in plan.node_allocation.values() for a in al]
    assert len(placed) == 10
    assert len(plan.node_allocation) == 10  # one per node
    assert h.evals[-1].status == EVAL_STATUS_COMPLETE


def test_system_sched_node_down_stops():
    h = Harness()
    nodes = [mock.node(i) for i in range(3)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    job = mock.system_job()
    h.state.upsert_job(h.next_index(), job)

    allocs = []
    for i, n in enumerate(nodes):
        a = mock.alloc()
        a.job = h.state.job_by_id(job.id)
        a.job_id = job.id
        a.node_id = n.id
        a.name = f"my-job.web[0]"
        allocs.append(a)
    h.state.upsert_allocs(h.next_index(), allocs)
    h.state.update_node_status(h.next_index(), nodes[0].id, NODE_STATUS_DOWN)

    ev = make_eval(job, EVAL_TRIGGER_NODE_UPDATE)
    ev.type = "system"
    h.process("system", ev)
    plan = h.plans[0]
    stopped = [a for ups in plan.node_update.values() for a in ups]
    # system jobs stop (not migrate) on down nodes
    assert len(stopped) == 1
    assert list(plan.node_update) == [nodes[0].id]


# ---------------------------------------------------------------------------
# Iterators
# ---------------------------------------------------------------------------

def _ctx():
    h = Harness()
    return h, EvalContext(h.state.snapshot(), Plan())


def test_static_iterator_visits_all_once():
    h, ctx = _ctx()
    nodes = [mock.node(i) for i in range(3)]
    it = StaticIterator(ctx, nodes)
    out = []
    while (n := it.next()) is not None:
        out.append(n)
    assert out == nodes
    assert ctx.metrics().nodes_evaluated == 3


def test_driver_iterator_filters():
    h, ctx = _ctx()
    good, bad, invalid = mock.node(), mock.node(), mock.node()
    del bad.attributes["driver.exec"]
    invalid.attributes["driver.exec"] = "false"
    it = DriverIterator(ctx, StaticIterator(ctx, [good, bad, invalid]),
                        ["exec"])
    out = []
    while (n := it.next()) is not None:
        out.append(n)
    assert out == [good]
    assert ctx.metrics().nodes_filtered == 2


def test_constraint_iterator_ops():
    h, ctx = _ctx()
    n = mock.node()
    cases = [
        (Constraint(l_target="$attr.kernel.name", r_target="linux",
                    operand="="), True),
        (Constraint(l_target="$attr.kernel.name", r_target="darwin",
                    operand="!="), True),
        (Constraint(l_target="$node.datacenter", r_target="dc1",
                    operand="="), True),
        (Constraint(l_target="$attr.version", r_target=">= 0.1.0, < 1.0",
                    operand="version"), True),
        (Constraint(l_target="$attr.version", r_target=">= 1.2",
                    operand="version"), False),
        (Constraint(l_target="$attr.kernel.name", r_target="^lin",
                    operand="regexp"), True),
        (Constraint(l_target="$attr.missing", r_target="x", operand="="),
         False),
        (Constraint(l_target="$meta.pci-dss", r_target="true", operand="="),
         True),
        (Constraint(l_target="bar", r_target="foo", operand="<"), True),
        (Constraint(l_target="foo", r_target="bar", operand="<"), False),
    ]
    for c, expected in cases:
        it = ConstraintIterator(ctx, StaticIterator(ctx, [n]), [c])
        got = it.next() is not None
        assert got == expected, f"{c} -> {got}, want {expected}"


def test_soft_constraints_pass():
    h, ctx = _ctx()
    n = mock.node()
    c = Constraint(hard=False, l_target="$attr.missing", r_target="x",
                   operand="=", weight=5)
    it = ConstraintIterator(ctx, StaticIterator(ctx, [n]), [c])
    assert it.next() is not None


def test_binpack_scores_and_skips_overfull():
    h, ctx = _ctx()
    empty = mock.node(1)
    full_node = mock.node(2)
    full_node.resources = Resources(cpu=600, memory_mb=300,
                                    networks=full_node.resources.networks)
    full_node.reserved = None
    task = mock.job().task_groups[0].tasks[0]
    task = task.copy()
    task.resources.networks = []  # pure cpu/mem packing

    src = StaticRankIterator(ctx, [RankedNode(empty), RankedNode(full_node)])
    it = BinPackIterator(ctx, src)
    it.set_tasks([task])
    out = []
    while (o := it.next()) is not None:
        out.append(o)
    assert [o.node.id for o in out] == [empty.id, full_node.id]
    # the nearly-full node gets the better (higher) binpack score
    assert out[1].score > out[0].score


def _packing_task(cpu=1024, mem=1024):
    task = mock.job().task_groups[0].tasks[0].copy()
    task.resources = Resources(cpu=cpu, memory_mb=mem)
    return task


def _packing_node(idx, cpu=2048, mem=2048):
    n = mock.node(idx)
    n.resources = Resources(cpu=cpu, memory_mb=mem,
                            networks=n.resources.networks)
    n.reserved = None
    return n


def test_binpack_counts_planned_allocs():
    """Allocs already staged in the PLAN consume capacity during
    ranking; an unplanned twin node still places
    (rank_test.go:98-168 TestBinPackIterator_PlannedAlloc)."""
    h, ctx = _ctx()
    n = _packing_node(1)
    free = _packing_node(2)
    for node in (n, free):
        h.state.upsert_node(h.next_index(), node)
    ctx.set_state(h.state.snapshot())
    planned = mock.alloc()
    planned.node_id = n.id
    planned.resources = Resources(cpu=2048, memory_mb=2048)
    ctx.plan().append_alloc(planned)

    it = BinPackIterator(ctx, StaticRankIterator(
        ctx, [RankedNode(n), RankedNode(free)]))
    it.set_tasks([_packing_task()])
    out = []
    while (o := it.next()) is not None:
        out.append(o)
    # The plan-staged alloc fills n; only the free twin places.
    assert [o.node.id for o in out] == [free.id]


def test_binpack_counts_existing_allocs():
    """Committed allocs consume capacity (rank_test.go:169-242)."""
    h, ctx = _ctx()
    n = _packing_node(1)
    h.state.upsert_node(h.next_index(), n)
    existing = mock.alloc()
    existing.node_id = n.id
    existing.resources = Resources(cpu=2048, memory_mb=2048)
    h.state.upsert_allocs(h.next_index(), [existing])
    ctx.set_state(h.state.snapshot())

    it = BinPackIterator(ctx, StaticRankIterator(ctx, [RankedNode(n)]))
    it.set_tasks([_packing_task()])
    assert it.next() is None  # existing alloc fills the node


def test_binpack_planned_evict_frees_capacity():
    """An eviction staged in the plan releases the evicted alloc's
    resources for ranking (rank_test.go:243-323)."""
    h, ctx = _ctx()
    n = _packing_node(1)
    h.state.upsert_node(h.next_index(), n)
    existing = mock.alloc()
    existing.node_id = n.id
    existing.resources = Resources(cpu=2048, memory_mb=2048)
    h.state.upsert_allocs(h.next_index(), [existing])
    ctx.set_state(h.state.snapshot())
    ctx.plan().append_update(existing, ALLOC_DESIRED_STATUS_STOP,
                             "making room")

    it = BinPackIterator(ctx, StaticRankIterator(ctx, [RankedNode(n)]))
    it.set_tasks([_packing_task()])
    out = it.next()
    assert out is not None and out.node.id == n.id
    assert out.score > 0


def test_job_anti_affinity_penalty():
    h, ctx = _ctx()
    n = mock.node()
    a = mock.alloc()
    a.node_id = n.id
    h.state.upsert_allocs(h.next_index(), [a])
    ctx.set_state(h.state.snapshot())

    src = StaticRankIterator(ctx, [RankedNode(n)])
    it = JobAntiAffinityIterator(ctx, src, 10.0, a.job_id)
    out = it.next()
    assert out.score == -10.0


def test_limit_and_max_score():
    h, ctx = _ctx()
    rn = [RankedNode(mock.node(i)) for i in range(5)]
    for i, r in enumerate(rn):
        r.score = float(i)
    it = LimitIterator(ctx, StaticRankIterator(ctx, rn), 3)
    ms = MaxScoreIterator(ctx, it)
    best = ms.next()
    assert best.score == 2.0  # only first 3 scanned
    assert ms.next() is None


def test_stack_limit_power_of_two_math():
    """Candidates scanned per placement: max(2, ceil(log2 N)) for
    service, always 2 for batch (reference stack.go:106-117,
    power-of-two-choices)."""
    from nomad_tpu.scheduler.stack import GenericStack

    h, ctx = _ctx()
    cases = [(1, 2), (2, 2), (3, 2), (4, 2), (5, 3), (100, 7),
             (10_000, 14)]
    svc = GenericStack(False, ctx)
    for n, want in cases:
        svc.set_nodes([mock.node(i) for i in range(n)])
        assert svc.limit.limit == want, (n, svc.limit.limit, want)
    batch = GenericStack(True, ctx)
    for n, _ in cases:
        batch.set_nodes([mock.node(i) for i in range(n)])
        assert batch.limit.limit == 2


def test_distinct_hosts_constraint():
    h = Harness()
    nodes = [mock.node(i) for i in range(3)]
    for n in nodes:
        h.state.upsert_node(h.next_index(), n)
    job = mock.job()
    job.task_groups[0].count = 3
    job.constraints.append(Constraint(operand="distinct_hosts"))
    h.state.upsert_job(h.next_index(), job)

    h.process("service", make_eval(job))
    plan = h.plans[0]
    placed = [a for al in plan.node_allocation.values() for a in al]
    assert len(placed) == 3
    # strictly one per node
    assert all(len(al) == 1 for al in plan.node_allocation.values())


# ---------------------------------------------------------------------------
# Utils
# ---------------------------------------------------------------------------

def test_materialize_task_groups():
    job = mock.job()
    out = materialize_task_groups(job)
    assert len(out) == 10
    assert "my-job.web[0]" in out and "my-job.web[9]" in out
    assert materialize_task_groups(None) == {}


def test_materialize_task_groups_memoized_per_version():
    job = mock.job()
    out = materialize_task_groups(job)
    # Cache hit: identical object for the same job version.
    assert materialize_task_groups(job) is out
    # The shared mapping is read-only (mutation would poison the cache).
    with pytest.raises(TypeError):
        out["rogue"] = None
    # A new job version recomputes.
    job.task_groups[0].count = 3
    job.modify_index += 1
    out2 = materialize_task_groups(job)
    assert out2 is not out and len(out2) == 3


def test_diff_allocs_buckets():
    job = mock.job()
    required = materialize_task_groups(job)

    def named_alloc(name, node="n1", stale=False):
        a = mock.alloc()
        a.name = name
        a.node_id = node
        a.job = job.copy()
        if stale:
            a.job.modify_index = 1
        return a

    allocs = [
        named_alloc("my-job.web[0]"),                   # ignore
        named_alloc("my-job.web[1]", node="tainted"),   # migrate
        named_alloc("my-job.web[2]", stale=True),       # update
        named_alloc("not-needed[0]"),                   # stop
    ]
    d = diff_allocs(job, {"tainted": True}, required, allocs)
    assert [t.name for t in d.ignore] == ["my-job.web[0]"]
    assert [t.name for t in d.migrate] == ["my-job.web[1]"]
    assert [t.name for t in d.update] == ["my-job.web[2]"]
    assert [t.name for t in d.stop] == ["not-needed[0]"]
    assert len(d.place) == 7  # web[3..9]


def test_diff_system_allocs_marks_node():
    job = mock.system_job()
    nodes = [mock.node(i) for i in range(2)]
    d = diff_system_allocs(job, nodes, {}, [])
    assert len(d.place) == 2
    assert {t.alloc.node_id for t in d.place} == {n.id for n in nodes}


def test_ready_nodes_memo_invalidates_on_node_change():
    """ready_nodes_in_dcs memoizes per (lineage, nodes index): repeated
    evals reuse the scan, any node write invalidates it, and callers get
    a private list they may shuffle."""
    from nomad_tpu.scheduler.util import ready_nodes_in_dcs

    h = Harness()
    for i in range(4):
        h.state.upsert_node(h.next_index(), mock.node(i))
    snap = h.state.snapshot()
    a = ready_nodes_in_dcs(snap, ["dc1"])
    b = ready_nodes_in_dcs(snap, ["dc1"])
    assert len(a) == 4 and [n.id for n in a] == [n.id for n in b]
    assert a is not b  # fresh list per caller
    b.reverse()  # caller-side mutation must not poison the cache
    assert [n.id for n in ready_nodes_in_dcs(snap, ["dc1"])] == \
        [n.id for n in a]

    # Draining a node bumps the nodes index: the memo must refresh.
    victim = a[0].copy()
    victim.drain = True
    h.state.upsert_node(h.next_index(), victim)
    c = ready_nodes_in_dcs(h.state.snapshot(), ["dc1"])
    assert len(c) == 3
    assert victim.id not in {n.id for n in c}


def test_tainted_nodes():
    h = Harness()
    n = mock.node()
    h.state.upsert_node(h.next_index(), n)
    a1, a2 = mock.alloc(), mock.alloc()
    a1.node_id = n.id
    a2.node_id = "missing-node"
    out = tainted_nodes(h.state, [a1, a2])
    assert out == {n.id: False, "missing-node": True}


def test_tasks_updated():
    a = mock.job().task_groups[0]
    b = mock.job().task_groups[0]
    assert not tasks_updated(a, b)
    b2 = b.copy()
    b2.tasks[0].driver = "docker"
    assert tasks_updated(a, b2)
    b3 = b.copy()
    b3.tasks[0].config = {"command": "/bin/other"}
    assert tasks_updated(a, b3)


def test_evict_and_place_limit():
    h, ctx = _ctx()
    from nomad_tpu.scheduler.util import AllocTuple, DiffResult

    allocs = []
    for i in range(4):
        a = mock.alloc()
        a.name = f"x[{i}]"
        allocs.append(AllocTuple(a.name, None, a))
    diff = DiffResult()
    limit = [2]
    limited = evict_and_place(ctx, diff, allocs, "test", limit)
    assert limited
    assert len(diff.place) == 2
    assert limit[0] == 0


# ---------------------------------------------------------------------------
# Versions
# ---------------------------------------------------------------------------

def test_version_constraints():
    assert check_constraint("1.2.3", ">= 1.0, < 2.0")
    assert not check_constraint("2.1.0", ">= 1.0, < 2.0")
    assert check_constraint("1.2.3", "= 1.2.3")
    assert check_constraint("1.3.0", "~> 1.2")
    assert not check_constraint("2.0.0", "~> 1.2")
    assert check_constraint("1.2.5", "~> 1.2.3")
    assert not check_constraint("1.3.0", "~> 1.2.3")
    assert not check_constraint("garbage", ">= 1.0")
    assert check_constraint("0.1.0", ">= 0.1.0")


def test_version_encoding_order():
    vs = ["0.0.1", "0.1.0", "0.1.0", "1.0.0-beta", "1.0.0", "1.2.3", "10.0.0"]
    encoded = [encode_version(v) for v in vs]
    assert encoded == sorted(encoded)
    assert encode_version("1.0.0-beta") < encode_version("1.0.0")
