"""Multi-region federation over gossip: WAN-style discovery and pruning.

The reference federates regions through serf member tags — a server
learns peer regions from gossip (nomad/serf.go, server.go:503-538) and
`forwardRegion` routes RPCs by that table (nomad/rpc.go:206-227).  The
unit tests in test_rpc.py wire the region table statically; these tests
exercise the live path: servers in different regions joined through one
gossip pool, the region table populated and pruned by join/fail events
alone, and cross-region RPCs riding the discovered routes.
"""
from __future__ import annotations

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool, RPCError

from tests.conftest import wait_until


def _server(region: str, name: str) -> Server:
    s = Server(ServerConfig(num_schedulers=1, enable_rpc=True,
                            enable_gossip=True, region=region,
                            server_name=name))
    # Tighten SWIM timings so failure pruning converges in test time.
    s.gossip.probe_interval = 0.05
    s.gossip.probe_timeout = 0.05
    s.gossip.suspect_timeout = 0.3
    s.establish_leadership()
    return s


@pytest.fixture
def pool():
    p = ConnPool()
    yield p
    p.shutdown()


def test_gossip_discovers_regions_and_forwards(pool):
    a = _server("region-a", "a1")
    b = _server("region-b", "b1")
    try:
        b.gossip.join(a.gossip.addr)
        wait_until(lambda: a.regions() == ["region-a", "region-b"],
                   msg="a discovers region-b")
        wait_until(lambda: b.regions() == ["region-a", "region-b"],
                   msg="b discovers region-a")

        # Write addressed to region-b through region-a's server rides
        # the gossip-discovered route.
        node = mock.node()
        pool.call(a.rpc_address(), "Node.Register",
                  {"node": node.to_dict(), "region": "region-b"})
        assert b.fsm.state.node_by_id(node.id) is not None
        assert a.fsm.state.node_by_id(node.id) is None

        # Cross-region read through the same discovered route.
        out = pool.call(a.rpc_address(), "Node.GetNode",
                        {"node_id": node.id, "region": "region-b"})
        assert out["node"]["id"] == node.id
    finally:
        a.shutdown()
        b.shutdown()


def test_region_route_pruned_on_failure(pool):
    a = _server("region-a", "a1")
    b = _server("region-b", "b1")
    try:
        b.gossip.join(a.gossip.addr)
        wait_until(lambda: "region-b" in a.regions(),
                   msg="a discovers region-b")

        # Crash region-b's server (no graceful leave): SWIM suspicion
        # must prune the route.
        b.gossip._stop.set()
        b.gossip.sock.close()
        wait_until(lambda: a.regions() == ["region-a"],
                   msg="region-b pruned after failure")
        with pytest.raises(RPCError, match="no path to region"):
            pool.call(a.rpc_address(), "Node.Register",
                      {"node": mock.node().to_dict(),
                       "region": "region-b"})
    finally:
        a.shutdown()
        b.shutdown()


def test_three_region_transitive_discovery(pool):
    """A third region joining any one member learns every region
    transitively, and every server can route to every region."""
    servers = [_server(f"region-{r}", f"{r}1") for r in ("a", "b", "c")]
    try:
        servers[1].gossip.join(servers[0].gossip.addr)
        servers[2].gossip.join(servers[0].gossip.addr)
        want = ["region-a", "region-b", "region-c"]
        for s in servers:
            wait_until(lambda s=s: s.regions() == want,
                       msg=f"{s.config.server_name} sees all regions")
        # c -> a route, never configured anywhere explicitly.
        node = mock.node()
        pool.call(servers[2].rpc_address(), "Node.Register",
                  {"node": node.to_dict(), "region": "region-a"})
        assert servers[0].fsm.state.node_by_id(node.id) is not None
    finally:
        for s in servers:
            s.shutdown()
