"""Columnar alloc contract (structs/alloc_slab.py): lazy SlabAlloc
materialization, the columnar raft wire, snapshot encoding, and
byte-parity between the slab path and the legacy object path.

The invariant everything here pins: a world that evolved through
columnar slabs digests (store fingerprint, per-alloc to_dict) EXACTLY
like one that evolved through the object contract — the slab is a
representation change, never a semantic one.
"""
from __future__ import annotations

import gc
import weakref

import msgpack
import pytest

import nomad_tpu.mock as mock
import nomad_tpu.scheduler.jax_binpack as jb
import nomad_tpu.structs.alloc_slab as alloc_slab
from nomad_tpu.scheduler import Harness
from nomad_tpu.server.fsm import SNAP_ALLOC_SLAB, NomadFSM
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import (
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_TRIGGER_JOB_REGISTER,
    Allocation,
    Evaluation,
    NetworkResource,
    Resources,
    SlabAlloc,
    Task,
    TaskGroup,
    codec,
)
from nomad_tpu.structs.alloc_slab import (
    AllocSlab,
    decode_alloc_list,
    decode_slabs,
    encode_alloc_update,
    encode_plan_batch,
    slab_ref,
)

pytestmark = pytest.mark.skipif(
    jb._native_bulk() is None, reason="native extension unavailable")


def make_eval(job):
    return Evaluation(id=f"ev-{job.id}", priority=job.priority,
                      type="service",
                      triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                      job_id=job.id)


def _job(n_groups=6, count=2):
    job = mock.job()
    job.task_groups = [
        TaskGroup(
            name=f"tg-{g}", count=count,
            tasks=[
                Task(name="web", driver="exec",
                     resources=Resources(
                         cpu=100, memory_mb=64,
                         networks=[NetworkResource(
                             mbits=5, dynamic_ports=["http", "admin"])])),
                Task(name="sidecar", driver="exec",
                     resources=Resources(cpu=50, memory_mb=32)),
            ])
        for g in range(n_groups)]
    return job


def _deterministic(monkeypatch):
    counter = {"n": 0}

    def fake_uuids(n):
        base = counter["n"]
        counter["n"] += n
        return [f"u-{base + i:08d}" for i in range(n)]

    monkeypatch.setattr(jb, "generate_uuids", fake_uuids)
    monkeypatch.setattr("nomad_tpu.structs.generate_uuids", fake_uuids)
    monkeypatch.setattr(jb, "_randrange", lambda n: 987654321 % n)

    # Frozen clock: metrics.allocation_time is wall-clock-derived and
    # would differ between the two contract runs (the fingerprint
    # digests it).
    class _FrozenTime:
        perf_counter = staticmethod(lambda: 0.0)

    monkeypatch.setattr(jb, "time", _FrozenTime)
    # The failed-alloc path stamps allocation_time through the stack's
    # own clock (scheduler/stack.py) — freeze it too so contended runs
    # (exhausted placements carry real metrics) digest identically.
    import nomad_tpu.scheduler.stack as stack
    monkeypatch.setattr(stack, "time", _FrozenTime)


_WORLD_CACHE: dict = {}


def _world(n_nodes=12, n_jobs=3):
    """One shared node/job prototype set per shape — both contract runs
    must see the SAME world (mock ids are random per construction)."""
    key = (n_nodes, n_jobs)
    world = _WORLD_CACHE.get(key)
    if world is None:
        nodes = [mock.node(i) for i in range(n_nodes)]
        jobs = []
        for j in range(n_jobs):
            job = _job()
            job.id = f"job-{j}"
            job.name = f"job-{j}"
            jobs.append(job)
        world = _WORLD_CACHE[key] = (nodes, jobs)
    return world


def _run_storm(monkeypatch, columnar: bool, n_nodes=12, n_jobs=3):
    """One deterministic eval stream through the jax-binpack scheduler;
    returns (harness, plans)."""
    _deterministic(monkeypatch)
    monkeypatch.setattr(alloc_slab, "COLUMNAR", columnar)
    nodes, jobs = _world(n_nodes, n_jobs)
    h = Harness()
    for n in nodes:
        h.state.upsert_node(h.next_index(), n.copy())
    plans = []
    for job in jobs:
        h.state.upsert_job(h.next_index(), job.copy())
        h.process("jax-binpack", make_eval(job))
        plans.append(h.plans[-1])
    return h, plans


def _norm(plan):
    out = {}
    for node_id, allocs in plan.node_allocation.items():
        rows = []
        for a in allocs:
            d = a.to_dict()
            d["metrics"]["allocation_time"] = 0.0
            rows.append(d)
        out[node_id] = rows
    return out


def _plan_allocs(plan):
    return [a for allocs in plan.node_allocation.values()
            for a in allocs]


# ---------------------------------------------------------------------------
# 1. scheduler-level parity: columnar vs object contract
# ---------------------------------------------------------------------------

class TestSchedulerParity:
    def test_columnar_plans_byte_identical_to_object_path(
            self, monkeypatch):
        with monkeypatch.context() as m:
            _h1, obj_plans = _run_storm(m, columnar=False)
        with monkeypatch.context() as m:
            _h2, col_plans = _run_storm(m, columnar=True)
        assert [_norm(p) for p in obj_plans] == \
            [_norm(p) for p in col_plans]
        # The columnar run really rode slabs (not a silent fallback).
        assert all(type(a) is SlabAlloc
                   for p in col_plans for a in _plan_allocs(p))
        assert all(type(a) is Allocation
                   for p in obj_plans for a in _plan_allocs(p))

    def test_columnar_store_fingerprint_parity(self, monkeypatch):
        """Apply both recordings to fresh stores with identical
        indexes: alloc set, per-table indexes and the full store digest
        must be byte-identical."""
        with monkeypatch.context() as m:
            _h1, obj_plans = _run_storm(m, columnar=False)
        with monkeypatch.context() as m:
            _h2, col_plans = _run_storm(m, columnar=True)
        stores = []
        for plans in (obj_plans, col_plans):
            s = StateStore()
            s.upsert_allocs_batched(
                [(5000 + i, _plan_allocs(p))
                 for i, p in enumerate(plans)])
            stores.append(s)
        s_obj, s_col = stores
        assert s_obj.get_index("allocs") == s_col.get_index("allocs")
        assert sorted(a.id for a in s_obj.allocs()) == \
            sorted(a.id for a in s_col.allocs())
        assert s_obj.fingerprint() == s_col.fingerprint()

    def test_verify_window_does_not_materialize_slab_rows(
            self, monkeypatch):
        """The vectorized window verify consumes slab columns: after a
        full evaluate_window pass the plan's slab allocs still have no
        heavy fields in their dicts."""
        from nomad_tpu.ops.plan_conflict import evaluate_window

        with monkeypatch.context() as m:
            h, plans = _run_storm(m, columnar=True)
            snap = h.state.snapshot()
            outcomes = evaluate_window(snap, plans)
        assert len(outcomes) == len(plans)
        for p in plans:
            for a in _plan_allocs(p):
                for heavy in ("resources", "task_resources", "metrics"):
                    assert heavy not in a.__dict__, \
                        f"window verify materialized {heavy}"


class _RecordingPlanner:
    """VerifyingPlanner wrapper recording every plan verdict — the
    rejection/partial-accept stream the contended parity rig
    byte-compares between the two contracts."""

    def __init__(self, harness):
        from nomad_tpu.scheduler.harness import VerifyingPlanner

        self.inner = VerifyingPlanner(harness)
        self.verdicts: list = []

    def _record(self, plan, result):
        self.verdicts.append((
            plan.eval_id,
            _norm_result(result),
            bool(result.refresh_index),
        ))

    def submit_plans(self, plans):
        out = self.inner.submit_plans(plans)
        for plan, (result, _state) in zip(plans, out):
            self._record(plan, result)
        return out

    def submit_plan(self, plan):
        result, state = self.inner.submit_plan(plan)
        self._record(plan, result)
        return result, state

    def update_eval(self, ev):
        self.inner.update_eval(ev)

    def create_eval(self, ev):
        self.inner.create_eval(ev)


def _norm_result(result):
    out = {}
    for node_id, allocs in result.node_allocation.items():
        rows = []
        for a in allocs:
            d = a.to_dict()
            d["metrics"]["allocation_time"] = 0.0
            rows.append(d)
        out[node_id] = rows
    return out


class TestContendedStormParity:
    """ISSUE 9 rig: a REAL contended fused storm (BatchEvalRunner
    through leader verify semantics) replayed through both contracts —
    alloc set, rejections, per-table indexes, and the store fingerprint
    byte-compared (extends the test_plan_batch.py recorded-storm and
    test_state_store_port.py batched-parity patterns)."""

    def _storm(self, monkeypatch, columnar: bool):
        from nomad_tpu.scheduler.batch import BatchEvalRunner

        _deterministic(monkeypatch)
        monkeypatch.setattr(alloc_slab, "COLUMNAR", columnar)
        # 6 nodes under 8 jobs x 4 TGs x count 2 at cpu=600: the later
        # evals over-commit the fleet, so the verifying planner emits
        # the full verdict spectrum (accepts, partial accepts with a
        # refresh, rejections) — not just the happy path.
        key = ("contended", 6, 8)
        world = _WORLD_CACHE.get(key)
        if world is None:
            nodes = [mock.node(i) for i in range(6)]
            jobs = []
            for j in range(8):
                job = mock.job()
                job.id = f"storm-job-{j}"
                job.name = f"storm-job-{j}"
                job.task_groups = [
                    TaskGroup(
                        name=f"tg-{g}", count=2,
                        tasks=[Task(
                            name="web", driver="exec",
                            resources=Resources(
                                cpu=600, memory_mb=256,
                                networks=[NetworkResource(
                                    mbits=5,
                                    dynamic_ports=["http"])]))])
                    for g in range(4)]
                jobs.append(job)
            world = _WORLD_CACHE[key] = (nodes, jobs)
        nodes, jobs = world
        h = Harness()
        for n in nodes:
            h.state.upsert_node(h.next_index(), n.copy())
        for job in jobs:
            h.state.upsert_job(h.next_index(), job.copy())
        h.planner = _RecordingPlanner(h)
        evals = [Evaluation(id=f"storm-ev-{j.id}", priority=50,
                            type=j.type,
                            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
                            job_id=j.id) for j in jobs]
        BatchEvalRunner(h.state.snapshot(), h,
                        state_refresh=h.snapshot).process(evals)
        return h

    def test_storm_replay_byte_parity(self, monkeypatch):
        with monkeypatch.context() as m:
            h_obj = self._storm(m, columnar=False)
        with monkeypatch.context() as m:
            h_col = self._storm(m, columnar=True)

        # The verdict stream: same plans, same accepted portions, same
        # rejections/refreshes, in the same order.
        v_obj = h_obj.planner.verdicts
        v_col = h_col.planner.verdicts
        assert len(v_obj) == len(v_col)
        assert v_obj == v_col
        assert any(refresh for _e, _n, refresh in v_obj), \
            "storm produced no contention — the rig lost its teeth"
        assert h_obj.planner.inner.conflicts == \
            h_col.planner.inner.conflicts

        # Alloc set + per-table indexes + full store digest.
        assert sorted(a.id for a in h_obj.state.allocs()) == \
            sorted(a.id for a in h_col.state.allocs())
        for table in ("allocs", "nodes", "jobs", "evals"):
            assert h_obj.state.get_index(table) == \
                h_col.state.get_index(table), table
        assert h_obj.state.fingerprint() == h_col.state.fingerprint()
        # And the columnar run genuinely rode slabs.
        assert any(type(a) is SlabAlloc for a in h_col.state.allocs())


# ---------------------------------------------------------------------------
# 2. lazy materialization semantics
# ---------------------------------------------------------------------------

class TestLazyMaterialization:
    def _one_alloc(self, monkeypatch):
        h, plans = _run_storm(monkeypatch, columnar=True, n_jobs=1)
        allocs = _plan_allocs(plans[0])
        assert allocs
        return allocs[0]

    def test_fields_materialize_on_read_and_round_trip(
            self, monkeypatch):
        a = self._one_alloc(monkeypatch)
        assert "task_resources" not in a.__dict__
        d = a.to_dict()  # materializes through the properties
        assert "task_resources" in a.__dict__
        twin = Allocation.from_dict(d)
        assert twin.to_dict() == d
        # Ports in the offer match the slab's column slice.
        slab, r = a.__dict__["_slab"], a.__dict__["_srow"]
        ports = [p for tr in a.task_resources.values()
                 for n in tr.networks for p in n.reserved_ports]
        o0, o1 = int(slab.port_off[r]), int(slab.port_off[r + 1])
        assert ports == slab.ports[o0:o1].tolist()

    def test_slab_vec_and_net_row_match_materialized_truth(
            self, monkeypatch):
        from nomad_tpu.models.fleet import (_net_row_build, _res_vector,
                                            alloc_vec, _net_row)

        a = self._one_alloc(monkeypatch)
        vec = alloc_vec(a)          # columnar fast path (unmaterialized)
        row = _net_row(a)
        assert "task_resources" not in a.__dict__
        # Materialize and recompute the object truth.
        assert list(vec) == list(_res_vector(a.resources))
        assert row == _net_row_build(a)

    def test_copy_preserves_slab_backing(self, monkeypatch):
        a = self._one_alloc(monkeypatch)
        c = a.copy()
        assert type(c) is SlabAlloc
        assert c.__dict__["_slab"] is a.__dict__["_slab"]
        assert "task_resources" not in c.__dict__
        assert c.to_dict() == a.to_dict()

    def test_heavy_assignment_flags_row_off_the_columnar_wire(
            self, monkeypatch):
        a = self._one_alloc(monkeypatch)
        assert slab_ref(a) is not None
        c = a.copy()
        c.task_resources = {}
        assert slab_ref(c) is None, \
            "a mutated heavy field must disable slab-reference encoding"
        assert slab_ref(a) is not None, "flag must not leak to siblings"

    def test_eviction_copy_rides_wire_as_scalar_delta(self, monkeypatch):
        a = self._one_alloc(monkeypatch)
        ev = a.copy()
        ev.desired_status = ALLOC_DESIRED_STATUS_STOP
        ev.desired_description = "alloc not needed"
        ref = slab_ref(ev)
        assert ref is not None
        _slab, _r, delta = ref
        assert delta == {"desired_status": ALLOC_DESIRED_STATUS_STOP,
                         "desired_description": "alloc not needed"}

    def test_refcount_reclaims_materialized_family(self, monkeypatch):
        """No cycles: dropping the plan frees allocs AND slab with gc
        disabled, even after materialization and wire caching."""
        h, plans = _run_storm(monkeypatch, columnar=True, n_jobs=1)
        allocs = _plan_allocs(plans[0])
        slab = allocs[0].__dict__["_slab"]
        slab.alloc(0)  # populate the decode cache too
        refs = [weakref.ref(a) for a in allocs] + [weakref.ref(slab)]
        was = gc.isenabled()
        gc.disable()
        try:
            del allocs, slab
            h.plans.clear()
            for p in plans:
                p.node_allocation.clear()
            del plans, h
            assert all(r() is None for r in refs), \
                "slab family survived refcount-only teardown"
        finally:
            if was:
                gc.enable()


# ---------------------------------------------------------------------------
# 3. the columnar wire
# ---------------------------------------------------------------------------

class TestColumnarWire:
    def test_plan_batch_wire_round_trip_byte_parity(self, monkeypatch):
        h, plans = _run_storm(monkeypatch, columnar=True)
        alloc_lists = [_plan_allocs(p) for p in plans]
        payload = encode_plan_batch(alloc_lists)
        # Full msgpack round trip, exactly like the raft log.
        payload = msgpack.unpackb(
            msgpack.packb(payload, use_bin_type=True),
            raw=False, strict_map_key=False)
        slabs = decode_slabs(payload)
        for sub, want in zip(payload["plans"], alloc_lists):
            got = decode_alloc_list(sub["alloc"], slabs)
            assert [a.to_dict() for a in got] == \
                [a.to_dict() for a in want]

    def test_wire_smaller_than_object_encoding(self, monkeypatch):
        h, plans = _run_storm(monkeypatch, columnar=True)
        alloc_lists = [_plan_allocs(p) for p in plans]
        col = msgpack.packb(encode_plan_batch(alloc_lists),
                            use_bin_type=True)
        obj = msgpack.packb(
            {"plans": [{"alloc": [a.to_dict() for a in allocs]}
                       for allocs in alloc_lists]},
            use_bin_type=True)
        assert len(col) < len(obj) // 2, (len(col), len(obj))

    def test_fsm_apply_columnar_vs_object_entries(self, monkeypatch):
        """Two FSMs, one fed the columnar PLAN_BATCH entry, one the
        object encoding of the same window: identical fingerprints."""
        h, plans = _run_storm(monkeypatch, columnar=True)
        alloc_lists = [_plan_allocs(p) for p in plans]
        e_col = codec.encode(codec.PLAN_BATCH_APPLY_REQUEST,
                             encode_plan_batch(alloc_lists))
        e_obj = codec.encode(
            codec.PLAN_BATCH_APPLY_REQUEST,
            {"plans": [{"alloc": [a.to_dict() for a in allocs]}
                       for allocs in alloc_lists]})
        f_col, f_obj = NomadFSM(), NomadFSM()
        f_col.apply(100, e_col)
        f_obj.apply(100, e_obj)
        assert f_col.state.fingerprint() == f_obj.state.fingerprint()

    def test_alloc_update_payload_back_compat(self):
        """A legacy all-dict ALLOC_UPDATE payload (client updates, old
        log entries) still decodes."""
        a = Allocation(id="a1", node_id="n1", job_id="j1",
                       resources=Resources(cpu=10))
        fsm = NomadFSM()
        fsm.apply(7, codec.encode(codec.ALLOC_UPDATE_REQUEST,
                                  {"alloc": [a.to_dict()]}))
        assert fsm.state.alloc_by_id("a1") is not None


# ---------------------------------------------------------------------------
# 4. slab cache invalidation
# ---------------------------------------------------------------------------

class TestCacheInvalidation:
    def _slab(self, monkeypatch):
        h, plans = _run_storm(monkeypatch, columnar=True, n_jobs=1)
        payload = msgpack.unpackb(
            msgpack.packb(
                encode_alloc_update(_plan_allocs(plans[0])),
                use_bin_type=True),
            raw=False, strict_map_key=False)
        return decode_slabs(payload)[0]

    def test_alloc_cached_then_invalidated_by_patch_row(
            self, monkeypatch):
        slab = self._slab(monkeypatch)
        a1 = slab.alloc(0)
        assert slab.alloc(0) is a1, "canonical row objects are cached"
        old_node = a1.node_id
        slab.patch_row(0, node_id="moved-node")
        a2 = slab.alloc(0)
        assert a2 is not a1, \
            "a patched row must not serve the stale cached object"
        assert a2.node_id == "moved-node"
        # The already-handed-out object keeps its snapshot (store
        # immutability semantics), it just stops being served.
        assert a1.node_id == old_node

    def test_alloc_with_is_never_cached(self, monkeypatch):
        slab = self._slab(monkeypatch)
        a = slab.alloc_with(0, create_index=9, modify_index=9)
        assert a.create_index == 9
        assert slab.alloc(0) is not a
        assert slab.alloc(0).create_index == 0

    def test_patch_row_rejects_non_scalar_columns(self, monkeypatch):
        slab = self._slab(monkeypatch)
        with pytest.raises(KeyError):
            slab.patch_row(0, task_resources={})

    def test_patch_row_does_not_leak_into_sibling_slabs(
            self, monkeypatch):
        """Scheduler-built slabs alias names/tgs (and groups) to the
        per-job-version col_meta cache, shared read-only with every
        sibling slab of the same job version — patch_row must
        copy-on-write, not rewrite a sibling's canonical rows through
        the shared list.  (Today the plan memo collapses same-version
        finishes onto one slab, so the aliasing is latent; this pins
        the seam's contract for the first caller that isn't.)"""
        h, plans = _run_storm(monkeypatch, columnar=True, n_jobs=1)
        proto = _plan_allocs(plans[0])[0].__dict__["_slab"]
        import numpy as np

        def sibling():
            s = AllocSlab(
                eval_id=proto.eval_id, job=proto.job,
                slots=proto.slots, metric_proto=proto.metric_proto,
                groups=proto.groups,        # shared, like col_meta
                ids=list(proto.ids), names=proto.names,   # shared
                tgs=proto.tgs,              # shared
                scores=list(proto.scores),
                port_off=np.asarray(proto.port_off), n_rows=proto.n)
            s.node_ids = list(proto.node_ids)
            s.ips = list(proto.ips)
            s.devs = list(proto.devs)
            s.seal(proto.n)
            return s
        slab_a, slab_b = sibling(), sibling()
        assert slab_a.names is slab_b.names, \
            "precondition: siblings share the col_meta names column"
        before = slab_b.names[0]
        slab_a.patch_row(0, name="patched-name", task_group="patched-tg")
        assert slab_a.names[0] == "patched-name"
        assert slab_b.names[0] == before, \
            "patch_row leaked through the shared col_meta column"
        assert slab_b.alloc(0).name == before
        assert slab_a.alloc(0).name == "patched-name"
        # Second patch mutates the now-private columns in place.
        slab_a.patch_row(1, name="second-patch")
        assert slab_b.names[1] == proto.names[1]


# ---------------------------------------------------------------------------
# 5. columnar FSM snapshots
# ---------------------------------------------------------------------------

class TestColumnarSnapshot:
    def _fsm_with_storm(self, monkeypatch):
        h, plans = _run_storm(monkeypatch, columnar=True)
        fsm = NomadFSM()
        alloc_lists = [_plan_allocs(p) for p in plans]
        fsm.apply(100, codec.encode(codec.PLAN_BATCH_APPLY_REQUEST,
                                    encode_plan_batch(alloc_lists)))
        return fsm, alloc_lists

    def test_snapshot_round_trips_fingerprint_identical(
            self, monkeypatch):
        fsm, _ = self._fsm_with_storm(monkeypatch)
        want = fsm.state.fingerprint(changelog_since=10 ** 9)
        blob = fsm.snapshot()
        # The snapshot actually used columnar records.
        unpacker = msgpack.Unpacker(raw=False, strict_map_key=False)
        unpacker.feed(blob)
        kinds = [k for k, _p in unpacker]
        assert SNAP_ALLOC_SLAB in kinds
        fsm.restore(blob)
        # The restored store's allocs are slab-backed and still lazy
        # (checked BEFORE the digest below materializes them).
        assert any("_slab" in a.__dict__ and
                   "task_resources" not in a.__dict__
                   for a in fsm.state.allocs())
        assert fsm.state.fingerprint(changelog_since=10 ** 9) == want

    def test_snapshot_smaller_than_object_encoding(self, monkeypatch):
        """The snapshot-size tax: columnar records must beat per-alloc
        dicts (which re-serialize the whole job per alloc)."""
        fsm, alloc_lists = self._fsm_with_storm(monkeypatch)
        col_blob = fsm.snapshot()

        # Twin world, same final state, forced through the OBJECT wire
        # (per-alloc dicts all the way): fingerprints match, so the
        # size delta is pure representation.
        twin = NomadFSM()
        twin.apply(100, codec.encode(
            codec.PLAN_BATCH_APPLY_REQUEST,
            {"plans": [{"alloc": [a.to_dict() for a in allocs]}
                       for allocs in alloc_lists]}))
        assert twin.state.fingerprint() == fsm.state.fingerprint()
        obj_blob = twin.snapshot()
        assert len(col_blob) < len(obj_blob) // 2, \
            (len(col_blob), len(obj_blob))
        # Both restore to the same world.
        f1, f2 = NomadFSM(), NomadFSM()
        f1.restore(col_blob)
        f2.restore(obj_blob)
        assert f1.state.fingerprint(changelog_since=10 ** 9) == \
            f2.state.fingerprint(changelog_since=10 ** 9)

    def test_client_merged_rows_keep_their_updates(self, monkeypatch):
        """A row the client merged (task_states) snapshots through the
        delta channel and round-trips its update."""
        fsm, _ = self._fsm_with_storm(monkeypatch)
        some = next(iter(fsm.state.allocs()))
        upd = some.copy()
        upd.client_status = "running"
        upd.task_states = {"web": {"state": "running"}}
        fsm.state.update_alloc_from_client(200, upd)
        want = fsm.state.fingerprint(changelog_since=10 ** 9)
        fsm.restore(fsm.snapshot())
        assert fsm.state.fingerprint(changelog_since=10 ** 9) == want
        back = fsm.state.alloc_by_id(some.id)
        assert back.client_status == "running"
        assert back.task_states == {"web": {"state": "running"}}
