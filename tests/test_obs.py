"""Trace & telemetry plane (nomad_tpu/obs/): ISSUE 10.

Four layers:

1. **Tracer units** — seedable ids, per-thread buffers, ring
   bound/overflow accounting, ambient nesting, Chrome-trace export
   shape, and the disabled-path contract (one module bool).
2. **Registry units** — the flatten grammar, provider replace/
   deregister, erroring-provider isolation, publish-to-metrics.
3. **Flight recorder** — incident file shape and bounds, rate limit,
   on-disk pruning, the stall watchdog, and the real triggers
   (breaker-open, overload entry).
4. **Span trees on a live server** — every terminal eval has a closed,
   single-rooted span tree even under seeded rpc drops and raft-apply
   faults with plan retries; exactly-once upsert spans for exactly-once
   placements; and one seeded chaos eval exports a Chrome trace
   spanning agent edge -> broker -> scheduler stages -> window verify
   -> raft apply -> store upsert (the ISSUE acceptance bar).

Plus the tier-1 tracing-overhead assertion: the bench asserts <=5% on
the config-4 stream; this suite asserts a generous structural bound on
a small stream so a hot-path instrumentation regression fails tier-1,
not just the nightly bench.
"""
from __future__ import annotations

import json
import os
import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu import faultinject
from nomad_tpu.faultinject import FaultPlan
from nomad_tpu.obs import flight, registry, trace
from nomad_tpu.obs.registry import MetricsRegistry, flatten
from nomad_tpu.obs.trace import Tracer
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.rpc import ConnPool
from nomad_tpu.structs import Resources, Task, TaskGroup
from nomad_tpu.utils.retry import RetryPolicy

from tests.conftest import wait_until

TERMINAL = ("complete", "failed", "canceled")


def _job(n_groups: int = 2, count: int = 1):
    job = mock.job()
    job.task_groups = [
        TaskGroup(name=f"tg-{g}", count=count,
                  tasks=[Task(name="web", driver="exec",
                              resources=Resources(cpu=100,
                                                  memory_mb=32))])
        for g in range(n_groups)]
    return job


# ---------------------------------------------------------------------------
# 1. tracer units
# ---------------------------------------------------------------------------

class TestTracerUnits:
    def test_seeded_ids_are_deterministic(self):
        a, b = Tracer(seed=7), Tracer(seed=7)
        assert [a.new_id() for _ in range(5)] == \
            [b.new_id() for _ in range(5)]
        assert Tracer(seed=8).new_id() != Tracer(seed=7).new_id()

    def test_span_timestamps_are_monotonic_deltas(self):
        t = Tracer(seed=1)
        with t.span("a"):
            pass
        span = t.snapshot()[0]
        # Tracer-epoch relative, not wall: a fresh tracer's first span
        # starts near zero regardless of the wall clock.
        assert 0.0 <= span["t0"] < 60.0
        assert span["dur"] >= 0.0

    def test_ambient_nesting_links_parents(self):
        t = Tracer(seed=1)
        with t.span("outer") as outer:
            with t.span("inner") as inner:
                assert t.ctx() == inner
            assert t.ctx() == outer
        assert t.ctx() is None
        by_name = {s["name"]: s for s in t.snapshot()}
        assert by_name["inner"]["parent_id"] == \
            by_name["outer"]["span_id"]
        assert by_name["inner"]["trace_id"] == \
            by_name["outer"]["trace_id"]
        assert by_name["outer"]["parent_id"] is None

    def test_attach_adopts_cross_thread_context(self):
        t = Tracer(seed=1)
        ctx = t.anchor("eval.created", eval_id="e1")
        done = threading.Event()

        def worker():
            with t.attach(ctx):
                with t.span("work"):
                    pass
            done.set()

        th = threading.Thread(target=worker)
        th.start()
        th.join(5.0)
        assert done.is_set()
        by_name = {s["name"]: s for s in t.snapshot()}
        assert by_name["work"]["parent_id"] == ctx["span_id"]
        assert by_name["work"]["trace_id"] == ctx["trace_id"]

    def test_ring_bound_and_overflow_accounting(self):
        t = Tracer(seed=1, ring=8)
        for i in range(200):
            t.record("s", 0.0, 0.0)
        st = t.stats()
        # 3 full thread-buffer flushes (64 spans each) hit the ring;
        # the ring keeps the newest 8 and counts every drop.
        assert st["ring"] == 8
        assert st["dropped"] == 192 - 8
        assert st["buffered"] == 200 - 192
        assert st["recorded"] == 200
        assert len(t.snapshot()) == 16  # ring + still-buffered

    def test_dead_thread_buffers_fold_into_ring(self):
        t = Tracer(seed=1)

        def worker():
            t.record("from-thread", 0.0, 0.0)

        th = threading.Thread(target=worker)
        th.start()
        th.join(5.0)
        names = [s["name"] for s in t.snapshot()]
        assert "from-thread" in names
        # The dead thread's buffer was folded; a second snapshot must
        # not double-report it.
        assert [s["name"] for s in t.snapshot()].count("from-thread") == 1

    def test_dead_thread_buffers_pruned_without_snapshot(self):
        """Short-lived recording threads (the applier's per-window
        respond thread) must not grow the buffer registry on an
        always-on tracer nobody snapshots: each NEW thread's
        registration sweeps the dead ones into the ring."""
        t = Tracer(seed=1)
        for _ in range(20):
            th = threading.Thread(
                target=lambda: t.record("s", 0.0, 0.0))
            th.start()
            th.join(5.0)
        with t._lock:
            live_bufs = len(t._bufs)
        assert live_bufs <= 2, live_bufs  # newest dead + this thread
        assert t.stats()["recorded"] == 20

    def test_chrome_trace_export_shape(self, tmp_path):
        t = Tracer(seed=1)
        with t.span("rpc.serve.Job.Register", method="Job.Register"):
            t.anchor("eval.created", eval_id="e1")
        path = str(tmp_path / "trace.json")
        n = t.export_chrome(path)
        with open(path) as fh:
            doc = json.load(fh)
        assert n == 2 and len(doc["traceEvents"]) == 2
        for ev in doc["traceEvents"]:
            assert ev["ph"] == "X"
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert "span_id" in ev["args"]
        cats = {ev["cat"] for ev in doc["traceEvents"]}
        assert cats == {"rpc", "eval"}

    def test_disabled_is_one_module_bool(self):
        assert trace.ENABLED is False and trace.tracer() is None
        # The no-op module API stays no-op with tracing off.
        args = {"a": 1}
        assert trace.inject(args) is args
        assert trace.ctx() is None
        with trace.client_call("Job.Register", args) as out:
            assert out is args

    def test_envelope_inject_extract_roundtrip(self):
        with trace.tracing(seed=3) as t:
            with t.span("outer"):
                args = trace.inject({"x": 1})
                assert trace.TRACE_KEY in args
                got = trace.extract(args)
                assert got == t.ctx()
            # inject copies: the caller's dict is never mutated.
            original = {"x": 1}
            with t.span("outer2"):
                stamped = trace.inject(original)
                assert stamped is not original
                assert trace.TRACE_KEY not in original


# ---------------------------------------------------------------------------
# 2. registry units
# ---------------------------------------------------------------------------

class TestRegistryUnits:
    def test_flatten_key_grammar(self):
        flat = flatten({"a": 1, "b": {"c": 2.5, "d": {"e": 3}},
                        "on": True, "name": "x", "ws": [1, 2, 3]},
                       "nomad.p")
        assert flat == {"nomad.p.a": 1, "nomad.p.b.c": 2.5,
                        "nomad.p.b.d.e": 3, "nomad.p.on": 1,
                        "nomad.p.name": "x", "nomad.p.ws.len": 3}

    def test_register_snapshot_deregister(self):
        reg = MetricsRegistry()
        tok = reg.register("broker", lambda: {"ready": 4})
        assert reg.snapshot() == {"nomad.broker.ready": 4}
        assert reg.providers() == ["broker"]
        assert reg.deregister(tok)
        assert reg.snapshot() == {} and not reg.deregister(tok)

    def test_same_name_replaces(self):
        reg = MetricsRegistry()
        reg.register("x", lambda: {"v": 1})
        reg.register("x", lambda: {"v": 2})
        assert reg.snapshot() == {"nomad.x.v": 2}
        assert reg.providers() == ["x"]

    def test_erroring_provider_is_isolated(self):
        reg = MetricsRegistry()
        reg.register("bad", lambda: 1 / 0)
        reg.register("good", lambda: {"v": 1})
        snap = reg.snapshot()
        assert snap["nomad.good.v"] == 1
        assert "ZeroDivisionError" in snap["nomad.bad.error"]

    def test_publish_sets_gauges_numeric_only(self):
        from nomad_tpu.utils.metrics import Metrics

        reg = MetricsRegistry()
        reg.register("p", lambda: {"depth": 3, "state": "normal"})
        m = Metrics()
        assert reg.publish(m) == 1
        assert m.inmem.snapshot()["gauges"] == {"nomad.p.depth": 3.0}

    def test_extra_registries_merge(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.register("one", lambda: {"v": 1})
        b.register("two", lambda: {"v": 2})
        assert a.snapshot(extra=[b]) == {"nomad.one.v": 1,
                                         "nomad.two.v": 2}


# ---------------------------------------------------------------------------
# 3. flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_incident_file_shape_and_sections(self, tmp_path):
        reg = MetricsRegistry()
        reg.register("broker", lambda: {"ready": 2})
        with trace.tracing(seed=5) as t:
            t.anchor("eval.created", eval_id="e1")
            with flight.installed(str(tmp_path), registries=[reg]):
                path = flight.trip("breaker.open", {"opens": 1})
        assert path is not None
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["reason"] == "breaker.open"
        assert doc["extra"] == {"opens": 1}
        assert any(s["name"] == "eval.created" for s in doc["spans"])
        # The pprof-goroutine analogue: this very thread's stack shows.
        assert any("test" in k.lower() or "main" in k.lower()
                   for k in doc["thread_stacks"])
        assert doc["metrics"]["providers"]["nomad.broker.ready"] == 2
        assert "counters" in doc["metrics"]["inmem"]

    def test_rate_limit_and_stats(self, tmp_path):
        with flight.installed(str(tmp_path), min_interval=60.0) as rec:
            assert flight.trip("overload.enter") is not None
            assert flight.trip("overload.enter") is None  # suppressed
            assert flight.trip("breaker.open") is not None  # other reason
            st = rec.stats()
            assert st["trips"] == 2 and st["suppressed"] == 1
            assert st["on_disk"] == 2

    def test_on_disk_bound_prunes_oldest(self, tmp_path):
        with flight.installed(str(tmp_path), max_files=3,
                              min_interval=0.0) as rec:
            for i in range(6):
                assert flight.trip(f"r{i}") is not None
            names = rec.incidents()
            assert len(names) == 3
            assert names[-1].startswith("incident-0006")

    def test_span_section_is_bounded(self, tmp_path):
        with trace.tracing(seed=5) as t:
            for _ in range(300):
                t.record("s", 0.0, 0.0)
            with flight.installed(str(tmp_path), max_spans=16):
                path = flight.trip("stall.test")
        with open(path) as fh:
            assert len(json.load(fh)["spans"]) == 16

    def test_stall_watchdog_trips_and_disarm_does_not(self, tmp_path):
        with flight.installed(str(tmp_path)) as rec:
            with flight.guard("fast.section", timeout=5.0):
                pass  # disarmed in time: no incident
            with flight.guard("slow.section", timeout=0.05):
                wait_until(lambda: rec.incidents(), timeout=5.0)
            names = rec.incidents()
            assert len(names) == 1 and "stall.slow.section" in names[0]
        # uninstall joined the watchdog thread.
        assert not any(th.name == "flight-stall-watchdog"
                       for th in threading.enumerate())

    def test_stall_guard_extra_fn_names_the_slow_component(self,
                                                           tmp_path):
        """ISSUE 13 satellite: the applier.window stall guard's
        incident dump carries the component executor's per-component
        attribution — a wedged window names WHAT it was verifying, not
        just that it wedged."""
        from nomad_tpu.server.plan_apply import ComponentExecutor

        executor = ComponentExecutor(workers=1)
        started = threading.Event()
        release = threading.Event()

        def slow():
            started.set()
            release.wait(10.0)
            return []

        runner = threading.Thread(
            target=lambda: executor.run_components(
                [slow, lambda: []],
                descs=[{"component": 0, "plans": 7,
                        "eval_ids": ["ev-stuck"]}, None]))
        with flight.installed(str(tmp_path)) as rec:
            runner.start()
            try:
                assert started.wait(5.0)
                with flight.guard("applier.window", timeout=0.05,
                                  extra_fn=executor.active):
                    wait_until(lambda: rec.incidents(), timeout=5.0)
            finally:
                release.set()
                runner.join(5.0)
                executor.stop()
            names = rec.incidents()
            assert len(names) == 1 and "applier.window" in names[0]
            with open(os.path.join(str(tmp_path), names[0])) as fh:
                doc = json.load(fh)
            verifying = doc["extra"]["verifying"]
            assert any("ev-stuck" in str(v.get("eval_ids"))
                       for v in verifying), \
                "the incident must name the slow component"
            assert "stalled_for_s" in doc["extra"]

    def test_breaker_open_trips(self, tmp_path):
        from nomad_tpu.scheduler.breaker import DeviceCircuitBreaker

        breaker = DeviceCircuitBreaker(failure_threshold=2)
        with flight.installed(str(tmp_path)) as rec:
            breaker.record_failure()
            assert rec.incidents() == []  # below the threshold
            breaker.record_failure()      # CLOSED -> OPEN
            names = rec.incidents()
            assert len(names) == 1 and "breaker.open" in names[0]

    def test_overload_entry_trips(self, tmp_path):
        from nomad_tpu.server.overload import OverloadController

        depth = [0]
        ctrl = OverloadController(brownout_ratio=0.5, overload_ratio=0.9)
        ctrl.add_source("q", lambda: (depth[0], 10))
        with flight.installed(str(tmp_path)) as rec:
            assert ctrl.state() == "normal" and rec.incidents() == []
            depth[0] = 10
            assert ctrl.state() == "overload"
            names = rec.incidents()
            assert len(names) == 1 and "overload.enter" in names[0]
            # Staying in overload is not a new entry edge.
            assert ctrl.state() == "overload"
            assert len(rec.incidents()) == 1

    def test_uninstalled_trip_is_noop(self):
        assert flight.INSTALLED is False
        assert flight.trip("breaker.open") is None


class TestFlightRecorderEdges:
    """ISSUE 14 satellite: the rate-limit window and max_files pruning
    get direct edge-case coverage, and incident JSON carries the
    controller's per-knob positions via the recorder-level extra_fn
    hook."""

    def test_same_reason_burst_rate_limits_per_reason(self, tmp_path):
        clock = [100.0]
        rec = flight.FlightRecorder(str(tmp_path), min_interval=5.0,
                                    clock=lambda: clock[0])
        # A burst of the SAME reason inside the window: one file.
        assert rec.record("control.reversal") is not None
        for _ in range(10):
            assert rec.record("control.reversal") is None
        # A different reason is a different window.
        assert rec.record("control.rail") is not None
        st = rec.stats()
        assert st["trips"] == 2 and st["suppressed"] == 10
        # The window is per-reason AND sliding: advancing past it
        # re-arms exactly that reason.
        clock[0] += 5.1
        assert rec.record("control.reversal") is not None
        assert rec.record("control.reversal") is None

    def test_prune_order_under_mixed_reasons(self, tmp_path):
        """max_files keeps the NEWEST incidents by sequence regardless
        of reason interleaving (the zero-padded seq prefix IS the sort
        key; a burst of reason-B files must evict old reason-A ones)."""
        rec = flight.FlightRecorder(str(tmp_path), max_files=3,
                                    min_interval=0.0)
        reasons = ["overload.enter", "control.rail", "breaker.open",
                   "control.reversal", "stall.applier.window"]
        for reason in reasons:
            assert rec.record(reason) is not None
        names = rec.incidents()
        assert len(names) == 3
        assert [n.split("-")[1] for n in names] == \
            ["0003", "0004", "0005"]
        assert "breaker.open" in names[0]
        assert "stall.applier.window" in names[-1]

    def test_extra_fn_carries_controller_positions(self, tmp_path):
        """Every incident — whatever tripped it — names where every
        control knob sat, via the recorder's extra_fn hook (the
        controller's positions() is the intended payload)."""
        from nomad_tpu.control import AIMD, Actuator, Controller

        ctl = Controller(lambda: {}, interval=0.05)
        state = {"v": 6}
        ctl.add_knob(
            Actuator("pipeline.depth", get=lambda: state["v"],
                     set=lambda v: state.__setitem__("v", v),
                     lo=1, hi=16, integer=True),
            law=AIMD(), driver=lambda view: 0)
        rec = flight.install(str(tmp_path), extra_fn=ctl.positions)
        try:
            path = flight.trip("breaker.open", {"opens": 1})
        finally:
            flight.uninstall()
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["extra"]["opens"] == 1  # the trigger's extra kept
        assert doc["extra"]["context"] == {"pipeline.depth": 6}

    def test_broken_extra_fn_does_not_eat_the_incident(self, tmp_path):
        def boom():
            raise RuntimeError("context bug")
        rec = flight.FlightRecorder(str(tmp_path), extra_fn=boom)
        path = rec.record("breaker.open")
        assert path is not None
        with open(path) as fh:
            assert "context" not in json.load(fh)["extra"]


class TestRegistryCollect:
    """ISSUE 14 satellite: collect() = snapshot() hardened for the
    serving surface — per-provider age_s staleness stamps and a sample
    deadline that isolates a hung provider instead of blocking the
    whole collection."""

    def test_age_stamps_track_value_changes(self):
        clock = [50.0]
        reg = MetricsRegistry(clock=lambda: clock[0])
        live = [0]
        reg.register("live", lambda: {"n": live[0]})
        reg.register("frozen", lambda: {"n": 1})
        reg.collect()
        clock[0] += 10.0
        live[0] += 1
        out = reg.collect()
        assert out["nomad.live.age_s"] == 0.0     # changed this sample
        assert out["nomad.frozen.age_s"] == 10.0  # frozen for 10s
        clock[0] += 5.0
        out = reg.collect()
        assert out["nomad.live.age_s"] == 5.0
        assert out["nomad.frozen.age_s"] == 15.0

    def test_hung_provider_isolated_by_sample_timeout(self):
        reg = MetricsRegistry()
        release = threading.Event()

        def hung():
            release.wait(30.0)
            return {"late": 1}
        reg.register("hung", hung)
        reg.register("fine", lambda: {"ok": 1})
        t0 = time.monotonic()
        out = reg.collect(timeout=0.2)
        try:
            wall = time.monotonic() - t0
            assert wall < 2.0  # the hang never blocks the collection
            assert "timeout" in out["nomad.hung.error"]
            assert out["nomad.fine.ok"] == 1
            # The abandoned sampler's late result can never pollute a
            # LATER collect (its queues died with it).
            release.set()
            out2 = reg.collect(timeout=1.0)
            assert out2.get("nomad.hung.late") == 1
            assert "nomad.hung.error" not in out2
        finally:
            release.set()
            reg.clear()  # reaps the parked sampler thread

    def test_erroring_provider_keeps_its_age_baseline(self):
        clock = [10.0]
        reg = MetricsRegistry(clock=lambda: clock[0])
        fail = [False]

        def flappy():
            if fail[0]:
                raise RuntimeError("torn down")
            return {"n": 1}
        reg.register("flappy", flappy)
        reg.collect()
        clock[0] += 3.0
        fail[0] = True
        out = reg.collect()
        # The .error path still stamps how long the last good value
        # has been standing.
        assert "torn down" in out["nomad.flappy.error"]
        assert out["nomad.flappy.age_s"] == 3.0

    def test_error_path_races_replace_on_name(self):
        """The erroring-provider path racing register() replacing the
        same name: collection never raises, and once the replacement
        lands its staleness clock starts fresh (the successor is not
        blamed for the predecessor's errors)."""
        clock = [0.0]
        reg = MetricsRegistry(clock=lambda: clock[0])

        def broken():
            raise RuntimeError("always failing")
        reg.register("racy", broken)
        stop = threading.Event()
        errors: list = []

        def collector():
            while not stop.is_set():
                try:
                    reg.collect()
                except Exception as e:  # pragma: no cover
                    errors.append(repr(e))

        t = threading.Thread(target=collector, daemon=True)
        t.start()
        try:
            for _ in range(50):
                reg.register("racy", broken)
                reg.register("racy", lambda: {"ok": 1})
        finally:
            stop.set()
            t.join(5.0)
        assert errors == []
        # Replace-on-name resets the age baseline: a provider
        # registered AFTER the collector stopped (so nothing sampled
        # it yet) starts its staleness clock at its own first sample.
        clock[0] = 7.0
        reg.register("racy", lambda: {"ok": 1})
        out = reg.collect()
        assert out["nomad.racy.ok"] == 1
        assert out["nomad.racy.age_s"] == 0.0

    def test_collect_snapshot_parity_and_extra(self):
        reg = MetricsRegistry()
        reg.register("a", lambda: {"x": 1, "flag": True})
        other = MetricsRegistry()
        other.register("b", lambda: {"y": 2})
        snap = reg.snapshot(extra=[other])
        out = reg.collect(extra=[other])
        for key, val in snap.items():
            assert out[key] == val  # same grammar, plus age stamps
        assert "nomad.a.age_s" in out and "nomad.b.age_s" in out


# ---------------------------------------------------------------------------
# 4. span trees on a live server
# ---------------------------------------------------------------------------

def _eval_spans(tracer, eval_id: str) -> list:
    return [s for s in tracer.snapshot()
            if (s.get("tags") or {}).get("eval_id") == eval_id]


def _assert_single_rooted_closed(spans: list, eval_id: str) -> dict:
    """The tree bar: every span closed (a duration, a trace id), ONE
    span whose parent lies outside the eval's set (the anchor hanging
    off the serving RPC), everything else parented within."""
    assert spans, f"eval {eval_id} recorded no spans"
    ids = {s["span_id"] for s in spans}
    assert len(ids) == len(spans), "duplicate span ids"
    roots = [s for s in spans if s["parent_id"] not in ids]
    assert len(roots) == 1, (
        f"eval {eval_id}: want exactly one root, got "
        f"{[(s['name'], s['parent_id']) for s in roots]}")
    assert roots[0]["name"] == "eval.created"
    assert len({s["trace_id"] for s in spans}) == 1
    for s in spans:
        assert s["dur"] >= 0.0
    return roots[0]


class TestSpanTreesLiveServer:
    SUBMIT = RetryPolicy(base=0.1, max_delay=0.5, max_attempts=10,
                         retryable=lambda e: isinstance(e, Exception),
                         name="obs.submit")

    def test_span_trees_complete_under_seeded_faults(self):
        """Seeded rpc.send/rpc.recv drops on submission plus a
        raft.apply error (the plan batch fails once, the broker
        redelivers, the retry commits): every terminal eval still has a
        closed single-rooted tree, and exactly-once placements carry
        exactly-once upsert accounting."""
        plan = FaultPlan.parse(
            "seed=10;"
            "rpc.send=drop(p=0.5,count=2,method=Job.Register);"
            "rpc.recv=drop(p=0.5,count=2,method=Job.Register);"
            "raft.apply=error(after=8,count=1)")
        with trace.tracing(seed=10) as tracer:
            with faultinject.injected(plan):
                srv = Server(ServerConfig(num_schedulers=2,
                                          enable_rpc=True,
                                          eval_nack_timeout=5.0))
                srv.establish_leadership()
                pool = ConnPool()
                try:
                    addr = srv.rpc_address()
                    for i in range(8):
                        self.SUBMIT.call(
                            lambda n=mock.node(i): pool.call(
                                addr, "Node.Register",
                                {"node": n.to_dict()}, timeout=2.0))
                    jobs = [_job(2) for _ in range(6)]
                    eval_ids = []
                    for job in jobs:
                        # timeout=2.0: a recv-dropped frame gets no
                        # reply at all — the retry policy must see a
                        # bounded timeout, not the 330s default.
                        out = self.SUBMIT.call(
                            lambda j=job: pool.call(
                                addr, "Job.Register",
                                {"job": j.to_dict()}, timeout=2.0))
                        eval_ids.append(out["eval_id"])

                    def terminal():
                        return all(
                            (srv.fsm.state.eval_by_id(eid) or
                             mock.job()).status in TERMINAL
                            if srv.fsm.state.eval_by_id(eid) else False
                            for eid in eval_ids)
                    wait_until(terminal, timeout=30.0)

                    state = srv.fsm.state
                    for eid in eval_ids:
                        ev = state.eval_by_id(eid)
                        assert ev.status == "complete", (eid, ev.status)
                        spans = _eval_spans(tracer, eid)
                        _assert_single_rooted_closed(spans, eid)
                        # Exactly-once: each placed alloc id appears
                        # once in state, and the upsert spans account
                        # for every placement exactly once.
                        allocs = [a for a in state.allocs_by_eval(eid)
                                  if a.node_id]
                        assert len({a.id for a in allocs}) == len(allocs)
                        upserts = [s for s in spans
                                   if s["name"] == "store.upsert"]
                        assert upserts, f"eval {eid}: no upsert span"
                        assert sum((s.get("tags") or {})["n_allocs"]
                                   for s in upserts) == len(allocs)
                    # The seeded fault really fired (else this proves
                    # nothing about plan retries).
                    assert plan.fire_count("raft.apply") == 1
                finally:
                    pool.shutdown()
                    srv.shutdown()

    def test_chaos_eval_exports_chrome_trace_across_planes(self,
                                                          tmp_path):
        """ISSUE acceptance: one seeded chaos eval's exported
        Chrome-trace tree spans agent edge -> broker -> scheduler
        stages -> window verify -> raft apply -> store upsert."""
        from nomad_tpu.agent import Agent, AgentConfig

        plan = FaultPlan.parse("seed=11;raft.apply=delay(secs=0.002,p=0.5)")
        with trace.tracing(seed=11) as tracer:
            with faultinject.injected(plan):
                agent = Agent(AgentConfig(server_enabled=True,
                                          http_port=0, rpc_port=0))
                try:
                    srv = agent.server
                    for i in range(8):
                        srv.node_register(mock.node(i))
                    out = agent.rpc("Job.Register",
                                    {"job": _job(3).to_dict()})
                    eval_id = out["eval_id"]
                    wait_until(
                        lambda: (srv.fsm.state.eval_by_id(eval_id)
                                 is not None and
                                 srv.fsm.state.eval_by_id(eval_id)
                                 .status in TERMINAL),
                        timeout=20.0)
                    assert srv.fsm.state.eval_by_id(eval_id).status == \
                        "complete"

                    spans = _eval_spans(tracer, eval_id)
                    root = _assert_single_rooted_closed(spans, eval_id)
                    names = {s["name"] for s in spans}
                    # The full plane walk.  Scheduler stages come from
                    # the fused batch worker (sched.*) or the plain
                    # worker (worker.invoke) depending on the backend.
                    assert "broker.wait" in names
                    assert {"sched.begin", "sched.submit"} <= names or \
                        "worker.invoke" in names
                    assert "applier.verify" in names   # window verify
                    assert "raft.apply" in names
                    assert "fsm.decode" in names
                    assert "store.upsert" in names
                    # Agent edge: the anchor's parent chain reaches the
                    # serving RPC span, whose parent is the in-proc
                    # client span — the trace's root.
                    all_spans = {s["span_id"]: s
                                 for s in tracer.snapshot()}
                    serve = all_spans[root["parent_id"]]
                    assert serve["name"] == "rpc.serve.Job.Register"
                    client = all_spans[serve["parent_id"]]
                    assert client["name"] == "rpc.client.Job.Register"
                    assert client["parent_id"] is None

                    # Export and re-read: the file is Chrome-trace
                    # loadable JSON with the whole walk inside.
                    path = str(tmp_path / "chaos-eval.json")
                    n = tracer.export_chrome(path)
                    with open(path) as fh:
                        doc = json.load(fh)
                    assert len(doc["traceEvents"]) == n >= len(spans)
                    exported = {e["name"] for e in doc["traceEvents"]
                                if e["args"].get("eval_id") == eval_id}
                    assert {"applier.verify", "raft.apply",
                            "store.upsert"} <= exported
                finally:
                    agent.shutdown()

    def test_metrics_endpoint_table(self):
        """/v1/agent/metrics beside the reference agent endpoint table
        (command/agent/http.go route registrations): the unified
        registry document over live HTTP, with every expected provider
        present and the in-mem sink riding along."""
        from nomad_tpu.agent import Agent, AgentConfig
        from nomad_tpu.api import APIClient

        agent = Agent(AgentConfig(server_enabled=True, http_port=0,
                                  rpc_port=0))
        try:
            client = APIClient(
                f"http://{agent.http.address[0]}:"
                f"{agent.http.address[1]}")
            doc = client.agent_metrics()
            providers = {k.split(".")[1] for k in doc["providers"]}
            assert {"broker", "plan_queue", "applier", "overload",
                    "heartbeat", "store", "workers", "rpc", "http",
                    "breaker"} <= providers
            # Key grammar: nomad.<provider>.<path...>, numeric gauges.
            assert doc["providers"]["nomad.plan_queue.depth"] == 0
            assert doc["providers"]["nomad.overload.state"] == "normal"
            assert isinstance(
                doc["providers"]["nomad.store.tables.nodes"], int)
            assert "counters" in doc["inmem"]

            # The CLI dump rides the same endpoint.
            from nomad_tpu.cli.main import main as cli_main
            rc = cli_main(
                ["-address", client.address, "metrics", "-filter",
                 "plan_queue"])
            assert rc == 0
        finally:
            agent.shutdown()

    def test_metrics_watch_mode(self, capsys):
        """ISSUE 14 satellite: `nomad-tpu metrics -watch N` re-samples
        every N seconds and renders deltas (rates for counters) —
        bounded here by -rounds; the substring filter rides to the
        server as ?filter= so the polled payload stays small."""
        from nomad_tpu.agent import Agent, AgentConfig
        from nomad_tpu.api import APIClient
        from nomad_tpu.cli.main import main as cli_main

        agent = Agent(AgentConfig(server_enabled=True, http_port=0,
                                  rpc_port=0))
        try:
            client = APIClient(
                f"http://{agent.http.address[0]}:"
                f"{agent.http.address[1]}")
            # Server-side filter: only matching provider keys return.
            doc = client.agent_metrics(filter="plan_queue")
            assert doc["providers"]
            assert all("plan_queue" in k for k in doc["providers"])

            rc = cli_main(
                ["-address", client.address, "metrics",
                 "-watch", "0.05", "-rounds", "2",
                 "-filter", "plan_queue"])
            assert rc == 0
            out = capsys.readouterr().out
            # Round 1 prints the listing; later rounds print the delta
            # header and per-key rates.
            assert "nomad.plan_queue.depth = 0" in out
            assert out.count("keys changed") == 2
            assert "/s)" in out
        finally:
            agent.shutdown()

    def test_registry_clears_on_server_shutdown(self):
        srv = Server(ServerConfig(num_schedulers=0))
        assert "broker" in srv.obs_registry.providers()
        srv.shutdown()
        assert srv.obs_registry.providers() == []


# ---------------------------------------------------------------------------
# 5. the tier-1 overhead assertion
# ---------------------------------------------------------------------------

class TestTracingOverhead:
    def _stream(self, h, jobs) -> float:
        from nomad_tpu.scheduler.pipeline import PipelinedEvalRunner

        class _Rec:
            def __init__(self):
                self.plans = []

            def submit_plan(self, plan):
                from nomad_tpu.structs import PlanResult
                self.plans.append(plan)
                result = PlanResult(
                    node_update=dict(plan.node_update),
                    node_allocation=dict(plan.node_allocation))
                return result, None

            def update_eval(self, ev):
                pass

            def create_eval(self, ev):
                pass

        best = float("inf")
        for _ in range(5):
            rec = _Rec()
            runner = PipelinedEvalRunner(h.state.snapshot(), rec,
                                         depth=4)
            evals = []
            for j in jobs:
                from nomad_tpu.structs import Evaluation, generate_uuid
                evals.append(Evaluation(
                    id=generate_uuid(), priority=j.priority,
                    type="service", triggered_by="job-register",
                    job_id=j.id, status="pending"))
            t0 = time.perf_counter()
            runner.process(evals)
            best = min(best, time.perf_counter() - t0)
            assert len(rec.plans) == len(jobs)
        return best

    def test_tracing_on_overhead_bounded(self):
        """The tier-1 tripwire behind bench.py's 5% assertion: on a
        small stream the tracing-ON best-of-5 must stay within 50% of
        OFF (generous — CI noise — but a hot path that started
        allocating per-span dicts with tracing OFF, or an O(n) tracer
        regression, blows way past it)."""
        from nomad_tpu.scheduler.harness import Harness

        h = Harness()
        for i in range(64):
            h.state.upsert_node(h.next_index(), mock.node(i))
        jobs = [_job(4) for _ in range(12)]
        for j in jobs:
            h.state.upsert_job(h.next_index(), j)
        self._stream(h, jobs)  # warm compile/prep caches
        off = self._stream(h, jobs)
        with trace.tracing(seed=2):
            on = self._stream(h, jobs)
        off2 = self._stream(h, jobs)
        baseline = min(off, off2)
        assert on <= baseline * 1.5 + 0.005, (
            f"tracing-on stream {on * 1000:.1f}ms vs off "
            f"{baseline * 1000:.1f}ms (> 1.5x + 5ms)")

    def test_disabled_sites_skip_the_tracer_entirely(self):
        """With tracing off the instrumentation is one module-bool
        read: no tracer exists to record into, and a stream leaves no
        spans behind when tracing is enabled AFTERWARDS."""
        assert trace.ENABLED is False
        from nomad_tpu.scheduler.harness import Harness

        h = Harness()
        for i in range(8):
            h.state.upsert_node(h.next_index(), mock.node(i))
        job = _job(2)
        h.state.upsert_job(h.next_index(), job)
        self._stream(h, [job])
        with trace.tracing(seed=4) as t:
            assert t.snapshot() == []
