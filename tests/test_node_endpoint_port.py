"""Port of the reference node endpoint table
(nomad/node_endpoint_test.go, v0.1.2): register / heartbeat /
deregister / status-transition behavior over the wire method table —
asserting heartbeat TTL responses and node-status transitions.

Every call here rides the full endpoint chain, which now includes the
overload admission wrapper (server/overload.py): node lifecycle is
system class and heartbeats ride the bypass lane, so this table is also
the regression net proving admission never starves node liveness —
including under a FORCED overload state (the last tests).
"""
from __future__ import annotations

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent.agent import InprocRPC
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.overload import OVERLOAD
from nomad_tpu.structs import (
    NODE_STATUS_DOWN,
    NODE_STATUS_INIT,
    NODE_STATUS_READY,
)


@pytest.fixture
def rig():
    srv = Server(ServerConfig(num_schedulers=0))
    srv.establish_leadership()
    rpc = InprocRPC(srv)
    yield srv, rpc
    srv.shutdown()


def _register(rpc, node):
    return rpc.call("Node.Register", {"node": node.to_dict()})


class TestNodeRegister:
    def test_register_returns_index_and_ttl(self, rig):
        """TestClientEndpoint_Register: the response carries the raft
        index, a heartbeat TTL (leader only), and the node is in
        state."""
        srv, rpc = rig
        node = mock.node(1)
        resp = _register(rpc, node)
        assert resp["index"] > 0
        assert resp["heartbeat_ttl"] >= srv.heartbeats.min_ttl
        out = srv.fsm.state.node_by_id(node.id)
        assert out is not None
        assert out.status == NODE_STATUS_READY
        assert out.create_index == resp["index"]

    def test_register_missing_node_id_errors(self, rig):
        _srv, rpc = rig
        node = mock.node(1)
        node.id = ""
        with pytest.raises(ValueError, match="missing node ID"):
            _register(rpc, node)

    def test_register_missing_datacenter_errors(self, rig):
        _srv, rpc = rig
        node = mock.node(1)
        node.datacenter = ""
        with pytest.raises(ValueError, match="missing datacenter"):
            _register(rpc, node)

    def test_ready_register_with_allocs_creates_evals(self, rig):
        """node_endpoint.go:64-90: a (re-)registering ready node with
        schedulable work triggers node-update evaluations."""
        srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        alloc = mock.alloc()
        alloc.node_id = node.id
        idx = srv.raft.applied_index()
        srv.fsm.state.upsert_job(idx + 1, alloc.job)
        srv.fsm.state.upsert_allocs(idx + 2, [alloc])
        resp = _register(rpc, node)
        assert resp["eval_ids"], "re-register must evaluate node work"
        ev = srv.fsm.state.eval_by_id(resp["eval_ids"][0])
        assert ev is not None and ev.triggered_by == "node-update"
        assert ev.job_id == alloc.job_id

    def test_init_register_creates_no_evals(self, rig):
        _srv, rpc = rig
        node = mock.node(1)
        node.status = NODE_STATUS_INIT
        resp = _register(rpc, node)
        assert resp["eval_ids"] == []


class TestNodeHeartbeat:
    def test_heartbeat_resets_ttl(self, rig):
        """TestClientEndpoint_UpdateStatus_HeartbeatOnly shape: each
        heartbeat returns a fresh TTL and re-arms the timer."""
        srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        assert srv.heartbeats.active() == 1
        resp = rpc.call("Node.Heartbeat", {"node_id": node.id})
        assert resp["heartbeat_ttl"] >= srv.heartbeats.min_ttl
        assert srv.heartbeats.active() == 1

    def test_heartbeat_unknown_node_errors(self, rig):
        _srv, rpc = rig
        with pytest.raises(KeyError):
            rpc.call("Node.Heartbeat", {"node_id": "nope"})

    def test_update_status_ready_returns_ttl_down_does_not(self, rig):
        """TestClientEndpoint_UpdateStatus: only the ready transition
        re-arms a TTL; down marks the node and spawns evals."""
        srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        resp = rpc.call("Node.UpdateStatus",
                        {"node_id": node.id, "status": "ready"})
        assert resp["heartbeat_ttl"] > 0
        resp = rpc.call("Node.UpdateStatus",
                        {"node_id": node.id, "status": "down"})
        assert resp["heartbeat_ttl"] == 0.0
        assert srv.fsm.state.node_by_id(node.id).status == \
            NODE_STATUS_DOWN

    def test_update_status_invalid_errors(self, rig):
        _srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        with pytest.raises(ValueError, match="invalid node status"):
            rpc.call("Node.UpdateStatus",
                     {"node_id": node.id, "status": "sideways"})


class TestNodeDeregister:
    def test_deregister_removes_node(self, rig):
        """TestClientEndpoint_Deregister: the node leaves state and the
        index advances."""
        srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        resp = rpc.call("Node.Deregister", {"node_id": node.id})
        assert resp["index"] > 0
        assert srv.fsm.state.node_by_id(node.id) is None

    def test_deregister_with_allocs_creates_evals(self, rig):
        """node_endpoint.go: deregistering a node with live allocs must
        evaluate every affected job so its work is replaced."""
        srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        alloc = mock.alloc()
        alloc.node_id = node.id
        idx = srv.raft.applied_index()
        srv.fsm.state.upsert_job(idx + 1, alloc.job)
        srv.fsm.state.upsert_allocs(idx + 2, [alloc])
        rpc.call("Node.Deregister", {"node_id": node.id})
        evs = [e for e in srv.fsm.state.evals()
               if e.triggered_by == "node-update"
               and e.node_id == node.id]
        assert len(evs) == 1 and evs[0].job_id == alloc.job_id


class TestNodeQueries:
    def test_get_node_round_trip(self, rig):
        srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        resp = rpc.call("Node.GetNode", {"node_id": node.id})
        assert resp["node"]["id"] == node.id
        assert resp["index"] == srv.fsm.state.get_index("nodes")
        assert rpc.call("Node.GetNode",
                        {"node_id": "nope"})["node"] is None

    def test_get_allocs_and_list(self, rig):
        srv, rpc = rig
        node = mock.node(1)
        _register(rpc, node)
        alloc = mock.alloc()
        alloc.node_id = node.id
        srv.fsm.state.upsert_allocs(srv.raft.applied_index() + 1,
                                    [alloc])
        resp = rpc.call("Node.GetAllocs", {"node_id": node.id})
        assert [a["id"] for a in resp["allocs"]] == [alloc.id]
        resp = rpc.call("Node.List", {})
        assert [n["id"] for n in resp["nodes"]] == [node.id]


class TestAdmissionPath:
    """The new part of the chain: the whole table above already rides
    the admission wrapper; these pin the OVERLOAD-state behavior."""

    def test_node_lifecycle_survives_full_overload(self, rig):
        """Node register/heartbeat/status/deregister are system class
        and heartbeats bypass admission: a fully overloaded server
        still serves ALL of them — shedding liveness would amplify the
        overload into a TTL-expiry storm."""
        srv, rpc = rig
        srv.overload.force_state(OVERLOAD)
        node = mock.node(1)
        resp = _register(rpc, node)
        assert resp["heartbeat_ttl"] > 0
        assert rpc.call("Node.Heartbeat",
                        {"node_id": node.id})["heartbeat_ttl"] > 0
        rpc.call("Node.UpdateStatus",
                 {"node_id": node.id, "status": "ready"})
        rpc.call("Node.Deregister", {"node_id": node.id})
        assert srv.fsm.state.node_by_id(node.id) is None
        assert srv.overload.stats()["heartbeat_lane"] >= 1

    def test_job_submission_sheds_in_overload(self, rig):
        from nomad_tpu.server.overload import ErrOverloaded

        srv, rpc = rig
        srv.overload.force_state(OVERLOAD)
        with pytest.raises(ErrOverloaded):
            rpc.call("Job.Register", {"job": mock.job().to_dict()})
        srv.overload.force_state(None)
        assert rpc.call("Job.Register",
                        {"job": mock.job().to_dict()})["eval_id"]
