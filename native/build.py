"""Build the _nomad_native C++ extension in place.

Usage: python native/build.py
Produces _nomad_native.<abi>.so next to the nomad_tpu package; the package
auto-detects it (nomad_tpu/utils/native.py) and falls back to pure Python
when absent.
"""
from __future__ import annotations

import os
import subprocess
import sys
import sysconfig


def build() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    src = os.path.join(here, "port_alloc.cpp")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(repo, f"_nomad_native{suffix}")
    include = sysconfig.get_paths()["include"]
    # Compile to a per-process temp name, then atomically rename: a
    # concurrent importer never sees a partially written .so.
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = [
        os.environ.get("CXX", "g++"), "-O2", "-std=c++17", "-shared",
        "-fPIC", f"-I{include}", src, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out


if __name__ == "__main__":
    path = build()
    print(f"built {path}")
    sys.path.insert(0, os.path.dirname(path))
    import _nomad_native

    ports = _nomad_native.assign_ports({22, 80}, [8080], 2, 20000, 60000,
                                       20)
    assert ports is not None and ports[0] == 8080 and len(ports) == 3
    assert _nomad_native.assign_ports({22}, [22], 0, 20000, 60000, 20) \
        is None
    used: set = set()
    assert _nomad_native.add_all(used, [1, 2, 3]) is False
    assert _nomad_native.add_all(used, [3]) is True
    print("self-test ok")
