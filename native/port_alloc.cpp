// _nomad_native: C++ hot-path helpers for the host scheduling plane.
//
// The reference implements its entire runtime in Go; our host plane is
// Python, and profiling shows the per-placement dynamic-port assignment
// (nomad_tpu/structs/network.py assign_network -- the sequential, stateful
// part of placement that cannot move to the TPU) dominating host time at
// 10k-node scale.  This module implements that inner loop in C++ against
// CPython sets, plus a bulk random-port reservation primitive.
//
// Built as a CPython extension (no pybind11; plain C API) by
// native/build.py; nomad_tpu falls back to the pure-Python path when the
// extension is unavailable.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <random>
#include <vector>

namespace {

thread_local std::mt19937 rng{std::random_device{}()};

// GC-untrack a freshly built, final-state object (no-op if untracked).
//
// Every object bulk_finish creates is acyclic BY CONSTRUCTION: allocs /
// metrics / resources / offers form trees whose only outbound edges go
// to long-lived store objects (job, strings) that never point back
// (nomad_tpu/state/store.py's immutability contract).  Refcounting alone
// reclaims them; leaving them GC-tracked only makes every young-gen
// collection scan the full burst (~1M objects per 64-eval storm, ~0.5 s
// of scanning that finds zero garbage) and re-scan the store's alloc
// table forever after.  Untracking is applied strictly AFTER an object's
// last mutation — CPython re-tracks dicts on insertion of container
// values, so ordering matters for dicts (instances and lists stay
// untracked once untracked).  tests/test_gc_untrack.py asserts these
// objects are still reclaimed by refcount alone.
inline void gc_untrack(PyObject* o) {
  if (o != nullptr) PyObject_GC_UnTrack(o);
}

// assign_ports(used: set[int], reserved: sequence[int], n_dynamic: int,
//              min_port: int, max_port: int, attempts: int)
//   -> list[int] | None
//
// Mirrors NetworkIndex.assign_network's port logic exactly: reserved ports
// must not collide with `used`; each dynamic port is picked uniformly from
// [min_port, max_port) avoiding `used` and already-picked ports, with a
// bounded number of attempts.  Returns the full offer port list
// (reserved + dynamic) or None on failure.  `used` is NOT mutated.
PyObject* assign_ports(PyObject*, PyObject* args) {
  PyObject* used;
  PyObject* reserved;
  Py_ssize_t n_dynamic;
  long min_port, max_port;
  Py_ssize_t attempts;
  if (!PyArg_ParseTuple(args, "OOnlln", &used, &reserved, &n_dynamic,
                        &min_port, &max_port, &attempts)) {
    return nullptr;
  }
  if (!PySet_Check(used)) {
    PyErr_SetString(PyExc_TypeError, "used must be a set");
    return nullptr;
  }

  PyObject* reserved_fast =
      PySequence_Fast(reserved, "reserved must be a sequence");
  if (reserved_fast == nullptr) return nullptr;
  Py_ssize_t n_reserved = PySequence_Fast_GET_SIZE(reserved_fast);

  PyObject* out = PyList_New(0);
  if (out == nullptr) {
    Py_DECREF(reserved_fast);
    return nullptr;
  }

  // Reserved ports: collision -> None.
  for (Py_ssize_t i = 0; i < n_reserved; i++) {
    PyObject* port = PySequence_Fast_GET_ITEM(reserved_fast, i);
    int hit = PySet_Contains(used, port);
    if (hit < 0) goto fail;
    if (hit) {
      Py_DECREF(reserved_fast);
      Py_DECREF(out);
      Py_RETURN_NONE;
    }
    if (PyList_Append(out, port) < 0) goto fail;
  }

  {
    std::uniform_int_distribution<long> dist(min_port, max_port - 1);
    for (Py_ssize_t d = 0; d < n_dynamic; d++) {
      bool placed = false;
      for (Py_ssize_t a = 0; a < attempts; a++) {
        long candidate = dist(rng);
        PyObject* port = PyLong_FromLong(candidate);
        if (port == nullptr) goto fail;
        int hit = PySet_Contains(used, port);
        if (hit < 0) {
          Py_DECREF(port);
          goto fail;
        }
        if (!hit) {
          // Also avoid ports already picked into this offer.
          int dup = PySequence_Contains(out, port);
          if (dup < 0) {
            Py_DECREF(port);
            goto fail;
          }
          if (!dup) {
            int rc = PyList_Append(out, port);
            Py_DECREF(port);
            if (rc < 0) goto fail;
            placed = true;
            break;
          }
        }
        Py_DECREF(port);
      }
      if (!placed) {
        Py_DECREF(reserved_fast);
        Py_DECREF(out);
        Py_RETURN_NONE;
      }
    }
  }

  Py_DECREF(reserved_fast);
  return out;

fail:
  Py_DECREF(reserved_fast);
  Py_DECREF(out);
  return nullptr;
}

// add_all(used: set[int], ports: sequence[int]) -> bool collide
PyObject* add_all(PyObject*, PyObject* args) {
  PyObject* used;
  PyObject* ports;
  if (!PyArg_ParseTuple(args, "OO", &used, &ports)) return nullptr;
  if (!PySet_Check(used)) {
    PyErr_SetString(PyExc_TypeError, "used must be a set");
    return nullptr;
  }
  PyObject* fast = PySequence_Fast(ports, "ports must be a sequence");
  if (fast == nullptr) return nullptr;
  bool collide = false;
  for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(fast); i++) {
    PyObject* port = PySequence_Fast_GET_ITEM(fast, i);
    int hit = PySet_Contains(used, port);
    if (hit < 0) {
      Py_DECREF(fast);
      return nullptr;
    }
    if (hit) {
      collide = true;
    } else if (PySet_Add(used, port) < 0) {
      Py_DECREF(fast);
      return nullptr;
    }
  }
  Py_DECREF(fast);
  return PyBool_FromLong(collide);
}

// format_uuids(data: bytes) -> list[str]
//
// Formats len(data)/16 UUID strings ("8-4-4-4-12" lowercase hex) from raw
// entropy bytes.  The Python twin (structs/model.py generate_uuids) hex()s
// the same buffer and slices; this builds each 36-char ASCII string
// directly.  The scheduler mints one UUID per placement (1k/eval), so the
// slicing loop was visible in profiles.
PyObject* format_uuids(PyObject*, PyObject* args) {
  const char* data;
  Py_ssize_t len;
  if (!PyArg_ParseTuple(args, "y#", &data, &len)) return nullptr;
  if (len % 16 != 0) {
    PyErr_SetString(PyExc_ValueError, "data length must be a multiple of 16");
    return nullptr;
  }
  static const char hexdig[] = "0123456789abcdef";
  // Dash positions in the 36-char output (after hex nibbles 8,12,16,20).
  Py_ssize_t n = len / 16;
  PyObject* out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* s = PyUnicode_New(36, 127);
    if (!s) {
      Py_DECREF(out);
      return nullptr;
    }
    Py_UCS1* w = PyUnicode_1BYTE_DATA(s);
    const unsigned char* b =
        reinterpret_cast<const unsigned char*>(data) + i * 16;
    Py_ssize_t o = 0;
    for (Py_ssize_t j = 0; j < 16; j++) {
      if (j == 4 || j == 6 || j == 8 || j == 10) w[o++] = '-';
      w[o++] = hexdig[b[j] >> 4];
      w[o++] = hexdig[b[j] & 0xF];
    }
    PyList_SET_ITEM(out, i, s);  // steals
  }
  gc_untrack(out);  // strings only: acyclic
  return out;
}

// ---------------------------------------------------------------------------
// bulk_finish: the scheduler finish loop's happy path in C.
//
// nomad_tpu/scheduler/jax_binpack.py finish_deferred constructs one
// Allocation (+ AllocMetric, Resources, NetworkResource, port picks) per
// placement; at 1k placements/eval the CPython interpreter overhead of
// that loop dominates the whole evaluation.  This function executes the
// same per-placement steps through the C API.  It processes a PREFIX of
// the placement list and stops (returning how far it got) at the first
// case that needs Python-side handling — complex network topology,
// bandwidth overflow (divergence fallback), CIDR-derived IPs — so the
// Python general loop resumes exactly where C left off.  Semantics are
// kept bit-identical (same LCG port stream, same dict layouts); parity
// is asserted by tests/test_native_finish.py against a pure-Python run
// with the same seed and uuids.
// ---------------------------------------------------------------------------

struct Interned {
  PyObject* name = nullptr;
  PyObject* task_group = nullptr;
  PyObject* resources = nullptr;
  PyObject* networks = nullptr;
  PyObject* device = nullptr;
  PyObject* ip = nullptr;
  PyObject* mbits = nullptr;
  PyObject* reserved = nullptr;
  PyObject* reserved_ports = nullptr;
  PyObject* dynamic_ports = nullptr;
  PyObject* id = nullptr;
  PyObject* task_resources = nullptr;
  PyObject* metrics = nullptr;
  PyObject* task_states = nullptr;
  PyObject* node_id = nullptr;
  PyObject* desired_status = nullptr;
  PyObject* desired_description = nullptr;
  PyObject* client_status = nullptr;
  PyObject* scores = nullptr;
  PyObject* coalesced = nullptr;
  PyObject* lazy_score_key = nullptr;
  PyObject* lazy_score_val = nullptr;
  PyObject* dunder_new = nullptr;
  PyObject* dunder_dict = nullptr;
  PyObject* proposed_allocs = nullptr;
  PyObject* binpack_suffix = nullptr;
  PyObject* srow = nullptr;
  bool ok = false;
};

Interned& interned() {
  static Interned s;
  if (!s.ok) {
    s.name = PyUnicode_InternFromString("name");
    s.task_group = PyUnicode_InternFromString("task_group");
    s.resources = PyUnicode_InternFromString("resources");
    s.networks = PyUnicode_InternFromString("networks");
    s.device = PyUnicode_InternFromString("device");
    s.ip = PyUnicode_InternFromString("ip");
    s.mbits = PyUnicode_InternFromString("mbits");
    s.reserved = PyUnicode_InternFromString("reserved");
    s.reserved_ports = PyUnicode_InternFromString("reserved_ports");
    s.dynamic_ports = PyUnicode_InternFromString("dynamic_ports");
    s.id = PyUnicode_InternFromString("id");
    s.task_resources = PyUnicode_InternFromString("task_resources");
    s.metrics = PyUnicode_InternFromString("metrics");
    s.task_states = PyUnicode_InternFromString("task_states");
    s.node_id = PyUnicode_InternFromString("node_id");
    s.desired_status = PyUnicode_InternFromString("desired_status");
    s.desired_description =
        PyUnicode_InternFromString("desired_description");
    s.client_status = PyUnicode_InternFromString("client_status");
    s.scores = PyUnicode_InternFromString("scores");
    s.coalesced = PyUnicode_InternFromString("coalesced_failures");
    s.lazy_score_key = PyUnicode_InternFromString("_lazy_score_key");
    s.lazy_score_val = PyUnicode_InternFromString("_lazy_score_val");
    s.dunder_new = PyUnicode_InternFromString("__new__");
    s.dunder_dict = PyUnicode_InternFromString("__dict__");
    s.proposed_allocs = PyUnicode_InternFromString("proposed_allocs");
    s.binpack_suffix = PyUnicode_InternFromString(".binpack");
    s.srow = PyUnicode_InternFromString("_srow");
    s.ok = true;
  }
  return s;
}

// cls.__new__(cls) + inst.__dict__ = d (steals nothing; returns new ref).
PyObject* make_instance(PyObject* cls, PyObject* d) {
  // Plain-Python heap classes (no custom __new__/__slots__ — true for
  // the dataclasses this serves): allocate directly and install the
  // attribute dict, skipping the __new__ descriptor machinery.
  PyTypeObject* tp = (PyTypeObject*)cls;
  PyObject* inst = tp->tp_alloc(tp, 0);
  if (!inst) return nullptr;
  PyObject** dictptr = _PyObject_GetDictPtr(inst);
  if (dictptr) {
    PyObject* old = *dictptr;
    Py_INCREF(d);
    *dictptr = d;
    Py_XDECREF(old);
    return inst;
  }
  if (PyObject_SetAttr(inst, interned().dunder_dict, d) < 0) {
    Py_DECREF(inst);
    return nullptr;
  }
  return inst;
}

// Accumulate one node's proposed-alloc network usage into (used, bw).
int walk_proposed(PyObject* ctx, PyObject* node_id, PyObject* used,
                  long* bw) {
  Interned& I = interned();
  PyObject* allocs =
      PyObject_CallMethodObjArgs(ctx, I.proposed_allocs, node_id, nullptr);
  if (!allocs) return -1;
  PyObject* it = PyObject_GetIter(allocs);
  Py_DECREF(allocs);
  if (!it) return -1;
  PyObject* alloc;
  while ((alloc = PyIter_Next(it))) {
    PyObject* trs = PyObject_GetAttr(alloc, I.task_resources);
    Py_DECREF(alloc);
    if (!trs) goto fail;
    {
      PyObject* values = PyDict_Values(trs);
      Py_DECREF(trs);
      if (!values) goto fail;
      for (Py_ssize_t i = 0; i < PyList_GET_SIZE(values); i++) {
        PyObject* nets =
            PyObject_GetAttr(PyList_GET_ITEM(values, i), I.networks);
        if (!nets) {
          Py_DECREF(values);
          goto fail;
        }
        PyObject* nets_fast = PySequence_Fast(nets, "networks");
        Py_DECREF(nets);
        if (!nets_fast) {
          Py_DECREF(values);
          goto fail;
        }
        for (Py_ssize_t j = 0; j < PySequence_Fast_GET_SIZE(nets_fast);
             j++) {
          PyObject* offer = PySequence_Fast_GET_ITEM(nets_fast, j);
          PyObject* rports = PyObject_GetAttr(offer, I.reserved_ports);
          if (!rports) {
            Py_DECREF(nets_fast);
            Py_DECREF(values);
            goto fail;
          }
          PyObject* rp_fast = PySequence_Fast(rports, "reserved_ports");
          Py_DECREF(rports);
          if (!rp_fast) {
            Py_DECREF(nets_fast);
            Py_DECREF(values);
            goto fail;
          }
          for (Py_ssize_t k = 0; k < PySequence_Fast_GET_SIZE(rp_fast);
               k++) {
            if (PySet_Add(used, PySequence_Fast_GET_ITEM(rp_fast, k)) <
                0) {
              Py_DECREF(rp_fast);
              Py_DECREF(nets_fast);
              Py_DECREF(values);
              goto fail;
            }
          }
          Py_DECREF(rp_fast);
          PyObject* mb = PyObject_GetAttr(offer, I.mbits);
          if (!mb) {
            Py_DECREF(nets_fast);
            Py_DECREF(values);
            goto fail;
          }
          *bw += PyLong_AsLong(mb);
          Py_DECREF(mb);
          if (PyErr_Occurred()) {
            Py_DECREF(nets_fast);
            Py_DECREF(values);
            goto fail;
          }
        }
        Py_DECREF(nets_fast);
      }
      Py_DECREF(values);
    }
  }
  Py_DECREF(it);
  return PyErr_Occurred() ? -1 : 0;
fail:
  Py_DECREF(it);
  return -1;
}

// Node-static network base lookup: cached tuple from net_base, else one
// callback into Python's _net_base_for (which computes, handles CIDR
// IPs, and caches).  Returns 1 ok (*out = borrowed tuple), 0 bail
// (complex topology), -1 error.
int node_base(PyObject* net_base, PyObject* base_fn, PyObject* ch_key,
              PyObject* node, PyObject** out) {
  PyObject* base = PyDict_GetItemWithError(net_base, ch_key);
  if (base) {
    if (base == Py_None) return 0;
    *out = base;  // borrowed from net_base, same as the miss path below
    return 1;
  }
  if (PyErr_Occurred()) return -1;
  base = PyObject_CallFunctionObjArgs(base_fn, ch_key, node, nullptr);
  if (!base) return -1;
  bool is_none = base == Py_None;
  Py_DECREF(base);
  if (is_none) return 0;
  // _net_base_for cached the tuple into net_base; borrow it from there
  // so the caller needs no ownership bookkeeping.
  base = PyDict_GetItem(net_base, ch_key);
  if (!base || base == Py_None) return 0;  // defensive: cacheless callback
  *out = base;
  return 1;
}

// bulk_finish(place, group_idx, chosen, scores, uuids, slots, nodes,
//             node_net, net_base, base_fn, allocs_idx, ctx, plan_nu, plan_na,
//             failed_list, alloc_proto, metric_proto,
//             alloc_cls, metric_cls, res_cls, net_cls,
//             statuses, coalesce_all, port_lcg, min_port, max_port)
//   -> (n_done, port_lcg, failed_map)
//
// slots[g] = (size_obj, tasks) with tasks = list of
//   (task_name, res_proto_dict, None | (mbits, net_proto, dyn_labels)).
// statuses = (run, pending, failed, client_failed, failed_desc).
// coalesce_all: 1 = a task group's first failure swallows ALL its later
// placements (generic-scheduler semantics: placements of one TG are
// interchangeable, reference scheduler/generic_sched.go failedTGAllocs);
// 0 = coalesce only placements with no chosen node (system semantics:
// placements are node-pinned, one node failing says nothing about the
// others).
PyObject* bulk_finish(PyObject*, PyObject* args) {
  PyObject *place, *group_idx, *chosen, *scores, *uuids, *slots, *nodes;
  PyObject *node_net, *net_base, *base_fn, *allocs_idx, *ctx, *plan_nu,
      *plan_na;
  PyObject *failed_list, *alloc_proto, *metric_proto;
  PyObject *alloc_cls, *metric_cls, *res_cls, *net_cls, *statuses;
  int coalesce_all;
  long long lcg;  // 64-bit: lcg*1103515245 overflows a 32-bit long
  long min_port, max_port;
  if (!PyArg_ParseTuple(
          args, "OOOOOOOOOOOOOOOOOOOOOOiLll", &place, &group_idx, &chosen,
          &scores, &uuids, &slots, &nodes, &node_net, &net_base, &base_fn,
          &allocs_idx, &ctx, &plan_nu, &plan_na, &failed_list, &alloc_proto,
          &metric_proto, &alloc_cls, &metric_cls,
          &res_cls, &net_cls, &statuses, &coalesce_all, &lcg, &min_port,
          &max_port)) {
    return nullptr;
  }
  Interned& I = interned();
  const long span = max_port - min_port;
  PyObject* st_run = PyTuple_GET_ITEM(statuses, 0);
  PyObject* st_pending = PyTuple_GET_ITEM(statuses, 1);
  PyObject* st_failed = PyTuple_GET_ITEM(statuses, 2);
  PyObject* st_cfailed = PyTuple_GET_ITEM(statuses, 3);
  PyObject* failed_desc = PyTuple_GET_ITEM(statuses, 4);

  PyObject* failed_map = PyDict_New();
  if (!failed_map) return nullptr;

  Py_ssize_t P = PyList_GET_SIZE(place);
  Py_ssize_t p = 0;
  for (; p < P; p++) {
    PyObject* missing = PyList_GET_ITEM(place, p);
    PyObject* tg = PyObject_GetAttr(missing, I.task_group);
    if (!tg) goto fail;
    PyObject* tg_key = PyLong_FromVoidPtr((void*)tg);
    if (!tg_key) {
      Py_DECREF(tg);
      goto fail;
    }

    long g = PyLong_AsLong(PyList_GET_ITEM(group_idx, p));
    long ch = PyLong_AsLong(PyList_GET_ITEM(chosen, p));

    // Coalesce onto a prior failure of the same task group (all
    // placements under generic semantics; only chosen-less ones under
    // node-pinned system semantics — see coalesce_all above).
    PyObject* prior = PyDict_GetItemWithError(failed_map, tg_key);
    if (!prior && PyErr_Occurred()) {
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      goto fail;
    }
    if (prior && !coalesce_all && ch >= 0) prior = nullptr;
    if (prior) {
      PyObject* m = PyObject_GetAttr(prior, I.metrics);
      PyObject* c = m ? PyObject_GetAttr(m, I.coalesced) : nullptr;
      if (!c) {
        Py_XDECREF(m);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      long v = PyLong_AsLong(c) + 1;
      Py_DECREF(c);
      PyObject* nv = PyLong_FromLong(v);
      int rc = nv ? PyObject_SetAttr(m, I.coalesced, nv) : -1;
      Py_XDECREF(nv);
      Py_DECREF(m);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      if (rc < 0) goto fail;
      continue;
    }

    if (ch < 0 && coalesce_all) {
      // First failure of a task group under generic semantics: bail so
      // the Python loop owns it — its sequential fallback can still
      // PLACE the copy when the device's rounds estimate stranded it
      // (fleet-fullness underestimates), and failures that survive get
      // the full filter/exhaustion explanation.  The system path
      // (coalesce_all=0, node-pinned) keeps its O(1) inline failures.
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      goto done;
    }

    PyObject* slot = PyList_GET_ITEM(slots, g);
    PyObject* size_obj = PyTuple_GET_ITEM(slot, 0);
    PyObject* tasks = PyTuple_GET_ITEM(slot, 1);

    PyObject* node = nullptr;
    PyObject* node_id = nullptr;
    PyObject* out_trs = nullptr;  // task name -> Resources
    double score = 0.0;

    if (ch >= 0) {
      node = PyList_GET_ITEM(nodes, ch);  // borrowed
      node_id = PyObject_GetAttr(node, I.id);
      if (!node_id) {
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      score = PyFloat_AsDouble(PyList_GET_ITEM(scores, p));

      // --- network state for the node -------------------------------
      PyObject* ch_key = PyLong_FromLong(ch);
      if (!ch_key) {
        Py_DECREF(node_id);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      PyObject* st = PyDict_GetItemWithError(node_net, ch_key);
      if (!st && PyErr_Occurred()) {
        Py_DECREF(ch_key);
        Py_DECREF(node_id);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      if (!st) {
        PyObject* base = nullptr;
        int rc = node_base(net_base, base_fn, ch_key, node, &base);
        if (rc < 0) {
          Py_DECREF(ch_key);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        if (rc == 0) {  // bail: Python path owns this placement
          Py_DECREF(ch_key);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto done;
        }
        PyObject* used = PySet_New(PyTuple_GET_ITEM(base, 0));
        if (!used) {
          Py_DECREF(ch_key);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        long bw = PyLong_AsLong(PyTuple_GET_ITEM(base, 1));
        // Probe for proposed allocs needing the exact walk: direct
        // lookup in the store's allocs-by-node index (node_id ->
        // alloc-id collection; snapshots copy-on-write so the borrowed
        // dict is stable for the eval).
        int busy;
        {
          PyObject* entry = PyDict_GetItemWithError(allocs_idx, node_id);
          if (!entry && PyErr_Occurred()) {
            Py_DECREF(used);
            Py_DECREF(ch_key);
            Py_DECREF(node_id);
            Py_DECREF(tg_key);
            Py_DECREF(tg);
            goto fail;
          }
          busy = entry ? PyObject_IsTrue(entry) : 0;
        }
        if (busy == 0) {
          int c1 = PyDict_Contains(plan_nu, node_id);
          int c2 = c1 == 0 ? PyDict_Contains(plan_na, node_id) : c1;
          if (c1 < 0 || c2 < 0) busy = -1;
          else busy = (c1 > 0 || c2 > 0) ? 1 : 0;
        }
        if (busy < 0) {
          Py_DECREF(used);
          Py_DECREF(ch_key);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        if (busy &&
            walk_proposed(ctx, node_id, used, &bw) < 0) {
          Py_DECREF(used);
          Py_DECREF(ch_key);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        PyObject* bw_obj = PyLong_FromLong(bw);
        st = bw_obj ? PyList_New(5) : nullptr;
        if (!st) {
          Py_XDECREF(bw_obj);
          Py_DECREF(used);
          Py_DECREF(ch_key);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        PyList_SET_ITEM(st, 0, used);  // steals
        PyList_SET_ITEM(st, 1, bw_obj);
        PyObject* avail = PyTuple_GET_ITEM(base, 2);
        Py_INCREF(avail);
        PyList_SET_ITEM(st, 2, avail);
        PyObject* ipo = PyTuple_GET_ITEM(base, 3);
        Py_INCREF(ipo);
        PyList_SET_ITEM(st, 3, ipo);
        PyObject* devo = PyTuple_GET_ITEM(base, 4);
        Py_INCREF(devo);
        PyList_SET_ITEM(st, 4, devo);
        gc_untrack(used);  // port ints only
        gc_untrack(st);    // [set, int, int, str, str]
        int rc2 = PyDict_SetItem(node_net, ch_key, st);
        Py_DECREF(st);  // dict holds it now
        if (rc2 < 0) {
          Py_DECREF(ch_key);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        st = PyDict_GetItem(node_net, ch_key);  // borrowed
      }
      Py_DECREF(ch_key);

      PyObject* used = PyList_GET_ITEM(st, 0);
      long bw_used = PyLong_AsLong(PyList_GET_ITEM(st, 1));
      long bw_avail = PyLong_AsLong(PyList_GET_ITEM(st, 2));
      PyObject* node_ip = PyList_GET_ITEM(st, 3);
      PyObject* node_dev = PyList_GET_ITEM(st, 4);

      // Total bandwidth ask up-front: no mid-slot rollback needed.
      long total_mbits = 0;
      Py_ssize_t n_tasks = PyList_GET_SIZE(tasks);
      for (Py_ssize_t t = 0; t < n_tasks; t++) {
        PyObject* net = PyTuple_GET_ITEM(PyList_GET_ITEM(tasks, t), 2);
        if (net != Py_None) {
          total_mbits += PyLong_AsLong(PyTuple_GET_ITEM(net, 0));
        }
      }
      if (bw_used + total_mbits > bw_avail) {
        // Divergence: Python fallback owns this placement onward.
        Py_DECREF(node_id);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto done;
      }

      out_trs = PyDict_New();
      if (!out_trs) {
        Py_DECREF(node_id);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      bool task_fail = false;
      for (Py_ssize_t t = 0; t < n_tasks && !task_fail; t++) {
        PyObject* task = PyList_GET_ITEM(tasks, t);
        PyObject* tname = PyTuple_GET_ITEM(task, 0);
        PyObject* res_proto = PyTuple_GET_ITEM(task, 1);
        PyObject* net = PyTuple_GET_ITEM(task, 2);
        PyObject* rd = PyDict_Copy(res_proto);
        if (!rd) {
          task_fail = true;
          break;
        }
        if (net == Py_None) {
          PyObject* empty = PyList_New(0);
          gc_untrack(empty);
          if (!empty || PyDict_SetItem(rd, I.networks, empty) < 0) {
            Py_XDECREF(empty);
            Py_DECREF(rd);
            task_fail = true;
            break;
          }
          Py_DECREF(empty);
        } else {
          PyObject* net_proto = PyTuple_GET_ITEM(net, 1);
          PyObject* labels = PyTuple_GET_ITEM(net, 2);
          Py_ssize_t n_dyn = PySequence_Fast_GET_SIZE(labels);
          PyObject* ports = PyList_New(0);
          if (!ports) {
            Py_DECREF(rd);
            task_fail = true;
            break;
          }
          bool port_fail = false;
          for (Py_ssize_t dp = 0; dp < n_dyn && !port_fail; dp++) {
            lcg = (lcg * 1103515245LL + 12345LL) & 0x3FFFFFFFLL;
            long port = min_port + (long)(lcg % span);
            long tries = 0;
            while (true) {
              PyObject* po = PyLong_FromLong(port);
              if (!po) {
                port_fail = true;
                break;
              }
              int hit = PySet_Contains(used, po);
              if (hit < 0) {
                Py_DECREF(po);
                port_fail = true;
                break;
              }
              if (!hit) {
                if (PySet_Add(used, po) < 0 ||
                    PyList_Append(ports, po) < 0) {
                  Py_DECREF(po);
                  port_fail = true;
                  break;
                }
                Py_DECREF(po);
                break;
              }
              Py_DECREF(po);
              port = min_port + (port - min_port + 1) % span;
              if (++tries > span) {
                // Whole dynamic range exhausted on this node: a genuine
                // error (the Python twin would spin); raise, don't bail.
                PyErr_SetString(PyExc_RuntimeError,
                                "dynamic port range exhausted");
                port_fail = true;
                break;
              }
            }
          }
          if (port_fail) {
            Py_DECREF(ports);
            Py_DECREF(rd);
            task_fail = true;
            break;
          }
          gc_untrack(ports);  // ints only
          PyObject* nd = PyDict_Copy(net_proto);
          PyObject* labels_copy = nd ? PySequence_List(labels) : nullptr;
          if (!labels_copy ||
              PyDict_SetItem(nd, I.device, node_dev) < 0 ||
              PyDict_SetItem(nd, I.ip, node_ip) < 0 ||
              PyDict_SetItem(nd, I.reserved_ports, ports) < 0 ||
              PyDict_SetItem(nd, I.dynamic_ports, labels_copy) < 0) {
            Py_XDECREF(labels_copy);
            Py_XDECREF(nd);
            Py_DECREF(ports);
            Py_DECREF(rd);
            task_fail = true;
            break;
          }
          gc_untrack(labels_copy);  // strings only
          Py_DECREF(labels_copy);
          Py_DECREF(ports);
          gc_untrack(nd);  // final: offer.__dict__
          PyObject* offer = make_instance(net_cls, nd);
          Py_DECREF(nd);
          if (!offer) {
            Py_DECREF(rd);
            task_fail = true;
            break;
          }
          gc_untrack(offer);
          PyObject* offer_list = PyList_New(1);
          if (!offer_list) {
            Py_DECREF(offer);
            Py_DECREF(rd);
            task_fail = true;
            break;
          }
          PyList_SET_ITEM(offer_list, 0, offer);  // steals
          gc_untrack(offer_list);
          int rc3 = PyDict_SetItem(rd, I.networks, offer_list);
          Py_DECREF(offer_list);
          if (rc3 < 0) {
            Py_DECREF(rd);
            task_fail = true;
            break;
          }
        }
        gc_untrack(rd);  // final: Resources.__dict__
        PyObject* res_inst = make_instance(res_cls, rd);
        Py_DECREF(rd);
        if (!res_inst || PyDict_SetItem(out_trs, tname, res_inst) < 0) {
          Py_XDECREF(res_inst);
          task_fail = true;
          break;
        }
        gc_untrack(res_inst);
        Py_DECREF(res_inst);
      }
      if (task_fail) {
        Py_DECREF(out_trs);
        Py_DECREF(node_id);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      // Commit bandwidth.
      PyObject* new_bw = PyLong_FromLong(bw_used + total_mbits);
      if (!new_bw) {
        Py_DECREF(out_trs);
        Py_DECREF(node_id);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      PyList_SetItem(st, 1, new_bw);  // steals
      gc_untrack(out_trs);  // final: alloc.task_resources
    }

    // --- metric + alloc construction --------------------------------
    // Lazy AllocMetric: only the proto copy + the one binpack score as
    // two scalars; factory dicts + the scores dict materialize on
    // first read (AllocMetric.__getattr__ in structs/model.py).
    PyObject* md = PyDict_Copy(metric_proto);
    if (!md) {
      Py_XDECREF(out_trs);
      Py_XDECREF(node_id);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      goto fail;
    }
    if (node_id) {
      PyObject* key = PyUnicode_Concat(node_id, I.binpack_suffix);
      PyObject* sv = key ? PyFloat_FromDouble(score) : nullptr;
      if (!sv || PyDict_SetItem(md, I.lazy_score_key, key) < 0 ||
          PyDict_SetItem(md, I.lazy_score_val, sv) < 0) {
        Py_XDECREF(sv);
        Py_XDECREF(key);
        Py_DECREF(md);
        Py_XDECREF(out_trs);
        Py_DECREF(node_id);
        Py_DECREF(tg_key);
        Py_DECREF(tg);
        goto fail;
      }
      Py_DECREF(sv);
      Py_DECREF(key);
    }
    gc_untrack(md);  // final: AllocMetric.__dict__
    PyObject* metric = make_instance(metric_cls, md);
    Py_DECREF(md);
    if (!metric) {
      Py_XDECREF(out_trs);
      Py_XDECREF(node_id);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      goto fail;
    }

    PyObject* ad = PyDict_Copy(alloc_proto);
    PyObject* tg_name = ad ? PyObject_GetAttr(tg, I.name) : nullptr;
    PyObject* m_name = tg_name ? PyObject_GetAttr(missing, I.name)
                               : nullptr;
    PyObject* ts = m_name ? PyDict_New() : nullptr;
    if (!ts ||
        PyDict_SetItem(ad, I.id, PyList_GET_ITEM(uuids, p)) < 0 ||
        PyDict_SetItem(ad, I.name, m_name) < 0 ||
        PyDict_SetItem(ad, I.task_group, tg_name) < 0 ||
        PyDict_SetItem(ad, I.resources, size_obj) < 0 ||
        PyDict_SetItem(ad, I.metrics, metric) < 0 ||
        PyDict_SetItem(ad, I.task_states, ts) < 0) {
      Py_XDECREF(ts);
      Py_XDECREF(m_name);
      Py_XDECREF(tg_name);
      Py_XDECREF(ad);
      Py_DECREF(metric);
      Py_XDECREF(out_trs);
      Py_XDECREF(node_id);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      goto fail;
    }
    Py_DECREF(ts);
    Py_DECREF(m_name);
    Py_DECREF(tg_name);
    Py_DECREF(metric);

    int rc4 = 0;
    if (node_id) {
      rc4 = PyDict_SetItem(ad, I.node_id, node_id) < 0 ||
            PyDict_SetItem(ad, I.task_resources, out_trs) < 0 ||
            PyDict_SetItem(ad, I.desired_status, st_run) < 0 ||
            PyDict_SetItem(ad, I.client_status, st_pending) < 0;
      Py_DECREF(out_trs);
      out_trs = nullptr;
    } else {
      PyObject* empty_trs = PyDict_New();
      rc4 = !empty_trs ||
            PyDict_SetItem(ad, I.task_resources, empty_trs) < 0 ||
            PyDict_SetItem(ad, I.desired_status, st_failed) < 0 ||
            PyDict_SetItem(ad, I.desired_description, failed_desc) < 0 ||
            PyDict_SetItem(ad, I.client_status, st_cfailed) < 0;
      Py_XDECREF(empty_trs);
    }
    if (rc4) {
      Py_DECREF(ad);
      Py_XDECREF(node_id);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      goto fail;
    }
    gc_untrack(metric);
    gc_untrack(ad);  // final: Allocation.__dict__
    PyObject* alloc = make_instance(alloc_cls, ad);
    Py_DECREF(ad);
    if (!alloc) {
      Py_XDECREF(node_id);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      goto fail;
    }

    gc_untrack(alloc);
    if (node_id) {
      PyObject* lst = PyDict_GetItemWithError(plan_na, node_id);
      if (!lst) {
        if (PyErr_Occurred()) {
          Py_DECREF(alloc);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        lst = PyList_New(0);
        gc_untrack(lst);  // holds only (untracked) allocs
        if (!lst || PyDict_SetItem(plan_na, node_id, lst) < 0) {
          Py_XDECREF(lst);
          Py_DECREF(alloc);
          Py_DECREF(node_id);
          Py_DECREF(tg_key);
          Py_DECREF(tg);
          goto fail;
        }
        Py_DECREF(lst);
        lst = PyDict_GetItem(plan_na, node_id);
      }
      int rc5 = PyList_Append(lst, alloc);
      Py_DECREF(alloc);
      Py_DECREF(node_id);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      if (rc5 < 0) goto fail;
    } else {
      int rc5 = PyList_Append(failed_list, alloc) < 0 ||
                PyDict_SetItem(failed_map, tg_key, alloc) < 0;
      Py_DECREF(alloc);
      Py_DECREF(tg_key);
      Py_DECREF(tg);
      if (rc5) goto fail;
    }
  }

done:
  return Py_BuildValue("(nLN)", p, lcg, failed_map);

fail:
  Py_DECREF(failed_map);
  return nullptr;
}

// ---------------------------------------------------------------------------
// bulk_finish_cols: the columnar finish loop (the AllocSlab contract).
//
// Same control flow as bulk_finish's generic (coalesce_all=1) happy
// path — identical per-node network state, identical LCG port stream,
// identical bail conditions — but instead of constructing the full
// Allocation object tree per placement it writes the assigned ports
// into the slab's int32 buffer, fills the slab's node_id/ip/device
// columns, and emits ONE small lazy SlabAlloc per row (a proto dict
// copy + five scalar inserts; the heavy fields materialize from the
// slab only at the client/API edge — nomad_tpu/structs/alloc_slab.py).
// Bails (returning how far it got) at the first chosen-less placement,
// complex network topology, or bandwidth divergence, exactly where the
// object path handed control to the Python tail.
//
// bulk_finish_cols(chosen, group_l, uuids, names, tg_names,
//                  slot_mbits, slot_ndyn, ports_buf,
//                  nids_out, ips_out, devs_out, lazy_proto, alloc_cls,
//                  nodes, node_net, net_base, base_fn, allocs_idx, ctx,
//                  plan_nu, plan_na, port_lcg, min_port, max_port)
//   -> (n_done, port_lcg)
// ---------------------------------------------------------------------------
PyObject* bulk_finish_cols(PyObject*, PyObject* args) {
  PyObject *chosen, *group_l, *uuids, *names, *tg_names;
  PyObject *slot_mbits, *slot_ndyn;
  Py_buffer ports_buf;
  PyObject *nids_out, *ips_out, *devs_out, *lazy_proto, *alloc_cls;
  PyObject *nodes, *node_net, *net_base, *base_fn, *allocs_idx, *ctx,
      *plan_nu, *plan_na;
  long long lcg;
  long min_port, max_port;
  if (!PyArg_ParseTuple(
          args, "OOOOOOOw*OOOOOOOOOOOOOLll", &chosen, &group_l, &uuids,
          &names, &tg_names, &slot_mbits, &slot_ndyn, &ports_buf,
          &nids_out, &ips_out, &devs_out, &lazy_proto, &alloc_cls,
          &nodes, &node_net, &net_base, &base_fn, &allocs_idx, &ctx,
          &plan_nu, &plan_na, &lcg, &min_port, &max_port)) {
    return nullptr;
  }
  Interned& I = interned();
  const long span = max_port - min_port;
  Py_ssize_t P = PyList_GET_SIZE(chosen);
  Py_ssize_t n_nodes = PyList_GET_SIZE(nodes);
  int32_t* pbuf = static_cast<int32_t*>(ports_buf.buf);
  Py_ssize_t poff = 0;
  // Per-node caches for this call: st borrowed from node_net (the dict
  // keeps it alive), node_id owned here — avoids a PyLong key build +
  // dict probe per placement on the hot path.
  std::vector<PyObject*> st_of(n_nodes, nullptr);
  std::vector<PyObject*> nid_of(n_nodes, nullptr);  // owned
  bool failed = false;
  Py_ssize_t p = 0;
  for (; p < P && !failed; p++) {
    long ch = PyLong_AsLong(PyList_GET_ITEM(chosen, p));
    if (ch == -1 && PyErr_Occurred()) {
      failed = true;
      break;
    }
    if (ch < 0 || ch >= n_nodes) break;  // tail owns failures/oddities
    long g = PyLong_AsLong(PyList_GET_ITEM(group_l, p));
    long ndyn = PyLong_AsLong(PyList_GET_ITEM(slot_ndyn, g));
    long total_mbits = PyLong_AsLong(PyList_GET_ITEM(slot_mbits, g));
    if (PyErr_Occurred()) {
      failed = true;
      break;
    }

    PyObject* st = st_of[ch];
    PyObject* node_id = nid_of[ch];
    if (st == nullptr) {
      // First placement on this node: build the fast per-node network
      // state exactly like the object path (shared with the Python
      // tail through node_net).
      PyObject* node = PyList_GET_ITEM(nodes, ch);
      node_id = PyObject_GetAttr(node, I.id);
      if (!node_id) {
        failed = true;
        break;
      }
      nid_of[ch] = node_id;  // owned for the rest of the call
      PyObject* ch_key = PyLong_FromLong(ch);
      if (!ch_key) {
        failed = true;
        break;
      }
      PyObject* base = nullptr;
      int rc = node_base(net_base, base_fn, ch_key, node, &base);
      if (rc < 0) {
        Py_DECREF(ch_key);
        failed = true;
        break;
      }
      if (rc == 0) {  // complex topology: Python tail owns it
        Py_DECREF(ch_key);
        break;
      }
      PyObject* used = PySet_New(PyTuple_GET_ITEM(base, 0));
      if (!used) {
        Py_DECREF(ch_key);
        failed = true;
        break;
      }
      long bw = PyLong_AsLong(PyTuple_GET_ITEM(base, 1));
      int busy;
      {
        PyObject* entry = PyDict_GetItemWithError(allocs_idx, node_id);
        if (!entry && PyErr_Occurred()) {
          Py_DECREF(used);
          Py_DECREF(ch_key);
          failed = true;
          break;
        }
        busy = entry ? PyObject_IsTrue(entry) : 0;
      }
      if (busy == 0) {
        int c1 = PyDict_Contains(plan_nu, node_id);
        int c2 = c1 == 0 ? PyDict_Contains(plan_na, node_id) : c1;
        if (c1 < 0 || c2 < 0) busy = -1;
        else busy = (c1 > 0 || c2 > 0) ? 1 : 0;
      }
      if (busy < 0 ||
          (busy && walk_proposed(ctx, node_id, used, &bw) < 0)) {
        Py_DECREF(used);
        Py_DECREF(ch_key);
        failed = true;
        break;
      }
      PyObject* bw_obj = PyLong_FromLong(bw);
      st = bw_obj ? PyList_New(5) : nullptr;
      if (!st) {
        Py_XDECREF(bw_obj);
        Py_DECREF(used);
        Py_DECREF(ch_key);
        failed = true;
        break;
      }
      PyList_SET_ITEM(st, 0, used);    // steals
      PyList_SET_ITEM(st, 1, bw_obj);  // steals
      PyObject* avail = PyTuple_GET_ITEM(base, 2);
      Py_INCREF(avail);
      PyList_SET_ITEM(st, 2, avail);
      PyObject* ipo = PyTuple_GET_ITEM(base, 3);
      Py_INCREF(ipo);
      PyList_SET_ITEM(st, 3, ipo);
      PyObject* devo = PyTuple_GET_ITEM(base, 4);
      Py_INCREF(devo);
      PyList_SET_ITEM(st, 4, devo);
      gc_untrack(used);
      gc_untrack(st);
      int rc2 = PyDict_SetItem(node_net, ch_key, st);
      Py_DECREF(st);  // node_net holds it now
      Py_DECREF(ch_key);
      if (rc2 < 0) {
        failed = true;
        break;
      }
      st_of[ch] = st;  // borrowed from node_net for this call
    }

    long bw_used = PyLong_AsLong(PyList_GET_ITEM(st, 1));
    long bw_avail = PyLong_AsLong(PyList_GET_ITEM(st, 2));
    if (PyErr_Occurred()) {
      failed = true;
      break;
    }
    if (bw_used + total_mbits > bw_avail) break;  // divergence: tail

    PyObject* used = PyList_GET_ITEM(st, 0);
    bool port_fail = false;
    for (long d = 0; d < ndyn && !port_fail; d++) {
      lcg = (lcg * 1103515245LL + 12345LL) & 0x3FFFFFFFLL;
      long port = min_port + (long)(lcg % span);
      long tries = 0;
      while (true) {
        PyObject* po = PyLong_FromLong(port);
        if (!po) {
          port_fail = true;
          break;
        }
        int hit = PySet_Contains(used, po);
        if (hit < 0) {
          Py_DECREF(po);
          port_fail = true;
          break;
        }
        if (!hit) {
          int rc3 = PySet_Add(used, po);
          Py_DECREF(po);
          if (rc3 < 0) {
            port_fail = true;
            break;
          }
          pbuf[poff + d] = (int32_t)port;
          break;
        }
        Py_DECREF(po);
        port = min_port + (port - min_port + 1) % span;
        if (++tries > span) {
          PyErr_SetString(PyExc_RuntimeError,
                          "dynamic port range exhausted");
          port_fail = true;
          break;
        }
      }
    }
    if (port_fail) {
      failed = true;
      break;
    }
    poff += ndyn;
    if (total_mbits) {
      PyObject* nb = PyLong_FromLong(bw_used + total_mbits);
      if (!nb || PyList_SetItem(st, 1, nb) < 0) {  // steals nb
        failed = true;
        break;
      }
    }

    // Slab columns: node id / ip / device for this row.
    Py_INCREF(node_id);
    PyObject* ipo = PyList_GET_ITEM(st, 3);
    Py_INCREF(ipo);
    PyObject* devo = PyList_GET_ITEM(st, 4);
    Py_INCREF(devo);
    if (PyList_SetItem(nids_out, p, node_id) < 0 ||  // steal; replaces None
        PyList_SetItem(ips_out, p, ipo) < 0 ||
        PyList_SetItem(devs_out, p, devo) < 0) {
      failed = true;
      break;
    }

    // The lazy alloc: proto copy + five scalar inserts.
    PyObject* ad = PyDict_Copy(lazy_proto);
    PyObject* srow = ad ? PyLong_FromSsize_t(p) : nullptr;
    if (!srow ||
        PyDict_SetItem(ad, I.id, PyList_GET_ITEM(uuids, p)) < 0 ||
        PyDict_SetItem(ad, I.name, PyList_GET_ITEM(names, p)) < 0 ||
        PyDict_SetItem(ad, I.task_group,
                       PyList_GET_ITEM(tg_names, p)) < 0 ||
        PyDict_SetItem(ad, I.node_id, node_id) < 0 ||
        PyDict_SetItem(ad, I.srow, srow) < 0) {
      Py_XDECREF(srow);
      Py_XDECREF(ad);
      failed = true;
      break;
    }
    Py_DECREF(srow);
    gc_untrack(ad);  // final: SlabAlloc.__dict__ (acyclic: the slab
    //                  never points back at scheduler-path allocs)
    PyObject* alloc = make_instance(alloc_cls, ad);
    Py_DECREF(ad);
    if (!alloc) {
      failed = true;
      break;
    }
    gc_untrack(alloc);

    PyObject* lst = PyDict_GetItemWithError(plan_na, node_id);
    if (!lst) {
      if (PyErr_Occurred()) {
        Py_DECREF(alloc);
        failed = true;
        break;
      }
      lst = PyList_New(0);
      gc_untrack(lst);  // holds only (untracked) allocs
      if (!lst || PyDict_SetItem(plan_na, node_id, lst) < 0) {
        Py_XDECREF(lst);
        Py_DECREF(alloc);
        failed = true;
        break;
      }
      Py_DECREF(lst);
      lst = PyDict_GetItem(plan_na, node_id);
    }
    int rc4 = PyList_Append(lst, alloc);
    Py_DECREF(alloc);
    if (rc4 < 0) {
      failed = true;
      break;
    }
  }

  for (PyObject* o : nid_of) Py_XDECREF(o);
  PyBuffer_Release(&ports_buf);
  if (failed) {
    if (!PyErr_Occurred()) {
      PyErr_SetString(PyExc_RuntimeError, "bulk_finish_cols failed");
    }
    return nullptr;
  }
  return Py_BuildValue("(nL)", p, lcg);
}

// bulk_finish_many(items) -> [(n_done, port_lcg), ...]
//
// items: list of bulk_finish_cols argument TUPLES (built by
// scheduler/jax_binpack._finish_native_args), one per evaluation of a
// drained pipeline window.  Runs every eval's columnar finish loop in
// ONE Python->C transition so the staged pipeline
// (scheduler/pipeline.py) amortizes the native-call setup across the
// window instead of re-entering the interpreter between evals.
// Exactly equivalent to calling bulk_finish_cols per item.
PyObject* bulk_finish_many(PyObject* self, PyObject* args) {
  PyObject* items;
  if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &items)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(items);
  PyObject* out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PyList_GET_ITEM(items, i);
    if (!PyTuple_Check(item)) {
      Py_DECREF(out);
      PyErr_SetString(PyExc_TypeError,
                      "bulk_finish_many items must be argument tuples");
      return nullptr;
    }
    PyObject* r = bulk_finish_cols(self, item);
    if (!r) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, r);  // steals
  }
  return out;
}

PyMethodDef methods[] = {
    {"assign_ports", assign_ports, METH_VARARGS,
     "Assign reserved + dynamic ports against a used-port set."},
    {"add_all", add_all, METH_VARARGS,
     "Add ports to a used-port set; returns True on any collision."},
    {"bulk_finish", bulk_finish, METH_VARARGS,
     "Scheduler finish-loop happy path: bulk alloc construction."},
    {"bulk_finish_cols", bulk_finish_cols, METH_VARARGS,
     "Columnar finish loop: ports into the AllocSlab buffer, lazy "
     "SlabAllocs into the plan."},
    {"bulk_finish_many", bulk_finish_many, METH_VARARGS,
     "bulk_finish_cols over a window of evals in one native call."},
    {"format_uuids", format_uuids, METH_VARARGS,
     "Format UUID strings from raw entropy bytes (16 per UUID)."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_nomad_native",
    "C++ hot-path helpers for the host scheduling plane.", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__nomad_native(void) {
  PyObject* m = PyModule_Create(&module);
  if (m == nullptr) return nullptr;
  // Bumped on any signature/behavior change of an existing function so a
  // stale prebuilt .so (same names, old ABI) is detected by the loader
  // (nomad_tpu/utils/native.py) instead of crashing mid-eval.
  if (PyModule_AddIntConstant(m, "ABI_VERSION", 6) < 0) {
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
